"""Capacity & compilation observability — the third rail beside
telemetry (metrics) and lifecycle (proposal spans).

ROADMAP item 1 pushes toward 100k–1M groups on a real mesh, and the two
silent killers of that push are retrace storms (a shape leak recompiles
the step kernel mid-flight) and HBM exhaustion (a geometry that fits
analytically but OOMs in practice).  Three legs make both observable:

- **Compile telemetry** (:class:`CompileTracker`): every jit entry the
  engines dispatch (``step``, ``step_donated``, ``serve_step``,
  ``serve_step_donated``, ``fleet_stats``, ``fleet_health``, bench
  loops) is wrapped in a
  tracked callable that detects a trace/compile by sampling the jitted
  function's executable-cache size around each call.  Each compile is
  counted per entry, timed (the call's wall time is trace+lower+compile
  at that point), observed into ``compile_us{entry=...}`` histograms,
  and emitted as a Chrome-trace span that the ``/trace`` endpoint
  merges with the lifecycle ring.  A compile AFTER an entry reached
  steady state (>= 1 compile + a clean call) is a retrace; the first
  one per entry raises an edge-triggered ``retrace_storm`` flight
  event.

- **Device-memory accounting**: :func:`measure_tree_bytes` sums the
  engines' known resident trees (state / carried inbox / health
  digest); :func:`device_memory_stats` adds ``device.memory_stats()``
  where the backend reports it.  :func:`engine_snapshot` folds both
  into ``capacity_bytes_in_use`` / ``capacity_bytes_peak`` /
  ``capacity_headroom_pct`` gauges with a watermark-crossing
  ``memory_pressure`` flight event wired into ``/healthz``.

- **Contracts-derived capacity model**: the same machine-readable
  CONTRACTS grammar that powers the lint passes (analysis/common.py)
  encodes exactly what a group costs —
  :func:`model_bytes_per_group` walks the ShardState / Inbox /
  StepInput / StepOutput / HealthDigest contracts and multiplies axis
  extents (from KernelParams) by dtype widths, honoring the optional-
  field materialization rules of the kstate constructors.  The model is
  cross-checked against measured device bytes in a differential test
  and predicts max-G per device budget (:func:`max_g_for_budget`).

Determinism: this module is in the determinism lint scope.  The
tracker's microsecond clock is INJECTED (``tracing.monotonic_us`` lives
outside the scope, same doctrine as lifecycle.py); flight records are
stamped with per-entry call counts, never the wall clock.

Concurrency: tracker state is guarded by ``CompileTracker.mu``; the
wrapped jitted call itself runs outside the lock.
"""

from __future__ import annotations

import threading
from collections import deque

import jax

from dragonboat_tpu import flight as _flight
from dragonboat_tpu import telemetry as _telemetry
from dragonboat_tpu.tracing import monotonic_us

# ---------------------------------------------------------------------------
# contracts-derived capacity model
# ---------------------------------------------------------------------------

#: bytes per element for the canonical contract dtypes (analysis/common.py
#: DTYPES); the kstate constructors build exactly these widths
DTYPE_BYTES = {"i32": 4, "u32": 4, "f32": 4, "bool": 1}

#: symbolic contract axis -> the KernelParams field holding its extent
#: (G is the free variable the model is *per*)
AXIS_PARAMS = {
    "P": "num_peers",
    "CAP": "log_cap",
    "K": "inbox_cap",
    "E": "msg_entries",
    "B": "proposal_cap",
    "RI": "readindex_cap",
}

#: contract classes with a leading-G per-group footprint.  HealthReport /
#: ShardRow are replicated O(K)/O(1) aggregates — not per-group cost
MODEL_CLASSES = ("ShardState", "Inbox", "StepInput", "StepOutput",
                 "HealthDigest", "InvariantDigest")

#: resident set: trees an engine holds for its lifetime (StepInput /
#: StepOutput are per-step transients) — the default for budget math
RESIDENT_CLASSES = ("ShardState", "Inbox", "HealthDigest",
                    "InvariantDigest")


def _optional_materialized(cls: str, fld: str, kp) -> bool:
    """Whether an ``optional`` contract field is actually allocated,
    mirroring the kstate constructors: payload columns exist only under
    ``inline_payloads``, and ``empty_input`` NEVER materializes
    ``prop_val`` (the host staging builders don't either)."""
    if (cls, fld) == ("StepInput", "prop_val"):
        return False
    return bool(kp.inline_payloads)


def _contract_table():
    from dragonboat_tpu.analysis.common import parse_contracts
    from dragonboat_tpu.core import health as _health
    from dragonboat_tpu.core import invariants as _invariants
    from dragonboat_tpu.core import kstate as _kstate

    table = dict(_kstate.CONTRACTS)
    table["HealthDigest"] = _health.CONTRACTS["HealthDigest"]
    table["InvariantDigest"] = _invariants.CONTRACTS["InvariantDigest"]
    return parse_contracts(table, "capacity")


def model_bytes_per_group(kp, classes=MODEL_CLASSES) -> dict:
    """Analytic bytes-per-group for each contract class at geometry
    ``kp``, plus ``"total"``.  Raises ValueError on a contract axis the
    model cannot size (a new axis must be added to AXIS_PARAMS)."""
    table = _contract_table()
    per: dict = {}
    for cls in classes:
        nbytes = 0
        for fld, fc in table[cls].items():
            if not fc.axes or fc.axes[0] != "G":
                raise ValueError(
                    f"capacity model: {cls}.{fld} has no leading G axis "
                    f"({fc.axes}) — not a per-group field")
            if fc.optional and not _optional_materialized(cls, fld, kp):
                continue
            n = DTYPE_BYTES[fc.dtype]
            for ax in fc.axes[1:]:
                if ax not in AXIS_PARAMS:
                    raise ValueError(
                        f"capacity model: {cls}.{fld} axis {ax!r} has no "
                        "KernelParams extent (update AXIS_PARAMS)")
                n *= int(getattr(kp, AXIS_PARAMS[ax]))
            nbytes += n
        per[cls] = nbytes
    per["total"] = sum(per[c] for c in classes)
    return per


def predict_bytes(kp, num_groups: int, classes=MODEL_CLASSES) -> int:
    """Analytic device bytes for ``num_groups`` groups of ``classes``."""
    return model_bytes_per_group(kp, classes)["total"] * int(num_groups)


def max_g_for_budget(kp, budget_bytes: int,
                     classes=RESIDENT_CLASSES) -> int:
    """Largest G whose resident footprint fits ``budget_bytes``."""
    per_group = model_bytes_per_group(kp, classes)["total"]
    if budget_bytes <= 0 or per_group <= 0:
        return 0
    return int(budget_bytes) // per_group


def bytes_for_contract(spec: str, kp, num_groups: int,
                       axis_extra: dict | None = None) -> int:
    """Closed-form bytes of one value declared as a contract string
    (``"[G, K] i32"``).  ``G`` resolves to ``num_groups``, symbolic axes
    through AXIS_PARAMS (kernel geometry) or ``axis_extra`` (host-side
    constants like histogram widths), decimal literals to themselves,
    and an empty axis list (``"[] i32"``) to a scalar.  Unlike
    ``model_bytes_per_group`` this sizes boundary crossings, which are
    not always per-group — hence no leading-G requirement."""
    from dragonboat_tpu.analysis.common import parse_contract

    fc = parse_contract(spec, "transfer")
    n = DTYPE_BYTES[fc.dtype]
    for ax in fc.axes:
        if ax == "G":
            n *= int(num_groups)
        elif ax.isdigit():
            n *= int(ax)
        elif ax in AXIS_PARAMS:
            n *= int(getattr(kp, AXIS_PARAMS[ax]))
        elif axis_extra and ax in axis_extra:
            n *= int(axis_extra[ax])
        else:
            raise ValueError(
                f"transfer model: axis {ax!r} in {spec!r} has no extent "
                "(KernelParams AXIS_PARAMS or axis_extra)")
    return n


# ---------------------------------------------------------------------------
# device-memory accounting
# ---------------------------------------------------------------------------


def measure_tree_bytes(*trees) -> int:
    """Sum of ``nbytes`` over the array leaves of the given pytrees
    (None subtrees and non-array leaves contribute 0).  Shape-derived —
    never forces a device sync."""
    total = 0
    for tree in trees:
        if tree is None:
            continue
        for leaf in jax.tree_util.tree_leaves(tree):
            nb = getattr(leaf, "nbytes", None)
            if nb is not None:
                total += int(nb)
    return total


def device_memory_stats() -> list:
    """Per-device allocator stats where the backend reports them
    (``device.memory_stats()`` — TPU/GPU; CPU returns nothing).  Each
    row: platform, bytes_in_use, peak_bytes_in_use, bytes_limit."""
    rows = []
    for dev in jax.devices():
        try:
            ms = dev.memory_stats()
        except Exception:
            ms = None
        if not ms:
            continue
        rows.append({
            "platform": str(dev.platform),
            "bytes_in_use": int(ms.get("bytes_in_use", 0)),
            "peak_bytes_in_use": int(ms.get("peak_bytes_in_use", 0)),
            "bytes_limit": int(ms.get("bytes_limit", 0)),
        })
    return rows


# ---------------------------------------------------------------------------
# compile telemetry
# ---------------------------------------------------------------------------

#: steady state = at least one compile followed by this many clean calls;
#: a compile after that is a retrace
STEADY_CLEAN_CALLS = 1


class _EntryState:
    """Counters for ONE wrapped callable.  Each ``wrap()`` call gets its
    own state (one per engine entry), so a legitimate first compile at a
    NEW engine's geometry is never mistaken for a retrace of another
    engine sharing the same underlying jitted function."""

    __slots__ = ("entry", "calls", "compiles", "retraces",
                 "compile_us_total", "last_compile_us", "clean_since",
                 "storm")

    def __init__(self, entry: str) -> None:
        self.entry = entry
        self.calls = 0
        self.compiles = 0
        self.retraces = 0
        self.compile_us_total = 0
        self.last_compile_us = 0
        self.clean_since = 0      # clean calls since the last compile
        self.storm = False        # latched on the first retrace


class TrackedEntry:
    """Callable wrapper around one jitted entry point.  A compile is
    detected by executable-cache growth (``fn._cache_size()``) across
    the call; functions without a cache probe are counted but never
    flagged.

    The cache size is global to the jitted function: if ANOTHER thread
    compiles the same function inside this wrapper's call window, the
    growth is attributed here.  Counters are exact whenever an engine's
    dispatches don't overlap another engine's first compile of a shared
    function (engines compile at startup, inside their own first
    calls); a concurrent late-joining engine can at worst smear its one
    legitimate compile into a peer's counters."""

    __slots__ = ("_tracker", "_fn", "_st")

    def __init__(self, tracker: "CompileTracker", fn, st: _EntryState
                 ) -> None:
        self._tracker = tracker
        self._fn = fn
        self._st = st

    def __call__(self, *args, **kwargs):
        size_of = getattr(self._fn, "_cache_size", None)
        before = size_of() if size_of is not None else -1
        clock = self._tracker._clock
        t0 = clock()
        result = self._fn(*args, **kwargs)
        elapsed = clock() - t0
        after = size_of() if size_of is not None else -1
        compiled = before >= 0 and after > before
        self._tracker._observe(self._st, compiled, t0, elapsed)
        return result

    def stats(self) -> dict:
        """Plain-int counter snapshot for this entry."""
        return self._tracker._stats_of(self._st)


class CompileTracker:
    """Counts traces/retraces per wrapped jit entry, times compiles into
    ``compile_us{entry=...}`` histograms and a bounded Chrome-trace span
    ring, and raises one edge-triggered ``retrace_storm`` flight event
    per entry that re-traces after steady state."""

    def __init__(self, clock=None, registry=None, recorder=None,
                 ring_size: int = 256,
                 steady_after: int = STEADY_CLEAN_CALLS) -> None:
        if ring_size <= 0:
            raise ValueError(f"ring_size must be positive, got {ring_size}")
        self.mu = threading.Lock()
        # injected microsecond clock (determinism doctrine: this module
        # names no wall clock; the default lives in tracing.py)
        self._clock = clock if clock is not None else monotonic_us
        self._registry = (registry if registry is not None
                          else _telemetry.GLOBAL)
        self._recorder = recorder if recorder is not None else _flight
        self.steady_after = max(0, int(steady_after))
        self._states: list = []                       # guarded-by: mu
        self._spans: deque = deque(maxlen=ring_size)  # guarded-by: mu
        self._hist = self._registry.histogram(
            "compile_us",
            help="trace+lower+compile wall time per jit entry",
            labelnames=("entry",))

    def wrap(self, entry: str, fn) -> TrackedEntry:
        """Wrap one jitted callable under label ``entry``.  Each wrap
        owns independent counters (see _EntryState)."""
        st = _EntryState(str(entry))
        with self.mu:
            self._states.append(st)
        return TrackedEntry(self, fn, st)

    def _observe(self, st: _EntryState, compiled: bool, t0: int,
                 elapsed_us: int) -> None:
        storm_edge = False
        with self.mu:
            st.calls += 1
            if not compiled:
                st.clean_since += 1
            else:
                retrace = (st.compiles > 0
                           and st.clean_since >= self.steady_after)
                st.compiles += 1
                st.clean_since = 0
                st.compile_us_total += int(elapsed_us)
                st.last_compile_us = int(elapsed_us)
                if retrace:
                    st.retraces += 1
                    if not st.storm:
                        st.storm = True
                        storm_edge = True
                self._spans.append({
                    "name": f"compile:{st.entry}", "cat": "compile",
                    "ph": "X", "ts": int(t0), "dur": int(elapsed_us),
                    "pid": "compile", "tid": st.entry,
                    "args": {"entry": st.entry, "calls": st.calls,
                             "compiles": st.compiles,
                             "retrace": retrace},
                })
            calls, compiles = st.calls, st.compiles
        if compiled:
            self._hist.labels(st.entry).observe(int(elapsed_us))
        if storm_edge:
            # edge-triggered, stamped with the entry's call count —
            # never the wall clock (flight doctrine)
            self._recorder.record(
                RETRACE_STORM, entry=st.entry, compiles=compiles,
                calls=calls, compile_us=int(elapsed_us), tick=calls)

    def _stats_of(self, st: _EntryState) -> dict:
        with self.mu:
            return {
                "calls": st.calls,
                "compiles": st.compiles,
                "retraces": st.retraces,
                "compile_us_total": st.compile_us_total,
                "last_compile_us": st.last_compile_us,
            }

    def chrome_events(self) -> list:
        """Completed compile spans as Chrome-trace events (merged into
        the /trace export beside the lifecycle ring; spans per
        (pid, tid) row are appended in clock order, so the strict
        validator's monotonicity holds)."""
        with self.mu:
            return [dict(ev, args=dict(ev["args"])) for ev in self._spans]

    def clear(self) -> None:
        """Forget recorded spans and wrapped states (dead engines drop
        out of snapshot(); live TrackedEntry wrappers keep their own
        counters but stop aggregating here).  For engine-recycling
        processes and test teardown."""
        with self.mu:
            self._states.clear()
            self._spans.clear()

    def snapshot(self) -> dict:
        """Aggregate counters by entry label across all wrapped states
        (two engines wrapping ``step`` sum into one ``step`` row)."""
        agg: dict = {}
        with self.mu:
            states = list(self._states)
        for st in states:
            row = agg.setdefault(st.entry, {
                "calls": 0, "compiles": 0, "retraces": 0,
                "compile_us_total": 0, "last_compile_us": 0})
            d = self._stats_of(st)
            for key in ("calls", "compiles", "retraces",
                        "compile_us_total"):
                row[key] += d[key]
            row["last_compile_us"] = max(row["last_compile_us"],
                                         d["last_compile_us"])
        return agg


#: process-wide tracker (same one-instance doctrine as flight.RECORDER /
#: lifecycle.TRACER): every engine's wrappers and the /trace merge read
#: one ring, so one export shows compiles across all engines
TRACKER = CompileTracker()

#: flight-record kinds this rail emits (declared in flight.py beside the
#: core transition kinds; re-exported here for callers of this module)
RETRACE_STORM = _flight.RETRACE_STORM
MEMORY_PRESSURE = _flight.MEMORY_PRESSURE


# ---------------------------------------------------------------------------
# transfer metering (host<->device boundary crossings)
# ---------------------------------------------------------------------------


class _SanctionedCrossing:
    """One declared boundary crossing: counts its tag, and — only while
    a disallow guard is active — re-allows transfers for its extent so
    everything OUTSIDE a sanctioned scope keeps raising."""

    __slots__ = ("_meter", "_tag", "_cm")

    def __init__(self, meter: "TransferMeter", tag: str) -> None:
        self._meter = meter
        self._tag = tag
        self._cm = None

    def __enter__(self) -> "_SanctionedCrossing":
        m = self._meter
        with m.mu:
            m._counts[self._tag] = m._counts.get(self._tag, 0) + 1
            guarding = m._guard_depth > 0
        if guarding:
            self._cm = jax.transfer_guard("allow")
            self._cm.__enter__()
        return self

    def __exit__(self, *exc) -> bool:
        cm, self._cm = self._cm, None
        if cm is not None:
            return bool(cm.__exit__(*exc))
        return False


class _TransferGuard:
    """``jax.transfer_guard("disallow")`` plus the meter's guard-depth
    bookkeeping (sanctioned scopes only pay the allow-context cost when
    a guard is actually active — unguarded runs stay at a dict bump)."""

    __slots__ = ("_meter", "_cm")

    def __init__(self, meter: "TransferMeter") -> None:
        self._meter = meter
        self._cm = None

    def __enter__(self) -> "_TransferGuard":
        m = self._meter
        with m.mu:
            m._guard_depth += 1
        self._cm = jax.transfer_guard("disallow")
        self._cm.__enter__()
        return self

    def __exit__(self, *exc) -> bool:
        m = self._meter
        with m.mu:
            m._guard_depth = max(0, m._guard_depth - 1)
        cm, self._cm = self._cm, None
        return bool(cm.__exit__(*exc)) if cm is not None else False


class TransferMeter:
    """Live host<->device crossing counter behind the transfer-boundary
    contract (analysis/transfer.py).  Every declared crossing site in
    the engine layer wraps its transfer in ``sanctioned(tag)``; the
    transfer lint's dynamic leg and the engine differentials run the
    step loop under ``guard()`` and diff ``counts()`` against the
    static TRANSFER_LEDGER — an unsanctioned implicit transfer raises,
    a sanctioned one is tallied under its declared tag."""

    def __init__(self) -> None:
        self.mu = threading.Lock()
        self._counts: dict = {}    # guarded-by: mu  (tag -> crossings)
        self._guard_depth = 0      # guarded-by: mu

    def sanctioned(self, tag: str) -> _SanctionedCrossing:
        """Context manager for one declared crossing (see class doc)."""
        return _SanctionedCrossing(self, tag)

    def guard(self) -> _TransferGuard:
        """Disallow-implicit-transfers context for tests and lint."""
        return _TransferGuard(self)

    def counts(self) -> dict:
        with self.mu:
            return dict(self._counts)

    def reset(self) -> None:
        with self.mu:
            self._counts.clear()


#: process-wide meter (one-instance doctrine, like TRACKER): the engine
#: layer's sanctioned scopes and the transfer lint's differential read
#: the same tallies
METER = TransferMeter()


# ---------------------------------------------------------------------------
# snapshot plumbing (engine.last_capacity / NodeHost merged view)
# ---------------------------------------------------------------------------

#: exact snapshot key set (validate_capacity rejects drift in either
#: direction)
_INT_KEYS = ("ticks", "capacity", "bytes_in_use", "bytes_peak",
             "device_bytes_in_use", "device_bytes_limit", "budget_bytes",
             "model_bytes_per_group", "model_predicted_bytes",
             "model_max_g_at_budget")
_BOOL_KEYS = ("memory_pressure", "retrace_storm")
_ENTRY_KEYS = ("calls", "compiles", "retraces", "compile_us_total",
               "last_compile_us")


def empty_dict() -> dict:
    """All-zero capacity snapshot (merge identity for hosts with no
    engine)."""
    d = {k: 0 for k in _INT_KEYS}
    d.update({k: False for k in _BOOL_KEYS})
    d["headroom_pct"] = 100.0
    d["entries"] = {}
    return d


def engine_snapshot(kp, num_groups: int, live_bytes: int, peak_bytes: int,
                    entries: dict, budget_bytes: int = 0,
                    watermark_pct: float = 10.0, ticks: int = 0,
                    classes=RESIDENT_CLASSES) -> dict:
    """Assemble one engine's capacity snapshot: measured live/peak tree
    bytes + allocator stats + the contracts model at this geometry +
    per-entry compile counters.  ``memory_pressure`` trips when headroom
    against the budget (explicit, else the device's reported
    bytes_limit) drops below ``watermark_pct``."""
    dev_rows = device_memory_stats()
    dev_in_use = max((r["bytes_in_use"] for r in dev_rows), default=0)
    dev_limit = max((r["bytes_limit"] for r in dev_rows), default=0)
    budget = int(budget_bytes) if budget_bytes > 0 else dev_limit
    used = max(int(live_bytes), dev_in_use)
    if budget > 0:
        headroom = max(0.0, 100.0 * (budget - used) / budget)
        pressure = headroom < float(watermark_pct)
    else:
        headroom, pressure = 100.0, False
    per_group = model_bytes_per_group(kp, classes)["total"]
    return {
        "ticks": int(ticks),
        "capacity": int(num_groups),
        "bytes_in_use": int(live_bytes),
        "bytes_peak": int(peak_bytes),
        "device_bytes_in_use": dev_in_use,
        "device_bytes_limit": dev_limit,
        "budget_bytes": budget,
        "headroom_pct": headroom,
        "memory_pressure": pressure,
        "retrace_storm": any(e["retraces"] > 0 for e in entries.values()),
        "model_bytes_per_group": per_group,
        "model_predicted_bytes": per_group * int(num_groups),
        "model_max_g_at_budget": (budget // per_group
                                  if budget > 0 and per_group > 0 else 0),
        "entries": {name: dict(e) for name, e in entries.items()},
    }


def merge_into(base: dict, other: dict, engine: str | None = None) -> None:
    """Accumulate ``other`` (empty_dict shape) into ``base``: per-engine
    footprints add, device/budget views take the widest, headroom takes
    the tightest, flags OR.  ``engine`` prefixes other's compile entries
    so a merged multi-engine view stays attributable."""
    base["ticks"] = max(base["ticks"], other["ticks"])
    for key in ("capacity", "bytes_in_use", "bytes_peak",
                "model_predicted_bytes"):
        base[key] += other[key]
    for key in ("device_bytes_in_use", "device_bytes_limit",
                "budget_bytes", "model_bytes_per_group"):
        base[key] = max(base[key], other[key])
    base["headroom_pct"] = min(base["headroom_pct"], other["headroom_pct"])
    for key in _BOOL_KEYS:
        base[key] = bool(base[key] or other[key])
    mg, og = base["model_max_g_at_budget"], other["model_max_g_at_budget"]
    base["model_max_g_at_budget"] = (min(mg, og) if mg and og
                                     else max(mg, og))
    for name, ent in other["entries"].items():
        tag = f"{engine}:{name}" if engine else name
        row = base["entries"].setdefault(
            tag, {k: 0 for k in _ENTRY_KEYS})
        for key in ("calls", "compiles", "retraces", "compile_us_total"):
            row[key] += ent[key]
        row["last_compile_us"] = max(row["last_compile_us"],
                                     ent["last_compile_us"])


def register_exposition(registry, source, replace: bool = False) -> None:
    """Register the capacity callback-gauge families on ``registry``,
    backed by ``source()`` -> capacity dict (or None for "no data
    yet").  Idempotent when ``replace`` is False (same ownership
    protocol as fleet/health.register_exposition: a NodeHost's merged
    view claims the names before any engine's device-only one)."""
    if not replace and registry.kind_of("capacity_bytes_in_use") is not None:
        return

    def _get() -> dict:
        d = source()
        return d if d is not None else empty_dict()

    registry.gauge_fn("capacity_bytes_in_use",
                      lambda: _get()["bytes_in_use"],
                      help="live bytes of the engines' resident trees")
    registry.gauge_fn("capacity_bytes_peak",
                      lambda: _get()["bytes_peak"],
                      help="peak live bytes since engine start")
    registry.gauge_fn("capacity_headroom_pct",
                      lambda: _get()["headroom_pct"],
                      help="% headroom against the device budget")
    registry.gauge_fn(
        "capacity_compile_total",
        lambda: {(n,): e["compiles"]
                 for n, e in _get()["entries"].items()},
        help="traces/compiles per jit entry",
        labelnames=("entry",))
    registry.gauge_fn(
        "capacity_retrace_total",
        lambda: {(n,): e["retraces"]
                 for n, e in _get()["entries"].items()},
        help="post-steady-state retraces per jit entry",
        labelnames=("entry",))


# ---------------------------------------------------------------------------
# strict schema validation (fleet_doctor / metrics_dump --capacity)
# ---------------------------------------------------------------------------


def _req_int(obj: dict, key: str, where: str) -> int:
    if key not in obj:
        raise ValueError(f"{where}: missing key {key!r}")
    v = obj[key]
    # bool is an int subclass; reject it where an int is required
    if isinstance(v, bool) or not isinstance(v, int) or v < 0:
        raise ValueError(f"{where}.{key}: expected non-negative int, "
                         f"got {v!r}")
    return v


def validate_capacity(cap: dict, where: str = "capacity") -> None:
    """Strictly check an ``empty_dict``-shaped capacity snapshot (the
    ``/debug/capacity`` payload and the ``/debug/groups`` ``capacity``
    section).  Raises ValueError naming the offending path."""
    if not isinstance(cap, dict):
        raise ValueError(f"{where}: expected dict, got {type(cap)}")
    for key in _INT_KEYS:
        _req_int(cap, key, where)
    for key in _BOOL_KEYS:
        if key not in cap:
            raise ValueError(f"{where}: missing key {key!r}")
        if not isinstance(cap[key], bool):
            raise ValueError(f"{where}.{key}: expected bool, "
                             f"got {cap[key]!r}")
    if "headroom_pct" not in cap:
        raise ValueError(f"{where}: missing key 'headroom_pct'")
    hr = cap["headroom_pct"]
    if isinstance(hr, bool) or not isinstance(hr, (int, float)) or hr < 0:
        raise ValueError(f"{where}.headroom_pct: expected non-negative "
                         f"number, got {hr!r}")
    if not isinstance(cap.get("entries"), dict):
        raise ValueError(f"{where}.entries: expected dict")
    for name, ent in cap["entries"].items():
        ew = f"{where}.entries[{name!r}]"
        if not isinstance(ent, dict):
            raise ValueError(f"{ew}: expected dict")
        for key in _ENTRY_KEYS:
            _req_int(ent, key, ew)
        extra = set(ent) - set(_ENTRY_KEYS)
        if extra:
            raise ValueError(f"{ew}: unexpected keys {sorted(extra)}")
    extra = set(cap) - set(_INT_KEYS) - set(_BOOL_KEYS) - {
        "headroom_pct", "entries"}
    if extra:
        raise ValueError(f"{where}: unexpected keys {sorted(extra)}")
