"""In-memory log rate limiter — parity ``internal/server/rate.go``.

Tracks the byte size of a shard's not-yet-applied log tail; when it
exceeds ``Config.max_in_mem_log_size`` the shard reports rate-limited and
new proposals are rejected with system-busy until applies drain the tail
(the reference additionally aggregates follower states; here the local
size is the signal — the leader is where proposals arrive)."""

from __future__ import annotations

import threading


class RateLimiter:
    def __init__(self, max_size: int) -> None:
        self.max_size = max_size
        self._size = 0
        self._mu = threading.Lock()

    def enabled(self) -> bool:
        return self.max_size > 0

    def increase(self, n: int) -> None:
        with self._mu:
            self._size += n

    def decrease(self, n: int) -> None:
        with self._mu:
            self._size = max(0, self._size - n)

    def reset(self) -> None:
        with self._mu:
            self._size = 0

    def get(self) -> int:
        with self._mu:
            return self._size

    def rate_limited(self) -> bool:
        if not self.enabled():
            return False
        with self._mu:
            return self._size > self.max_size
