"""Hard/soft settings — parity with ``internal/settings/hard.go:5-21``.

Hard settings can NEVER change once a deployment has written data; their
hash is stamped into the data dir's flag file and checked on every reopen
(environment.go check → ErrHardSettingsChanged).  Like the reference, a
``dragonboat-tpu-hard-settings.json`` file in the working directory can
override the defaults at first deployment time.
"""

from __future__ import annotations

import hashlib
import json
import os
from dataclasses import asdict, dataclass, fields


@dataclass(frozen=True)
class HardSettings:
    """Values that shape the on-disk format (hard.go hard struct)."""

    # max client sessions concurrently tracked per raft shard (hard.go)
    lru_max_session_count: int = 4096
    # max size of each entry batch in the log engine (hard.go)
    logdb_entry_batch_size: int = 48
    # block size of the snapshot file format (rsm/snapshotio block CRC)
    snapshot_block_size: int = 128 * 1024

    def hash(self) -> int:
        """Deterministic stamp of every hard value (hard.go Hash())."""
        h = hashlib.md5()
        for f in fields(self):
            h.update(f.name.encode())
            h.update(str(getattr(self, f.name)).encode())
        return int.from_bytes(h.digest()[:8], "little")


@dataclass(frozen=True)
class SoftSettings:
    """Tunables that do NOT affect the data format (soft.go excerpt)."""

    # engine ingress queue lengths (soft.go GetSoftSettings)
    incoming_proposal_queue_length: int = 2048
    incoming_read_index_queue_length: int = 4096
    # snapshot chunk streaming
    snapshot_chunk_size: int = 2 * 1024 * 1024
    max_concurrent_streaming_snapshots: int = 128
    # in-memory log growth guard (logentry GC trigger)
    in_mem_gc_timeout: int = 100


def _load(cls, filename: str):
    defaults = cls()
    try:
        with open(os.path.join(os.getcwd(), filename)) as f:
            overrides = json.load(f)
    except (OSError, ValueError):
        return defaults
    known = {f.name for f in fields(cls)}
    vals = asdict(defaults)
    vals.update({k: v for k, v in overrides.items() if k in known})
    return cls(**vals)


hard: HardSettings = _load(HardSettings, "dragonboat-tpu-hard-settings.json")
soft: SoftSettings = _load(SoftSettings, "dragonboat-tpu-soft-settings.json")
