"""server — host environment services (dirs, locks, identity, settings).

Parity with the reference's ``internal/server`` (environment.go) and
``internal/settings`` (hard.go): the NodeHost data-directory hierarchy,
exclusive dir locking, the on-disk flag file that pins address/hostname/
deployment-id/LogDB-type/hard-settings so an incompatible reopen is
refused, and the persistent NodeHost identity.
"""

from dragonboat_tpu.server.env import (
    DirLockedError,
    Env,
    IncompatibleDataError,
    NotOwnerError,
)
from dragonboat_tpu.server.settings import HardSettings, hard

__all__ = [
    "DirLockedError",
    "Env",
    "HardSettings",
    "IncompatibleDataError",
    "NotOwnerError",
    "hard",
]
