"""Env — NodeHost data-directory management.

Parity with ``internal/server/environment.go``:

- dir hierarchy  ``<node_host_dir>/<deployment_id %020d>/<host-part>/``
  (getDeploymentIDSubDirName :376; the host-part keeps multiple in-process
  NodeHosts on one box separate, like the reference's per-address dirs);
- exclusive LOCK file via flock so two NodeHosts can never share a data
  dir (:290 LockNodeHostDir, ErrLockDirectory);
- ``dragonboat.ds`` flag file pinning raft address, hostname, deployment
  id, LogDB type, binary version and the hard-settings hash — any
  mismatch refuses the reopen (:390 check, ErrNotOwner /
  ErrHostnameChanged / ErrDeploymentIDChanged / ErrLogDBType /
  ErrIncompatibleData);
- persistent NodeHost identity (NODEHOST.ID; :206-270);
- per-shard snapshot dirs with a REMOVED tombstone flag (:127-204, :304).
"""

from __future__ import annotations

import json
import os
import socket
import uuid

from dragonboat_tpu.logger import get_logger
from dragonboat_tpu.server.settings import hard

_LOG = get_logger("server")

LOCK_FILENAME = "LOCK"
FLAG_FILENAME = "dragonboat.ds"
NHID_FILENAME = "NODEHOST.ID"
REMOVED_FLAG = "REMOVED.dbtpu"
BIN_VER = 1


class EnvError(Exception):
    pass


class DirLockedError(EnvError):
    """Another NodeHost holds the data dir (ErrLockDirectory)."""


class NotOwnerError(EnvError):
    """The data dir belongs to a different raft address (ErrNotOwner)."""


class IncompatibleDataError(EnvError):
    """Hostname / deployment id / LogDB type / bin ver / hard settings
    changed since the dir was created."""


def _sanitize(addr: str) -> str:
    return "".join(c if (c.isalnum() or c in "._-") else "_" for c in addr)


class Env:
    """One NodeHost's view of its data directories."""

    def __init__(self, node_host_dir: str, raft_address: str,
                 deployment_id: int = 0, wal_dir: str = "",
                 fs=None) -> None:
        from dragonboat_tpu.vfs import default_fs

        self.fs = fs if fs is not None else default_fs()
        self.raft_address = raft_address
        self.deployment_id = deployment_id
        self.hostname = socket.gethostname()
        suffix = (f"{deployment_id:020d}", _sanitize(raft_address))
        self.root = os.path.join(os.path.abspath(node_host_dir), *suffix)
        # WALDir (config.go): optionally place the raft log on a separate
        # (low-latency) volume; everything else stays under the root
        self.wal_root = (os.path.join(os.path.abspath(wal_dir), *suffix)
                         if wal_dir else self.root)
        self.fs.makedirs(self.root)
        if self.wal_root != self.root:
            self.fs.makedirs(self.wal_root)
        self._lock_files: list = []
        self._nhid: str | None = None

    # -- dirs -------------------------------------------------------------

    @property
    def logdb_dir(self) -> str:
        d = os.path.join(self.wal_root, "logdb")
        self.fs.makedirs(d)
        return d

    def snapshot_dir(self, shard_id: int, replica_id: int) -> str:
        """GetSnapshotDir (:127): per-replica snapshot home."""
        d = os.path.join(
            self.root, "snapshot",
            f"snapshot-{shard_id:016X}-{replica_id:016X}",
        )
        self.fs.makedirs(d)
        return d

    def remove_snapshot_dir(self, shard_id: int, replica_id: int) -> None:
        """RemoveSnapshotDir (:304): tombstone then best-effort delete."""
        d = self.snapshot_dir(shard_id, replica_id)
        with self.fs.open(os.path.join(d, REMOVED_FLAG), "w") as f:
            f.write("removed\n")
            self.fs.fsync(f)
        for fn in self.fs.listdir(d):
            if fn != REMOVED_FLAG:
                try:
                    self.fs.remove(os.path.join(d, fn))
                except OSError:
                    pass

    def snapshot_dir_removed(self, shard_id: int, replica_id: int) -> bool:
        return self.fs.exists(os.path.join(
            self.snapshot_dir(shard_id, replica_id), REMOVED_FLAG))

    # -- locking ----------------------------------------------------------

    def lock(self) -> None:
        """LockNodeHostDir (:290): exclusive, non-blocking flock on every
        data root (the WAL volume included — two NodeHosts must never
        share a log directory)."""
        if self._lock_files:
            return
        dirs = [self.root]
        if self.wal_root != self.root:
            dirs.append(self.wal_root)
        for d in dirs:
            fp = os.path.join(d, LOCK_FILENAME)
            f = self.fs.open(fp, "a+")
            try:
                self.fs.flock_exclusive(f)
            except OSError:
                f.close()
                self.close()
                raise DirLockedError(
                    f"failed to lock data directory {d}: another "
                    f"NodeHost is using it")
            self._lock_files.append(f)

    def close(self) -> None:
        for f in self._lock_files:
            try:
                self.fs.flock_unlock(f)
            except OSError:
                pass
            f.close()
        self._lock_files = []

    # -- flag file (dragonboat.ds) -----------------------------------------

    def check_node_host_dir(self, logdb_type: str,
                            compatible: tuple[str, ...] = ()) -> None:
        """check (:390): create or validate the data-status flag file, in
        the root AND on the WAL volume (checkNodeHostDir validates both
        data dirs).  The root flag records whether a separate WAL dir was
        in use, so reopening with a changed wal_dir is refused instead of
        silently starting from an empty raft log.

        ``compatible`` lists legacy logdb_type strings this engine can
        open by in-place migration; a matching legacy flag is rewritten
        to the current type so an OLD binary cannot later open the
        migrated dir and silently see an empty log."""
        status = {
            "address": self.raft_address,
            "hostname": self.hostname,
            "deployment_id": self.deployment_id,
            "logdb_type": logdb_type,
            "bin_ver": BIN_VER,
            "hard_hash": hard.hash(),
            "wal": self.wal_root if self.wal_root != self.root else "",
        }
        dirs = [self.root]
        if self.wal_root != self.root:
            dirs.append(self.wal_root)
        # validate EVERY dir before rewriting ANY legacy flag: a refused
        # open (wrong owner/hostname/hard-hash/...) must leave the dir
        # untouched for its rightful binary
        rewrite = [self._check_dir(d, status, compatible) for d in dirs]
        for d, legacy in zip(dirs, rewrite):
            if legacy:
                fp = os.path.join(d, FLAG_FILENAME)
                with self.fs.open(fp, "r") as f:
                    saved = json.loads(f.read())
                saved["logdb_type"] = status["logdb_type"]
                self._write_flag(fp, saved)

    def _write_flag(self, fp: str, status: dict) -> None:
        tmp = fp + ".tmp"
        with self.fs.open(tmp, "w") as f:
            json.dump(status, f)
            self.fs.fsync(f)
        self.fs.replace(tmp, fp)

    def _check_dir(self, d: str, status: dict,
                   compatible: tuple[str, ...] = ()) -> bool:
        """Returns True when the dir carries a legacy-compatible flag the
        caller should rewrite AFTER all dirs validate."""
        fp = os.path.join(d, FLAG_FILENAME)
        if not self.fs.exists(fp):
            self._write_flag(fp, status)
            return False
        with self.fs.open(fp, "r") as f:
            saved = json.loads(f.read())
        legacy = saved.get("logdb_type") in compatible
        if legacy:
            saved = dict(saved)
            saved["logdb_type"] = status["logdb_type"]
        if saved.get("address", "").strip().lower() != \
                self.raft_address.strip().lower():
            raise NotOwnerError(
                f"data dir {d} belongs to raft address "
                f"{saved.get('address')!r}, not {self.raft_address!r}")
        if saved.get("hostname") and saved["hostname"] != self.hostname:
            raise IncompatibleDataError(
                f"hostname changed: {saved['hostname']} -> {self.hostname}")
        if saved.get("deployment_id", 0) != self.deployment_id:
            raise IncompatibleDataError(
                f"deployment id changed: {saved.get('deployment_id')} -> "
                f"{self.deployment_id}")
        if saved.get("logdb_type") and \
                saved["logdb_type"] != status["logdb_type"]:
            raise IncompatibleDataError(
                f"LogDB type changed: {saved['logdb_type']} -> "
                f"{status['logdb_type']}")
        if saved.get("bin_ver") != BIN_VER:
            raise IncompatibleDataError(
                f"binary version changed: {saved.get('bin_ver')} -> {BIN_VER}")
        if saved.get("hard_hash") != hard.hash():
            raise IncompatibleDataError(
                "hard settings changed since this deployment was created — "
                "refusing to open (would corrupt data)")
        if saved.get("wal", "") != status["wal"]:
            raise IncompatibleDataError(
                f"WALDir changed: {saved.get('wal') or '<none>'} -> "
                f"{status['wal'] or '<none>'} — the raft log would be "
                f"left behind")
        return legacy

    # -- identity ----------------------------------------------------------

    def node_host_id(self) -> str:
        """Persistent NodeHost identity (:206 NodeHostID / :212 Prepare)."""
        if self._nhid is not None:
            return self._nhid
        fp = os.path.join(self.root, NHID_FILENAME)
        if self.fs.exists(fp):
            with self.fs.open(fp, "r") as f:
                self._nhid = f.read().strip()
        else:
            self._nhid = f"nhid-{uuid.uuid4()}"
            tmp = fp + ".tmp"
            with self.fs.open(tmp, "w") as f:
                f.write(self._nhid + "\n")
                self.fs.fsync(f)
            self.fs.replace(tmp, fp)
        return self._nhid
