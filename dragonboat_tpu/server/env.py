"""Env — NodeHost data-directory management.

Parity with ``internal/server/environment.go``:

- dir hierarchy  ``<node_host_dir>/<deployment_id %020d>/<host-part>/``
  (getDeploymentIDSubDirName :376; the host-part keeps multiple in-process
  NodeHosts on one box separate, like the reference's per-address dirs);
- exclusive LOCK file via flock so two NodeHosts can never share a data
  dir (:290 LockNodeHostDir, ErrLockDirectory);
- ``dragonboat.ds`` flag file pinning raft address, hostname, deployment
  id, LogDB type, binary version and the hard-settings hash — any
  mismatch refuses the reopen (:390 check, ErrNotOwner /
  ErrHostnameChanged / ErrDeploymentIDChanged / ErrLogDBType /
  ErrIncompatibleData);
- persistent NodeHost identity (NODEHOST.ID; :206-270);
- per-shard snapshot dirs with a REMOVED tombstone flag (:127-204, :304).
"""

from __future__ import annotations

import fcntl
import json
import os
import socket
import uuid

from dragonboat_tpu.logger import get_logger
from dragonboat_tpu.server.settings import hard

_LOG = get_logger("server")

LOCK_FILENAME = "LOCK"
FLAG_FILENAME = "dragonboat.ds"
NHID_FILENAME = "NODEHOST.ID"
REMOVED_FLAG = "REMOVED.dbtpu"
BIN_VER = 1


class EnvError(Exception):
    pass


class DirLockedError(EnvError):
    """Another NodeHost holds the data dir (ErrLockDirectory)."""


class NotOwnerError(EnvError):
    """The data dir belongs to a different raft address (ErrNotOwner)."""


class IncompatibleDataError(EnvError):
    """Hostname / deployment id / LogDB type / bin ver / hard settings
    changed since the dir was created."""


def _sanitize(addr: str) -> str:
    return "".join(c if (c.isalnum() or c in "._-") else "_" for c in addr)


class Env:
    """One NodeHost's view of its data directories."""

    def __init__(self, node_host_dir: str, raft_address: str,
                 deployment_id: int = 0) -> None:
        self.raft_address = raft_address
        self.deployment_id = deployment_id
        self.hostname = socket.gethostname()
        self.root = os.path.join(
            os.path.abspath(node_host_dir),
            f"{deployment_id:020d}",
            _sanitize(raft_address),
        )
        os.makedirs(self.root, exist_ok=True)
        self._lock_file = None
        self._nhid: str | None = None

    # -- dirs -------------------------------------------------------------

    @property
    def logdb_dir(self) -> str:
        d = os.path.join(self.root, "logdb")
        os.makedirs(d, exist_ok=True)
        return d

    def snapshot_dir(self, shard_id: int, replica_id: int) -> str:
        """GetSnapshotDir (:127): per-replica snapshot home."""
        d = os.path.join(
            self.root, "snapshot",
            f"snapshot-{shard_id:016X}-{replica_id:016X}",
        )
        os.makedirs(d, exist_ok=True)
        return d

    def remove_snapshot_dir(self, shard_id: int, replica_id: int) -> None:
        """RemoveSnapshotDir (:304): tombstone then best-effort delete."""
        d = self.snapshot_dir(shard_id, replica_id)
        with open(os.path.join(d, REMOVED_FLAG), "w") as f:
            f.write("removed\n")
            f.flush()
            os.fsync(f.fileno())
        for fn in os.listdir(d):
            if fn != REMOVED_FLAG:
                try:
                    os.remove(os.path.join(d, fn))
                except OSError:
                    pass

    def snapshot_dir_removed(self, shard_id: int, replica_id: int) -> bool:
        return os.path.exists(os.path.join(
            self.snapshot_dir(shard_id, replica_id), REMOVED_FLAG))

    # -- locking ----------------------------------------------------------

    def lock(self) -> None:
        """LockNodeHostDir (:290): exclusive, non-blocking flock."""
        if self._lock_file is not None:
            return
        fp = os.path.join(self.root, LOCK_FILENAME)
        f = open(fp, "a+")
        try:
            fcntl.flock(f.fileno(), fcntl.LOCK_EX | fcntl.LOCK_NB)
        except OSError:
            f.close()
            raise DirLockedError(
                f"failed to lock data directory {self.root}: another "
                f"NodeHost is using it")
        self._lock_file = f

    def close(self) -> None:
        if self._lock_file is not None:
            fcntl.flock(self._lock_file.fileno(), fcntl.LOCK_UN)
            self._lock_file.close()
            self._lock_file = None

    # -- flag file (dragonboat.ds) -----------------------------------------

    def check_node_host_dir(self, logdb_type: str) -> None:
        """check (:390): create or validate the data-status flag file."""
        fp = os.path.join(self.root, FLAG_FILENAME)
        status = {
            "address": self.raft_address,
            "hostname": self.hostname,
            "deployment_id": self.deployment_id,
            "logdb_type": logdb_type,
            "bin_ver": BIN_VER,
            "hard_hash": hard.hash(),
        }
        if not os.path.exists(fp):
            tmp = fp + ".tmp"
            with open(tmp, "w") as f:
                json.dump(status, f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, fp)
            return
        with open(fp) as f:
            saved = json.load(f)
        if saved.get("address", "").strip().lower() != \
                self.raft_address.strip().lower():
            raise NotOwnerError(
                f"data dir {self.root} belongs to raft address "
                f"{saved.get('address')!r}, not {self.raft_address!r}")
        if saved.get("hostname") and saved["hostname"] != self.hostname:
            raise IncompatibleDataError(
                f"hostname changed: {saved['hostname']} -> {self.hostname}")
        if saved.get("deployment_id", 0) != self.deployment_id:
            raise IncompatibleDataError(
                f"deployment id changed: {saved.get('deployment_id')} -> "
                f"{self.deployment_id}")
        if saved.get("logdb_type") and saved["logdb_type"] != logdb_type:
            raise IncompatibleDataError(
                f"LogDB type changed: {saved['logdb_type']} -> {logdb_type}")
        if saved.get("bin_ver") != BIN_VER:
            raise IncompatibleDataError(
                f"binary version changed: {saved.get('bin_ver')} -> {BIN_VER}")
        if saved.get("hard_hash") != hard.hash():
            raise IncompatibleDataError(
                "hard settings changed since this deployment was created — "
                "refusing to open (would corrupt data)")

    # -- identity ----------------------------------------------------------

    def node_host_id(self) -> str:
        """Persistent NodeHost identity (:206 NodeHostID / :212 Prepare)."""
        if self._nhid is not None:
            return self._nhid
        fp = os.path.join(self.root, NHID_FILENAME)
        if os.path.exists(fp):
            with open(fp) as f:
                self._nhid = f.read().strip()
        else:
            self._nhid = f"nhid-{uuid.uuid4()}"
            tmp = fp + ".tmp"
            with open(tmp, "w") as f:
                f.write(self._nhid + "\n")
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, fp)
        return self._nhid
