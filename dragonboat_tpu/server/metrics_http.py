"""Stdlib-http /metrics endpoint (opt-in via NodeHostConfig.enable_metrics).

Serves the Prometheus text exposition of one or more
``telemetry.Registry`` objects (a NodeHost serves its per-hub registry
concatenated with the process-global one that module-scoped producers
like the logdb engines write to), plus ``/flight`` — the flight
recorder tail as JSON — ``/trace`` — the lifecycle tracer's completed
proposal spans as Chrome-trace-event JSON, loadable directly in
Perfetto / chrome://tracing — and ``/healthz``.

A ``ThreadingHTTPServer`` on a daemon thread: scrapes never run on an
engine thread, and the collect path takes no registry lock while
evaluating callback gauges (see telemetry.Registry.collect), so a
scrape cannot invert against engine-held host locks.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from dragonboat_tpu import flight
from dragonboat_tpu import lifecycle
from dragonboat_tpu.logger import get_logger

_LOG = get_logger("metrics_http")

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


class MetricsServer:
    """One /metrics listener over a list of registries."""

    def __init__(self, registries, address: str = "127.0.0.1:0",
                 flight_recorder=None, tracer=None) -> None:
        self.registries = list(registries)
        self.flight_recorder = (flight_recorder if flight_recorder
                                is not None else flight.RECORDER)
        self.tracer = tracer if tracer is not None else lifecycle.TRACER
        host, _, port = address.rpartition(":")
        if not host:
            host, port = address or "127.0.0.1", "0"
        outer = self

        class _Handler(BaseHTTPRequestHandler):
            def do_GET(self) -> None:          # noqa: N802 (http.server API)
                path = self.path.split("?", 1)[0]
                if path == "/metrics":
                    body = outer.render().encode("utf-8")
                    ctype = CONTENT_TYPE
                elif path == "/flight":
                    body = (outer.flight_recorder.dump_json(indent=2)
                            + "\n").encode("utf-8")
                    ctype = "application/json"
                elif path == "/trace":
                    body = (json.dumps(outer.tracer.export_chrome_trace(),
                                       sort_keys=True)
                            + "\n").encode("utf-8")
                    ctype = "application/json"
                elif path == "/healthz":
                    body = b"ok\n"
                    ctype = "text/plain"
                else:
                    self.send_error(404)
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, fmt, *args) -> None:
                _LOG.debug("metrics http: " + fmt, *args)

        self._httpd = ThreadingHTTPServer((host, int(port)), _Handler)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name=f"metrics-http-{self._httpd.server_address[1]}",
            daemon=True)
        self._thread.start()

    @property
    def address(self) -> str:
        host, port = self._httpd.server_address[:2]
        return f"{host}:{port}"

    def render(self) -> str:
        return "".join(r.exposition() for r in self.registries)

    def close(self) -> None:
        self._httpd.shutdown()
        self._thread.join(timeout=2)
        self._httpd.server_close()
