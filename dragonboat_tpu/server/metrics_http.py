"""Stdlib-http /metrics endpoint (opt-in via NodeHostConfig.enable_metrics).

Serves the Prometheus text exposition of one or more
``telemetry.Registry`` objects (a NodeHost serves its per-hub registry
concatenated with the process-global one that module-scoped producers
like the logdb engines write to), plus ``/flight`` — the flight
recorder tail as JSON — ``/trace`` — the lifecycle tracer's completed
proposal spans as Chrome-trace-event JSON, loadable directly in
Perfetto / chrome://tracing — ``/healthz``, and the fleet-health
drill-down pair ``/debug/groups`` (NodeHost.info(): health summary +
NodeHostInfo-parity shard list) and ``/debug/group/<id>``
(NodeHost.shard_info(): one group's O(1) device row + host registers),
``/debug/capacity`` (capacity.py merged snapshot: live/peak bytes,
headroom, per-entry compile counters), and ``/debug/fabric``
(fabric.py: per-link transport telemetry + the commit-path hop
census).  ``/trace`` merges the compile tracker's spans and the
fabric meter's remote child spans into the lifecycle ring's, so one
Perfetto timeline shows proposals beside the compiles that stalled
them and the remote hosts their quorum rounds touched.

``/healthz`` is honest: with a ``health_source`` wired (core/health.py
merged snapshot), any nonzero anomaly-class count turns it into a 503
with a structured JSON body naming the tripped classes; a
``capacity_source`` reporting memory pressure or a retrace storm
degrades it the same way (with a ``capacity`` section in the body);
without either it keeps the legacy unconditional ``ok``.

A ``ThreadingHTTPServer`` on a daemon thread: scrapes never run on an
engine thread, and the collect path takes no registry lock while
evaluating callback gauges (see telemetry.Registry.collect), so a
scrape cannot invert against engine-held host locks.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from dragonboat_tpu import flight
from dragonboat_tpu import lifecycle
from dragonboat_tpu.logger import get_logger

_LOG = get_logger("metrics_http")

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


class MetricsServer:
    """One /metrics listener over a list of registries."""

    def __init__(self, registries, address: str = "127.0.0.1:0",
                 flight_recorder=None, tracer=None,
                 health_source=None, info_source=None,
                 shard_info_source=None, capacity_source=None,
                 compile_tracker=None, invariants_source=None,
                 fabric_source=None, fabric_trace_source=None) -> None:
        self.registries = list(registries)
        self.flight_recorder = (flight_recorder if flight_recorder
                                is not None else flight.RECORDER)
        self.tracer = tracer if tracer is not None else lifecycle.TRACER
        # health_source() -> health dict (core/health.py empty_dict
        # shape); info_source() -> NodeHost.info() dict;
        # shard_info_source(shard_id) -> dict | None;
        # capacity_source() -> capacity dict (capacity.py empty_dict
        # shape) — serves /debug/capacity and widens /healthz
        self.health_source = health_source
        self.info_source = info_source
        self.shard_info_source = shard_info_source
        self.capacity_source = capacity_source
        # invariants_source() -> invariants dict (core/invariants.py
        # empty_dict shape + violations_seen) — widens /healthz: a
        # protocol-invariant violation is a BUG, so the degradation is
        # sticky (violations_seen, not the instantaneous total)
        self.invariants_source = invariants_source
        # fabric_source() -> fabric.FabricMeter.snapshot() dict (serves
        # /debug/fabric); fabric_trace_source() -> remote child spans as
        # Chrome events, merged into /trace so one Perfetto timeline
        # shows the origin's span beside every remote host it touched
        self.fabric_source = fabric_source
        self.fabric_trace_source = fabric_trace_source
        if compile_tracker is None:
            # imported here, not at module top: capacity.py pulls jax,
            # which importers of this module must not pay for eagerly
            from dragonboat_tpu import capacity as _capacity

            compile_tracker = _capacity.TRACKER
        self.compile_tracker = compile_tracker
        host, _, port = address.rpartition(":")
        if not host:
            host, port = address or "127.0.0.1", "0"
        outer = self

        class _Handler(BaseHTTPRequestHandler):
            def do_GET(self) -> None:          # noqa: N802 (http.server API)
                path = self.path.split("?", 1)[0]
                status = 200
                if path == "/metrics":
                    body = outer.render().encode("utf-8")
                    ctype = CONTENT_TYPE
                elif path == "/flight":
                    body = (outer.flight_recorder.dump_json(indent=2)
                            + "\n").encode("utf-8")
                    ctype = "application/json"
                elif path == "/trace":
                    # one timeline: proposal spans beside compile spans
                    # and the fabric's remote child spans (distinct pid
                    # rows in Perfetto / chrome://tracing; remote spans
                    # share the proposal's tid, stitching the hosts)
                    trace = outer.tracer.export_chrome_trace()
                    events = (list(trace.get("traceEvents", ()))
                              + outer.compile_tracker.chrome_events())
                    if outer.fabric_trace_source is not None:
                        events += outer.fabric_trace_source()
                    trace["traceEvents"] = events
                    body = (json.dumps(trace, sort_keys=True)
                            + "\n").encode("utf-8")
                    ctype = "application/json"
                elif path == "/healthz":
                    status, body, ctype = outer.healthz()
                elif path == "/debug/fabric" and outer.fabric_source:
                    body = (json.dumps(outer.fabric_source(),
                                       sort_keys=True)
                            + "\n").encode("utf-8")
                    ctype = "application/json"
                elif path == "/debug/capacity" and outer.capacity_source:
                    body = (json.dumps(outer.capacity_source(),
                                       sort_keys=True)
                            + "\n").encode("utf-8")
                    ctype = "application/json"
                elif path == "/debug/groups" and outer.info_source:
                    body = (json.dumps(outer.info_source(), sort_keys=True)
                            + "\n").encode("utf-8")
                    ctype = "application/json"
                elif (path.startswith("/debug/group/")
                        and outer.shard_info_source):
                    try:
                        sid = int(path[len("/debug/group/"):])
                    except ValueError:
                        self.send_error(404)
                        return
                    d = outer.shard_info_source(sid)
                    if d is None:
                        self.send_error(404)
                        return
                    body = (json.dumps(d, sort_keys=True)
                            + "\n").encode("utf-8")
                    ctype = "application/json"
                else:
                    self.send_error(404)
                    return
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, fmt, *args) -> None:
                _LOG.debug("metrics http: " + fmt, *args)

        self._httpd = ThreadingHTTPServer((host, int(port)), _Handler)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name=f"metrics-http-{self._httpd.server_address[1]}",
            daemon=True)
        self._thread.start()

    @property
    def address(self) -> str:
        host, port = self._httpd.server_address[:2]
        return f"{host}:{port}"

    def healthz(self) -> tuple[int, bytes, str]:
        """(status, body, content-type) for /healthz: degraded (503 +
        structured JSON) when any anomaly-class count is nonzero, when
        the capacity view reports memory pressure / a retrace storm, or
        when the invariant probe has EVER seen a protocol-invariant
        violation (sticky — a violation is a bug, not a condition that
        clears)."""
        h = (self.health_source() if self.health_source is not None
             else None)
        counts = h.get("class_count", {}) if h else {}
        tripped = {c: n for c, n in counts.items() if n}
        cap = (self.capacity_source() if self.capacity_source is not None
               else None)
        cap_tripped = [k for k in ("memory_pressure", "retrace_storm")
                       if cap and cap.get(k)]
        inv = (self.invariants_source()
               if self.invariants_source is not None else None)
        inv_tripped = bool(inv) and (inv.get("violations_seen", 0) > 0
                                     or inv.get("total", 0) > 0)
        if not tripped and not cap_tripped and not inv_tripped:
            return 200, b"ok\n", "text/plain"
        payload = {
            "status": "degraded",
            "class_count": counts,
            "anomalous": h.get("anomalous", 0) if h else 0,
            "worst": h.get("worst", []) if h else [],
        }
        if cap_tripped:
            payload["capacity"] = {
                "tripped": cap_tripped,
                "headroom_pct": cap["headroom_pct"],
                "bytes_in_use": cap["bytes_in_use"],
                "budget_bytes": cap["budget_bytes"],
                "entries": cap["entries"],
            }
        if inv_tripped:
            payload["invariants"] = {
                "total": inv.get("total", 0),
                "violations_seen": inv.get("violations_seen", 0),
                "per_invariant": inv.get("per_invariant", {}),
                "first": inv.get("first"),
            }
        body = json.dumps(payload, sort_keys=True) + "\n"
        return 503, body.encode("utf-8"), "application/json"

    def render(self) -> str:
        return "".join(r.exposition() for r in self.registries)

    def close(self) -> None:
        self._httpd.shutdown()
        self._thread.join(timeout=2)
        self._httpd.server_close()
