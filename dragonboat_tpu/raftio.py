"""raftio — the storage/transport/observability plugin seams.

Parity with the reference's ``raftio/`` package: ILogDB (logdb.go:61-110),
ITransport + connection types (transport.go:54-80), INodeRegistry
(registry.go), and the event listener interfaces (listener.go:33,59).
These are THE seams the survey says must be reproduced (SURVEY §1).
"""

from __future__ import annotations

import abc
import enum
from dataclasses import dataclass
from typing import Callable, Protocol, Sequence

from dragonboat_tpu import raftpb as pb


@dataclass(frozen=True)
class NodeInfo:
    shard_id: int
    replica_id: int


@dataclass(frozen=True)
class RaftState:
    state: pb.State
    first_index: int
    entry_count: int


class ILogDB(abc.ABC):
    """Persistent log storage — parity raftio/logdb.go:61-110.

    save_raft_state carries the single-writer-per-worker contract of the
    reference (:78-83): the engine calls it with a batch of Updates from one
    step slot; the implementation must make them durable before returning."""

    @abc.abstractmethod
    def name(self) -> str: ...

    @abc.abstractmethod
    def close(self) -> None: ...

    @abc.abstractmethod
    def list_node_info(self) -> list[NodeInfo]: ...

    @abc.abstractmethod
    def save_bootstrap_info(self, shard_id: int, replica_id: int,
                            bootstrap: pb.Bootstrap) -> None: ...

    @abc.abstractmethod
    def get_bootstrap_info(self, shard_id: int,
                           replica_id: int) -> pb.Bootstrap | None: ...

    @abc.abstractmethod
    def save_raft_state(self, updates: Sequence[pb.Update],
                        worker_id: int) -> None: ...

    @abc.abstractmethod
    def iterate_entries(self, shard_id: int, replica_id: int, low: int,
                        high: int, max_size: int) -> list[pb.Entry]: ...

    @abc.abstractmethod
    def read_raft_state(self, shard_id: int, replica_id: int,
                        last_index: int) -> RaftState | None: ...

    @abc.abstractmethod
    def remove_entries_to(self, shard_id: int, replica_id: int,
                          index: int) -> None: ...

    @abc.abstractmethod
    def compact_entries_to(self, shard_id: int, replica_id: int,
                           index: int) -> None: ...

    @abc.abstractmethod
    def save_snapshots(self, updates: Sequence[pb.Update]) -> None: ...

    @abc.abstractmethod
    def get_snapshot(self, shard_id: int,
                     replica_id: int) -> pb.Snapshot | None: ...

    @abc.abstractmethod
    def remove_node_data(self, shard_id: int, replica_id: int) -> None: ...

    @abc.abstractmethod
    def import_snapshot(self, snapshot: pb.Snapshot,
                        replica_id: int) -> None: ...


class IConnection(Protocol):
    """Message-batch connection — raftio/transport.go."""

    def close(self) -> None: ...
    def send_message_batch(self, batch: pb.MessageBatch) -> None: ...


class ISnapshotConnection(Protocol):
    def close(self) -> None: ...
    def send_chunk(self, chunk: dict) -> None: ...


class ITransport(abc.ABC):
    """Raft transport — parity raftio/transport.go:54-80."""

    @abc.abstractmethod
    def name(self) -> str: ...

    @abc.abstractmethod
    def start(self) -> None: ...

    @abc.abstractmethod
    def close(self) -> None: ...

    @abc.abstractmethod
    def get_connection(self, target: str) -> IConnection: ...

    @abc.abstractmethod
    def get_snapshot_connection(self, target: str) -> ISnapshotConnection: ...


MessageHandler = Callable[[pb.MessageBatch], None]
ChunkHandler = Callable[[dict], bool]


class INodeRegistry(abc.ABC):
    """Address resolution — parity raftio/registry.go."""

    @abc.abstractmethod
    def add(self, shard_id: int, replica_id: int, url: str) -> None: ...

    @abc.abstractmethod
    def remove(self, shard_id: int, replica_id: int) -> None: ...

    @abc.abstractmethod
    def remove_shard(self, shard_id: int) -> None: ...

    @abc.abstractmethod
    def resolve(self, shard_id: int, replica_id: int) -> tuple[str, str]:
        """Returns (address, connection key)."""


# ---------------------------------------------------------------------------
# event listeners (raftio/listener.go)
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class LeaderInfo:
    shard_id: int
    replica_id: int
    term: int
    leader_id: int


@dataclass(frozen=True)
class CampaignInfo:
    shard_id: int
    replica_id: int
    term: int


@dataclass(frozen=True)
class SnapshotInfo:
    shard_id: int
    replica_id: int
    from_: int
    index: int
    term: int


@dataclass(frozen=True)
class EntryInfo:
    shard_id: int
    replica_id: int
    index: int


@dataclass(frozen=True)
class ReplicationInfo:
    shard_id: int
    replica_id: int
    from_: int
    index: int
    term: int


@dataclass(frozen=True)
class ProposalInfo:
    shard_id: int
    replica_id: int
    entries: tuple[pb.Entry, ...]


@dataclass(frozen=True)
class ReadIndexInfo:
    shard_id: int
    replica_id: int


@dataclass(frozen=True)
class NodeHostInfoEvent:
    node_host_id: str
    raft_address: str
    region: str = ""


class IRaftEventListener(Protocol):
    """Leader-changed callbacks — raftio/listener.go:33."""

    def leader_updated(self, info: LeaderInfo) -> None: ...


class ISystemEventListener(Protocol):
    """16-event system listener — raftio/listener.go:59-76."""

    def node_host_shutting_down(self) -> None: ...
    def node_unloaded(self, info: NodeInfo) -> None: ...
    def node_deleted(self, info: NodeInfo) -> None: ...
    def node_ready(self, info: NodeInfo) -> None: ...
    def membership_changed(self, info: NodeInfo) -> None: ...
    def connection_established(self, addr: str, snapshot: bool) -> None: ...
    def connection_failed(self, addr: str, snapshot: bool) -> None: ...
    def send_snapshot_started(self, info: SnapshotInfo) -> None: ...
    def send_snapshot_completed(self, info: SnapshotInfo) -> None: ...
    def send_snapshot_aborted(self, info: SnapshotInfo) -> None: ...
    def snapshot_received(self, info: SnapshotInfo) -> None: ...
    def snapshot_recovered(self, info: SnapshotInfo) -> None: ...
    def snapshot_created(self, info: SnapshotInfo) -> None: ...
    def snapshot_compacted(self, info: SnapshotInfo) -> None: ...
    def log_compacted(self, info: EntryInfo) -> None: ...
    def log_db_compacted(self, info: EntryInfo) -> None: ...
