"""Log storage engines behind raftio.ILogDB.

- :class:`MemLogDB` — in-memory engine for tests and the loopback runtime
  (the analog of the reference's pebble-on-MemFS configuration).
- :mod:`.tan` — the file-backed engine modeled on the reference's tan
  (per-shard append-only log files + in-memory index + manifest,
  ``internal/tan/``), which is batch-append-shaped like the kernel's
  SaveRaftState batches.
- :mod:`.kv` / :mod:`.kvdb` — the sorted-KV LSM engine and its ILogDB
  adapter (the analog of the reference's Pebble logdb,
  ``internal/logdb/kv_logdb.go``) — the second storage design point.
- :class:`LogReader` — the raft core's cached read-side window over stable
  storage (parity internal/logdb/logreader.go).
"""

from dragonboat_tpu.logdb.memdb import MemLogDB
from dragonboat_tpu.logdb.logreader import LogReader
from dragonboat_tpu.logdb.kvdb import KVLogDB, KVLogDBFactory

__all__ = ["MemLogDB", "LogReader", "KVLogDB", "KVLogDBFactory"]

