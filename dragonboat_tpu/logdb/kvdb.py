"""KVLogDB — ILogDB over the OrderedKV sorted-string engine.

The reference's default logdb lays raft state out as sorted keys in a
Pebble KV store (``internal/logdb/kv_logdb.go``, key scheme in
``internal/logdb/key.go``): entries under big-endian (shard, replica,
index) keys so a range scan walks the log in order, plus point keys for
hard state, snapshot, bootstrap and the max-index watermark.  This is the
same design point re-derived over :class:`~dragonboat_tpu.logdb.kv.OrderedKV`
(tan.py is the OTHER reference engine — purpose-built log files).

Semantics match MemLogDB/TanLogDB (the contract suite in tests/test_kvdb.py
runs the same scenarios as tests/test_tan.py):

- conflict overwrite: a save batch starting at ``first`` invalidates every
  stored entry at or above it — recorded by moving the max-index watermark
  down; stale higher-index keys are ignored by reads and physically dropped
  at compaction (the reference deletes them in the same write batch;
  with an LSM a watermark costs one point write instead of N deletes);
- ``remove_entries_to`` advances a per-node floor key consulted by reads;
  physical reclamation happens in ``compact_entries_to`` via the engine's
  compaction filter (parity: logdb.go compaction taskQueue).
"""

from __future__ import annotations

import struct
import threading
from typing import Sequence

from dragonboat_tpu import raftpb as pb
from dragonboat_tpu.logdb.kv import FlushError, OrderedKV
from dragonboat_tpu.raftio import ILogDB, NodeInfo, RaftState

# key prefixes — big-endian fields keep lexicographic == numeric order
_K_BOOTSTRAP = 0x01
_K_STATE = 0x02
_K_SNAPSHOT = 0x03
_K_MAXINDEX = 0x04
_K_FLOOR = 0x05
_K_ENTRY = 0x10

_NODE = struct.Struct(">BQQ")         # prefix, shard, replica
_ENTRY = struct.Struct(">BQQQ")       # prefix, shard, replica, index


def _nk(prefix: int, shard_id: int, replica_id: int) -> bytes:
    return _NODE.pack(prefix, shard_id, replica_id)


def _ek(shard_id: int, replica_id: int, index: int) -> bytes:
    return _ENTRY.pack(_K_ENTRY, shard_id, replica_id, index)


def _u64(v: int) -> bytes:
    return struct.pack(">Q", v)


class KVLogDB(ILogDB):
    """ILogDB over one OrderedKV directory (single writer; the sharded
    wrapper provides per-partition concurrency)."""

    def __init__(self, root_dir: str, fs=None,
                 memtable_bytes: int = 4 << 20, max_ssts: int = 6) -> None:
        self._mu = threading.RLock()
        # (shard, replica) -> entry floor; mirrors the _K_FLOOR keys so the
        # compaction filter runs without KV reads from inside the engine
        self._floors: dict[tuple[int, int], int] = {}
        self._maxidx: dict[tuple[int, int], int] = {}
        self.kv = OrderedKV(root_dir, fs=fs, memtable_bytes=memtable_bytes,
                            max_ssts=max_ssts,
                            compaction_filter=self._drop_key)
        for k, v in self.kv.scan(bytes([_K_FLOOR]), bytes([_K_FLOOR + 1])):
            _, s, r = _NODE.unpack(k)
            self._floors[(s, r)] = struct.unpack(">Q", v)[0]
        for k, v in self.kv.scan(bytes([_K_MAXINDEX]), bytes([_K_MAXINDEX + 1])):
            _, s, r = _NODE.unpack(k)
            self._maxidx[(s, r)] = struct.unpack(">Q", v)[0]

    def _drop_key(self, key: bytes) -> bool:
        if key[0] != _K_ENTRY:
            return False
        _, s, r, idx = _ENTRY.unpack(key)
        if idx <= self._floors.get((s, r), 0):
            return True
        return idx > self._maxidx.get((s, r), 1 << 62)

    # -- ILogDB ---------------------------------------------------------

    def name(self) -> str:
        return "kv"

    def close(self) -> None:
        self.kv.close()

    def list_node_info(self) -> list[NodeInfo]:
        seen = set()
        for k, _ in self.kv.scan(bytes([_K_BOOTSTRAP]), bytes([_K_MAXINDEX + 1])):
            _, s, r = _NODE.unpack(k)
            seen.add((s, r))
        return [NodeInfo(s, r) for (s, r) in sorted(seen)]

    def save_bootstrap_info(self, shard_id, replica_id, bootstrap) -> None:
        self.kv.put(_nk(_K_BOOTSTRAP, shard_id, replica_id),
                    pb.encode_bootstrap(bootstrap))

    def get_bootstrap_info(self, shard_id, replica_id):
        raw = self.kv.get(_nk(_K_BOOTSTRAP, shard_id, replica_id))
        return None if raw is None else pb.decode_bootstrap(raw)

    def save_raft_state(self, updates: Sequence[pb.Update],
                        worker_id: int = 0) -> None:
        puts = []
        marks: dict[tuple[int, int], int] = {}
        with self._mu:
            for ud in updates:
                key = (ud.shard_id, ud.replica_id)
                if not ud.state.is_empty():
                    puts.append((_nk(_K_STATE, *key),
                                 pb.encode_state(ud.state)))
                if not ud.snapshot.is_empty():
                    buf = bytearray()
                    pb.encode_snapshot(ud.snapshot, buf)
                    puts.append((_nk(_K_SNAPSHOT, *key), bytes(buf)))
                if ud.entries_to_save:
                    for e in ud.entries_to_save:
                        buf = bytearray()
                        pb.encode_entry(e, buf)
                        puts.append((_ek(*key, e.index), bytes(buf)))
                    # the overwrite watermark: entries above the batch tail
                    # are dead even if their keys still exist
                    marks[key] = ud.entries_to_save[-1].index
                    puts.append((_nk(_K_MAXINDEX, *key), _u64(marks[key])))
            # the new watermark must be visible BEFORE the write: the
            # write itself may trigger a memtable flush + compaction, and
            # the compaction filter would otherwise drop this very
            # batch's entries as above-watermark stale keys (a compaction
            # can only fire after the WAL append+fsync succeeded, so the
            # batch is durable by the time the filter consults the mark).
            # A write that never reached the WAL rolls the memory view
            # back to match disk; a FlushError means the batch itself IS
            # durable (WAL fsync preceded the flush), so the marks stand.
            prev = {k: self._maxidx.get(k) for k in marks}
            self._maxidx.update(marks)
            try:
                self.kv.write_batch(puts, sync=True)
            except FlushError:
                raise
            except BaseException as exc:
                if getattr(exc, "batch_durable", False):
                    # a KeyboardInterrupt/SystemExit escaping the
                    # post-fsync flush: the batch IS on disk — rolling
                    # the marks back would let a later compaction drop
                    # this batch's own entries as above-watermark while
                    # the durable _K_MAXINDEX key still claims them
                    raise
                for k, v in prev.items():
                    if v is None:
                        self._maxidx.pop(k, None)
                    else:
                        self._maxidx[k] = v
                raise

    def iterate_entries(self, shard_id, replica_id, low, high, max_size):
        key = (shard_id, replica_id)
        with self._mu:
            hi = min(high, self._maxidx.get(key, 0) + 1)
            floor = self._floors.get(key, 0)
        out, size, expect = [], 0, low
        if low <= floor:
            return out
        for k, raw in self.kv.scan(_ek(shard_id, replica_id, low),
                                   _ek(shard_id, replica_id, max(hi, low))):
            idx = _ENTRY.unpack(k)[3]
            if idx != expect:
                break                      # gap: contiguous run ends
            e, _ = pb.decode_entry(memoryview(raw), 0)
            size += pb.entry_size(e)
            if out and max_size and size > max_size:
                break
            out.append(e)
            expect += 1
        return out

    def read_raft_state(self, shard_id, replica_id, last_index):
        key = (shard_id, replica_id)
        raw_state = self.kv.get(_nk(_K_STATE, *key))
        snapshot = self.get_snapshot(shard_id, replica_id)
        with self._mu:
            maxidx = self._maxidx.get(key, 0)
        first = (snapshot.index if snapshot is not None else 0) + 1
        count = 0
        if maxidx >= first:
            run = self.iterate_entries(shard_id, replica_id, first,
                                       maxidx + 1, 0)
            count = len(run)
        if raw_state is None and snapshot is None and count == 0:
            return None
        state = (pb.decode_state(raw_state)
                 if raw_state is not None else pb.State())
        return RaftState(state=state, first_index=first, entry_count=count)

    def remove_entries_to(self, shard_id, replica_id, index):
        key = (shard_id, replica_id)
        with self._mu:
            if index <= self._floors.get(key, 0):
                return
            # floor moves only after the key is durable: a failed put
            # must not leave reads (or a later compaction) ahead of disk
            # — unlike the save-path watermark, nothing in this write
            # depends on the new floor being visible mid-flush.  A
            # FlushError means the put itself landed, so the floor moves.
            try:
                self.kv.put(_nk(_K_FLOOR, *key), _u64(index))
            except BaseException as exc:
                # FlushError (and a batch_durable-tagged interrupt)
                # means the put itself landed, so the floor moves
                if isinstance(exc, FlushError) or getattr(
                        exc, "batch_durable", False):
                    self._floors[key] = index
                raise
            self._floors[key] = index

    def compact_entries_to(self, shard_id, replica_id, index):
        self.remove_entries_to(shard_id, replica_id, index)
        self.kv.compact()                  # physical reclamation

    def save_snapshots(self, updates) -> None:
        puts = []
        for ud in updates:
            if not ud.snapshot.is_empty():
                buf = bytearray()
                pb.encode_snapshot(ud.snapshot, buf)
                puts.append((_nk(_K_SNAPSHOT, ud.shard_id, ud.replica_id),
                             bytes(buf)))
        if puts:
            self.kv.write_batch(puts, sync=True)

    def get_snapshot(self, shard_id, replica_id):
        raw = self.kv.get(_nk(_K_SNAPSHOT, shard_id, replica_id))
        if raw is None:
            return None
        ss, _ = pb.decode_snapshot(memoryview(raw), 0)
        return None if ss.is_empty() else ss

    def remove_node_data(self, shard_id, replica_id) -> None:
        key = (shard_id, replica_id)
        with self._mu:
            dels = [_nk(p, *key) for p in
                    (_K_BOOTSTRAP, _K_STATE, _K_SNAPSHOT, _K_MAXINDEX,
                     _K_FLOOR)]
            dels += [k for k, _ in self.kv.scan(_ek(*key, 0),
                                                _ek(*key, (1 << 64) - 1))]
            try:
                self.kv.write_batch([], dels, sync=True)
            except BaseException as exc:
                # the deletion batch IS durable on FlushError and on a
                # KeyboardInterrupt/SystemExit tagged batch_durable by
                # the post-fsync flush — the in-memory books must drop
                # with it or a re-added node would inherit a stale
                # floor/watermark over fresh entries
                if isinstance(exc, FlushError) or getattr(
                        exc, "batch_durable", False):
                    self._floors.pop(key, None)
                    self._maxidx.pop(key, None)
                raise
            self._floors.pop(key, None)
            self._maxidx.pop(key, None)

    def import_snapshot(self, snapshot: pb.Snapshot, replica_id: int) -> None:
        key = (snapshot.shard_id, replica_id)
        with self._mu:
            self.remove_node_data(snapshot.shard_id, replica_id)
            buf = bytearray()
            pb.encode_snapshot(snapshot, buf)
            st = pb.State(term=snapshot.term, vote=0, commit=snapshot.index)
            boot = pb.Bootstrap(
                addresses=dict(snapshot.membership.addresses), join=False)
            self.kv.write_batch([
                (_nk(_K_SNAPSHOT, *key), bytes(buf)),
                (_nk(_K_STATE, *key), pb.encode_state(st)),
                (_nk(_K_BOOTSTRAP, *key), pb.encode_bootstrap(boot)),
            ], sync=True)


class KVLogDBFactory:
    """config.LogDBFactory equivalent for NodeHostConfig."""

    def __init__(self, root_dir: str, fs=None) -> None:
        self.root_dir = root_dir
        self.fs = fs

    def create(self) -> KVLogDB:
        return KVLogDB(self.root_dir, fs=self.fs)
