"""OrderedKV — a small LSM-style ordered key-value engine.

The second storage design point behind :class:`~dragonboat_tpu.raftio.ILogDB`:
the reference ships BOTH a purpose-built log engine (tan) and a sorted-KV
engine (Pebble behind ``internal/logdb/kv``); tan.py is the former, this is
the latter.  Same shape as any LSM: an fsync-gated WAL, an in-memory
memtable, immutable sorted-string tables flushed from it, newest-wins reads
through the stack, and a full-merge compaction that drops tombstones and
anything the owner's compaction filter declares dead (raft entry floors ride
in through that filter — range deletes never write per-key tombstones).

Not a port of Pebble: single-writer (the sharded wrapper provides
concurrency), full-merge instead of leveled compaction (log batches at our
scale produce a handful of tables), per-file CRC instead of per-block, and
values stay on disk — the open-time scan builds only the key index.

Crash safety: a torn WAL tail is truncated on open (the batch was never
acknowledged); an SST is published by atomic rename, so a crash mid-flush
leaves only a ``*.tmp`` that open() sweeps; the WAL is truncated only after
its contents are durable in a published SST.
"""

from __future__ import annotations

import struct
import threading
import zlib
from bisect import bisect_left, insort
from typing import Callable, Iterator, Sequence

WAL_MAGIC = 0x4B560001
SST_MAGIC = 0x4B560002
_WAL_HDR = struct.Struct("<III")      # magic, payload length, crc32
_SST_HDR = struct.Struct("<IQ")       # magic, record count
_REC = struct.Struct("<Iq")           # klen, vlen (-1 == tombstone)

_TOMB = None                          # in-memory tombstone marker


class CorruptKVError(Exception):
    """A non-tail record failed its checksum — the store is damaged."""


class FlushError(Exception):
    """A memtable flush (or its compaction) failed AFTER the triggering
    batch was durably appended to the WAL and applied to the memtable.
    The batch itself is safe — recovery replays it — but the store is
    degraded (the flush retries on the next write/close).  Callers that
    stage side effects on write success must treat this as success for
    the batch and failure for the engine."""


class _SSTable:
    """One immutable sorted table: in-memory key index, values on disk."""

    def __init__(self, fs, path: str) -> None:
        self.fs = fs
        self.path = path
        self.keys: list[bytes] = []
        self._off: list[int] = []      # value file offset (or -1 tombstone)
        self._vlen: list[int] = []
        self._fh = None
        self._load()

    def _load(self) -> None:
        with self.fs.open(self.path, "rb") as f:
            hdr = f.read(_SST_HDR.size)
            if len(hdr) < _SST_HDR.size:
                raise CorruptKVError(f"{self.path}: short header")
            magic, count = _SST_HDR.unpack(hdr)
            if magic != SST_MAGIC:
                raise CorruptKVError(f"{self.path}: bad magic")
            crc = 0
            off = _SST_HDR.size
            for _ in range(count):
                rh = f.read(_REC.size)
                klen, vlen = _REC.unpack(rh)
                key = f.read(klen)
                crc = zlib.crc32(rh, crc)
                crc = zlib.crc32(key, crc)
                self.keys.append(key)
                off += _REC.size + klen
                if vlen < 0:
                    self._off.append(-1)
                    self._vlen.append(0)
                else:
                    self._off.append(off)
                    self._vlen.append(vlen)
                    crc = zlib.crc32(f.read(vlen), crc)
                    off += vlen
            tail = f.read(4)
            if len(tail) < 4 or struct.unpack("<I", tail)[0] != crc:
                raise CorruptKVError(f"{self.path}: checksum mismatch")

    def _handle(self):
        if self._fh is None:
            self._fh = self.fs.open(self.path, "rb")
        return self._fh

    def _value(self, i: int):
        if self._off[i] < 0:
            return _TOMB
        f = self._handle()
        f.seek(self._off[i])
        return f.read(self._vlen[i])

    def get(self, key: bytes):
        """(found, value_or_tombstone)."""
        i = bisect_left(self.keys, key)
        if i < len(self.keys) and self.keys[i] == key:
            return True, self._value(i)
        return False, _TOMB

    def iter_range(self, lo: bytes, hi: bytes) -> Iterator[tuple[bytes, object]]:
        i = bisect_left(self.keys, lo)
        while i < len(self.keys) and self.keys[i] < hi:
            yield self.keys[i], self._value(i)
            i += 1

    def iter_all(self) -> Iterator[tuple[bytes, object]]:
        """Every record, no artificial upper bound — the compaction
        merge must never exclude a key (an excluded key is deleted with
        the old tables)."""
        for i in range(len(self.keys)):
            yield self.keys[i], self._value(i)

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None


class OrderedKV:
    """Single-writer ordered KV store over a directory.

    ``compaction_filter(key) -> bool`` (True = drop) is consulted for every
    live key during compaction — the hook range-deletion rides on.
    """

    def __init__(self, root_dir: str, fs=None, memtable_bytes: int = 4 << 20,
                 max_ssts: int = 6,
                 compaction_filter: Callable[[bytes], bool] | None = None):
        from dragonboat_tpu.vfs import default_fs

        self.fs = fs if fs is not None else default_fs()
        self.root = root_dir
        self.fs.makedirs(self.root)
        self.memtable_bytes = memtable_bytes
        self.max_ssts = max_ssts
        self.compaction_filter = compaction_filter
        self._mu = threading.RLock()
        self._mem: dict[bytes, object] = {}
        self._mem_keys: list[bytes] = []   # sorted view of _mem
        self._mem_size = 0
        self._ssts: list[_SSTable] = []    # oldest .. newest
        self._seq = 0
        self._open()

    # -- open / recovery ------------------------------------------------

    def _path(self, name: str) -> str:
        return f"{self.root}/{name}"

    def _open(self) -> None:
        seqs = []
        for fn in sorted(self.fs.listdir(self.root)):
            if fn.endswith(".tmp"):
                self.fs.remove(self._path(fn))   # unpublished flush
            elif fn.startswith("sst-") and fn.endswith(".kv"):
                seqs.append(int(fn[4:-3]))
        for s in sorted(seqs):
            self._ssts.append(_SSTable(self.fs, self._path(f"sst-{s:08d}.kv")))
            self._seq = max(self._seq, s)
        wal = self._path("wal")
        if self.fs.exists(wal):
            self._replay_wal(wal)
        self._wal = self.fs.open(wal, "ab")

    def _replay_wal(self, path: str) -> None:
        good = 0
        with self.fs.open(path, "rb") as f:
            data = f.read()
        off = 0
        while off + _WAL_HDR.size <= len(data):
            magic, ln, crc = _WAL_HDR.unpack_from(data, off)
            body = data[off + _WAL_HDR.size: off + _WAL_HDR.size + ln]
            if magic != WAL_MAGIC or len(body) < ln:
                break                      # torn tail
            if zlib.crc32(body) != crc:
                if off + _WAL_HDR.size + ln >= len(data):
                    break                  # torn tail mid-payload
                raise CorruptKVError(f"{path}: mid-log checksum mismatch")
            self._apply_wal_batch(body)
            off += _WAL_HDR.size + ln
            good = off
        if good < len(data):
            with self.fs.open(path, "r+b") as tf:
                tf.truncate(good)
                self.fs.fsync(tf)

    def _apply_wal_batch(self, body: bytes) -> None:
        mv = memoryview(body)
        off = 0
        while off < len(mv):
            op = mv[off]
            klen, vlen = _REC.unpack_from(mv, off + 1)
            off += 1 + _REC.size
            key = bytes(mv[off:off + klen])
            off += klen
            if op == 1:
                self._mem_put(key, bytes(mv[off:off + vlen]))
                off += vlen
            else:
                self._mem_put(key, _TOMB)

    # -- memtable -------------------------------------------------------

    def _mem_put(self, key: bytes, val) -> None:
        if key not in self._mem:
            insort(self._mem_keys, key)
        else:
            self._mem_size -= len(key) + len(self._mem[key] or b"")
        self._mem[key] = val
        self._mem_size += len(key) + len(val or b"")

    # -- write path -----------------------------------------------------

    def write_batch(self, puts: Sequence[tuple[bytes, bytes]],
                    dels: Sequence[bytes] = (), sync: bool = True) -> None:
        """Atomically apply puts+dels: one WAL record, one optional fsync."""
        parts = []
        for k, v in puts:
            parts.append(bytes([1]) + _REC.pack(len(k), len(v)) + k + v)
        for k in dels:
            parts.append(bytes([2]) + _REC.pack(len(k), -1) + k)
        body = b"".join(parts)
        with self._mu:
            self._wal.write(_WAL_HDR.pack(WAL_MAGIC, len(body),
                                          zlib.crc32(body)) + body)
            if sync:
                self.fs.fsync(self._wal)
            for k, v in puts:
                self._mem_put(k, v)
            for k in dels:
                self._mem_put(k, _TOMB)
            if self._mem_size >= self.memtable_bytes:
                if not sync:
                    # no durability claim to scope: an unsynced batch is
                    # best-effort either way, so flush errors propagate raw
                    self._flush_locked()
                else:
                    try:
                        self._flush_locked()
                    except Exception as e:
                        raise FlushError(
                            "flush failed after the batch was made durable"
                        ) from e
                    except BaseException as e:
                        # KeyboardInterrupt/SystemExit must propagate
                        # with their own TYPE (signal semantics), but
                        # carry the durability fact: the batch was
                        # WAL-appended + fsynced before the flush, so a
                        # caller staging side effects on write success
                        # must NOT roll them back (logdb/kvdb.py
                        # save_raft_state checks this attribute)
                        e.batch_durable = True
                        raise

    def put(self, key: bytes, val: bytes, sync: bool = True) -> None:
        self.write_batch([(key, val)], sync=sync)

    def delete(self, key: bytes, sync: bool = True) -> None:
        self.write_batch([], [key], sync=sync)

    # -- flush / compaction ---------------------------------------------

    def _write_sst(self, items: Iterator[tuple[bytes, object]],
                   drop_tombstones: bool) -> str | None:
        """Write a published SST from sorted (key, value) items."""
        self._seq += 1
        name = f"sst-{self._seq:08d}.kv"
        tmp = self._path(name + ".tmp")
        crc = 0
        count = 0
        payload = []
        for key, val in items:
            if val is _TOMB:
                if drop_tombstones:
                    continue
                rec = _REC.pack(len(key), -1) + key
            else:
                rec = _REC.pack(len(key), len(val)) + key + val
            crc = zlib.crc32(rec, crc)
            payload.append(rec)
            count += 1
        if count == 0:
            self._seq -= 1
            return None
        with self.fs.open(tmp, "wb") as f:
            f.write(_SST_HDR.pack(SST_MAGIC, count))
            for rec in payload:
                f.write(rec)
            f.write(struct.pack("<I", crc))
            self.fs.fsync(f)
        self.fs.replace(tmp, self._path(name))
        # the rename itself must be durable before anything depends on
        # the published table (the WAL truncation, old-table deletion)
        self.fs.fsync_dir(self.root)
        return self._path(name)

    def _flush_locked(self) -> None:
        if not self._mem:
            return
        path = self._write_sst(
            ((k, self._mem[k]) for k in self._mem_keys),
            drop_tombstones=False)
        if path is not None:
            self._ssts.append(_SSTable(self.fs, path))
        self._mem.clear()
        self._mem_keys.clear()
        self._mem_size = 0
        self._wal.close()
        with self.fs.open(self._path("wal"), "wb") as f:
            self.fs.fsync(f)
        self._wal = self.fs.open(self._path("wal"), "ab")
        if len(self._ssts) > self.max_ssts:
            self._compact_locked()

    def _merged(self) -> Iterator[tuple[bytes, object]]:
        """Newest-wins merge of all SSTs (memtable excluded).  Unbounded
        iteration: a range-bounded merge would silently drop (then
        delete) any key past the bound."""
        iters = [list(t.iter_all()) for t in self._ssts]
        merged: dict[bytes, object] = {}
        for run in iters:                  # oldest first: later wins
            for k, v in run:
                merged[k] = v
        for k in sorted(merged):
            yield k, merged[k]

    def _compact_locked(self) -> None:
        filt = self.compaction_filter

        def live():
            for k, v in self._merged():
                if v is _TOMB:
                    continue               # full merge: tombstones die here
                if filt is not None and filt(k):
                    continue
                yield k, v

        old = self._ssts
        path = self._write_sst(live(), drop_tombstones=True)
        self._ssts = [_SSTable(self.fs, path)] if path is not None else []
        for t in old:
            t.close()
            self.fs.remove(t.path)

    def flush(self) -> None:
        with self._mu:
            self._flush_locked()

    def compact(self) -> None:
        """Flush and fully merge — physical reclamation point."""
        with self._mu:
            self._flush_locked()
            self._compact_locked()

    # -- read path ------------------------------------------------------

    def get(self, key: bytes):
        with self._mu:
            if key in self._mem:
                v = self._mem[key]
                return None if v is _TOMB else v
            for t in reversed(self._ssts):
                found, v = t.get(key)
                if found:
                    return None if v is _TOMB else v
            return None

    def scan(self, lo: bytes, hi: bytes) -> list[tuple[bytes, bytes]]:
        """Sorted live (key, value) pairs with lo <= key < hi.

        Returns a materialized list: the snapshot is taken under the lock,
        so a caller iterating slowly never blocks (or races) the writer."""
        with self._mu:
            merged: dict[bytes, object] = {}
            for t in self._ssts:           # oldest first: later wins
                for k, v in t.iter_range(lo, hi):
                    merged[k] = v
            i = bisect_left(self._mem_keys, lo)
            while i < len(self._mem_keys) and self._mem_keys[i] < hi:
                k = self._mem_keys[i]
                merged[k] = self._mem[k]
                i += 1
        return [(k, merged[k]) for k in sorted(merged)
                if merged[k] is not _TOMB]

    def close(self) -> None:
        with self._mu:
            self._flush_locked()
            self._wal.close()
            for t in self._ssts:
                t.close()
