"""Sharded LogDB — N single-writer tan partitions whose fsyncs overlap.

Parity with the reference's ``internal/logdb/sharded.go:34-80`` ShardedDB:
the log engine is split into ``num_shards`` independent single-writer
databases so that concurrent step workers flushing different partitions
never serialize on one file or one lock.  Routing is the single fixed
hash ``partition(shard_id) = shard_id % num_shards`` (the reference's
``internal/server/partition.go:59`` folds the worker count in as well,
but that pins a pure concurrency knob into the data layout — here only
``num_shards`` shapes the directory, so ``ExecShards`` stays freely
tunable on existing dirs).  The step workers hash shards the same way
(``shard_id % W``), so whenever the worker-pool size divides
``num_shards`` each partition is appended by exactly one worker — the
single-writer-per-worker contract of ``raftio/logdb.go:78-83`` — and W
workers fsync W different files concurrently; when it doesn't divide,
two workers may share a partition and its internal lock keeps that safe.

Deliberate differences from the reference:

- the reference panics when one ``SaveRaftState`` batch spans partitions
  (``sharded.go getParititionID``) because its callers are per-worker.
  Here the batched device engine legitimately saves a ``[G]``-lane batch
  covering many partitions in ONE call (engine/kernel_engine.py step
  loop), so a spanning batch is grouped per partition and the partition
  flushes run **in parallel** on a small pool — the fsyncs overlap in
  the device queue instead of paying P serial flush round-trips.
- the shard count is pinned by a ``TANSHARDS`` marker file instead of a
  manifest binary-format stamp; reopening with a different geometry is
  refused (the partition hash would silently mis-route reads).
- a legacy unsharded layout (``log-*.tan`` directly in the root, the
  pre-round-4 format) is migrated in place on open by replaying the old
  engine and re-saving every node into its home partition.
"""

from __future__ import annotations

import os
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Sequence

from dragonboat_tpu import lifecycle
from dragonboat_tpu import raftpb as pb
from dragonboat_tpu.logdb.tan import TanLogDB
from dragonboat_tpu.raftio import ILogDB, NodeInfo, RaftState

_MARKER = "TANSHARDS"


class ShardGeometryError(Exception):
    """The on-disk partition count does not match the configuration."""


class ShardedLogDB(ILogDB):
    """``num_shards`` TanLogDB partitions under one root directory."""

    def __init__(self, root_dir: str, num_shards: int = 16,
                 max_file_size: int = 64 << 20, fs=None,
                 engine: str = "tan",
                 recovery_mode: str = "strict") -> None:
        from dragonboat_tpu.vfs import default_fs

        if num_shards < 1:
            raise ValueError("num_shards must be >= 1")
        if engine not in ("tan", "kv"):
            raise ValueError(f"unknown logdb engine {engine!r}")
        self.fs = fs if fs is not None else default_fs()
        self.root = root_dir
        self.num_shards = num_shards
        self.engine = engine
        self.recovery_mode = recovery_mode
        self.fs.makedirs(self.root)
        # refuse a legacy layout under a non-tan engine BEFORE the marker
        # is written: a persisted "kv" marker over tan data would make the
        # directory unopenable under either engine
        if self.engine != "tan" and self._legacy_files():
            raise ShardGeometryError(
                f"{self.root}: legacy flat tan layout cannot migrate to "
                f"engine {self.engine!r}; open it as tan")
        self._check_marker()
        self._migrate_legacy(max_file_size)

        def make_part(path: str):
            if engine == "kv":
                from dragonboat_tpu.logdb.kvdb import KVLogDB

                return KVLogDB(path, fs=self.fs)
            return TanLogDB(path, max_file_size=max_file_size, fs=self.fs,
                            recovery_mode=recovery_mode)

        self._parts = [
            make_part(os.path.join(self.root, f"part-{i:02d}"))
            for i in range(num_shards)
        ]
        # corruption sites quarantined by the tan partitions on open
        # (always empty under engine="kv" or recovery_mode="strict")
        self.quarantined: list[str] = [
            q for p in self._parts
            for q in getattr(p, "quarantined", ())]
        # flush pool for batches that span partitions (device engine):
        # sized to the partition count, NOT cpu_count — these tasks block
        # in fsync, they do not compute
        self._pool = ThreadPoolExecutor(
            max_workers=min(num_shards, 16),
            thread_name_prefix="tanshard-flush")
        self._closed = False
        self._close_mu = threading.Lock()

    # -- geometry --------------------------------------------------------

    def _marker_path(self) -> str:
        return os.path.join(self.root, _MARKER)

    def _legacy_files(self) -> list[str]:
        """Pre-sharding flat tan log files directly in the root."""
        return [fn for fn in self.fs.listdir(self.root)
                if fn.startswith("log-") and fn.endswith(".tan")]

    def _check_marker(self) -> None:
        mp = self._marker_path()
        if self.fs.exists(mp):
            with self.fs.open(mp, "rb") as f:
                fields = f.read().decode("ascii").split()
            want = fields[0]
            # pre-engine markers carried only the count: they are tan dirs
            want_engine = fields[1] if len(fields) > 1 else "tan"
            if want != str(self.num_shards):
                raise ShardGeometryError(
                    f"{self.root}: on-disk shard count {want} != "
                    f"configured {self.num_shards}")
            if want_engine != self.engine:
                raise ShardGeometryError(
                    f"{self.root}: on-disk engine {want_engine!r} != "
                    f"configured {self.engine!r}")
        else:
            with self.fs.open(mp, "wb") as f:
                # count alone on line 1: an older (count-only) parser
                # that int()s the first line still reaches its geometry
                # error path instead of a raw ValueError; whitespace
                # split here reads both layouts
                f.write(f"{self.num_shards}\n{self.engine}\n"
                        .encode("ascii"))
                self.fs.fsync(f)

    @staticmethod
    def stored_shard_count(root_dir: str, fs) -> int | None:
        """The shard count pinned in ``root_dir``, or None if the dir was
        never opened by a ShardedLogDB (tools open existing dirs with
        whatever geometry the owning NodeHost pinned)."""
        mp = os.path.join(root_dir, _MARKER)
        if not fs.exists(mp):
            return None
        with fs.open(mp, "rb") as f:
            return int(f.read().decode("ascii").split()[0])

    def _migrate_legacy(self, max_file_size: int) -> None:
        """Fold a pre-sharding flat layout into the partition dirs."""
        legacy = self._legacy_files()
        if not legacy:
            return
        old = TanLogDB(self.root, max_file_size=max_file_size, fs=self.fs)
        try:
            tmp_parts: dict[int, TanLogDB] = {}

            def part_for(shard_id: int) -> TanLogDB:
                pid = self._pid(shard_id)
                db = tmp_parts.get(pid)
                if db is None:
                    db = tmp_parts[pid] = TanLogDB(
                        os.path.join(self.root, f"part-{pid:02d}"),
                        max_file_size=max_file_size, fs=self.fs)
                return db

            for ni in old.list_node_info():
                dst = part_for(ni.shard_id)
                bs = old.get_bootstrap_info(ni.shard_id, ni.replica_id)
                if bs is not None:
                    dst.save_bootstrap_info(ni.shard_id, ni.replica_id, bs)
                ss = old.get_snapshot(ni.shard_id, ni.replica_id)
                rs = old.read_raft_state(ni.shard_id, ni.replica_id, 0)
                ents: list[pb.Entry] = []
                if rs is not None and rs.entry_count:
                    ents = old.iterate_entries(
                        ni.shard_id, ni.replica_id, rs.first_index,
                        rs.first_index + rs.entry_count, 0)
                dst.save_raft_state([pb.Update(
                    shard_id=ni.shard_id, replica_id=ni.replica_id,
                    state=(rs.state if rs is not None else pb.State()),
                    entries_to_save=tuple(ents),
                    snapshot=(ss if ss is not None else pb.Snapshot()),
                )], worker_id=0)
            for db in tmp_parts.values():
                db.close()
        finally:
            old.close()
        for fn in legacy:
            self.fs.remove(os.path.join(self.root, fn))

    def _pid(self, shard_id: int) -> int:
        return shard_id % self.num_shards

    def _part(self, shard_id: int) -> ILogDB:
        return self._parts[self._pid(shard_id)]

    # -- ILogDB ----------------------------------------------------------

    def name(self) -> str:
        return f"sharded-{self.engine}-{self.num_shards}"

    def close(self) -> None:
        with self._close_mu:
            if self._closed:
                return
            self._closed = True
        self._pool.shutdown(wait=True)
        for p in self._parts:
            p.close()

    def list_node_info(self) -> list[NodeInfo]:
        out: list[NodeInfo] = []
        for p in self._parts:
            out.extend(p.list_node_info())
        return out

    def save_bootstrap_info(self, shard_id, replica_id, bootstrap) -> None:
        self._part(shard_id).save_bootstrap_info(
            shard_id, replica_id, bootstrap)

    def get_bootstrap_info(self, shard_id, replica_id):
        return self._part(shard_id).get_bootstrap_info(shard_id, replica_id)

    def save_raft_state(self, updates: Sequence[pb.Update],
                        worker_id: int) -> None:
        """One partition -> direct append+fsync under that partition's
        lock (the per-worker fast path); a spanning batch -> grouped
        appends flushed in parallel (one future per touched partition)."""
        groups: dict[int, list[pb.Update]] = {}
        for ud in updates:
            groups.setdefault(self._pid(ud.shard_id), []).append(ud)
        if not groups:
            return
        if len(groups) == 1:
            pid, uds = next(iter(groups.items()))
            self._parts[pid].save_raft_state(uds, worker_id)
        else:
            futs = [self._pool.submit(self._parts[pid].save_raft_state,
                                      uds, worker_id)
                    for pid, uds in groups.items()]
            for fu in futs:
                fu.result()
        # lifecycle: entries in this batch are durable NOW — stamp the
        # sampled ones after every touched partition has fsynced
        if lifecycle.TRACER.enabled:
            for ud in updates:
                for e in ud.entries_to_save:
                    if e.key:
                        lifecycle.TRACER.stamp(e.key, lifecycle.STAGE_FSYNC)

    def iterate_entries(self, shard_id, replica_id, low, high, max_size):
        return self._part(shard_id).iterate_entries(
            shard_id, replica_id, low, high, max_size)

    def read_raft_state(self, shard_id, replica_id, last_index):
        return self._part(shard_id).read_raft_state(
            shard_id, replica_id, last_index)

    def remove_entries_to(self, shard_id, replica_id, index):
        self._part(shard_id).remove_entries_to(shard_id, replica_id, index)

    def compact_entries_to(self, shard_id, replica_id, index):
        self._part(shard_id).compact_entries_to(shard_id, replica_id, index)

    def save_snapshots(self, updates):
        groups: dict[int, list[pb.Update]] = {}
        for ud in updates:
            groups.setdefault(self._pid(ud.shard_id), []).append(ud)
        for pid, uds in groups.items():
            self._parts[pid].save_snapshots(uds)

    def get_snapshot(self, shard_id, replica_id):
        return self._part(shard_id).get_snapshot(shard_id, replica_id)

    def remove_node_data(self, shard_id, replica_id):
        self._part(shard_id).remove_node_data(shard_id, replica_id)

    def import_snapshot(self, snapshot: pb.Snapshot,
                        replica_id: int) -> None:
        self._part(snapshot.shard_id).import_snapshot(snapshot, replica_id)


class ShardedLogDBFactory:
    """config.LogDBFactory equivalent producing the sharded engine."""

    def __init__(self, root_dir: str, num_shards: int = 16,
                 max_file_size: int = 64 << 20, fs=None,
                 engine: str = "tan",
                 recovery_mode: str = "strict") -> None:
        self.root_dir = root_dir
        self.num_shards = num_shards
        self.max_file_size = max_file_size
        self.fs = fs
        self.engine = engine
        self.recovery_mode = recovery_mode

    def create(self) -> ShardedLogDB:
        return ShardedLogDB(self.root_dir, self.num_shards,
                            self.max_file_size, fs=self.fs,
                            engine=self.engine,
                            recovery_mode=self.recovery_mode)
