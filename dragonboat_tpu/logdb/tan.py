"""tan — the durable raft-log engine (file-backed ILogDB).

Re-expression of the reference's purpose-built log engine
(``internal/tan/db.go:97-173`` write path, ``index.go:37-56`` in-memory
index, ``compaction.go`` whole-file compaction): WAL-style append-only log
files holding checksummed records, an in-memory per-node index rebuilt by
replaying the files on open, and compaction that deletes whole obsolete
files after re-homing any still-live node metadata.

Differences from the reference, deliberate:

- one record = one ``pb.Update`` batch (state + entries + optional snapshot
  metadata), matching the engine's batched ``save_raft_state`` shape — the
  ``[G]``-batch from the device kernel lands as a run of records followed by
  ONE fsync (raftio/logdb.go:78-83 single-writer contract);
- node metadata (latest state / snapshot / bootstrap) is re-appended to the
  active file before an old file is deleted, replacing tan's
  versionSet/manifest machinery with a self-describing log;
- a torn final record (crash mid-write) is truncated away on open; a bad
  checksum anywhere earlier is corruption and refuses to open.
"""

from __future__ import annotations

import os
import struct
import threading
import time
import zlib
from dataclasses import dataclass, field
from typing import Sequence

from dragonboat_tpu import flight
from dragonboat_tpu import raftpb as pb
from dragonboat_tpu import telemetry
from dragonboat_tpu.raftio import ILogDB, NodeInfo, RaftState

# write-path latency lives in the process-global registry: tan shards are
# module-scoped single writers, not per-NodeHost objects, and a scrape
# wants the host-wide durability picture in one family
_SAVE_US = telemetry.GLOBAL.histogram(
    "logdb.save_us", help="save_raft_state batch latency (append+fsync), us")
_FSYNC_US = telemetry.GLOBAL.histogram(
    "logdb.fsync_us", help="fsync latency at the durability point, us")

MAGIC = 0x7A4E0002
_HDR = struct.Struct("<III")          # magic, payload length, crc32

# record types
R_UPDATE = 1       # state + entries (+ snapshot meta) for one node
R_BOOTSTRAP = 2
R_SNAPSHOT = 3
R_COMPACT = 4      # compaction floor advance
R_REMOVE = 5       # node data removed
R_META = 6         # re-homed node metadata (pre file-deletion checkpoint)

_KEY = struct.Struct("<BQQ")          # rectype, shard_id, replica_id


class CorruptLogError(OSError):
    """A record failed its checksum — the log is damaged.

    An OSError subclass so a corrupt read hit at RUNTIME (not open)
    routes through the engine workers' storage-failure path into the
    NodeHost controlled crash, instead of being retried forever by the
    generic exception guard."""


class _RangeIndex:
    """Range-based entry index (reference ``index.go:37-56`` indexEntry):
    one appended record covering entries ``[first..last]`` costs ONE tuple
    ``(first, last, fileno, offset)`` — not one dict slot per entry.  The
    entries inside a record are contiguous, so the ordinal of index ``i``
    is just ``i - first``.  Compaction keeps record-aligned ranges and a
    visibility ``floor``: indexes at or below the floor read as absent,
    and fully-covered ranges are dropped; a range straddling the floor
    keeps its original ``first`` so the ordinal math stays valid.
    """

    __slots__ = ("_r", "floor")

    def __init__(self) -> None:
        # sorted by first, non-overlapping: [first, last, fileno, offset]
        self._r: list[list[int]] = []
        self.floor = 0

    def __bool__(self) -> bool:
        return any(r[1] > self.floor for r in self._r)

    def add(self, first: int, last: int, fileno: int, off: int) -> None:
        """Index one record; conflict-overwrite truncates any stale
        suffix at or above ``first`` (raft log overwrite semantics)."""
        r = self._r
        while r and r[-1][0] >= first:
            r.pop()
        if r and r[-1][1] >= first:
            r[-1][1] = first - 1
        r.append([first, last, fileno, off])

    def get(self, i: int) -> tuple[int, int, int] | None:
        """index -> (fileno, record offset, ordinal within record)."""
        if i <= self.floor:
            return None
        r = self._r
        lo, hi = 0, len(r)
        while lo < hi:
            mid = (lo + hi) // 2
            if r[mid][0] <= i:
                lo = mid + 1
            else:
                hi = mid
        if lo == 0:
            return None
        first, last, fileno, off = r[lo - 1]
        if i > last:
            return None
        return fileno, off, i - first

    def compact(self, floor: int) -> None:
        if floor <= self.floor:
            return
        self.floor = floor
        self._r = [r for r in self._r if r[1] > floor]

    def contiguous_count(self, start: int) -> int:
        """Number of consecutively-present entries from ``start``."""
        if start <= self.floor:
            return 0
        count, expect = 0, start
        for first, last, _, _ in self._r:
            if last < expect:
                continue
            if first > expect:
                break
            count += last - expect + 1
            expect = last + 1
        return count

    def filenos(self) -> set[int]:
        return {r[2] for r in self._r if r[1] > self.floor}


@dataclass
class _Node:
    state: pb.State = field(default_factory=pb.State)
    snapshot: pb.Snapshot = field(default_factory=pb.Snapshot)
    bootstrap: pb.Bootstrap | None = None
    entries: _RangeIndex = field(default_factory=_RangeIndex)
    max_index: int = 0
    removed: bool = False


def _enc_update(ud: pb.Update) -> bytes:
    buf = bytearray()
    st = pb.encode_state(ud.state)
    buf += struct.pack("<I", len(st))
    buf += st
    buf += struct.pack("<I", len(ud.entries_to_save))
    for e in ud.entries_to_save:
        pb.encode_entry(e, buf)
    if ud.snapshot.is_empty():
        buf += b"\x00"
    else:
        buf += b"\x01"
        pb.encode_snapshot(ud.snapshot, buf)
    return bytes(buf)


def _dec_update(shard_id: int, replica_id: int, data: bytes) -> pb.Update:
    mv = memoryview(data)
    (nstate,) = struct.unpack_from("<I", mv, 0)
    off = 4
    state = pb.decode_state(bytes(mv[off:off + nstate]))
    off += nstate
    (n_ent,) = struct.unpack_from("<I", mv, off)
    off += 4
    ents = []
    for _ in range(n_ent):
        e, off = pb.decode_entry(mv, off)
        ents.append(e)
    snapshot = pb.Snapshot()
    if mv[off] == 1:
        snapshot, _ = pb.decode_snapshot(mv, off + 1)
    return pb.Update(shard_id=shard_id, replica_id=replica_id, state=state,
                     entries_to_save=tuple(ents), snapshot=snapshot)


class TanLogDB(ILogDB):
    """File-backed ILogDB; one instance owns one directory."""

    def __init__(self, root_dir: str, max_file_size: int = 64 << 20,
                 fs=None, recovery_mode: str = "strict") -> None:
        from dragonboat_tpu.vfs import default_fs

        if recovery_mode not in ("strict", "quarantine"):
            raise ValueError(f"unknown recovery_mode {recovery_mode!r}")
        self.fs = fs if fs is not None else default_fs()
        self.root = root_dir
        self.max_file_size = max_file_size
        # "strict": a bad checksum in a non-tail file refuses to open
        # (the historical behavior).  "quarantine": truncate the file at
        # the corruption, record it in ``quarantined``, and clamp each
        # node's persisted commit to what is still contiguously present —
        # the node then reopens behind the shard and the leader re-
        # replicates (or snapshots) it back, instead of a dead replica.
        self.recovery_mode = recovery_mode
        self.quarantined: list[str] = []
        self.fs.makedirs(self.root)
        self._mu = threading.RLock()
        self._nodes: dict[tuple[int, int], _Node] = {}
        # fileno -> set of node keys whose latest metadata lives there
        self._file_meta: dict[int, set[tuple[int, int]]] = {}
        # fileno -> set of node keys with indexed entries there
        self._file_entries: dict[int, set[tuple[int, int]]] = {}
        self._readers: dict[int, object] = {}
        self._active_fileno = 0
        self._active = None
        self._closed = False
        self._recover()
        if self._active is None:
            self._open_active(self._next_fileno())

    # -- file plumbing ---------------------------------------------------

    def _path(self, fileno: int) -> str:
        return os.path.join(self.root, f"log-{fileno:08d}.tan")

    def _lognames(self) -> list[int]:
        out = []
        for fn in self.fs.listdir(self.root):
            if fn.startswith("log-") and fn.endswith(".tan"):
                out.append(int(fn[4:-4]))
        return sorted(out)

    def _next_fileno(self) -> int:
        names = self._lognames()
        return (names[-1] + 1) if names else 1

    def _open_active(self, fileno: int) -> None:
        self._active_fileno = fileno
        self._active = self.fs.open(self._path(fileno), "ab")

    def _reader(self, fileno: int):
        f = self._readers.get(fileno)
        if f is None:
            f = self._readers[fileno] = self.fs.open(self._path(fileno), "rb")
        return f

    def _append(self, rectype: int, shard_id: int, replica_id: int,
                body: bytes) -> tuple[int, int]:
        """Append one framed record; returns (fileno, offset)."""
        payload = _KEY.pack(rectype, shard_id, replica_id) + body
        frame = _HDR.pack(MAGIC, len(payload), zlib.crc32(payload)) + payload
        if self._active.tell() + len(frame) > self.max_file_size \
                and self._active.tell() > 0:
            self._rotate()
        off = self._active.tell()
        self._active.write(frame)
        return self._active_fileno, off

    def _rotate(self) -> None:
        self.fs.fsync(self._active)
        self._active.close()
        self._open_active(self._active_fileno + 1)

    def _sync(self) -> None:
        """THE fsync (engine.go:1343 SaveRaftState durability point)."""
        t0 = time.perf_counter()
        self.fs.fsync(self._active)
        _FSYNC_US.observe((time.perf_counter() - t0) * 1e6)

    # -- recovery --------------------------------------------------------

    def _recover(self) -> None:
        files = self._lognames()
        for i, fileno in enumerate(files):
            last_file = i == len(files) - 1
            self._replay_file(fileno, truncate_tail=last_file)
        if files:
            # resume appending to the newest file
            self._open_active(files[-1])
        if self.quarantined:
            self._clamp_after_quarantine()

    def _clamp_after_quarantine(self) -> None:
        """Quarantine dropped records, so a node's persisted commit may
        point past the entries still on disk — the in-core log asserts
        ``commit <= last_index`` on load.  Clamp each commit to the
        contiguous range actually present; raft re-commits the rest once
        the leader re-replicates (committed-entry durability lives on
        the quorum, not this replica)."""
        for key, n in self._nodes.items():
            if n.removed:
                continue
            avail = n.snapshot.index + n.entries.contiguous_count(
                n.snapshot.index + 1)
            if n.state.commit > avail:
                n.state = pb.State(term=n.state.term, vote=n.state.vote,
                                   commit=avail)

    def _replay_file(self, fileno: int, truncate_tail: bool) -> None:
        """Single-pass scan + validate of a whole log file — the frame walk
        runs in C when available (native/dbtpu_native.c dbtpu_tan_scan),
        the record decode stays in Python (it builds the index)."""
        from dragonboat_tpu import native

        path = self._path(fileno)
        with self.fs.open(path, "rb") as f:
            buf = f.read()
        recs, scan_end, torn = native.tan_scan(buf, MAGIC)
        for off, poff, plen in recs:
            self._apply_record(fileno, off, buf[poff:poff + plen])
        if torn:
            if truncate_tail:
                with self.fs.open(path, "r+b") as tf:
                    tf.truncate(scan_end)
                return
            if self.recovery_mode == "quarantine":
                with self.fs.open(path, "r+b") as tf:
                    tf.truncate(scan_end)
                self.quarantined.append(f"{path}@{scan_end}")
                flight.record(flight.QUARANTINE, path=path,
                              truncated_at=scan_end)
                return
            raise CorruptLogError(
                f"{path}@{scan_end}: bad record in non-tail log file")

    def _apply_record(self, fileno: int, off: int, payload: bytes) -> None:
        rectype, shard_id, replica_id = _KEY.unpack_from(payload, 0)
        body = payload[_KEY.size:]
        key = (shard_id, replica_id)
        n = self._nodes.setdefault(key, _Node())
        if rectype in (R_UPDATE, R_META):
            ud = _dec_update(shard_id, replica_id, body)
            if not ud.state.is_empty():
                n.state = ud.state
            if not ud.snapshot.is_empty():
                n.snapshot = ud.snapshot
            if ud.entries_to_save:
                first = ud.entries_to_save[0].index
                tail = ud.entries_to_save[-1].index
                n.entries.add(first, tail, fileno, off)
                n.max_index = tail
            self._file_meta.setdefault(fileno, set()).add(key)
            if ud.entries_to_save:
                self._file_entries.setdefault(fileno, set()).add(key)
            n.removed = False
        elif rectype == R_BOOTSTRAP:
            n.bootstrap = pb.decode_bootstrap(body)
            n.removed = False
            self._file_meta.setdefault(fileno, set()).add(key)
        elif rectype == R_SNAPSHOT:
            ss, _ = pb.decode_snapshot(memoryview(body), 0)
            if ss.index >= n.snapshot.index:
                n.snapshot = ss
            self._file_meta.setdefault(fileno, set()).add(key)
        elif rectype == R_COMPACT:
            (floor,) = struct.unpack("<Q", body)
            n.entries.compact(floor)
        elif rectype == R_REMOVE:
            self._nodes[key] = _Node(removed=True)

    # -- read side -------------------------------------------------------

    def _read_record(self, fileno: int, off: int) -> pb.Update:
        f = self._reader(fileno)
        f.seek(off)
        magic, ln, crc = _HDR.unpack(f.read(_HDR.size))
        payload = f.read(ln)
        if magic != MAGIC or zlib.crc32(payload) != crc:
            raise CorruptLogError(f"{self._path(fileno)}@{off}")
        rectype, shard_id, replica_id = _KEY.unpack_from(payload, 0)
        return _dec_update(shard_id, replica_id, payload[_KEY.size:])

    # -- ILogDB ----------------------------------------------------------

    def name(self) -> str:
        return "tan"

    def close(self) -> None:
        with self._mu:
            if self._closed:
                return
            self._closed = True
            if self._active is not None:
                try:
                    self._sync()
                finally:
                    self._active.close()
            for f in self._readers.values():
                f.close()
            self._readers.clear()

    def list_node_info(self) -> list[NodeInfo]:
        with self._mu:
            return [NodeInfo(s, r) for (s, r), n in self._nodes.items()
                    if not n.removed]

    def save_bootstrap_info(self, shard_id, replica_id, bootstrap) -> None:
        with self._mu:
            fileno, _ = self._append(R_BOOTSTRAP, shard_id, replica_id,
                                     pb.encode_bootstrap(bootstrap))
            self._sync()
            key = (shard_id, replica_id)
            self._nodes.setdefault(key, _Node()).bootstrap = bootstrap
            self._file_meta.setdefault(fileno, set()).add(key)

    def get_bootstrap_info(self, shard_id, replica_id):
        with self._mu:
            n = self._nodes.get((shard_id, replica_id))
            return n.bootstrap if n and not n.removed else None

    def save_raft_state(self, updates: Sequence[pb.Update],
                        worker_id: int) -> None:
        """Batch append + ONE fsync (raftio/logdb.go:78-83)."""
        t0 = time.perf_counter()
        with self._mu:
            wrote = False
            for ud in updates:
                if ud.state.is_empty() and not ud.entries_to_save \
                        and ud.snapshot.is_empty():
                    continue
                fileno, off = self._append(
                    R_UPDATE, ud.shard_id, ud.replica_id, _enc_update(ud))
                self._apply_record_index(fileno, off, ud)
                wrote = True
            if wrote:
                self._sync()
        if wrote:
            _SAVE_US.observe((time.perf_counter() - t0) * 1e6)

    def _apply_record_index(self, fileno: int, off: int,
                            ud: pb.Update) -> None:
        key = (ud.shard_id, ud.replica_id)
        n = self._nodes.setdefault(key, _Node())
        if not ud.state.is_empty():
            n.state = ud.state
        if not ud.snapshot.is_empty():
            n.snapshot = ud.snapshot
        if ud.entries_to_save:
            first = ud.entries_to_save[0].index
            tail = ud.entries_to_save[-1].index
            n.entries.add(first, tail, fileno, off)
            n.max_index = tail
            self._file_entries.setdefault(fileno, set()).add(key)
        self._file_meta.setdefault(fileno, set()).add(key)
        n.removed = False

    def iterate_entries(self, shard_id, replica_id, low, high, max_size):
        with self._mu:
            n = self._nodes.get((shard_id, replica_id))
            if n is None or n.removed:
                return []
            out, size = [], 0
            rec_cache: dict[tuple[int, int], pb.Update] = {}
            for i in range(low, high):
                loc = n.entries.get(i)
                if loc is None:
                    break
                fileno, off, ordinal = loc
                ud = rec_cache.get((fileno, off))
                if ud is None:
                    ud = rec_cache[(fileno, off)] = self._read_record(
                        fileno, off)
                e = ud.entries_to_save[ordinal]
                size += pb.entry_size(e)
                if out and max_size and size > max_size:
                    break
                out.append(e)
            return out

    def read_raft_state(self, shard_id, replica_id, last_index):
        with self._mu:
            n = self._nodes.get((shard_id, replica_id))
            if n is None or n.removed:
                return None
            if n.state.is_empty() and not n.entries and n.snapshot.is_empty():
                return None
            first = n.snapshot.index + 1
            count = n.entries.contiguous_count(first)
            return RaftState(state=n.state, first_index=first,
                             entry_count=count)

    def remove_entries_to(self, shard_id, replica_id, index):
        with self._mu:
            key = (shard_id, replica_id)
            n = self._nodes.get(key)
            if n is None:
                return
            self._append(R_COMPACT, shard_id, replica_id,
                         struct.pack("<Q", index))
            self._sync()
            n.entries.compact(index)
            self._gc_files()

    def compact_entries_to(self, shard_id, replica_id, index):
        self.remove_entries_to(shard_id, replica_id, index)

    def _gc_files(self) -> None:
        """Delete whole log files with no live index references
        (tan compaction.go), re-homing live node metadata first."""
        live: dict[int, set[tuple[int, int]]] = {}
        for key, n in self._nodes.items():
            if n.removed:
                continue
            for fileno in n.entries.filenos():
                live.setdefault(fileno, set()).add(key)
        for fileno in self._lognames():
            if fileno == self._active_fileno:
                continue
            if live.get(fileno):
                continue
            # re-home the latest metadata of nodes whose meta lives here
            for key in sorted(self._file_meta.get(fileno, ())):
                n = self._nodes.get(key)
                if n is None or n.removed:
                    continue
                meta = pb.Update(shard_id=key[0], replica_id=key[1],
                                 state=n.state, snapshot=n.snapshot)
                mf, moff = self._append(R_META, key[0], key[1],
                                        _enc_update(meta))
                self._file_meta.setdefault(mf, set()).add(key)
                if n.bootstrap is not None:
                    bf, _ = self._append(R_BOOTSTRAP, key[0], key[1],
                                         pb.encode_bootstrap(n.bootstrap))
                    self._file_meta.setdefault(bf, set()).add(key)
            self._sync()
            r = self._readers.pop(fileno, None)
            if r is not None:
                r.close()
            self.fs.remove(self._path(fileno))
            self._file_meta.pop(fileno, None)
            self._file_entries.pop(fileno, None)

    def save_snapshots(self, updates):
        with self._mu:
            wrote = False
            for ud in updates:
                if ud.snapshot.is_empty():
                    continue
                buf = bytearray()
                pb.encode_snapshot(ud.snapshot, buf)
                fileno, _ = self._append(R_SNAPSHOT, ud.shard_id,
                                         ud.replica_id, bytes(buf))
                key = (ud.shard_id, ud.replica_id)
                n = self._nodes.setdefault(key, _Node())
                if ud.snapshot.index >= n.snapshot.index:
                    n.snapshot = ud.snapshot
                self._file_meta.setdefault(fileno, set()).add(key)
                wrote = True
            if wrote:
                self._sync()

    def get_snapshot(self, shard_id, replica_id):
        with self._mu:
            n = self._nodes.get((shard_id, replica_id))
            if n is None or n.removed or n.snapshot.is_empty():
                return None
            return n.snapshot

    def remove_node_data(self, shard_id, replica_id):
        with self._mu:
            self._append(R_REMOVE, shard_id, replica_id, b"")
            self._sync()
            self._nodes[(shard_id, replica_id)] = _Node(removed=True)
            self._gc_files()

    def import_snapshot(self, snapshot: pb.Snapshot, replica_id: int) -> None:
        """Rebuild a node from an exported snapshot (tools/import.go:134)."""
        with self._mu:
            key = (snapshot.shard_id, replica_id)
            self._append(R_REMOVE, snapshot.shard_id, replica_id, b"")
            n = _Node()
            n.state = pb.State(term=snapshot.term, vote=0,
                               commit=snapshot.index)
            n.snapshot = snapshot
            n.bootstrap = pb.Bootstrap(
                addresses=dict(snapshot.membership.addresses), join=False)
            self._nodes[key] = n
            meta = pb.Update(shard_id=snapshot.shard_id,
                             replica_id=replica_id, state=n.state,
                             snapshot=snapshot)
            fileno, _ = self._append(R_META, snapshot.shard_id, replica_id,
                                     _enc_update(meta))
            self._file_meta.setdefault(fileno, set()).add(key)
            self._append(R_BOOTSTRAP, snapshot.shard_id, replica_id,
                         pb.encode_bootstrap(n.bootstrap))
            self._sync()


class TanLogDBFactory:
    """config.LogDBFactory equivalent for NodeHostConfig."""

    def __init__(self, root_dir: str, max_file_size: int = 64 << 20,
                 recovery_mode: str = "strict") -> None:
        self.root_dir = root_dir
        self.max_file_size = max_file_size
        self.recovery_mode = recovery_mode

    def create(self) -> TanLogDB:
        return TanLogDB(self.root_dir, self.max_file_size,
                        recovery_mode=self.recovery_mode)
