"""LogReader — the raft core's read-side window over an ILogDB.

Parity with ``internal/logdb/logreader.go``: tracks (marker, length) over
the stable log, serves term()/entries() to the in-memory EntryLog, and is
advanced by Append/ApplySnapshot/Compact as the engine persists updates.
Implements the :class:`dragonboat_tpu.core.logentry.ILogDBReader` protocol.
"""

from __future__ import annotations

import threading
from typing import Sequence

from dragonboat_tpu import raftpb as pb
from dragonboat_tpu.core.logentry import CompactedError, UnavailableError
from dragonboat_tpu.raftio import ILogDB


class LogReader:
    def __init__(self, shard_id: int, replica_id: int, logdb: ILogDB) -> None:
        self.shard_id = shard_id
        self.replica_id = replica_id
        self.logdb = logdb
        self._mu = threading.RLock()
        self._snapshot = pb.Snapshot()
        # parity logreader.go:74-80 (NewLogReader): markerIndex=0, length=1,
        # so first_index()==1 and a fresh node accepts the bootstrap entry 1
        self._marker = 0      # marker acts as a virtual entry (its term is known)
        self._length = 1
        self._marker_term = 0

    # -- ILogDBReader ----------------------------------------------------

    def first_index(self) -> int:
        with self._mu:
            return self._marker + 1

    def last_index(self) -> int:
        with self._mu:
            return self._marker + self._length - 1

    def term(self, index: int) -> int:
        with self._mu:
            if index == self._marker:
                return self._marker_term
            if index < self._marker:
                raise CompactedError(index)
            if index > self.last_index():
                raise UnavailableError(index)
            ents = self.logdb.iterate_entries(
                self.shard_id, self.replica_id, index, index + 1, 0
            )
            if not ents:
                raise UnavailableError(index)
            return ents[0].term

    def entries(self, low: int, high: int, max_size: int) -> list[pb.Entry]:
        with self._mu:
            if low <= self._marker:
                raise CompactedError(low)
            if high > self.last_index() + 1:
                raise UnavailableError(high)
            return self.logdb.iterate_entries(
                self.shard_id, self.replica_id, low, high, max_size
            )

    def snapshot(self) -> pb.Snapshot:
        with self._mu:
            return self._snapshot

    # -- engine-side advancement ----------------------------------------

    def set_range(self, first: int, length: int) -> None:
        """Extend the known stable range (logreader.go SetRange)."""
        if length == 0:
            return
        with self._mu:
            last = first + length - 1
            if last <= self.last_index():
                return
            if first > self.last_index() + 1:
                # gap: reset to the new range (snapshot install path)
                self._marker = first - 1
                self._length = length + 1
                return
            self._length = last - self._marker + 1

    def append(self, entries: Sequence[pb.Entry]) -> None:
        if not entries:
            return
        with self._mu:
            first = entries[0].index
            last = entries[-1].index
            if first > self.last_index() + 1:
                raise AssertionError(
                    f"missing log entry gap: {first} > {self.last_index() + 1}"
                )
            if last <= self._marker:
                return
            self._length = last - self._marker + 1

    def apply_snapshot(self, ss: pb.Snapshot) -> None:
        with self._mu:
            self._snapshot = ss
            self._marker = ss.index
            self._marker_term = ss.term
            self._length = 1

    def create_snapshot(self, ss: pb.Snapshot) -> None:
        """Record a locally-taken snapshot without resetting the window
        (logreader.go CreateSnapshot) — it becomes the payload of
        InstallSnapshot messages to lagging peers."""
        with self._mu:
            if ss.index < self._snapshot.index:
                return
            self._snapshot = ss

    def set_state(self, st: pb.State) -> None:
        pass  # state is persisted by the engine; nothing cached here

    def compact(self, index: int) -> None:
        """Advance marker after log compaction (logreader.go Compact)."""
        with self._mu:
            if index < self._marker:
                raise CompactedError(index)
            if index > self.last_index():
                raise UnavailableError(index)
            term = self.term(index)
            self._length -= index - self._marker
            self._marker = index
            self._marker_term = term
