"""In-memory ILogDB engine.

Functional parity with the reference's logdb semantics (state+entries+
snapshot per (shard, replica), batched SaveRaftState, iterate/compact) with
Python dict storage — the loopback/test engine, and the semantic reference
for the tan file engine.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Sequence

from dragonboat_tpu import raftpb as pb
from dragonboat_tpu.raftio import ILogDB, NodeInfo, RaftState


@dataclass
class _NodeStore:
    state: pb.State = field(default_factory=pb.State)
    entries: dict[int, pb.Entry] = field(default_factory=dict)
    snapshot: pb.Snapshot = field(default_factory=pb.Snapshot)
    bootstrap: pb.Bootstrap | None = None
    max_index: int = 0


class MemLogDB(ILogDB):
    def __init__(self) -> None:
        self._mu = threading.RLock()
        self._nodes: dict[tuple[int, int], _NodeStore] = {}
        self._closed = False

    def _node(self, shard_id: int, replica_id: int) -> _NodeStore:
        key = (shard_id, replica_id)
        st = self._nodes.get(key)
        if st is None:
            st = self._nodes[key] = _NodeStore()
        return st

    # -- ILogDB ---------------------------------------------------------

    def name(self) -> str:
        return "mem"

    def close(self) -> None:
        self._closed = True

    def list_node_info(self) -> list[NodeInfo]:
        with self._mu:
            return [NodeInfo(s, r) for (s, r) in self._nodes]

    def save_bootstrap_info(self, shard_id, replica_id, bootstrap) -> None:
        with self._mu:
            self._node(shard_id, replica_id).bootstrap = bootstrap

    def get_bootstrap_info(self, shard_id, replica_id):
        with self._mu:
            return self._node(shard_id, replica_id).bootstrap

    def save_raft_state(self, updates: Sequence[pb.Update], worker_id: int) -> None:
        """Batched durable write — parity raftio/logdb.go:78-83 (the one
        fsync per step-slot in the engine pipeline)."""
        with self._mu:
            for ud in updates:
                st = self._node(ud.shard_id, ud.replica_id)
                if not ud.state.is_empty():
                    st.state = ud.state
                if not ud.snapshot.is_empty():
                    st.snapshot = ud.snapshot
                if ud.entries_to_save:
                    # conflict overwrite: a batch starting at `first`
                    # invalidates every previously-stored entry at or above
                    # it, regardless of term (the reference overwrites by
                    # index unconditionally on the save path)
                    first = ud.entries_to_save[0].index
                    for i in list(st.entries):
                        if i >= first:
                            del st.entries[i]
                    for e in ud.entries_to_save:
                        st.entries[e.index] = e
                    st.max_index = max(st.entries) if st.entries else 0

    def iterate_entries(self, shard_id, replica_id, low, high, max_size):
        with self._mu:
            st = self._node(shard_id, replica_id)
            out, size = [], 0
            for i in range(low, high):
                e = st.entries.get(i)
                if e is None:
                    break
                size += pb.entry_size(e)
                if out and max_size and size > max_size:
                    break
                out.append(e)
            return out

    def read_raft_state(self, shard_id, replica_id, last_index):
        with self._mu:
            st = self._node(shard_id, replica_id)
            if st.state.is_empty() and not st.entries and st.snapshot.is_empty():
                return None
            first = st.snapshot.index + 1
            count = 0
            i = first
            while i in st.entries:
                count += 1
                i += 1
            return RaftState(state=st.state, first_index=first, entry_count=count)

    def remove_entries_to(self, shard_id, replica_id, index):
        with self._mu:
            st = self._node(shard_id, replica_id)
            for i in list(st.entries):
                if i <= index:
                    del st.entries[i]

    def compact_entries_to(self, shard_id, replica_id, index):
        self.remove_entries_to(shard_id, replica_id, index)

    def save_snapshots(self, updates):
        with self._mu:
            for ud in updates:
                if not ud.snapshot.is_empty():
                    self._node(ud.shard_id, ud.replica_id).snapshot = ud.snapshot

    def get_snapshot(self, shard_id, replica_id):
        with self._mu:
            ss = self._node(shard_id, replica_id).snapshot
            return None if ss.is_empty() else ss

    def remove_node_data(self, shard_id, replica_id):
        with self._mu:
            self._nodes.pop((shard_id, replica_id), None)

    def import_snapshot(self, snapshot: pb.Snapshot, replica_id: int) -> None:
        with self._mu:
            st = self._node(snapshot.shard_id, replica_id)
            st.snapshot = snapshot
            st.entries.clear()
            st.state = pb.State(
                term=snapshot.term, vote=0, commit=snapshot.index
            )
