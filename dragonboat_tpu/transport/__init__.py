"""Transport layer: message batches + snapshot streaming behind
raftio.ITransport (SURVEY §2.7)."""

from dragonboat_tpu.transport.chan import ChanTransport, ChanTransportFactory
from dragonboat_tpu.transport.hub import TransportHub

__all__ = ["ChanTransport", "ChanTransportFactory", "TransportHub"]
