"""In-process channel transport.

Parity with the reference's ``plugin/chan``: a process-global listening map
address → handler so full multi-NodeHost clusters run in one process with no
sockets (chan.go:49-60) — the primary test transport and the template for
the device-loopback path.
"""

from __future__ import annotations

import threading
from typing import Callable

from dragonboat_tpu import raftpb as pb
from dragonboat_tpu.raftio import IConnection, ISnapshotConnection, ITransport


class _Registry:
    def __init__(self) -> None:
        self.mu = threading.RLock()
        self.listening: dict[str, "ChanTransport"] = {}  # guarded-by: mu

    def register(self, addr: str, t: "ChanTransport") -> None:
        with self.mu:
            self.listening[addr] = t

    def unregister(self, addr: str) -> None:
        with self.mu:
            self.listening.pop(addr, None)

    def get(self, addr: str) -> "ChanTransport | None":
        with self.mu:
            return self.listening.get(addr)


_GLOBAL = _Registry()


class _Conn:
    def __init__(self, owner: "ChanTransport", target: str) -> None:
        self.owner = owner
        self.target = target

    def close(self) -> None:
        pass

    def send_message_batch(self, batch: pb.MessageBatch) -> None:
        t = _GLOBAL.get(self.target)
        if t is None or not t.running or self.owner.partitioned:
            raise ConnectionError(f"{self.target} unreachable")
        t.deliver(batch)


class _SnapConn:
    def __init__(self, owner: "ChanTransport", target: str) -> None:
        self.owner = owner
        self.target = target

    def close(self) -> None:
        pass

    def send_chunk(self, chunk: dict) -> None:
        t = _GLOBAL.get(self.target)
        if t is None or not t.running or self.owner.partitioned:
            raise ConnectionError(f"{self.target} unreachable")
        t.deliver_chunk(chunk)


class ChanTransport(ITransport):
    def __init__(self, addr: str, message_handler, chunk_handler) -> None:
        self.addr = addr
        self.message_handler = message_handler
        self.chunk_handler = chunk_handler
        self.running = False
        self.partitioned = False  # monkey-test hook (monkey.go:170)
        # test hooks (monkey transport hooks :83-89): drop predicate,
        # per-message delay (seconds), seeded in-batch reordering, and
        # duplicate injection (raft must tolerate at-least-once delivery)
        self.drop_predicate: Callable[[pb.Message], bool] | None = None
        self.delay_func: Callable[[pb.Message], float] | None = None
        self.reorder_rng = None  # random.Random; shuffles batch requests
        self.duplicate_predicate: Callable[[pb.Message], bool] | None = None

    def name(self) -> str:
        return "chan-transport"

    def start(self) -> None:
        self.running = True
        _GLOBAL.register(self.addr, self)

    def close(self) -> None:
        self.running = False
        _GLOBAL.unregister(self.addr)

    def get_connection(self, target: str) -> IConnection:
        return _Conn(self, target)

    def get_snapshot_connection(self, target: str) -> ISnapshotConnection:
        return _SnapConn(self, target)

    def deliver(self, batch: pb.MessageBatch) -> None:
        if self.partitioned:
            return
        reqs = batch.requests
        if self.drop_predicate is not None:
            reqs = tuple(m for m in reqs if not self.drop_predicate(m))
        if self.duplicate_predicate is not None:
            reqs = reqs + tuple(
                m for m in reqs if self.duplicate_predicate(m))
        if self.reorder_rng is not None and len(reqs) > 1:
            shuffled = list(reqs)
            self.reorder_rng.shuffle(shuffled)
            reqs = tuple(shuffled)
        if reqs is not batch.requests:
            batch = pb.MessageBatch(
                requests=reqs,
                deployment_id=batch.deployment_id,
                source_address=batch.source_address,
                bin_ver=batch.bin_ver,
                # the fabric trace header survives chaos rewrites — a
                # dropped/duplicated message keeps the batch's contexts
                fabric=batch.fabric,
            )
        if self.delay_func is not None:
            delays = [self.delay_func(m) for m in batch.requests]
            d = max(delays, default=0.0)
            if d > 0:
                threading.Timer(d, self.message_handler, (batch,)).start()
                return
        # hub_recv stamping moved to the NodeHost inbound seam
        # (fabric.METER.on_batch_received) — one site covering chan AND
        # tcp, off the fabric header when the sender attached one
        self.message_handler(batch)

    def deliver_chunk(self, chunk: dict) -> None:
        if not self.partitioned:
            self.chunk_handler(chunk)


class ChanTransportFactory:
    """config.TransportFactory equivalent."""

    def create(self, nhconfig, message_handler, chunk_handler) -> ChanTransport:
        return ChanTransport(nhconfig.raft_address, message_handler, chunk_handler)

    def validate(self, addr: str) -> bool:
        return True
