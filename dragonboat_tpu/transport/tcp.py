"""TCP transport: framed MessageBatch + snapshot chunk streams over sockets.

Parity with the reference's TCP module (``internal/transport/tcp.go``):
a length+CRC framed request header (:64-110) in front of each payload, a
method field separating raft batches (100) from snapshot chunks (200), a
listener spawning one reader per accepted connection, and cached outbound
connections per target.  Payload integrity rides the application-layer CRCs
already inside raftpb's wire encodings (the header carries its own CRC and
a payload CRC, mirroring requestHeader).
"""

from __future__ import annotations

import socket
import ssl
import struct
import threading
import zlib

from dragonboat_tpu import raftpb as pb
from dragonboat_tpu.raftio import IConnection, ISnapshotConnection, ITransport

RAFT_TYPE = 100
SNAPSHOT_TYPE = 200
_REQ_HDR = struct.Struct(">HQII")     # method, size, header-crc, payload-crc
MAX_FRAME = 1 << 30
# the reference's per-request preamble (tcp.go:43-44): 2 magic bytes
# before every header; the all-zero poison announces a clean close
GO_MAGIC = b"\xae\x7d"
GO_POISON = b"\x00\x00"


def _encode_header(method: int, payload: bytes) -> bytes:
    """requestHeader.encode (tcp.go:79-90): crc field zeroed while hashing."""
    pcrc = zlib.crc32(payload)
    raw = _REQ_HDR.pack(method, len(payload), 0, pcrc)
    hcrc = zlib.crc32(raw)
    return _REQ_HDR.pack(method, len(payload), hcrc, pcrc)


def _decode_header(raw: bytes) -> tuple[int, int, int]:
    method, size, hcrc, pcrc = _REQ_HDR.unpack(raw)
    expected = zlib.crc32(_REQ_HDR.pack(method, size, 0, pcrc))
    if hcrc != expected:
        raise ValueError("request header crc mismatch")
    if method not in (RAFT_TYPE, SNAPSHOT_TYPE):
        raise ValueError(f"invalid method {method}")
    if size > MAX_FRAME:
        raise ValueError("frame too large")
    return method, size, pcrc


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        part = sock.recv(n - len(buf))
        if not part:
            raise ConnectionError("peer closed")
        buf += part
    return bytes(buf)


def _send_frame(sock: socket.socket, method: int, payload: bytes) -> None:
    sock.sendall(_encode_header(method, payload) + payload)


class _TCPConn:
    """Cached outbound connection (TCPConnection, tcp.go:298)."""

    def __init__(self, target: str,
                 client_ctx: ssl.SSLContext | None = None,
                 wire: str = "native") -> None:
        self.wire = wire
        host, port = target.rsplit(":", 1)
        sock = socket.create_connection((host, int(port)), timeout=5)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        if client_ctx is not None:
            # mutual TLS (tcp.go getConnection → tls.Dial with the client
            # certificate; the server name is not checked — the CA is the
            # trust anchor, matching MutualTLS semantics)
            sock = client_ctx.wrap_socket(sock, server_hostname=host)
        self.sock = sock
        self.mu = threading.Lock()

    def close(self) -> None:
        if self.wire == "go":
            # clean-close handshake (tcp.go sendPoison): a reference
            # peer distinguishes shutdown from a dropped connection
            try:
                with self.mu:
                    self.sock.sendall(GO_POISON)
            except OSError:
                pass
        try:
            self.sock.close()
        except OSError:
            pass

    def send_message_batch(self, batch: pb.MessageBatch) -> None:
        with self.mu:
            if self.wire == "go":
                from dragonboat_tpu.raftpb import gowire

                payload = gowire.encode_message_batch(
                    batch.requests, batch.deployment_id,
                    batch.source_address,
                    # a real Go receiver REJECTS BinVer != 210
                    # (transport.go:312); the hub builds batches with
                    # the default 0
                    batch.bin_ver or gowire.TRANSPORT_BIN_VERSION,
                    # fabric trace header rides as an optional field a
                    # reference peer's _skip_field ignores
                    fabric=(pb.encode_fabric_header(batch.fabric)
                            if batch.fabric is not None else None))
                # one buffer, one syscall: with TCP_NODELAY a separate
                # magic write would emit its own 2-byte segment per batch
                self.sock.sendall(GO_MAGIC +
                                  _encode_header(RAFT_TYPE, payload) +
                                  payload)
            else:
                _send_frame(self.sock, RAFT_TYPE,
                            pb.encode_message_batch(batch))

    def send_chunk(self, chunk) -> None:
        if self.wire == "go":
            # reference snapshot framing (tcp.go:373): the same
            # magic+header preamble with method=200 per chunk, payload
            # a gogo-marshaled pb.Chunk (gowire.GoChunk here — the hub
            # splits with split_snapshot_message_go on this wire)
            from dragonboat_tpu.raftpb import gowire

            if not isinstance(chunk, gowire.GoChunk):
                raise TypeError(
                    "go-wire transport sends gowire.GoChunk records")
            payload = gowire.encode_chunk(chunk)
            with self.mu:
                self.sock.sendall(GO_MAGIC +
                                  _encode_header(SNAPSHOT_TYPE, payload) +
                                  payload)
            return
        with self.mu:
            _send_frame(self.sock, SNAPSHOT_TYPE, pb.encode_chunk(chunk))


class _ConnProxy(IConnection):
    """Hands a cached connection back to the hub; evicts it on failure so
    the next send re-dials (the hub's breaker paces the retries)."""

    def __init__(self, transport: "TCPTransport", target: str) -> None:
        self.transport = transport
        self.target = target

    def close(self) -> None:
        pass

    def _call(self, fn_name: str, arg) -> None:
        if self.transport.partitioned:
            raise ConnectionError(f"{self.transport.addr} partitioned")
        conn = self.transport._conn(self.target)
        try:
            getattr(conn, fn_name)(arg)
        except Exception:
            self.transport._evict(self.target, conn)
            raise

    def send_message_batch(self, batch: pb.MessageBatch) -> None:
        self._call("send_message_batch", batch)

    def send_chunk(self, chunk) -> None:
        if isinstance(chunk, dict):   # chan-transport dict shape
            m = chunk.get("message")
            raise ValueError("tcp transport requires pb.Chunk, got dict "
                             f"(message={m is not None})")
        self._call("send_chunk", chunk)


class TCPTransport(ITransport):
    """Listener + connection cache (NewTCPTransport, tcp.go:394)."""

    def __init__(self, addr: str, message_handler, chunk_handler,
                 listen_addr: str = "",
                 server_ctx: ssl.SSLContext | None = None,
                 client_ctx: ssl.SSLContext | None = None,
                 wire: str = "native") -> None:
        if wire not in ("native", "go"):
            raise ValueError(f"unknown wire {wire!r}")
        self.wire = wire
        self.addr = addr
        # ListenAddress (config.go): where to bind; RaftAddress is what is
        # advertised to peers (NAT / 0.0.0.0 binds)
        self.listen_addr = listen_addr or addr
        self.server_ctx = server_ctx
        self.client_ctx = client_ctx
        self.message_handler = message_handler
        self.chunk_handler = chunk_handler
        # chaos-parity with ChanTransport: while True, inbound frames are
        # read-and-discarded and outbound sends fail (partition_node)
        self.partitioned = False
        self.mu = threading.Lock()
        self.conns: dict[str, _TCPConn] = {}               # guarded-by: mu
        self.running = False
        self._listener: socket.socket | None = None
        self._accepted: set[socket.socket] = set()         # guarded-by: mu

    def name(self) -> str:
        return ("tcp-transport" if self.wire == "native"
                else "go-tcp-transport")

    def start(self) -> None:
        host, port = self.listen_addr.rsplit(":", 1)
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind((host, int(port)))
        s.listen(128)
        self._listener = s
        self.running = True
        threading.Thread(target=self._accept_main,
                         name=f"tcp-accept-{self.addr}", daemon=True).start()

    def close(self) -> None:
        self.running = False
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
        with self.mu:
            for c in self.conns.values():
                c.close()
            self.conns.clear()
            accepted, self._accepted = self._accepted, set()
        for sock in accepted:   # unblock reader threads stuck in recv()
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass

    def _accept_main(self) -> None:
        while self.running:
            try:
                sock, _ = self._listener.accept()
            except OSError:
                return
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self.mu:
                self._accepted.add(sock)
            threading.Thread(target=self._read_main, args=(sock,),
                             daemon=True).start()

    def _read_main(self, sock: socket.socket) -> None:
        """Per-connection reader (tcp.go read loop).  The TLS handshake
        happens HERE, per connection with a timeout — in the accept loop a
        stalled client would block every other peer's inbound path."""
        try:
            if self.server_ctx is not None:
                plain = sock
                try:
                    sock.settimeout(10.0)
                    sock = self.server_ctx.wrap_socket(sock,
                                                       server_side=True)
                    sock.settimeout(None)
                except (ssl.SSLError, OSError):
                    return
                finally:
                    if sock is plain:   # handshake failed
                        with self.mu:
                            self._accepted.discard(plain)
                        try:
                            plain.close()
                        except OSError:
                            pass
                    else:               # track the wrapped socket instead
                        with self.mu:
                            self._accepted.discard(plain)
                            self._accepted.add(sock)
            while self.running:
                if self.wire == "go":
                    # per-request preamble (tcp.go readMagicNumber):
                    # magic continues, poison is a clean close
                    pre = _recv_exact(sock, 2)
                    if pre == GO_POISON:
                        # ack the poison (tcp.go:507 sendPoisonAck) —
                        # a reference peer blocks in waitPoisonAck for
                        # its deadline on every clean close otherwise
                        try:
                            sock.sendall(GO_POISON)
                        except OSError:
                            pass
                        break
                    if pre != GO_MAGIC:
                        raise ValueError("bad magic")
                raw = _recv_exact(sock, _REQ_HDR.size)
                method, size, pcrc = _decode_header(raw)
                payload = _recv_exact(sock, size)
                if zlib.crc32(payload) != pcrc:
                    raise ValueError("payload crc mismatch")
                if self.partitioned:
                    continue
                if method == SNAPSHOT_TYPE and self.wire == "go":
                    # a reference peer's snapshot stream: decode the
                    # gogo-marshaled Chunk and hand it to the chunk
                    # sink's go-wire reassembler (ChunkSink.add
                    # dispatches on the record type)
                    from dragonboat_tpu.raftpb import gowire

                    self.chunk_handler(gowire.decode_chunk(payload))
                    continue
                if method == RAFT_TYPE:
                    if self.wire == "go":
                        from dragonboat_tpu.raftpb import gowire

                        reqs, dep, src, ver, fab = \
                            gowire.decode_message_batch(payload)
                        batch = pb.MessageBatch(
                            requests=reqs, deployment_id=dep,
                            source_address=src, bin_ver=ver,
                            # None for an absent blob or an unknown
                            # header version (old/new peer — drop it)
                            fabric=(pb.decode_fabric_header(fab)
                                    if fab is not None else None))
                    else:
                        batch = pb.decode_message_batch(payload)
                    self.message_handler(batch)
                else:
                    self.chunk_handler(pb.decode_chunk(payload))
        except (ConnectionError, ValueError, OSError):
            pass
        finally:
            with self.mu:
                self._accepted.discard(sock)
            try:
                sock.close()
            except OSError:
                pass

    # -- outbound --------------------------------------------------------

    def _conn(self, target: str) -> _TCPConn:
        with self.mu:
            c = self.conns.get(target)
            if c is None:
                c = self.conns[target] = _TCPConn(target, self.client_ctx,
                                                  wire=self.wire)
            return c

    def _evict(self, target: str, conn: _TCPConn) -> None:
        with self.mu:
            if self.conns.get(target) is conn:
                del self.conns[target]
        conn.close()

    def get_connection(self, target: str) -> IConnection:
        return _ConnProxy(self, target)

    def get_snapshot_connection(self, target: str) -> ISnapshotConnection:
        return _ConnProxy(self, target)


def _tls_contexts(nhconfig):
    """Mutual-TLS contexts from NodeHostConfig (config.go MutualTLS +
    CAFile/CertFile/KeyFile): both sides present certificates signed by
    the shared CA and require the peer to do the same."""
    if not nhconfig.mutual_tls:
        return None, None
    server = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    server.load_cert_chain(nhconfig.cert_file, nhconfig.key_file)
    server.load_verify_locations(nhconfig.ca_file)
    server.verify_mode = ssl.CERT_REQUIRED
    client = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
    client.load_cert_chain(nhconfig.cert_file, nhconfig.key_file)
    client.load_verify_locations(nhconfig.ca_file)
    # full verification incl. the server identity (config.go:727 sets
    # ServerName = target host): node certificates must carry the host
    # in their SANs — a compromised key for one identity must not let
    # its holder impersonate every other peer
    client.check_hostname = True
    client.verify_mode = ssl.CERT_REQUIRED
    return server, client


class TCPTransportFactory:
    """config.TransportFactory for real sockets (DefaultTransportFactory).

    ``wire="go"`` makes every connection speak the reference's exact
    byte format — the 2-byte magic preamble + 18-byte crc'd request
    header (tcp.go:43,64-110) around a gogo-protobuf MessageBatch
    (raftpb/gowire.py) — so a host can exchange raft traffic with
    reference hosts over DCN.  Snapshot streaming interops too: method
    200 requests carry reference-layout Chunks both ways (gowire
    GoChunk + chunks.py split_snapshot_message_go/GoChunkSink), with
    SM images transcoded at the fleet boundary (rsm/gosnapshot.py):
    reference container + re-banked sessions outbound — in flight for
    live streams (GoStreamTranscoder), whole-image for file catchup —
    and naturalized inbound before recovery.  File catchup, on-disk
    live streams and witness heals all interop in both directions."""

    def __init__(self, wire: str = "native") -> None:
        self.wire = wire

    def create(self, nhconfig, message_handler, chunk_handler) -> TCPTransport:
        server_ctx, client_ctx = _tls_contexts(nhconfig)
        return TCPTransport(nhconfig.raft_address, message_handler,
                            chunk_handler,
                            listen_addr=nhconfig.listen_address,
                            server_ctx=server_ctx, client_ctx=client_ctx,
                            wire=self.wire)

    def validate(self, addr: str) -> bool:
        try:
            host, port = addr.rsplit(":", 1)
            return 0 < int(port) < 65536 and bool(host)
        except ValueError:
            return False
