"""Snapshot chunk streaming: split, reassemble, GC.

Parity with the reference's chunked snapshot transfer
(``internal/transport/snapshot.go:49,211-217`` sender split,
``chunk.go:106-194`` receiver ``Chunk.Add`` with per-transfer locks, a
concurrency cap and tick-based GC of stalled transfers).  The sender reads
the snapshot file and emits ``raftpb.Chunk`` records; the receiver
reassembles them into a local file and delivers the original
InstallSnapshot message (filepath rewritten) once the last chunk lands.
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass, field

from dragonboat_tpu import raftpb as pb

SNAPSHOT_CHUNK_SIZE = 2 * 1024 * 1024   # snapshot.go:49 snapshotChunkSize
MAX_CONCURRENT_STREAMS = 128            # chunk.go:42 MaxConcurrentStreaming
GC_TICKS = 30                           # stalled-transfer timeout in ticks


def split_snapshot_message(m: pb.Message, deployment_id: int,
                           chunk_size: int = SNAPSHOT_CHUNK_SIZE,
                           source_address: str = ""):
    """Yield Chunk records for an InstallSnapshot message
    (snapshot.go:211 SendSnapshot read-and-split).

    External snapshot files (rsm/files.go) ride the SAME chunk stream,
    concatenated after the container in ``ss.files`` order; the receiver
    splits them back out using the per-file sizes recorded on the
    snapshot (ChunkSink._split_external_files)."""
    ss = m.snapshot
    main_size = os.path.getsize(ss.filepath) if ss.filepath else 0
    file_size = main_size + sum(f.file_size for f in ss.files)
    count = max(1, (file_size + chunk_size - 1) // chunk_size)

    def byte_stream():
        paths = ([ss.filepath] if ss.filepath else []) + [
            f.filepath for f in ss.files]
        for p in paths:
            with open(p, "rb") as f:
                while True:
                    block = f.read(chunk_size)
                    if not block:
                        break
                    yield block

    class _concat:
        def __init__(self):
            self.gen = byte_stream()
            self.buf = b""

        def __enter__(self):
            return self

        def __exit__(self, *a):
            return False

        def read(self, n):
            while len(self.buf) < n:
                block = next(self.gen, None)
                if block is None:
                    break
                self.buf += block
            out, self.buf = self.buf[:n], self.buf[n:]
            return out

    with (_concat() if file_size else _null_file()) as f:
        for cid in range(count):
            data = f.read(chunk_size)
            yield pb.Chunk(
                shard_id=m.shard_id,
                replica_id=m.to,
                from_=m.from_,
                chunk_id=cid,
                chunk_count=count,
                chunk_size=len(data),
                file_size=file_size,
                index=ss.index,
                term=ss.term,
                deployment_id=deployment_id,
                source_address=source_address if cid == 0 else "",
                data=data,
                message=m if cid == 0 else None,
            )


class _null_file:
    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False

    def read(self, n):
        return b""


@dataclass
class _Transfer:
    message: pb.Message | None = None
    next_chunk: int = 0
    chunk_count: int = 0
    path: str = ""
    fh: object = None
    idle_ticks: int = 0
    validated: int = 0
    source_address: str = ""


class ChunkSink:
    """Receiver-side reassembly — parity chunk.go:106 (Chunk.Add)."""

    def __init__(self, snapshot_dir: str, deployment_id: int,
                 deliver, max_concurrent: int = MAX_CONCURRENT_STREAMS):
        """``deliver(message, source_address)`` is called with the rebuilt
        InstallSnapshot (filepath pointing at the reassembled local file)."""
        self.dir = snapshot_dir
        self.deployment_id = deployment_id
        self.deliver = deliver
        self.max_concurrent = max_concurrent
        self.mu = threading.Lock()
        self.transfers: dict[tuple[int, int, int], _Transfer] = {}
        # reference-layout chunks (go-wire transports) reassemble under
        # their own semantics; shares dir/deliver/GC with this sink
        self._go: "GoChunkSink | None" = None

    def add(self, c) -> bool:
        """Returns False when the chunk is rejected (out of order, over the
        concurrency cap, wrong deployment).  Dispatches reference-layout
        ``gowire.GoChunk`` records (no embedded message, per-file split)
        to the go-wire reassembler."""
        if not isinstance(c, pb.Chunk):
            if self._go is None:
                # construct under the lock: one reader thread per inbound
                # connection — two concurrent first-chunks must not each
                # build a sink and orphan the loser's open transfer
                with self.mu:
                    if self._go is None:
                        self._go = GoChunkSink(
                            self.dir, self.deployment_id, self.deliver,
                            self.max_concurrent)
            return self._go.add(c)
        if c.deployment_id != self.deployment_id:
            return False
        key = (c.shard_id, c.replica_id, c.from_)
        completed = None
        with self.mu:
            t = self.transfers.get(key)
            if c.chunk_id == 0:
                if t is not None:
                    self._abort_locked(key)
                if len(self.transfers) >= self.max_concurrent:
                    return False
                if c.message is None:
                    return False
                os.makedirs(self.dir, exist_ok=True)
                path = os.path.join(
                    self.dir,
                    f"incoming-{c.shard_id:016X}-{c.replica_id:016X}"
                    f"-{c.index:016X}.gbsnap",
                )
                t = _Transfer(message=c.message, chunk_count=c.chunk_count,
                              path=path, fh=open(path, "wb"),
                              source_address=c.source_address)
                self.transfers[key] = t
            elif t is None or c.chunk_id != t.next_chunk:
                # out-of-order/stale chunk: drop the whole transfer
                if t is not None:
                    self._abort_locked(key)
                return False
            t.idle_ticks = 0
            t.fh.write(c.data)
            t.validated += len(c.data)
            t.next_chunk = c.chunk_id + 1
            # streamed transfers (chunkwriter.py) carry chunk_count=0 until
            # the tail chunk, whose count/file_size close the transfer
            if c.is_last():
                t.fh.close()
                if c.file_size and t.validated != c.file_size:
                    os.remove(t.path)
                    del self.transfers[key]
                    return False
                del self.transfers[key]
                completed = t
        if completed is not None:
            # deliver OUTSIDE the lock: dispatch recurses into the whole
            # nodehost message path and must not serialize other transfers
            m = completed.message
            from dataclasses import replace
            files = self._split_external_files(completed.path,
                                               m.snapshot.files)
            m = replace(m, snapshot=replace(m.snapshot,
                                            filepath=completed.path,
                                            files=files))
            self.deliver(m, completed.source_address)
        return True

    @staticmethod
    def _split_external_files(path: str, files):
        """The sender concatenated external snapshot files after the
        container (split_snapshot_message); carve them back out next to
        the reassembled file and truncate the container to its own bytes
        (chunk.go multi-file reassembly, compressed into one stream)."""
        if not files:
            return files
        from dataclasses import replace
        total = os.path.getsize(path)
        main_size = total - sum(f.file_size for f in files)
        out = []
        with open(path, "rb") as f:
            f.seek(main_size)
            for sf in files:
                dst = f"{path}.xf{sf.file_id}"
                remaining = sf.file_size
                with open(dst, "wb") as o:
                    while remaining:
                        block = f.read(min(remaining, 1 << 20))
                        if not block:
                            break
                        o.write(block)
                        remaining -= len(block)
                out.append(replace(sf, filepath=dst))
        with open(path, "r+b") as f:
            f.truncate(main_size)
        return tuple(out)

    def _abort_locked(self, key) -> None:
        t = self.transfers.pop(key, None)
        if t is not None and t.fh is not None:
            try:
                t.fh.close()
                os.remove(t.path)
            except OSError:
                pass

    def tick(self) -> None:
        """Advance the GC clock; drop stalled transfers (chunk.go GC)."""
        with self.mu:
            stalled = []
            for key, t in self.transfers.items():
                t.idle_ticks += 1
                if t.idle_ticks >= GC_TICKS:
                    stalled.append(key)
            for key in stalled:
                self._abort_locked(key)
        if self._go is not None:
            self._go.tick()

    def inflight(self) -> int:
        with self.mu:
            n = len(self.transfers)
        return n + (self._go.inflight() if self._go is not None else 0)


# ---------------------------------------------------------------------------
# Go-wire snapshot streaming (snapshot.go getChunks / chunk.go Add): the
# reference splits PER FILE (each chunk names its file and carries
# file_chunk_id/count alongside the global chunk_id/count) and the
# RECEIVER synthesizes the InstallSnapshot message from chunk fields —
# there is no embedded chunk-0 message on this wire.  The local on-disk
# layout of a reassembled transfer stays the repo's own (incoming-*.gbsnap
# + .xfN external files, same as the native sink) — only the WIRE format
# must match the Go fleet.
# ---------------------------------------------------------------------------


def split_snapshot_message_go(m: pb.Message, deployment_id: int,
                              chunk_size: int = SNAPSHOT_CHUNK_SIZE):
    """Yield reference-layout GoChunks for an InstallSnapshot
    (snapshot.go:204 getChunks + :225 loadChunkData read-at-send).
    Witness snapshots ship as the reference's single synthetic chunk
    (snapshot.go:262 getWitnessChunk) carrying a well-formed EMPTY image
    in the REFERENCE container format (rsm/gosnapshot.py) — the Go
    receiver validates every chunk-0 payload against its SnapshotHeader
    layout (chunk.go:214 NewSnapshotValidator) even though witness
    snapshots are partial and never recovered from."""
    from dragonboat_tpu.raftpb import gowire

    ss = m.snapshot
    if ss.witness:
        data = witness_image_bytes()
        yield gowire.GoChunk(
            shard_id=m.shard_id, replica_id=m.to, from_=m.from_,
            chunk_id=0, chunk_count=1, chunk_size=len(data), data=data,
            index=ss.index, term=ss.term, membership=ss.membership,
            filepath="witness.snapshot", file_size=len(data),
            deployment_id=deployment_id, file_chunk_id=0,
            file_chunk_count=1, on_disk_index=0, witness=True)
        return
    if not ss.filepath or os.path.getsize(ss.filepath) == 0:
        raise ValueError("empty snapshot file")  # snapshot.go:208 panic
    # the Go receiver byte-validates AND later recovers from the main
    # image in ITS container format — transcode ours (sessions
    # re-banked, user payload verbatim; rsm/gosnapshot.py).  External
    # files ride raw: has_file_info chunks are never validated and the
    # bytes are the user's own.
    from dragonboat_tpu.rsm.gosnapshot import (
        native_image_to_go,
        sniff_v2_file,
    )

    main_path = ss.filepath
    tmp_path = None
    if not sniff_v2_file(main_path):
        # transcode into a sibling temp file and stream from disk: the
        # paced transfer can run for minutes and must not pin a
        # multi-GB image (or its transcoded copy) in memory
        tmp_path = main_path + ".gowire"
        with open(main_path, "rb") as f:
            img = native_image_to_go(f.read())
        with open(tmp_path, "wb") as f:
            f.write(img)
        del img
        main_path = tmp_path
    try:
        files: list[tuple[str, int, pb.SnapshotFile | None]] = [
            (main_path, os.path.getsize(main_path), None)]
        for sf in ss.files:
            files.append((sf.filepath, sf.file_size, sf))
        per_file = [max(1, (sz + chunk_size - 1) // chunk_size)
                    for _, sz, _ in files]
        total = sum(per_file)
        chunk_id = 0
        for (path, size, sf), count in zip(files, per_file):
            with open(path, "rb") as f:
                for fcid in range(count):
                    data = f.read(chunk_size)
                    yield gowire.GoChunk(
                        shard_id=m.shard_id,
                        replica_id=m.to,
                        from_=m.from_,
                        chunk_id=chunk_id,
                        chunk_count=total,
                        chunk_size=len(data),
                        data=data,
                        index=ss.index,
                        term=ss.term,
                        membership=ss.membership,
                        filepath=path,
                        file_size=size,
                        deployment_id=deployment_id,
                        file_chunk_id=fcid,
                        file_chunk_count=count,
                        has_file_info=sf is not None,
                        file_info=sf if sf is not None
                        else pb.SnapshotFile(file_id=0, filepath=""),
                        on_disk_index=ss.on_disk_index,
                        witness=False,  # witness branch returned above
                    )
                    chunk_id += 1
    finally:
        if tmp_path is not None:
            try:
                os.remove(tmp_path)
            except OSError:
                pass


@dataclass
class _GoTransfer:
    next_chunk: int = 0
    path: str = ""                      # container file
    fh: object = None
    idle_ticks: int = 0
    main_written: int = 0
    files: list = field(default_factory=list)   # (SnapshotFile, local path)
    cur_file_fh: object = None
    cur_file_written: int = 0
    first: object = None                # chunk 0 (message fields)


class GoChunkSink:
    """Receiver reassembly for reference-layout chunks (chunk.go:106
    Add): strict global ordering, per-file writes, and the final
    InstallSnapshot synthesized from chunk fields (chunk.go toMessage).
    Shares the native sink's directory, delivery callback and GC
    cadence — ``ChunkSink`` owns one and dispatches by chunk type."""

    def __init__(self, snapshot_dir: str, deployment_id: int, deliver,
                 max_concurrent: int = MAX_CONCURRENT_STREAMS):
        self.dir = snapshot_dir
        self.deployment_id = deployment_id
        self.deliver = deliver
        self.max_concurrent = max_concurrent
        self.mu = threading.Lock()
        self.transfers: dict[tuple[int, int, int], _GoTransfer] = {}

    def add(self, c) -> bool:
        if c.deployment_id != self.deployment_id:
            return False
        key = (c.shard_id, c.replica_id, c.from_)
        if c.is_poison():
            # a failed sender poisons its stream (raftpb LastChunkCount-1,
            # job.go): drop the transfer, nothing to deliver
            with self.mu:
                self._abort_locked(key)
            return False
        completed = None
        with self.mu:
            t = self.transfers.get(key)
            if c.chunk_id == 0:
                if t is not None:
                    self._abort_locked(key)
                if len(self.transfers) >= self.max_concurrent:
                    return False
                os.makedirs(self.dir, exist_ok=True)
                path = os.path.join(
                    self.dir,
                    f"incoming-{c.shard_id:016X}-{c.replica_id:016X}"
                    f"-{c.index:016X}.gbsnap",
                )
                t = _GoTransfer(path=path, fh=open(path, "wb"), first=c)
                self.transfers[key] = t
            elif t is None or c.chunk_id != t.next_chunk:
                if t is not None:
                    self._abort_locked(key)
                return False
            t.idle_ticks = 0
            t.next_chunk = c.chunk_id + 1
            if not c.has_file_info:
                if t.fh is None:     # main file already closed: protocol
                    self._abort_locked(key)   # violation, clean reject
                    return False
                t.fh.write(c.data)
                t.main_written += len(c.data)
                # counted transfers close the main file on its last file
                # chunk; STREAMED ones (rsm.ChunkWriter — file_chunk
                # counts are 0 / the LastChunkCount sentinel) only at
                # the stream tail.  file_size is unknown for streams
                # (0): size validation applies to counted files only.
                if c.is_last_file_chunk() or c.is_last():
                    t.fh.close()
                    t.fh = None
                    if c.file_size and t.main_written != c.file_size:
                        self._abort_locked(key)
                        return False
            else:
                if c.file_chunk_id == 0:
                    if t.cur_file_fh is not None:   # protocol violation
                        self._abort_locked(key)
                        return False
                    dst = f"{t.path}.xf{c.file_info.file_id}"
                    t.cur_file_fh = open(dst, "wb")
                    t.cur_file_written = 0
                    t.files.append((c.file_info, dst))
                if t.cur_file_fh is None:
                    self._abort_locked(key)
                    return False
                t.cur_file_fh.write(c.data)
                t.cur_file_written += len(c.data)
                if c.is_last_file_chunk():
                    t.cur_file_fh.close()
                    t.cur_file_fh = None
                    if t.cur_file_written != c.file_size:
                        self._abort_locked(key)
                        return False
            if c.is_last():
                if t.fh is not None or t.cur_file_fh is not None:
                    self._abort_locked(key)   # a file never closed
                    return False
                del self.transfers[key]
                completed = t
        if completed is not None:
            try:
                self._naturalize(completed)
            except Exception:
                # a malformed image must reject the TRANSFER (files
                # cleaned), not kill the connection reader — every
                # other malformed-chunk path returns False the same way
                for pth in ([completed.path, completed.path + ".transcode"]
                            + [d for _, d in completed.files]):
                    try:
                        os.remove(pth)
                    except OSError:
                        pass
                return False
            self.deliver(self._to_message(completed), "")
        return True

    @staticmethod
    def _naturalize(t: _GoTransfer) -> None:
        """A main image from a Go peer (or a transcoding TPU peer)
        arrives in the reference container; rewrite it into the repo's
        own format (sessions included) so the ordinary recovery path
        reads it.  A TPU live stream (our container bytes, our magic)
        passes through untouched; witness transfers are never
        recovered from, so their image is left as received."""
        if t.first is not None and t.first.witness:
            return
        from dragonboat_tpu.rsm.gosnapshot import (
            go_image_to_native,
            sniff_v2_file,
        )

        if not sniff_v2_file(t.path):
            return                   # our own live stream: pass through
        with open(t.path, "rb") as f:
            data = f.read()
        native = go_image_to_native(data)
        tmp = t.path + ".transcode"
        with open(tmp, "wb") as f:
            f.write(native)
        os.replace(tmp, t.path)
        t.main_written = len(native)

    @staticmethod
    def _to_message(t: _GoTransfer) -> pb.Message:
        """chunk.go toMessage: rebuild the InstallSnapshot from the
        chunk fields, filepaths rewritten to the reassembled local
        files."""
        from dataclasses import replace

        c0 = t.first
        files = tuple(replace(sf, filepath=dst) for sf, dst in t.files)
        ss = pb.Snapshot(
            filepath=t.path,
            file_size=t.main_written,
            index=c0.index,
            term=c0.term,
            membership=c0.membership,
            files=files,
            shard_id=c0.shard_id,
            on_disk_index=c0.on_disk_index,
            witness=c0.witness,
        )
        # term stays 0 (chunk.go toMessage sets no Term): a zero-term
        # message bypasses the staleness gate (raft.go
        # onMessageTermNotMatched / pycore.py:858) — the snapshot's own
        # term rides in ss.term; the sender's message term never crossed
        # this wire
        return pb.Message(
            type=pb.MessageType.INSTALL_SNAPSHOT,
            to=c0.replica_id,
            from_=c0.from_,
            shard_id=c0.shard_id,
            snapshot=ss,
        )

    def _abort_locked(self, key) -> None:
        t = self.transfers.pop(key, None)
        if t is None:
            return
        for fh in (t.fh, t.cur_file_fh):
            if fh is not None:
                try:
                    fh.close()
                except OSError:
                    pass
        for p in [t.path] + [dst for _, dst in t.files]:
            try:
                os.remove(p)
            except OSError:
                pass

    def tick(self) -> None:
        with self.mu:
            stalled = []
            for key, t in self.transfers.items():
                t.idle_ticks += 1
                if t.idle_ticks >= GC_TICKS:
                    stalled.append(key)
            for key in stalled:
                self._abort_locked(key)

    def inflight(self) -> int:
        with self.mu:
            return len(self.transfers)


def adapt_native_chunks_to_go(chunks):
    """Adapt a NATIVE streamed chunk sequence (the on-disk SM live
    stream, rsm/chunkwriter.py — repo-container bytes cut into chunks)
    into reference-layout GoChunks carrying the REFERENCE container,
    transcoded in flight (rsm/gosnapshot.GoStreamTranscoder: sessions
    re-banked, user payload verbatim, reference blocks + tail) — a
    real Go receiver validates the blocks as they arrive, so the bytes
    must be reference-shaped on the wire, not just at rest.  Chunk
    numbering follows chunkwriter.go: mid chunks carry chunk_count=0,
    and a final EMPTY LastChunkCount chunk closes the stream.
    Already-adapted GoChunks pass through."""
    from dragonboat_tpu.raftpb import gowire
    from dragonboat_tpu.rsm.gosnapshot import GoStreamTranscoder

    meta = None
    first = None
    tr = None
    pending: list[bytes] = []
    chunk_id = 0

    def go_chunk(data: bytes, count: int):
        nonlocal chunk_id
        c0 = first
        ss = meta
        ck = gowire.GoChunk(
            shard_id=c0.shard_id, replica_id=c0.replica_id,
            from_=c0.from_, chunk_id=chunk_id, chunk_size=len(data),
            chunk_count=count, data=data, index=c0.index, term=c0.term,
            membership=ss.membership if ss is not None else pb.Membership(),
            filepath=f"snapshot-{c0.index:016X}.gbsnap",
            deployment_id=c0.deployment_id,
            file_chunk_id=chunk_id, file_chunk_count=count,
            on_disk_index=ss.on_disk_index if ss is not None else 0,
            witness=False,
        )
        chunk_id += 1
        return ck

    for c in chunks:
        if not isinstance(c, pb.Chunk):
            yield c
            continue
        if c.message is not None:
            meta = c.message.snapshot
        if first is None:
            first = c
            tr = GoStreamTranscoder(pending.append)
        tr.write(c.data)
        if c.is_last():
            tr.close()
        while pending:
            yield go_chunk(pending.pop(0), 0)
    if first is not None:
        # chunkwriter.go getTailChunk: an empty LastChunkCount chunk
        # closes the streamed transfer
        yield go_chunk(b"", gowire.LAST_CHUNK_COUNT)


def witness_image_bytes() -> bytes:
    """The witness chunk payload in the REFERENCE container format
    (rsm.GetWitnessSnapshot): the Go receiver runs its snapshot
    validator on every chunk-0 payload (chunk.go:214), so the image
    must be bytes that validator accepts — witness snapshots being
    partial (never recovered from) does not exempt them from the
    byte-level check."""
    from dragonboat_tpu.rsm.gosnapshot import witness_image

    return witness_image()
