"""Snapshot chunk streaming: split, reassemble, GC.

Parity with the reference's chunked snapshot transfer
(``internal/transport/snapshot.go:49,211-217`` sender split,
``chunk.go:106-194`` receiver ``Chunk.Add`` with per-transfer locks, a
concurrency cap and tick-based GC of stalled transfers).  The sender reads
the snapshot file and emits ``raftpb.Chunk`` records; the receiver
reassembles them into a local file and delivers the original
InstallSnapshot message (filepath rewritten) once the last chunk lands.
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass, field

from dragonboat_tpu import raftpb as pb

SNAPSHOT_CHUNK_SIZE = 2 * 1024 * 1024   # snapshot.go:49 snapshotChunkSize
MAX_CONCURRENT_STREAMS = 128            # chunk.go:42 MaxConcurrentStreaming
GC_TICKS = 30                           # stalled-transfer timeout in ticks


def split_snapshot_message(m: pb.Message, deployment_id: int,
                           chunk_size: int = SNAPSHOT_CHUNK_SIZE,
                           source_address: str = ""):
    """Yield Chunk records for an InstallSnapshot message
    (snapshot.go:211 SendSnapshot read-and-split).

    External snapshot files (rsm/files.go) ride the SAME chunk stream,
    concatenated after the container in ``ss.files`` order; the receiver
    splits them back out using the per-file sizes recorded on the
    snapshot (ChunkSink._split_external_files)."""
    ss = m.snapshot
    main_size = os.path.getsize(ss.filepath) if ss.filepath else 0
    file_size = main_size + sum(f.file_size for f in ss.files)
    count = max(1, (file_size + chunk_size - 1) // chunk_size)

    def byte_stream():
        paths = ([ss.filepath] if ss.filepath else []) + [
            f.filepath for f in ss.files]
        for p in paths:
            with open(p, "rb") as f:
                while True:
                    block = f.read(chunk_size)
                    if not block:
                        break
                    yield block

    class _concat:
        def __init__(self):
            self.gen = byte_stream()
            self.buf = b""

        def __enter__(self):
            return self

        def __exit__(self, *a):
            return False

        def read(self, n):
            while len(self.buf) < n:
                block = next(self.gen, None)
                if block is None:
                    break
                self.buf += block
            out, self.buf = self.buf[:n], self.buf[n:]
            return out

    with (_concat() if file_size else _null_file()) as f:
        for cid in range(count):
            data = f.read(chunk_size)
            yield pb.Chunk(
                shard_id=m.shard_id,
                replica_id=m.to,
                from_=m.from_,
                chunk_id=cid,
                chunk_count=count,
                chunk_size=len(data),
                file_size=file_size,
                index=ss.index,
                term=ss.term,
                deployment_id=deployment_id,
                source_address=source_address if cid == 0 else "",
                data=data,
                message=m if cid == 0 else None,
            )


class _null_file:
    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False

    def read(self, n):
        return b""


@dataclass
class _Transfer:
    message: pb.Message | None = None
    next_chunk: int = 0
    chunk_count: int = 0
    path: str = ""
    fh: object = None
    idle_ticks: int = 0
    validated: int = 0
    source_address: str = ""


class ChunkSink:
    """Receiver-side reassembly — parity chunk.go:106 (Chunk.Add)."""

    def __init__(self, snapshot_dir: str, deployment_id: int,
                 deliver, max_concurrent: int = MAX_CONCURRENT_STREAMS):
        """``deliver(message, source_address)`` is called with the rebuilt
        InstallSnapshot (filepath pointing at the reassembled local file)."""
        self.dir = snapshot_dir
        self.deployment_id = deployment_id
        self.deliver = deliver
        self.max_concurrent = max_concurrent
        self.mu = threading.Lock()
        self.transfers: dict[tuple[int, int, int], _Transfer] = {}

    def add(self, c: pb.Chunk) -> bool:
        """Returns False when the chunk is rejected (out of order, over the
        concurrency cap, wrong deployment)."""
        if c.deployment_id != self.deployment_id:
            return False
        key = (c.shard_id, c.replica_id, c.from_)
        completed = None
        with self.mu:
            t = self.transfers.get(key)
            if c.chunk_id == 0:
                if t is not None:
                    self._abort_locked(key)
                if len(self.transfers) >= self.max_concurrent:
                    return False
                if c.message is None:
                    return False
                os.makedirs(self.dir, exist_ok=True)
                path = os.path.join(
                    self.dir,
                    f"incoming-{c.shard_id:016X}-{c.replica_id:016X}"
                    f"-{c.index:016X}.gbsnap",
                )
                t = _Transfer(message=c.message, chunk_count=c.chunk_count,
                              path=path, fh=open(path, "wb"),
                              source_address=c.source_address)
                self.transfers[key] = t
            elif t is None or c.chunk_id != t.next_chunk:
                # out-of-order/stale chunk: drop the whole transfer
                if t is not None:
                    self._abort_locked(key)
                return False
            t.idle_ticks = 0
            t.fh.write(c.data)
            t.validated += len(c.data)
            t.next_chunk = c.chunk_id + 1
            # streamed transfers (chunkwriter.py) carry chunk_count=0 until
            # the tail chunk, whose count/file_size close the transfer
            if c.is_last():
                t.fh.close()
                if c.file_size and t.validated != c.file_size:
                    os.remove(t.path)
                    del self.transfers[key]
                    return False
                del self.transfers[key]
                completed = t
        if completed is not None:
            # deliver OUTSIDE the lock: dispatch recurses into the whole
            # nodehost message path and must not serialize other transfers
            m = completed.message
            from dataclasses import replace
            files = self._split_external_files(completed.path,
                                               m.snapshot.files)
            m = replace(m, snapshot=replace(m.snapshot,
                                            filepath=completed.path,
                                            files=files))
            self.deliver(m, completed.source_address)
        return True

    @staticmethod
    def _split_external_files(path: str, files):
        """The sender concatenated external snapshot files after the
        container (split_snapshot_message); carve them back out next to
        the reassembled file and truncate the container to its own bytes
        (chunk.go multi-file reassembly, compressed into one stream)."""
        if not files:
            return files
        from dataclasses import replace
        total = os.path.getsize(path)
        main_size = total - sum(f.file_size for f in files)
        out = []
        with open(path, "rb") as f:
            f.seek(main_size)
            for sf in files:
                dst = f"{path}.xf{sf.file_id}"
                remaining = sf.file_size
                with open(dst, "wb") as o:
                    while remaining:
                        block = f.read(min(remaining, 1 << 20))
                        if not block:
                            break
                        o.write(block)
                        remaining -= len(block)
                out.append(replace(sf, filepath=dst))
        with open(path, "r+b") as f:
            f.truncate(main_size)
        return tuple(out)

    def _abort_locked(self, key) -> None:
        t = self.transfers.pop(key, None)
        if t is not None and t.fh is not None:
            try:
                t.fh.close()
                os.remove(t.path)
            except OSError:
                pass

    def tick(self) -> None:
        """Advance the GC clock; drop stalled transfers (chunk.go GC)."""
        with self.mu:
            stalled = []
            for key, t in self.transfers.items():
                t.idle_ticks += 1
                if t.idle_ticks >= GC_TICKS:
                    stalled.append(key)
            for key in stalled:
                self._abort_locked(key)

    def inflight(self) -> int:
        with self.mu:
            return len(self.transfers)
