"""TransportHub: send queues, batching, circuit breakers over an ITransport.

Parity with ``internal/transport/transport.go:173`` (Transport): per-target
send queues drained into MessageBatch frames, a circuit breaker per address
(:176-177, :293), failure → unreachable callbacks funneled back to raft as
Unreachable messages, and snapshot chunk dispatch.
"""

from __future__ import annotations

import threading
import time
import zlib
from collections import deque
from random import Random
from typing import Callable

from dragonboat_tpu import fabric
from dragonboat_tpu import flight
from dragonboat_tpu import lifecycle
from dragonboat_tpu import raftpb as pb
from dragonboat_tpu.events import EventHub
from dragonboat_tpu.raftio import INodeRegistry, ITransport, SnapshotInfo

SEND_QUEUE_LEN = 1024 * 2
BREAKER_RESET_SECONDS = 1.0
BREAKER_MAX_RESET_SECONDS = 30.0
BREAKER_JITTER = 0.25


def _msg_size(m: pb.Message) -> int:
    """Approximate queued size (config.go MaxSendQueueSize accounting)."""
    return 64 + sum(pb.entry_size(e) for e in m.entries)


class CircuitBreaker:
    """Failure breaker with capped exponential backoff
    (transport.go GetCircuitBreaker).

    closed --fail()--> open --cooldown elapses--> half-open, where
    ``ready()`` returns True and the next outcome decides: ``succeed()``
    closes the breaker and resets the backoff; another ``fail()``
    re-opens it with a doubled cooldown, capped at ``max_reset``.  A
    fixed ``reset_after`` makes every breaker in a partitioned fleet
    retry in lockstep, hammering a recovering peer once a second —
    backoff spreads the probes out, and the jitter decorrelates
    breakers that tripped on the same tick.  The jitter is drawn from a
    per-breaker seeded PRNG, so a fault schedule replayed with the same
    seeds observes the same cooldowns (the chaos harness depends on
    this).

    ``now`` parameters exist for deterministic unit tests; production
    callers omit them and get the monotonic clock.
    """

    def __init__(self, reset_after: float = BREAKER_RESET_SECONDS,
                 max_reset: float = BREAKER_MAX_RESET_SECONDS,
                 seed: int = 0) -> None:
        self.base_reset = reset_after         # guarded-by: <init-only>
        self.max_reset = max_reset            # guarded-by: <init-only>
        self.reset_after = reset_after        # guarded-by: mu (current cooldown)
        self.tripped_at = 0.0                 # guarded-by: mu
        self.trip_streak = 0                  # guarded-by: mu
        self._rng = Random(seed)              # guarded-by: mu
        self.mu = threading.Lock()

    def ready(self, now: float | None = None) -> bool:
        if now is None:
            now = time.monotonic()
        with self.mu:
            return (now - self.tripped_at) >= self.reset_after

    def state(self, now: float | None = None) -> str:
        """closed | open | half-open — observability + test surface."""
        if now is None:
            now = time.monotonic()
        with self.mu:
            if self.trip_streak == 0:
                return "closed"
            if (now - self.tripped_at) >= self.reset_after:
                return "half-open"
            return "open"

    def fail(self, now: float | None = None) -> bool:
        """Record one failure; returns True when this failure OPENED a
        closed breaker (the closed->open edge, for trip accounting)."""
        if now is None:
            now = time.monotonic()
        with self.mu:
            self.trip_streak += 1
            opened = self.trip_streak == 1
            cooldown = self.base_reset * (2 ** min(self.trip_streak - 1, 30))
            cooldown *= 1.0 + BREAKER_JITTER * self._rng.random()
            self.reset_after = min(cooldown, self.max_reset)
            self.tripped_at = now
        return opened

    def succeed(self) -> None:
        with self.mu:
            self.trip_streak = 0
            self.reset_after = self.base_reset
            self.tripped_at = 0.0


class TransportHub:
    def __init__(
        self,
        source_address: str,
        deployment_id: int,
        transport: ITransport,
        resolver: INodeRegistry,
        unreachable_cb: Callable[[pb.Message], None],
        sync: bool = True,
        events=None,
        snapshot_send_bps: int = 0,
        max_send_queue_bytes: int = 0,
    ) -> None:
        self.snapshot_send_bps = snapshot_send_bps
        # MaxSendQueueSize (config.go): BYTES of queued messages per
        # target; 0 = unlimited. A full queue drops the NEW message and
        # reports it (rate-limited), never silently evicts older ones
        self.max_send_queue_bytes = max_send_queue_bytes
        # shared snapshot-bandwidth bucket: the bytes/s cap is per HOST,
        # so concurrent streams draw from one budget
        self._snap_mu = threading.Lock()
        self._snap_sent = 0                   # guarded-by: _snap_mu
        self._snap_start = 0.0                # guarded-by: _snap_mu
        self.source_address = source_address
        self.deployment_id = deployment_id
        self.transport = transport
        self.resolver = resolver
        self.unreachable_cb = unreachable_cb
        self.sync = sync
        self.events = events if events is not None else EventHub()
        self.mu = threading.Lock()
        self.queues: dict[str, deque[tuple[pb.Message, int]]] = {}  # guarded-by: mu
        self.queue_bytes: dict[str, int] = {}                       # guarded-by: mu
        self.breakers: dict[str, CircuitBreaker] = {}               # guarded-by: mu
        # (addr, snapshot) -> last observed connection state; edge-triggered
        # listener events fire only on state changes (and first observation)
        self.connected: dict[tuple[str, bool], bool] = {}           # guarded-by: mu
        # counters live in the shared process-wide registry (events.Metrics)
        self.metrics = self.events.metrics
        registry = getattr(self.metrics, "registry", None)
        if registry is not None:
            registry.gauge_fn(
                "transport.breakers", self._breaker_states,
                help="per-address circuit breakers by current state",
                labelnames=("state",))
        # per-link fabric telemetry: the meter folds this hub's queue
        # depths and breaker states into /debug/fabric (weakly held —
        # a closed hub just vanishes from the snapshot)
        fabric.METER.attach_hub(source_address, self)

    def _breaker_states(self) -> dict[tuple[str, ...], float]:
        """Callback-gauge source: breaker count per state.  Copies the
        breaker map under ``mu`` and evaluates ``b.state()`` (which takes
        each breaker's own lock) after releasing it — the scrape thread
        never holds two locks at once."""
        with self.mu:
            breakers = list(self.breakers.values())
        counts = {"closed": 0, "open": 0, "half-open": 0}
        for b in breakers:
            counts[b.state()] += 1
        return {(state,): float(n) for state, n in counts.items()}

    def _record_trip(self, addr: str) -> None:
        """closed->open edge accounting (called when ``fail()`` opened)."""
        self.metrics.inc("transport.breaker_trips")
        flight.record(flight.BREAKER_TRIP, addr=addr)

    def _note_connection(self, addr: str, ok: bool, snapshot: bool) -> None:
        """Edge-triggered ConnectionEstablished/Failed events, keyed per
        (addr, snapshot) connection class
        (transport.go SendMessageBatch → sysEvents, event.go:54-90)."""
        key = (addr, snapshot)
        with self.mu:
            prev = self.connected.get(key)
            self.connected[key] = ok
            fire = ok != prev  # first observation (prev None) always fires
        if not fire:
            return
        if ok:
            self.events.connection_established(addr, snapshot)
        else:
            self.events.connection_failed(addr, snapshot)

    def breaker(self, addr: str) -> CircuitBreaker:
        with self.mu:
            b = self.breakers.get(addr)
            if b is None:
                # per-addr deterministic jitter seed: replaying a fault
                # schedule sees identical cooldown sequences per peer
                b = self.breakers[addr] = CircuitBreaker(
                    seed=zlib.crc32(addr.encode()))
            return b

    def trip_breaker(self, addr: str, count: int = 1) -> CircuitBreaker:
        """Force ``count`` failures onto the breaker for ``addr`` — the
        chaos harness's forced-trip fault (monkey.go breaker kicks)."""
        b = self.breaker(addr)
        for _ in range(count):
            if b.fail():
                self._record_trip(addr)
        return b

    def send(self, m: pb.Message) -> bool:
        """Enqueue and (synchronously, in the loopback runtime) flush one
        message — Send (transport.go:115-136)."""
        if m.is_local():
            raise AssertionError("local message sent to transport")
        if m.type == pb.MessageType.INSTALL_SNAPSHOT:
            return self.send_snapshot(m)
        try:
            addr, _key = self.resolver.resolve(m.shard_id, m.to)
        except KeyError:
            self.metrics.inc("transport.dropped")
            return False
        b = self.breaker(addr)
        if not b.ready():
            self.metrics.inc("transport.dropped")
            self._notify_unreachable(m)
            return False
        sz = _msg_size(m)
        with self.mu:
            q = self.queues.setdefault(addr, deque())
            used = self.queue_bytes.get(addr, 0)
            if (self.max_send_queue_bytes
                    and used + sz > self.max_send_queue_bytes) \
                    or len(q) >= SEND_QUEUE_LEN:
                self.metrics.inc("transport.dropped")
                return False
            q.append((m, sz))
            self.queue_bytes[addr] = used + sz
        if self.sync:
            self.flush(addr)
        return True

    def flush(self, addr: str | None = None) -> None:
        addrs = [addr] if addr else list(self.queues)
        for a in addrs:
            with self.mu:
                q = self.queues.get(a)
                if not q:
                    continue
                msgs = tuple(m for m, _ in q)
                nbytes = sum(s for _, s in q)
                q.clear()
                self.queue_bytes[a] = 0
            # fabric trace header: sampled replicate keys + parked
            # quorum-ack returns ride the frame (None when empty, so
            # the bytes are identical to an old peer's frame)
            header = fabric.METER.header_for(self.source_address, a, msgs)
            batch = pb.MessageBatch(
                requests=msgs,
                deployment_id=self.deployment_id,
                source_address=self.source_address,
                fabric=header,
            )
            b = self.breaker(a)
            try:
                conn = self.transport.get_connection(a)
                conn.send_message_batch(batch)
                b.succeed()
                self.metrics.inc("transport.sent", len(msgs))
                # lifecycle sidecar: replicated entries left this host —
                # stamp the sampled spans (flush is transport-agnostic,
                # so hub_send covers chan AND tcp)
                if lifecycle.TRACER.enabled:
                    for m in msgs:
                        if m.type == pb.MessageType.REPLICATE:
                            for e in m.entries:
                                if e.key:
                                    lifecycle.TRACER.stamp(
                                        e.key, lifecycle.STAGE_HUB_SEND)
                fabric.METER.on_send(self.source_address, a, msgs,
                                     nbytes, header)
                self._note_connection(a, True, False)
            except Exception:
                if b.fail():
                    self._record_trip(a)
                self.metrics.inc("transport.send_failed", len(msgs))
                self._note_connection(a, False, False)
                for m in msgs:
                    self._notify_unreachable(m)

    def send_snapshot(self, m: pb.Message) -> bool:
        """Stream an InstallSnapshot in a background job — the reference
        runs snapshot sends in a dedicated job pool (snapshot.go:211,
        job.go:43-69); blocking the engine thread here would stall every
        shard's ticks for the duration of a transfer."""
        from dragonboat_tpu.transport.chunks import (
            split_snapshot_message,
            split_snapshot_message_go,
        )

        # the transport picks the chunk layout: go-wire fleets speak the
        # reference's per-file Chunk records (no embedded message);
        # everything else ships the native concatenated stream
        go_wire = getattr(self.transport, "wire", "native") == "go"

        def job() -> None:
            if go_wire:
                chunks = split_snapshot_message_go(m, self.deployment_id)
            else:
                chunks = split_snapshot_message(
                    m, self.deployment_id,
                    source_address=self.source_address)
            self.send_snapshot_chunks(m, chunks)

        threading.Thread(target=job, name="snapshot-stream",
                         daemon=True).start()
        return True

    def send_snapshot_chunks(self, m: pb.Message, chunks) -> bool:
        """Send an InstallSnapshot as a chunk stream (snapshot.go:211).
        On a go-wire transport, NATIVE chunks (the on-disk SM live
        stream, rsm/chunkwriter.py) are adapted to the reference layout
        per chunk — file-based sends arrive here already split by
        split_snapshot_message_go."""
        if getattr(self.transport, "wire", "native") == "go":
            from dragonboat_tpu.transport.chunks import (
                adapt_native_chunks_to_go,
            )

            chunks = adapt_native_chunks_to_go(chunks)
        try:
            addr, _ = self.resolver.resolve(m.shard_id, m.to)
        except KeyError:
            self._notify_snapshot_failed(m)
            return False
        b = self.breaker(addr)
        if not b.ready():
            self._notify_snapshot_failed(m)
            return False
        info = SnapshotInfo(shard_id=m.shard_id, replica_id=m.to,
                            from_=m.from_, index=m.snapshot.index,
                            term=m.snapshot.term)
        self.events.send_snapshot_started(info)
        try:
            conn = self.transport.get_snapshot_connection(addr)
            # MaxSnapshotSendBytesPerSecond (config.go): pace the stream so
            # a large transfer cannot saturate the links raft traffic uses
            bps = self.snapshot_send_bps
            for c in chunks:
                conn.send_chunk(c)
                fabric.METER.on_chunk_sent(
                    self.source_address, addr,
                    len(getattr(c, "data", b"")))
                if bps > 0:
                    self._pace_snapshot(len(getattr(c, "data", b"")), bps)
            b.succeed()
            self.metrics.inc("transport.snapshots_sent")
            self._note_connection(addr, True, True)
            self.events.send_snapshot_completed(info)
            return True
        except Exception:
            if b.fail():
                self._record_trip(addr)
            self._note_connection(addr, False, True)
            self.events.send_snapshot_aborted(info)
            self._notify_unreachable(m)
            self._notify_snapshot_failed(m)
            return False

    def _pace_snapshot(self, n: int, bps: int) -> None:
        """Shared host-wide pacing (MaxSnapshotSendBytesPerSecond is the
        NodeHost total): all streams draw from one budget.  The window
        resets after idle so old credit can't fund a burst."""
        while True:
            now = time.monotonic()
            with self._snap_mu:
                if now - self._snap_start > 5.0 + self._snap_sent / bps:
                    self._snap_start, self._snap_sent = now, 0
                if n:
                    self._snap_sent += n
                    n = 0
                ahead = self._snap_sent / bps - (now - self._snap_start)
            if ahead <= 0:
                return
            time.sleep(min(ahead, 1.0))

    def _notify_snapshot_failed(self, m: pb.Message) -> None:
        """Feed a rejected SnapshotStatus back to the sender's raft
        (transport failure → raft.go:1136 handleLeaderSnapshotStatus)."""
        self.unreachable_cb(
            pb.Message(
                type=pb.MessageType.SNAPSHOT_STATUS,
                from_=m.to,
                to=m.from_,
                shard_id=m.shard_id,
                reject=True,
            )
        )

    def _notify_unreachable(self, m: pb.Message) -> None:
        self.unreachable_cb(
            pb.Message(
                type=pb.MessageType.UNREACHABLE,
                from_=m.to,
                to=m.from_,
                shard_id=m.shard_id,
            )
        )
