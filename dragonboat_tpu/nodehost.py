"""NodeHost — the public façade of the framework.

Parity with the reference's ``nodehost.go``: one NodeHost per process (or
several, for in-process clusters over the chan transport) hosting many raft
shards; all client entry points (SyncPropose :576, SyncRead :600,
Propose :805, ReadIndex :840, StaleRead :894, RequestSnapshot :963,
membership changes :1038-1237, RequestLeaderTransfer :1238,
GetNodeHostInfo :1359) and the engine/tick machinery (:1824+).

The loopback engine steps nodes synchronously on an engine thread (the
reference's partitioned worker pools collapse to one executor here; the
batched TPU kernel executor replaces it for device-resident shards).
"""

from __future__ import annotations

import threading
import time
import uuid
from dataclasses import dataclass, field

from dragonboat_tpu import raftpb as pb
from dragonboat_tpu.client import Session
from dragonboat_tpu.config import Config, NodeHostConfig
from dragonboat_tpu.events import EventHub
from dragonboat_tpu.logdb.memdb import MemLogDB
from dragonboat_tpu.logdb.sharded import ShardedLogDB
from dragonboat_tpu.server.env import Env
from dragonboat_tpu.node import Node, _SnapshotRequest
from dragonboat_tpu.raftio import ILogDB, NodeInfo, SnapshotInfo
from dragonboat_tpu.registry import Registry
from dragonboat_tpu.request import (
    LogicalClock,
    RequestDroppedError,
    RequestError,
    RequestRejectedError,
    RequestState,
    RequestResultCode,
)
from dragonboat_tpu.rsm.statemachine import StateMachine
from dragonboat_tpu.statemachine import Result
from dragonboat_tpu import fabric
from dragonboat_tpu.transport.chan import ChanTransportFactory
from dragonboat_tpu.transport.chunks import ChunkSink
from dragonboat_tpu.transport.hub import TransportHub, _msg_size
from dragonboat_tpu.logger import get_logger

_LOG = get_logger("nodehost")

DEFAULT_TIMEOUT_S = 5.0


class ShardNotFoundError(RequestError):
    pass


class AdmissionRefusedError(RequestError):
    """StartReplica refused by the capacity admission controller
    (control.check_admission): the host is at or past its derated
    device-capacity watermark.  Carries the evidence row so callers can
    act on it (retry elsewhere, raise the budget, relax the policy)."""

    def __init__(self, shard_id: int, evidence: dict) -> None:
        super().__init__(
            f"shard {shard_id}: device admission refused "
            f"(occupied {evidence.get('occupied')} >= "
            f"limit {evidence.get('limit')})")
        self.shard_id = shard_id
        self.evidence = dict(evidence)


@dataclass
class ShardInfo:
    shard_id: int
    replica_id: int
    leader_id: int
    term: int
    is_leader: bool
    membership: pb.Membership
    last_applied: int


@dataclass
class NodeHostInfo:
    node_host_id: str
    raft_address: str
    shard_info_list: list[ShardInfo] = field(default_factory=list)


class NodeHost:
    # serializes the process-global threading.stack_size() window below
    _stack_size_mu = threading.Lock()

    def __init__(self, nhconfig: NodeHostConfig,
                 logdb: ILogDB | None = None,
                 auto_run: bool = True) -> None:
        nhconfig.validate()
        self.config = nhconfig
        from dragonboat_tpu.vfs import default_fs

        self.fs = (nhconfig.expert.fs if nhconfig.expert.fs is not None
                   else default_fs())
        # durable mode: with a NodeHostDir, the data dir is locked, the
        # flag file validated, identity persisted, and the tan log engine
        # is the default LogDB (nodehost.go NewNodeHost → server.NewEnv →
        # CreateNodeHostDir / LockNodeHostDir / CheckNodeHostDir)
        self.env: Env | None = None
        if nhconfig.node_host_dir:
            # NodeHostDir always drives env services (lock, flag file,
            # identity, snapshot placement) — a custom LogDB only swaps
            # the engine, as in the reference (config.LogDBFactory)
            self.env = Env(nhconfig.node_host_dir, nhconfig.raft_address,
                           nhconfig.deployment_id,
                           wal_dir=nhconfig.wal_dir, fs=self.fs)
            self.env.lock()
            try:
                custom = logdb is not None or nhconfig.logdb_factory is not None
                if logdb is not None:
                    self.logdb: ILogDB = logdb
                    self.env.check_node_host_dir(self.logdb.name())
                elif nhconfig.logdb_factory is not None:
                    self.logdb = nhconfig.logdb_factory.create()  # type: ignore[union-attr]
                    self.env.check_node_host_dir(self.logdb.name())
                else:
                    # validate the dir BEFORE tan touches the wal root so a
                    # refused reopen leaves no stray log files behind;
                    # legacy flat-"tan" dirs migrate in place and get the
                    # flag bumped so a rolled-back binary refuses them
                    # instead of seeing an empty log
                    engine = nhconfig.expert.logdb.engine
                    self.env.check_node_host_dir(
                        f"sharded-{engine}",
                        compatible=("tan",) if engine == "tan" else ())
                    self.logdb = ShardedLogDB(
                        self.env.logdb_dir,
                        num_shards=nhconfig.expert.logdb.shards,
                        fs=self.fs, engine=engine,
                        recovery_mode=nhconfig.expert.logdb.recovery_mode)
                self.id = self.env.node_host_id()
            except Exception:
                db = getattr(self, "logdb", None)
                if db is not None and db is not logdb:
                    db.close()
                self.env.close()
                raise
        else:
            self.id = f"nhid-{uuid.uuid4()}"
            self.logdb = logdb if logdb is not None else (
                nhconfig.logdb_factory.create()  # type: ignore[union-attr]
                if nhconfig.logdb_factory else MemLogDB()
            )
        if nhconfig.address_by_node_host_id:
            # dynamic addressing: targets are NodeHostIDs, resolved through
            # the gossip view (registry/gossip.go:99)
            from dragonboat_tpu.gossip import GossipManager, GossipRegistry

            self.registry = GossipRegistry(GossipManager(
                self.id, nhconfig.raft_address,
                nhconfig.gossip.bind_address,
                nhconfig.gossip.advertise_address,
                list(nhconfig.gossip.seed),
                shard_info_fn=self._local_shard_views,
            ))
        else:
            self.registry = Registry()
        self.events = EventHub(
            raft_listener=nhconfig.raft_event_listener,
            system_listener=nhconfig.system_event_listener,
        )
        self.mu = threading.RLock()
        self.nodes: dict[int, Node] = {}
        # merged fleet telemetry view: host-resident replicas recounted
        # at scrape time + the engines' decimated device reductions
        # (core/fleet.py).  Registered BEFORE any engine exists, so the
        # engines' standalone device-only registration no-ops on this
        # registry and the merged view owns the family names
        from dragonboat_tpu.core import fleet as _fleet

        _fleet.register_exposition(self.events.metrics.registry,
                                   self._fleet_snapshot, replace=True)
        # merged anomaly-health view (core/health.py), same ownership
        # protocol: the host's merged snapshot claims the family names
        # before any engine's device-only registration can
        from dragonboat_tpu.core import health as _health

        _health.register_exposition(self.events.metrics.registry,
                                    self._health_snapshot, replace=True)
        # merged protocol-invariant view (core/invariants.py), same
        # ownership protocol.  Host-resident replicas contribute nothing
        # (the probe is a device reduction); the merged view exists so a
        # violation on EITHER engine degrades this host's /healthz
        from dragonboat_tpu.core import invariants as _invariants

        _invariants.register_exposition(self.events.metrics.registry,
                                        self._invariants_snapshot,
                                        replace=True)
        # merged capacity view (capacity.py), same ownership protocol
        from dragonboat_tpu import capacity as _capacity

        _capacity.register_exposition(self.events.metrics.registry,
                                      self._capacity_snapshot, replace=True)
        # a directly-injected ILogDB object cannot be reopened by
        # restart() (no recipe to rebuild it); factories can
        self._injected_logdb = logdb is not None
        # start_replica arguments per shard, so restart() can rebuild
        # every replica from disk after a controlled crash
        self._replica_specs: dict[int, tuple] = {}        # guarded-by: mu
        # ONE logical clock for every node's request books — advanced
        # once per tick round by the ticker (absolute deadline stamps;
        # the per-lane per-book advance walk was the 100k election
        # pump's dominant cost, PERF.md)
        self.logical_clock = LogicalClock()
        self._tick_round_no = 0
        self.chunk_sink = ChunkSink(
            snapshot_dir=f"/tmp/dragonboat_tpu/{self.id}/incoming",
            deployment_id=nhconfig.deployment_id,
            deliver=self._on_snapshot_reassembled,
        )
        factory = nhconfig.transport_factory or ChanTransportFactory()
        self.transport = factory.create(
            nhconfig, self._handle_message_batch, self.chunk_sink.add)
        self.transport.start()
        self.hub = TransportHub(
            source_address=nhconfig.raft_address,
            deployment_id=nhconfig.deployment_id,
            transport=self.transport,
            resolver=self.registry,
            unreachable_cb=self._on_unreachable,
            events=self.events,
            snapshot_send_bps=nhconfig.max_snapshot_send_bytes_per_second,
            max_send_queue_bytes=nhconfig.max_send_queue_size,
        )
        self._stopped = False
        # a storage-layer failure is a controlled crash (the reference arms
        # an engine crash channel for injected FS errors, nodehost.go:361):
        # the host stops accepting work and records the fault for the
        # operator; restart from disk is the recovery path
        self.fatal_error: Exception | None = None
        # monkey-test partition flag (monkey.go:170 PartitionNode)
        self._partitioned = False
        self._work = threading.Event()
        self._engine_thread: threading.Thread | None = None
        self._tick_interval = nhconfig.rtt_millisecond / 1000.0
        # the batched device engine, created on the first device-resident
        # shard (engine/kernel_engine.py)
        self.kernel_engine = None
        # the shared multi-chip engine, attached on the first
        # mesh-resident shard (engine/mesh_engine.py)
        self.mesh_engine = None
        # elastic fleet controller (control.py): consumes each decimated
        # health observation on the engine ticker thread (_control_round)
        # and plans rate-limited, hysteresis-guarded leader transfers off
        # this host.  Single-owner state: only the ticker touches it
        from dragonboat_tpu import control as _control

        _ex = nhconfig.expert
        self._controller = _control.FleetController(_control.ControlPolicy(
            enabled=_ex.control_enabled,
            hot_score=_ex.control_hot_score,
            lag_hot=_ex.control_lag_hot,
            hysteresis=_ex.control_hysteresis,
            cooldown_obs=_ex.control_cooldown_obs,
            max_transfers=_ex.control_max_transfers,
            seed=_ex.control_seed,
            warmup_obs=_ex.control_warmup_obs))
        self._ctrl_seen_seq = 0   # engine health observations consumed
        # partitioned step workers (engine.go:1107 workerPool: shards hash
        # onto fixed workers so each node is stepped by exactly one
        # thread; the sharded LogDB gives each partition its own active
        # file + lock, so different workers' fsyncs genuinely overlap —
        # logdb/sharded.py, parity internal/logdb/sharded.go:34)
        import os as _os

        self._num_workers = max(1, min(
            nhconfig.expert.engine.exec_shards, _os.cpu_count() or 1, 8))
        self._worker_events = [threading.Event()
                               for _ in range(self._num_workers)]
        self._workers: list[threading.Thread] = []
        # dedicated RSM-apply workers (engine.go:1153 applyWorkerMain): a
        # slow user SM occupies one of these, never a step worker
        from dragonboat_tpu.engine.apply_pool import ApplyPool

        # NOT capped by cpu_count: apply workers exist to absorb BLOCKED
        # user SMs (the reference runs a fixed 16 regardless of cores)
        self._apply_pool = ApplyPool(
            num_workers=max(1, min(nhconfig.expert.engine.apply_shards, 16)),
            on_work_done=self._work.set, name=f"apply-{self.id[:8]}")
        # proposal-lifecycle tracing (lifecycle.py): re-point the
        # process-global tracer at this host's expert knobs — the tracer
        # is process-wide (like flight.RECORDER) so spans stay whole
        # when a proposal crosses hosts over the in-proc transport
        from dragonboat_tpu import lifecycle as _lifecycle

        _lifecycle.TRACER.configure(
            sample_every=nhconfig.expert.trace_sample_every,
            slow_commit_us=nhconfig.expert.trace_slow_commit_us)
        # fabric link telemetry + hop census (fabric.py): the meter is
        # process-wide for the same reason the tracer is — links span
        # hosts, so one registry must see both ends
        fabric.METER.configure(enabled=nhconfig.expert.fabric_telemetry)
        # opt-in persistent jit compile cache (hostenv): geometry sweeps
        # and restarts stop paying full recompiles
        if nhconfig.expert.compile_cache:
            from dragonboat_tpu import hostenv as _hostenv

            cache_dir = _hostenv.enable_compile_cache()
            if cache_dir:
                _LOG.info("NodeHost %s: persistent jax compile cache at %s",
                          nhconfig.raft_address, cache_dir)
        # opt-in Prometheus /metrics endpoint (enable_metrics): serves
        # this host's registry + the process-global one (module-scoped
        # producers like the logdb latency histograms live there)
        self._metrics_server = None
        if nhconfig.enable_metrics:
            from dragonboat_tpu.server.metrics_http import MetricsServer
            from dragonboat_tpu.telemetry import GLOBAL

            self._metrics_server = MetricsServer(
                [self.events.metrics.registry, GLOBAL],
                address=nhconfig.metrics_address or "127.0.0.1:0",
                health_source=self._health_snapshot,
                info_source=self.info,
                shard_info_source=self._shard_info_or_none,
                capacity_source=self._capacity_snapshot,
                invariants_source=self._invariants_snapshot,
                fabric_source=fabric.METER.snapshot,
                fabric_trace_source=fabric.METER.chrome_events)
            _LOG.info("NodeHost %s metrics endpoint on %s",
                      nhconfig.raft_address, self._metrics_server.address)
        self._auto_run = auto_run
        if auto_run:
            self._start_engine_threads()

    @property
    def metrics_address(self) -> str | None:
        """The bound host:port of the /metrics endpoint (None when
        enable_metrics is off)."""
        return (self._metrics_server.address
                if self._metrics_server is not None else None)

    def _fleet_snapshot(self) -> dict:
        """Scrape-time fleet view: the engines' cached device reductions
        merged with a host-side recount of host-resident replicas (a
        plain 3-replica cluster has no device state to reduce, but
        /metrics must still answer role/leaderless/lag questions)."""
        from dragonboat_tpu.core import fleet as _fleet

        base = _fleet.empty_dict()
        for eng in (self.kernel_engine, self.mesh_engine):
            d = getattr(eng, "last_fleet", None)
            if d:
                _fleet.merge_into(base, d)
        with self.mu:
            nodes = list(self.nodes.values())
        for n in nodes:
            if getattr(n, "engine", None) is not None:
                continue        # device-resident: covered by the reduction
            try:
                raft = n.peer.raft if n.peer is not None else None
                if raft is None:
                    _fleet.add_host_shard(base, "follower", False, 0, 0)
                    continue
                lag = max(0, int(raft.log.committed)
                          - int(raft.log.processed))
                _fleet.add_host_shard(
                    base, raft.state.name.lower(),
                    int(raft.leader_id) == 0, int(raft.term), lag)
            except Exception:
                # a replica being torn down mid-scrape still counts
                _fleet.add_host_shard(base, "follower", False, 0, 0)
        return base

    def _health_snapshot(self) -> dict:
        """Scrape-time anomaly view: the engines' cached O(K) device
        reports merged (offenders tagged by engine) with a host-side
        recount of host-resident replicas.  The anomaly-class detectors
        are device-side only, so host replicas contribute just the
        instantaneous leaderless count — the single source of truth the
        chaos convergence oracle reads."""
        from dragonboat_tpu.core import health as _health

        base = _health.empty_dict()
        for name, eng in (("kernel", self.kernel_engine),
                          ("mesh", self.mesh_engine)):
            d = getattr(eng, "last_health", None)
            if d:
                _health.merge_into(base, d, engine=name)
        with self.mu:
            nodes = list(self.nodes.values())
        for n in nodes:
            if getattr(n, "engine", None) is not None:
                continue        # device-resident: covered by the report
            try:
                if int(n.leader_id()) == 0:
                    base["leaderless_now"] += 1
            except Exception:
                base["leaderless_now"] += 1   # torn down mid-scrape
        return base

    def _invariants_snapshot(self) -> dict:
        """Scrape-time protocol-invariant view: the engines' cached O(1)
        probe reports merged (first offender tagged by engine).  The
        probe is device-side only — host-resident replicas contribute
        nothing.  A nonzero ``violations_seen`` is sticky for each
        engine's lifetime: /healthz stays degraded after a transient
        step-scope violation (it is a bug either way)."""
        from dragonboat_tpu.core import invariants as _invariants

        base = _invariants.empty_dict()
        base["violations_seen"] = 0
        for name, eng in (("kernel", self.kernel_engine),
                          ("mesh", self.mesh_engine)):
            d = getattr(eng, "last_invariants", None)
            if d:
                _invariants.merge_into(base, d, engine=name)
                base["violations_seen"] += d.get("violations_seen", 0)
        return base

    def _capacity_snapshot(self) -> dict:
        """Scrape-time capacity view: the engines' cached decimated
        capacity snapshots merged, compile entries tagged by engine.
        Host-resident replicas hold no device state — only the engines
        contribute."""
        from dragonboat_tpu import capacity as _capacity

        base = _capacity.empty_dict()
        for name, eng in (("kernel", self.kernel_engine),
                          ("mesh", self.mesh_engine)):
            d = getattr(eng, "last_capacity", None)
            if d:
                _capacity.merge_into(base, d, engine=name)
        return base

    def _start_engine_threads(self) -> None:
        """Spawn the engine ticker + step workers (also from restart()).

        Worker threads jit-compile the step kernel on their first
        engine iteration; XLA's compile recursion on large graphs
        overflows the default pthread stack (observed as a segfault
        inside backend_compile in exec-0 threads, 2026-07-31), so
        engine threads get a deep stack.  stack_size() is process-
        global for threads created while set — the class lock keeps
        concurrent NodeHost constructions from racing the window."""
        with NodeHost._stack_size_mu:
            prev_stack = threading.stack_size()
            try:
                threading.stack_size(64 << 20)
            except (ValueError, RuntimeError):
                prev_stack = None
            try:
                self._engine_thread = threading.Thread(
                    target=self._engine_main,
                    name=f"engine-{self.id[:12]}", daemon=True)
                self._engine_thread.start()
                for w in range(self._num_workers):
                    t = threading.Thread(
                        target=self._worker_main, args=(w,),
                        name=f"exec-{w}-{self.id[:8]}", daemon=True)
                    t.start()
                    self._workers.append(t)
            finally:
                if prev_stack is not None:
                    threading.stack_size(prev_stack)

    # -- lifecycle ------------------------------------------------------

    def close(self) -> None:
        self.events.node_host_shutting_down()
        with self.mu:
            self._stopped = True
            nodes = list(self.nodes.values())
            self.nodes.clear()
        if self.mesh_engine is not None:
            from dragonboat_tpu.engine.mesh_engine import detach_mesh_engine

            for n in nodes:
                if getattr(n, "engine", None) is self.mesh_engine:
                    self.mesh_engine.remove_replica(n)
            detach_mesh_engine(self.mesh_engine)
            self.mesh_engine = None
        self._work.set()
        for ev in self._worker_events:
            ev.set()
        if self._engine_thread is not None:
            self._engine_thread.join(timeout=5)
        for t in self._workers:
            t.join(timeout=5)
        # drain in-flight applies before destroying SMs: sm.close() must
        # not run concurrently with its own update()
        for n in nodes:
            if not self._apply_pool.flush(n.shard_id, timeout=5):
                _LOG.warning("shard %d: apply still running at close",
                             n.shard_id)
        self._apply_pool.stop()
        for n in nodes:
            n.destroy()
            self.events.node_unloaded(NodeInfo(n.shard_id, n.replica_id))
        if self.kernel_engine is not None:
            # flushes a DRAGONBOAT_TPU_TRACE_DIR-armed profiler capture
            # while the backend is still alive (atexit-only flush races
            # interpreter shutdown)
            self.kernel_engine.close()
        if self._metrics_server is not None:
            self._metrics_server.close()
            self._metrics_server = None
        self.transport.close()
        try:
            self.logdb.close()
        except OSError:
            # a storage fault mid-shutdown must not abort the close: the
            # fsync that failed was already surfaced as fatal_error
            _LOG.exception("logdb close failed")
        self.events.close()
        close_registry = getattr(self.registry, "close", None)
        if close_registry is not None:
            close_registry()
        if self.env is not None:
            self.env.close()

    def restart(self, timeout_s: float = 5.0) -> None:
        """Recover IN PLACE from a controlled storage crash: reopen the
        log engine from the data dir and rebuild every replica that was
        running when ``_on_fatal`` halted the host.

        The reference's ErrorFS crash arming panics the process and the
        operator restarts it (nodehost.go:361-367) — a library host
        cannot exec itself, so this is that operator restart: same
        process, same Env lock, fresh LogDB + Nodes from what reached
        stable storage.  Acks sent after the failed fsync were never
        acted on (the host halted immediately), so replaying the disk
        state is exactly the durable prefix."""
        with self.mu:
            if not self._stopped:
                raise RequestError("restart requires a stopped host")
            if self._injected_logdb:
                raise RequestError(
                    "cannot restart: the injected LogDB object has no "
                    "reopen recipe (use a logdb_factory)")
            if self.config.logdb_factory is None and self.env is None:
                raise RequestError(
                    "cannot restart: no durable data dir to recover from")
            nodes = list(self.nodes.values())
            self.nodes.clear()
            specs = sorted(self._replica_specs.items())
            self._replica_specs.clear()
        if self.mesh_engine is not None:
            from dragonboat_tpu.engine.mesh_engine import detach_mesh_engine

            for n in nodes:
                if getattr(n, "engine", None) is self.mesh_engine:
                    self.mesh_engine.remove_replica(n)
            detach_mesh_engine(self.mesh_engine)
            self.mesh_engine = None
        self.kernel_engine = None
        self._work.set()
        for ev in self._worker_events:
            ev.set()
        if self._engine_thread is not None:
            self._engine_thread.join(timeout=timeout_s)
        for t in self._workers:
            t.join(timeout=timeout_s)
        self._workers = []
        self._engine_thread = None
        for n in nodes:
            self._apply_pool.flush(n.shard_id, timeout=timeout_s)
            n.destroy()
            self.events.node_unloaded(NodeInfo(n.shard_id, n.replica_id))
        try:
            self.logdb.close()
        except OSError:
            # the engine that failed its fsync may fail the closing one
            # too; the reopen below rereads whatever IS durable
            _LOG.exception("logdb close failed during restart")
        if self.config.logdb_factory is not None:
            self.logdb = self.config.logdb_factory.create()
        else:
            self.logdb = ShardedLogDB(
                self.env.logdb_dir,
                num_shards=self.config.expert.logdb.shards,
                fs=self.fs, engine=self.config.expert.logdb.engine,
                recovery_mode=self.config.expert.logdb.recovery_mode)
        with self.mu:
            self.fatal_error = None
            self._stopped = False
        if self._auto_run:
            self._start_engine_threads()
        for _sid, (members, join, create_sm, cfg) in specs:
            self.start_replica(members, join, create_sm, cfg)
        _LOG.info("NodeHost %s restarted with %d replica(s)",
                  self.id, len(specs))

    def simulate_kill(self) -> None:
        """Chaos surface: die like a killed process — stop every thread
        and drop every in-memory structure WITHOUT the orderly close's
        final log fsync or Env unlock.  What survives is exactly what
        reached stable storage; on a shared MemFS the companion call is
        ``fs.crash(prefix)``, which also reverts unsynced bytes and
        releases the dead process's file locks."""
        with self.mu:
            self._stopped = True
            if self.fatal_error is None:
                self.fatal_error = RequestError("simulated process kill")
            nodes = list(self.nodes.values())
            self.nodes.clear()
            self._replica_specs.clear()
        if self.mesh_engine is not None:
            from dragonboat_tpu.engine.mesh_engine import detach_mesh_engine

            for n in nodes:
                if getattr(n, "engine", None) is self.mesh_engine:
                    self.mesh_engine.remove_replica(n)
            detach_mesh_engine(self.mesh_engine)
            self.mesh_engine = None
        self._work.set()
        for ev in self._worker_events:
            ev.set()
        if self._engine_thread is not None:
            self._engine_thread.join(timeout=5)
        for t in self._workers:
            t.join(timeout=5)
        # brief drain so sm.close() cannot race an in-flight update()
        # on these in-process threads (a real kill has no such race)
        for n in nodes:
            self._apply_pool.flush(n.shard_id, timeout=1)
        self._apply_pool.stop()
        for n in nodes:
            n.destroy()
        if self._metrics_server is not None:
            self._metrics_server.close()
            self._metrics_server = None
        self.transport.close()
        self.events.close()
        close_registry = getattr(self.registry, "close", None)
        if close_registry is not None:
            close_registry()
        # deliberately NOT closed: self.logdb (its close() fsyncs — a
        # dead process never runs it) and self.env (the kernel releases
        # a dead process's flocks; MemFS.crash models that)

    def start_replica(self, initial_members: dict[int, str], join: bool,
                      create_sm, cfg: Config) -> None:
        """StartReplica (nodehost.go:499) for a regular/concurrent SM
        factory ``create_sm(shard_id, replica_id)``."""
        cfg.validate()
        self._admit_replica(cfg)
        with self.mu:
            if cfg.shard_id in self.nodes:
                raise RequestError("shard already started")
            # bootstrap-record check (startShard, nodehost.go:1526)
            bootstrap = self.logdb.get_bootstrap_info(
                cfg.shard_id, cfg.replica_id)
            new_node = bootstrap is None
            if new_node:
                self.logdb.save_bootstrap_info(
                    cfg.shard_id, cfg.replica_id,
                    pb.Bootstrap(addresses=dict(initial_members), join=join),
                )
            elif bootstrap.addresses and initial_members and not join:
                if bootstrap.addresses != initial_members:
                    raise RequestError("initial members mismatch")
            user_sm = create_sm(cfg.shard_id, cfg.replica_id)
            sm = StateMachine(cfg.shard_id, cfg.replica_id, user_sm,
                              cfg.ordered_config_change,
                              cfg.snapshot_compression, fs=self.fs)
            snapshot_dir = (
                self.env.snapshot_dir(cfg.shard_id, cfg.replica_id)
                if self.env is not None
                else f"/tmp/dragonboat_tpu/{self.id}/snapshots"
            )
            mesh = (cfg.mesh_resident and not cfg.is_witness
                    and self.config.expert.mesh is not None)
            device = cfg.device_resident and not cfg.is_witness and not mesh
            node_cls = Node
            if device or mesh:
                from dragonboat_tpu.engine.kernel_engine import KernelNode

                node_cls = KernelNode
            node = node_cls(cfg, self.logdb, sm, self._send_message,
                            snapshot_dir, events=self.events, fs=self.fs,
                            worker_id=cfg.shard_id % self._num_workers,
                            clock=self.logical_clock)
            node.membership_changed_cb = (
                lambda cc, sid=cfg.shard_id: self._on_membership_change(sid, cc)
            )
            node.stream_snapshot_cb = self._stream_snapshot
            node.notify_commit = self.config.notify_commit
            node.apply_pool = self._apply_pool
            members = initial_members if not join else {}
            node.start(members, initial=not join, new_node=new_node)
            for rid, addr in (members or {}).items():
                self.registry.add(cfg.shard_id, rid, addr)
            # when re-starting, membership from the RSM rebuilds the registry
            m = sm.get_membership()
            for rid, addr in {**m.addresses, **m.non_votings, **m.witnesses}.items():
                self.registry.add(cfg.shard_id, rid, addr)
            self.nodes[cfg.shard_id] = node
            self._replica_specs[cfg.shard_id] = (
                dict(initial_members), join, create_sm, cfg)
        if mesh:
            self._inject_mesh_shard(node, members)
        elif device:
            # outside self.mu: the engine lock orders engine.mu -> host.mu
            # on the eviction path, so injection must not hold host.mu
            self._inject_kernel_shard(node, members)
        self.events.node_ready(NodeInfo(cfg.shard_id, cfg.replica_id))
        self._work.set()

    def stop_replica(self, shard_id: int) -> None:
        with self.mu:
            node = self.nodes.pop(shard_id, None)
            self._replica_specs.pop(shard_id, None)
        if node is None:
            raise ShardNotFoundError(f"shard {shard_id} not found")
        if self.mesh_engine is not None and getattr(
                node, "engine", None) is self.mesh_engine:
            self.mesh_engine.remove_replica(node)
        elif self.kernel_engine is not None:
            self.kernel_engine.remove_shard(shard_id)
        self._apply_pool.flush(shard_id)
        node.destroy()
        self.events.node_unloaded(NodeInfo(shard_id, node.replica_id))

    # -- kernel engine glue ----------------------------------------------

    def _admit_replica(self, cfg: Config) -> None:
        """Capacity-driven admission (control.check_admission): a
        device-resident StartReplica past the derated capacity watermark
        is refused under policy "enforce", recorded-but-admitted under
        "warn".  The limit is max_g_for_budget over the explicit device
        budget (else the backend-reported bytes_limit) derated by the
        headroom watermark; with no resolvable budget the gate never
        refuses — capacity unknown is not capacity exhausted."""
        from dragonboat_tpu import capacity as _capacity
        from dragonboat_tpu import control as _control
        from dragonboat_tpu import flight as _flight

        ex = self.config.expert
        mode = ex.admission_policy
        if mode not in (_control.ADMISSION_ENFORCE, _control.ADMISSION_WARN):
            return
        mesh = (cfg.mesh_resident and not cfg.is_witness
                and ex.mesh is not None)
        if not (cfg.device_resident and not cfg.is_witness and not mesh):
            return
        self.events.metrics.inc("control_admission_total")
        budget = ex.capacity_device_budget_bytes
        if budget <= 0:
            budget = max((r["bytes_limit"]
                          for r in _capacity.device_memory_stats()),
                         default=0)
        limit = _control.admission_limit(
            self._kernel_params(), budget, ex.capacity_watermark_pct,
            _capacity.max_g_for_budget)
        with self.mu:
            occupied = sum(
                1 for n in self.nodes.values()
                if getattr(n, "engine", None) is not None
                and getattr(n, "lane", -1) >= 0)
        d = _control.check_admission(cfg.shard_id, occupied, limit,
                                     mode=mode)
        if d is None:
            return
        self.events.metrics.inc("control_admission_refused")
        _flight.record(_flight.ADMISSION_REFUSED,
                       tick=self._tick_round_no, shard_id=d.shard_id,
                       mode=mode, evidence=d.evidence)
        if mode == _control.ADMISSION_ENFORCE:
            raise AdmissionRefusedError(cfg.shard_id, d.evidence)
        _LOG.warning("shard %d: admission watermark exceeded (%s) — "
                     "admitted under policy 'warn'",
                     cfg.shard_id, d.evidence)

    def _inject_kernel_shard(self, node, members: dict[int, str]) -> None:
        """Move a freshly-bootstrapped shard onto the device kernel: the
        pycore Peer built by node.start() provides the persisted state;
        its in-memory tail (bootstrap config changes) rides along."""
        from dragonboat_tpu.core import params as KP
        from dragonboat_tpu.engine.kernel_engine import (
            KernelEngine,
            _LaneInit,
        )

        if self.kernel_engine is None:
            ex = self.config.expert
            self.kernel_engine = KernelEngine(
                self._kernel_params(), ex.kernel_capacity,
                self._send_message, events=self.events,
                fleet_stats_every=ex.fleet_stats_every,
                pipeline_depth=ex.kernel_pipeline_depth,
                health_top_k=ex.health_top_k,
                health_thresholds=self._health_thresholds(),
                invariant_probe=ex.invariant_probe,
                capacity_watermark_pct=ex.capacity_watermark_pct,
                capacity_budget_bytes=ex.capacity_device_budget_bytes)
            self.kernel_engine.on_evict = self._on_kernel_evict
        init = self._build_lane_init(node, members)
        self._inject_into_engine(self.kernel_engine, node, init,
                                 "device-resident")

    def _health_thresholds(self):
        from dragonboat_tpu.core import health as _health

        ex = self.config.expert
        return _health.HealthThresholds(
            leaderless_ticks=ex.health_leaderless_ticks,
            stall_ticks=ex.health_stall_ticks,
            lag_ticks=ex.health_lag_ticks,
            churn_trip=ex.health_churn_trip,
            runaway_ticks=ex.health_runaway_ticks)

    def _kernel_params(self, min_inbox: int = 0):
        import jax

        from dragonboat_tpu.core import params as KP

        ex = self.config.expert
        return KP.KernelParams(
            num_peers=ex.kernel_num_peers,
            log_cap=ex.kernel_log_cap,
            inbox_cap=max(ex.kernel_inbox_cap, min_inbox),
            msg_entries=ex.kernel_msg_entries,
            proposal_cap=ex.kernel_proposal_cap,
            readindex_cap=ex.kernel_readindex_cap,
            apply_batch=ex.kernel_apply_batch,
            compaction_overhead=ex.kernel_compaction_overhead,
            # platform-tuned read lowering (params.py onehot_reads): the
            # one-hot form wins on device, dynamic indexing wins on CPU
            onehot_reads=(jax.default_backend() != "cpu"),
        )

    def _build_lane_init(self, node, members: dict[int, str]):
        """Capture persisted state from the bootstrapped pycore Peer and
        make it durable BEFORE a device engine takes over (the lane is
        injected with stable == last; idempotent on restart)."""
        from dragonboat_tpu.core import params as KP
        from dragonboat_tpu.engine.kernel_engine import _LaneInit

        raft = node.peer.raft
        log = raft.log
        first, last = log.first_index(), log.last_index()
        entries = log.get_entries(first, last + 1) if last >= first else []
        ss = self.logdb.get_snapshot(node.shard_id, node.replica_id)
        m = node.sm.get_membership()
        peers = ([(rid, KP.K_VOTER) for rid in sorted(m.addresses)]
                 + [(rid, KP.K_NON_VOTING) for rid in sorted(m.non_votings)]
                 + [(rid, KP.K_WITNESS) for rid in sorted(m.witnesses)])
        if not peers:
            peers = [(rid, KP.K_VOTER) for rid in sorted(members)]
        init = _LaneInit(
            term=raft.term, vote=raft.vote, committed=log.committed,
            applied=node.sm.get_last_applied(),
            snap_index=ss.index if ss is not None else 0,
            snap_term=ss.term if ss is not None else 0,
            entries=entries, peers=peers,
        )
        self.logdb.save_raft_state([pb.Update(
            shard_id=node.shard_id, replica_id=node.replica_id,
            state=pb.State(term=raft.term, vote=raft.vote,
                           commit=log.committed),
            entries_to_save=tuple(entries),
        )], worker_id=0)
        return init

    def _fallback_host_side(self, node, kind: str, err) -> None:
        """Run a shard host-side rather than leaving a dead device shard
        registered (its bootstrap state is already durable)."""
        node.peer = None
        self._on_kernel_evict(node, [])
        import logging

        logging.getLogger("dragonboat_tpu.nodehost").warning(
            "shard %d: not %s (%s); running host-side",
            node.shard_id, kind, err)

    def _inject_into_engine(self, engine, node, init, kind: str) -> None:
        try:
            if len(init.entries) > engine.kp.log_cap:
                raise RequestError(
                    "log tail larger than the kernel ring")
            if len(init.peers) > engine.kp.num_peers:
                raise RequestError(
                    "membership larger than the kernel peer book")
            node.peer = None  # the lane owns the protocol state now
            node.on_evict_cb = self._on_kernel_evict
            engine.add_shard(node, init)
        except Exception as e:
            self._fallback_host_side(node, kind, e)

    def _inject_mesh_shard(self, node, members: dict[int, str]) -> None:
        """Place this replica onto the process-wide mesh engine (the
        multi-chip serving path, engine/mesh_engine.py): its peers live
        on other devices along mesh axis 'r', possibly attached by other
        NodeHosts sharing the MeshSpec."""
        from dragonboat_tpu.engine.mesh_engine import attach_mesh_engine

        # persist the bootstrap state FIRST: every fallback below rebuilds
        # the shard host-side from the LogDB
        init = self._build_lane_init(node, members)
        spec = self.config.expert.mesh
        if self.mesh_engine is None:
            try:
                kp = self._kernel_params(min_inbox=5 * (spec.replicas - 1))
                self.mesh_engine = attach_mesh_engine(
                    kp, spec, events=self.events,
                    fleet_stats_every=self.config.expert.fleet_stats_every,
                    pipeline_depth=self.config.expert.kernel_pipeline_depth,
                    health_top_k=self.config.expert.health_top_k,
                    health_thresholds=self._health_thresholds(),
                    invariant_probe=self.config.expert.invariant_probe,
                    capacity_watermark_pct=(
                        self.config.expert.capacity_watermark_pct),
                    capacity_budget_bytes=(
                        self.config.expert.capacity_device_budget_bytes))
            except Exception as e:
                # not enough devices, or geometry mismatch with an
                # already-attached engine
                self._fallback_host_side(node, "mesh-resident", e)
                return
        self._inject_into_engine(self.mesh_engine, node, init,
                                 "mesh-resident")

    def _on_kernel_evict(self, knode, carry: list[pb.Message]) -> None:
        """needs_host slow path: rebuild the shard as a host-resident
        pycore Node from the (already durable) LogDB state and keep every
        in-flight request future alive."""
        cfg = knode.cfg
        with self.mu:
            if self._stopped or self.nodes.get(cfg.shard_id) is not knode:
                return  # stopped/replaced concurrently — do not resurrect
        node = Node(cfg, self.logdb, knode.sm, self._send_message,
                    knode.snapshot_dir, events=self.events, fs=self.fs,
                    worker_id=cfg.shard_id % self._num_workers,
                    clock=self.logical_clock)
        node.membership_changed_cb = (
            lambda cc, sid=cfg.shard_id: self._on_membership_change(sid, cc))
        node.stream_snapshot_cb = self._stream_snapshot
        node.apply_pool = self._apply_pool
        # transplant the books so callers' futures survive the move
        for attr in ("pending_proposals", "pending_reads",
                     "pending_config_change", "pending_snapshot",
                     "pending_transfer", "pending_log_query",
                     "pending_compaction", "rate_limiter", "notify_commit"):
            setattr(node, attr, getattr(knode, attr))
        node.start({}, initial=False, new_node=False)
        for m in carry:
            node.handle_message(m)
        # atomic handoff: _moved is set under knode.mu, THEN the queues
        # and scalar requests are drained under the same lock — any later
        # ingress (Node._post) sees _moved and lands on the successor
        with knode.mu:
            knode._moved = node
            node.incoming_msgs.extend(knode.incoming_msgs)
            knode.incoming_msgs = []
            node.incoming_proposals.extend(knode.incoming_proposals)
            knode.incoming_proposals = []
            for f in ("config_change_entry", "transfer_target",
                      "snapshot_request", "log_query_range",
                      "compaction_request_key"):
                v = getattr(knode, f)
                if v is not None and getattr(node, f) is None:
                    setattr(node, f, v)
                setattr(knode, f, None)
            node._transfer_awaiting = knode._transfer_awaiting
            node._last_leader = (knode._leader_cache,
                                 knode._leader_term_cache)
        with self.mu:
            if self.nodes.get(cfg.shard_id) is knode:
                self.nodes[cfg.shard_id] = node
            # else: stop_replica raced us and already destroyed the books
        self._work.set()

    stop_shard = stop_replica

    # -- engine ---------------------------------------------------------

    def _engine_main(self) -> None:
        """Ticker + work fan-out (the reference's nodeTicker plus the
        signal side of the worker ready queues, engine.go:1107+)."""
        last_tick = time.monotonic()
        while not self._stopped:
            self._work.wait(timeout=self._tick_interval / 4)
            self._work.clear()
            now = time.monotonic()
            if now - last_tick >= self._tick_interval:
                last_tick = now
                self._do_tick_round()
                self.chunk_sink.tick()
            for ev in self._worker_events:
                ev.set()

    def _worker_main(self, w: int) -> None:
        """One step worker: advances the shards hashed to partition w
        (shard_id % workers), plus the kernel engine on worker 0."""
        ev = self._worker_events[w]
        while not self._stopped:
            ev.wait(timeout=self._tick_interval / 2)
            ev.clear()
            progressed = True
            while progressed and not self._stopped:
                progressed = False
                with self.mu:
                    nodes = [n for sid, n in self.nodes.items()
                             if sid % self._num_workers == w]
                for n in nodes:
                    try:
                        if n.step():
                            progressed = True
                    except OSError as e:
                        self._on_fatal(e)
                        return
                    except Exception:
                        _LOG.exception("shard %d step failed", n.shard_id)
                if w == 0:
                    for eng in (self.kernel_engine, self.mesh_engine):
                        if eng is None:
                            continue
                        try:
                            if eng.step_all():
                                progressed = True
                        except OSError as e:
                            self._on_fatal(e)
                            return
                        except Exception:
                            _LOG.exception("device engine step failed")

    def run_once(self) -> int:
        """Step every node until quiescent; returns steps executed."""
        steps = 0
        progressed = True
        while progressed and not self._stopped:
            progressed = False
            with self.mu:
                nodes = list(self.nodes.values())
            for n in nodes:
                try:
                    if n.step():
                        progressed = True
                        steps += 1
                except OSError as e:
                    self._on_fatal(e)
                    return steps
                except Exception:
                    _LOG.exception("shard %d step failed", n.shard_id)
            for eng in (self.kernel_engine, self.mesh_engine):
                if eng is None:
                    continue
                try:
                    if eng.step_all():
                        progressed = True
                        steps += 1
                except OSError as e:
                    self._on_fatal(e)
                    return steps
                except Exception:
                    _LOG.exception("device engine step failed")
        return steps

    def _on_fatal(self, exc: Exception) -> None:
        """Controlled crash on a storage failure: a raft log or snapshot
        write that did not reach stable storage voids every ack sent after
        it, so the host stops stepping immediately (the reference panics
        the process; a library records the fault and halts —
        nodehost.go:361-367 ErrorFS crash arming)."""
        with self.mu:
            if self.fatal_error is None:
                self.fatal_error = exc
            self._stopped = True
        _LOG.critical("storage failure, halting NodeHost: %s", exc)
        self._work.set()
        for ev in self._worker_events:
            ev.set()

    def _do_tick_round(self, sweep_every: int = 8) -> None:
        """One tick round: advance the shared clock ONCE, tick the
        host-resident nodes, and hand engine-registered lanes to their
        engine as a single pending round (consumed as one vectorized
        [G]-bool broadcast at the next device step).  Per-lane Python
        here was the 100k election pump's wall clock (~25 s/round);
        request-timeout GC over engine lanes is an amortized sweep
        (books compare absolute deadline stamps, so skipped rounds
        cannot drift the deadline — only delay its firing by at most
        ``sweep_every`` rounds)."""
        self.logical_clock.advance()
        self._tick_round_no += 1
        sweep = (self._tick_round_no % sweep_every) == 0
        with self.mu:
            nodes = list(self.nodes.values())
        for n in nodes:
            if getattr(n, "engine", None) is not None and n.lane >= 0:
                if sweep:
                    n.gc_books()
                continue
            n.tick()
        for eng in (self.kernel_engine, self.mesh_engine):
            if eng is not None:
                eng.tick_round()
        self._control_round()

    def tick_all(self) -> None:
        """Manual tick for auto_run=False test drivers (books GC every
        round — deterministic timeouts for tests)."""
        self._do_tick_round(sweep_every=1)

    def _control_round(self) -> None:
        """Close the observe→act loop once per NEW decimated health
        observation: feed the kernel engine's cached top-K digest (plus
        the step-latency EWMA) to the FleetController and apply the
        planned transfers.  Runs on the engine ticker thread, outside
        engine.mu (lock order engine.mu -> node.mu: the transfer call
        takes node locks, so it must never run under the engine's)."""
        eng = self.kernel_engine
        if eng is None or not self._controller.policy.enabled:
            return
        seq = int(getattr(eng, "_health_seq", 0))
        if seq <= self._ctrl_seen_seq:
            return            # no new observation since the last plan
        self._ctrl_seen_seq = seq
        health = getattr(eng, "last_health", None) or {}
        worst = health.get("worst", [])
        lanes = {int(w.get("lane", -1)) for w in worst}
        hot_us = self.config.expert.control_hot_ewma_us
        host_hot = bool(hot_us) and int(self.events.metrics.snapshot().get(
            "engine.kernel_step.ewma_us", 0)) >= hot_us
        # digest offenders are the candidate set — except under host-
        # level overload, where every led shard qualifies (the planner's
        # host_hot semantics), so the snapshot must include them all
        with self.mu:
            nodes = [n for n in self.nodes.values()
                     if getattr(n, "engine", None) is eng
                     and (host_hot or getattr(n, "lane", -1) in lanes)]
        shards = []
        for n in nodes:
            try:
                mb = n.sm.get_membership()
                shards.append({
                    "shard_id": int(n.shard_id),
                    "replica_id": int(n.replica_id),
                    "lane": int(n.lane),
                    "is_leader": bool(n.is_leader()),
                    "term": int(n.node_term()),
                    "membership": {"addresses": {
                        int(r): str(a) for r, a in mb.addresses.items()}},
                })
            except Exception:
                continue      # torn down mid-plan: skip this round's row
        from dragonboat_tpu import flight as _flight

        for d in self._controller.observe(worst, shards,
                                          host_hot=host_hot):
            _flight.record(_flight.CONTROL_TRANSFER,
                           tick=self._tick_round_no, shard_id=d.shard_id,
                           target=d.target, evidence=d.evidence)
            try:
                self.request_leader_transfer(d.shard_id, d.target)
                self.events.metrics.inc("control_transfer_issued")
            except RequestError as e:
                self.events.metrics.inc("control_transfer_failed")
                _LOG.warning("control transfer shard %d -> %d failed: %s",
                             d.shard_id, d.target, e)

    def _stream_snapshot(self, node: Node, m: pb.Message) -> None:
        """Live-stream an on-disk SM's snapshot to a lagging peer
        (nodehost.go:1888-1891 → rsm.ChunkWriter + transport job.go):
        the image is produced by the SM directly into transport chunks —
        no sender-side file.  Runs as a background job so a large stream
        never stalls the step workers."""
        import queue as _queue

        from dragonboat_tpu.rsm.chunkwriter import ChunkWriter

        class _Aborted(Exception):
            pass

        def job() -> None:
            q: _queue.Queue = _queue.Queue(maxsize=8)
            DONE, FAIL = object(), object()
            aborted = threading.Event()

            def emit(c) -> None:
                # never block forever: if the consumer abandoned the
                # stream (breaker open, send error), the producer must
                # unwind instead of deadlocking inside the SM lock
                while not aborted.is_set():
                    try:
                        q.put(c, timeout=0.2)
                        return
                    except _queue.Full:
                        continue
                raise _Aborted()

            cw = ChunkWriter(
                emit, shard_id=node.shard_id, to_replica=m.to,
                from_=node.replica_id,
                deployment_id=self.config.deployment_id,
                source_address=self.config.raft_address,
            )

            def on_meta(index, term, membership):
                from dataclasses import replace

                cw.index, cw.term = index, term
                cw.message = replace(m, snapshot=pb.Snapshot(
                    index=index, term=term, membership=membership,
                    shard_id=node.shard_id, type=node.sm.sm_type,
                    on_disk_index=index,
                ))

            def producer() -> None:
                try:
                    node.sm.stream_snapshot(cw, on_meta=on_meta)
                    cw.close()
                    q.put(DONE)
                except _Aborted:
                    pass  # consumer gone; nothing to report
                except Exception:
                    _LOG.exception("snapshot stream save failed")
                    # deliver FAIL with the same patience as emit: the
                    # consumer may be paced; dropping it would leave the
                    # consumer blocked in q.get() forever
                    while not aborted.is_set():
                        try:
                            q.put(FAIL, timeout=0.2)
                            break
                        except _queue.Full:
                            continue

            t = threading.Thread(target=producer, name="snapshot-save-stream",
                                 daemon=True)
            t.start()

            def chunks():
                while True:
                    item = q.get()
                    if item is DONE:
                        return
                    if item is FAIL:
                        raise RuntimeError("stream producer failed")
                    yield item

            try:
                self.hub.send_snapshot_chunks(m, chunks())
            finally:
                # unwind the producer whether or not the send completed
                aborted.set()
                while t.is_alive():
                    try:
                        q.get_nowait()
                    except _queue.Empty:
                        pass
                    t.join(timeout=0.05)

        threading.Thread(target=job, name="snapshot-stream-job",
                         daemon=True).start()

    # -- transport glue --------------------------------------------------

    def _send_message(self, m: pb.Message) -> None:
        if self._partitioned:
            return  # monkey partition: silence sends (nodehost.go:1877)
        self.hub.send(m)
        self._work.set()

    def _handle_message_batch(self, batch: pb.MessageBatch) -> None:
        """Inbound dispatch (messageHandler.HandleMessageBatch,
        nodehost.go:2072)."""
        if batch.deployment_id != self.config.deployment_id:
            return  # transport.go:306-311 deployment-id gate
        if self._partitioned:
            return  # monkey partition: silence receive (nodehost.go:2076)
        # learn the sender's address so responses resolve even before any
        # membership entry applies locally (transport.go:317-324).  Not in
        # gossip mode: targets there are NodeHostIDs, and pinning a raw
        # address would permanently bypass gossip re-resolution after the
        # sender moves
        if batch.source_address and not self.config.address_by_node_host_id:
            for m in batch.requests:
                if m.from_ != 0:
                    self.registry.add(m.shard_id, m.from_, batch.source_address)
        # fabric inbound seam: BOTH transports funnel here, so one call
        # covers per-link recv accounting, delivery latency off the
        # header's sender stamp, hub_recv span stamping (the PR 7 fix),
        # and the remote child span + hop-census bookkeeping.  The byte
        # estimate mirrors the hub's send-side _msg_size so the two ends
        # of a link stay comparable
        fabric.METER.on_batch_received(
            self.config.raft_address, batch,
            nbytes=sum(_msg_size(m) for m in batch.requests))
        for m in batch.requests:
            with self.mu:
                node = self.nodes.get(m.shard_id)
            if node is not None:
                # hub delivery skips links the mesh serves: a resident
                # link's copy is a stray (the exchange already carried
                # it) and accepting it would double-deliver; cut links
                # and off-mesh senders keep the hub as their carrier
                eng = self.mesh_engine
                if (eng is not None
                        and getattr(node, "engine", None) is eng
                        and not eng.hub_accepts(node, m)):
                    continue
                node.handle_message(m)
        self._work.set()

    def _on_snapshot_reassembled(self, m: pb.Message,
                                 source_address: str) -> None:
        """A chunk stream completed: deliver the rebuilt InstallSnapshot
        (chunk.go:106 → nodehost.go:2072 handoff).  The sender address rides
        chunk 0 so a joining replica can respond before any membership
        entry applies locally."""
        self.events.snapshot_received(SnapshotInfo(
            shard_id=m.shard_id, replica_id=m.to, from_=m.from_,
            index=m.snapshot.index, term=m.snapshot.term))
        self._handle_message_batch(pb.MessageBatch(
            requests=(m,), deployment_id=self.config.deployment_id,
            source_address=source_address))

    def _on_unreachable(self, m: pb.Message) -> None:
        with self.mu:
            node = self.nodes.get(m.shard_id)
        if node is not None:
            node.handle_message(m)

    def _on_membership_change(self, shard_id: int, cc: pb.ConfigChange) -> None:
        if cc.type in (pb.ConfigChangeType.ADD_NODE,
                       pb.ConfigChangeType.ADD_NON_VOTING,
                       pb.ConfigChangeType.ADD_WITNESS) and cc.address:
            self.registry.add(shard_id, cc.replica_id, cc.address)
        elif cc.type == pb.ConfigChangeType.REMOVE_NODE:
            self.registry.remove(shard_id, cc.replica_id)
        with self.mu:
            node = self.nodes.get(shard_id)
        if node is not None:
            self.events.membership_changed(
                NodeInfo(shard_id, node.replica_id))
            if (cc.type == pb.ConfigChangeType.REMOVE_NODE
                    and cc.replica_id == node.replica_id):
                self.events.node_deleted(NodeInfo(shard_id, node.replica_id))

    # -- helpers ---------------------------------------------------------

    def _node(self, shard_id: int) -> Node:
        # fail fast after a controlled crash: workers no longer step, so
        # every request would otherwise ride its full timeout
        if self.fatal_error is not None:
            raise RequestError(
                f"node host halted by storage failure: {self.fatal_error}")
        with self.mu:
            node = self.nodes.get(shard_id)
        if node is None:
            raise ShardNotFoundError(f"shard {shard_id} not found")
        return node

    def _ticks(self, timeout_s: float) -> int:
        return max(2, int(timeout_s * 1000 / self.config.rtt_millisecond))

    # -- client API: writes ----------------------------------------------

    def propose(self, session: Session, cmd: bytes,
                timeout_s: float = DEFAULT_TIMEOUT_S) -> RequestState:
        node = self._node(session.shard_id)
        rs = node.propose(session, cmd, self._ticks(timeout_s))
        self._work.set()
        return rs

    def sync_propose(self, session: Session, cmd: bytes,
                     timeout_s: float = DEFAULT_TIMEOUT_S) -> Result:
        rs = self.propose(session, cmd, timeout_s)
        result = rs.get(timeout_s)
        # acked-write accounting: rs.get raised on anything but a
        # committed+applied proposal, so this counts exactly the writes
        # a client may rely on (the chaos telemetry invariant checks it
        # against the oracle's committed-entry count)
        self.events.metrics.inc("raft.proposals_acked")
        if not session.is_noop_session():
            session.proposal_completed()
        return result

    # -- client API: sessions --------------------------------------------

    def sync_get_session(self, shard_id: int,
                         timeout_s: float = DEFAULT_TIMEOUT_S) -> Session:
        s = Session.new_session(shard_id)
        s.prepare_for_register()
        node = self._node(shard_id)
        rs = node.propose_session_op(s, self._ticks(timeout_s))
        self._work.set()
        rs.get(timeout_s)
        s.prepare_for_propose()
        return s

    def sync_close_session(self, session: Session,
                           timeout_s: float = DEFAULT_TIMEOUT_S) -> None:
        session.prepare_for_unregister()
        node = self._node(session.shard_id)
        rs = node.propose_session_op(session, self._ticks(timeout_s))
        self._work.set()
        rs.get(timeout_s)

    def get_noop_session(self, shard_id: int) -> Session:
        return Session.new_noop_session(shard_id)

    # -- client API: reads -----------------------------------------------

    def read_index(self, shard_id: int,
                   timeout_s: float = DEFAULT_TIMEOUT_S) -> RequestState:
        node = self._node(shard_id)
        rs = node.read(self._ticks(timeout_s))
        self._work.set()
        return rs

    def read_local_node(self, shard_id: int, query: object) -> object:
        return self._node(shard_id).sm.lookup(query)

    def na_read_local_node(self, shard_id: int, query: object) -> object:
        """NAReadLocalNode (nodehost.go:877): the no-copy byte-slice
        variant — Python has no owned/borrowed distinction, so this is
        read_local_node under the reference's name."""
        return self.read_local_node(shard_id, query)

    def get_log_reader(self, shard_id: int):
        """GetLogReader (nodehost.go:617): the shard's read-only log
        reader (first/last index, term lookups, entry ranges)."""
        return self._node(shard_id).log_reader

    def get_node_host_registry(self):
        """GetNodeHostRegistry (nodehost.go:463): (registry, ok) — ok
        only when gossip addressing is active (the registry then carries
        other hosts' metadata)."""
        from dragonboat_tpu.gossip import GossipRegistry

        return self.registry, isinstance(self.registry, GossipRegistry)

    @property
    def raft_address(self) -> str:
        """RaftAddress (nodehost.go:447)."""
        return self.config.raft_address

    def get_node_user(self, shard_id: int) -> "NodeUser":
        """GetNodeUser (nodehost.go:1324): a per-shard handle bundling
        propose/read_index for one shard (INodeUser API shape; calls
        resolve the shard live so eviction/stop is always respected)."""
        self._node(shard_id)  # raises ShardNotFoundError when absent
        return NodeUser(self, shard_id)

    def sync_read(self, shard_id: int, query: object,
                  timeout_s: float = DEFAULT_TIMEOUT_S) -> object:
        rs = self.read_index(shard_id, timeout_s)
        rs.get(timeout_s)
        return self.read_local_node(shard_id, query)

    def stale_read(self, shard_id: int, query: object) -> object:
        """StaleRead (nodehost.go:894): local lookup, no linearizability."""
        return self.read_local_node(shard_id, query)

    # -- membership ------------------------------------------------------

    def _sync_request_config_change(
        self, shard_id: int, cc_type: pb.ConfigChangeType, replica_id: int,
        target: str, config_change_index: int, timeout_s: float,
    ) -> None:
        rs = self._request_config_change(
            shard_id, cc_type, replica_id, target, config_change_index,
            timeout_s)
        rs.get(timeout_s)

    def sync_request_add_replica(self, shard_id: int, replica_id: int,
                                 target: str, config_change_index: int = 0,
                                 timeout_s: float = DEFAULT_TIMEOUT_S) -> None:
        self._sync_request_config_change(
            shard_id, pb.ConfigChangeType.ADD_NODE, replica_id, target,
            config_change_index, timeout_s)

    def sync_request_add_nonvoting(self, shard_id: int, replica_id: int,
                                   target: str, config_change_index: int = 0,
                                   timeout_s: float = DEFAULT_TIMEOUT_S) -> None:
        self._sync_request_config_change(
            shard_id, pb.ConfigChangeType.ADD_NON_VOTING, replica_id, target,
            config_change_index, timeout_s)

    def sync_request_add_witness(self, shard_id: int, replica_id: int,
                                 target: str, config_change_index: int = 0,
                                 timeout_s: float = DEFAULT_TIMEOUT_S) -> None:
        self._sync_request_config_change(
            shard_id, pb.ConfigChangeType.ADD_WITNESS, replica_id, target,
            config_change_index, timeout_s)

    def sync_request_delete_replica(self, shard_id: int, replica_id: int,
                                    config_change_index: int = 0,
                                    timeout_s: float = DEFAULT_TIMEOUT_S) -> None:
        self._sync_request_config_change(
            shard_id, pb.ConfigChangeType.REMOVE_NODE, replica_id, "",
            config_change_index, timeout_s)

    def sync_get_shard_membership(self, shard_id: int,
                                  timeout_s: float = DEFAULT_TIMEOUT_S
                                  ) -> pb.Membership:
        rs = self.read_index(shard_id, timeout_s)
        rs.get(timeout_s)
        return self._node(shard_id).sm.get_membership()

    def get_shard_membership(self, shard_id: int) -> pb.Membership:
        return self._node(shard_id).sm.get_membership()

    # -- async request variants (nodehost.go:963-1238: the Request*
    # family returns the future; the Sync* family above waits on it) ----

    def request_snapshot(self, shard_id: int,
                         timeout_s: float = DEFAULT_TIMEOUT_S,
                         export_path: str = "",
                         compaction_overhead: int | None = None
                         ) -> RequestState:
        """RequestSnapshot (nodehost.go:963) — the async variant."""
        node = self._node(shard_id)
        req = _SnapshotRequest(
            exported=bool(export_path),
            path=export_path,
            override_compaction=compaction_overhead is not None,
            compaction_overhead=compaction_overhead or 0,
        )
        rs = node.request_snapshot(req, self._ticks(timeout_s))
        self._work.set()
        return rs

    def request_compaction(self, shard_id: int,
                           timeout_s: float = DEFAULT_TIMEOUT_S
                           ) -> RequestState:
        """RequestCompaction (nodehost.go:993) — the async variant."""
        rs = self._node(shard_id).request_compaction(self._ticks(timeout_s))
        self._work.set()
        return rs

    def _request_config_change(
        self, shard_id: int, cc_type: pb.ConfigChangeType, replica_id: int,
        target: str, config_change_index: int, timeout_s: float,
    ) -> RequestState:
        node = self._node(shard_id)
        cc = pb.ConfigChange(
            config_change_id=config_change_index,
            type=cc_type, replica_id=replica_id, address=target,
        )
        rs = node.request_config_change(cc, self._ticks(timeout_s))
        self._work.set()
        return rs

    def request_add_replica(self, shard_id: int, replica_id: int,
                            target: str, config_change_index: int = 0,
                            timeout_s: float = DEFAULT_TIMEOUT_S
                            ) -> RequestState:
        return self._request_config_change(
            shard_id, pb.ConfigChangeType.ADD_NODE, replica_id, target,
            config_change_index, timeout_s)

    def request_add_nonvoting(self, shard_id: int, replica_id: int,
                              target: str, config_change_index: int = 0,
                              timeout_s: float = DEFAULT_TIMEOUT_S
                              ) -> RequestState:
        return self._request_config_change(
            shard_id, pb.ConfigChangeType.ADD_NON_VOTING, replica_id,
            target, config_change_index, timeout_s)

    def request_add_witness(self, shard_id: int, replica_id: int,
                            target: str, config_change_index: int = 0,
                            timeout_s: float = DEFAULT_TIMEOUT_S
                            ) -> RequestState:
        return self._request_config_change(
            shard_id, pb.ConfigChangeType.ADD_WITNESS, replica_id, target,
            config_change_index, timeout_s)

    def request_delete_replica(self, shard_id: int, replica_id: int,
                               config_change_index: int = 0,
                               timeout_s: float = DEFAULT_TIMEOUT_S
                               ) -> RequestState:
        return self._request_config_change(
            shard_id, pb.ConfigChangeType.REMOVE_NODE, replica_id, "",
            config_change_index, timeout_s)

    def propose_session(self, session: Session,
                        timeout_s: float = DEFAULT_TIMEOUT_S
                        ) -> RequestState:
        """ProposeSession (nodehost.go:816): propose the session's
        current lifecycle op (the caller prepared it for register or
        unregister) and return the future."""
        node = self._node(session.shard_id)
        rs = node.propose_session_op(session, self._ticks(timeout_s))
        self._work.set()
        return rs

    # -- leadership ------------------------------------------------------

    def request_leader_transfer(self, shard_id: int, target: int) -> None:
        node = self._node(shard_id)
        node.request_leader_transfer(target, self._ticks(DEFAULT_TIMEOUT_S))
        self._work.set()

    def get_leader_id(self, shard_id: int) -> tuple[int, bool]:
        node = self._node(shard_id)
        lid = node.leader_id()
        return lid, lid != 0

    # -- snapshots -------------------------------------------------------

    def sync_request_snapshot(self, shard_id: int,
                              timeout_s: float = DEFAULT_TIMEOUT_S,
                              export_path: str = "",
                              compaction_overhead: int | None = None) -> int:
        rs = self.request_snapshot(shard_id, timeout_s,
                                   export_path=export_path,
                                   compaction_overhead=compaction_overhead)
        r = rs.wait(timeout_s)
        if r.code != RequestResultCode.COMPLETED:
            raise RequestError(f"snapshot failed: {r.code.name}")
        return r.snapshot_index

    def sync_request_compaction(self, shard_id: int,
                                timeout_s: float = DEFAULT_TIMEOUT_S) -> None:
        """SyncRequestCompaction: LogDB compaction up to the snapshotter's
        compacted-to index, processed on the engine thread
        (nodehost.go RequestCompaction → node.go:972)."""
        rs = self.request_compaction(shard_id, timeout_s)
        r = rs.wait(timeout_s)
        if r.code == RequestResultCode.REJECTED:
            raise RequestRejectedError(
                "nothing to compact (no snapshot taken yet)")
        if r.code != RequestResultCode.COMPLETED:
            raise RequestError(f"compaction failed: {r.code.name}")

    def remove_data(self, shard_id: int, replica_id: int) -> None:
        """RemoveData (nodehost.go:1295): purge a stopped replica's
        state; raises while the shard is still running."""
        with self.mu:
            if shard_id in self.nodes:
                raise RequestError("shard still running")
        self.logdb.remove_node_data(shard_id, replica_id)

    def sync_remove_data(self, shard_id: int, replica_id: int,
                         timeout_s: float = DEFAULT_TIMEOUT_S) -> None:
        """SyncRemoveData (nodehost.go:1259)."""
        self.remove_data(shard_id, replica_id)

    # -- log queries -----------------------------------------------------

    def query_raft_log(self, shard_id: int, first: int, last: int,
                       max_size: int = 0,
                       timeout_s: float = DEFAULT_TIMEOUT_S):
        """QueryRaftLog (nodehost.go:781): the request rides the engine's
        step loop and the result comes back on the Update path
        (node.go:1238 handleLogQuery → node.go:319 processLogQuery)."""
        node = self._node(shard_id)
        rs = node.query_raft_log(first, last, max_size,
                                 self._ticks(timeout_s))
        self._work.set()
        r = rs.wait(timeout_s)
        if r.code == RequestResultCode.COMPLETED:
            return rs.log_query_result
        if r.code == RequestResultCode.REJECTED:
            raise RequestError("log query out of range")
        raise RequestError(f"log query failed: {r.code.name}")

    # -- info ------------------------------------------------------------

    def _local_shard_views(self):
        """This host's shards as ShardViews for the gossip exchange
        (view.go:77 toShardViewList): replica addresses come from the
        replicated membership, leadership from the live node."""
        from dragonboat_tpu.gossip import ShardView

        with self.mu:
            nodes = list(self.nodes.values())
        out = []
        for n in nodes:
            mb = n.sm.get_membership()
            out.append(ShardView(
                shard_id=n.shard_id,
                replicas=dict(mb.addresses),
                config_change_index=mb.config_change_id,
                leader_id=n.leader_id(),
                term=n.node_term(),
            ))
        return out

    def get_node_host_info(self) -> NodeHostInfo:
        with self.mu:
            nodes = list(self.nodes.values())
        infos = [
            ShardInfo(
                shard_id=n.shard_id,
                replica_id=n.replica_id,
                leader_id=n.leader_id(),
                term=n.node_term(),
                is_leader=n.is_leader(),
                membership=n.sm.get_membership(),
                last_applied=n.sm.get_last_applied(),
            )
            for n in nodes
        ]
        return NodeHostInfo(
            node_host_id=self.id,
            raft_address=self.config.raft_address,
            shard_info_list=infos,
        )

    @staticmethod
    def _membership_dict(mb) -> dict:
        return {
            "addresses": {int(r): str(a) for r, a in mb.addresses.items()},
            "non_votings": {int(r): str(a)
                            for r, a in mb.non_votings.items()},
            "witnesses": {int(r): str(a) for r, a in mb.witnesses.items()},
            "config_change_id": int(mb.config_change_id),
        }

    def info(self) -> dict:
        """JSON-able ``NodeHostInfo`` parity view plus the merged health
        snapshot — the ``/debug/groups`` payload and ``fleet_doctor``'s
        per-host input.  Same shard fields as ``get_node_host_info``,
        with each shard's residency (host / device / mesh) attached."""
        nhi = self.get_node_host_info()
        with self.mu:
            nodes = dict(self.nodes)
        shards = []
        for si in nhi.shard_info_list:
            n = nodes.get(si.shard_id)
            shards.append({
                "shard_id": int(si.shard_id),
                "replica_id": int(si.replica_id),
                "leader_id": int(si.leader_id),
                "term": int(si.term),
                "is_leader": bool(si.is_leader),
                "last_applied": int(si.last_applied),
                "membership": self._membership_dict(si.membership),
                "resident": self._residency(n),
                "lane": int(getattr(n, "lane", -1)),
            })
        return {
            "node_host_id": nhi.node_host_id,
            "raft_address": nhi.raft_address,
            "health": self._health_snapshot(),
            "capacity": self._capacity_snapshot(),
            "fleet": self._fleet_snapshot(),
            "fabric": fabric.METER.snapshot(),
            "shards": shards,
        }

    def _residency(self, node) -> str:
        eng = getattr(node, "engine", None)
        if eng is None:
            return "host"
        return "mesh" if eng is self.mesh_engine else "device"

    def _shard_info_or_none(self, shard_id: int) -> dict | None:
        """HTTP-callback form of ``shard_info``: None for a 404 instead
        of a raised ShardNotFoundError."""
        try:
            return self.shard_info(shard_id)
        except (ShardNotFoundError, RequestError):
            return None

    def shard_info(self, shard_id: int) -> dict:
        """Drill-down for ONE group: the device row fetched O(1) by
        dynamic_index (never a full-state materialization) merged with
        every host-side register — pending books, logdb range + snapshot
        meta, peer breaker states, and this host's gossip ShardView."""
        node = self._node(shard_id)
        mb = node.sm.get_membership()
        reads = node.pending_reads
        with reads.mu:
            reads_pending = (len(reads.batching)
                             + sum(len(v) for v in reads.pending.values())
                             + len(reads.waiting))
        info = {
            "shard_id": int(shard_id),
            "replica_id": int(node.replica_id),
            "leader_id": int(node.leader_id()),
            "term": int(node.node_term()),
            "is_leader": bool(node.is_leader()),
            "last_applied": int(node.sm.get_last_applied()),
            "membership": self._membership_dict(mb),
            "resident": self._residency(node),
            "pending": {
                "proposals": len(node.pending_proposals.pending),
                "read_indexes": reads_pending,
            },
        }
        rs = self.logdb.read_raft_state(shard_id, node.replica_id, 0)
        ss = self.logdb.get_snapshot(shard_id, node.replica_id)
        info["logdb"] = {
            "first_index": int(rs.first_index) if rs is not None else 0,
            "last_index": (int(rs.first_index + rs.entry_count - 1)
                           if rs is not None else 0),
            "entry_count": int(rs.entry_count) if rs is not None else 0,
            "snapshot": ({"index": int(ss.index), "term": int(ss.term)}
                         if ss is not None and ss.index else None),
        }
        me = self.config.raft_address
        info["breakers"] = {
            str(addr): self.hub.breaker(addr).state()
            for addr in sorted(set(mb.addresses.values()))
            if addr and addr != me
        }
        info["shard_view"] = {
            "shard_id": int(shard_id),
            "replicas": {int(r): str(a) for r, a in mb.addresses.items()},
            "config_change_index": int(mb.config_change_id),
            "leader_id": int(node.leader_id()),
            "term": int(node.node_term()),
        }
        eng = getattr(node, "engine", None)
        info["device"] = (eng.health_row(node.lane)
                          if eng is not None else None)
        return info

    def has_node_info(self, shard_id: int, replica_id: int) -> bool:
        return self.logdb.get_bootstrap_info(shard_id, replica_id) is not None

    def metrics(self) -> dict[str, int]:
        """Counter snapshot (the reference's Prometheus surface); the
        transport hub shares the same registry under ``transport.*``."""
        return self.events.metrics.snapshot()

    # -- chaos-test surface (monkey.go, build tag dragonboat_monkeytest) --

    def partition_node(self) -> None:
        """Silence this host's sends AND receives (monkey.go:170
        PartitionNode): the cluster sees a dead machine while local
        clients keep timing out against it."""
        self._partitioned = True
        t = self.transport
        if hasattr(t, "partitioned"):
            t.partitioned = True
        self._set_mesh_partitioned(True)

    def restore_partitioned_node(self) -> None:
        """monkey.go:178 RestorePartitionedNode."""
        self._partitioned = False
        t = self.transport
        if hasattr(t, "partitioned"):
            t.partitioned = False
        self._set_mesh_partitioned(False)
        self._work.set()

    def _set_mesh_partitioned(self, cut: bool) -> None:
        """Mesh traffic never crosses the host transport, so a monkey
        partition of this host also masks its mesh rows device-side."""
        if self.mesh_engine is None:
            return
        with self.mu:
            nodes = list(self.nodes.values())
        for n in nodes:
            if getattr(n, "engine", None) is self.mesh_engine:
                self.mesh_engine.set_partitioned(n, cut)

    def _set_mesh_hub_served(self, served: bool) -> None:
        """Force every mesh link of THIS host's replicas onto the hub
        (symmetrically, both endpoints) so transport faults — drop,
        delay — apply to its consensus traffic like any other hub
        traffic.  Healing restores the links resident; a concurrent
        fault on a peer's host sharing a link is healed with it (chaos
        plans schedule soft transport faults one host at a time)."""
        eng = self.mesh_engine
        if eng is None:
            return
        with self.mu:
            nodes = list(self.nodes.values())
        for n in nodes:
            if getattr(n, "engine", None) is not eng:
                continue
            for rid in range(1, eng.spec.replicas + 1):
                if rid != n.replica_id:
                    eng.set_link_hub_served(n, rid, served)

    def get_session_hash(self, shard_id: int) -> int:
        """Convergence oracle over the session book (monkey.go:117)."""
        return self._node(shard_id).sm.get_session_hash()

    def get_membership_hash(self, shard_id: int) -> int:
        """Convergence oracle over membership (monkey.go:118)."""
        return self._node(shard_id).sm.get_membership_hash()

    def get_sm_hash(self, shard_id: int) -> int:
        """User-SM convergence oracle (monkey.go:114 GetStateMachineHash);
        the user SM must expose ``get_hash() -> int``."""
        sm = self._node(shard_id).sm.sm
        get_hash = getattr(sm, "get_hash", None)
        if get_hash is None:
            raise RequestError(
                "state machine does not implement get_hash()")
        return int(get_hash())


class NodeUser:
    """Per-shard client handle (nodehost.go:1324 GetNodeUser /
    INodeUser): Propose and ReadIndex bound to one shard; the futures
    are the same RequestStates the NodeHost API returns."""

    __slots__ = ("_nh", "shard_id")

    def __init__(self, nh: NodeHost, shard_id: int) -> None:
        self._nh = nh
        self.shard_id = shard_id

    def propose(self, session: Session, cmd: bytes,
                timeout_s: float = DEFAULT_TIMEOUT_S) -> RequestState:
        if session.shard_id != self.shard_id:
            raise RequestError("session targets a different shard")
        return self._nh.propose(session, cmd, timeout_s)

    def read_index(self, timeout_s: float = DEFAULT_TIMEOUT_S
                   ) -> RequestState:
        return self._nh.read_index(self.shard_id, timeout_s)
