"""ICI transport: cross-chip replica groups via shard_map + collectives.

The reference's replicas talk over a framed TCP transport
(``internal/transport/tcp.go:64-394``); when every replica of a group is a
row of the same SPMD program, that transport seam collapses into an
``all_gather`` of the step's fixed-width out-lanes over the mesh's replica
axis — the message blocks ride ICI, and the per-address circuit breakers /
send queues disappear because delivery is the collective itself.

Layout
------
Mesh ``('g', 'r')``: axis ``r`` has one device per replica slot (R total);
axis ``g`` block-parallelizes disjoint group sets (no communication).  The
global state has leading dim ``G = g_size * R * n_local`` laid out
block-major: row ``((ig * R) + ir) * n_local + n`` is replica ``ir+1`` of
group ``ig * n_local + n``, so a flat ``P(('g', 'r'))`` sharding gives
device ``(ig, ir)`` the ``n_local`` rows of its replica slot.

Each step: local batched raft step → ``all_gather`` out-lanes over ``'r'``
→ rebuild the grouped ``[n_local * R]`` view → reuse the single-device
router → keep the rows addressed to my replica slot.  Correctness therefore
reduces to the router's (tests/test_device_router.py); these collectives
only change *where* the lanes live.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as PS

try:
    # jax >= 0.6: top-level shard_map, replication check kwarg is
    # check_vma
    _shard_map_impl = jax.shard_map
    _SHARD_MAP_NOCHECK = {"check_vma": False}
except AttributeError:
    # jax 0.4/0.5: experimental namespace, kwarg is check_rep
    from jax.experimental.shard_map import shard_map as _shard_map_impl

    _SHARD_MAP_NOCHECK = {"check_rep": False}


def shard_map(f, *, mesh, in_specs, out_specs):
    """`jax.shard_map(..., check_vma=False)` across jax versions."""
    return _shard_map_impl(f, mesh=mesh, in_specs=in_specs,
                           out_specs=out_specs, **_SHARD_MAP_NOCHECK)

from dragonboat_tpu.core import params as KP
from dragonboat_tpu.core.kernel import step
from dragonboat_tpu.core.kstate import (
    Inbox,
    ShardState,
    StepInput,
    StepOutput,
    empty_inbox,
    init_state,
)
from dragonboat_tpu.core.router import route


@dataclass(frozen=True)
class IciCluster:
    """Static geometry of a mesh-sharded cluster."""

    kp: KP.KernelParams
    mesh: Mesh
    replicas: int        # R — size of mesh axis 'r'
    n_local: int         # groups per device
    num_groups: int      # total groups = g_size * n_local

    @property
    def g_size(self) -> int:
        return self.mesh.shape["g"]

    @property
    def total_rows(self) -> int:
        return self.g_size * self.replicas * self.n_local

    def sharding(self, extra_dims: int = 0) -> NamedSharding:
        return NamedSharding(self.mesh, PS(("g", "r"), *([None] * extra_dims)))

    def shard(self, tree):
        """Place a [G]-leading pytree onto the mesh."""
        return jax.tree.map(
            lambda x: jax.device_put(x, self.sharding(x.ndim - 1)), tree
        )


def make_ici_cluster(
    kp: KP.KernelParams,
    mesh: Mesh,
    num_groups: int,
    election: int = 10,
) -> tuple[IciCluster, ShardState, Inbox]:
    """Build a cluster whose replica axis spans mesh axis 'r'.

    ``num_groups`` must divide evenly over mesh axis 'g'."""
    R = mesh.shape["r"]
    g_size = mesh.shape["g"]
    assert num_groups % g_size == 0, "num_groups must divide mesh axis g"
    n_local = num_groups // g_size
    cluster = IciCluster(kp=kp, mesh=mesh, replicas=R, n_local=n_local,
                         num_groups=num_groups)

    # block-major replica-id layout (see module docstring)
    rids = np.empty((cluster.total_rows,), np.int32)
    for ig in range(g_size):
        for ir in range(R):
            lo = (ig * R + ir) * n_local
            rids[lo:lo + n_local] = ir + 1
    pids = np.arange(1, R + 1, dtype=np.int32)
    state = init_state(kp, cluster.total_rows, rids, pids,
                       election_timeout=election)
    box = empty_inbox(kp, cluster.total_rows)
    return cluster, cluster.shard(state), cluster.shard(box)


def _exchange(kp: KP.KernelParams, R: int, n_local: int,
              out: StepOutput) -> Inbox:
    """Collective message exchange: all_gather the out-lanes over the
    replica axis, rebuild the grouped view, reuse the single-device
    router, keep the rows addressed to my replica slot."""
    gathered = jax.tree.map(
        lambda x: jax.lax.all_gather(x, "r", axis=0), out
    )

    def to_grouped(x):  # [R, n_local, ...] -> [n_local * R, ...] group-major
        if x is None:  # optional lanes (e.g. s_ent_val without payloads)
            return None
        x = jnp.swapaxes(x, 0, 1)
        return x.reshape((n_local * R,) + x.shape[2:])

    out_full = StepOutput(*[to_grouped(f) for f in gathered])
    box_full = route(kp, R, out_full)          # [n_local * R, ...] grouped
    t = jax.lax.axis_index("r")

    def mine(x):  # keep rows addressed to my replica slot
        g = x.reshape((n_local, R) + x.shape[1:])
        return jax.lax.dynamic_index_in_dim(g, t, axis=1, keepdims=False)

    return jax.tree.map(mine, box_full)


def _ici_body(kp: KP.KernelParams, replicas: int,
              state: ShardState, box: Inbox, inp: StepInput):
    """shard_map body: local [n_local] step + collective message exchange."""
    state, out = step(kp, state, box, inp)
    box = _exchange(kp, replicas, state.term.shape[0], out)
    return state, box, out


@functools.partial(jax.jit, static_argnums=(0, 1))
def _jit_ici_step(kp, cluster: IciCluster, state, box, inp):
    body = shard_map(
        functools.partial(_ici_body, kp, cluster.replicas),
        mesh=cluster.mesh,
        in_specs=(PS(("g", "r")), PS(("g", "r")), PS(("g", "r"))),
        out_specs=(PS(("g", "r")), PS(("g", "r")), PS(("g", "r"))),
    )
    return body(state, box, inp)


def ici_cluster_step(cluster: IciCluster, state: ShardState, box: Inbox,
                     inp: StepInput):
    """One cluster step with cross-chip message routing.

    Equivalent of router.cluster_step for mesh-resident replicas; the
    transport seam (raftio.ITransport) is the all_gather inside."""
    return _jit_ici_step(cluster.kp, cluster, state, box, inp)


def _mask_outgoing(out: StepOutput, cut: jnp.ndarray) -> StepOutput:
    """Zero the out-lanes addressed over cut LINKS.

    ``cut`` is the per-link mask ``[G, num_peers] bool``: ``cut[g, p]``
    severs the mesh link between row ``g`` and its group peer rid
    ``p + 1`` (mesh addressing pins peer slot ``p`` to rid ``p + 1``, so
    the column index doubles as the slot index).  A whole-True row is
    the old per-lane partition (monkey.go:170 PartitionNode): the row
    sends nothing on the mesh, but still ticks, persists and applies.
    A single column is the round-17 hub-fallback surface: traffic for
    that link leaves the mesh and rides the host hub instead
    (MeshEngine._emit_messages)."""
    P = cut.shape[1]

    def zpeer(a):  # [G, P(, E)] peer-slot lanes: zero slot p where cut
        c = cut.reshape(cut.shape + (1,) * (a.ndim - 2))
        return jnp.where(c, jnp.zeros_like(a), a)

    # response lanes are addressed by rid, not slot: lane k of row g is
    # masked when the link to its destination rid is cut.  One-hot
    # compare + any, NOT take_along_axis: a per-lane gather here would
    # breach the mesh HLO budget (analysis/hlo_budget.json gates them)
    rid = jnp.arange(1, P + 1, dtype=out.r_to.dtype)
    cut_to = jnp.any(
        (out.r_to[:, :, None] == rid) & cut[:, None, :], axis=-1)  # [G, K]
    return out._replace(
        r_type=jnp.where(cut_to, jnp.zeros_like(out.r_type), out.r_type),
        s_rep=zpeer(out.s_rep), s_hb=zpeer(out.s_hb),
        s_vote=zpeer(out.s_vote), s_timeout_now=zpeer(out.s_timeout_now),
    )


def _mask_incoming(box: Inbox, cut: jnp.ndarray) -> Inbox:
    """Zero inbox slots whose SOURCE arrives over a cut link.  Every
    field is zeroed, not just the type: the kernel's inbox contract is
    route()'s (invalid slots are all-zero), and a slot with mtype=0 but
    a live term would still feed term adoption (caught by
    tests/test_mesh_differential.py)."""
    P = cut.shape[1]
    # one-hot source match (gather-free, like _mask_outgoing); from_=0
    # (empty slot) matches no rid and stays untouched
    rid = jnp.arange(1, P + 1, dtype=box.from_.dtype)
    cut_src = jnp.any(
        (box.from_[:, :, None] == rid) & cut[:, None, :], axis=-1)  # [G, K]
    return jax.tree.map(
        lambda x: jnp.where(
            cut_src.reshape(cut_src.shape + (1,) * (x.ndim - 2)),
            jnp.zeros_like(x), x),
        box,
    )


def _serve_body(kp: KP.KernelParams, replicas: int,
                state: ShardState, box: Inbox, inp: StepInput,
                cut: jnp.ndarray):
    """shard_map body for the SERVING path: host-staged StepInput, a
    device-resident inbox carried between steps, and a per-link cut
    mask reserving the host hub for cut / off-mesh links.

    Returns (state, next_box, out).  The round-16 ``pending`` scalar
    (a per-step device->host crossing) is gone: the host derives
    drain-pending from the [G, C] activity flags it already fetches
    every step (MeshDispatch.note_output_flags), so the serving step
    downloads nothing beyond the masked output path."""
    state, out = step(kp, state, box, inp)
    box = _exchange(kp, replicas, state.term.shape[0],
                    _mask_outgoing(out, cut))
    # symmetric receive-side masking: with BOTH endpoints of a cut link
    # masked, a one-sided (asymmetric) mask update can never leak a
    # message across a link the host already re-routed over the hub
    box = _mask_incoming(box, cut)
    return state, box, out


@functools.partial(jax.jit, static_argnums=(0, 1))
def jit_serve_step(kp, cluster: IciCluster, state, box, inp, cut):
    """Jitted serving entry (non-donated): the depth-0 mesh oracle the
    engine dispatch layer wraps in compile telemetry.  ``cut`` is the
    per-link mask ``[G, num_peers] bool`` (see ``_mask_outgoing``)."""
    body = shard_map(
        functools.partial(_serve_body, kp, cluster.replicas),
        mesh=cluster.mesh,
        in_specs=(PS(("g", "r")), PS(("g", "r")), PS(("g", "r")),
                  PS(("g", "r"), None)),
        out_specs=(PS(("g", "r")), PS(("g", "r")), PS(("g", "r"))),
    )
    return body(state, box, inp, cut)


@functools.partial(jax.jit, static_argnums=(0, 1), donate_argnums=(2, 3, 4))
def jit_serve_step_donated(kp, cluster: IciCluster, state, box, inp, cut):
    """Donating twin of ``jit_serve_step`` for the pipelined dispatch:
    state, the carried inbox and the staged input hand their buffers to
    XLA (kstate.DONATION ``serve_step_donated``; host no-touch rule
    applies after dispatch).  ``cut`` is NOT donated — the engine caches
    the device copy of the per-link mask across steps."""
    body = shard_map(
        functools.partial(_serve_body, kp, cluster.replicas),
        mesh=cluster.mesh,
        in_specs=(PS(("g", "r")), PS(("g", "r")), PS(("g", "r")),
                  PS(("g", "r"), None)),
        out_specs=(PS(("g", "r")), PS(("g", "r")), PS(("g", "r"))),
    )
    return body(state, box, inp, cut)


def ici_serve_step(cluster: IciCluster, state: ShardState, box: Inbox,
                   inp: StepInput, cut):
    """One serving step: kernel + in-mesh routing + per-link cut mask.

    The mesh-engine equivalent of router.cluster_step — the transport
    seam (transport.go:86-101) is the all_gather inside the body."""
    return jit_serve_step(cluster.kp, cluster, state, box, inp, cut)


def self_driving_input(kp: KP.KernelParams, state: ShardState,
                       tick: bool = True, propose: bool = True) -> StepInput:
    """bench_loop.full_step's feedback shape for sharded state: proposals on
    leaders, instant-apply RSM cursor, logical clock ticking."""
    G, B = state.term.shape[0], kp.proposal_cap
    is_leader = state.role == KP.LEADER
    pv = jnp.broadcast_to(is_leader[:, None], (G, B)) & jnp.asarray(propose)
    z = lambda: jnp.zeros((G,), jnp.int32)  # noqa: E731
    return StepInput(
        prop_valid=pv,
        prop_cc=jnp.zeros((G, B), bool),
        ri_valid=jnp.zeros((G,), bool),
        ri_low=z(),
        ri_high=z(),
        transfer_to=z(),
        tick=jnp.broadcast_to(jnp.asarray(tick, bool), (G,)),
        quiesced=jnp.zeros((G,), bool),
        applied=state.processed,
    )


@functools.partial(jax.jit, static_argnums=(0, 1, 2, 3))
def ici_run_steps(kp, cluster: IciCluster, iters: int, propose: bool,
                  state, box):
    """iters self-driving sharded steps under one jit (bench inner loop)."""
    body_fn = functools.partial(_ici_body, kp, cluster.replicas)

    def one(st, bx):
        inp = self_driving_input(kp, st, tick=True, propose=propose)
        st, bx, _ = body_fn(st, bx, inp)
        return st, bx

    def sharded(st, bx):
        return jax.lax.fori_loop(
            0, iters, lambda _, c: one(*c), (st, bx)
        )

    return shard_map(
        sharded,
        mesh=cluster.mesh,
        in_specs=(PS(("g", "r")), PS(("g", "r"))),
        out_specs=(PS(("g", "r")), PS(("g", "r"))),
    )(state, box)
