"""Multi-chip execution: sharded cluster steps over a jax.sharding.Mesh.

The reference scales by spreading replicas of each raft group over NodeHosts
connected by TCP (``internal/transport/transport.go:86-101``); the TPU-native
equivalent co-schedules the whole cluster as one SPMD program and exchanges
message blocks over ICI collectives (SURVEY §7.8).
"""

from dragonboat_tpu.parallel.ici import (  # noqa: F401
    IciCluster,
    make_ici_cluster,
    ici_cluster_step,
)
