"""Pallas kernels for the device-resident fabric's hot gather shapes.

Round 17 moves co-located consensus traffic onto the mesh
(parallel/ici.py), which leaves two gather-shaped selects on the
serving path's critical loop:

  1. **inbox staging** — picking response lanes by a per-row lane
     index (core/router.route's ``pick``), an ``[G, K]`` batched
     gather that XLA serializes over the batch axis on TPU (the same
     pathology kernel._get1 documents);
  2. **quorum match** — the q-th largest match among voting members
     (core/kernel._sorted_match_quorum_index), which XLA lowers as a
     full ``jnp.sort`` plus a gather even though only ONE order
     statistic is consumed.

Each kernel holds its row block in VMEM and stays VPU-shaped (one-hot
compares + reductions, no gathers/scatters — the raft kernel's
discipline).  Semantics are bit-identical to the XLA references
exported next to them; ``tests/test_fabric_pallas.py`` pins that in
interpret mode and ``scripts/tpu_pallas_ab.py`` A/Bs the compiled
numbers as ``kind=fabric_ab`` rungs.  ``interpret`` defaults to True
off-TPU (pallas TPU lowering needs the real backend).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

I32 = jnp.int32
ROW_BLOCK = 8     # sublane dimension: rows per grid program
_INT_MIN = jnp.iinfo(jnp.int32).min
_INT_MAX = jnp.iinfo(jnp.int32).max


def _default_interpret(interpret: bool | None) -> bool:
    if interpret is None:
        # compiled path on real TPU hardware; PJRT plugins may register
        # the chip under another name (e.g. "axon"), so match both
        return jax.devices()[0].platform not in ("tpu", "axon")
    return bool(interpret)


# ---------------------------------------------------------------------------
# inbox staging: batched lane gather
# ---------------------------------------------------------------------------


def gather_lanes_xla(vals, idx):
    """XLA reference arm: ``out[g, m] = vals[g, idx[g, m]]`` — the
    batched HLO gather route()'s lane pick would emit without the
    one-hot rewrite.  ``idx`` must be in range (no sentinel)."""
    return jnp.take_along_axis(vals, idx, axis=1)


def _gather_block_kernel(K: int, M: int, vals_ref, idx_ref, out_ref):
    """One grid program: M lane picks against an [8, K] block in VMEM.
    An out-of-range index has no hot slot and reads 0 — the router's
    lane==K sentinel convention, not an error."""
    pos = jax.lax.broadcasted_iota(I32, (ROW_BLOCK, K), 1)

    def body(j, _):
        oh = pos == idx_ref[:, j][:, None]            # [8, K] one-hot
        out_ref[:, j] = jnp.sum(
            jnp.where(oh, vals_ref[:, :], 0), axis=1)
        return 0

    jax.lax.fori_loop(0, M, body, 0)


@functools.partial(jax.jit, static_argnums=(2,))
def _gather_pallas(vals, idx, interpret: bool):
    G, K = vals.shape
    M = idx.shape[1]
    pad = (-G) % ROW_BLOCK
    if pad:
        vals = jnp.pad(vals, ((0, pad), (0, 0)))
        idx = jnp.pad(idx, ((0, pad), (0, 0)))
    Gp = G + pad

    def block(i):
        return (i, 0)

    out = pl.pallas_call(
        functools.partial(_gather_block_kernel, K, M),
        grid=(Gp // ROW_BLOCK,),
        in_specs=[
            pl.BlockSpec((ROW_BLOCK, K), block),
            pl.BlockSpec((ROW_BLOCK, M), block),
        ],
        out_specs=pl.BlockSpec((ROW_BLOCK, M), block),
        out_shape=jax.ShapeDtypeStruct((Gp, M), vals.dtype),
        interpret=interpret,
    )(vals, idx)
    return out[:G]


def gather_lanes_pallas(vals, idx, interpret: bool | None = None):
    """``gather_lanes_xla`` semantics as a VMEM block kernel: the [G, K]
    value rows stay resident across all M picks instead of one gather
    dispatch per lane.  Bit-identical for in-range indexes; an index
    == K reads 0 (the one-hot sentinel, matching router.route's
    ``onehot_reads`` branch)."""
    return _gather_pallas(vals, idx, _default_interpret(interpret))


# ---------------------------------------------------------------------------
# quorum match: one order statistic, not a sort
# ---------------------------------------------------------------------------


def quorum_match_xla(match, voting, quorum):
    """XLA reference arm — core/kernel._sorted_match_quorum_index's
    exact shape: mask non-voters to INT_MAX, full ascending sort, then
    gather the single ``nv - quorum`` position (clipped)."""
    mv = jnp.where(voting, match, _INT_MAX)
    srt = jnp.sort(mv, axis=1)
    nv = jnp.sum(voting.astype(I32), axis=1)
    pos = jnp.clip(nv - quorum, 0, match.shape[1] - 1)
    return jnp.take_along_axis(srt, pos[:, None], axis=1)[:, 0]


def _quorum_block_kernel(R: int, match_ref, voting_ref, q_ref, out_ref):
    """Rank-select without the sort: the q-th largest voter match is
    the largest value v with at least q voter matches >= v (duplicate
    values collapse onto the same candidate, so ties pick the same
    element the ascending sort would).  When fewer than q voters exist
    the sort reference clips to position 0 — the smallest masked value
    — which the fallback arm reproduces (INT_MAX when no voters)."""
    m = match_ref[:, :]                               # [8, R]
    v = voting_ref[:, :] != 0
    q = q_ref[:, 0]

    def body(j, cnt):
        ge = (m[:, j][:, None] >= m) & v[:, j][:, None] & v
        return cnt + ge.astype(I32)

    # cnt[i] = #{voting j : match[j] >= match[i]}  (R tiny: 2D passes)
    cnt = jax.lax.fori_loop(0, R, body, jnp.zeros_like(m))
    ok = v & (cnt >= q[:, None])
    best = jnp.max(jnp.where(ok, m, _INT_MIN), axis=1)
    fallback = jnp.min(jnp.where(v, m, _INT_MAX), axis=1)
    out_ref[:, 0] = jnp.where(jnp.any(ok, axis=1), best, fallback)


@functools.partial(jax.jit, static_argnums=(3,))
def _quorum_pallas(match, voting, quorum, interpret: bool):
    G, R = match.shape
    pad = (-G) % ROW_BLOCK
    if pad:
        match = jnp.pad(match, ((0, pad), (0, 0)))
        voting = jnp.pad(voting, ((0, pad), (0, 0)))
        quorum = jnp.pad(quorum, (0, pad))
    Gp = G + pad

    def block(i):
        return (i, 0)

    out = pl.pallas_call(
        functools.partial(_quorum_block_kernel, R),
        grid=(Gp // ROW_BLOCK,),
        in_specs=[
            pl.BlockSpec((ROW_BLOCK, R), block),
            pl.BlockSpec((ROW_BLOCK, R), block),
            pl.BlockSpec((ROW_BLOCK, 1), block),
        ],
        out_specs=pl.BlockSpec((ROW_BLOCK, 1), block),
        out_shape=jax.ShapeDtypeStruct((Gp, 1), match.dtype),
        interpret=interpret,
    )(match, voting.astype(I32), quorum[:, None])
    return out[:G, 0]


def quorum_match_pallas(match, voting, quorum,
                        interpret: bool | None = None):
    """``quorum_match_xla`` semantics as a VMEM block kernel computing
    the one consumed order statistic via compare-counts instead of a
    full sort + gather.  Bit-identical (tests/test_fabric_pallas.py),
    including the fewer-voters-than-quorum and zero-voter clips."""
    return _quorum_pallas(match, voting, quorum,
                          _default_interpret(interpret))
