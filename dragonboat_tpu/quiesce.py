"""Quiesce state machine — parity with the reference's ``quiesce.go``.

An idle shard (no proposals, reads, config changes, or non-heartbeat
messages for ``election_tick * 10`` ticks) enters quiesce: the raft engine
stops receiving real ticks (``Peer.quiesced_tick`` only advances the
logical clock, quiesce.go:43-54 + internal/raft/raft.go:650), so no
heartbeats or elections fire and thousands of idle shards cost nothing.
Any client activity or non-heartbeat message wakes the shard back up
(quiesce.go:60-77 ``record``).
"""

from __future__ import annotations

from dataclasses import dataclass

from dragonboat_tpu import raftpb as pb
from dragonboat_tpu.logger import get_logger

_LOG = get_logger("quiesce")


@dataclass
class QuiesceState:
    """Per-node quiesce bookkeeping (quiesce.go:24-34)."""

    shard_id: int = 0
    replica_id: int = 0
    election_tick: int = 0
    enabled: bool = False
    current_tick: int = 0
    quiesced_since: int = 0
    idle_since: int = 0
    exit_quiesce_tick: int = 0
    _new_quiesce_flag: bool = False

    def threshold(self) -> int:
        return self.election_tick * 10

    def quiesced(self) -> bool:
        return self.enabled and self.quiesced_since > 0

    def new_quiesce_state(self) -> bool:
        """True once per quiesce entry (quiesce.go:38-40 swap)."""
        flag, self._new_quiesce_flag = self._new_quiesce_flag, False
        return flag

    def tick(self) -> int:
        if not self.enabled:
            return 0
        self.current_tick += 1
        if not self.quiesced():
            if self.current_tick - self.idle_since > self.threshold():
                self._enter_quiesce()
        return self.current_tick

    def record(self, msg_type: pb.MessageType) -> None:
        """Client/raft activity observed — reset the idle clock and wake
        from quiesce.  Heartbeats are ignored while awake and during the
        election_tick grace window right after entering quiesce (trailing
        heartbeats from not-yet-quiesced peers); past the window they do
        wake the shard (quiesce.go:60-77)."""
        if not self.enabled:
            return
        if msg_type in (pb.MessageType.HEARTBEAT,
                        pb.MessageType.HEARTBEAT_RESP):
            if not self.quiesced() or self._new_to_quiesce():
                return
        self.idle_since = self.current_tick
        if self.quiesced():
            self._exit_quiesce()
            _LOG.info(
                "shard %d replica %d exited quiesce, msg %s, tick %d",
                self.shard_id, self.replica_id, msg_type.name,
                self.current_tick,
            )

    def _new_to_quiesce(self) -> bool:
        """Just entered quiesce: trailing heartbeats from peers that have
        not yet quiesced must not wake us (quiesce.go:84-89)."""
        return (self.quiesced()
                and self.current_tick - self.quiesced_since < self.election_tick)

    def _just_exited_quiesce(self) -> bool:
        return (not self.quiesced()
                and self.current_tick - self.exit_quiesce_tick < self.threshold())

    def try_enter_quiesce(self) -> None:
        """A peer's Quiesce message arrived (quiesce.go:96-104)."""
        if not self.enabled or self._just_exited_quiesce():
            return
        if not self.quiesced():
            self._enter_quiesce()

    def _enter_quiesce(self) -> None:
        self.quiesced_since = self.current_tick
        self.idle_since = self.current_tick
        self._new_quiesce_flag = True
        _LOG.info("shard %d replica %d entered quiesce",
                  self.shard_id, self.replica_id)

    def _exit_quiesce(self) -> None:
        self.quiesced_since = 0
        self.exit_quiesce_tick = self.current_tick
