"""The RSM apply orchestrator.

Parity with ``internal/rsm/statemachine.go``: drains committed-entry Tasks,
applies session ops / config changes / user updates with at-most-once dedup,
maintains the applied index, and drives snapshot save/recover through the
versioned block-CRC file format.  Wraps the three host SM kinds behind one
managed interface with the reference's RWMutex discipline
(managed.go:57, adapter.go).
"""

from __future__ import annotations

import io
import os
import struct
import threading
import zlib
from dataclasses import dataclass, field
from typing import Callable, Sequence

from dragonboat_tpu import raftpb as pb
from dragonboat_tpu import statemachine as sm_api
from dragonboat_tpu.rsm.encoded import get_payload
from dragonboat_tpu.rsm.membership import MembershipStore
from dragonboat_tpu.rsm.session import LRUSession
from dragonboat_tpu.rsm.snapshotio import (
    SnapshotFormatError,
    read_snapshot,
    shrink_snapshot_file,
    write_snapshot,
)


@dataclass
class Task:
    """One unit of apply work — parity statemachine.go:111 (Task)."""

    shard_id: int = 0
    replica_id: int = 0
    entries: list[pb.Entry] = field(default_factory=list)
    save: bool = False
    recover: bool = False
    initial: bool = False
    stream: bool = False
    shard_closed: bool = False
    ss_request: object = None


@dataclass
class ApplyResult:
    index: int
    key: int
    client_id: int
    series_id: int
    result: sm_api.Result
    rejected: bool = False


class StateMachine:
    """Managed SM + session/membership apply loop for one shard."""

    def __init__(
        self,
        shard_id: int,
        replica_id: int,
        user_sm: object,
        ordered_config_change: bool = False,
        compress_snapshots: bool = False,
        fs=None,
    ) -> None:
        from dragonboat_tpu.vfs import default_fs

        self.fs = fs if fs is not None else default_fs()
        self.shard_id = shard_id
        self.replica_id = replica_id
        self.compress_snapshots = compress_snapshots
        self.sm = user_sm
        self.sm_type = sm_api.sm_type_of(user_sm)
        self.sessions = LRUSession()
        self.members = MembershipStore(shard_id, ordered_config_change)
        self._mu = threading.RLock()
        self.last_applied = 0
        self.last_applied_term = 0
        self.on_disk_init_index = 0
        if self.sm_type == pb.StateMachineType.ON_DISK:
            self.on_disk_init_index = self.sm.open(lambda: False)
            self.last_applied = self.on_disk_init_index

    # -- reads ----------------------------------------------------------

    def lookup(self, query: object) -> object:
        with self._mu:
            return self.sm.lookup(query)

    def get_membership(self) -> pb.Membership:
        return self.members.get()

    def get_last_applied(self) -> int:
        # deliberately lock-free: the applied cursor is a monotonic int
        # (atomic to read) and the step path polls it every step — taking
        # _mu here would let a slow user Update() holding the apply lock
        # wedge the step worker, exactly what the apply pool exists to
        # prevent (engine.go:1153 apply/step isolation)
        return self.last_applied

    # -- hash oracles for chaos testing (monkey.go:113-121) ---------------

    def get_session_hash(self) -> int:
        buf = io.BytesIO()
        self.sessions.save(buf)
        return zlib.crc32(buf.getvalue())

    def get_membership_hash(self) -> int:
        return self.members.get_hash()

    # -- apply ----------------------------------------------------------

    def handle(self, entries: Sequence[pb.Entry]) -> list[ApplyResult]:
        """Apply a batch of committed entries in order
        (statemachine.go:877 handle / :935 handleEntry)."""
        out: list[ApplyResult] = []
        with self._mu:
            for e in entries:
                if e.index <= self.last_applied:
                    continue  # on-disk SM replay skip (statemachine.go:912)
                out.append(self._handle_entry(e))
                self.last_applied = e.index
                self.last_applied_term = e.term
        return out

    def _handle_entry(self, e: pb.Entry) -> ApplyResult:
        res = ApplyResult(
            index=e.index, key=e.key, client_id=e.client_id,
            series_id=e.series_id, result=sm_api.Result(),
        )
        if e.type == pb.EntryType.METADATA:
            # witness replication strips payloads (raft.go:770
            # makeMetadataEntries): the entry advances the applied cursor
            # but must never reach sessions or the user SM
            return res
        if e.is_config_change():
            cc = pb.decode_config_change(e.cmd)
            accepted = self.members.handle_config_change(cc, e.index)
            res.rejected = not accepted
            res.result = sm_api.Result(value=e.index if accepted else 0)
            return res
        if e.is_new_session_request():
            r = self.sessions.register_client_id(e.client_id)
            res.result = r
            res.rejected = r.value == 0
            return res
        if e.is_end_of_session_request():
            r = self.sessions.unregister_client_id(e.client_id)
            res.result = r
            res.rejected = r.value == 0
            return res
        if not e.is_session_managed():
            # noop-session update: apply without dedup
            if len(e.cmd) == 0:
                return res  # empty entry (leader noop)
            res.result = self._update(e)
            return res
        # session-managed update with dedup
        cached, has_cached, need_update, session = self.sessions.update_required(e)
        if session is None:
            res.rejected = True  # unknown session (expired / never registered)
            return res
        if has_cached:
            res.result = cached
            return res
        if not need_update:
            # already responded; nothing to return (client moved on)
            res.rejected = True
            return res
        session.clear_to(e.responded_to)
        res.result = self._update(e)
        session.add_response(e.series_id, res.result)
        return res

    def _update(self, e: pb.Entry) -> sm_api.Result:
        entry = sm_api.Entry(index=e.index, cmd=get_payload(e))
        if self.sm_type == pb.StateMachineType.REGULAR:
            return self.sm.update(entry)
        results = self.sm.update([entry])
        return results[0].result if results else sm_api.Result()

    # -- snapshot save/recover (statemachine.go:553/246) -------------------

    def _prepare_save(self):
        """Under the apply lock: meta + session image + the payload writer
        (ctx captured for concurrent/on-disk SMs so the payload itself can
        be produced OUTSIDE the lock — statemachine.go:553 Prepare under
        mu, save concurrent).  The returned collection receives the user
        SM's external snapshot files (rsm/files.go)."""
        index, term = self.last_applied, self.last_applied_term
        membership = self.members.get()
        sbuf = io.BytesIO()
        self.sessions.save(sbuf)
        session_data = sbuf.getvalue()
        fc = _FileCollection()
        if self.sm_type == pb.StateMachineType.REGULAR:
            def write_payload(w):
                self.sm.save_snapshot(w, fc, lambda: False)
        elif self.sm_type == pb.StateMachineType.CONCURRENT:
            ctx = self.sm.prepare_snapshot()

            def write_payload(w):
                self.sm.save_snapshot(ctx, w, fc, lambda: False)
        else:
            ctx = self.sm.prepare_snapshot()

            def write_payload(w):
                self.sm.save_snapshot(ctx, w, lambda: False)
        return index, term, membership, session_data, write_payload, fc

    def save_snapshot(self, path: str) -> tuple[int, int, pb.Membership]:
        index, term, membership, _ = self.save_snapshot_with_files(path)
        return index, term, membership

    def save_snapshot_with_files(self, path: str):
        """save_snapshot + the external files the user SM attached
        (ISnapshotFileCollection, rsm/files.go): each is copied next to
        the snapshot container as ``<path>.xf<file_id>`` and returned as
        a pb.SnapshotFile tuple for the snapshot record."""
        from dragonboat_tpu.vfs import copy_file

        with self._mu:
            index, term, membership, session_data, write_payload, fc = \
                self._prepare_save()
            tmp = path + ".generating"
            with self.fs.open(tmp, "wb") as f:
                write_snapshot(f, session_data, write_payload,
                               compress=self.compress_snapshots)
                self.fs.fsync(f)
        # the external-file copies run OUTSIDE the apply lock: fc is
        # fixed once write_payload returned, snapshot requests are
        # serialized with this shard's applies (apply-pool lane / step
        # path), and a multi-GB artifact copy must not stall lookups
        files = []
        for sf in fc.files:
            dst = f"{path}.xf{sf.file_id}"
            dtmp = dst + ".generating"
            size = copy_file(self.fs, sf.filepath, dtmp)
            self.fs.replace(dtmp, dst)
            files.append(pb.SnapshotFile(
                file_id=sf.file_id, filepath=dst,
                metadata=sf.metadata, file_size=size))
        self.fs.replace(tmp, path)
        return index, term, membership, tuple(files)

    def stream_snapshot(self, w, on_meta=None) -> tuple[int, int, "pb.Membership"]:
        """Streaming save (statemachine.go:568 Stream): write the same
        container ``save_snapshot`` produces into ``w`` (a ChunkWriter),
        without any local file.  ``on_meta(index, term, membership)`` is
        called under the apply lock BEFORE payload bytes are written.

        Only prepare runs under the apply lock; the payload is produced
        outside it (concurrent/on-disk SMs snapshot a prepared ctx), so a
        slow or paced network transfer never blocks applies.  REGULAR SMs
        have no prepared-ctx contract and keep the lock for the write —
        the reference only streams on-disk SMs at all."""
        with self._mu:
            # external files are not carried on the stream path (the
            # reference only streams on-disk SMs, which have no file
            # collection API)
            index, term, membership, session_data, write_payload, _fc = \
                self._prepare_save()
            if on_meta is not None:
                on_meta(index, term, membership)
            if self.sm_type == pb.StateMachineType.REGULAR:
                write_snapshot(w, session_data, write_payload,
                               compress=self.compress_snapshots)
                return index, term, membership
        write_snapshot(w, session_data, write_payload,
                       compress=self.compress_snapshots)
        return index, term, membership

    def recover_from_snapshot(self, path: str, ss: pb.Snapshot) -> None:
        with self._mu:
            with self.fs.open(path, "rb") as f:
                session_data, payload = read_snapshot(f)
                # a shrunken snapshot carries no payload — the on-disk
                # SM's own durable storage has the data (statemachine.go
                # :295 isShrunkSnapshot skip); feeding it to any other SM
                # kind would silently lose state
                shrunk = getattr(payload, "shrunk", False)
                if shrunk and self.sm_type != pb.StateMachineType.ON_DISK:
                    raise SnapshotFormatError(
                        "shrunk snapshot on a non-on-disk SM")
                if shrunk and self.last_applied < ss.index:
                    # the payload was dropped on the assumption the
                    # receiver's own durable storage covers ss.index —
                    # if it doesn't (a lagging peer was handed a shrunk
                    # file), skipping silently would fake an applied
                    # cursor over data that never arrived
                    raise SnapshotFormatError(
                        f"shrunk snapshot at index {ss.index} does not "
                        f"cover this SM (applied {self.last_applied})")
                self.sessions = LRUSession.load(io.BytesIO(session_data))
                if not shrunk:
                    if self.sm_type == pb.StateMachineType.ON_DISK:
                        self.sm.recover_from_snapshot(payload, lambda: False)
                    else:
                        # external files recorded on the snapshot reach
                        # the user SM with their local paths
                        # (rsm/files.go; sm recover contract)
                        ufiles = tuple(
                            sm_api.SnapshotFile(
                                file_id=f.file_id, filepath=f.filepath,
                                metadata=f.metadata)
                            for f in ss.files)
                        self.sm.recover_from_snapshot(payload, ufiles,
                                                      lambda: False)
            self.members.set(ss.membership)
            self.last_applied = ss.index
            self.last_applied_term = ss.term

    def restore_bookkeeping(self, ss: pb.Snapshot) -> None:
        """Advance membership + applied meta WITHOUT touching the user SM
        — the restore path for file-less witness/dummy snapshots
        (raft.go:728 makeWitnessSnapshot carries no data)."""
        with self._mu:
            self.members.set(ss.membership)
            self.last_applied = max(self.last_applied, ss.index)
            self.last_applied_term = ss.term

    def applied_meta(self) -> tuple[int, int, "pb.Membership"]:
        """(applied index, term, membership) as one consistent read."""
        with self._mu:
            return self.last_applied, self.last_applied_term, \
                self.members.get()

    def sync(self) -> None:
        """On-disk SM durability barrier (disk.go Sync)."""
        if self.sm_type == pb.StateMachineType.ON_DISK:
            self.sm.sync()

    def shrink_recorded_snapshot(self, path: str) -> None:
        """Replace the recorded snapshot file with its shrunken form once
        an on-disk SM has synced the data into its own storage
        (snapshotter.go:200 Shrink).  No-op for other SM kinds."""
        if self.sm_type != pb.StateMachineType.ON_DISK:
            return
        sbuf = io.BytesIO()
        LRUSession().save(sbuf)
        shrink_snapshot_file(path, self.fs, sbuf.getvalue())

    def close(self) -> None:
        self.sm.close()


class _FileCollection:
    def __init__(self) -> None:
        self.files: list[sm_api.SnapshotFile] = []

    def add_file(self, file_id: int, path: str, metadata: bytes) -> None:
        # a duplicate id would silently overwrite the copied artifact and
        # desync the recorded sizes from the shipped byte stream
        # (files.go AddFile panics on duplicates)
        if any(f.file_id == file_id for f in self.files):
            raise ValueError(f"duplicate snapshot file id {file_id}")
        self.files.append(sm_api.SnapshotFile(file_id, path, metadata))
