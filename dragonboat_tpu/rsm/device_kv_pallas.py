"""Pallas apply kernel for DeviceKV — the rsm-apply hot loop as a real
TPU kernel.

Why pallas here: the XLA lowering of ``DeviceKV.apply_kernel`` is a
``lax.scan`` over the AB command lanes, and every iteration streams the
whole ``[G, T]`` table through HBM (AB x 2 full passes).  This kernel
keeps an 8-shard block of the table resident in VMEM across the entire
apply window — one HBM read + one write of the table per step instead of
AB of each — while the per-command work stays VPU-shaped ([8, T]
elementwise one-hot selects, no gathers/scatters, same discipline as the
raft kernel).

Semantics are bit-identical to the XLA path (same linear-probe order,
same last-write-wins within a window); ``tests/test_device_kv_pallas.py``
asserts exact state/result equality in interpret mode.  ``interpret=True``
is forced on CPU (pallas TPU lowering needs the real backend); on TPU the
compiled path runs — validation of the speedup is pending device access
(the tunnel was down when this landed; see PERF.md).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from dragonboat_tpu.core.params import splitmix32
from dragonboat_tpu.rsm.device_kv import DeviceKV

I32 = jnp.int32
SHARD_BLOCK = 8   # sublane dimension: shards per grid program


def _apply_block_kernel(T: int, D: int, AB: int, hash_keys: bool,
                        cmds_ref, valid_ref,
                        _keys_in, _vals_in, _count_in,
                        keys_ref, vals_ref, count_ref,
                        results_ref, ok_ref):
    """One grid program: apply AB commands to an [8, T] table block held
    in VMEM.  keys/vals/count are input_output_aliased (in-place): the
    output refs start holding the input tables, so the kernel reads and
    writes through them and ignores the shadow input refs."""
    pos = jax.lax.broadcasted_iota(I32, (SHARD_BLOCK, T), 1)

    def body(j, _):
        key = cmds_ref[:, j, 0]                       # [8]
        val = cmds_ref[:, j, 1]
        lane_ok = valid_ref[:, j] != 0
        if hash_keys:
            # the SAME mixer as DeviceKV._probe_slots — probe order must
            # stay bit-identical between the pallas and XLA paths
            h = splitmix32(key.astype(jnp.uint32)).astype(I32) & (T - 1)
        else:
            h = key & (T - 1)
        rel = (pos - h[:, None]) & (T - 1)            # [8, T]
        in_window = rel < D
        K = keys_ref[:, :]                            # current table keys
        hit = (K == key[:, None] + 1) & in_window
        empty = (K == 0) & in_window
        # first (lowest probe offset) hit, else first empty — identical
        # pick order to the sequential XLA path
        hit_rel = jnp.where(hit, rel, T)
        empty_rel = jnp.where(empty, rel, T)
        min_hit = jnp.min(hit_rel, axis=1)            # [8]
        min_empty = jnp.min(empty_rel, axis=1)
        use_rel = jnp.where(min_hit < T, min_hit, min_empty)
        found = use_rel < T
        do = lane_ok & found & (key >= 0)
        is_new = do & ~(min_hit < T)
        target = (h + use_rel) & (T - 1)              # [8]
        onehot = (pos == target[:, None]) & do[:, None]
        keys_ref[:, :] = jnp.where(onehot, key[:, None] + 1, K)
        vals_ref[:, :] = jnp.where(onehot, val[:, None], vals_ref[:, :])
        count_ref[:, 0] = count_ref[:, 0] + is_new.astype(I32)
        results_ref[:, j] = jnp.where(do, val, -1)
        ok_ref[:, j] = do.astype(I32)
        return 0

    jax.lax.fori_loop(0, AB, body, 0)


# keys/vals/count are donated: callers replace their state dict with the
# returned one, and without donation XLA must copy the whole table into
# the aliased output buffers — re-adding the HBM traffic the kernel
# exists to remove
@functools.partial(jax.jit, static_argnums=(0, 1), donate_argnums=(2, 3, 4))
def _apply_pallas(kv: DeviceKV, interpret: bool, keys, vals, count,
                  cmd_lanes, valid_mask):
    G = keys.shape[0]
    T, D = kv.table_cap, kv.probe_depth
    AB = cmd_lanes.shape[1]
    pad = (-G) % SHARD_BLOCK
    if pad:
        keys = jnp.pad(keys, ((0, pad), (0, 0)))
        vals = jnp.pad(vals, ((0, pad), (0, 0)))
        count = jnp.pad(count, (0, pad))
        cmd_lanes = jnp.pad(cmd_lanes, ((0, pad), (0, 0), (0, 0)))
        valid_mask = jnp.pad(valid_mask, ((0, pad), (0, 0)))
    Gp = G + pad
    grid = (Gp // SHARD_BLOCK,)

    def block(i):  # shard-block index map
        return (i, 0)

    kernel = functools.partial(_apply_block_kernel, T, D, AB, kv.hash_keys)
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((SHARD_BLOCK, AB, 2), lambda i: (i, 0, 0)),
            pl.BlockSpec((SHARD_BLOCK, AB), block),
            pl.BlockSpec((SHARD_BLOCK, T), block),
            pl.BlockSpec((SHARD_BLOCK, T), block),
            pl.BlockSpec((SHARD_BLOCK, 1), block),
        ],
        out_specs=[
            pl.BlockSpec((SHARD_BLOCK, T), block),
            pl.BlockSpec((SHARD_BLOCK, T), block),
            pl.BlockSpec((SHARD_BLOCK, 1), block),
            pl.BlockSpec((SHARD_BLOCK, AB), block),
            pl.BlockSpec((SHARD_BLOCK, AB), block),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((Gp, T), I32),       # keys
            jax.ShapeDtypeStruct((Gp, T), I32),       # vals
            jax.ShapeDtypeStruct((Gp, 1), I32),       # count
            jax.ShapeDtypeStruct((Gp, AB), I32),      # results
            jax.ShapeDtypeStruct((Gp, AB), I32),      # ok
        ],
        # tables update in place: alias inputs 2/3/4 onto outputs 0/1/2
        input_output_aliases={2: 0, 3: 1, 4: 2},
        interpret=interpret,
    )(cmd_lanes, valid_mask.astype(I32), keys, vals, count[:, None])
    nkeys, nvals, ncount, results, ok = out
    return (nkeys[:G], nvals[:G], ncount[:G, 0], results[:G],
            ok[:G].astype(bool))


def apply_kernel_pallas(kv: DeviceKV, sm_state: dict, cmd_lanes,
                        valid_mask, interpret: bool | None = None):
    """``DeviceKV.apply_kernel`` semantics backed by the pallas block
    kernel.  NOT drop-in on buffer lifetime: the input state arrays are
    DONATED (callers must replace their state dict with the returned one
    and never touch the old arrays — keeping a pre-apply copy requires
    an explicit ``jnp.copy`` first).  Donation is what lets the aliased
    tables update in place; with ``G`` not a multiple of SHARD_BLOCK the
    pad path copies anyway, so size ``G`` block-aligned for the zero-copy
    claim to hold.  ``interpret`` defaults to True off-TPU."""
    if interpret is None:
        # compiled path on real TPU hardware; PJRT plugins may register
        # the chip under another name (e.g. "axon"), so match both
        interpret = jax.devices()[0].platform not in ("tpu", "axon")
    keys, vals, count, results, ok = _apply_pallas(
        kv, interpret, sm_state["keys"], sm_state["vals"],
        sm_state["count"], cmd_lanes, valid_mask)
    return {"keys": keys, "vals": vals, "count": count}, (results, ok)
