"""Replicated membership store.

Parity with ``internal/rsm/membership.go``: the {config_change_id,
addresses, non_votings, witnesses, removed} record replicated through
config-change entries, with ordered-CC enforcement and the rejection rules
(:111-206).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass

from dragonboat_tpu import raftpb as pb


class MembershipStore:
    def __init__(self, shard_id: int, ordered: bool) -> None:
        self.shard_id = shard_id
        self.ordered = ordered
        self._mu = threading.RLock()
        self.membership = pb.Membership(config_change_id=0)

    def set(self, m: pb.Membership) -> None:
        with self._mu:
            self.membership = m.copy()

    def get(self) -> pb.Membership:
        with self._mu:
            return self.membership.copy()

    def get_hash(self) -> int:
        """Membership hash oracle for chaos tests (monkey.go:118)."""
        import zlib

        with self._mu:
            m = self.membership
            parts = [
                str(sorted(m.addresses.items())),
                str(sorted(m.non_votings.items())),
                str(sorted(m.witnesses.items())),
                str(sorted(m.removed)),
            ]
            return zlib.crc32("|".join(parts).encode())

    # -- config change application (membership.go:111-280) ----------------

    def _rejected(self, cc: pb.ConfigChange) -> str | None:
        m = self.membership
        rid = cc.replica_id
        if self.ordered and cc.config_change_id != m.config_change_id:
            return "config change id not matched"
        if rid in m.removed:
            return "replica already removed"
        if cc.type == pb.ConfigChangeType.ADD_NODE:
            if rid in m.witnesses:
                return "cannot promote witness"
            if cc.address in m.addresses.values() and m.addresses.get(rid) != cc.address:
                return "address already in use"
            if rid in m.addresses and m.addresses[rid] != cc.address:
                return "replica exists with different address"
        elif cc.type == pb.ConfigChangeType.ADD_NON_VOTING:
            if rid in m.addresses or rid in m.witnesses:
                return "replica already a member"
            if cc.address in m.addresses.values():
                return "address already in use"
        elif cc.type == pb.ConfigChangeType.ADD_WITNESS:
            if rid in m.addresses or rid in m.non_votings:
                return "replica already a member"
        elif cc.type == pb.ConfigChangeType.REMOVE_NODE:
            pass
        return None

    def handle_config_change(self, cc: pb.ConfigChange, index: int) -> bool:
        """Apply (or reject) one committed config change; returns accepted."""
        with self._mu:
            reason = self._rejected(cc)
            if reason is not None:
                return False
            m = self.membership.copy()
            rid = cc.replica_id
            if cc.type == pb.ConfigChangeType.ADD_NODE:
                m.non_votings.pop(rid, None)
                m.addresses[rid] = cc.address
            elif cc.type == pb.ConfigChangeType.ADD_NON_VOTING:
                m.non_votings[rid] = cc.address
            elif cc.type == pb.ConfigChangeType.ADD_WITNESS:
                m.witnesses[rid] = cc.address
            elif cc.type == pb.ConfigChangeType.REMOVE_NODE:
                m.addresses.pop(rid, None)
                m.non_votings.pop(rid, None)
                m.witnesses.pop(rid, None)
                m.removed[rid] = True
            self.membership = pb.Membership(
                config_change_id=index,
                addresses=m.addresses,
                non_votings=m.non_votings,
                witnesses=m.witnesses,
                removed=m.removed,
            )
            return True
