"""Versioned snapshot file format with per-block CRCs.

Parity with the reference's snapshot formats (``internal/rsm/snapshotio.go``
header + ``rwv.go`` block writer/validator + ``encoded.go`` compression):
a fixed header (version, sizes, checksum type, header CRC), a session
payload, the user-SM payload written in CRC-framed blocks, and a footer
with the payload checksum.  Corrupt blocks fail recovery instead of
feeding bad state to the SM.

V2 layout (little-endian):
  magic "DBTPUSNP" | u32 version | u32 header_crc | u64 session_len
  | session bytes | blocks: [u32 len | u32 crc | bytes]* | u32 0 terminator

V3 adds the compression envelope (encoded.go analog): each block frame is
[u32 stored_len | u32 crc(stored) | u8 compressed | stored bytes], where
compressed blocks hold zlib(raw).  The payload checksum covers the RAW
bytes, so V2 and V3 of the same payload verify identically.
"""

from __future__ import annotations

import io
import struct
import zlib
from typing import BinaryIO

MAGIC = b"DBTPUSNP"
V2 = 2
V3 = 3
# version-field flag: the file is a SHRUNKEN snapshot (snapshotio.go:462
# ShrinkSnapshot) — a valid container whose payload was dropped because an
# on-disk SM holds the data durably itself; only the (empty) session image
# remains.  Recovery must never feed a shrunk payload to a non-on-disk SM.
SHRUNK = 0x100
BLOCK_SIZE = 256 * 1024
# only compress when it actually shrinks the block by a margin (skip
# incompressible payloads rather than pay decompress for nothing)
_MIN_GAIN = 0.9


class SnapshotFormatError(ValueError):
    pass


class BlockWriter:
    """CRC-framed block writer (rwv.go IVWriter; V3 adds compression)."""

    def __init__(self, f: BinaryIO, block_size: int = BLOCK_SIZE,
                 compress: bool = False) -> None:
        self.f = f
        self.block_size = block_size
        self.compress = compress
        self.buf = bytearray()
        self.payload_crc = 0

    def write(self, data: bytes) -> int:
        self.buf += data
        while len(self.buf) >= self.block_size:
            self._flush_block(self.buf[: self.block_size])
            del self.buf[: self.block_size]
        return len(data)

    def _flush_block(self, block: bytes) -> None:
        self.payload_crc = zlib.crc32(block, self.payload_crc)
        if not self.compress:
            self.f.write(struct.pack("<II", len(block), zlib.crc32(block)))
            self.f.write(block)
            return
        packed = zlib.compress(block, 1)
        stored, flag = ((packed, 1)
                        if len(packed) < len(block) * _MIN_GAIN
                        else (block, 0))
        # frame CRC covers the flag byte too — a flipped flag must fail
        # validation, not reach zlib or stream wrong bytes to the SM
        crc = zlib.crc32(stored, zlib.crc32(bytes([flag])))
        self.f.write(struct.pack("<IIB", len(stored), crc, flag))
        self.f.write(stored)

    def close(self) -> None:
        if self.buf:
            self._flush_block(bytes(self.buf))
            self.buf.clear()
        self.f.write(struct.pack("<I", 0))  # terminator
        self.f.write(struct.pack("<I", self.payload_crc))


class BlockReader:
    """Validating reader over CRC-framed blocks (rwv.go IVReader)."""

    def __init__(self, f: BinaryIO, version: int = V2) -> None:
        self.f = f
        self.version = version
        self.payload_crc = 0
        self.buf = bytearray()
        self.eof = False

    def _fill(self) -> None:
        if self.eof:
            return
        hdr = self.f.read(4)
        (ln,) = struct.unpack("<I", hdr)
        if ln == 0:
            (expect,) = struct.unpack("<I", self.f.read(4))
            if expect != self.payload_crc:
                raise SnapshotFormatError("payload checksum mismatch")
            self.eof = True
            return
        if self.version >= V3:
            crc, flag = struct.unpack("<IB", self.f.read(5))
        else:
            (crc,) = struct.unpack("<I", self.f.read(4))
            flag = 0
        stored = self.f.read(ln)
        expect = (zlib.crc32(stored, zlib.crc32(bytes([flag])))
                  if self.version >= V3 else zlib.crc32(stored))
        if len(stored) != ln or expect != crc:
            raise SnapshotFormatError("block checksum mismatch")
        try:
            block = zlib.decompress(stored) if flag else stored
        except zlib.error as e:
            raise SnapshotFormatError(f"corrupt compressed block: {e}")
        self.payload_crc = zlib.crc32(block, self.payload_crc)
        self.buf += block

    def read(self, n: int = -1) -> bytes:
        if n < 0:
            while not self.eof:
                self._fill()
            out = bytes(self.buf)
            self.buf.clear()
            return out
        while len(self.buf) < n and not self.eof:
            self._fill()
        out = bytes(self.buf[:n])
        del self.buf[:n]
        return out


def write_snapshot(f: BinaryIO, session_data: bytes,
                   write_payload, compress: bool = False,
                   shrunk: bool = False) -> None:
    """write_payload(w) receives a BlockWriter for the SM payload."""
    header = struct.pack("<Q", len(session_data))
    f.write(MAGIC)
    version = (V3 if compress else V2) | (SHRUNK if shrunk else 0)
    f.write(struct.pack("<I", version))
    f.write(struct.pack("<I", zlib.crc32(header)))
    f.write(header)
    f.write(struct.pack("<I", zlib.crc32(session_data)))
    f.write(session_data)
    w = BlockWriter(f, compress=compress)
    write_payload(w)
    w.close()


def read_snapshot(f: BinaryIO):
    """Returns (session_bytes, BlockReader for the payload).  The reader
    carries ``.shrunk`` — True for a shrunken on-disk-SM snapshot whose
    payload was dropped (ShrinkSnapshot, snapshotio.go:462)."""
    if f.read(8) != MAGIC:
        raise SnapshotFormatError("bad magic")
    (version,) = struct.unpack("<I", f.read(4))
    shrunk = bool(version & SHRUNK)
    version &= ~SHRUNK
    if version not in (V2, V3):
        raise SnapshotFormatError(f"unsupported version {version}")
    (hcrc,) = struct.unpack("<I", f.read(4))
    header = f.read(8)
    if zlib.crc32(header) != hcrc:
        raise SnapshotFormatError("header checksum mismatch")
    (slen,) = struct.unpack("<Q", header)
    (scrc,) = struct.unpack("<I", f.read(4))
    session = f.read(slen)
    if zlib.crc32(session) != scrc:
        raise SnapshotFormatError("session checksum mismatch")
    reader = BlockReader(f, version=version)
    reader.shrunk = shrunk
    return session, reader


def shrink_snapshot_file(path: str, fs, session_data: bytes = b"") -> None:
    """Atomically replace a recorded snapshot with its shrunken form: a
    valid container holding ``session_data`` (normally an empty session
    image) and zero payload blocks (snapshotio.go:462 ShrinkSnapshot +
    :486 ReplaceSnapshot)."""
    tmp = path + ".shrinking"
    with fs.open(tmp, "wb") as f:
        write_snapshot(f, session_data, lambda w: None, shrunk=True)
        fs.fsync(f)
    fs.replace(tmp, path)


def is_shrunk_snapshot(path: str, fs) -> bool:
    """Header-only check (snapshotter.go Shrunk)."""
    with fs.open(path, "rb") as f:
        if f.read(8) != MAGIC:
            return False
        raw = f.read(4)
        if len(raw) != 4:
            return False
        (version,) = struct.unpack("<I", raw)
        return bool(version & SHRUNK)
