"""RSM apply layer: session dedup, membership, snapshot IO, managed SMs.

Re-expression of the reference's ``internal/rsm`` (SURVEY §2.5): the layer
between committed raft entries and user state machines."""

from dragonboat_tpu.rsm.session import LRUSession, Session
from dragonboat_tpu.rsm.membership import MembershipStore
from dragonboat_tpu.rsm.statemachine import StateMachine, Task

__all__ = ["LRUSession", "Session", "MembershipStore", "StateMachine", "Task"]
