"""ChunkWriter — stream a snapshot image straight into transport chunks.

Parity with ``internal/rsm/chunkwriter.go``: on-disk state machines stream
their snapshot live to a lagging peer — the image is cut into
``pb.Chunk`` records as it is produced, never materialized as a local
file on the sender.  The byte stream IS the same container
``rsm/snapshotio.write_snapshot`` emits, so the receiver's reassembled
file is recovered through the ordinary ``read_snapshot`` path.

Chunk numbering for streams (the reference marks the tail with
``LastChunkCount`` since the total is unknown up front, chunk.go):
intermediate chunks carry ``chunk_count=0`` ("more to come"); the final
chunk carries ``chunk_count=chunk_id+1`` and the total ``file_size``,
which is what ``Chunk.is_last()`` keys on.
"""

from __future__ import annotations

from dragonboat_tpu import raftpb as pb

STREAM_CHUNK_SIZE = 2 * 1024 * 1024  # snapshot.go:49 snapshotChunkSize


class ChunkWriter:
    """File-like writer that emits pb.Chunk records via ``emit(chunk)``.

    ``message`` (the InstallSnapshot carrying the image's metadata) must
    be assigned before the first flush — the ordinary flow sets it from
    the on-meta callback before any payload bytes are written."""

    def __init__(self, emit, shard_id: int, to_replica: int, from_: int,
                 deployment_id: int, source_address: str = "",
                 chunk_size: int = STREAM_CHUNK_SIZE) -> None:
        self.emit = emit
        self.shard_id = shard_id
        self.to_replica = to_replica
        self.from_ = from_
        self.deployment_id = deployment_id
        self.source_address = source_address
        self.chunk_size = chunk_size
        self.message: pb.Message | None = None
        self.index = 0
        self.term = 0
        self.buf = bytearray()
        self.chunk_id = 0
        self.total = 0
        self.closed = False

    def write(self, data: bytes) -> int:
        self.buf += data
        self.total += len(data)
        while len(self.buf) >= self.chunk_size:
            self._flush(bytes(self.buf[: self.chunk_size]), last=False)
            del self.buf[: self.chunk_size]
        return len(data)

    def _flush(self, block: bytes, last: bool) -> None:
        assert self.message is not None, "stream meta not set before flush"
        self.emit(pb.Chunk(
            shard_id=self.shard_id,
            replica_id=self.to_replica,
            from_=self.from_,
            chunk_id=self.chunk_id,
            chunk_count=(self.chunk_id + 1) if last else 0,
            chunk_size=len(block),
            file_size=self.total if last else 0,
            index=self.index,
            term=self.term,
            deployment_id=self.deployment_id,
            source_address=self.source_address if self.chunk_id == 0 else "",
            data=block,
            message=self.message if self.chunk_id == 0 else None,
        ))
        self.chunk_id += 1

    def close(self) -> None:
        """Emit the tail chunk (always — a last chunk is what completes
        the transfer on the receiver, even for an empty payload)."""
        if self.closed:
            return
        self.closed = True
        self._flush(bytes(self.buf), last=True)
        self.buf.clear()
