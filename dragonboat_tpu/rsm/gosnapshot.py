"""The reference's V2 snapshot container format (write + validate).

A Go receiver validates EVERY snapshot chunk stream against this layout
(chunk.go:214 -> rsm.NewSnapshotValidator), so anything the go wire
ships as a snapshot image must be bytes a Go fleet accepts.  Layout
(internal/rsm/snapshotio.go saveHeader + rwv.go BlockWriter):

    [ header region: 1024 bytes                                   ]
      u64 LE header_len | SnapshotHeader protobuf | zero padding
    [ payload blocks: <=2 MiB each, 4-byte CRC32-IEEE appended    ]
    [ tail: u64 LE total_block_bytes | 8-byte magic               ]

SnapshotHeader (raftpb/snapshotheader.go MarshalTo): session_size(1),
data_store_size(2), unreliable_time(3), git_version(4, unconditional),
header_checksum(5, emitted once computed), payload_checksum(6),
checksum_type(7), version(8), compression_type(9).  HeaderChecksum is
the CRC32 of the header marshaled WITHOUT it; PayloadChecksum is the
CRC32 of the concatenated block CRCs (rwv.go processNewBlock feeds fh).

Used today for the witness image (GetWitnessSnapshot parity — payload
is the reference's empty LRU session bank: u64 LE 4096 | u64 LE 0);
``validate_v2`` reimplements the reference's v2validator so tests can
prove emitted bytes pass the exact algorithm a Go receiver runs.
"""

from __future__ import annotations

import struct
import zlib

HEADER_SIZE = 1024                        # settings.SnapshotHeaderSize
BLOCK_SIZE = 2 * 1024 * 1024              # settings.SnapshotChunkSize
CHECKSUM_SIZE = 4
TAIL_SIZE = 16
MAGIC = bytes([0x3F, 0x5B, 0xCB, 0xF1, 0xFA, 0xBA, 0x81, 0x9F])
LRU_MAX_SESSION_COUNT = 4096              # settings hard default
V2 = 2
CRC32IEEE = 0
NO_COMPRESSION = 0


def _uvarint(out: bytearray, x: int) -> None:
    while x >= 0x80:
        out.append((x & 0x7F) | 0x80)
        x >>= 7
    out.append(x)


def _marshal_header(unreliable_time: int, payload_checksum: bytes,
                    header_checksum: bytes | None) -> bytes:
    """snapshotheader.go MarshalTo — unconditional scalar emit, the two
    checksum fields only when present."""
    out = bytearray()
    out.append(0x08)
    _uvarint(out, 0)                      # session_size (writer leaves 0)
    out.append(0x10)
    _uvarint(out, 0)                      # data_store_size
    out.append(0x18)
    _uvarint(out, unreliable_time)
    out.append(0x22)
    _uvarint(out, 0)                      # git_version: empty string
    if header_checksum is not None:
        out.append(0x2A)
        _uvarint(out, len(header_checksum))
        out += header_checksum
    out.append(0x32)
    _uvarint(out, len(payload_checksum))
    out += payload_checksum
    out.append(0x38)
    _uvarint(out, CRC32IEEE)              # checksum_type
    out.append(0x40)
    _uvarint(out, V2)                     # version
    out.append(0x48)
    _uvarint(out, NO_COMPRESSION)         # compression_type
    return bytes(out)


def write_v2(payload: bytes, unreliable_time: int = 1) -> bytes:
    """The complete container for ``payload`` (block split + CRCs + tail
    + header), as newSnapshotWriter/Close produce it."""
    blocks = bytearray()
    crc_cat = bytearray()                 # fh: concatenated block CRCs
    total = 0
    for off in range(0, len(payload), BLOCK_SIZE):
        block = payload[off:off + BLOCK_SIZE]
        crc = struct.pack("<I", zlib.crc32(block))
        blocks += block + crc
        crc_cat += crc
        total += len(block) + CHECKSUM_SIZE
    if not payload:                       # Close flushes even empty
        pass
    tail = struct.pack("<Q", total) + MAGIC
    payload_checksum = struct.pack("<I", zlib.crc32(bytes(crc_cat)))
    # HeaderChecksum: CRC32 of the header marshaled WITHOUT it
    pre = _marshal_header(unreliable_time, payload_checksum, None)
    hc = struct.pack("<I", zlib.crc32(pre))
    hdr = _marshal_header(unreliable_time, payload_checksum, hc)
    if len(hdr) > HEADER_SIZE - 8:
        raise ValueError("snapshot header too large")
    region = struct.pack("<Q", len(hdr)) + hdr
    region += bytes(HEADER_SIZE - len(region))
    return region + bytes(blocks) + tail


def empty_lru_session() -> bytes:
    """rsm.GetEmptyLRUSession: max count + zero sessions."""
    return struct.pack("<QQ", LRU_MAX_SESSION_COUNT, 0)


def witness_image() -> bytes:
    """rsm.GetWitnessSnapshot (snapshotio.go:139): a well-formed V2
    container whose payload is the empty session bank."""
    return write_v2(empty_lru_session())


def validate_v2(data: bytes) -> bool:
    """The reference receiver's validation, reimplemented from
    rwv.go v2validator (AddChunk over the whole image + Validate):
    header length sane, every block's CRC matches, tail magic + total
    correct.  Exists so tests prove emitted bytes pass the EXACT
    algorithm chunk.go:214 runs on an inbound stream."""
    if len(data) < HEADER_SIZE:
        return False
    (hlen,) = struct.unpack_from("<Q", data, 0)
    if hlen > HEADER_SIZE - 8:
        return False
    body = data[HEADER_SIZE:]
    if len(body) < TAIL_SIZE:
        return False
    tail, blocks = body[-TAIL_SIZE:], body[:-TAIL_SIZE]
    if tail[8:] != MAGIC:
        return False
    (total,) = struct.unpack_from("<Q", tail, 0)
    if total != len(blocks):
        return False
    i = 0
    step = BLOCK_SIZE + CHECKSUM_SIZE
    while len(blocks) - i > step:
        if not _block_ok(blocks[i:i + step]):
            return False
        i += step
    rest = blocks[i:]
    return len(rest) == 0 or _block_ok(rest)


def _block_ok(block: bytes) -> bool:
    if len(block) <= CHECKSUM_SIZE:
        return False
    payload, crc = block[:-CHECKSUM_SIZE], block[-CHECKSUM_SIZE:]
    return struct.pack("<I", zlib.crc32(payload)) == crc
