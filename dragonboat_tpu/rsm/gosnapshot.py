"""The reference's V2 snapshot container format (write + validate).

A Go receiver validates EVERY snapshot chunk stream against this layout
(chunk.go:214 -> rsm.NewSnapshotValidator), so anything the go wire
ships as a snapshot image must be bytes a Go fleet accepts.  Layout
(internal/rsm/snapshotio.go saveHeader + rwv.go BlockWriter):

    [ header region: 1024 bytes                                   ]
      u64 LE header_len | SnapshotHeader protobuf | zero padding
    [ payload blocks: <=2 MiB each, 4-byte CRC32-IEEE appended    ]
    [ tail: u64 LE total_block_bytes | 8-byte magic               ]

SnapshotHeader (raftpb/snapshotheader.go MarshalTo): session_size(1),
data_store_size(2), unreliable_time(3), git_version(4, unconditional),
header_checksum(5, emitted once computed), payload_checksum(6),
checksum_type(7), version(8), compression_type(9).  HeaderChecksum is
the CRC32 of the header marshaled WITHOUT it; PayloadChecksum is the
CRC32 of the concatenated block CRCs (rwv.go processNewBlock feeds fh).

Used today for the witness image (GetWitnessSnapshot parity — payload
is the reference's empty LRU session bank: u64 LE 4096 | u64 LE 0);
``validate_v2`` reimplements the reference's v2validator so tests can
prove emitted bytes pass the exact algorithm a Go receiver runs.
"""

from __future__ import annotations

import struct
import zlib

HEADER_SIZE = 1024                        # settings.SnapshotHeaderSize
BLOCK_SIZE = 2 * 1024 * 1024              # settings.SnapshotChunkSize
CHECKSUM_SIZE = 4
TAIL_SIZE = 16
MAGIC = bytes([0x3F, 0x5B, 0xCB, 0xF1, 0xFA, 0xBA, 0x81, 0x9F])
LRU_MAX_SESSION_COUNT = 4096              # settings hard default
V2 = 2
CRC32IEEE = 0
NO_COMPRESSION = 0


def _uvarint(out: bytearray, x: int) -> None:
    while x >= 0x80:
        out.append((x & 0x7F) | 0x80)
        x >>= 7
    out.append(x)


def _marshal_header(unreliable_time: int, payload_checksum: bytes,
                    header_checksum: bytes | None) -> bytes:
    """snapshotheader.go MarshalTo — unconditional scalar emit, the two
    checksum fields only when present."""
    out = bytearray()
    out.append(0x08)
    _uvarint(out, 0)                      # session_size (writer leaves 0)
    out.append(0x10)
    _uvarint(out, 0)                      # data_store_size
    out.append(0x18)
    _uvarint(out, unreliable_time)
    out.append(0x22)
    _uvarint(out, 0)                      # git_version: empty string
    if header_checksum is not None:
        out.append(0x2A)
        _uvarint(out, len(header_checksum))
        out += header_checksum
    out.append(0x32)
    _uvarint(out, len(payload_checksum))
    out += payload_checksum
    out.append(0x38)
    _uvarint(out, CRC32IEEE)              # checksum_type
    out.append(0x40)
    _uvarint(out, V2)                     # version
    out.append(0x48)
    _uvarint(out, NO_COMPRESSION)         # compression_type
    return bytes(out)


def write_v2(payload: bytes, unreliable_time: int = 1) -> bytes:
    """The complete container for ``payload`` (block split + CRCs + tail
    + header), as newSnapshotWriter/Close produce it."""
    blocks = bytearray()
    crc_cat = bytearray()                 # fh: concatenated block CRCs
    total = 0
    for off in range(0, len(payload), BLOCK_SIZE):
        block = payload[off:off + BLOCK_SIZE]
        crc = struct.pack("<I", zlib.crc32(block))
        blocks += block + crc
        crc_cat += crc
        total += len(block) + CHECKSUM_SIZE
    if not payload:                       # Close flushes even empty
        pass
    tail = struct.pack("<Q", total) + MAGIC
    payload_checksum = struct.pack("<I", zlib.crc32(bytes(crc_cat)))
    # HeaderChecksum: CRC32 of the header marshaled WITHOUT it
    pre = _marshal_header(unreliable_time, payload_checksum, None)
    hc = struct.pack("<I", zlib.crc32(pre))
    hdr = _marshal_header(unreliable_time, payload_checksum, hc)
    if len(hdr) > HEADER_SIZE - 8:
        raise ValueError("snapshot header too large")
    region = struct.pack("<Q", len(hdr)) + hdr
    region += bytes(HEADER_SIZE - len(region))
    return region + bytes(blocks) + tail


def empty_lru_session() -> bytes:
    """rsm.GetEmptyLRUSession: max count + zero sessions."""
    return struct.pack("<QQ", LRU_MAX_SESSION_COUNT, 0)


def witness_image() -> bytes:
    """rsm.GetWitnessSnapshot (snapshotio.go:139): a well-formed V2
    container whose payload is the empty session bank."""
    return write_v2(empty_lru_session())


def validate_v2(data: bytes) -> bool:
    """The reference receiver's validation, reimplemented from
    rwv.go v2validator (AddChunk over the whole image + Validate):
    header length sane, every block's CRC matches, tail magic + total
    correct.  Exists so tests prove emitted bytes pass the EXACT
    algorithm chunk.go:214 runs on an inbound stream."""
    if len(data) < HEADER_SIZE:
        return False
    (hlen,) = struct.unpack_from("<Q", data, 0)
    if hlen > HEADER_SIZE - 8:
        return False
    body = data[HEADER_SIZE:]
    if len(body) < TAIL_SIZE:
        return False
    tail, blocks = body[-TAIL_SIZE:], body[:-TAIL_SIZE]
    if tail[8:] != MAGIC:
        return False
    (total,) = struct.unpack_from("<Q", tail, 0)
    if total != len(blocks):
        return False
    i = 0
    step = BLOCK_SIZE + CHECKSUM_SIZE
    while len(blocks) - i > step:
        if not _block_ok(blocks[i:i + step]):
            return False
        i += step
    rest = blocks[i:]
    return len(rest) == 0 or _block_ok(rest)


def _block_ok(block: bytes) -> bool:
    if len(block) <= CHECKSUM_SIZE:
        return False
    payload, crc = block[:-CHECKSUM_SIZE], block[-CHECKSUM_SIZE:]
    return struct.pack("<I", zlib.crc32(payload)) == crc


def read_v2(data: bytes) -> bytes:
    """Extract the payload stream from a reference V2 container
    (SnapshotReader semantics: skip the 1024-byte header region,
    de-block verifying each CRC, strip the tail).  Raises ValueError on
    any mismatch."""
    if not validate_v2(data):
        raise ValueError("not a valid reference V2 snapshot container")
    blocks = data[HEADER_SIZE:-TAIL_SIZE]
    out = bytearray()
    step = BLOCK_SIZE + CHECKSUM_SIZE
    i = 0
    while i < len(blocks):
        block = blocks[i:i + step]
        out += block[:-CHECKSUM_SIZE]
        i += step
    return bytes(out)


# ---------------------------------------------------------------------------
# session-bank translation (lrusession.go save/load <-> rsm/session.py)
# ---------------------------------------------------------------------------


def go_session_bank_decode(payload: bytes) -> tuple[list, int]:
    """Parse the Go LRU session bank at the head of a payload stream:
    ``u64 max | u64 count | count x (u64 json_len | Session JSON)``
    (lrusession.go save + session.go save).  Returns ([(client_id,
    responded_to, {series: (value, data_bytes)})...], bytes_consumed)."""
    import base64
    import json

    if len(payload) < 16:
        raise ValueError("go session bank: truncated")
    count = struct.unpack_from("<Q", payload, 8)[0]
    off = 16
    sessions = []
    for _ in range(count):
        if off + 8 > len(payload):
            raise ValueError("go session bank: truncated session")
        (jlen,) = struct.unpack_from("<Q", payload, off)
        off += 8
        rec = json.loads(payload[off:off + jlen].decode())
        off += jlen
        hist = {}
        for series, res in (rec.get("History") or {}).items():
            d = res.get("Data")
            hist[int(series)] = (
                int(res.get("Value") or 0),
                base64.b64decode(d) if d else b"",
            )
        sessions.append((int(rec.get("ClientID") or 0),
                         int(rec.get("RespondedUpTo") or 0), hist))
    return sessions, off


def go_session_bank_encode(sessions: list) -> bytes:
    """The inverse: our session records -> the Go bank bytes (JSON keys
    as Go's json.Marshal of rsm.Session emits them; Go's Unmarshal is
    order-insensitive)."""
    import base64
    import json

    out = bytearray(struct.pack("<QQ", LRU_MAX_SESSION_COUNT,
                                len(sessions)))
    for client_id, responded_to, hist in sessions:
        rec = {
            "History": {
                str(series): {
                    "Value": value,
                    "Data": (base64.b64encode(data).decode()
                             if data else None),
                }
                for series, (value, data) in sorted(hist.items())
            },
            "ClientID": client_id,
            "RespondedUpTo": responded_to,
        }
        blob = json.dumps(rec, separators=(",", ":")).encode()
        out += struct.pack("<Q", len(blob))
        out += blob
    return bytes(out)


# ---------------------------------------------------------------------------
# whole-image transcode (regular SM snapshots, file-based catchup)
# ---------------------------------------------------------------------------


def native_image_to_go(data: bytes) -> bytes:
    """Our DBTPUSNP container -> the reference container: sessions
    re-banked into the Go format, the user payload carried verbatim.
    The result is what a Go peer's validator AND its recovery path
    expect for a regular-SM snapshot image."""
    import io

    from dragonboat_tpu.rsm.session import LRUSession
    from dragonboat_tpu.rsm.snapshotio import read_snapshot

    session_bytes, reader = read_snapshot(io.BytesIO(data))
    if getattr(reader, "shrunk", False):
        # a shrunken image's empty payload is a bookkeeping artifact,
        # not state; rebuilding it as a full reference container would
        # bypass the receiver's shrunk guards and wipe the SM
        raise ValueError("shrunken snapshot cannot cross the go wire")
    user = b"".join(iter(lambda: reader.read(1 << 20), b""))
    lru = LRUSession.load(io.BytesIO(session_bytes)) if session_bytes \
        else LRUSession()
    sessions = [
        (s.client_id, s.responded_to,
         {k: (r.value, r.data) for k, r in s.history.items()})
        for s in lru.sessions.values()
    ]
    return write_v2(go_session_bank_encode(sessions) + user)


def go_image_to_native(data: bytes) -> bytes:
    """The reference container -> our DBTPUSNP container: the Go
    session bank becomes our LRUSession image, the user payload is
    carried verbatim — so a Go-written snapshot recovers a TPU replica
    through the ordinary read_snapshot path (sessions included: dedup
    state survives the fleet boundary)."""
    import io

    from dragonboat_tpu.rsm.session import LRUSession, Session
    from dragonboat_tpu.statemachine import Result
    from dragonboat_tpu.rsm.snapshotio import write_snapshot

    payload = read_v2(data)
    sessions, consumed = go_session_bank_decode(payload)
    user = payload[consumed:]
    lru = LRUSession()
    for client_id, responded_to, hist in sessions:
        s = Session(client_id=client_id, responded_to=responded_to)
        for series, (value, d) in hist.items():
            s.history[series] = Result(value=value, data=d)
        lru.sessions[client_id] = s
    sbuf = io.BytesIO()
    lru.save(sbuf)
    out = io.BytesIO()
    write_snapshot(out, sbuf.getvalue(), lambda w: w.write(user))
    return out.getvalue()


def sniff_v2_file(path: str) -> bool:
    """Cheap reference-container sniff without reading the image:
    first 8 bytes (header length — our DBTPUSNP magic reads as an
    impossible value) + last 8 (tail magic)."""
    import os

    try:
        size = os.path.getsize(path)
        if size < HEADER_SIZE + TAIL_SIZE:
            return False
        with open(path, "rb") as f:
            head = f.read(8)
            f.seek(-8, 2)
            tail = f.read(8)
    except OSError:
        return False
    (hlen,) = struct.unpack("<Q", head)
    return hlen <= HEADER_SIZE - 8 and tail == MAGIC


class GoStreamTranscoder:
    """Streaming our-container -> reference-container transcode (the
    live-stream path: rsm/chunkwriter.py produces the repo container
    progressively; a real Go receiver validates reference blocks as
    they arrive, so the byte stream must be reference-shaped IN FLIGHT).

    Feed container bytes with ``write``; reference-file fragments come
    out through ``out(bytes)`` in validator-aligned units (the 1024-byte
    header first, then 2 MiB CRC'd blocks, then the 16-byte tail at
    ``close``).  Sessions are re-banked go-side; the user payload passes
    through verbatim.  Mirrors what chunkwriter.go emits for a streamed
    Go snapshot, dummy payload checksum included."""

    def __init__(self, out) -> None:
        self.out = out
        self.buf = bytearray()
        self.state = "preamble"          # -> session -> blocks -> done
        self.version = 0
        self.slen = 0
        self.scrc = 0
        self.payload_crc = 0
        # go-side block framer state
        self._go_block = bytearray()
        self._started = False

    # -- go-side emission ------------------------------------------------

    def _emit_header(self) -> None:
        # chunkwriter.go getHeader: streamed headers carry a DUMMY
        # payload checksum ({0,0,0,0}) since the total is unknown
        pre = _marshal_header(1, b"\x00\x00\x00\x00", None)
        hc = struct.pack("<I", zlib.crc32(pre))
        hdr = _marshal_header(1, b"\x00\x00\x00\x00", hc)
        region = struct.pack("<Q", len(hdr)) + hdr
        region += bytes(HEADER_SIZE - len(region))
        self.out(region)
        self._started = True

    def _go_write(self, data: bytes) -> None:
        self._go_block += data
        while len(self._go_block) >= BLOCK_SIZE:
            block = bytes(self._go_block[:BLOCK_SIZE])
            del self._go_block[:BLOCK_SIZE]
            self.out(block + struct.pack("<I", zlib.crc32(block)))

    def _go_close(self) -> None:
        if self._go_block:
            block = bytes(self._go_block)
            self._go_block.clear()
            self.out(block + struct.pack("<I", zlib.crc32(block)))
        self.out(struct.pack("<Q", self._emitted_block_bytes) + MAGIC)

    # -- our-side incremental parse --------------------------------------

    def write(self, data: bytes) -> None:
        self.buf += data
        progressed = True
        while progressed:
            progressed = False
            if self.state == "preamble":
                # MAGIC(8) version(4) hcrc(4) header(8) scrc(4)
                if len(self.buf) < 28:
                    return
                if bytes(self.buf[:8]) != b"DBTPUSNP":
                    raise ValueError("not a repo snapshot container")
                (self.version,) = struct.unpack_from("<I", self.buf, 8)
                if self.version & 0x100:
                    # a shrunken image's empty payload is bookkeeping,
                    # not state — transcoding it would bypass the
                    # receiver's shrunk guards and wipe the SM
                    raise ValueError(
                        "shrunken snapshot cannot cross the go wire")
                if self.version not in (2, 3):
                    raise ValueError(
                        f"unsupported container version {self.version}")
                (self.slen,) = struct.unpack_from("<Q", self.buf, 16)
                (self.scrc,) = struct.unpack_from("<I", self.buf, 24)
                del self.buf[:28]
                self.state = "session"
                progressed = True
            elif self.state == "session":
                if len(self.buf) < self.slen:
                    return
                session = bytes(self.buf[:self.slen])
                del self.buf[:self.slen]
                if zlib.crc32(session) != self.scrc:
                    raise ValueError("session checksum mismatch")
                import io

                from dragonboat_tpu.rsm.session import LRUSession

                lru = (LRUSession.load(io.BytesIO(session))
                       if session else LRUSession())
                sessions = [
                    (s.client_id, s.responded_to,
                     {k: (r.value, r.data) for k, r in s.history.items()})
                    for s in lru.sessions.values()
                ]
                self._emitted_block_bytes = 0
                out0 = self.out

                def counting(b, _o=out0, _s=self):
                    if _s._started:
                        _s._emitted_block_bytes += len(b)
                    _o(b)

                self.out = counting
                self._emit_header()
                self._go_write(go_session_bank_encode(sessions))
                self.state = "blocks"
                progressed = True
            elif self.state == "blocks":
                if len(self.buf) < 4:
                    return
                (ln,) = struct.unpack_from("<I", self.buf, 0)
                if ln == 0:                 # terminator + payload crc
                    if len(self.buf) < 8:
                        return
                    (expect,) = struct.unpack_from("<I", self.buf, 4)
                    if expect != self.payload_crc:
                        raise ValueError("payload checksum mismatch")
                    del self.buf[:8]
                    self.state = "done"
                    return
                hdr = 9 if self.version >= 3 else 8
                if len(self.buf) < hdr + ln:
                    return
                if self.version >= 3:
                    crc, flag = struct.unpack_from("<IB", self.buf, 4)
                else:
                    (crc,) = struct.unpack_from("<I", self.buf, 4)
                    flag = 0
                stored = bytes(self.buf[hdr:hdr + ln])
                del self.buf[:hdr + ln]
                expect = (zlib.crc32(stored, zlib.crc32(bytes([flag])))
                          if self.version >= 3 else zlib.crc32(stored))
                if expect != crc:
                    raise ValueError("block checksum mismatch")
                block = zlib.decompress(stored) if flag else stored
                self.payload_crc = zlib.crc32(block, self.payload_crc)
                self._go_write(block)
                progressed = True
            else:
                return

    def close(self) -> None:
        if self.state != "done":
            raise ValueError(
                f"stream ended mid-{self.state} (truncated container)")
        self._go_close()
