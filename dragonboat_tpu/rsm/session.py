"""Raft-thesis client sessions: LRU of per-client cached responses.

Parity with ``internal/rsm/session.go``/``lrusession.go``: an LRU (capacity
LRU_MAX_SESSION_COUNT = 4096, internal/settings/hard.go) of
client_id → {series_id → cached Result}; duplicate series return the cached
response instead of re-applying; sessions serialize into every snapshot
(lrusession.go:93-152).
"""

from __future__ import annotations

import struct
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import BinaryIO

from dragonboat_tpu import raftpb as pb
from dragonboat_tpu.statemachine import Result

LRU_MAX_SESSION_COUNT = 4096


@dataclass
class Session:
    client_id: int
    responded_to: int = 0
    history: dict[int, Result] = field(default_factory=dict)

    def add_response(self, series_id: int, result: Result) -> None:
        if series_id in self.history:
            raise AssertionError("adding a duplicate response")
        self.history[series_id] = result

    def get_response(self, series_id: int) -> tuple[Result, bool]:
        r = self.history.get(series_id)
        return (r if r is not None else Result()), r is not None

    def has_responded(self, series_id: int) -> bool:
        return series_id <= self.responded_to

    def clear_to(self, responded_to: int) -> None:
        """Drop cached responses the client has acknowledged
        (session.go clearTo)."""
        if responded_to <= self.responded_to:
            return
        self.responded_to = responded_to
        for k in [k for k in self.history if k <= responded_to]:
            del self.history[k]

    # -- snapshot serialization -----------------------------------------

    def save(self, w: BinaryIO) -> None:
        w.write(struct.pack("<QQI", self.client_id, self.responded_to,
                            len(self.history)))
        for series_id in sorted(self.history):
            r = self.history[series_id]
            w.write(struct.pack("<QQI", series_id, r.value, len(r.data)))
            w.write(r.data)

    @staticmethod
    def load(r: BinaryIO) -> "Session":
        client_id, responded_to, n = struct.unpack("<QQI", r.read(20))
        s = Session(client_id=client_id, responded_to=responded_to)
        for _ in range(n):
            series_id, value, dlen = struct.unpack("<QQI", r.read(20))
            s.history[series_id] = Result(value=value, data=r.read(dlen))
        return s


class LRUSession:
    """The replicated session store (lrusession.go)."""

    def __init__(self, capacity: int = LRU_MAX_SESSION_COUNT) -> None:
        self.capacity = capacity
        self.sessions: OrderedDict[int, Session] = OrderedDict()

    def register_client_id(self, client_id: int) -> Result:
        """RegisterClientID entry — creates (or resets) the session."""
        self.sessions[client_id] = Session(client_id=client_id)
        self.sessions.move_to_end(client_id)
        self._evict()
        return Result(value=client_id)

    def unregister_client_id(self, client_id: int) -> Result:
        if client_id in self.sessions:
            del self.sessions[client_id]
            return Result(value=client_id)
        return Result(value=0)

    def get_session(self, client_id: int) -> Session | None:
        s = self.sessions.get(client_id)
        if s is not None:
            self.sessions.move_to_end(client_id)
        return s

    def _evict(self) -> None:
        while len(self.sessions) > self.capacity:
            self.sessions.popitem(last=False)

    # -- dedup entry point (statemachine.go update path) ------------------

    def update_required(self, e: pb.Entry) -> tuple[Result, bool, bool, Session | None]:
        """Returns (cached_result, has_cached, update_needed, session).

        Mirrors rsm's session lookup before applying a session-managed
        entry: an unknown session rejects the proposal; an already-responded
        series is a no-op; a cached series returns the cached result."""
        s = self.get_session(e.client_id)
        if s is None:
            return Result(), False, False, None
        if s.has_responded(e.series_id):
            return Result(), False, False, s
        r, ok = s.get_response(e.series_id)
        if ok:
            return r, True, False, s
        return Result(), False, True, s

    # -- snapshot serialization -----------------------------------------

    def save(self, w: BinaryIO) -> None:
        w.write(struct.pack("<I", len(self.sessions)))
        for client_id in self.sessions:  # LRU order preserved
            self.sessions[client_id].save(w)

    @staticmethod
    def load(r: BinaryIO, capacity: int = LRU_MAX_SESSION_COUNT) -> "LRUSession":
        (n,) = struct.unpack("<I", r.read(4))
        lru = LRUSession(capacity)
        for _ in range(n):
            s = Session.load(r)
            lru.sessions[s.client_id] = s
        return lru
