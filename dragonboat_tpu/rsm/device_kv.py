"""DeviceKV — the device-native state machine (IDeviceStateMachine).

The north star's rsm-apply kernel (BASELINE.json; SURVEY §7.4 "in-memory
KV state machine applied as a fused on-device kernel"): committed entry
lanes are applied to a per-shard open-addressing hash table that lives in
HBM, vmapped across the ``[G]`` shard axis — the device analog of the
reference's in-memory KV RSM (internal/tests/kvtest.go) that its
benchmarks apply on the host.

Design constraints shared with the raft kernel (core/kernel.py):

- scatter-free: every table write is a one-hot select (vmapped sub-32-bit
  scatters miscompile on TPU; selects vectorize better anyway);
- fixed shapes: table capacity and probe depth are static; a full probe
  window rejects the write (result -1) instead of growing;
- int32 lanes: keys/values are i32 (the bench's 16-byte payloads are
  (key, value) pairs; bigger payloads stay host-side by design — the
  device holds what the data path needs).

Keys are stored +1 so 0 stays the empty sentinel.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp

from dragonboat_tpu.core.params import splitmix32
from dragonboat_tpu.statemachine import IDeviceStateMachine

I32 = jnp.int32


@dataclasses.dataclass(frozen=True)
class DeviceKV(IDeviceStateMachine):
    """Fixed-capacity linear-probe hash table per shard.

    Frozen/hashable so it can ride as a jit static argument (the bench's
    run_steps_sm caches its executable on (kp, replicas, kv, iters)).
    Keys must be >= 0 (the +1 storage offset reserves 0 as the empty
    sentinel); negative keys are rejected at the apply boundary and
    return None from lookup."""

    table_cap: int = 1024
    probe_depth: int = 8
    # hash_keys=False direct-maps key -> slot key & (cap-1): with a key
    # space <= table_cap no two keys share a home slot, so inserts can
    # never be rejected — the bench uses this for its strict no-loss
    # contract; hashed mode serves arbitrary key spaces (with -1 rejects
    # when a probe window fills, as any fixed-capacity table must)
    hash_keys: bool = True
    # route applies through the pallas block kernel
    # (rsm/device_kv_pallas.py): the table block stays VMEM-resident
    # across the whole apply window instead of streaming [G, T] through
    # HBM per command lane.  Bit-identical results either way
    # (tests/test_device_kv_pallas.py); interpret-mode off-TPU
    use_pallas: bool = False

    def __post_init__(self) -> None:
        assert self.table_cap & (self.table_cap - 1) == 0, \
            "table_cap must be 2^n"

    def init_state(self, num_shards: int) -> dict:
        T = self.table_cap
        return {
            "keys": jnp.zeros((num_shards, T), I32),   # stored key+1; 0=empty
            "vals": jnp.zeros((num_shards, T), I32),
            "count": jnp.zeros((num_shards,), I32),
        }

    # -- apply -----------------------------------------------------------

    def _probe_slots(self, key):
        if self.hash_keys:
            h = (splitmix32(key.astype(jnp.uint32)).astype(I32)
                 & (self.table_cap - 1))
        else:
            h = key & (self.table_cap - 1)
        return (h + jnp.arange(self.probe_depth, dtype=I32)) & (self.table_cap - 1)

    def _put_one(self, keys, vals, count, key, val, valid):
        """Insert/update one (key, val); scatter-free one-hot write."""
        slots = self._probe_slots(key)                       # [D]
        pk = keys[slots]                                     # [D]
        hit = pk == key + 1
        empty = pk == 0
        usable = hit | empty
        found = jnp.any(usable)
        # first matching slot wins; else first empty (linear probe order)
        first_hit = jnp.argmax(hit)
        pick = jnp.where(jnp.any(hit), first_hit, jnp.argmax(empty))
        slot = slots[pick]
        do = valid & found & (key >= 0)
        is_new = do & ~jnp.any(hit)
        oh = (jnp.arange(keys.shape[0], dtype=I32) == slot) & do
        keys = jnp.where(oh, key + 1, keys)
        vals = jnp.where(oh, val, vals)
        count = count + jnp.where(is_new, 1, 0)
        # ok is a separate status flag: payloads are arbitrary i32, so a
        # stored value of -1 must stay distinguishable from a reject
        ok = do
        result = jnp.where(do, val, -1)
        return keys, vals, count, result, ok

    def apply_kernel(self, sm_state: dict, cmd_lanes, valid_mask):
        """Apply ``[G, B, 2]`` (key, value) command lanes where
        ``valid_mask [G, B]`` holds; returns (new_state,
        (results [G, B] i32, ok [G, B] bool)) — ok False on a valid lane
        means the probe window was full and the write was rejected.
        Lanes apply in order (later writes to the same key win), matching
        sequential host apply semantics."""

        def per_shard(keys, vals, count, cmds, valid):
            def body(carry, x):
                k, v, c = carry
                cmd, lane_ok = x
                k, v, c, r, okf = self._put_one(k, v, c, cmd[0], cmd[1],
                                                lane_ok)
                return (k, v, c), (r, okf)

            (keys, vals, count), (results, ok) = jax.lax.scan(
                body, (keys, vals, count), (cmds, valid))
            return keys, vals, count, results, ok

        keys, vals, count, results, ok = jax.vmap(per_shard)(
            sm_state["keys"], sm_state["vals"], sm_state["count"],
            cmd_lanes, valid_mask)
        return {"keys": keys, "vals": vals, "count": count}, (results, ok)

    def apply_kernel_range(self, sm_state: dict, first_key, vals, valid_mask):
        """One-pass apply of a CONTIGUOUS key window to a direct-mapped
        table — the natural shape of raft apply (a consecutive log window
        landing in an array-backed SM).  Lane j writes key
        ``(first_key + j) & (table_cap - 1)`` with ``vals[:, j]``; with
        window width <= table_cap the keys are distinct, so the whole
        ``[G, B]`` window lands in one vectorized pass (each table slot
        GATHERS its lane — the same scatter-free trick as the raft
        kernel's replicate append) instead of B serial iterations.

        Bit-identical to ``apply_kernel`` driven with the same
        (key, value) lanes on a ``hash_keys=False`` table."""
        assert not self.hash_keys, "range apply requires hash_keys=False"
        T = self.table_cap
        B = vals.shape[1]
        assert B <= T, "window wider than the table aliases keys"
        slots = jnp.arange(T, dtype=I32)[None, :]            # [1, T]
        rel = (slots - first_key[:, None]) & (T - 1)         # [G, T]
        lane_of_slot = jnp.minimum(rel, B - 1)
        lane_valid = jnp.take_along_axis(
            valid_mask.astype(I32), lane_of_slot, axis=1).astype(bool)
        written = (rel < B) & lane_valid                     # [G, T]
        new_vals = jnp.take_along_axis(vals, lane_of_slot, axis=1)
        was_empty = sm_state["keys"] == 0
        # a direct-mapped slot's key IS the slot index
        out_keys = jnp.where(written, slots + 1, sm_state["keys"])
        out_vals = jnp.where(written, new_vals, sm_state["vals"])
        count = sm_state["count"] + jnp.sum(
            (written & was_empty).astype(I32), axis=-1)
        results = jnp.where(valid_mask, vals, -1)
        return ({"keys": out_keys, "vals": out_vals, "count": count},
                (results, valid_mask))

    # -- reads -----------------------------------------------------------

    @functools.partial(jax.jit, static_argnums=0)
    def _lookup_dev(self, keys_row, key):
        slots = self._probe_slots(jnp.asarray(key, I32))
        pk = keys_row[slots]
        hit = (pk == key + 1) & (key >= 0)
        return jnp.any(hit), slots[jnp.argmax(hit)]

    def lookup(self, sm_state: dict, shard_slot: int, query: object):
        """Host-callable point lookup (StaleRead analog)."""
        key = int(query)  # type: ignore[arg-type]
        found, slot = self._lookup_dev(sm_state["keys"][shard_slot], key)
        if not bool(found):
            return None
        return int(sm_state["vals"][shard_slot, slot])
