"""Encoded-entry payload envelope (entry compression).

Parity with the reference's ``internal/rsm/encoded.go``: a proposal's
payload is wrapped at propose time into an ENCODED entry whose Cmd is

    | header (1 byte)              | body                        |
    | Version 4b | Compression 3b | Session 1b |

with Version 0, Session unset (the reference never sets it on the
propose path, ``request.go:1094``), and the body being the raw payload
(NoCompression), a snappy BLOCK (the golang/snappy block format the
reference uses via ``internal/utils/dio/io.go:40-130``), or — a repo
extension — a zlib stream (flag value outside the reference's range;
fast C-backed path for fleets that don't need Go interop).

The snappy block codec here is an independent implementation of the
public snappy format spec (uvarint decoded-length preamble, then
literal/copy elements); the encoder always emits copy-2 elements
(1-64 byte matches, 16-bit offsets), which every conforming decoder —
including the Go fleet's — accepts.
"""

from __future__ import annotations

from dragonboat_tpu import raftpb as pb

EE_HEADER_SIZE = 1
EE_V0 = 0 << 4
EE_NO_COMPRESSION = 0 << 1
EE_SNAPPY = 1 << 1
EE_ZLIB = 2 << 1           # repo extension: NOT understood by Go fleets
_VER_MASK = 0x0F << 4
_CT_MASK = 0x07 << 1
_SESSION_MASK = 0x01

# config.CompressionType spellings accepted by Config.entry_compression
NO_COMPRESSION = "no-compression"
SNAPPY = "snappy"
ZLIB = "zlib"
COMPRESSION_TYPES = (NO_COMPRESSION, SNAPPY, ZLIB)

# the reference's snappy block limit (encoded.go:161 MaxBlockLen comment:
# "roughly limited to 3.42GBytes"); shared ceiling for every type here
MAX_PAYLOAD = (1 << 32) - 1


# ---------------------------------------------------------------------------
# snappy block format (public spec; independent implementation)
# ---------------------------------------------------------------------------


def _put_uvarint(out: bytearray, v: int) -> None:
    while v >= 0x80:
        out.append((v & 0x7F) | 0x80)
        v >>= 7
    out.append(v)


def _read_uvarint(buf, pos: int) -> tuple[int, int]:
    v = shift = 0
    while True:
        if pos >= len(buf):
            raise ValueError("snappy: truncated length preamble")
        b = buf[pos]
        pos += 1
        v |= (b & 0x7F) << shift
        if not b & 0x80:
            return v, pos
        shift += 7
        if shift > 63:
            raise ValueError("snappy: length preamble overflow")


def _emit_literal(out: bytearray, lit) -> None:
    n = len(lit) - 1
    if n < 60:
        out.append(n << 2)
    elif n < (1 << 8):
        out.append(60 << 2)
        out.append(n)
    elif n < (1 << 16):
        out.append(61 << 2)
        out += n.to_bytes(2, "little")
    elif n < (1 << 24):
        out.append(62 << 2)
        out += n.to_bytes(3, "little")
    else:
        out.append(63 << 2)
        out += n.to_bytes(4, "little")
    out += lit


def _emit_copy2(out: bytearray, offset: int, length: int) -> None:
    """Copy elements as copy-2 chunks (tag 0b10): 1-64 byte length,
    16-bit offset — the simplest element every decoder accepts."""
    while length > 0:
        n = min(64, length)
        out.append(((n - 1) << 2) | 2)
        out += offset.to_bytes(2, "little")
        length -= n


def snappy_block_encode(data: bytes) -> bytes:
    """Greedy hash-match encoder: 4-byte anchors hashed into a FIXED
    16K-slot position table (the golang/snappy shape — O(1) memory at
    any payload size; a dict keyed by raw 4-byte slices costs ~100x the
    input in transient allocations), matches verified by comparison,
    16-bit offsets, copy-2 elements."""
    if len(data) > MAX_PAYLOAD:
        raise ValueError("snappy: payload too large")
    out = bytearray()
    _put_uvarint(out, len(data))
    n = len(data)
    i = lit_start = 0
    # table sized to the input (golang/snappy: grow from 256 toward 16K
    # while smaller than the payload) — a sub-KB proposal must not pay
    # a 16K-slot zero-fill per call on the propose hot path
    table_size, shift = 256, 24
    while table_size < (1 << 14) and table_size < n:
        table_size <<= 1
        shift -= 1
    table = [0] * table_size              # position+1; 0 = empty slot
    while i + 4 <= n:
        v = int.from_bytes(data[i:i + 4], "little")
        h = ((v * 0x1E35A7BD) & 0xFFFFFFFF) >> shift
        j = table[h] - 1
        table[h] = i + 1
        if 0 <= j and i - j < (1 << 16) and data[j:j + 4] == data[i:i + 4]:
            length = 4
            while (i + length < n and length < (1 << 24)
                   and data[j + length] == data[i + length]):
                length += 1
            if lit_start < i:
                _emit_literal(out, data[lit_start:i])
            _emit_copy2(out, i - j, length)
            i += length
            lit_start = i
        else:
            i += 1
    if lit_start < n:
        _emit_literal(out, data[lit_start:])
    return bytes(out)


def snappy_block_decode(buf) -> bytes:
    want, pos = _read_uvarint(buf, 0)
    out = bytearray()
    n = len(buf)
    while pos < n:
        tag = buf[pos]
        pos += 1
        t = tag & 3
        if t == 0:                               # literal
            ln = tag >> 2
            if ln >= 60:
                nb = ln - 59
                if pos + nb > n:
                    raise ValueError("snappy: truncated literal length")
                ln = int.from_bytes(buf[pos:pos + nb], "little")
                pos += nb
            ln += 1
            if pos + ln > n:
                raise ValueError("snappy: truncated literal")
            out += buf[pos:pos + ln]
            pos += ln
            continue
        if t == 1:                               # copy-1
            ln = ((tag >> 2) & 0x7) + 4
            if pos >= n:
                raise ValueError("snappy: truncated copy-1")
            off = ((tag >> 5) << 8) | buf[pos]
            pos += 1
        elif t == 2:                             # copy-2
            ln = (tag >> 2) + 1
            if pos + 2 > n:
                raise ValueError("snappy: truncated copy-2")
            off = int.from_bytes(buf[pos:pos + 2], "little")
            pos += 2
        else:                                    # copy-4
            ln = (tag >> 2) + 1
            if pos + 4 > n:
                raise ValueError("snappy: truncated copy-4")
            off = int.from_bytes(buf[pos:pos + 4], "little")
            pos += 4
        if off == 0 or off > len(out):
            raise ValueError("snappy: invalid copy offset")
        for _ in range(ln):                      # overlapping copies
            out.append(out[-off])
    if len(out) != want:
        raise ValueError(
            f"snappy: decoded {len(out)} bytes, preamble said {want}")
    return bytes(out)


# ---------------------------------------------------------------------------
# the encoded-entry envelope (encoded.go GetEncoded / GetPayload)
# ---------------------------------------------------------------------------


def get_encoded(compression: str, cmd: bytes) -> bytes:
    """Wrap a proposal payload (GetEncoded, encoded.go:75).  Empty
    payloads never reach here — the propose path keeps them as plain
    APPLICATION entries, as the reference does (request.go:1091)."""
    if not cmd:
        raise ValueError("empty payload cannot be encoded")
    if len(cmd) > MAX_PAYLOAD:
        raise ValueError("payload too big")
    if compression == NO_COMPRESSION:
        return bytes([EE_V0 | EE_NO_COMPRESSION]) + cmd
    if compression == SNAPPY:
        return bytes([EE_V0 | EE_SNAPPY]) + snappy_block_encode(cmd)
    if compression == ZLIB:
        import zlib

        return bytes([EE_V0 | EE_ZLIB]) + zlib.compress(cmd, 1)
    raise ValueError(f"unknown entry compression {compression!r}")


def get_payload(entry) -> bytes:
    """The payload ready for the state machine (GetPayload,
    encoded.go:54): ENCODED entries are unwrapped, everything else
    passes through."""
    if entry.type != pb.EntryType.ENCODED:
        return entry.cmd
    cmd = entry.cmd
    if not cmd:
        raise ValueError("encoded entry with empty cmd")
    header = cmd[0]
    if header & _VER_MASK != EE_V0:
        raise ValueError(f"unknown encoded-entry version {header >> 4}")
    if header & _SESSION_MASK:
        raise ValueError("session-bearing encoded entries not supported")
    ct = header & _CT_MASK
    body = cmd[EE_HEADER_SIZE:]
    if ct == EE_NO_COMPRESSION:
        return bytes(body)
    if ct == EE_SNAPPY:
        return snappy_block_decode(body)
    if ct == EE_ZLIB:
        import zlib

        return zlib.decompress(body)
    raise ValueError(f"unknown encoded-entry compression flag {ct >> 1}")
