"""Typed metrics registry with Prometheus text exposition.

The reference exposes its counters through a Prometheus registry
(nodehost metrics + event.go); the seed's ``events.Metrics`` collapsed
all of that into one ``defaultdict(int)``, which silently conflates
monotonic counters with set-anywhere gauges.  This module is the typed
replacement: ``Counter`` / ``Gauge`` / ``Histogram`` instruments with
optional label families, callback gauges evaluated at collect time, and
a ``Registry`` that renders the Prometheus text format (0.0.4) plus a
strict parser for round-trip tests and the one-shot scraper.

Locking: the registry lock only guards the family table; instrument
values are guarded by per-instrument locks, and callback gauges are
evaluated with NO registry lock held, so a callback may take host locks
(e.g. NodeHost.mu) without inverting against engine threads that hold
host locks while bumping counters.

Determinism: this module is in the determinism lint scope — it never
reads the wall clock and never draws randomness; histograms observe
caller-supplied values and exposition output is sorted by name.
"""

from __future__ import annotations

import bisect
import re
import threading

from dragonboat_tpu.logger import get_logger

_LOG = get_logger("telemetry")


class InstrumentTypeError(TypeError):
    """Wrong operation for the instrument's type — ``inc()`` on a gauge,
    ``set()`` on a counter, or re-registering a name as another kind."""


_SANITIZE_RE = re.compile(r"[^a-zA-Z0-9_:]")

# fsync / write / step latencies in microseconds
DEFAULT_LATENCY_BUCKETS_US = (
    100.0, 250.0, 500.0, 1000.0, 2500.0, 5000.0, 10000.0,
    25000.0, 50000.0, 100000.0, 250000.0, 500000.0, 1000000.0)


def sanitize_name(name: str) -> str:
    """Legacy dotted name -> Prometheus metric name (dots become ``_``)."""
    out = _SANITIZE_RE.sub("_", name)
    if out and out[0].isdigit():
        out = "_" + out
    return out


def _escape_label(value: str) -> str:
    return (value.replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _escape_help(text: str) -> str:
    return text.replace("\\", "\\\\").replace("\n", "\\n")


def _fmt_value(v) -> str:
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, int):
        return str(v)
    f = float(v)
    if f == float("inf"):
        return "+Inf"
    if f == float("-inf"):
        return "-Inf"
    return repr(f)


class Counter:
    """Monotonic counter: ``inc()`` only; ``set()`` raises."""

    kind = "counter"

    def __init__(self, name: str) -> None:
        self.name = name
        self.mu = threading.Lock()
        self._value = 0                                   # guarded-by: mu

    def inc(self, delta: int = 1) -> None:
        if delta < 0:
            raise ValueError(
                f"counter {self.name!r}: inc({delta}) is negative "
                "(counters are monotonic; use a gauge)")
        with self.mu:
            self._value += delta

    def set(self, value) -> None:
        raise InstrumentTypeError(
            f"{self.name!r} is a counter: set() would break monotonicity "
            "(register a gauge instead)")

    def observe(self, value) -> None:
        raise InstrumentTypeError(
            f"{self.name!r} is a counter: observe() needs a histogram")

    def value(self):
        with self.mu:
            return self._value

    def _force_set(self, value) -> None:
        """Legacy-shim escape hatch (events.Metrics migration only)."""
        with self.mu:
            self._value = value


class Gauge:
    """Point-in-time value: ``set()`` only; ``inc()`` raises."""

    kind = "gauge"

    def __init__(self, name: str) -> None:
        self.name = name
        self.mu = threading.Lock()
        self._value = 0                                   # guarded-by: mu

    def set(self, value) -> None:
        with self.mu:
            self._value = value

    def inc(self, delta: int = 1) -> None:
        raise InstrumentTypeError(
            f"{self.name!r} is a gauge: inc() is a counter operation "
            "(register a counter instead)")

    def observe(self, value) -> None:
        raise InstrumentTypeError(
            f"{self.name!r} is a gauge: observe() needs a histogram")

    def value(self):
        with self.mu:
            return self._value

    def _force_add(self, delta) -> None:
        """Legacy-shim escape hatch (events.Metrics migration only)."""
        with self.mu:
            self._value += delta


class Histogram:
    """Fixed-bucket histogram: cumulative ``le`` exposition with
    ``_sum`` / ``_count``, +Inf bucket implicit."""

    kind = "histogram"

    def __init__(self, name: str, buckets=DEFAULT_LATENCY_BUCKETS_US
                 ) -> None:
        bounds = tuple(float(b) for b in buckets)
        if not bounds:
            raise ValueError(f"histogram {name!r}: needs >= 1 bucket bound")
        if any(b2 <= b1 for b1, b2 in zip(bounds, bounds[1:])):
            raise ValueError(
                f"histogram {name!r}: bucket bounds must be strictly "
                f"increasing, got {bounds}")
        self.name = name
        self.buckets = bounds
        self.mu = threading.Lock()
        self._counts = [0] * (len(bounds) + 1)            # guarded-by: mu
        self._sum = 0.0                                   # guarded-by: mu
        self._total = 0                                   # guarded-by: mu

    def observe(self, value) -> None:
        v = float(value)
        i = bisect.bisect_left(self.buckets, v)
        with self.mu:
            self._counts[i] += 1
            self._sum += v
            self._total += 1

    def inc(self, delta: int = 1) -> None:
        raise InstrumentTypeError(
            f"{self.name!r} is a histogram: use observe(value)")

    def set(self, value) -> None:
        raise InstrumentTypeError(
            f"{self.name!r} is a histogram: use observe(value)")

    def snapshot_hist(self):
        """(cumulative counts per bound + +Inf, sum, total)."""
        with self.mu:
            counts = list(self._counts)
            total, s = self._total, self._sum
        cum, running = [], 0
        for c in counts:
            running += c
            cum.append(running)
        return cum, s, total


class Family:
    """One registered metric name: fixed label names, a child instrument
    per label-values tuple (the empty tuple for unlabeled metrics), or a
    callback evaluated at collect time."""

    def __init__(self, name: str, kind: str, labelnames, help: str,
                 ctor) -> None:
        self.name = name
        self.kind = kind
        self.labelnames = tuple(labelnames)
        self.help = help
        self.callback = None          # set by Registry.gauge_fn
        self.mu = threading.Lock()
        self._children: dict[tuple, object] = {}          # guarded-by: mu
        self._ctor = ctor

    def labels(self, *values, **kv):
        if kv:
            if values or sorted(kv) != sorted(self.labelnames):
                raise ValueError(
                    f"{self.name!r}: expected labels {self.labelnames}, "
                    f"got {tuple(sorted(kv))}")
            values = tuple(kv[k] for k in self.labelnames)
        key = tuple(str(v) for v in values)
        if len(key) != len(self.labelnames):
            raise ValueError(
                f"{self.name!r}: expected {len(self.labelnames)} label "
                f"value(s) for {self.labelnames}, got {len(key)}")
        with self.mu:
            child = self._children.get(key)
            if child is None:
                child = self._ctor()
                self._children[key] = child
        return child

    def children(self) -> dict:
        with self.mu:
            return dict(self._children)


class Registry:
    """Typed instrument registry + Prometheus text exposition."""

    def __init__(self) -> None:
        self.mu = threading.Lock()
        self._families: dict[str, Family] = {}            # guarded-by: mu

    # -- registration ---------------------------------------------------

    def _family(self, name: str, kind: str, labelnames, help: str,
                ctor) -> Family:
        with self.mu:
            fam = self._families.get(name)
            if fam is None:
                fam = Family(name, kind, labelnames, help, ctor)
                self._families[name] = fam
                return fam
        if fam.kind != kind or fam.callback is not None:
            have = "callback gauge" if fam.callback is not None else fam.kind
            raise InstrumentTypeError(
                f"{name!r} is already registered as a {have}, "
                f"not a {kind}")
        if tuple(labelnames) != fam.labelnames:
            raise ValueError(
                f"{name!r}: label names {tuple(labelnames)} do not match "
                f"registered {fam.labelnames}")
        return fam

    def counter(self, name: str, help: str = "", labelnames=()):
        fam = self._family(name, "counter", labelnames, help,
                           lambda: Counter(name))
        return fam if labelnames else fam.labels()

    def gauge(self, name: str, help: str = "", labelnames=()):
        fam = self._family(name, "gauge", labelnames, help,
                           lambda: Gauge(name))
        return fam if labelnames else fam.labels()

    def histogram(self, name: str, help: str = "",
                  buckets=DEFAULT_LATENCY_BUCKETS_US, labelnames=()):
        fam = self._family(name, "histogram", labelnames, help,
                           lambda: Histogram(name, buckets))
        return fam if labelnames else fam.labels()

    def gauge_fn(self, name: str, fn, help: str = "", labelnames=()
                 ) -> None:
        """Register (or re-point, e.g. after a host restart rebuilds the
        producer) a gauge whose value is ``fn()`` at collect time.
        Unlabeled: ``fn() -> number``.  Labeled: ``fn() -> {label-values
        tuple (or single str): number}``."""
        with self.mu:
            fam = self._families.get(name)
            if fam is None:
                fam = Family(name, "gauge", labelnames, help, None)
                fam.callback = fn
                self._families[name] = fam
                return
        if fam.kind != "gauge" or fam.callback is None:
            raise InstrumentTypeError(
                f"{name!r} is already registered as a non-callback "
                f"{fam.kind}")
        if tuple(labelnames) != fam.labelnames:
            raise ValueError(
                f"{name!r}: label names {tuple(labelnames)} do not match "
                f"registered {fam.labelnames}")
        fam.callback = fn

    def kind_of(self, name: str) -> str | None:
        with self.mu:
            fam = self._families.get(name)
        return None if fam is None else fam.kind

    # -- collection -----------------------------------------------------

    def _fam_samples(self, fam: Family):
        """[(suffix, {label: value}, number)] — registry lock NOT held,
        so callbacks may take producer locks."""
        out = []
        if fam.callback is not None:
            try:
                got = fam.callback()
            except Exception:
                _LOG.exception("callback gauge %r raised", fam.name)
                return out
            if fam.labelnames:
                for key in sorted(got, key=str):
                    kt = key if isinstance(key, tuple) else (key,)
                    labels = dict(zip(fam.labelnames,
                                      (str(k) for k in kt)))
                    out.append(("", labels, got[key]))
            else:
                out.append(("", {}, got))
            return out
        children = fam.children()
        for key in sorted(children):
            child = children[key]
            labels = dict(zip(fam.labelnames, key))
            if fam.kind == "histogram":
                cum, s, total = child.snapshot_hist()
                for bound, c in zip(child.buckets, cum[:-1]):
                    le = dict(labels)
                    le["le"] = _fmt_value(bound)
                    out.append(("_bucket", le, c))
                inf = dict(labels)
                inf["le"] = "+Inf"
                out.append(("_bucket", inf, cum[-1]))
                out.append(("_sum", labels, s))
                out.append(("_count", labels, total))
            else:
                out.append(("", labels, child.value()))
        return out

    def collect(self):
        """[(family, samples)] sorted by name; values read outside the
        registry lock."""
        with self.mu:
            fams = list(self._families.values())
        fams.sort(key=lambda f: f.name)
        return [(fam, self._fam_samples(fam)) for fam in fams]

    def exposition(self) -> str:
        """Prometheus text format 0.0.4."""
        lines = []
        for fam, samples in self.collect():
            pname = sanitize_name(fam.name)
            if fam.help:
                lines.append(f"# HELP {pname} {_escape_help(fam.help)}")
            lines.append(f"# TYPE {pname} {fam.kind}")
            for suffix, labels, value in samples:
                if labels:
                    inner = ",".join(
                        f'{k}="{_escape_label(str(v))}"'
                        for k, v in labels.items())
                    label_str = "{" + inner + "}"
                else:
                    label_str = ""
                lines.append(
                    f"{pname}{suffix}{label_str} {_fmt_value(value)}")
        return "\n".join(lines) + "\n"

    def snapshot(self) -> dict:
        """Flat legacy view: unlabeled counters/gauges keep their exact
        registered (dotted) names; labeled samples render as
        ``name{k=v}``; histograms flatten to ``name.count`` /
        ``name.sum``.  Callback gauges are evaluated."""
        out: dict = {}
        for fam, samples in self.collect():
            for suffix, labels, value in samples:
                if fam.kind == "histogram":
                    if suffix == "_count":
                        key = fam.name + ".count"
                    elif suffix == "_sum":
                        key = fam.name + ".sum"
                    else:
                        continue
                    rest = {k: v for k, v in labels.items() if k != "le"}
                    if rest:
                        key += "{" + ",".join(
                            f"{k}={v}" for k, v in rest.items()) + "}"
                else:
                    key = fam.name
                    if labels:
                        key += "{" + ",".join(
                            f"{k}={v}" for k, v in labels.items()) + "}"
                out[key] = value
        return out


# process-global registry for module-scoped producers (logdb engines
# have no handle on a NodeHost's per-hub registry); the /metrics
# endpoint serves a host's registry concatenated with this one
GLOBAL = Registry()


def global_registry() -> Registry:
    return GLOBAL


# -- strict text-format parser (round-trip tests + metrics_dump) --------

_METRIC_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)"          # metric name
    r"(?:\{(.*)\})?"                        # optional label block
    r" (\+Inf|-Inf|NaN|[-+]?[0-9]*\.?[0-9]+(?:[eE][-+]?[0-9]+)?)$")
_LABEL_RE = re.compile(
    r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"(?:,|$)')


def _unescape_label(value: str) -> str:
    return (value.replace("\\n", "\n").replace('\\"', '"')
            .replace("\\\\", "\\"))


def parse_exposition(text: str) -> dict:
    """Strict parser for the exposition subset this module emits.

    Returns ``{family: {"type": kind, "help": str, "samples":
    [(sample_name, {label: value}, float)]}}`` and raises ``ValueError``
    on anything malformed: samples without a preceding TYPE, duplicate
    TYPE lines, label syntax errors, non-cumulative histogram buckets,
    a missing ``+Inf`` bucket, or ``_count`` disagreeing with it.
    """
    families: dict[str, dict] = {}

    def family_of(sample_name: str) -> str | None:
        if sample_name in families:
            return sample_name
        for suffix in ("_bucket", "_sum", "_count"):
            if sample_name.endswith(suffix):
                base = sample_name[:-len(suffix)]
                if base in families and families[base]["type"] == \
                        "histogram":
                    return base
        return None

    for lineno, raw in enumerate(text.split("\n"), 1):
        line = raw.rstrip("\r")
        if not line.strip():
            continue
        if line.startswith("#"):
            parts = line.split(" ", 3)
            if len(parts) < 3 or parts[0] != "#":
                raise ValueError(f"line {lineno}: malformed comment "
                                 f"{line!r}")
            if parts[1] == "HELP":
                name = parts[2]
                if not _METRIC_NAME_RE.match(name):
                    raise ValueError(
                        f"line {lineno}: bad metric name {name!r}")
                fam = families.setdefault(
                    name, {"type": None, "help": "", "samples": []})
                fam["help"] = parts[3] if len(parts) > 3 else ""
            elif parts[1] == "TYPE":
                if len(parts) != 4:
                    raise ValueError(f"line {lineno}: malformed TYPE "
                                     f"{line!r}")
                name, kind = parts[2], parts[3]
                if not _METRIC_NAME_RE.match(name):
                    raise ValueError(
                        f"line {lineno}: bad metric name {name!r}")
                if kind not in ("counter", "gauge", "histogram",
                                "summary", "untyped"):
                    raise ValueError(
                        f"line {lineno}: unknown type {kind!r}")
                fam = families.setdefault(
                    name, {"type": None, "help": "", "samples": []})
                if fam["type"] is not None:
                    raise ValueError(
                        f"line {lineno}: duplicate TYPE for {name!r}")
                if fam["samples"]:
                    raise ValueError(
                        f"line {lineno}: TYPE for {name!r} after its "
                        "samples")
                fam["type"] = kind
            else:
                raise ValueError(
                    f"line {lineno}: unknown comment {parts[1]!r}")
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            raise ValueError(f"line {lineno}: malformed sample {line!r}")
        sname, labelblock, valstr = m.group(1), m.group(2), m.group(3)
        labels: dict[str, str] = {}
        if labelblock is not None:
            pos = 0
            while pos < len(labelblock):
                lm = _LABEL_RE.match(labelblock, pos)
                if lm is None:
                    raise ValueError(
                        f"line {lineno}: bad label syntax at offset "
                        f"{pos} in {labelblock!r}")
                if lm.group(1) in labels:
                    raise ValueError(
                        f"line {lineno}: duplicate label "
                        f"{lm.group(1)!r}")
                labels[lm.group(1)] = _unescape_label(lm.group(2))
                pos = lm.end()
        if valstr == "+Inf":
            value = float("inf")
        elif valstr == "-Inf":
            value = float("-inf")
        else:
            value = float(valstr)
        base = family_of(sname)
        if base is None:
            raise ValueError(
                f"line {lineno}: sample {sname!r} has no preceding "
                "TYPE declaration")
        if families[base]["type"] is None:
            raise ValueError(
                f"line {lineno}: sample {sname!r} before TYPE")
        families[base]["samples"].append((sname, labels, value))

    # histogram consistency: per label-set, buckets cumulative with a
    # +Inf bound equal to _count
    for name, fam in families.items():
        if fam["type"] != "histogram":
            continue
        series: dict[tuple, list] = {}
        counts: dict[tuple, float] = {}
        for sname, labels, value in fam["samples"]:
            rest = tuple(sorted((k, v) for k, v in labels.items()
                                if k != "le"))
            if sname == name + "_bucket":
                if "le" not in labels:
                    raise ValueError(
                        f"{name}: _bucket sample without le label")
                series.setdefault(rest, []).append(
                    (labels["le"], value))
            elif sname == name + "_count":
                counts[rest] = value
        for rest, buckets in series.items():
            vals = [v for _, v in buckets]
            if vals != sorted(vals):
                raise ValueError(
                    f"{name}: histogram buckets not cumulative")
            les = [le for le, _ in buckets]
            if "+Inf" not in les:
                raise ValueError(f"{name}: histogram missing +Inf bucket")
            inf_val = dict(buckets)["+Inf"]
            if rest in counts and counts[rest] != inf_val:
                raise ValueError(
                    f"{name}: _count {counts[rest]} != +Inf bucket "
                    f"{inf_val}")
    return families
