"""Node registry: (shard, replica) → address resolution.

Parity with ``internal/registry/registry.go:36`` (static Registry).  The
gossip-based dynamic registry (gossip.go) is a later phase; the seam is the
same INodeRegistry interface.
"""

from __future__ import annotations

import threading

from dragonboat_tpu.raftio import INodeRegistry


class Registry(INodeRegistry):
    def __init__(self, stream_connections: int = 4) -> None:
        self.mu = threading.RLock()
        self.addr: dict[tuple[int, int], str] = {}
        self.stream_connections = stream_connections

    def add(self, shard_id: int, replica_id: int, url: str) -> None:
        with self.mu:
            self.addr[(shard_id, replica_id)] = url

    def remove(self, shard_id: int, replica_id: int) -> None:
        with self.mu:
            self.addr.pop((shard_id, replica_id), None)

    def remove_shard(self, shard_id: int) -> None:
        with self.mu:
            for k in [k for k in self.addr if k[0] == shard_id]:
                del self.addr[k]

    def resolve(self, shard_id: int, replica_id: int) -> tuple[str, str]:
        with self.mu:
            addr = self.addr.get((shard_id, replica_id))
        if addr is None:
            raise KeyError(f"no address for shard {shard_id} replica {replica_id}")
        # connection key spreads (shard, replica) pairs over StreamConnections
        # parallel sockets per peer pair (registry.go:79-85)
        key = f"{addr}-{(shard_id * 31 + replica_id) % self.stream_connections}"
        return addr, key
