"""Filesystem abstraction with error injection and crash simulation.

Parity with the reference's ``internal/vfs/vfs.go:29-46`` (IFS = OS fs /
strict MemFS / ErrorFS): every storage component (tan log engine, server
Env, snapshotter, import tool) takes an ``IFS`` so tests can

- run whole clusters with zero disk IO (:class:`MemFS`),
- simulate power loss — unsynced writes vanish (:meth:`MemFS.crash`),
- inject IO errors at precise points (:class:`ErrorFS`), which the
  NodeHost turns into controlled crashes the way the reference arms its
  engine crash channel when it detects an ErrorFS (nodehost.go:361-367).

The file objects returned by ``open`` support the stdlib surface the
storage layer uses: read/write/seek/tell/truncate/flush/close and the
context-manager protocol.  Durability goes through ``IFS.fsync(f)`` (not
``os.fsync``) so MemFS can model the synced/unsynced distinction.
"""

from __future__ import annotations

import io
import os
import threading

__all__ = ["OSFS", "MemFS", "ErrorFS", "InjectedError", "default_fs",
           "copy_file"]


class InjectedError(OSError):
    """Raised by ErrorFS at an injection point."""


# ---------------------------------------------------------------------------
# OS filesystem
# ---------------------------------------------------------------------------


class OSFS:
    """The real filesystem (vfs.go Default)."""

    def open(self, path: str, mode: str = "rb"):
        return open(path, mode)

    def makedirs(self, path: str) -> None:
        os.makedirs(path, exist_ok=True)

    def listdir(self, path: str) -> list[str]:
        return os.listdir(path)

    def remove(self, path: str) -> None:
        os.remove(path)

    def replace(self, src: str, dst: str) -> None:
        os.replace(src, dst)

    def exists(self, path: str) -> bool:
        return os.path.exists(path)

    def getsize(self, path: str) -> int:
        return os.path.getsize(path)

    def fsync(self, f) -> None:
        f.flush()
        os.fsync(f.fileno())

    def fsync_dir(self, path: str) -> None:
        """Make directory-entry changes (rename/create/remove) durable —
        required after ``replace`` before depending on the new name."""
        fd = os.open(path, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)

    def flock_exclusive(self, f) -> None:
        """Non-blocking exclusive lock; OSError if held elsewhere."""
        import fcntl

        fcntl.flock(f.fileno(), fcntl.LOCK_EX | fcntl.LOCK_NB)

    def flock_unlock(self, f) -> None:
        import fcntl

        fcntl.flock(f.fileno(), fcntl.LOCK_UN)


def default_fs() -> OSFS:
    return OSFS()


# ---------------------------------------------------------------------------
# In-memory filesystem with power-loss simulation
# ---------------------------------------------------------------------------


class _MemNode:
    __slots__ = ("data", "synced")

    def __init__(self) -> None:
        self.data = bytearray()       # current (possibly unsynced) content
        self.synced = b""             # content as of the last fsync


class _MemFile:
    """File handle over a _MemNode; supports binary and text modes."""

    def __init__(self, fs: "MemFS", path: str, node: _MemNode, mode: str):
        self._fs = fs
        self._path = path
        self._node = node
        self._binary = "b" in mode
        self._append = "a" in mode
        self._readable = "r" in mode or "+" in mode
        self._writable = any(c in mode for c in "wa+x")
        self._pos = len(node.data) if self._append else 0
        self.closed = False

    # -- io surface --
    def read(self, n: int = -1):
        data = self._node.data
        if n is None or n < 0:
            out = bytes(data[self._pos:])
        else:
            out = bytes(data[self._pos:self._pos + n])
        self._pos += len(out)
        return out if self._binary else out.decode()

    def write(self, b) -> int:
        if not self._binary and isinstance(b, str):
            b = b.encode()
        b = bytes(b)
        if self._append:
            self._pos = len(self._node.data)
        d = self._node.data
        end = self._pos + len(b)
        if end > len(d):
            d.extend(b"\x00" * (end - len(d)))
        d[self._pos:end] = b
        self._pos = end
        return len(b)

    def seek(self, pos: int, whence: int = 0) -> int:
        if whence == 0:
            self._pos = pos
        elif whence == 1:
            self._pos += pos
        else:
            self._pos = len(self._node.data) + pos
        return self._pos

    def tell(self) -> int:
        return self._pos

    def truncate(self, n: int | None = None) -> int:
        n = self._pos if n is None else n
        del self._node.data[n:]
        return n

    def flush(self) -> None:  # NOT durable — only IFS.fsync is
        pass

    def fileno(self) -> int:
        raise io.UnsupportedOperation("MemFS files have no OS fd")

    def close(self) -> None:
        self.closed = True

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    # iteration (json.load etc. only use read; keep minimal)
    def readable(self) -> bool:
        return self._readable

    def writable(self) -> bool:
        return self._writable


class MemFS:
    """Strict in-memory FS: ``crash()`` drops everything not fsynced —
    the reference's strict MemFS power-loss model (vfs.go NewStrictMem)."""

    def __init__(self) -> None:
        self._mu = threading.RLock()
        self._files: dict[str, _MemNode] = {}
        self._dirs: set[str] = {"/"}
        self._locks: set[str] = set()

    def _norm(self, path: str) -> str:
        return os.path.abspath(path)

    # -- IFS surface --
    def open(self, path: str, mode: str = "rb"):
        p = self._norm(path)
        with self._mu:
            node = self._files.get(p)
            if node is not None and "x" in mode:
                raise FileExistsError(p)
            if node is None:
                # stdlib parity: every "r" flavor (incl. "r+") requires an
                # existing file; only w/a/x create
                if "r" in mode:
                    raise FileNotFoundError(p)
                node = self._files[p] = _MemNode()
            if "w" in mode:
                node.data = bytearray()
            return _MemFile(self, p, node, mode)

    def makedirs(self, path: str) -> None:
        with self._mu:
            self._dirs.add(self._norm(path))

    def listdir(self, path: str) -> list[str]:
        p = self._norm(path) + os.sep
        with self._mu:
            return sorted({f[len(p):].split(os.sep)[0]
                           for f in self._files if f.startswith(p)})

    def remove(self, path: str) -> None:
        p = self._norm(path)
        with self._mu:
            if p not in self._files:
                raise FileNotFoundError(p)
            del self._files[p]

    def replace(self, src: str, dst: str) -> None:
        s, d = self._norm(src), self._norm(dst)
        with self._mu:
            if s not in self._files:
                raise FileNotFoundError(s)
            node = self._files.pop(s)
            # rename is atomic+durable once the source was synced
            self._files[d] = node

    def exists(self, path: str) -> bool:
        p = self._norm(path)
        with self._mu:
            return p in self._files or p in self._dirs or any(
                f.startswith(p + os.sep) for f in self._files)

    def getsize(self, path: str) -> int:
        p = self._norm(path)
        with self._mu:
            if p not in self._files:
                raise FileNotFoundError(p)
            return len(self._files[p].data)

    def fsync(self, f) -> None:
        if not isinstance(f, _MemFile):
            raise TypeError("MemFS.fsync on a non-MemFS file")
        with self._mu:
            f._node.synced = bytes(f._node.data)

    def fsync_dir(self, path: str) -> None:
        pass  # MemFS models renames as atomic+durable (see replace)

    def flock_exclusive(self, f) -> None:
        with self._mu:
            if f._path in self._locks:
                raise OSError(f"{f._path}: already locked")
            self._locks.add(f._path)

    def flock_unlock(self, f) -> None:
        with self._mu:
            self._locks.discard(f._path)

    # -- test surface --
    def crash(self, prefix: str | None = None) -> None:
        """Simulate power loss: revert every file to its last-synced
        content; files never synced disappear.  Locks are released.

        ``prefix`` scopes the loss to one path subtree — the model for a
        single process dying while other NodeHosts share this MemFS
        (each host's data dir is a distinct subtree)."""
        with self._mu:
            pfx = None if prefix is None else self._norm(prefix)
            for p in list(self._files):
                if pfx is not None and not (
                        p == pfx or p.startswith(pfx + os.sep)):
                    continue
                node = self._files[p]
                if node.synced:
                    node.data = bytearray(node.synced)
                else:
                    del self._files[p]
            if pfx is None:
                self._locks.clear()
            else:
                self._locks = {p for p in self._locks
                               if not (p == pfx or
                                       p.startswith(pfx + os.sep))}


# ---------------------------------------------------------------------------
# Error injection
# ---------------------------------------------------------------------------

_FILE_OPS = ("write", "read", "fsync")


class _ErrFile:
    """Wraps a file so write/read also hit the injection hook."""

    def __init__(self, fs: "ErrorFS", path: str, f):
        self._fs = fs
        self._path = path
        self._f = f

    def write(self, b):
        self._fs._check("write", self._path)
        return self._f.write(b)

    def read(self, n: int = -1):
        self._fs._check("read", self._path)
        return self._f.read(n)

    def __getattr__(self, name):
        return getattr(self._f, name)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self._f.close()
        return False


class ErrorFS:
    """Error-injecting FS wrapper (vfs.go ErrorFS / charybdefs analog).

    ``inject`` is ``(op, path) -> bool``; ops: open, write, read, fsync,
    remove, replace, listdir.  Convenience constructors cover the common
    policies: fail every matching op (:meth:`on_op`), or start failing
    after N successful operations (:meth:`fail_after`) — the pattern used
    to walk a workload through every IO point."""

    def __init__(self, base, inject=None) -> None:
        self.base = base
        self.inject = inject or (lambda op, path: False)
        self.ops = 0
        self._mu = threading.Lock()

    @classmethod
    def on_op(cls, base, *ops: str, path_substr: str = ""):
        def hook(op, path):
            return op in ops and path_substr in path
        return cls(base, hook)

    @classmethod
    def fail_after(cls, base, n: int, *ops: str):
        fs = cls(base)
        target_ops = ops or ("write", "fsync")

        def hook(op, path, fs=fs):
            return op in target_ops and fs.ops > n
        fs.inject = hook
        return fs

    def _check(self, op: str, path: str) -> None:
        with self._mu:
            self.ops += 1
        if self.inject(op, path):
            raise InjectedError(f"injected {op} error: {path}")

    # -- IFS surface (delegating, with checks on mutating/read ops) --
    def open(self, path: str, mode: str = "rb"):
        self._check("open", path)
        return _ErrFile(self, path, self.base.open(path, mode))

    def makedirs(self, path: str) -> None:
        self.base.makedirs(path)

    def listdir(self, path: str) -> list[str]:
        self._check("listdir", path)
        return self.base.listdir(path)

    def remove(self, path: str) -> None:
        self._check("remove", path)
        self.base.remove(path)

    def replace(self, src: str, dst: str) -> None:
        self._check("replace", src)
        self.base.replace(src, dst)

    def exists(self, path: str) -> bool:
        return self.base.exists(path)

    def getsize(self, path: str) -> int:
        return self.base.getsize(path)

    def fsync(self, f) -> None:
        inner = f._f if isinstance(f, _ErrFile) else f
        self._check("fsync", getattr(f, "_path", "?"))
        self.base.fsync(inner)

    def fsync_dir(self, path: str) -> None:
        self._check("fsync", path)
        self.base.fsync_dir(path)

    def flock_exclusive(self, f) -> None:
        inner = f._f if isinstance(f, _ErrFile) else f
        self.base.flock_exclusive(inner)

    def flock_unlock(self, f) -> None:
        inner = f._f if isinstance(f, _ErrFile) else f
        self.base.flock_unlock(inner)


def copy_file(fs, src: str, dst: str, block: int = 1 << 20) -> int:
    """Copy src -> dst through ``fs`` with a trailing fsync; returns the
    byte count.  The one file-copy loop (snapshot containers, external
    snapshot files, import staging) so block size and fsync discipline
    cannot drift between call sites."""
    n = 0
    with fs.open(src, "rb") as f, fs.open(dst, "wb") as out:
        while True:
            chunk = f.read(block)
            if not chunk:
                break
            out.write(chunk)
            n += len(chunk)
        fs.fsync(out)
    return n
