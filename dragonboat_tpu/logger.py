"""Module logger registry — parity with the reference's ``logger/`` package.

The reference exposes per-module loggers with settable levels
(logger/logger.go: GetLogger(pkgName) + SetLogLevel); this maps onto
Python's stdlib logging with a ``dragonboat_tpu.<module>`` namespace so
applications can route/filter with standard tooling.
"""

from __future__ import annotations

import logging

_ROOT = "dragonboat_tpu"

CRITICAL = logging.CRITICAL
ERROR = logging.ERROR
WARNING = logging.WARNING
INFO = logging.INFO
DEBUG = logging.DEBUG

_LEVELS = {
    "CRITICAL": CRITICAL,
    "ERROR": ERROR,
    "WARNING": WARNING,
    "INFO": INFO,
    "DEBUG": DEBUG,
}


def get_logger(pkg_name: str) -> logging.Logger:
    """GetLogger (logger/logger.go): the module logger for pkg_name."""
    return logging.getLogger(f"{_ROOT}.{pkg_name}")


def set_log_level(pkg_name: str, level: int | str) -> None:
    """SetLogLevel: adjust one module's verbosity at runtime."""
    if isinstance(level, str):
        level = _LEVELS[level.upper()]
    get_logger(pkg_name).setLevel(level)


def set_default_log_level(level: int | str) -> None:
    if isinstance(level, str):
        level = _LEVELS[level.upper()]
    logging.getLogger(_ROOT).setLevel(level)


# library convention: attach only a NullHandler and keep propagation on —
# the application routes dragonboat_tpu.* through its own logging config
# (the reference similarly lets callers install their own ILogger factory)
logging.getLogger(_ROOT).addHandler(logging.NullHandler())
