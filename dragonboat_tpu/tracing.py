"""Tracing / profiling hooks.

The reference has no tracer (SURVEY §5 flags this as a gap to fill, not a
port): its observability is module loggers + Prometheus counters.  The
TPU build adds a real trace path on top of the same metrics registry:

- ``start_trace(dir)`` / ``stop_trace()`` — JAX profiler capture (XLA
  device traces, host Python, HLO cost attribution) viewable in
  TensorBoard / Perfetto;
- ``annotate(name)`` — named span visible inside the device trace
  (``jax.profiler.TraceAnnotation``), used around the kernel engine's
  step phases;
- ``StepTimer`` — lightweight EWMA + max step-latency accounting that
  feeds the shared metrics registry (``engine.step_us_*`` counters), on
  all the time (the profiler itself is opt-in: capture costs memory).

Environment: ``DRAGONBOAT_TPU_TRACE_DIR`` arms profiler capture at import
of the engine, for drive-by profiling without code changes.
"""

from __future__ import annotations

import contextlib
import os
import time

_active_trace_dir: str | None = None


def start_trace(trace_dir: str) -> None:
    """Begin a JAX profiler capture into ``trace_dir``."""
    global _active_trace_dir
    import jax

    jax.profiler.start_trace(trace_dir)
    _active_trace_dir = trace_dir


def stop_trace() -> str | None:
    """End the capture; returns the trace dir (None if none active)."""
    global _active_trace_dir
    if _active_trace_dir is None:
        return None
    import jax

    jax.profiler.stop_trace()
    d, _active_trace_dir = _active_trace_dir, None
    return d


def maybe_start_from_env() -> bool:
    """Arm capture when DRAGONBOAT_TPU_TRACE_DIR is set (idempotent).
    JAX only serializes the capture on stop, so an env-armed trace
    registers an atexit stop — otherwise the dir would stay empty."""
    d = os.environ.get("DRAGONBOAT_TPU_TRACE_DIR")
    if d and _active_trace_dir is None:
        import atexit

        start_trace(d)
        atexit.register(stop_trace)
        return True
    return False


def annotate(name: str):
    """Named span in the device trace; near-zero cost when no capture is
    active (a module-flag check, no jax import or span object)."""
    if _active_trace_dir is None:
        return contextlib.nullcontext()
    try:
        import jax

        return jax.profiler.TraceAnnotation(name)
    except Exception:
        return contextlib.nullcontext()


class StepTimer:
    """Step-latency accounting into a Metrics registry.

    Keeps an exponentially-weighted mean and the max in integer
    microseconds so the snapshot stays a plain counter dict."""

    def __init__(self, metrics, prefix: str) -> None:
        self.metrics = metrics
        self.prefix = prefix
        self._ewma_us = 0.0

    @contextlib.contextmanager
    def measure(self):
        t0 = time.perf_counter()
        yield
        us = (time.perf_counter() - t0) * 1e6
        self._ewma_us = us if self._ewma_us == 0 else (
            0.9 * self._ewma_us + 0.1 * us)
        m = self.metrics
        m.inc(f"{self.prefix}.steps")
        m.inc(f"{self.prefix}.total_us", int(us))
        with m.mu:
            key = f"{self.prefix}.ewma_us"
            m.counters[key] = int(self._ewma_us)
            key = f"{self.prefix}.max_us"
            m.counters[key] = max(m.counters.get(key, 0), int(us))
