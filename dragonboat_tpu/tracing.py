"""Tracing / profiling hooks.

The reference has no tracer (SURVEY §5 flags this as a gap to fill, not a
port): its observability is module loggers + Prometheus counters.  The
TPU build adds a real trace path on top of the same metrics registry:

- ``start_trace(dir)`` / ``stop_trace()`` — JAX profiler capture (XLA
  device traces, host Python, HLO cost attribution) viewable in
  TensorBoard / Perfetto;
- ``annotate(name)`` — named span visible inside the device trace
  (``jax.profiler.TraceAnnotation``), used around the kernel engine's
  step phases;
- ``StepTimer`` — lightweight EWMA + max step-latency accounting that
  feeds the shared metrics registry (``engine.step_us_*`` counters), on
  all the time (the profiler itself is opt-in: capture costs memory).

Environment: ``DRAGONBOAT_TPU_TRACE_DIR`` arms profiler capture at import
of the engine, for drive-by profiling without code changes.
"""

from __future__ import annotations

import contextlib
import os
import time

_active_trace_dir: str | None = None
# set while the ACTIVE capture was armed by DRAGONBOAT_TPU_TRACE_DIR
# (maybe_start_from_env) rather than an explicit start_trace call —
# engine close() stops env-armed captures, never user-started ones
_env_armed = False


def monotonic_us() -> int:
    """Monotonic microsecond clock for lifecycle stage stamps.

    Lives HERE (outside the determinism lint scope) so lifecycle.py can
    receive it by injection: the tracer module itself never names a wall
    clock, tests inject a deterministic counter, and the lint keeps the
    replay-path modules honest."""
    return time.monotonic_ns() // 1000


def start_trace(trace_dir: str) -> None:
    """Begin a JAX profiler capture into ``trace_dir``.

    Raises ``RuntimeError`` when a capture is already active: the JAX
    profiler is a process singleton, and silently overwriting
    ``_active_trace_dir`` would make ``stop_trace`` report the second
    dir while the capture file lands in the first."""
    global _active_trace_dir
    if _active_trace_dir is not None:
        raise RuntimeError(
            f"a trace is already active in {_active_trace_dir!r}; call "
            "stop_trace() before starting another capture")
    import jax

    jax.profiler.start_trace(trace_dir)
    _active_trace_dir = trace_dir


def stop_trace() -> str | None:
    """End the capture; returns the trace dir (None if none active)."""
    global _active_trace_dir, _env_armed
    if _active_trace_dir is None:
        return None
    import jax

    jax.profiler.stop_trace()
    d, _active_trace_dir = _active_trace_dir, None
    _env_armed = False
    return d


def stop_env_trace() -> str | None:
    """Stop the capture ONLY when it was armed by the environment
    (``DRAGONBOAT_TPU_TRACE_DIR``); returns the flushed dir, or None.

    Engine ``close()`` calls this: JAX only serializes a capture on
    stop, so an env-armed trace that survived to interpreter shutdown
    depended on atexit LIFO ordering to flush at all — a host that is
    closed deliberately should flush its capture right there, while the
    backend is unambiguously alive.  A capture the USER started with
    ``start_trace`` is left alone (they own its lifetime)."""
    if not _env_armed:
        return None
    return stop_trace()


_env_hook_registered = False


def _atexit_stop() -> None:
    """atexit wrapper: an env-armed capture may already have been
    stopped by hand, and interpreter-shutdown stops must never mask the
    real exit path with a profiler error."""
    try:
        stop_trace()
    except Exception:
        pass


def maybe_start_from_env() -> bool:
    """Arm capture when DRAGONBOAT_TPU_TRACE_DIR is set (idempotent).
    JAX only serializes the capture on stop, so an env-armed trace
    registers an atexit stop — otherwise the dir would stay empty.

    Ordering: atexit hooks run LIFO, so the stop hook must be
    registered AFTER the engine/JAX import chain has registered its own
    teardown (backend shutdown) — i.e. here, after ``start_trace`` has
    imported jax — or the profiler would try to serialize the capture
    into an already-torn-down backend.  The hook is registered exactly
    once per process."""
    global _env_hook_registered, _env_armed
    d = os.environ.get("DRAGONBOAT_TPU_TRACE_DIR")
    if d and _active_trace_dir is None:
        import atexit

        start_trace(d)          # imports jax; its atexit hooks exist now
        _env_armed = True
        if not _env_hook_registered:
            _env_hook_registered = True
            atexit.register(_atexit_stop)
        return True
    return False


def annotate(name: str):
    """Named span in the device trace; near-zero cost when no capture is
    active (a module-flag check, no jax import or span object)."""
    if _active_trace_dir is None:
        return contextlib.nullcontext()
    try:
        import jax

        return jax.profiler.TraceAnnotation(name)
    except Exception:
        return contextlib.nullcontext()


class StepTimer:
    """Step-latency accounting into a Metrics registry.

    Typed instruments via the events.Metrics facade: ``.steps`` /
    ``.total_us`` are counters, ``.ewma_us`` / ``.max_us`` gauges, and
    ``.latency_us`` a fixed-bucket histogram for the Prometheus
    exposition; the legacy snapshot keys are unchanged."""

    def __init__(self, metrics, prefix: str) -> None:
        self.metrics = metrics
        self.prefix = prefix
        self._ewma_us = 0.0
        self._max_us = 0

    @contextlib.contextmanager
    def measure(self):
        t0 = time.perf_counter()
        yield
        us = (time.perf_counter() - t0) * 1e6
        self._ewma_us = us if self._ewma_us == 0 else (
            0.9 * self._ewma_us + 0.1 * us)
        self._max_us = max(self._max_us, int(us))
        m = self.metrics
        m.inc(f"{self.prefix}.steps")
        m.inc(f"{self.prefix}.total_us", int(us))
        m.set(f"{self.prefix}.ewma_us", int(self._ewma_us))
        m.set(f"{self.prefix}.max_us", self._max_us)
        m.observe(f"{self.prefix}.latency_us", us)
