"""Fabric observability: per-link transport telemetry, cross-host trace
propagation, and the commit-path hop census.

ROADMAP item 2 ("device-resident message fabric") defines success as
the lifecycle tracer's hub_send/hub_recv spans *disappearing* from
sampled commit paths; this module makes that criterion measurable.
Three legs, one process-global ``FabricMeter`` (``METER`` — the same
one-recorder doctrine as ``flight.RECORDER`` and ``lifecycle.TRACER``;
links span hosts, so the registry must too):

- **per-link telemetry**: every (src, dst) host pair the transport hub
  touches gets typed instruments — send/recv counters labeled by
  message class (request_vote / append / heartbeat / read_index /
  snapshot_chunk / other), byte totals + batch-size histograms, and a
  per-link delivery-latency histogram off the sender's stamped clock —
  exposed at ``/debug/fabric`` and merged into ``NodeHost.info()``.
  Hub queue depths and breaker states are folded into the snapshot
  through weakly-held hub references (``attach_hub``).

- **cross-host trace propagation**: sampled proposals carry a compact
  ``raftpb.FabricHeader`` on the transport frame (native wire: a
  magic-guarded trailer old decoders ignore; go wire: an unknown
  protobuf field reference peers skip).  The receiving host stamps the
  proposal span's ``hub_recv`` on EVERY transport — fixing the PR 7
  in-proc-only caveat — and opens a child *remote span* (remote_recv →
  remote_step → ack_return) that ``chrome_events()`` exports with
  ``pid`` = host, stitching into one Chrome trace at ``/trace``.

- **the hop census**: each header crossing increments the traced
  commit's host-hub hop count and distinct-host set; the lifecycle
  tracer's finish/scrub hooks retire the census into a hop-count
  histogram plus the ``fabric.p50_commit_host_hops`` gauge — the
  baseline ROADMAP item 2 must drive to zero
  (``scripts/metrics_dump.py --fabric`` emits it as
  ``build/fabric_census.json``).

Discipline: this module is in BOTH the concurrency and determinism
lint scopes.  It never names a wall clock — the microsecond clock is
injected (``tracing.monotonic_us`` by default, a counter in tests), the
same instruments-observe-caller-values doctrine as lifecycle.py — and
all mutable state is ``guarded-by: mu``.  Distinct-host sets are kept
as insertion-ordered dicts so no set iteration can leak process-varying
order into a snapshot.
"""

from __future__ import annotations

import threading
import weakref
from collections import deque

from dragonboat_tpu import lifecycle
from dragonboat_tpu import raftpb as pb
from dragonboat_tpu import telemetry
from dragonboat_tpu.tracing import monotonic_us

# -- message-class taxonomy (per-link counter labels) -----------------------

CLASS_REQUEST_VOTE = "request_vote"
CLASS_APPEND = "append"
CLASS_HEARTBEAT = "heartbeat"
CLASS_READ_INDEX = "read_index"
CLASS_SNAPSHOT = "snapshot_chunk"
CLASS_OTHER = "other"

MESSAGE_CLASSES = (CLASS_REQUEST_VOTE, CLASS_APPEND, CLASS_HEARTBEAT,
                   CLASS_READ_INDEX, CLASS_SNAPSHOT, CLASS_OTHER)

#: carrier classes for a co-located link (round 17): ``resident`` =
#: consensus traffic rides the in-step mesh collective, ``hub`` = cut /
#: partitioned, host-hub delivered (the fallback matrix in README)
LINK_CLASS_RESIDENT = "resident"
LINK_CLASS_HUB = "hub"
LINK_CLASSES = (LINK_CLASS_RESIDENT, LINK_CLASS_HUB)

_CLASS_OF = {
    pb.MessageType.REQUEST_VOTE: CLASS_REQUEST_VOTE,
    pb.MessageType.REQUEST_VOTE_RESP: CLASS_REQUEST_VOTE,
    pb.MessageType.REQUEST_PREVOTE: CLASS_REQUEST_VOTE,
    pb.MessageType.REQUEST_PREVOTE_RESP: CLASS_REQUEST_VOTE,
    pb.MessageType.REPLICATE: CLASS_APPEND,
    pb.MessageType.REPLICATE_RESP: CLASS_APPEND,
    pb.MessageType.HEARTBEAT: CLASS_HEARTBEAT,
    pb.MessageType.HEARTBEAT_RESP: CLASS_HEARTBEAT,
    pb.MessageType.READ_INDEX: CLASS_READ_INDEX,
    pb.MessageType.READ_INDEX_RESP: CLASS_READ_INDEX,
    pb.MessageType.INSTALL_SNAPSHOT: CLASS_SNAPSHOT,
}


def class_of(mtype) -> str:
    """Message-class label for a raftpb.MessageType."""
    return _CLASS_OF.get(mtype, CLASS_OTHER)


# remote child-span stages (chrome_events pid=host rows); ack_return is
# shared with the origin span's taxonomy — the same instant closes both
STAGE_REMOTE_RECV = "remote_recv"    # header ctx arrived at a remote host
STAGE_REMOTE_STEP = "remote_step"    # remote host sent its quorum response
STAGE_ACK_RETURN = lifecycle.STAGE_ACK_RETURN

# byte-scaled buckets for the per-link batch-size histograms (the
# shared telemetry default is microsecond-scaled)
BYTES_BUCKETS = (64.0, 256.0, 1024.0, 4096.0, 16384.0, 65536.0,
                 262144.0, 1048576.0)
# host-hub hops per commit are small integers; one bucket per count
HOPS_BUCKETS = (1.0, 2.0, 3.0, 4.0, 6.0, 8.0, 12.0, 16.0)

# Chrome-trace pid offset for host rows: lifecycle spans use
# pid=shard_id (small ints) — remote spans must never collide with them
HOST_PID_BASE = 1_000_000


class _Link:
    """Mutable per-(src, dst) tallies.  Owned by FabricMeter, every
    field mutated only under the meter's ``mu``."""

    __slots__ = ("sent", "recv", "bytes_sent", "bytes_recv",
                 "batches_sent", "batches_recv", "delivery_us")

    def __init__(self, delivery_samples: int) -> None:
        self.sent = dict.fromkeys(MESSAGE_CLASSES, 0)
        self.recv = dict.fromkeys(MESSAGE_CLASSES, 0)
        self.bytes_sent = 0
        self.bytes_recv = 0
        self.batches_sent = 0
        self.batches_recv = 0
        # recent per-batch delivery latencies (sender stamp -> receive)
        self.delivery_us: deque = deque(maxlen=delivery_samples)


def _quantile(samples: list, q: float) -> float:
    """Nearest-rank quantile of a non-empty sorted list."""
    return float(samples[min(len(samples) - 1, int(q * len(samples)))])


class FabricMeter:
    """Process-wide link registry + remote-span book + hop census."""

    def __init__(self, clock=None, registry=None, enabled: bool = True,
                 delivery_samples: int = 512, ring_size: int = 256,
                 max_census: int = 4096, max_remote: int = 4096) -> None:
        self.mu = threading.Lock()
        self._clock = clock if clock is not None else monotonic_us
        self._enabled = bool(enabled)
        self._delivery_samples = max(1, int(delivery_samples))
        self._max_census = max(1, int(max_census))
        self._max_remote = max(1, int(max_remote))
        self._links: dict[tuple[str, str], _Link] = {}      # guarded-by: mu
        # carrier class per directed link ("resident" | "hub"), kept by
        # the mesh engine's cut-mask transitions (round 17)
        self._link_classes: dict[tuple[str, str], str] = {}  # guarded-by: mu
        # hop census per traced proposal key: origin, crossings so far,
        # distinct hosts (insertion-ordered dict used as a set — the
        # determinism lint bans bare set iteration)
        self._census: dict[int, dict] = {}                  # guarded-by: mu
        self._hops_done: deque = deque(maxlen=ring_size)    # guarded-by: mu
        self._census_finished = 0                           # guarded-by: mu
        self._census_dropped = 0                            # guarded-by: mu
        # remote child spans keyed (host, key): stamp lists like the
        # lifecycle tracer's, retired to a bounded ring on ack_return
        self._remote: dict[tuple[str, int], list] = {}      # guarded-by: mu
        self._remote_ring: deque = deque(maxlen=ring_size)  # guarded-by: mu
        # quorum-ack return contexts parked at a remote host, keyed
        # (host, shard): attached to the next response batch home
        self._returns: dict[tuple[str, int], list] = {}     # guarded-by: mu
        # weakly-held transport hubs for queue-depth/breaker folding
        self._hubs: dict[str, object] = {}                  # guarded-by: mu
        # stable Chrome pid per host address, in first-seen order
        self._host_pids: dict[str, int] = {}                # guarded-by: mu
        reg = registry if registry is not None else telemetry.GLOBAL
        self._sent_ctr = reg.counter(
            "fabric.link_sent",
            help="messages sent per (src, dst) link by message class",
            labelnames=("src", "dst", "cls"))
        self._recv_ctr = reg.counter(
            "fabric.link_recv",
            help="messages received per (src, dst) link by message class",
            labelnames=("src", "dst", "cls"))
        self._bytes_hist = reg.histogram(
            "fabric.link_batch_bytes",
            help="per-batch payload bytes per (src, dst) link",
            buckets=BYTES_BUCKETS, labelnames=("src", "dst"))
        self._delivery_hist = reg.histogram(
            "fabric.link_delivery_us",
            help="per-batch delivery latency (sender stamp to receive) "
                 "per (src, dst) link",
            labelnames=("src", "dst"))
        self._hops_hist = reg.histogram(
            "fabric.commit_host_hops",
            help="host-hub hops traversed per sampled commit's quorum "
                 "round (ROADMAP item 2 baseline)",
            buckets=HOPS_BUCKETS)
        reg.gauge_fn(
            "fabric.p50_commit_host_hops", self._p50_hops_fn,
            help="median host-hub hops per sampled commit (recent ring)")
        reg.gauge_fn(
            "fabric.queue_depth", self._queue_depth_fn,
            help="transport-hub send-queue depth per attached host",
            labelnames=("host",))

    # -- configuration ----------------------------------------------------

    @property
    def enabled(self) -> bool:
        return self._enabled

    def configure(self, enabled: bool | None = None) -> None:
        """Re-point the process-global meter at a host's expert config
        (NodeHost.__init__); None leaves the knob unchanged."""
        with self.mu:
            if enabled is not None:
                self._enabled = bool(enabled)

    def attach_hub(self, addr: str, hub) -> None:
        """Weakly register a host's TransportHub so snapshots can fold
        in queue depths and breaker states without owning its life."""
        with self.mu:
            self._hubs[addr] = weakref.ref(hub)

    def set_link_class(self, src: str, dst: str, cls: str) -> None:
        """Classify one directed link's carrier for the doctor view:
        ``resident`` (the mesh collective carries it; the hub never
        sees its consensus traffic) or ``hub`` (cut / partitioned /
        off-mesh — host-hub delivered).  The mesh engine refreshes
        these on admission and on every per-link cut flip; unregistered
        links are hub links by construction."""
        if cls not in LINK_CLASSES:
            raise ValueError(f"unknown link class {cls!r} "
                             f"(want one of {sorted(LINK_CLASSES)})")
        with self.mu:
            self._link_classes[(str(src), str(dst))] = cls

    def drop_link_classes(self, addr: str) -> None:
        """Forget every link class touching ``addr`` (replica detached:
        its resident links are gone, not healed)."""
        with self.mu:
            for key in [k for k in self._link_classes
                        if addr in k]:
                del self._link_classes[key]

    def reset(self) -> None:
        """Drop links, census, spans and hub attachments (tests)."""
        with self.mu:
            self._links.clear()
            self._link_classes.clear()
            self._census.clear()
            self._hops_done.clear()
            self._census_finished = 0
            self._census_dropped = 0
            self._remote.clear()
            self._remote_ring.clear()
            self._returns.clear()
            self._hubs.clear()
            self._host_pids.clear()

    # -- gauge callbacks (collect-time, must not hold two locks) ----------

    def _p50_hops_fn(self) -> float:
        with self.mu:
            done = sorted(self._hops_done)
        return _quantile(done, 0.50) if done else 0.0

    def _queue_depth_fn(self) -> dict:
        with self.mu:
            hubs = list(self._hubs.items())
        out = {}
        for addr, ref in hubs:
            hub = ref()
            if hub is None:
                continue
            with hub.mu:
                out[(addr,)] = float(sum(
                    len(q) for q in hub.queues.values()))
        return out

    # -- send path (transport hub flush) ----------------------------------

    def header_for(self, src: str, dst: str,
                   msgs) -> pb.FabricHeader | None:
        """The fabric header for an outbound batch ``src -> dst``:
        sampled replicate entry keys become outbound contexts, and any
        quorum-ack contexts parked here for ``dst`` ride home with
        their hop count advanced.  None when there is nothing to carry
        (the frame stays byte-identical to an old peer's)."""
        if not self._enabled:
            return None
        ctxs: list[pb.FabricContext] = []
        if lifecycle.TRACER.enabled:
            for m in msgs:
                if m.type == pb.MessageType.REPLICATE:
                    for e in m.entries:
                        if e.key and lifecycle.TRACER.sampled(e.key):
                            ctxs.append(pb.FabricContext(
                                key=e.key, origin=src, hop=0,
                                shard_id=m.shard_id))
        resp_shards = {m.shard_id: True for m in msgs
                       if m.type == pb.MessageType.REPLICATE_RESP}
        if resp_shards:
            with self.mu:
                for sid in resp_shards:
                    parked = self._returns.get((src, sid))
                    if not parked:
                        continue
                    keep = []
                    for c in parked:
                        if c.origin == dst:
                            ctxs.append(c)
                        else:
                            keep.append(c)
                    if keep:
                        self._returns[(src, sid)] = keep
                    else:
                        del self._returns[(src, sid)]
        if not ctxs:
            return None
        return pb.FabricHeader(sent_us=self._clock(), ctxs=tuple(ctxs))

    def on_send(self, src: str, dst: str, msgs, nbytes: int,
                header: pb.FabricHeader | None = None) -> None:
        """Successful batch send ``src -> dst``: link counters plus one
        census crossing (and a remote_step stamp) per carried context."""
        if not self._enabled:
            return
        t = self._clock()
        with self.mu:
            link = self._links.get((src, dst))
            if link is None:
                link = self._links[(src, dst)] = \
                    _Link(self._delivery_samples)
            for m in msgs:
                link.sent[class_of(m.type)] += 1
            link.bytes_sent += nbytes
            link.batches_sent += 1
            if header is not None:
                for c in header.ctxs:
                    # one hop-census crossing per carried context
                    cen = self._census.get(c.key)
                    if cen is None:
                        if len(self._census) >= self._max_census:
                            # leak upstream degrades the census, never
                            # host memory (same doctrine as the
                            # tracer's max_active bound)
                            self._census.pop(next(iter(self._census)))
                            self._census_dropped += 1
                        cen = self._census[c.key] = {
                            "origin": c.origin, "hops": 0,
                            "hosts": {c.origin: True}}
                    cen["hops"] += 1
                    cen["hosts"][src] = True
                    cen["hosts"][dst] = True
                    if c.origin != src:
                        # a remote host sending the quorum ack home
                        sp = self._remote.get((src, c.key))
                        if sp is not None:
                            sp.append((STAGE_REMOTE_STEP, t))
        for m in msgs:
            self._sent_ctr.labels(src, dst, class_of(m.type)).inc()
        self._bytes_hist.labels(src, dst).observe(nbytes)

    def on_chunk_sent(self, src: str, dst: str, nbytes: int) -> None:
        """One snapshot chunk left ``src`` for ``dst`` (the chunk path
        bypasses MessageBatch frames)."""
        if not self._enabled:
            return
        with self.mu:
            link = self._links.get((src, dst))
            if link is None:
                link = self._links[(src, dst)] = \
                    _Link(self._delivery_samples)
            link.sent[CLASS_SNAPSHOT] += 1
            link.bytes_sent += nbytes
        self._sent_ctr.labels(src, dst, CLASS_SNAPSHOT).inc()

    # -- receive path (NodeHost inbound seam) -----------------------------

    def on_batch_received(self, local: str, batch: pb.MessageBatch,
                          nbytes: int = 0) -> None:
        """Inbound batch at host ``local``: recv counters, delivery
        latency off the header's sender stamp, hub_recv stamping for
        carried trace contexts (every transport — the PR 7 fix), child
        remote spans, and return-context parking for the quorum ack."""
        header = batch.fabric
        if lifecycle.TRACER.enabled:
            if header is not None:
                self._walk_ctxs(local, header)
            else:
                # headerless frame (old peer / fabric off at the
                # sender): the in-proc fallback PR 7 shipped — sampled
                # replicate entries stamp straight off the batch
                for m in batch.requests:
                    if m.type == pb.MessageType.REPLICATE:
                        for e in m.entries:
                            if e.key:
                                lifecycle.TRACER.stamp(
                                    e.key, lifecycle.STAGE_HUB_RECV)
        if not self._enabled:
            return
        src = batch.source_address
        if not src:
            return
        delivery = None
        if header is not None:
            delivery = max(0, self._clock() - header.sent_us)
        with self.mu:
            link = self._links.get((src, local))
            if link is None:
                link = self._links[(src, local)] = \
                    _Link(self._delivery_samples)
            for m in batch.requests:
                link.recv[class_of(m.type)] += 1
            link.bytes_recv += nbytes
            link.batches_recv += 1
            if delivery is not None:
                link.delivery_us.append(delivery)
        for m in batch.requests:
            self._recv_ctr.labels(src, local, class_of(m.type)).inc()
        if delivery is not None:
            self._delivery_hist.labels(src, local).observe(delivery)

    def _walk_ctxs(self, local: str, header: pb.FabricHeader) -> None:
        """Per-context receive actions (tracer enabled)."""
        t = self._clock()
        for c in header.ctxs:
            if c.origin == local:
                # the quorum ack came home: close the remote child span
                lifecycle.TRACER.stamp(c.key, STAGE_ACK_RETURN)
                with self.mu:
                    retired = []
                    for hk in list(self._remote):
                        if hk[1] == c.key:
                            sp = self._remote.pop(hk)
                            sp.append((STAGE_ACK_RETURN, t))
                            retired.append(
                                {"host": hk[0], "key": c.key,
                                 "stamps": sp})
                    self._remote_ring.extend(retired)
            else:
                # an outbound replicate landed on a remote host
                lifecycle.TRACER.stamp(c.key, lifecycle.STAGE_HUB_RECV)
                with self.mu:
                    if (local, c.key) not in self._remote:
                        if len(self._remote) >= self._max_remote:
                            continue
                        self._remote[(local, c.key)] = [
                            (STAGE_REMOTE_RECV, t)]
                    parked = self._returns.setdefault(
                        (local, c.shard_id), [])
                    returned = pb.FabricContext(
                        key=c.key, origin=c.origin, hop=c.hop + 1,
                        shard_id=c.shard_id)
                    if len(parked) < self._max_remote:
                        parked.append(returned)

    # -- hop census -------------------------------------------------------

    def _census_finish(self, key: int, kind: str) -> None:
        """Lifecycle finish hook: retire the commit's census entry."""
        if kind != "proposal":
            return
        with self.mu:
            cen = self._census.pop(key, None)
            if cen is None:
                return
            self._census_finished += 1
            self._hops_done.append(cen["hops"])
            # the span is over: any unreturned contexts / open remote
            # spans for this key are garbage now
            for hk in [hk for hk in self._remote if hk[1] == key]:
                del self._remote[hk]
            for rk in list(self._returns):
                kept = [c for c in self._returns[rk] if c.key != key]
                if kept:
                    self._returns[rk] = kept
                else:
                    del self._returns[rk]
        self._hops_hist.observe(cen["hops"])

    def _census_drop(self, key: int, kind: str) -> None:
        """Lifecycle scrub hook: a traced commit died uncommitted."""
        if kind != "proposal":
            return
        with self.mu:
            if self._census.pop(key, None) is not None:
                self._census_dropped += 1

    # -- export -----------------------------------------------------------

    def host_pid(self, addr: str) -> int:
        """Stable Chrome-trace pid for a host address."""
        with self.mu:
            pid = self._host_pids.get(addr)
            if pid is None:
                pid = self._host_pids[addr] = (
                    HOST_PID_BASE + len(self._host_pids))
            return pid

    def chrome_events(self) -> list[dict]:
        """Retired remote child spans as complete Chrome trace events:
        ``pid`` = host (offset so shard rows never collide), ``tid`` =
        the proposal key — the same tid as the origin's lifecycle span,
        so Perfetto stitches the two timelines into one trace."""
        with self.mu:
            retired = [dict(sp, stamps=list(sp["stamps"]))
                       for sp in self._remote_ring]
        events = []
        for sp in retired:
            pid = self.host_pid(sp["host"])
            stamps = sp["stamps"]
            for i, (stage, ts) in enumerate(stamps):
                dur = (stamps[i + 1][1] - ts) if i + 1 < len(stamps) else 0
                events.append({
                    "name": stage, "cat": "fabric", "ph": "X",
                    "ts": ts, "dur": max(0, dur),
                    "pid": pid, "tid": sp["key"],
                    "args": {"host": sp["host"], "key": sp["key"]},
                })
        return events

    def snapshot(self) -> dict:
        """The merged JSON-able fabric view (``/debug/fabric``,
        ``NodeHost.info()["fabric"]``).  Validated by
        ``validate_fabric`` — the same strict-schema doctrine as
        ``capacity.validate_capacity``."""
        with self.mu:
            links = []
            for (src, dst) in sorted(self._links):
                li = self._links[(src, dst)]
                samples = sorted(li.delivery_us)
                links.append({
                    "src": src, "dst": dst,
                    "sent": dict(li.sent), "recv": dict(li.recv),
                    "bytes_sent": li.bytes_sent,
                    "bytes_recv": li.bytes_recv,
                    "batches_sent": li.batches_sent,
                    "batches_recv": li.batches_recv,
                    "delivery_count": len(samples),
                    "delivery_p50_us": (
                        _quantile(samples, 0.50) if samples else 0.0),
                    "delivery_p99_us": (
                        _quantile(samples, 0.99) if samples else 0.0),
                })
            done = sorted(self._hops_done)
            hop_counts: dict[str, int] = {}
            for h in done:
                hop_counts[str(h)] = hop_counts.get(str(h), 0) + 1
            census = {
                "active": len(self._census),
                "finished": self._census_finished,
                "dropped": self._census_dropped,
                "p50_commit_host_hops": (
                    _quantile(done, 0.50) if done else 0.0),
                "hop_counts": hop_counts,
            }
            remote = {"active": len(self._remote),
                      "retired": len(self._remote_ring)}
            link_classes = {f"{src}->{dst}": cls
                            for (src, dst), cls
                            in sorted(self._link_classes.items())}
            hubs = list(self._hubs.items())
            enabled = self._enabled
        hub_view = {}
        for addr, ref in hubs:
            hub = ref()
            if hub is None:
                continue
            with hub.mu:
                depth = sum(len(q) for q in hub.queues.values())
                qbytes = sum(hub.queue_bytes.values())
                breakers = list(hub.breakers.items())
            # breaker states evaluated outside the hub lock (each takes
            # its own) — the snapshot thread never holds two locks
            hub_view[addr] = {
                "queue_msgs": depth,
                "queue_bytes": qbytes,
                "breakers": {peer: b.state()
                             for peer, b in sorted(breakers)},
            }
        return {"enabled": enabled, "links": links, "census": census,
                "remote_spans": remote, "hubs": hub_view,
                "link_classes": link_classes}


def validate_fabric(obj, where: str = "fabric") -> int:
    """Strict schema validation of a ``FabricMeter.snapshot()`` payload;
    returns the link count.  Raises ``ValueError`` on any missing key,
    wrong type, unknown message class, unknown breaker state, or
    negative counter — the same parser-strictness doctrine as
    ``telemetry.parse_exposition``."""
    if not isinstance(obj, dict):
        raise ValueError(f"{where}: must be an object, "
                         f"got {type(obj).__name__}")
    for req in ("enabled", "links", "census", "remote_spans", "hubs",
                "link_classes"):
        if req not in obj:
            raise ValueError(f"{where}: missing required key {req!r}")
    lc = obj["link_classes"]
    if not isinstance(lc, dict):
        raise ValueError(f"{where}.link_classes: must be an object")
    for link, cls in lc.items():
        if not isinstance(link, str) or "->" not in link:
            raise ValueError(f"{where}.link_classes: key {link!r} must "
                             f"be a 'src->dst' string")
        if cls not in LINK_CLASSES:
            raise ValueError(f"{where}.link_classes.{link}: unknown "
                             f"link class {cls!r}")
    if not isinstance(obj["enabled"], bool):
        raise ValueError(f"{where}.enabled: must be a bool")
    if not isinstance(obj["links"], list):
        raise ValueError(f"{where}.links: must be an array")
    for i, li in enumerate(obj["links"]):
        w = f"{where}.links[{i}]"
        if not isinstance(li, dict):
            raise ValueError(f"{w}: must be an object")
        for req in ("src", "dst", "sent", "recv", "bytes_sent",
                    "bytes_recv", "batches_sent", "batches_recv",
                    "delivery_count", "delivery_p50_us",
                    "delivery_p99_us"):
            if req not in li:
                raise ValueError(f"{w}: missing required key {req!r}")
        for s in ("src", "dst"):
            if not isinstance(li[s], str) or not li[s]:
                raise ValueError(f"{w}.{s}: must be a non-empty string")
        for side in ("sent", "recv"):
            d = li[side]
            if not isinstance(d, dict):
                raise ValueError(f"{w}.{side}: must be an object")
            for cls, n in d.items():
                if cls not in MESSAGE_CLASSES:
                    raise ValueError(
                        f"{w}.{side}: unknown message class {cls!r}")
                if not isinstance(n, int) or n < 0:
                    raise ValueError(f"{w}.{side}.{cls}: must be a "
                                     f"non-negative int, got {n!r}")
        for k in ("bytes_sent", "bytes_recv", "batches_sent",
                  "batches_recv", "delivery_count"):
            if not isinstance(li[k], int) or li[k] < 0:
                raise ValueError(f"{w}.{k}: must be a non-negative int, "
                                 f"got {li[k]!r}")
        for k in ("delivery_p50_us", "delivery_p99_us"):
            if not isinstance(li[k], (int, float)) or li[k] < 0:
                raise ValueError(f"{w}.{k}: must be a non-negative "
                                 f"number, got {li[k]!r}")
    cen = obj["census"]
    if not isinstance(cen, dict):
        raise ValueError(f"{where}.census: must be an object")
    for req in ("active", "finished", "dropped", "p50_commit_host_hops",
                "hop_counts"):
        if req not in cen:
            raise ValueError(f"{where}.census: missing required "
                             f"key {req!r}")
    for k in ("active", "finished", "dropped"):
        if not isinstance(cen[k], int) or cen[k] < 0:
            raise ValueError(f"{where}.census.{k}: must be a "
                             f"non-negative int, got {cen[k]!r}")
    if (not isinstance(cen["p50_commit_host_hops"], (int, float))
            or cen["p50_commit_host_hops"] < 0):
        raise ValueError(f"{where}.census.p50_commit_host_hops: must be "
                         f"a non-negative number")
    if not isinstance(cen["hop_counts"], dict):
        raise ValueError(f"{where}.census.hop_counts: must be an object")
    for h, n in cen["hop_counts"].items():
        if not h.isdigit() or not isinstance(n, int) or n <= 0:
            raise ValueError(f"{where}.census.hop_counts[{h!r}]: must "
                             f"map a digit string to a positive int")
    rem = obj["remote_spans"]
    if not isinstance(rem, dict):
        raise ValueError(f"{where}.remote_spans: must be an object")
    for k in ("active", "retired"):
        if (k not in rem or not isinstance(rem[k], int) or rem[k] < 0):
            raise ValueError(f"{where}.remote_spans.{k}: must be a "
                             f"non-negative int")
    if not isinstance(obj["hubs"], dict):
        raise ValueError(f"{where}.hubs: must be an object")
    for addr, hv in obj["hubs"].items():
        w = f"{where}.hubs[{addr!r}]"
        if not isinstance(hv, dict):
            raise ValueError(f"{w}: must be an object")
        for k in ("queue_msgs", "queue_bytes"):
            if (k not in hv or not isinstance(hv[k], int) or hv[k] < 0):
                raise ValueError(f"{w}.{k}: must be a non-negative int")
        if "breakers" not in hv or not isinstance(hv["breakers"], dict):
            raise ValueError(f"{w}.breakers: must be an object")
        for peer, state in hv["breakers"].items():
            if state not in ("closed", "open", "half-open"):
                raise ValueError(f"{w}.breakers[{peer!r}]: unknown "
                                 f"state {state!r}")
    return len(obj["links"])


# process-wide meter: the transport hubs and the NodeHost inbound seam
# account here so one registry shows every link in the process (the
# same one-recorder doctrine as flight.RECORDER / lifecycle.TRACER).
# NodeHost.__init__ re-points ``enabled`` at its expert config.
METER = FabricMeter()

# census retirement rides the tracer's completion hooks: finish
# observes the hop count, scrub drops the entry (proposal spans only —
# read spans are host-local).  Registered for the GLOBAL meter alone;
# test-private meters wire their own tracer's hooks explicitly.
lifecycle.TRACER.set_hooks(on_finish=METER._census_finish,
                           on_scrub=METER._census_drop)
