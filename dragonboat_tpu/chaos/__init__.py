"""Deterministic chaos harness: seeded fault plans over the storage,
transport, and process seams, driven by a schedule runner with a
convergence oracle (the reference's monkey.go nightly harness,
docs/test.md, re-expressed as replayable fault schedules).

- :mod:`dragonboat_tpu.chaos.faultplan` — seeded FaultPlan generation
  and the canonical-JSON trace contract (same seed -> byte-identical
  trace; a recorded trace replays as a plan).
- :mod:`dragonboat_tpu.chaos.crashfs` — CrashPointFS, an ErrorFS that
  trips at the Nth matching op, optionally tearing the final write.
- :mod:`dragonboat_tpu.chaos.oracle` — pure convergence checks: zero
  committed-entry loss, identical committed prefixes, monotone applied
  indices, hash equality.
- :mod:`dragonboat_tpu.chaos.runner` — builds a MemFS cluster, executes
  a plan against it, and returns the recorded trace + oracle report.
"""

from dragonboat_tpu.chaos.crashfs import CrashPointFS
from dragonboat_tpu.chaos.faultplan import FaultEvent, FaultPlan
from dragonboat_tpu.chaos.oracle import OracleReport, check_convergence
from dragonboat_tpu.chaos.runner import (
    DetectorResult,
    HotspotResult,
    ScheduleResult,
    run_detector_differential,
    run_hotspot,
    run_schedule,
)

__all__ = [
    "CrashPointFS",
    "DetectorResult",
    "FaultEvent",
    "FaultPlan",
    "HotspotResult",
    "OracleReport",
    "check_convergence",
    "ScheduleResult",
    "run_detector_differential",
    "run_hotspot",
    "run_schedule",
]
