"""Schedule runner — executes a FaultPlan against a live MemFS cluster.

One run builds ``n_replicas`` durable NodeHosts, each over its OWN
MemFS (so per-host power loss is ``that_fs.crash()``) wrapped in a
:class:`CrashPointFS` (so storage faults arm per host), all joined by
the chan transport.  The plan's steps interleave with a write workload;
every executed event is recorded, and the recorded trace is canonical
JSON — running the same seed twice yields byte-identical traces
(tests/test_chaos_schedules.py asserts exactly that).

This module intentionally uses the wall clock: it WAITS on real raft
progress (elections, replication, restart recovery), so it is excluded
from the determinism lint's replay-path globs.  The deterministic
contract lives in faultplan/crashfs/oracle, which are covered.
"""

from __future__ import annotations

import struct
import time
import zlib
from dataclasses import dataclass, field
from random import Random

from dragonboat_tpu import flight
from dragonboat_tpu.chaos.crashfs import CrashPointFS
from dragonboat_tpu.chaos.faultplan import FaultPlan, canonical_json
from dragonboat_tpu.chaos.oracle import (OracleReport, check_convergence,
                                         check_hot_drained,
                                         check_invariant_probe,
                                         check_journals_equal,
                                         check_no_acked_loss)
from dragonboat_tpu.config import (
    Config,
    ExpertConfig,
    LogDBConfig,
    MeshSpec,
    NodeHostConfig,
)
from dragonboat_tpu.nodehost import NodeHost
from dragonboat_tpu.statemachine import IStateMachine, Result
from dragonboat_tpu.vfs import MemFS


class ChaosKV(IStateMachine):
    """Workload SM: kv store plus an append-only journal of every
    applied command — the committed-prefix observable the oracle
    compares across replicas (monkey-test HashKV with history)."""

    def __init__(self, shard_id, replica_id):
        self.kv = {}
        self.journal: list[bytes] = []

    def update(self, entry):
        cmd = bytes(entry.cmd)
        self.journal.append(cmd)
        k, v = cmd.decode().split("=", 1)
        self.kv[k] = v
        return Result(value=len(self.journal))

    def lookup(self, query):
        return self.kv.get(query)

    def save_snapshot(self, w, files, done):
        blob = b"\x00".join(self.journal)
        w.write(struct.pack("<I", len(blob)))
        w.write(blob)

    def recover_from_snapshot(self, r, files, done):
        (n,) = struct.unpack("<I", r.read(4))
        blob = r.read(n)
        self.journal = blob.split(b"\x00") if blob else []
        self.kv = {}
        for cmd in self.journal:
            k, v = cmd.decode().split("=", 1)
            self.kv[k] = v

    def get_hash(self) -> int:
        return zlib.crc32(b"\x00".join(self.journal))


def _counter_pred(every: int):
    """Deterministic per-message predicate: True on every Nth call."""
    state = {"n": 0}

    def pred(_m) -> bool:
        state["n"] += 1
        return state["n"] % every == 0
    return pred


@dataclass
class ScheduleResult:
    seed: int
    trace_json: str
    report: OracleReport
    acked_count: int
    plan_json: str


@dataclass
class _Cluster:
    seed: int
    n: int
    # run shards as lanes of the batched device kernel instead of host
    # Peers, optionally through the depth-1 software pipeline — chaos
    # then exercises crash/restart with a donated step in flight
    device_resident: bool = False
    pipeline_depth: int = 0
    # run shards as rows of the shared MESH engine (one replica per
    # device along axis 'r'): partition/delay/drop faults then drive the
    # round-17 per-link cut masks and hub fallback instead of the chan
    # transport alone
    mesh_resident: bool = False
    # extra ExpertConfig kwargs (detector differentials tune the health
    # cadence/thresholds per fault kind)
    expert_overrides: dict = field(default_factory=dict)
    # shard ids started on every host and the workload SM they run; the
    # hotspot differential skews proposals across two shards to heat
    # exactly one of them
    shards: tuple = (1,)
    sm_cls: type = ChaosKV
    hosts: dict = field(default_factory=dict)      # rid -> NodeHost
    mems: dict = field(default_factory=dict)       # rid -> MemFS
    fss: dict = field(default_factory=dict)        # rid -> CrashPointFS
    addrs: dict = field(default_factory=dict)
    cfgs: dict = field(default_factory=dict)       # (rid, shard) -> Config
    epochs: dict = field(default_factory=dict)     # rid -> restart epoch
    # acked-proposal counters harvested from hosts REPLACED by a process
    # restart (a fresh NodeHost starts a fresh registry at zero); the
    # telemetry invariant sums these with every current host's counter
    acked_base: dict = field(default_factory=dict)  # rid -> int

    SHARD = 1

    def start(self) -> None:
        self.addrs = {rid: f"cs{self.seed}-{rid}"
                      for rid in range(1, self.n + 1)}
        for rid in sorted(self.addrs):
            self.mems[rid] = MemFS()
            self.epochs[rid] = 0
            self._spawn(rid)

    def _nhconfig(self, rid: int) -> NodeHostConfig:
        kw = dict(
            fs=self.fss[rid],
            kernel_log_cap=256, kernel_capacity=4,
            kernel_pipeline_depth=self.pipeline_depth,
            logdb=LogDBConfig(shards=1, recovery_mode="quarantine"))
        if self.mesh_resident:
            # one shared ('g','r') = (1, n) mesh across the hosts; the
            # spec name keys the engine registry so every host attaches
            # to the SAME engine (one device per replica slot)
            kw["mesh"] = MeshSpec(name=f"cs{self.seed}-mesh", g_size=1,
                                  replicas=self.n, n_local=1)
        kw.update(self.expert_overrides)
        return NodeHostConfig(
            raft_address=self.addrs[rid], rtt_millisecond=5,
            node_host_dir="/data",
            expert=ExpertConfig(**kw))

    def _spawn(self, rid: int) -> None:
        """Fresh NodeHost (+ fresh CrashPointFS) over rid's MemFS."""
        old = self.hosts.get(rid)
        if old is not None:
            self.acked_base[rid] = (self.acked_base.get(rid, 0)
                                    + self._acked_counter(old))
        self.fss[rid] = CrashPointFS(self.mems[rid])
        nh = NodeHost(self._nhconfig(rid))
        for sid in self.shards:
            cfg = Config(shard_id=sid, replica_id=rid, election_rtt=10,
                         heartbeat_rtt=1, snapshot_entries=0,
                         compaction_overhead=5,
                         device_resident=self.device_resident,
                         mesh_resident=self.mesh_resident)
            self.cfgs[(rid, sid)] = cfg
            nh.start_replica(dict(self.addrs), False, self.sm_cls, cfg)
        self.hosts[rid] = nh

    # -- liveness --------------------------------------------------------

    def live(self, rid: int) -> bool:
        nh = self.hosts[rid]
        return nh.fatal_error is None and not nh._stopped

    def live_rids(self) -> list:
        return [rid for rid in sorted(self.hosts) if self.live(rid)]

    def reset_breakers(self) -> None:
        """Post-heal: close every breaker so recovery is not paced by
        leftover backoff cooldowns (production relies on the backoff
        probes; the harness heals instantly to keep schedules fast)."""
        for rid in self.live_rids():
            hub = self.hosts[rid].hub
            for addr in sorted(self.addrs.values()):
                hub.breaker(addr).succeed()

    # -- telemetry observations ------------------------------------------

    @staticmethod
    def _acked_counter(nh) -> int:
        try:
            snap = nh.events.metrics.snapshot()
            return int(snap.get("raft.proposals_acked", 0))
        except Exception:
            return 0

    def acked_total(self) -> int:
        """Acked-proposal counter summed across every host epoch: dead
        hosts' registries are still readable (snapshot is a pure dict
        walk), and replaced hosts' counts live in ``acked_base``."""
        total = sum(self.acked_base.values())
        for rid in sorted(self.hosts):
            total += self._acked_counter(self.hosts[rid])
        return total

    def leaderless_total(self) -> int:
        """Sum of the ``health.leaderless_now`` callback gauge over
        live, unpartitioned hosts (evaluated through the legacy snapshot
        view so this exercises the same path a scrape does).  The health
        engine's merged snapshot counts host-resident shards alongside
        device/mesh rows, so the oracle and the anomaly detector read
        ONE source of truth."""
        total = 0
        for rid in self.live_rids():
            nh = self.hosts[rid]
            if nh._partitioned:
                continue
            snap = nh.events.metrics.snapshot()
            total += int(snap.get("health.leaderless_now", 0))
        return total

    def invariant_counters(self) -> dict:
        """Invariant-probe counters merged across every live host's
        engines (the same `_invariants_snapshot` view a scrape reads).
        ``violations_seen`` is sticky per engine lifetime, so a
        transient mid-schedule trip survives to this harvest."""
        from dragonboat_tpu.core import invariants as _invariants

        base = _invariants.empty_dict()
        base["violations_seen"] = 0
        for rid in self.live_rids():
            d = self.hosts[rid]._invariants_snapshot()
            _invariants.merge_into(base, d, engine=f"r{rid}")
            base["violations_seen"] += int(d.get("violations_seen", 0))
        return base

    # -- event execution -------------------------------------------------

    def execute(self, ev) -> dict:
        flight.record(flight.CHAOS_FAULT, fault=ev.kind, target=ev.target,
                      params=dict(ev.params))
        fn = getattr(self, "_ev_" + ev.kind)
        return fn(ev.target, dict(ev.params))

    def _ev_drop(self, rid: int, p: dict) -> dict:
        self.hosts[rid].transport.drop_predicate = _counter_pred(p["every"])
        # device-resident mesh links never see transport predicates —
        # force this host's links onto the hub so the fault applies
        self.hosts[rid]._set_mesh_hub_served(True)
        return {"applied": self.live(rid)}

    def _ev_delay(self, rid: int, p: dict) -> dict:
        secs = p["seconds"]
        self.hosts[rid].transport.delay_func = lambda m: secs
        self.hosts[rid]._set_mesh_hub_served(True)
        return {"applied": self.live(rid)}

    def _ev_duplicate(self, rid: int, p: dict) -> dict:
        self.hosts[rid].transport.duplicate_predicate = _counter_pred(
            p["every"])
        return {"applied": self.live(rid)}

    def _ev_reorder(self, rid: int, p: dict) -> dict:
        self.hosts[rid].transport.reorder_rng = Random(p["seed"])
        return {"applied": self.live(rid)}

    def _ev_heal_transport(self, rid: int, p: dict) -> dict:
        t = self.hosts[rid].transport
        t.drop_predicate = None
        t.delay_func = None
        t.duplicate_predicate = None
        t.reorder_rng = None
        # restore this host's mesh links resident (drop/delay cut them)
        self.hosts[rid]._set_mesh_hub_served(False)
        return {"applied": True}

    def _ev_partition(self, rid: int, p: dict) -> dict:
        self.hosts[rid].partition_node()
        return {"applied": True}

    def _ev_restore_partition(self, rid: int, p: dict) -> dict:
        self.hosts[rid].restore_partitioned_node()
        self.reset_breakers()
        return {"applied": True}

    def _ev_breaker_trip(self, rid: int, p: dict) -> dict:
        target_addr = self.addrs[rid]
        for other in self.live_rids():
            if other != rid:
                self.hosts[other].hub.trip_breaker(
                    target_addr, count=p["count"])
        return {"applied": True}

    def _ev_heal_breaker(self, rid: int, p: dict) -> dict:
        self.reset_breakers()
        return {"applied": True}

    def _ev_crash_write(self, rid: int, p: dict) -> dict:
        self.fss[rid].arm(p["after_ops"], torn=p["torn"])
        tripped = self._pump_until(
            lambda: self.hosts[rid].fatal_error is not None, timeout=15.0)
        return {"tripped": tripped}

    def _ev_restart_inplace(self, rid: int, p: dict) -> dict:
        self.fss[rid].heal()
        self.hosts[rid].restart()
        self.epochs[rid] += 1
        self.reset_breakers()
        return {"restarted": True}

    def _ev_kill(self, rid: int, p: dict) -> dict:
        self.hosts[rid].simulate_kill()
        # the process is gone: unsynced bytes vanish, its flocks release
        self.mems[rid].crash()
        return {"killed": True}

    def _ev_restart_process(self, rid: int, p: dict) -> dict:
        self._spawn(rid)
        self.epochs[rid] += 1
        self.reset_breakers()
        return {"restarted": True}

    # -- workload --------------------------------------------------------

    def propose(self, cmd: bytes, timeout: float = 8.0,
                shard: int | None = None) -> bool:
        """Propose through any live host (host routing forwards to the
        leader); True once acked.  Duplicate commits from retried
        timeouts are fine — the oracle compares journals for equality,
        and a duplicate lands identically on every replica."""
        sid = self.SHARD if shard is None else shard
        deadline = time.time() + timeout
        while time.time() < deadline:
            for rid in self.live_rids():
                nh = self.hosts[rid]
                if nh._partitioned:
                    continue
                try:
                    nh.sync_propose(nh.get_noop_session(sid), cmd,
                                    timeout_s=1.5)
                    return True
                except Exception:
                    continue
            time.sleep(0.02)
        return False

    def _pump_until(self, cond, timeout: float) -> bool:
        """Feed proposals until ``cond`` holds (durability traffic is
        what walks an armed CrashPointFS to its trip)."""
        deadline = time.time() + timeout
        i = 0
        while time.time() < deadline:
            if cond():
                return True
            self.propose(f"pump{i}=x".encode(), timeout=1.0)
            i += 1
        return cond()

    # -- observations ----------------------------------------------------

    def sample(self, applied_samples: dict) -> None:
        for rid in self.live_rids():
            nh = self.hosts[rid]
            if nh._partitioned:
                continue
            try:
                applied = nh._node(self.SHARD).sm.get_last_applied()
            except Exception:
                continue
            applied_samples.setdefault(rid, []).append(
                (self.epochs[rid], applied))

    def journals(self, shard: int | None = None) -> dict:
        sid = self.SHARD if shard is None else shard
        out = {}
        for rid in self.live_rids():
            try:
                out[rid] = list(
                    self.hosts[rid]._node(sid).sm.sm.journal)
            except Exception:
                continue
        return out

    def hashes(self, kind: str) -> dict:
        fn = {"sm": "get_sm_hash", "session": "get_session_hash",
              "membership": "get_membership_hash"}[kind]
        out = {}
        for rid in self.live_rids():
            try:
                out[rid] = getattr(self.hosts[rid], fn)(self.SHARD)
            except Exception:
                continue
        return out

    def close(self) -> None:
        for rid in sorted(self.hosts):
            nh = self.hosts[rid]
            try:
                if nh.fatal_error is not None and nh._stopped:
                    continue        # killed/crashed and never restarted
                nh.close()
            except Exception:
                pass


def run_schedule(seed: int, plan: FaultPlan | None = None,
                 n_replicas: int = 3, steps: int = 6,
                 proposals_per_step: int = 4,
                 converge_timeout: float = 30.0,
                 device_resident: bool = False,
                 pipeline_depth: int = 0,
                 mesh_resident: bool = False) -> ScheduleResult:
    """Execute one composed fault schedule; returns the recorded trace
    (canonical JSON) and the oracle report.  Pass ``plan`` to replay a
    recorded trace (``FaultPlan.from_json``) instead of generating.
    ``device_resident=True`` runs the shards on the batched kernel
    engine, ``pipeline_depth=1`` additionally through the overlapped
    donating step loop — so faults land while a step is in flight.
    ``mesh_resident=True`` runs them as rows of one shared mesh engine:
    transport faults then exercise the per-link cut masks (hub
    fallback) instead of the chan transport alone."""
    if plan is None:
        plan = FaultPlan.generate(seed, n_replicas=n_replicas, steps=steps)
    cluster = _Cluster(seed=seed, n=plan.n_replicas,
                       device_resident=device_resident,
                       pipeline_depth=pipeline_depth,
                       mesh_resident=mesh_resident)
    executed: list = []
    acked: list = []
    applied_samples: dict = {}
    report = OracleReport()
    try:
        cluster.start()
        # settle: a leader before the first fault
        cluster.propose(b"genesis=1", timeout=10.0) or report.fail(
            "no initial commit — cluster never settled")
        for step in range(plan.steps + 1):
            for ev in plan.events_at(step):
                outcome = cluster.execute(ev)
                executed.append({**ev.as_dict(), "outcome": outcome})
                if outcome.get("tripped") is False:
                    report.fail(f"crash point on replica {ev.target} "
                                "never tripped")
            if step < plan.steps:
                for i in range(proposals_per_step):
                    cmd = f"s{step}i{i}=v{seed}".encode()
                    if cluster.propose(cmd):
                        acked.append(cmd)
                cluster.sample(applied_samples)
        # every replica is healed now; wait for full convergence
        deadline = time.time() + converge_timeout
        converged = False
        while time.time() < deadline and not converged:
            cluster.sample(applied_samples)
            js = cluster.journals()
            if len(js) == cluster.n:
                vals = list(js.values())
                have = set(vals[0])
                converged = all(v == vals[0] for v in vals[1:]) and all(
                    c in have for c in acked)
            if not converged:
                time.sleep(0.1)
        if not converged:
            report.fail("cluster did not converge after final heal")
        report.merge(check_convergence(
            acked, cluster.journals(), applied_samples,
            cluster.hashes("sm"), cluster.hashes("session"),
            cluster.hashes("membership")))
        # telemetry invariants — the observability layer must agree with
        # the oracle's ground truth after every schedule:
        # 1. every ack the workload observed is in some host's acked
        #    counter (counters also see pump/genesis traffic, so >=)
        acked_seen = cluster.acked_total()
        if acked_seen < len(acked):
            report.fail(f"acked-proposal counter {acked_seen} < "
                        f"{len(acked)} oracle-observed acks — telemetry "
                        "lost acked writes")
        # 2. the leaderless gauge returns to 0 once converged.  A
        #    follower may learn the leader an append after the journals
        #    equalize, so this is a deadline-bounded wait — but EVENT-
        #    driven, not a sleep-poll: every transition that can clear
        #    leaderlessness lands a flight record (leader_change from
        #    host-resident elections, anomaly_cleared from the device
        #    health engines), so the oracle re-reads the gauge exactly
        #    when the recorder wakes it
        if converged:
            deadline = time.time() + 5.0
            seq = flight.RECORDER.next_seq
            leaderless = cluster.leaderless_total()
            while leaderless and time.time() < deadline:
                # wait for record #seq to land (anything after the gauge
                # read), capped so a transition the recorder missed
                # (e.g. a pre-sample race) still re-checks promptly
                flight.RECORDER.wait_beyond(
                    seq, timeout=min(0.5, max(0.0,
                                              deadline - time.time())))
                seq = flight.RECORDER.next_seq
                leaderless = cluster.leaderless_total()
            if leaderless:
                report.fail(f"health.leaderless_now gauge stuck at "
                            f"{leaderless} after convergence")
        # 3. the runtime invariant probe stayed silent: no interleaving
        #    of faults may produce a protocol-invariant violation.  The
        #    harvested counters ride the report either way, so every
        #    schedule's verdict records what the probe observed.
        report.invariant_probe = cluster.invariant_counters()
        report.merge(check_invariant_probe(report.invariant_probe))
        if not report.ok:
            # attach the flight-recorder tail so a failure report carries
            # the recent structured transitions (leader changes, trips,
            # chaos faults) alongside the oracle verdict
            report.flight_tail = flight.RECORDER.tail(64)
    finally:
        cluster.close()
    return ScheduleResult(
        seed=seed, trace_json=canonical_json(executed), report=report,
        acked_count=len(acked), plan_json=plan.to_json())


# -- detector differential --------------------------------------------------
#
# The fleet-health engine (core/health.py) is itself under chaos test:
# each fault kind below must raise its MAPPED anomaly class during the
# fault window (observed via the flight recorder's anomaly_raised edge,
# so a one-tick flag cannot be missed by a polling race), every class
# must clear to zero after the heal converges, and at sampled instants
# the device report is cross-checked byte-for-byte against the
# pure-python recount oracle.

#: fault kind -> the anomaly class it must raise
DETECTOR_FAULT_CLASS = {
    # no quorum anywhere: every lane sits candidate/leaderless
    "isolate_quorum": "leaderless",
    # back-to-back leadership transfers: known-leader -> known-leader
    # handoffs pump the churn leaky bucket
    "leader_flap": "churn",
    # a partitioned replica campaigns forever (pre_vote off), its term
    # rising tick over tick
    "campaign_storm": "term_runaway",
}
DETECTOR_FAULTS = tuple(sorted(DETECTOR_FAULT_CLASS))


@dataclass
class DetectorResult:
    seed: int
    fault: str
    anomaly_class: str
    raised: bool              # mapped class raised inside the window
    cleared: bool             # ALL classes zero after convergence
    differential_checks: int  # recount cross-checks performed
    failures: list

    @property
    def ok(self) -> bool:
        return not self.failures


def _health_differential(eng) -> tuple[bool, dict, dict]:
    """Sample one engine's (state, inbox, digest) under its lock and
    compare the jitted fleet_health report against the pure-python
    recount — the device detector and the oracle must agree exactly."""
    import jax

    from dragonboat_tpu.core import health as _health

    with eng.mu:
        if eng._health_digest is None:
            eng._health_digest = eng._make_health_digest()
        state, inbox = eng.state, eng._fleet_inbox_from()
        digest = eng._health_digest
        report, _ = _health.fleet_health(
            state, inbox, digest, thresholds=eng.health_thresholds,
            k=eng.health_top_k)
        state_h = jax.device_get(state)
        inbox_h = jax.device_get(inbox)
        digest_h = jax.device_get(digest)
    dev = _health.report_to_dict(report)
    ref, _ = _health.recount(state_h, inbox_h, digest_h,
                             thresholds=eng.health_thresholds,
                             k=eng.health_top_k)
    return dev == ref, dev, ref


def _wait_anomaly_raised(cls: str, since_seq: int, deadline: float) -> bool:
    """Event-driven wait for an anomaly_raised flight record of ``cls``
    recorded at sequence >= ``since_seq``."""
    while True:
        scanned_to = flight.RECORDER.next_seq
        for rec in flight.RECORDER.tail():
            if (rec["seq"] >= since_seq
                    and rec["kind"] == flight.ANOMALY_RAISED
                    and rec.get("cls") == cls):
                return True
        remaining = deadline - time.time()
        if remaining <= 0:
            return False
        # block until record #scanned_to lands (anything newer than the
        # tail scan above), capped for safety against ring overwrite
        flight.RECORDER.wait_beyond(scanned_to,
                                    timeout=min(0.5, remaining))


def run_detector_differential(seed: int, fault: str | None = None,
                              n_replicas: int = 3,
                              fault_window: float = 25.0,
                              converge_timeout: float = 30.0
                              ) -> DetectorResult:
    """Run ONE fault schedule against a device-resident cluster and
    check the health engine's verdicts (see module comment above).
    ``fault`` defaults to ``DETECTOR_FAULTS[seed % 3]`` so consecutive
    seeds sweep the taxonomy."""
    from dragonboat_tpu.core import health as _health

    if fault is None:
        fault = DETECTOR_FAULTS[seed % len(DETECTOR_FAULTS)]
    cls = DETECTOR_FAULT_CLASS[fault]
    # fast health ticks; per-fault threshold tuning keeps the windows
    # short without loosening what is being detected
    overrides: dict = {"fleet_stats_every": 5}
    if fault == "leader_flap":
        # one observed known->known handoff trips the bucket
        overrides["health_churn_trip"] = _health.CHURN_INC
    elif fault == "campaign_storm":
        # campaigns fire every ~election timeout; stretch the tick so
        # each consecutive pair of ticks sees a higher term
        overrides["fleet_stats_every"] = 20
        overrides["health_runaway_ticks"] = 2
    cluster = _Cluster(seed=seed, n=n_replicas, device_resident=True,
                       expert_overrides=overrides)
    failures: list = []
    raised = False
    cleared = False
    diff_checks = 0

    def check_diff(rid: int, where: str) -> None:
        nonlocal diff_checks
        eng = cluster.hosts[rid].kernel_engine
        if eng is None:
            failures.append(f"{where}: replica {rid} has no kernel engine")
            return
        ok, dev, ref = _health_differential(eng)
        diff_checks += 1
        if not ok:
            failures.append(f"{where}: device report diverged from "
                            f"recount: {dev} != {ref}")

    def wait_leader(timeout: float) -> int:
        deadline = time.time() + timeout
        while time.time() < deadline:
            for rid in cluster.live_rids():
                nh = cluster.hosts[rid]
                if nh._partitioned:
                    continue
                try:
                    lid, ok = nh.get_leader_id(cluster.SHARD)
                except Exception:
                    continue
                if ok and lid:
                    return lid
            time.sleep(0.05)
        return 0

    try:
        cluster.start()
        # generous settle: the FIRST device-resident cluster in a
        # process pays the kernel jit compile inside this window
        if not cluster.propose(b"genesis=1", timeout=45.0):
            failures.append("no initial commit — cluster never settled")
        lid = wait_leader(10.0)
        if not lid:
            failures.append("no leader before fault injection")
        start_seq = flight.RECORDER.next_seq
        deadline = time.time() + fault_window
        rids = sorted(cluster.hosts)
        healed: list = []

        if fault == "isolate_quorum":
            # partition the leader AND one follower: the remaining host
            # campaigns without quorum, so every engine's lane persists
            # leaderless past the threshold
            victims = [lid] + [r for r in rids if r != lid][:1]
            for r in victims:
                cluster.hosts[r].partition_node()
                healed.append(r)
            raised = _wait_anomaly_raised(cls, start_seq, deadline)
            observe = next(r for r in rids if r not in victims)
            check_diff(observe, "mid-fault")
        elif fault == "leader_flap":
            # transfer leadership round-robin until the churn bucket
            # trips (two transfers usually suffice; the loop is bounded
            # by the fault window)
            while not raised and time.time() < deadline:
                cur = wait_leader(5.0)
                if not cur:
                    continue
                target = next(r for r in rids if r != cur)
                try:
                    cluster.hosts[cur].request_leader_transfer(
                        cluster.SHARD, target)
                except Exception:
                    pass
                raised = _wait_anomaly_raised(
                    cls, start_seq, min(deadline, time.time() + 2.0))
            check_diff(rids[0], "mid-fault")
        elif fault == "campaign_storm":
            victim = next(r for r in rids if r != lid)
            cluster.hosts[victim].partition_node()
            healed.append(victim)
            raised = _wait_anomaly_raised(cls, start_seq, deadline)
            check_diff(victim, "mid-fault")
        else:
            raise ValueError(f"unknown detector fault {fault!r}")
        if not raised:
            failures.append(f"fault {fault} never raised anomaly class "
                            f"{cls} within {fault_window}s")

        # heal and converge (the convergence oracle of run_schedule,
        # reduced to its journal-equality core)
        for r in healed:
            cluster.hosts[r].restore_partitioned_node()
        cluster.reset_breakers()
        marker = f"healed{seed}=1".encode()
        if not cluster.propose(marker, timeout=15.0):
            failures.append("post-heal proposal never acked")
        deadline = time.time() + converge_timeout
        converged = False
        while time.time() < deadline and not converged:
            js = cluster.journals()
            if len(js) == cluster.n:
                vals = list(js.values())
                converged = (all(v == vals[0] for v in vals[1:])
                             and marker in vals[0])
            if not converged:
                time.sleep(0.1)
        if not converged:
            failures.append("cluster did not converge after heal")

        # every class must clear to zero — event-driven on the flight
        # recorder (anomaly_cleared / leader_change wake the re-check)
        def counts_all_zero() -> bool:
            for rid in cluster.live_rids():
                eng = cluster.hosts[rid].kernel_engine
                d = getattr(eng, "last_health", None)
                if d and any(d["class_count"].values()):
                    return False
            return True

        deadline = time.time() + converge_timeout
        cleared = counts_all_zero()
        while not cleared and time.time() < deadline:
            seq = flight.RECORDER.next_seq
            flight.RECORDER.wait_beyond(
                seq, timeout=min(0.5, max(0.0, deadline - time.time())))
            cleared = counts_all_zero()
        if not cleared:
            failures.append("anomaly classes did not clear to zero "
                            "after convergence")
        check_diff(rids[0], "post-convergence")
    finally:
        cluster.close()
    return DetectorResult(seed=seed, fault=fault, anomaly_class=cls,
                          raised=raised, cleared=cleared,
                          differential_checks=diff_checks,
                          failures=failures)


# -- hotspot differential ---------------------------------------------------
#
# The elastic controller (control.py) is itself under chaos test: a
# zipfian proposal skew (HOTSPOT_SKEW:1) lands on ONE seeded-choice
# shard whose apply path is deliberately slow.  The engine retires
# apply outputs inside its step-timer window, so the backlog throttles
# the whole engine round and the hosts' step-latency EWMA
# (engine.kernel_step.ewma_us) climbs an order of magnitude — the
# host_hot signal the controller keys on (device commit→apply lag
# stays flow-controlled to a constant window, so lag_divergence is by
# design NOT the observable here).  The controller on the hot leader's
# host must flight-record a hysteresis-guarded control_transfer with
# its evidence row and leadership must actually leave the initially
# hot replica, all with zero acked-write loss across the handoff.

#: hot:cold proposals per pump round (the "100:1 onto one host" skew)
HOTSPOT_SKEW = 100
#: per-entry apply cost of HotspotKV — enough to inflate the engine
#: round well past HOTSPOT_HOT_EWMA_US under the skew, small enough
#: that the capped backlog drains well inside the convergence window
HOTSPOT_APPLY_DELAY_S = 0.01
#: host-hot threshold for the run: idle CPU steps measure ~10-15 ms,
#: the pump pushes the EWMA to ~90 ms, so 30 ms separates cleanly in
#: both directions
HOTSPOT_HOT_EWMA_US = 30_000
#: pump backpressure: stop firing once this many proposals are
#: unresolved — bounds the post-drain apply time (cap * delay) without
#: capping the overload signal (the EWMA saturates long before this)
HOTSPOT_MAX_PENDING = 800


class HotspotKV(ChaosKV):
    """ChaosKV with a deliberately slow apply path: under skewed load
    the apply backlog backpressures the engine round, inflating the
    step-latency EWMA the controller's host_hot gate reads."""

    def update(self, entry):
        time.sleep(HOTSPOT_APPLY_DELAY_S)
        return super().update(entry)


@dataclass
class HotspotResult:
    seed: int
    hot_shard: int
    cold_shard: int
    initial_leader: int       # replica leading the hot shard at pump start
    final_leader: int         # replica leading it after the drain
    transfers: list           # control_transfer flight records (hot shard)
    acked_count: int
    report: OracleReport

    @property
    def ok(self) -> bool:
        return self.report.ok


def run_hotspot(seed: int, n_replicas: int = 3,
                transfer_window: float = 30.0,
                converge_timeout: float = 45.0) -> HotspotResult:
    """Drive the zipfian skew onto one device-resident shard and check
    the observe→act loop end to end: the controller drains the hot
    host within the window (check_hot_drained), every acked write
    survives the handoff (check_no_acked_loss + journal equality per
    shard), the leaderless gauge returns to zero, and the runtime
    invariant probe stayed silent throughout."""
    rng = Random(seed)
    shards = (1, 2)
    hot = rng.choice(shards)
    cold = shards[0] if hot == shards[1] else shards[1]
    overrides = dict(
        # fast decimated observations; two consecutive hot observations
        # satisfy the hysteresis; the step-latency EWMA is the hot
        # signal (see the section comment)
        fleet_stats_every=5,
        control_enabled=True, control_hysteresis=2,
        control_cooldown_obs=8, control_max_transfers=1,
        control_seed=seed, control_hot_ewma_us=HOTSPOT_HOT_EWMA_US)
    cluster = _Cluster(seed=seed, n=n_replicas, device_resident=True,
                       expert_overrides=overrides, shards=shards,
                       sm_cls=HotspotKV)
    report = OracleReport()
    transfers: list = []
    pending: list = []        # (shard, cmd, RequestState) fired async
    initial_leader = 0
    final_leader = 0
    acked: dict = {hot: [], cold: []}

    def wait_leader(sid: int, timeout: float) -> int:
        deadline = time.time() + timeout
        while time.time() < deadline:
            for rid in cluster.live_rids():
                try:
                    lid, ok = cluster.hosts[rid].get_leader_id(sid)
                except Exception:
                    continue
                if ok and lid:
                    return lid
            time.sleep(0.05)
        return 0

    def fire(sid: int, cmd: bytes) -> None:
        # async propose: the futures are harvested after the pump stops.
        # The backlog IS the fault — a sync ack per proposal would
        # throttle the skew down to the apply rate and no lag would
        # ever build
        rids = cluster.live_rids()
        nh = cluster.hosts[rids[len(pending) % len(rids)]]
        try:
            rs = nh.propose(nh.get_noop_session(sid), cmd, timeout_s=15.0)
        except Exception:
            return            # book full / not ready: a drop, not an ack
        pending.append((sid, cmd, rs))

    def unresolved() -> int:
        return sum(1 for _, _, rs in pending if not rs._event.is_set())

    def max_ewma() -> int:
        return max((int(cluster.hosts[rid].events.metrics.snapshot()
                        .get("engine.kernel_step.ewma_us", 0))
                    for rid in cluster.live_rids()), default=0)

    try:
        cluster.start()
        # settle both shards (the first device-resident cluster in a
        # process pays the kernel jit compile inside this window)
        for sid in shards:
            if not cluster.propose(f"genesis{sid}=1".encode(),
                                   timeout=45.0, shard=sid):
                report.fail(f"shard {sid}: no initial commit — cluster "
                            "never settled")
        # compile warmup: the first steps carry the jit cost, so every
        # host's EWMA starts far above the threshold.  The policy's
        # warmup_obs suppresses controller action on that noise; the
        # harness additionally waits for the decay so the baseline
        # leader is read from a quiet fleet and start_seq excludes any
        # residual warmup decisions
        deadline = time.time() + 60.0
        while max_ewma() >= HOTSPOT_HOT_EWMA_US and time.time() < deadline:
            time.sleep(0.25)
        if max_ewma() >= HOTSPOT_HOT_EWMA_US:
            report.fail("engines never settled below the hot threshold "
                        "after compile warmup")
        initial_leader = wait_leader(hot, 10.0)
        if not initial_leader:
            report.fail("no leader on the hot shard before the pump")
        start_seq = flight.RECORDER.next_seq
        deadline = time.time() + transfer_window
        i = 0
        while time.time() < deadline and not transfers:
            if unresolved() < HOTSPOT_MAX_PENDING:
                batch = [hot] * HOTSPOT_SKEW + [cold]
                rng.shuffle(batch)
                for sid in batch:
                    fire(sid, f"h{sid}i{i}=v{seed}".encode())
                    i += 1
            transfers = [
                r for r in flight.RECORDER.tail()
                if r["seq"] >= start_seq
                and r["kind"] == flight.CONTROL_TRANSFER
                and r.get("shard_id") == hot]
            # let the apply backlog shape the next health digest before
            # re-scanning (the scan itself is cheap; the controller acts
            # on decimated ticks, not on our polling cadence)
            time.sleep(0.05)
        # bounded drain: leadership must actually leave the hot replica
        if transfers:
            deadline = time.time() + 15.0
            while time.time() < deadline:
                lid = wait_leader(hot, 5.0)
                if lid and lid != initial_leader:
                    final_leader = lid
                    break
                time.sleep(0.05)
            if not final_leader:
                final_leader = wait_leader(hot, 1.0)
        report.merge(check_hot_drained(initial_leader, final_leader,
                                       transfers))
        # pump stopped: resolve the outstanding futures (the backlog
        # drains at the slow-apply rate), then the completed ones are
        # exactly the acked set the loss oracle holds the fleet to
        deadline = time.time() + converge_timeout
        while unresolved() and time.time() < deadline:
            time.sleep(0.1)
        if unresolved():
            report.fail(f"{unresolved()} proposals still unresolved "
                        "after the drain window")
        for sid, cmd, rs in pending:
            if rs.wait(0).completed():
                acked[sid].append(cmd)
        # post-drain liveness: the fleet still commits on both shards
        # under the new leadership, and the marker doubles as the
        # convergence fence for the journal comparison
        markers = {}
        for sid in shards:
            markers[sid] = f"drained{sid}x{seed}=1".encode()
            if not cluster.propose(markers[sid], timeout=15.0, shard=sid):
                report.fail(f"shard {sid}: post-drain proposal never "
                            "acked")
        deadline = time.time() + converge_timeout
        converged = False
        while time.time() < deadline and not converged:
            converged = True
            for sid in shards:
                js = cluster.journals(shard=sid)
                vals = list(js.values())
                if (len(js) != cluster.n
                        or any(v != vals[0] for v in vals[1:])
                        or markers[sid] not in vals[0]):
                    converged = False
                    break
            if not converged:
                time.sleep(0.1)
        if not converged:
            report.fail("cluster did not converge after the drain")
        for sid in shards:
            js = cluster.journals(shard=sid)
            report.merge(check_journals_equal(js))
            report.merge(check_no_acked_loss(acked[sid], js))
        # the leaderless gauge returns to zero once converged —
        # event-driven on the flight recorder, as in run_schedule
        if converged:
            deadline = time.time() + 5.0
            seq = flight.RECORDER.next_seq
            leaderless = cluster.leaderless_total()
            while leaderless and time.time() < deadline:
                flight.RECORDER.wait_beyond(
                    seq, timeout=min(0.5, max(0.0,
                                              deadline - time.time())))
                seq = flight.RECORDER.next_seq
                leaderless = cluster.leaderless_total()
            if leaderless:
                report.fail(f"health.leaderless_now gauge stuck at "
                            f"{leaderless} after the drain")
        report.invariant_probe = cluster.invariant_counters()
        report.merge(check_invariant_probe(report.invariant_probe))
        if not report.ok:
            report.flight_tail = flight.RECORDER.tail(64)
    finally:
        cluster.close()
    return HotspotResult(
        seed=seed, hot_shard=hot, cold_shard=cold,
        initial_leader=initial_leader, final_leader=final_leader,
        transfers=transfers, acked_count=sum(map(len, acked.values())),
        report=report)
