"""CrashPointFS — crash-at-the-Nth-op error injection with torn writes.

Extends :class:`dragonboat_tpu.vfs.ErrorFS`: instead of a static inject
hook, the fs is **armed** with a countdown over matching operations.
When the countdown reaches zero the fs *trips*: the triggering op — and
every matching op after it — raises ``InjectedError`` until
:meth:`heal` is called.  This models a disk that dies and stays dead
until the operator replaces it, which is exactly the window the
NodeHost's controlled-crash + ``restart()`` path must survive.

With ``torn=True`` the tripping op, if it is a ``write``, first lands a
PREFIX of the buffer on the underlying fs before raising — a torn final
record, the crash shape tan's tail-truncation recovery exists for
(logdb/tan.py ``_replay_file``).
"""

from __future__ import annotations

import threading

from dragonboat_tpu.vfs import ErrorFS, InjectedError, _ErrFile

DEFAULT_OPS = ("write", "fsync")


class _CrashFile(_ErrFile):
    """File wrapper whose write path knows how to tear the last write."""

    def write(self, b):
        consumed = self._fs._on_write(self._path, self._f, b)
        if consumed:
            return len(b)
        return self._f.write(b)


class CrashPointFS(ErrorFS):
    """ErrorFS with an armed crash point (charybdefs fault cartridge).

    ``arm(after_ops, torn)`` starts a countdown: the next ``after_ops``
    matching operations succeed, the one after trips the fs.  Ops are
    matched by name (default ``write``/``fsync`` — the durability path)
    and, optionally, by ``path_substr``.
    """

    def __init__(self, base, ops: tuple = DEFAULT_OPS,
                 path_substr: str = "") -> None:
        super().__init__(base, self._inject)
        self.match_ops = ops                 # guarded-by: <init-only>
        self.path_substr = path_substr       # guarded-by: <init-only>
        self._armed = False                  # guarded-by: _cmu
        self._countdown = 0                  # guarded-by: _cmu
        self._torn = False                   # guarded-by: _cmu
        self.tripped = False                 # guarded-by: _cmu
        self.trip_count = 0                  # guarded-by: _cmu
        self._cmu = threading.Lock()

    # -- arming ----------------------------------------------------------

    def arm(self, after_ops: int, torn: bool = False) -> None:
        """Trip after ``after_ops`` more matching operations succeed."""
        with self._cmu:
            self._armed = True
            self._countdown = after_ops
            self._torn = torn
            self.tripped = False

    def heal(self) -> None:
        """Clear the trip — the replacement disk; IO flows again."""
        with self._cmu:
            self._armed = False
            self.tripped = False
            self._torn = False

    # -- injection -------------------------------------------------------

    def _matches(self, op: str, path: str) -> bool:
        return op in self.match_ops and self.path_substr in path

    def _inject(self, op: str, path: str) -> bool:
        if not self._matches(op, path):
            return False
        fail, _ = self._step()
        return fail

    def _step(self) -> tuple:
        """Advance the countdown for one matching op.  Returns
        ``(fail, tear)``: fail the op, and — only on the very op that
        trips while armed torn — tear it."""
        with self._cmu:
            if self.tripped:
                self.trip_count += 1
                return True, False
            if not self._armed:
                return False, False
            if self._countdown > 0:
                self._countdown -= 1
                return False, False
            self.tripped = True
            self.trip_count += 1
            return True, self._torn

    def _on_write(self, path: str, inner_file, b) -> bool:
        """The write path, torn-aware: normally behaves exactly like the
        inject hook, but when the TRIPPING op is a write armed with
        ``torn=True``, half the buffer reaches the file before the
        error — the torn-final-record crash shape.  Returns True when
        the (partial) write was consumed here."""
        with self._mu:
            self.ops += 1
        if not self._matches("write", path):
            return False
        fail, tear = self._step()
        if not fail:
            return False
        if tear:
            data = b.encode() if isinstance(b, str) else bytes(b)
            inner_file.write(data[:max(1, len(data) // 2)])
        raise InjectedError(f"injected write error (crash point): {path}")

    # -- IFS overrides ---------------------------------------------------

    def open(self, path: str, mode: str = "rb"):
        self._check("open", path)
        return _CrashFile(self, path, self.base.open(path, mode))
