"""Convergence oracle — pure checks over replica observations.

The runner samples each replica's applied journal (the exact sequence
of committed user commands its SM applied), applied index, and the
monkey.go hash oracles; these functions turn the samples into a
verdict.  Everything here is pure data -> data so the determinism lint
covers it and tests can feed synthetic histories.

The three safety properties (ISSUE 3 tentpole):

- **zero committed-entry loss** — every command the workload saw an ack
  for is present in every replica's journal;
- **identical committed prefixes** — any two replicas' journals are
  prefix-ordered at all times, and equal at convergence;
- **monotone applied indices** — a replica's applied index never moves
  backwards between samples (restart resets the baseline: recovery
  legitimately replays from a snapshot/zero up to the durable commit).
"""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass
class OracleReport:
    ok: bool = True
    failures: list = field(default_factory=list)
    # last flight-recorder records at failure time, attached by the
    # RUNNER (this module stays pure — no clocks, no global recorder
    # reads — so the determinism lint keeps covering it)
    flight_tail: list = field(default_factory=list)
    # runtime invariant-probe counters harvested from every live host
    # after the final heal (attached by the runner; see
    # check_invariant_probe for the verdict over them)
    invariant_probe: dict = field(default_factory=dict)

    def fail(self, msg: str) -> None:
        self.ok = False
        self.failures.append(msg)

    def merge(self, other: "OracleReport") -> None:
        if not other.ok:
            self.ok = False
            self.failures.extend(other.failures)


def check_prefix_consistent(journals: dict) -> OracleReport:
    """Any two replicas' journals must be prefix-ordered — a divergent
    suffix means two replicas committed different entries at the same
    index, the one thing raft may never do."""
    rep = OracleReport()
    rids = sorted(journals)
    for i, a in enumerate(rids):
        for b in rids[i + 1:]:
            ja, jb = journals[a], journals[b]
            n = min(len(ja), len(jb))
            if ja[:n] != jb[:n]:
                k = next(x for x in range(n) if ja[x] != jb[x])
                rep.fail(f"replicas {a} and {b} diverge at journal "
                         f"position {k}: {ja[k]!r} != {jb[k]!r}")
    return rep


def check_no_acked_loss(acked: list, journals: dict) -> OracleReport:
    """Every acked command must appear in every replica's journal."""
    rep = OracleReport()
    for rid in sorted(journals):
        have = set(journals[rid])
        missing = [c for c in acked if c not in have]
        if missing:
            rep.fail(f"replica {rid} lost {len(missing)} acked "
                     f"command(s), first: {missing[0]!r}")
    return rep


def check_journals_equal(journals: dict) -> OracleReport:
    rep = OracleReport()
    rids = sorted(journals)
    first = journals[rids[0]]
    for rid in rids[1:]:
        if journals[rid] != first:
            rep.fail(f"replica {rid} journal length {len(journals[rid])}"
                     f" != replica {rids[0]} length {len(first)} "
                     "(or content differs) after convergence")
    return rep


def check_monotone_applied(samples: dict) -> OracleReport:
    """``samples[rid]`` is the time-ordered list of (epoch, applied)
    observations for one replica; ``epoch`` increments on each restart
    of that replica.  Within an epoch applied may never decrease."""
    rep = OracleReport()
    for rid in sorted(samples):
        prev_epoch, prev_applied = -1, -1
        for epoch, applied in samples[rid]:
            if epoch == prev_epoch and applied < prev_applied:
                rep.fail(f"replica {rid} applied index moved backwards "
                         f"within epoch {epoch}: {prev_applied} -> "
                         f"{applied}")
            prev_epoch, prev_applied = epoch, applied
    return rep


def check_hashes_equal(name: str, hashes: dict) -> OracleReport:
    rep = OracleReport()
    if len(set(hashes.values())) > 1:
        rep.fail(f"{name} hashes diverge: " + ", ".join(
            f"r{rid}={hashes[rid]:#x}" for rid in sorted(hashes)))
    return rep


def check_hot_drained(initial_leader: int, final_leader: int,
                      transfers: list) -> OracleReport:
    """Controller drain verdict (hotspot differential): at least one
    ``control_transfer`` decision was flight-recorded for the hot
    shard, every decision carries its full evidence row (the
    observe→act loop must be auditable, not just effective), and
    leadership actually left the initially hot replica."""
    rep = OracleReport()
    if not transfers:
        rep.fail("controller planned no transfer off the hot shard")
        return rep
    for rec in transfers:
        ev = rec.get("evidence") or {}
        missing = [k for k in ("obs", "lane", "score", "lag", "streak",
                               "term") if k not in ev]
        if missing:
            rep.fail(f"transfer record seq {rec.get('seq')} missing "
                     f"evidence field(s): {', '.join(missing)}")
    if final_leader == 0:
        rep.fail("hot shard leaderless after the transfer window")
    elif final_leader == initial_leader:
        rep.fail(f"leadership never left replica {initial_leader} "
                 "despite planned transfers")
    return rep


def check_invariant_probe(counters: dict) -> OracleReport:
    """The device-side invariant probe must stay silent through a whole
    chaos schedule — faults may delay commits, but no interleaving of
    crashes/partitions/delays is allowed to produce a protocol-invariant
    violation (``violations_seen`` is sticky per engine lifetime, so a
    one-tick trip during the fault window still fails here)."""
    rep = OracleReport()
    seen = int(counters.get("violations_seen", 0))
    live = int(counters.get("total", 0))
    if seen or live:
        first = counters.get("first")
        rep.fail(f"invariant probe tripped during the schedule: "
                 f"violations_seen={seen} live_total={live}"
                 + (f", first offender: {first}" if first else ""))
    return rep


def check_convergence(acked: list, journals: dict, applied_samples: dict,
                      sm_hashes: dict, session_hashes: dict,
                      membership_hashes: dict) -> OracleReport:
    """The full oracle, run once after the final heal + settle."""
    rep = OracleReport()
    rep.merge(check_prefix_consistent(journals))
    rep.merge(check_journals_equal(journals))
    rep.merge(check_no_acked_loss(acked, journals))
    rep.merge(check_monotone_applied(applied_samples))
    rep.merge(check_hashes_equal("sm", sm_hashes))
    rep.merge(check_hashes_equal("session", session_hashes))
    rep.merge(check_hashes_equal("membership", membership_hashes))
    return rep
