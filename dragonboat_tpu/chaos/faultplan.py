"""FaultPlan — seeded, deterministic composed fault schedules.

A plan is a sequence of :class:`FaultEvent` records over three seams:

========= =============================================================
storage   ``crash_write`` (CrashPointFS trips at the Nth durability op,
          optionally tearing the final write) healed by
          ``restart_inplace`` (NodeHost.restart from the data dir)
transport ``drop`` / ``delay`` / ``duplicate`` / ``reorder`` (chan
          hooks), ``partition`` (monkey.go PartitionNode), and
          ``breaker_trip`` (forced hub CircuitBreaker failures), healed
          by ``heal_transport`` / ``restore_partition``
process   ``kill`` (simulate_kill + MemFS power loss) healed by
          ``restart_process`` (a fresh NodeHost over the same data dir)
========= =============================================================

Generation is a pure function of the seed (``from random import
Random`` — no global RNG, no wall clock), and serialization is
canonical JSON (sorted keys, tight separators), so the SAME seed always
yields the SAME bytes and a recorded trace replays as a plan
(:meth:`FaultPlan.from_json`).

Invariants the generator maintains so every schedule is recoverable:

- at most ONE replica is faulted-down (crashed, killed, or partitioned)
  at any time — a 3-replica shard keeps its quorum;
- every down event is followed by its matching restart/heal event;
- the final step heals everything, so the convergence oracle always
  runs against a fully-connected cluster.
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from random import Random

# kinds that take a replica out (at most one outstanding at a time)
DOWN_KINDS = ("crash_write", "kill", "partition")
# benign transport faults that may overlap freely
SOFT_KINDS = ("drop", "delay", "duplicate", "reorder", "breaker_trip")
HEAL_FOR = {
    "crash_write": "restart_inplace",
    "kill": "restart_process",
    "partition": "restore_partition",
    "drop": "heal_transport",
    "delay": "heal_transport",
    "duplicate": "heal_transport",
    "reorder": "heal_transport",
    "breaker_trip": "heal_breaker",
}
SEAM_FOR = {
    "crash_write": "storage",
    "restart_inplace": "storage",
    "kill": "process",
    "restart_process": "process",
    "partition": "transport",
    "restore_partition": "transport",
    "drop": "transport",
    "delay": "transport",
    "duplicate": "transport",
    "reorder": "transport",
    "breaker_trip": "transport",
    "heal_transport": "transport",
    "heal_breaker": "transport",
}


@dataclass(frozen=True)
class FaultEvent:
    """One scheduled fault (or heal) at a workload step."""

    step: int
    seam: str
    kind: str
    target: int            # replica id
    params: tuple          # sorted (key, value) pairs — hashable, canonical

    def as_dict(self) -> dict:
        return {"step": self.step, "seam": self.seam, "kind": self.kind,
                "target": self.target, "params": dict(self.params)}

    @classmethod
    def from_dict(cls, d: dict) -> "FaultEvent":
        return cls(step=int(d["step"]), seam=str(d["seam"]),
                   kind=str(d["kind"]), target=int(d["target"]),
                   params=tuple(sorted(d.get("params", {}).items())))


def canonical_json(obj) -> str:
    """THE trace encoding: identical structures -> identical bytes."""
    return json.dumps(obj, sort_keys=True, separators=(",", ":"))


@dataclass(frozen=True)
class FaultPlan:
    seed: int
    n_replicas: int
    steps: int
    events: tuple

    def events_at(self, step: int) -> list:
        return [e for e in self.events if e.step == step]

    def to_json(self) -> str:
        return canonical_json({
            "seed": self.seed, "n_replicas": self.n_replicas,
            "steps": self.steps,
            "events": [e.as_dict() for e in self.events]})

    @classmethod
    def from_json(cls, blob: str) -> "FaultPlan":
        d = json.loads(blob)
        return cls(seed=int(d["seed"]), n_replicas=int(d["n_replicas"]),
                   steps=int(d["steps"]),
                   events=tuple(FaultEvent.from_dict(e)
                                for e in d["events"]))

    @classmethod
    def generate(cls, seed: int, n_replicas: int = 3,
                 steps: int = 6) -> "FaultPlan":
        """Pure function of (seed, n_replicas, steps)."""
        rng = Random(seed)
        events: list = []
        down: tuple | None = None       # (rid, kind) awaiting its heal
        soft: list = []                 # [(rid, kind)] awaiting heal

        def add(step: int, kind: str, rid: int, **params) -> None:
            events.append(FaultEvent(
                step=step, seam=SEAM_FOR[kind], kind=kind, target=rid,
                params=tuple(sorted(params.items()))))

        for step in range(steps):
            # recover an outstanding down replica before anything else
            # this step (rng-gated so outages span 1..k steps)
            if down is not None and (step == steps - 1
                                     or rng.random() < 0.6):
                rid, kind = down
                add(step, HEAL_FOR[kind], rid)
                down = None
            # heal a lingering soft fault now and then
            if soft and rng.random() < 0.4:
                rid, kind = soft.pop(rng.randrange(len(soft)))
                add(step, HEAL_FOR[kind], rid)
            # inject something new (not on the last step: it must heal)
            if step < steps - 1 and rng.random() < 0.85:
                hard_ok = down is None and step < steps - 2
                kind = rng.choice(DOWN_KINDS + SOFT_KINDS) if hard_ok \
                    else rng.choice(SOFT_KINDS)
                rid = rng.randrange(1, n_replicas + 1)
                if kind in DOWN_KINDS:
                    # never take down a replica already soft-faulted in a
                    # way that would stall its recovery IO
                    if any(r == rid for r, _ in soft):
                        kind = rng.choice(SOFT_KINDS)
                if kind in DOWN_KINDS:
                    if kind == "crash_write":
                        add(step, kind, rid,
                            after_ops=rng.randrange(2, 30),
                            torn=rng.random() < 0.5)
                    else:
                        add(step, kind, rid)
                    down = (rid, kind)
                elif any(r == rid and k == kind for r, k in soft):
                    pass        # already active on this replica
                elif kind == "drop":
                    add(step, kind, rid, every=rng.randrange(3, 7))
                    soft.append((rid, kind))
                elif kind == "delay":
                    add(step, kind, rid,
                        seconds=rng.choice((0.002, 0.005, 0.01)))
                    soft.append((rid, kind))
                elif kind == "duplicate":
                    add(step, kind, rid, every=rng.randrange(2, 5))
                    soft.append((rid, kind))
                elif kind == "reorder":
                    add(step, kind, rid, seed=rng.getrandbits(32))
                    soft.append((rid, kind))
                else:           # breaker_trip: self-heals after cooldown
                    add(step, kind, rid, count=1)
        # final barrier: everything heals at step == steps
        if down is not None:
            add(steps, HEAL_FOR[down[1]], down[0])
        for rid, kind in soft:
            add(steps, HEAL_FOR[kind], rid)
        return cls(seed=seed, n_replicas=n_replicas, steps=steps,
                   events=tuple(events))
