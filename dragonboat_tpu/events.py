"""Event publication + metrics — parity with the reference's ``event.go``.

User-provided listeners (raftio.IRaftEventListener / ISystemEventListener)
are invoked from a dedicated worker thread so a slow listener can never
stall the engine (event.go:54-90 runs listeners on the events goroutine).
Exceptions from listeners are logged and swallowed.

Metrics: the legacy ``inc``/``set``/``snapshot`` counter surface is now
a compat shim over the typed instrument registry in
``dragonboat_tpu/telemetry.py`` (Counter/Gauge/Histogram + Prometheus
exposition).  Legacy dotted names keep working and keep their exact
keys in ``snapshot()``; a wrong-typed operation on a name (``inc`` on a
gauge, ``set`` on a counter) logs once and falls back to the old
defaultdict semantics instead of raising, so unmigrated callers degrade
instead of crashing — new code should use ``metrics.registry``
directly and gets the strict typed behavior.
"""

from __future__ import annotations

import queue
import threading
from typing import Callable

from dragonboat_tpu import flight
from dragonboat_tpu.logger import get_logger
from dragonboat_tpu.raftio import (
    EntryInfo,
    LeaderInfo,
    NodeInfo,
    SnapshotInfo,
)
from dragonboat_tpu.telemetry import InstrumentTypeError, Registry

_LOG = get_logger("events")


class Metrics:
    """Legacy counter facade over a typed ``telemetry.Registry``.

    ``inc(name)`` lazily registers a Counter, ``set(name)`` a Gauge,
    ``observe(name)`` a Histogram.  A name already registered as the
    other kind is the old counter/gauge conflation bug — the shim logs
    one warning per (op, name) and applies the legacy defaultdict
    semantics so existing callers keep running while they migrate.
    """

    def __init__(self, registry: Registry | None = None) -> None:
        self.registry = registry if registry is not None else Registry()
        # legacy alias: old code synchronized on metrics.mu
        self.mu = self.registry.mu
        self._warn_mu = threading.Lock()
        self._warned: set[tuple[str, str]] = set()    # guarded-by: _warn_mu

    def _warn_once(self, op: str, name: str, use: str) -> None:
        with self._warn_mu:
            if (op, name) in self._warned:
                return
            self._warned.add((op, name))
        _LOG.warning(
            "legacy %s() on %r which is registered as a %s — applying "
            "defaultdict semantics; migrate the caller to the typed "
            "registry", op, name, use)

    def inc(self, name: str, delta: int = 1) -> None:
        try:
            self.registry.counter(name).inc(delta)
        except InstrumentTypeError:
            self._warn_once("inc", name, self.registry.kind_of(name))
            try:
                self.registry.gauge(name)._force_add(delta)
            except InstrumentTypeError:
                pass        # histogram / callback gauge: drop the inc

    def set(self, name: str, value: int) -> None:
        try:
            self.registry.gauge(name).set(value)
        except InstrumentTypeError:
            self._warn_once("set", name, self.registry.kind_of(name))
            try:
                self.registry.counter(name)._force_set(value)
            except InstrumentTypeError:
                pass        # histogram / callback gauge: drop the set

    def observe(self, name: str, value, buckets=None) -> None:
        if buckets is not None:
            self.registry.histogram(name, buckets=buckets).observe(value)
        else:
            self.registry.histogram(name).observe(value)

    def snapshot(self) -> dict:
        return self.registry.snapshot()


class EventHub:
    """Queue-decoupled listener dispatch (event.go:54-90)."""

    def __init__(self, raft_listener=None, system_listener=None,
                 metrics: Metrics | None = None) -> None:
        self.raft_listener = raft_listener
        self.system_listener = system_listener
        self.metrics = metrics or Metrics()
        self._q: queue.Queue = queue.Queue()
        self._worker: threading.Thread | None = None
        if raft_listener is not None or system_listener is not None:
            self._worker = threading.Thread(
                target=self._run, name="events", daemon=True)
            self._worker.start()

    def close(self) -> None:
        if self._worker is not None:
            self._q.put(None)
            self._worker.join(timeout=2)
            self._worker = None

    def _run(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                return
            fn, args = item
            try:
                fn(*args)
            except Exception:
                _LOG.exception("event listener raised")

    def _dispatch(self, listener, method: str, *args) -> None:
        if listener is None:
            return
        fn: Callable | None = getattr(listener, method, None)
        if fn is None:
            return
        self._q.put((fn, args))

    # -- raft events (listener.go:33) -----------------------------------

    def leader_updated(self, info: LeaderInfo) -> None:
        self.metrics.inc("raft.leader_updated")
        flight.record(flight.LEADER_CHANGE, shard_id=info.shard_id,
                      replica_id=info.replica_id, term=int(info.term),
                      leader_id=int(info.leader_id))
        self._dispatch(self.raft_listener, "leader_updated", info)

    # -- system events (listener.go:59-76) ------------------------------

    def node_host_shutting_down(self) -> None:
        self._dispatch(self.system_listener, "node_host_shutting_down")

    def node_unloaded(self, info: NodeInfo) -> None:
        self._dispatch(self.system_listener, "node_unloaded", info)

    def node_deleted(self, info: NodeInfo) -> None:
        self._dispatch(self.system_listener, "node_deleted", info)

    def node_ready(self, info: NodeInfo) -> None:
        self.metrics.inc("system.node_ready")
        self._dispatch(self.system_listener, "node_ready", info)

    def membership_changed(self, info: NodeInfo) -> None:
        self.metrics.inc("system.membership_changed")
        self._dispatch(self.system_listener, "membership_changed", info)

    def connection_established(self, addr: str, snapshot: bool) -> None:
        self.metrics.inc("transport.connection_established")
        self._dispatch(self.system_listener, "connection_established",
                       addr, snapshot)

    def connection_failed(self, addr: str, snapshot: bool) -> None:
        self.metrics.inc("transport.connection_failed")
        self._dispatch(self.system_listener, "connection_failed",
                       addr, snapshot)

    def send_snapshot_started(self, info: SnapshotInfo) -> None:
        flight.record(flight.SNAPSHOT, phase="send_started",
                      shard_id=info.shard_id, replica_id=info.replica_id,
                      to=info.from_, index=int(info.index),
                      term=int(info.term))
        self._dispatch(self.system_listener, "send_snapshot_started", info)

    def send_snapshot_completed(self, info: SnapshotInfo) -> None:
        self._dispatch(self.system_listener, "send_snapshot_completed", info)

    def send_snapshot_aborted(self, info: SnapshotInfo) -> None:
        self._dispatch(self.system_listener, "send_snapshot_aborted", info)

    def snapshot_received(self, info: SnapshotInfo) -> None:
        self.metrics.inc("snapshot.received")
        flight.record(flight.SNAPSHOT, phase="received",
                      shard_id=info.shard_id, replica_id=info.replica_id,
                      from_=info.from_, index=int(info.index),
                      term=int(info.term))
        self._dispatch(self.system_listener, "snapshot_received", info)

    def snapshot_recovered(self, info: SnapshotInfo) -> None:
        self.metrics.inc("snapshot.recovered")
        self._dispatch(self.system_listener, "snapshot_recovered", info)

    def snapshot_created(self, info: SnapshotInfo) -> None:
        self.metrics.inc("snapshot.created")
        flight.record(flight.SNAPSHOT, phase="created",
                      shard_id=info.shard_id, replica_id=info.replica_id,
                      index=int(info.index), term=int(info.term))
        self._dispatch(self.system_listener, "snapshot_created", info)

    def snapshot_compacted(self, info: SnapshotInfo) -> None:
        self._dispatch(self.system_listener, "snapshot_compacted", info)

    def log_compacted(self, info: EntryInfo) -> None:
        self.metrics.inc("log.compacted")
        self._dispatch(self.system_listener, "log_compacted", info)

    def log_db_compacted(self, info: EntryInfo) -> None:
        self.metrics.inc("logdb.compacted")
        self._dispatch(self.system_listener, "log_db_compacted", info)
