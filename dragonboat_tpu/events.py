"""Event publication + metrics — parity with the reference's ``event.go``.

User-provided listeners (raftio.IRaftEventListener / ISystemEventListener)
are invoked from a dedicated worker thread so a slow listener can never
stall the engine (event.go:54-90 runs listeners on the events goroutine).
Exceptions from listeners are logged and swallowed.

Metrics: a process-wide counter registry analogous to the reference's
Prometheus surface (event.go metrics + nodehost metrics); exported as a
plain dict snapshot so any exporter can scrape it.
"""

from __future__ import annotations

import queue
import threading
from collections import defaultdict
from typing import Callable

from dragonboat_tpu.logger import get_logger
from dragonboat_tpu.raftio import (
    EntryInfo,
    LeaderInfo,
    NodeInfo,
    SnapshotInfo,
)

_LOG = get_logger("events")


class Metrics:
    """Process-wide counters (reference: Prometheus registry)."""

    def __init__(self) -> None:
        self.mu = threading.Lock()
        self.counters: dict[str, int] = defaultdict(int)   # guarded-by: mu

    def inc(self, name: str, delta: int = 1) -> None:
        with self.mu:
            self.counters[name] += delta

    def set(self, name: str, value: int) -> None:
        with self.mu:
            self.counters[name] = value

    def snapshot(self) -> dict[str, int]:
        with self.mu:
            return dict(self.counters)


class EventHub:
    """Queue-decoupled listener dispatch (event.go:54-90)."""

    def __init__(self, raft_listener=None, system_listener=None,
                 metrics: Metrics | None = None) -> None:
        self.raft_listener = raft_listener
        self.system_listener = system_listener
        self.metrics = metrics or Metrics()
        self._q: queue.Queue = queue.Queue()
        self._worker: threading.Thread | None = None
        if raft_listener is not None or system_listener is not None:
            self._worker = threading.Thread(
                target=self._run, name="events", daemon=True)
            self._worker.start()

    def close(self) -> None:
        if self._worker is not None:
            self._q.put(None)
            self._worker.join(timeout=2)
            self._worker = None

    def _run(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                return
            fn, args = item
            try:
                fn(*args)
            except Exception:
                _LOG.exception("event listener raised")

    def _dispatch(self, listener, method: str, *args) -> None:
        if listener is None:
            return
        fn: Callable | None = getattr(listener, method, None)
        if fn is None:
            return
        self._q.put((fn, args))

    # -- raft events (listener.go:33) -----------------------------------

    def leader_updated(self, info: LeaderInfo) -> None:
        self.metrics.inc("raft.leader_updated")
        self._dispatch(self.raft_listener, "leader_updated", info)

    # -- system events (listener.go:59-76) ------------------------------

    def node_host_shutting_down(self) -> None:
        self._dispatch(self.system_listener, "node_host_shutting_down")

    def node_unloaded(self, info: NodeInfo) -> None:
        self._dispatch(self.system_listener, "node_unloaded", info)

    def node_deleted(self, info: NodeInfo) -> None:
        self._dispatch(self.system_listener, "node_deleted", info)

    def node_ready(self, info: NodeInfo) -> None:
        self.metrics.inc("system.node_ready")
        self._dispatch(self.system_listener, "node_ready", info)

    def membership_changed(self, info: NodeInfo) -> None:
        self.metrics.inc("system.membership_changed")
        self._dispatch(self.system_listener, "membership_changed", info)

    def connection_established(self, addr: str, snapshot: bool) -> None:
        self.metrics.inc("transport.connection_established")
        self._dispatch(self.system_listener, "connection_established",
                       addr, snapshot)

    def connection_failed(self, addr: str, snapshot: bool) -> None:
        self.metrics.inc("transport.connection_failed")
        self._dispatch(self.system_listener, "connection_failed",
                       addr, snapshot)

    def send_snapshot_started(self, info: SnapshotInfo) -> None:
        self._dispatch(self.system_listener, "send_snapshot_started", info)

    def send_snapshot_completed(self, info: SnapshotInfo) -> None:
        self._dispatch(self.system_listener, "send_snapshot_completed", info)

    def send_snapshot_aborted(self, info: SnapshotInfo) -> None:
        self._dispatch(self.system_listener, "send_snapshot_aborted", info)

    def snapshot_received(self, info: SnapshotInfo) -> None:
        self.metrics.inc("snapshot.received")
        self._dispatch(self.system_listener, "snapshot_received", info)

    def snapshot_recovered(self, info: SnapshotInfo) -> None:
        self.metrics.inc("snapshot.recovered")
        self._dispatch(self.system_listener, "snapshot_recovered", info)

    def snapshot_created(self, info: SnapshotInfo) -> None:
        self.metrics.inc("snapshot.created")
        self._dispatch(self.system_listener, "snapshot_created", info)

    def snapshot_compacted(self, info: SnapshotInfo) -> None:
        self._dispatch(self.system_listener, "snapshot_compacted", info)

    def log_compacted(self, info: EntryInfo) -> None:
        self.metrics.inc("log.compacted")
        self._dispatch(self.system_listener, "log_compacted", info)

    def log_db_compacted(self, info: EntryInfo) -> None:
        self.metrics.inc("logdb.compacted")
        self._dispatch(self.system_listener, "log_db_compacted", info)
