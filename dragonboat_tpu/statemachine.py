"""User state-machine plugin interfaces.

Parity with the reference's ``statemachine/`` package: IStateMachine
(rsm.go:142), IConcurrentStateMachine (concurrent.go:45) and
IOnDiskStateMachine (disk.go:56).  Applications implement one of these and
register a factory with NodeHost.start_replica; linearizable writes arrive
via update(), linearizable reads via lookup() after a ReadIndex round.

The TPU build adds a fourth, device-native kind: IDeviceStateMachine — an
RSM whose update step is itself a JAX kernel over committed entry lanes
(the north star's fused on-device rsm-apply); the engine batches committed
entries into fixed lanes and applies them without leaving the device.
"""

from __future__ import annotations

import abc
from dataclasses import dataclass, field
from typing import BinaryIO, Callable, Iterable, Protocol, Sequence

from dragonboat_tpu import raftpb as pb


@dataclass(frozen=True)
class Result:
    """Result of an update — parity statemachine/rsm.go Result."""

    value: int = 0
    data: bytes = b""


@dataclass(frozen=True)
class Entry:
    """Entry visible to user SMs — (index, cmd, result)."""

    index: int
    cmd: bytes
    result: Result = field(default_factory=Result)


@dataclass(frozen=True)
class SnapshotFile:
    file_id: int
    filepath: str
    metadata: bytes


class ISnapshotFileCollection(Protocol):
    def add_file(self, file_id: int, path: str, metadata: bytes) -> None: ...


class IStateMachine(abc.ABC):
    """Regular in-memory SM — statemachine/rsm.go:142.  The framework
    serializes update/lookup/save_snapshot with an RWMutex discipline."""

    @abc.abstractmethod
    def update(self, entry: Entry) -> Result: ...

    @abc.abstractmethod
    def lookup(self, query: object) -> object: ...

    @abc.abstractmethod
    def save_snapshot(self, w: BinaryIO, files: ISnapshotFileCollection,
                      done: Callable[[], bool]) -> None: ...

    @abc.abstractmethod
    def recover_from_snapshot(self, r: BinaryIO, files: Sequence[SnapshotFile],
                              done: Callable[[], bool]) -> None: ...

    def close(self) -> None:  # optional
        return None


class IConcurrentStateMachine(abc.ABC):
    """Concurrent SM — statemachine/concurrent.go:45: batched updates,
    concurrent lookups, and prepare/save snapshot split."""

    @abc.abstractmethod
    def update(self, entries: list[Entry]) -> list[Entry]: ...

    @abc.abstractmethod
    def lookup(self, query: object) -> object: ...

    @abc.abstractmethod
    def prepare_snapshot(self) -> object: ...

    @abc.abstractmethod
    def save_snapshot(self, ctx: object, w: BinaryIO,
                      files: ISnapshotFileCollection,
                      done: Callable[[], bool]) -> None: ...

    @abc.abstractmethod
    def recover_from_snapshot(self, r: BinaryIO, files: Sequence[SnapshotFile],
                              done: Callable[[], bool]) -> None: ...

    def close(self) -> None:
        return None


class IOnDiskStateMachine(abc.ABC):
    """On-disk SM — statemachine/disk.go:56: owns its own durable state,
    opens to its persisted index, and streams snapshots."""

    @abc.abstractmethod
    def open(self, stopc: Callable[[], bool]) -> int:
        """Open the SM and return the index of the last applied entry."""

    @abc.abstractmethod
    def update(self, entries: list[Entry]) -> list[Entry]: ...

    @abc.abstractmethod
    def lookup(self, query: object) -> object: ...

    @abc.abstractmethod
    def sync(self) -> None: ...

    @abc.abstractmethod
    def prepare_snapshot(self) -> object: ...

    @abc.abstractmethod
    def save_snapshot(self, ctx: object, w: BinaryIO,
                      done: Callable[[], bool]) -> None: ...

    @abc.abstractmethod
    def recover_from_snapshot(self, r: BinaryIO,
                              done: Callable[[], bool]) -> None: ...

    def close(self) -> None:
        return None


class IDeviceStateMachine(abc.ABC):
    """TPU-native SM: apply is a device kernel over committed entry lanes.

    No reference analog — this is the fused rsm-apply path from
    BASELINE.json's north star.  Implementations provide pure functions the
    engine jits and batches across shards."""

    @abc.abstractmethod
    def init_state(self, num_shards: int) -> object:
        """Device pytree holding per-shard SM state."""

    @abc.abstractmethod
    def apply_kernel(self, sm_state: object, cmd_lanes: object,
                     valid_mask: object) -> tuple[object, object]:
        """(new_state, (results, ok)) — vmapped over shards by the
        engine.  ``ok`` is a per-lane bool: False on a valid lane means
        the SM rejected the command (results values are free-form, so
        status must not be encoded in them)."""

    @abc.abstractmethod
    def lookup(self, sm_state: object, shard_slot: int, query: object) -> object: ...


CreateStateMachineFunc = Callable[[int, int], IStateMachine]
CreateConcurrentStateMachineFunc = Callable[[int, int], IConcurrentStateMachine]
CreateOnDiskStateMachineFunc = Callable[[int, int], IOnDiskStateMachine]


def sm_type_of(sm: object) -> pb.StateMachineType:
    if isinstance(sm, IOnDiskStateMachine):
        return pb.StateMachineType.ON_DISK
    if isinstance(sm, IConcurrentStateMachine):
        return pb.StateMachineType.CONCURRENT
    return pb.StateMachineType.REGULAR
