"""Go-wire interop codec — byte-compatible with the reference raftpb.

The reference serializes its transport/storage types in a HYBRID format:

- ``Message`` / ``MessageBatch`` / ``Snapshot`` / ``Membership`` /
  ``SnapshotFile`` / ``State`` / ``EntryBatch`` are protobuf (gogo
  generated, nullable=false), with the notable gogo property that every
  scalar field is emitted **unconditionally** — zero values included —
  in ascending field order (``/root/reference/raftpb/message.go:32``,
  ``snapshot.go:72``, ``membership.go:29``, ``state.go:27``,
  ``messagebatch.go:23``, ``snapshotfile.go:28``, ``entrybatch.go:25``).
- ``Entry`` is **Colfer** (the hand-optimized
  ``/root/reference/raftpb/raft_optimized.go:161-301``): per-field
  header byte = field number, 0x80 flag selects an 8-byte big-endian
  fixed form for values >= 2**49, little-endian 7-bit varints below,
  zero fields skipped entirely, record terminated by 0x7f.  Entries
  embedded in a protobuf ``Message``/``EntryBatch`` are length-delimited
  Colfer blobs.

This module encodes/decodes the package's own dataclasses
(:mod:`dragonboat_tpu.raftpb`) to and from that wire, so a TPU host can
join a DCN cluster speaking the reference's TCP protocol.  Maps are
emitted in sorted key order (Go's map iteration is random, so any order
is conformant; sorted keeps us deterministic for tests and checksums).

Provenance note for reviewers: the build environment has no Go
toolchain, so the golden fixtures in ``tests/test_gowire.py`` are
hand-traced from the generated marshal code cited above rather than
emitted by the reference binary; each fixture cites the lines it was
traced from.
"""

from __future__ import annotations

import dataclasses
import struct
from typing import Sequence

from dragonboat_tpu import raftpb as pb

# --------------------------------------------------------------------------
# protobuf primitives (common.go encodeVarintRaft / sovRaft / skipRaft)
# --------------------------------------------------------------------------


def _uvarint(out: bytearray, x: int) -> None:
    while x >= 0x80:
        out.append((x & 0x7F) | 0x80)
        x >>= 7
    out.append(x)


def _read_uvarint(mv, i: int) -> tuple[int, int]:
    x = 0
    shift = 0
    while True:
        if i >= len(mv):
            raise ValueError("gowire: truncated varint")
        if shift >= 64:
            raise ValueError("gowire: varint overflow")
        b = mv[i]
        i += 1
        x |= (b & 0x7F) << shift
        if b < 0x80:
            return x & 0xFFFFFFFFFFFFFFFF, i
        shift += 7


def _tag(out: bytearray, field: int, wire: int) -> None:
    _uvarint(out, (field << 3) | wire)


def _bool(out: bytearray, v: bool) -> None:
    out.append(1 if v else 0)


def _bytes(out: bytearray, b: bytes) -> None:
    _uvarint(out, len(b))
    out += b


def _read_bytes(mv, i: int) -> tuple[bytes, int]:
    n, i = _read_uvarint(mv, i)
    if i + n > len(mv):
        raise ValueError("gowire: truncated length-delimited field")
    return bytes(mv[i:i + n]), i + n


def _skip_field(mv, i: int, wire: int) -> int:
    """skipRaft: tolerate unknown fields like the generated decoders."""
    if wire == 0:
        _, i = _read_uvarint(mv, i)
        return i
    if wire == 1:
        return i + 8
    if wire == 2:
        n, i = _read_uvarint(mv, i)
        return i + n
    if wire == 5:
        return i + 4
    raise ValueError(f"gowire: unsupported wire type {wire}")


# --------------------------------------------------------------------------
# Entry — Colfer (raft_optimized.go:161-301 marshal, :303-? unmarshal)
# --------------------------------------------------------------------------

_FIXED_THRESHOLD = 1 << 49


def _colfer_u64(out: bytearray, field: int, x: int) -> None:
    if x >= _FIXED_THRESHOLD:
        out.append(field | 0x80)
        out += struct.pack(">Q", x)
    elif x != 0:
        out.append(field)
        _uvarint(out, x)      # colfer varints are the same LE base-128


def _colfer_read_u64(mv, i: int) -> tuple[int, int]:
    """The <2**49 varint arm (up to 8 groups, 9th byte taken whole —
    raft_optimized.go unmarshal ``shift == 56`` break)."""
    if i >= len(mv):
        raise ValueError("gowire: truncated colfer varint")
    x = mv[i]
    i += 1
    if x >= 0x80:
        x &= 0x7F
        shift = 7
        while True:
            if i >= len(mv):
                raise ValueError("gowire: truncated colfer varint")
            b = mv[i]
            i += 1
            if b < 0x80 or shift == 56:
                x |= b << shift
                break
            x |= (b & 0x7F) << shift
            shift += 7
    return x, i


def encode_entry(e: pb.Entry) -> bytes:
    out = bytearray()
    _colfer_u64(out, 0, e.term)
    _colfer_u64(out, 1, e.index)
    t = int(e.type)
    if t != 0:
        # field 2 is int32: negatives take the 0x80 flag + two's
        # complement varint; our EntryType enum is never negative
        out.append(2)
        _uvarint(out, t)
    _colfer_u64(out, 3, e.key)
    _colfer_u64(out, 4, e.client_id)
    _colfer_u64(out, 5, e.series_id)
    _colfer_u64(out, 6, e.responded_to)
    if e.cmd:
        out.append(7)
        _uvarint(out, len(e.cmd))
        out += e.cmd
    out.append(0x7F)
    return bytes(out)


def decode_entry(data) -> pb.Entry:
    mv = memoryview(data)
    vals = {0: 0, 1: 0, 2: 0, 3: 0, 4: 0, 5: 0, 6: 0}
    cmd = b""
    i = 0
    if i >= len(mv):
        raise ValueError("gowire: empty entry")
    # colfer decodes fields in ascending order; headers double as both
    # field id and format flag
    for field in range(7):
        if i >= len(mv):
            raise ValueError("gowire: truncated entry")
        h = mv[i]
        if h == field:
            i += 1
            vals[field], i = _colfer_read_u64(mv, i)
        elif h == (field | 0x80) and field != 2:
            i += 1
            if i + 8 > len(mv):
                raise ValueError("gowire: truncated entry fixed64")
            vals[field] = struct.unpack_from(">Q", mv, i)[0]
            i += 8
        elif h == (2 | 0x80) and field == 2:
            # negative int32: Go marshals the magnitude (^v+1), so the
            # decoded varint IS |v| — not producible by valid EntryTypes
            i += 1
            x, i = _colfer_read_u64(mv, i)
            vals[2] = -x
    if i < len(mv) and mv[i] == 7:
        i += 1
        n, i = _colfer_read_u64(mv, i)
        if i + n > len(mv):
            raise ValueError("gowire: truncated entry cmd")
        cmd = bytes(mv[i:i + n])
        i += n
    if i >= len(mv) or mv[i] != 0x7F:
        raise ValueError("gowire: entry missing 0x7f terminator")
    return pb.Entry(
        term=vals[0], index=vals[1], type=pb.EntryType(vals[2]),
        key=vals[3], client_id=vals[4], series_id=vals[5],
        responded_to=vals[6], cmd=cmd)


def encode_entry_batch(entries: Sequence[pb.Entry]) -> bytes:
    out = bytearray()
    for e in entries:
        _tag(out, 1, 2)
        _bytes(out, encode_entry(e))
    return bytes(out)


def decode_entry_batch(data) -> tuple[pb.Entry, ...]:
    mv = memoryview(data)
    i = 0
    ents = []
    while i < len(mv):
        key, i = _read_uvarint(mv, i)
        field, wire = key >> 3, key & 7
        if field == 1 and wire == 2:
            blob, i = _read_bytes(mv, i)
            ents.append(decode_entry(blob))
        else:
            i = _skip_field(mv, i, wire)
    return tuple(ents)


# --------------------------------------------------------------------------
# State (state.go:27) — every field always emitted
# --------------------------------------------------------------------------


def encode_state(s: pb.State) -> bytes:
    out = bytearray()
    _tag(out, 1, 0)
    _uvarint(out, s.term)
    _tag(out, 2, 0)
    _uvarint(out, s.vote)
    _tag(out, 3, 0)
    _uvarint(out, s.commit)
    return bytes(out)


def decode_state(data) -> pb.State:
    mv = memoryview(data)
    i = 0
    term = vote = commit = 0
    while i < len(mv):
        key, i = _read_uvarint(mv, i)
        field, wire = key >> 3, key & 7
        if field == 1 and wire == 0:
            term, i = _read_uvarint(mv, i)
        elif field == 2 and wire == 0:
            vote, i = _read_uvarint(mv, i)
        elif field == 3 and wire == 0:
            commit, i = _read_uvarint(mv, i)
        else:
            i = _skip_field(mv, i, wire)
    return pb.State(term=term, vote=vote, commit=commit)


# --------------------------------------------------------------------------
# Membership (membership.go:29): ccid(1), addresses(2), removed(3),
# non_votings(4), witnesses(5); map entries are {key:1 varint,
# value:2 string | bool}
# --------------------------------------------------------------------------


def _map_str(out: bytearray, field: int, m: dict[int, str]) -> None:
    for k in sorted(m):
        v = m[k].encode()
        _tag(out, field, 2)
        inner = bytearray()
        _tag(inner, 1, 0)
        _uvarint(inner, k)
        _tag(inner, 2, 2)
        _bytes(inner, v)
        _bytes(out, bytes(inner))


def _read_map_str(mv, i: int) -> tuple[int, str, int]:
    blob, i = _read_bytes(mv, i)
    k, v = 0, b""
    j = 0
    while j < len(blob):
        key, j = _read_uvarint(blob, j)
        field, wire = key >> 3, key & 7
        if field == 1 and wire == 0:
            k, j = _read_uvarint(blob, j)
        elif field == 2 and wire == 2:
            v, j = _read_bytes(blob, j)
        else:
            j = _skip_field(blob, j, wire)
    return k, v.decode(), i


def encode_membership(m: pb.Membership) -> bytes:
    out = bytearray()
    _tag(out, 1, 0)
    _uvarint(out, m.config_change_id)
    _map_str(out, 2, m.addresses)
    for k in sorted(m.removed):
        _tag(out, 3, 2)
        inner = bytearray()
        _tag(inner, 1, 0)
        _uvarint(inner, k)
        _tag(inner, 2, 0)
        _bool(inner, m.removed[k])
        _bytes(out, bytes(inner))
    _map_str(out, 4, m.non_votings)
    _map_str(out, 5, m.witnesses)
    return bytes(out)


def decode_membership(data) -> pb.Membership:
    mv = memoryview(data)
    i = 0
    ccid = 0
    addresses: dict[int, str] = {}
    removed: dict[int, bool] = {}
    non_votings: dict[int, str] = {}
    witnesses: dict[int, str] = {}
    while i < len(mv):
        key, i = _read_uvarint(mv, i)
        field, wire = key >> 3, key & 7
        if field == 1 and wire == 0:
            ccid, i = _read_uvarint(mv, i)
        elif field == 2 and wire == 2:
            k, v, i = _read_map_str(mv, i)
            addresses[k] = v
        elif field == 3 and wire == 2:
            blob, i = _read_bytes(mv, i)
            k, v = 0, False
            j = 0
            while j < len(blob):
                bkey, j = _read_uvarint(blob, j)
                bf, bw = bkey >> 3, bkey & 7
                if bf == 1 and bw == 0:
                    k, j = _read_uvarint(blob, j)
                elif bf == 2 and bw == 0:
                    b, j = _read_uvarint(blob, j)
                    v = bool(b)
                else:
                    j = _skip_field(blob, j, bw)
            removed[k] = v
        elif field == 4 and wire == 2:
            k, v, i = _read_map_str(mv, i)
            non_votings[k] = v
        elif field == 5 and wire == 2:
            k, v, i = _read_map_str(mv, i)
            witnesses[k] = v
        else:
            i = _skip_field(mv, i, wire)
    return pb.Membership(config_change_id=ccid, addresses=addresses,
                         removed=removed, non_votings=non_votings,
                         witnesses=witnesses)


# --------------------------------------------------------------------------
# SnapshotFile (snapshotfile.go:28): filepath(2), file_size(3),
# file_id(4), metadata(5, only when non-nil)
# --------------------------------------------------------------------------


def encode_snapshot_file(f: pb.SnapshotFile) -> bytes:
    out = bytearray()
    _tag(out, 2, 2)
    _bytes(out, f.filepath.encode())
    _tag(out, 3, 0)
    _uvarint(out, f.file_size)
    _tag(out, 4, 0)
    _uvarint(out, f.file_id)
    if f.metadata:
        _tag(out, 5, 2)
        _bytes(out, f.metadata)
    return bytes(out)


def decode_snapshot_file(data) -> pb.SnapshotFile:
    mv = memoryview(data)
    i = 0
    fp, size, fid, meta = b"", 0, 0, b""
    while i < len(mv):
        key, i = _read_uvarint(mv, i)
        field, wire = key >> 3, key & 7
        if field == 2 and wire == 2:
            fp, i = _read_bytes(mv, i)
        elif field == 3 and wire == 0:
            size, i = _read_uvarint(mv, i)
        elif field == 4 and wire == 0:
            fid, i = _read_uvarint(mv, i)
        elif field == 5 and wire == 2:
            meta, i = _read_bytes(mv, i)
        else:
            i = _skip_field(mv, i, wire)
    return pb.SnapshotFile(file_id=fid, filepath=fp.decode(),
                           metadata=meta, file_size=size)


# --------------------------------------------------------------------------
# Snapshot (snapshot.go:72): filepath(2) .. witness(14); checksum(8)
# only when non-nil, files(7) repeated; everything else always emitted
# --------------------------------------------------------------------------


def encode_snapshot(s: pb.Snapshot) -> bytes:
    out = bytearray()
    _tag(out, 2, 2)
    _bytes(out, s.filepath.encode())
    _tag(out, 3, 0)
    _uvarint(out, s.file_size)
    _tag(out, 4, 0)
    _uvarint(out, s.index)
    _tag(out, 5, 0)
    _uvarint(out, s.term)
    _tag(out, 6, 2)
    _bytes(out, encode_membership(s.membership))
    for f in s.files:
        _tag(out, 7, 2)
        _bytes(out, encode_snapshot_file(f))
    if s.checksum:
        _tag(out, 8, 2)
        _bytes(out, s.checksum)
    _tag(out, 9, 0)
    _bool(out, s.dummy)
    _tag(out, 10, 0)
    _uvarint(out, s.shard_id)
    _tag(out, 11, 0)
    _uvarint(out, int(s.type))
    _tag(out, 12, 0)
    _bool(out, s.imported)
    _tag(out, 13, 0)
    _uvarint(out, s.on_disk_index)
    _tag(out, 14, 0)
    _bool(out, s.witness)
    return bytes(out)


def decode_snapshot(data) -> pb.Snapshot:
    mv = memoryview(data)
    i = 0
    kw: dict = {"membership": pb.Membership(), "files": []}
    while i < len(mv):
        key, i = _read_uvarint(mv, i)
        field, wire = key >> 3, key & 7
        if field == 2 and wire == 2:
            b, i = _read_bytes(mv, i)
            kw["filepath"] = b.decode()
        elif field == 3 and wire == 0:
            kw["file_size"], i = _read_uvarint(mv, i)
        elif field == 4 and wire == 0:
            kw["index"], i = _read_uvarint(mv, i)
        elif field == 5 and wire == 0:
            kw["term"], i = _read_uvarint(mv, i)
        elif field == 6 and wire == 2:
            b, i = _read_bytes(mv, i)
            kw["membership"] = decode_membership(b)
        elif field == 7 and wire == 2:
            b, i = _read_bytes(mv, i)
            kw["files"].append(decode_snapshot_file(b))
        elif field == 8 and wire == 2:
            kw["checksum"], i = _read_bytes(mv, i)
        elif field == 9 and wire == 0:
            v, i = _read_uvarint(mv, i)
            kw["dummy"] = bool(v)
        elif field == 10 and wire == 0:
            kw["shard_id"], i = _read_uvarint(mv, i)
        elif field == 11 and wire == 0:
            v, i = _read_uvarint(mv, i)
            kw["type"] = pb.StateMachineType(v)
        elif field == 12 and wire == 0:
            v, i = _read_uvarint(mv, i)
            kw["imported"] = bool(v)
        elif field == 13 and wire == 0:
            kw["on_disk_index"], i = _read_uvarint(mv, i)
        elif field == 14 and wire == 0:
            v, i = _read_uvarint(mv, i)
            kw["witness"] = bool(v)
        else:
            i = _skip_field(mv, i, wire)
    kw["files"] = tuple(kw["files"])
    return pb.Snapshot(**kw)


# --------------------------------------------------------------------------
# Message (message.go:32): type(1) .. hint(10) always; entries(11)
# repeated Colfer blobs; snapshot(12) always; hint_high(13) always
# --------------------------------------------------------------------------


def encode_message(m: pb.Message) -> bytes:
    out = bytearray()
    _tag(out, 1, 0)
    _uvarint(out, int(m.type))
    _tag(out, 2, 0)
    _uvarint(out, m.to)
    _tag(out, 3, 0)
    _uvarint(out, m.from_)
    _tag(out, 4, 0)
    _uvarint(out, m.shard_id)
    _tag(out, 5, 0)
    _uvarint(out, m.term)
    _tag(out, 6, 0)
    _uvarint(out, m.log_term)
    _tag(out, 7, 0)
    _uvarint(out, m.log_index)
    _tag(out, 8, 0)
    _uvarint(out, m.commit)
    _tag(out, 9, 0)
    _bool(out, m.reject)
    _tag(out, 10, 0)
    _uvarint(out, m.hint)
    for e in m.entries:
        _tag(out, 11, 2)
        _bytes(out, encode_entry(e))
    _tag(out, 12, 2)
    _bytes(out, encode_snapshot(m.snapshot))
    _tag(out, 13, 0)
    _uvarint(out, m.hint_high)
    return bytes(out)


def decode_message(data) -> pb.Message:
    mv = memoryview(data)
    i = 0
    kw: dict = {"entries": []}
    while i < len(mv):
        key, i = _read_uvarint(mv, i)
        field, wire = key >> 3, key & 7
        if field == 1 and wire == 0:
            v, i = _read_uvarint(mv, i)
            kw["type"] = pb.MessageType(v)
        elif field == 2 and wire == 0:
            kw["to"], i = _read_uvarint(mv, i)
        elif field == 3 and wire == 0:
            kw["from_"], i = _read_uvarint(mv, i)
        elif field == 4 and wire == 0:
            kw["shard_id"], i = _read_uvarint(mv, i)
        elif field == 5 and wire == 0:
            kw["term"], i = _read_uvarint(mv, i)
        elif field == 6 and wire == 0:
            kw["log_term"], i = _read_uvarint(mv, i)
        elif field == 7 and wire == 0:
            kw["log_index"], i = _read_uvarint(mv, i)
        elif field == 8 and wire == 0:
            v, i = _read_uvarint(mv, i)
            kw["commit"] = v
        elif field == 9 and wire == 0:
            v, i = _read_uvarint(mv, i)
            kw["reject"] = bool(v)
        elif field == 10 and wire == 0:
            kw["hint"], i = _read_uvarint(mv, i)
        elif field == 11 and wire == 2:
            b, i = _read_bytes(mv, i)
            kw["entries"].append(decode_entry(b))
        elif field == 12 and wire == 2:
            b, i = _read_bytes(mv, i)
            kw["snapshot"] = decode_snapshot(b)
        elif field == 13 and wire == 0:
            kw["hint_high"], i = _read_uvarint(mv, i)
        else:
            i = _skip_field(mv, i, wire)
    kw["entries"] = tuple(kw["entries"])
    return pb.Message(**kw)


# --------------------------------------------------------------------------
# MessageBatch (messagebatch.go:23): requests(1) repeated;
# deployment_id(2), source_address(3), bin_ver(4) always
# --------------------------------------------------------------------------


# the fabric trace header rides an unknown-to-the-reference field: the
# gogo decoder (and ours) skips any unrecognized tag, so a reference
# peer sees nothing and an old frame simply carries no header
FABRIC_FIELD = 15


def encode_message_batch(requests: Sequence[pb.Message],
                         deployment_id: int = 0,
                         source_address: str = "",
                         bin_ver: int = 0,
                         fabric: bytes | None = None) -> bytes:
    out = bytearray()
    for m in requests:
        _tag(out, 1, 2)
        _bytes(out, encode_message(m))
    _tag(out, 2, 0)
    _uvarint(out, deployment_id)
    _tag(out, 3, 2)
    _bytes(out, source_address.encode())
    _tag(out, 4, 0)
    _uvarint(out, bin_ver)
    if fabric is not None:
        _tag(out, FABRIC_FIELD, 2)
        _bytes(out, fabric)
    return bytes(out)


def decode_message_batch(data) -> tuple[
        tuple[pb.Message, ...], int, str, int, bytes | None]:
    """-> (requests, deployment_id, source_address, bin_ver, fabric) —
    ``fabric`` is the raw version-prefixed header blob (field 15) or
    None when the frame carries no header (old peers)."""
    mv = memoryview(data)
    i = 0
    msgs: list[pb.Message] = []
    dep, src, ver = 0, "", 0
    fabric: bytes | None = None
    while i < len(mv):
        key, i = _read_uvarint(mv, i)
        field, wire = key >> 3, key & 7
        if field == 1 and wire == 2:
            b, i = _read_bytes(mv, i)
            msgs.append(decode_message(b))
        elif field == 2 and wire == 0:
            dep, i = _read_uvarint(mv, i)
        elif field == 3 and wire == 2:
            b, i = _read_bytes(mv, i)
            src = b.decode()
        elif field == 4 and wire == 0:
            ver, i = _read_uvarint(mv, i)
        elif field == FABRIC_FIELD and wire == 2:
            b, i = _read_bytes(mv, i)
            fabric = bytes(b)
        else:
            i = _skip_field(mv, i, wire)
    return tuple(msgs), dep, src, ver, fabric


# --------------------------------------------------------------------------
# Chunk (chunk.go:44-146 MarshalTo): the snapshot-stream record a Go
# fleet ships on its snapshot connections.  Same unconditional-emit
# framing as the other gogo records; note there is NO field 11.
# --------------------------------------------------------------------------

# raft.go:256-261: streamed transfers don't know their total up front —
# the tail chunk carries the LastChunkCount sentinel instead
LAST_CHUNK_COUNT = (1 << 64) - 1
POISON_CHUNK_COUNT = (1 << 64) - 2
# raftio/binversion.go:30: the reference REJECTS received batches and
# chunks whose BinVer differs (transport.go:312, chunk.go:108) — every
# outbound go-wire record must stamp this
TRANSPORT_BIN_VERSION = 210


@dataclasses.dataclass(frozen=True)
class GoChunk:
    """The reference's pb.Chunk, reference field layout (chunk.go:11-31).
    Deliberately distinct from the repo's own ``raftpb.Chunk`` (native
    wire: concatenated stream + embedded chunk-0 message) — the Go wire
    splits PER FILE and synthesizes the InstallSnapshot receiver-side."""

    shard_id: int = 0
    replica_id: int = 0          # target
    from_: int = 0               # sender replica
    chunk_id: int = 0
    chunk_size: int = 0
    chunk_count: int = 0
    data: bytes = b""
    index: int = 0
    term: int = 0
    membership: pb.Membership = dataclasses.field(
        default_factory=pb.Membership)
    filepath: str = ""
    file_size: int = 0
    deployment_id: int = 0
    file_chunk_id: int = 0
    file_chunk_count: int = 0
    has_file_info: bool = False
    file_info: pb.SnapshotFile = dataclasses.field(
        default_factory=lambda: pb.SnapshotFile(file_id=0, filepath=""))
    bin_ver: int = TRANSPORT_BIN_VERSION
    on_disk_index: int = 0
    witness: bool = False

    def is_last(self) -> bool:
        # IsLastChunk (raft.go:267): counted transfers end at
        # chunk_count == chunk_id+1; streamed ones at the sentinel
        return (self.chunk_count == LAST_CHUNK_COUNT
                or self.chunk_count == self.chunk_id + 1)

    def is_last_file_chunk(self) -> bool:
        # IsLastFileChunk (raft.go:273)
        return self.file_chunk_id + 1 == self.file_chunk_count

    def is_poison(self) -> bool:
        return self.chunk_count == POISON_CHUNK_COUNT


def encode_chunk(c: GoChunk) -> bytes:
    out = bytearray()
    _tag(out, 1, 0)
    _uvarint(out, c.shard_id)
    _tag(out, 2, 0)
    _uvarint(out, c.replica_id)
    _tag(out, 3, 0)
    _uvarint(out, c.from_)
    _tag(out, 4, 0)
    _uvarint(out, c.chunk_id)
    _tag(out, 5, 0)
    _uvarint(out, c.chunk_size)
    _tag(out, 6, 0)
    _uvarint(out, c.chunk_count)
    if c.data:
        _tag(out, 7, 2)
        _bytes(out, c.data)
    _tag(out, 8, 0)
    _uvarint(out, c.index)
    _tag(out, 9, 0)
    _uvarint(out, c.term)
    _tag(out, 10, 2)
    _bytes(out, encode_membership(c.membership))
    _tag(out, 12, 2)
    _bytes(out, c.filepath.encode())
    _tag(out, 13, 0)
    _uvarint(out, c.file_size)
    _tag(out, 14, 0)
    _uvarint(out, c.deployment_id)
    _tag(out, 15, 0)
    _uvarint(out, c.file_chunk_id)
    _tag(out, 16, 0)
    _uvarint(out, c.file_chunk_count)
    _tag(out, 17, 0)
    _bool(out, c.has_file_info)
    _tag(out, 18, 2)
    _bytes(out, encode_snapshot_file(c.file_info))
    _tag(out, 19, 0)
    _uvarint(out, c.bin_ver)
    _tag(out, 20, 0)
    _uvarint(out, c.on_disk_index)
    _tag(out, 21, 0)
    _bool(out, c.witness)
    return bytes(out)


def decode_chunk(data) -> GoChunk:
    mv = memoryview(data)
    i = 0
    kw: dict = {}
    while i < len(mv):
        key, i = _read_uvarint(mv, i)
        field, wire = key >> 3, key & 7
        if field == 1 and wire == 0:
            kw["shard_id"], i = _read_uvarint(mv, i)
        elif field == 2 and wire == 0:
            kw["replica_id"], i = _read_uvarint(mv, i)
        elif field == 3 and wire == 0:
            kw["from_"], i = _read_uvarint(mv, i)
        elif field == 4 and wire == 0:
            kw["chunk_id"], i = _read_uvarint(mv, i)
        elif field == 5 and wire == 0:
            kw["chunk_size"], i = _read_uvarint(mv, i)
        elif field == 6 and wire == 0:
            kw["chunk_count"], i = _read_uvarint(mv, i)
        elif field == 7 and wire == 2:
            kw["data"], i = _read_bytes(mv, i)
        elif field == 8 and wire == 0:
            kw["index"], i = _read_uvarint(mv, i)
        elif field == 9 and wire == 0:
            kw["term"], i = _read_uvarint(mv, i)
        elif field == 10 and wire == 2:
            b, i = _read_bytes(mv, i)
            kw["membership"] = decode_membership(b)
        elif field == 12 and wire == 2:
            b, i = _read_bytes(mv, i)
            kw["filepath"] = b.decode()
        elif field == 13 and wire == 0:
            kw["file_size"], i = _read_uvarint(mv, i)
        elif field == 14 and wire == 0:
            kw["deployment_id"], i = _read_uvarint(mv, i)
        elif field == 15 and wire == 0:
            kw["file_chunk_id"], i = _read_uvarint(mv, i)
        elif field == 16 and wire == 0:
            kw["file_chunk_count"], i = _read_uvarint(mv, i)
        elif field == 17 and wire == 0:
            v, i = _read_uvarint(mv, i)
            kw["has_file_info"] = bool(v)
        elif field == 18 and wire == 2:
            b, i = _read_bytes(mv, i)
            kw["file_info"] = decode_snapshot_file(b)
        elif field == 19 and wire == 0:
            kw["bin_ver"], i = _read_uvarint(mv, i)
        elif field == 20 and wire == 0:
            kw["on_disk_index"], i = _read_uvarint(mv, i)
        elif field == 21 and wire == 0:
            v, i = _read_uvarint(mv, i)
            kw["witness"] = bool(v)
        else:
            i = _skip_field(mv, i, wire)
    return GoChunk(**kw)
