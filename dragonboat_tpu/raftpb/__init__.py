"""dragonboat_tpu.raftpb — the wire/state record algebra of the framework.

TPU-native re-expression of the reference's ``raftpb`` package
(``/root/reference/raftpb/``).  The reference hand-rolls protobuf structs
(``raftpb/message.go:6-20``, ``raftpb/entry.go:6-15``, ``raftpb/state.go:11``,
``raftpb/update.go:74-112``); here the same algebra exists in two forms:

1. **Host records** (this module): frozen dataclasses used by the host runtime
   (NodeHost, LogDB, transport, RSM).  These carry variable-length payloads
   (``Entry.cmd``, membership maps, snapshots) that never live on device.
2. **Device lanes** (``dragonboat_tpu.core``): fixed-width SoA arrays holding
   the subset of fields the batched Raft kernel needs (terms, indexes,
   cursors, flow-control state).  The kernel engine's staging buffers
   (``engine.kernel_engine._InboxBuilder`` / ``_InputBuilder``) and the
   device router (``core.router``) convert between the two.

Enum values mirror the reference exactly (``raftpb/types.go:8-215``) so that
recorded histories, golden tests, and host interop stay comparable.
"""

from __future__ import annotations

import enum
import struct
import zlib
from dataclasses import dataclass, field, replace
from typing import Iterable, Sequence


class MessageType(enum.IntEnum):
    """Raft message algebra — parity with /root/reference/raftpb/types.go:8-38."""

    LOCAL_TICK = 0
    ELECTION = 1
    LEADER_HEARTBEAT = 2
    CONFIG_CHANGE_EVENT = 3
    NOOP = 4
    PING = 5
    PONG = 6
    PROPOSE = 7
    SNAPSHOT_STATUS = 8
    UNREACHABLE = 9
    CHECK_QUORUM = 10
    BATCHED_READ_INDEX = 11
    REPLICATE = 12
    REPLICATE_RESP = 13
    REQUEST_VOTE = 14
    REQUEST_VOTE_RESP = 15
    INSTALL_SNAPSHOT = 16
    HEARTBEAT = 17
    HEARTBEAT_RESP = 18
    READ_INDEX = 19
    READ_INDEX_RESP = 20
    QUIESCE = 21
    SNAPSHOT_RECEIVED = 22
    LEADER_TRANSFER = 23
    TIMEOUT_NOW = 24
    RATE_LIMIT = 25
    REQUEST_PREVOTE = 26
    REQUEST_PREVOTE_RESP = 27
    LOG_QUERY = 28


NUM_MESSAGE_TYPES = 29


class EntryType(enum.IntEnum):
    """Parity with /root/reference/raftpb/types.go:110-115."""

    APPLICATION = 0
    CONFIG_CHANGE = 1
    ENCODED = 2
    METADATA = 3


class ConfigChangeType(enum.IntEnum):
    """Parity with /root/reference/raftpb/types.go:137-142."""

    ADD_NODE = 0
    REMOVE_NODE = 1
    ADD_NON_VOTING = 2
    ADD_WITNESS = 3


class StateMachineType(enum.IntEnum):
    """Parity with /root/reference/raftpb/types.go:164-169."""

    UNKNOWN = 0
    REGULAR = 1
    CONCURRENT = 2
    ON_DISK = 3


class CompressionType(enum.IntEnum):
    NO_COMPRESSION = 0
    SNAPPY = 1  # host payloads use zlib when snappy unavailable; tagged distinctly


class ChecksumType(enum.IntEnum):
    CRC32IEEE = 0
    HIGHWAY = 1


# Client-session sentinel values — parity with client/session.go semantics:
# a NoOP session proposal carries SeriesID==NoOPSeriesID and is not deduped.
NOOP_SERIES_ID = 0
SERIES_ID_FIRST_PROPOSAL = 1
# SeriesID used by a client to unregister its session.
SERIES_ID_FOR_UNREGISTER = (1 << 64) - 1
SERIES_ID_FOR_REGISTER = (1 << 64) - 2

U64_MASK = (1 << 64) - 1


@dataclass(frozen=True, slots=True)
class Entry:
    """One raft log entry — parity with /root/reference/raftpb/entry.go:6-15."""

    term: int = 0
    index: int = 0
    type: EntryType = EntryType.APPLICATION
    key: int = 0
    client_id: int = 0
    series_id: int = 0
    responded_to: int = 0
    cmd: bytes = b""

    def is_empty(self) -> bool:
        return len(self.cmd) == 0

    def is_config_change(self) -> bool:
        return self.type == EntryType.CONFIG_CHANGE

    def is_session_managed(self) -> bool:
        # parity: raftpb/raft.go IsSessionManaged — config change entries and
        # NoOP-session client ops are not session managed.
        if self.is_config_change():
            return False
        return self.client_id != 0 or self.series_id != NOOP_SERIES_ID

    def is_noop_session(self) -> bool:
        return self.series_id == NOOP_SERIES_ID

    def is_new_session_request(self) -> bool:
        return (
            not self.is_config_change()
            and len(self.cmd) == 0
            and self.client_id != 0
            and self.series_id == SERIES_ID_FOR_REGISTER
        )

    def is_end_of_session_request(self) -> bool:
        return (
            not self.is_config_change()
            and len(self.cmd) == 0
            and self.client_id != 0
            and self.series_id == SERIES_ID_FOR_UNREGISTER
        )

    def is_update(self) -> bool:
        return (
            not self.is_config_change()
            and not self.is_new_session_request()
            and not self.is_end_of_session_request()
        )

    def is_proposal(self) -> bool:
        return not self.is_config_change()


@dataclass(frozen=True, slots=True)
class State:
    """Persistent raft state — parity with /root/reference/raftpb/state.go:11."""

    term: int = 0
    vote: int = 0
    commit: int = 0

    def is_empty(self) -> bool:
        return self == State()


@dataclass(frozen=True, slots=True)
class Membership:
    """Replicated membership — parity with /root/reference/raftpb/membership.go:11-17."""

    config_change_id: int = 0
    addresses: dict[int, str] = field(default_factory=dict)  # voters
    non_votings: dict[int, str] = field(default_factory=dict)
    witnesses: dict[int, str] = field(default_factory=dict)
    removed: dict[int, bool] = field(default_factory=dict)

    def copy(self) -> "Membership":
        return Membership(
            self.config_change_id,
            dict(self.addresses),
            dict(self.non_votings),
            dict(self.witnesses),
            dict(self.removed),
        )


@dataclass(frozen=True, slots=True)
class ConfigChange:
    """Parity with the reference's raftpb.ConfigChange payload."""

    config_change_id: int = 0
    type: ConfigChangeType = ConfigChangeType.ADD_NODE
    replica_id: int = 0
    address: str = ""
    initialize: bool = False


@dataclass(frozen=True, slots=True)
class SnapshotFile:
    """External file attached to a snapshot (rsm/files.go parity)."""

    file_id: int = 0
    filepath: str = ""
    metadata: bytes = b""
    file_size: int = 0


@dataclass(frozen=True, slots=True)
class Snapshot:
    """Snapshot metadata — parity with /root/reference/raftpb/snapshot.go:16-60."""

    filepath: str = ""
    file_size: int = 0
    index: int = 0
    term: int = 0
    membership: Membership = field(default_factory=Membership)
    files: tuple[SnapshotFile, ...] = ()
    checksum: bytes = b""
    dummy: bool = False
    shard_id: int = 0
    type: StateMachineType = StateMachineType.UNKNOWN
    imported: bool = False
    on_disk_index: int = 0
    witness: bool = False

    def is_empty(self) -> bool:
        return self.index == 0


@dataclass(frozen=True, slots=True)
class Bootstrap:
    """Initial membership record — parity with raftpb.Bootstrap."""

    addresses: dict[int, str] = field(default_factory=dict)
    join: bool = False
    type: StateMachineType = StateMachineType.REGULAR


@dataclass(frozen=True, slots=True)
class SystemCtx:
    """ReadIndex context pair — parity with raftpb.SystemCtx {Low, High}."""

    low: int = 0
    high: int = 0


@dataclass(frozen=True, slots=True)
class ReadyToRead:
    index: int = 0
    system_ctx: SystemCtx = field(default_factory=SystemCtx)


@dataclass(frozen=True, slots=True)
class Message:
    """Parity with /root/reference/raftpb/message.go:6-20."""

    type: MessageType = MessageType.NOOP
    to: int = 0
    from_: int = 0
    shard_id: int = 0
    term: int = 0
    log_term: int = 0
    log_index: int = 0
    commit: int = 0
    reject: bool = False
    hint: int = 0
    hint_high: int = 0
    entries: tuple[Entry, ...] = ()
    snapshot: Snapshot = field(default_factory=Snapshot)

    def is_local(self) -> bool:
        """Local-only message types never cross the transport
        (parity: raftpb/raft.go IsLocalMessageType)."""
        return self.type in _LOCAL_TYPES

    def is_response(self) -> bool:
        return self.type in _RESPONSE_TYPES


_LOCAL_TYPES = frozenset(
    {
        MessageType.ELECTION,
        MessageType.LEADER_HEARTBEAT,
        MessageType.UNREACHABLE,
        MessageType.SNAPSHOT_STATUS,
        MessageType.CHECK_QUORUM,
        MessageType.LOCAL_TICK,
        MessageType.BATCHED_READ_INDEX,
        MessageType.SNAPSHOT_RECEIVED,
        MessageType.RATE_LIMIT,
        MessageType.LOG_QUERY,
    }
)

_RESPONSE_TYPES = frozenset(
    {
        MessageType.REPLICATE_RESP,
        MessageType.REQUEST_VOTE_RESP,
        MessageType.HEARTBEAT_RESP,
        MessageType.READ_INDEX_RESP,
        MessageType.REQUEST_PREVOTE_RESP,
    }
)


@dataclass(frozen=True, slots=True)
class FabricContext:
    """One sampled proposal's trace context riding a transport frame
    (fabric.py cross-host propagation): the lifecycle trace key, the
    origin NodeHost address, the shard, and the host-hub hop count so
    far.  ``origin == receiver`` marks a context returning home (the
    quorum ack), anything else an outbound replicate."""

    key: int = 0
    origin: str = ""
    hop: int = 0
    shard_id: int = 0


@dataclass(frozen=True, slots=True)
class FabricHeader:
    """Versioned optional trace header on a MessageBatch.  Absent by
    default — old frames (and old peers) carry/see nothing; the native
    wire appends it as a magic-guarded trailer the old decoder ignores,
    the go wire ships it in an unknown-to-the-reference protobuf field
    its decoder skips.  ``sent_us`` is the sender's injected monotonic
    clock at flush time (per-link delivery-latency attribution)."""

    version: int = 1
    sent_us: int = 0
    ctxs: tuple[FabricContext, ...] = ()


# bump when the FabricContext layout changes; decoders return None for
# versions they do not understand (mixed-version clusters interop — the
# header degrades to absent, never to a parse error)
FABRIC_WIRE_VERSION = 1
# native-wire trailer guard: little-endian b"FBH1" after the message
# array (still inside the CRC-covered body)
_FABRIC_MAGIC = 0x31484246


def encode_fabric_header(h: FabricHeader) -> bytes:
    """Version-prefixed header blob shared by both wire formats."""
    buf = bytearray(struct.pack("<BQI", h.version, h.sent_us, len(h.ctxs)))
    for c in h.ctxs:
        o = c.origin.encode()
        buf += struct.pack("<QQII", c.key, c.shard_id, c.hop, len(o))
        buf += o
    return bytes(buf)


def decode_fabric_header(data) -> FabricHeader | None:
    """None for an unknown version (forward compat), raises on a
    truncated blob of a known version (corruption, not skew)."""
    mv = memoryview(data)
    version, sent_us, n = struct.unpack_from("<BQI", mv, 0)
    if version != FABRIC_WIRE_VERSION:
        return None
    off = 13
    ctxs = []
    for _ in range(n):
        key, shard_id, hop, olen = struct.unpack_from("<QQII", mv, off)
        off += 24
        origin = bytes(mv[off:off + olen]).decode()
        if len(origin.encode()) != olen:
            raise ValueError("fabric header truncated")
        off += olen
        ctxs.append(FabricContext(key=key, origin=origin, hop=hop,
                                  shard_id=shard_id))
    return FabricHeader(version=version, sent_us=sent_us, ctxs=tuple(ctxs))


@dataclass(frozen=True, slots=True)
class MessageBatch:
    """Transport frame — parity with raftpb/messagebatch.go:6, plus the
    optional fabric trace header (absent on old frames)."""

    requests: tuple[Message, ...] = ()
    deployment_id: int = 0
    source_address: str = ""
    bin_ver: int = 0
    fabric: FabricHeader | None = None


@dataclass(frozen=True, slots=True)
class LeaderUpdate:
    leader_id: int = 0
    term: int = 0


@dataclass(frozen=True, slots=True)
class LogQueryResult:
    error: int = 0  # 0 ok, 1 out of range, 2 unavailable
    first_index: int = 0
    last_index: int = 0
    entries: tuple[Entry, ...] = ()


@dataclass(frozen=True, slots=True)
class UpdateCommit:
    """Parity with /root/reference/raftpb/update.go:60-72."""

    processed: int = 0
    last_applied: int = 0
    stable_log_to: int = 0
    stable_log_term: int = 0
    stable_snapshot_to: int = 0
    ready_to_read: int = 0


@dataclass(frozen=True, slots=True)
class Update:
    """Device→host result batch for one shard —
    parity with /root/reference/raftpb/update.go:74-112."""

    shard_id: int = 0
    replica_id: int = 0
    state: State = field(default_factory=State)
    fast_apply: bool = False
    entries_to_save: tuple[Entry, ...] = ()
    committed_entries: tuple[Entry, ...] = ()
    more_committed_entries: bool = False
    snapshot: Snapshot = field(default_factory=Snapshot)
    ready_to_reads: tuple[ReadyToRead, ...] = ()
    messages: tuple[Message, ...] = ()
    last_applied: int = 0
    update_commit: UpdateCommit = field(default_factory=UpdateCommit)
    dropped_entries: tuple[Entry, ...] = ()
    dropped_read_indexes: tuple[SystemCtx, ...] = ()
    log_query_result: LogQueryResult = field(default_factory=LogQueryResult)
    leader_update: LeaderUpdate | None = None

    def has_update(self) -> bool:
        return bool(
            not self.state.is_empty()
            or self.entries_to_save
            or self.committed_entries
            or self.messages
            or self.ready_to_reads
            or not self.snapshot.is_empty()
            or self.dropped_entries
            or self.dropped_read_indexes
            or self.leader_update is not None
        )


# ---------------------------------------------------------------------------
# Serialization.
#
# The reference uses hand-optimized protobuf wire format
# (raftpb/raft_optimized.go).  Interop with Go processes is a non-goal for the
# TPU build; what matters is a stable, checksummed, compact binary format for
# (a) the LogDB on-disk layout, (b) the TCP transport frames, (c) golden tests.
# We use a little-endian fixed-header format: cheap to encode from Python and
# trivially fuzzable.  All varints in the reference become fixed u64 here —
# entries are dominated by payloads, and storage batches are compressed.
# ---------------------------------------------------------------------------

_ENTRY_HDR = struct.Struct("<QQBQQQQI")  # term,index,type,key,client,series,responded,cmdlen
_MSG_HDR = struct.Struct("<BQQQQQQQBQQII")  # type,to,from,shard,term,logterm,logindex,commit,reject,hint,hinthigh,nentries,snaplen
_STATE = struct.Struct("<QQQ")


def encode_entry(e: Entry, buf: bytearray) -> None:
    buf += _ENTRY_HDR.pack(
        e.term, e.index, e.type, e.key, e.client_id, e.series_id, e.responded_to, len(e.cmd)
    )
    buf += e.cmd


def decode_entry(data: memoryview, off: int) -> tuple[Entry, int]:
    term, index, typ, key, client, series, responded, cmdlen = _ENTRY_HDR.unpack_from(data, off)
    off += _ENTRY_HDR.size
    cmd = bytes(data[off : off + cmdlen])
    off += cmdlen
    return (
        Entry(term, index, EntryType(typ), key, client, series, responded, cmd),
        off,
    )


def entry_size(e: Entry) -> int:
    """In-memory size estimate used for rate limiting — parity with
    the reference's Entry.SizeUpperLimit usage in server/rate.go."""
    return _ENTRY_HDR.size + len(e.cmd)


def encode_state(s: State) -> bytes:
    return _STATE.pack(s.term, s.vote, s.commit)


def decode_state(data: bytes) -> State:
    t, v, c = _STATE.unpack(data)
    return State(t, v, c)


def _encode_membership(m: Membership, buf: bytearray) -> None:
    def emap(d: dict[int, str]) -> None:
        buf.extend(struct.pack("<I", len(d)))
        for k in sorted(d):
            v = d[k].encode()
            buf.extend(struct.pack("<QI", k, len(v)))
            buf.extend(v)

    buf.extend(struct.pack("<Q", m.config_change_id))
    emap(m.addresses)
    emap(m.non_votings)
    emap(m.witnesses)
    buf.extend(struct.pack("<I", len(m.removed)))
    for k in sorted(m.removed):
        buf.extend(struct.pack("<Q", k))


def _decode_membership(data: memoryview, off: int) -> tuple[Membership, int]:
    def dmap() -> dict[int, str]:
        nonlocal off
        (n,) = struct.unpack_from("<I", data, off)
        off += 4
        out: dict[int, str] = {}
        for _ in range(n):
            k, ln = struct.unpack_from("<QI", data, off)
            off += 12
            out[k] = bytes(data[off : off + ln]).decode()
            off += ln
        return out

    (ccid,) = struct.unpack_from("<Q", data, off)
    off += 8
    addresses = dmap()
    non_votings = dmap()
    witnesses = dmap()
    (n,) = struct.unpack_from("<I", data, off)
    off += 4
    removed: dict[int, bool] = {}
    for _ in range(n):
        (k,) = struct.unpack_from("<Q", data, off)
        off += 8
        removed[k] = True
    return Membership(ccid, addresses, non_votings, witnesses, removed), off


_SNAP_HDR = struct.Struct("<QQQQBBBBI")  # index,term,shard,ondisk,dummy,type,imported,witness,pathlen


def encode_snapshot(s: Snapshot, buf: bytearray) -> None:
    p = s.filepath.encode()
    buf += _SNAP_HDR.pack(
        s.index, s.term, s.shard_id, s.on_disk_index,
        int(s.dummy), int(s.type), int(s.imported), int(s.witness), len(p),
    )
    buf += p
    buf += struct.pack("<Q", s.file_size)
    buf += struct.pack("<I", len(s.checksum))
    buf += s.checksum
    _encode_membership(s.membership, buf)
    buf += struct.pack("<I", len(s.files))
    for f in s.files:
        fp = f.filepath.encode()
        buf += struct.pack("<QQII", f.file_id, f.file_size, len(fp),
                           len(f.metadata))
        buf += fp
        buf += f.metadata


def decode_snapshot(data: memoryview, off: int) -> tuple[Snapshot, int]:
    index, term, shard, ondisk, dummy, typ, imported, witness, plen = _SNAP_HDR.unpack_from(
        data, off
    )
    off += _SNAP_HDR.size
    path = bytes(data[off : off + plen]).decode()
    off += plen
    (fsize,) = struct.unpack_from("<Q", data, off)
    off += 8
    (clen,) = struct.unpack_from("<I", data, off)
    off += 4
    checksum = bytes(data[off : off + clen])
    off += clen
    membership, off = _decode_membership(data, off)
    (nf,) = struct.unpack_from("<I", data, off)
    off += 4
    files = []
    for _ in range(nf):
        fid, fsz, fplen, mlen = struct.unpack_from("<QQII", data, off)
        off += 24
        fpath = bytes(data[off : off + fplen]).decode()
        off += fplen
        meta = bytes(data[off : off + mlen])
        off += mlen
        files.append(SnapshotFile(fid, fpath, meta, fsz))
    return (
        Snapshot(
            filepath=path,
            file_size=fsize,
            index=index,
            term=term,
            membership=membership,
            files=tuple(files),
            checksum=checksum,
            dummy=bool(dummy),
            shard_id=shard,
            type=StateMachineType(typ),
            imported=bool(imported),
            on_disk_index=ondisk,
            witness=bool(witness),
        ),
        off,
    )


def encode_message(m: Message, buf: bytearray) -> None:
    snap = bytearray()
    if not m.snapshot.is_empty():
        encode_snapshot(m.snapshot, snap)
    buf += _MSG_HDR.pack(
        int(m.type), m.to, m.from_, m.shard_id, m.term, m.log_term, m.log_index,
        m.commit, int(m.reject), m.hint, m.hint_high, len(m.entries), len(snap),
    )
    for e in m.entries:
        encode_entry(e, buf)
    buf += snap


def decode_message(data: memoryview, off: int) -> tuple[Message, int]:
    (typ, to, frm, shard, term, logterm, logindex, commit, reject, hint, hinthigh,
     nent, snaplen) = _MSG_HDR.unpack_from(data, off)
    off += _MSG_HDR.size
    entries = []
    for _ in range(nent):
        e, off = decode_entry(data, off)
        entries.append(e)
    snapshot = Snapshot()
    if snaplen:
        snapshot, off = decode_snapshot(data, off)
    return (
        Message(
            type=MessageType(typ),
            to=to,
            from_=frm,
            shard_id=shard,
            term=term,
            log_term=logterm,
            log_index=logindex,
            commit=commit,
            reject=bool(reject),
            hint=hint,
            hint_high=hinthigh,
            entries=tuple(entries),
            snapshot=snapshot,
        ),
        off,
    )


def encode_message_batch(b: MessageBatch) -> bytes:
    buf = bytearray()
    src = b.source_address.encode()
    buf += struct.pack("<QII", b.deployment_id, b.bin_ver, len(src))
    buf += src
    buf += struct.pack("<I", len(b.requests))
    for m in b.requests:
        encode_message(m, buf)
    if b.fabric is not None:
        # versioned optional trailer, still under the CRC: the decoder
        # reads exactly n messages, so an old peer ignores these bytes
        buf += struct.pack("<I", _FABRIC_MAGIC)
        buf += encode_fabric_header(b.fabric)
    crc = zlib.crc32(bytes(buf))
    return struct.pack("<I", crc) + bytes(buf)


def decode_message_batch(data: bytes) -> MessageBatch:
    (crc,) = struct.unpack_from("<I", data, 0)
    body = memoryview(data)[4:]
    if zlib.crc32(bytes(body)) != crc:
        raise ValueError("message batch checksum mismatch")
    off = 0
    deployment_id, bin_ver, slen = struct.unpack_from("<QII", body, off)
    off += 16
    src = bytes(body[off : off + slen]).decode()
    off += slen
    (n,) = struct.unpack_from("<I", body, off)
    off += 4
    msgs = []
    for _ in range(n):
        m, off = decode_message(body, off)
        msgs.append(m)
    fabric = None
    if len(body) - off >= 4:
        (magic,) = struct.unpack_from("<I", body, off)
        if magic == _FABRIC_MAGIC:
            fabric = decode_fabric_header(body[off + 4:])
    return MessageBatch(tuple(msgs), deployment_id, src, bin_ver, fabric)


def encode_bootstrap(b: Bootstrap) -> bytes:
    buf = bytearray()
    buf += struct.pack("<BI", int(b.join), len(b.addresses))
    for k in sorted(b.addresses):
        v = b.addresses[k].encode()
        buf += struct.pack("<QI", k, len(v))
        buf += v
    buf += struct.pack("<B", int(b.type))
    return bytes(buf)


def decode_bootstrap(data: bytes) -> Bootstrap:
    mv = memoryview(data)
    join, n = struct.unpack_from("<BI", mv, 0)
    off = 5
    addrs: dict[int, str] = {}
    for _ in range(n):
        k, ln = struct.unpack_from("<QI", mv, off)
        off += 12
        addrs[k] = bytes(mv[off : off + ln]).decode()
        off += ln
    (typ,) = struct.unpack_from("<B", mv, off)
    return Bootstrap(addrs, bool(join), StateMachineType(typ))


def encode_config_change(cc: ConfigChange) -> bytes:
    addr = cc.address.encode()
    return (
        struct.pack(
            "<QBQBI", cc.config_change_id, int(cc.type), cc.replica_id,
            int(cc.initialize), len(addr),
        )
        + addr
    )


def decode_config_change(data: bytes) -> ConfigChange:
    ccid, typ, rid, init, alen = struct.unpack_from("<QBQBI", data, 0)
    off = struct.calcsize("<QBQBI")
    addr = data[off : off + alen].decode()
    return ConfigChange(ccid, ConfigChangeType(typ), rid, addr, bool(init))


def entries_to_apply(entries: Sequence[Entry], applied: int) -> Sequence[Entry]:
    """Skip entries at or below the applied index —
    parity with /root/reference/raftpb/entry.go:27 (EntriesToApply)."""
    if not entries:
        return entries
    last = entries[-1].index
    if last <= applied:
        return ()
    first = entries[0].index
    if first > applied + 1:
        raise ValueError(f"gap between applied {applied} and first entry {first}")
    return entries[applied + 1 - first :]


# ---------------------------------------------------------------------------
# Snapshot wire chunk — parity raftpb/chunk.go:11 (Chunk).
#
# A snapshot transfer is a stream of fixed-size chunks; chunk 0 additionally
# carries the encoded InstallSnapshot message (metadata + membership) so the
# receiver can rebuild and deliver it once the file is reassembled.
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class Chunk:
    shard_id: int = 0
    replica_id: int = 0          # target
    from_: int = 0               # sender replica
    chunk_id: int = 0
    chunk_count: int = 0
    chunk_size: int = 0          # bytes of data in this chunk
    file_size: int = 0           # total snapshot file size
    index: int = 0               # snapshot index (transfer identity)
    term: int = 0
    deployment_id: int = 0
    bin_ver: int = 1
    source_address: str = ""           # sender NodeHost address (chunk 0)
    data: bytes = b""
    message: "Message | None" = None   # chunk 0 only

    def is_last(self) -> bool:
        return self.chunk_id == self.chunk_count - 1


_CHUNK_HDR = struct.Struct("<QQQQQQQQQQIII")


def encode_chunk(c: Chunk) -> bytes:
    buf = bytearray()
    mbuf = bytearray()
    if c.message is not None:
        encode_message(c.message, mbuf)
    src = c.source_address.encode()
    buf += _CHUNK_HDR.pack(
        c.shard_id, c.replica_id, c.from_, c.chunk_id, c.chunk_count,
        c.chunk_size, c.file_size, c.index, c.term, c.deployment_id,
        len(src), len(mbuf), len(c.data),
    )
    buf += src
    buf += mbuf
    buf += c.data
    crc = zlib.crc32(bytes(buf))
    return struct.pack("<I", crc) + bytes(buf)


def decode_chunk(data: bytes) -> Chunk:
    (crc,) = struct.unpack_from("<I", data, 0)
    body = memoryview(data)[4:]
    if zlib.crc32(bytes(body)) != crc:
        raise ValueError("chunk checksum mismatch")
    (shard_id, replica_id, from_, chunk_id, chunk_count, chunk_size,
     file_size, index, term, deployment_id, slen, mlen, dlen) = \
        _CHUNK_HDR.unpack_from(body, 0)
    off = _CHUNK_HDR.size
    src = bytes(body[off:off + slen]).decode()
    off += slen
    message = None
    if mlen:
        message, _ = decode_message(body, off)
        off += mlen
    payload = bytes(body[off:off + dlen])
    return Chunk(
        shard_id=shard_id, replica_id=replica_id, from_=from_,
        chunk_id=chunk_id, chunk_count=chunk_count, chunk_size=chunk_size,
        file_size=file_size, index=index, term=term,
        deployment_id=deployment_id, source_address=src, data=payload,
        message=message,
    )
