"""Flight recorder: a bounded ring buffer of fleet transition records.

When a chaos schedule diverges or an operator asks "what happened just
before this", per-shard state is already gone — the kernel overwrote
it.  The flight recorder keeps the last N *transitions* (leader
changes, term bumps, snapshot send/recv, breaker trips, quarantine
truncations, chaos fault injections) as small structured dicts,
dumpable to JSON on demand and automatically appended to a chaos-oracle
failure report.

Determinism: this module is in the determinism lint scope.  Records are
stamped with a process-monotonic sequence number plus whatever tick the
*caller* supplies (engine step counters, chaos event indices) — never
the wall clock — so a replayed schedule produces an identical tail.
"""

from __future__ import annotations

import json
import threading
from collections import deque

# transition kinds recorded by the built-in hooks (callers may add more)
LEADER_CHANGE = "leader_change"
SNAPSHOT = "snapshot"
BREAKER_TRIP = "breaker_trip"
QUARANTINE = "quarantine"
CHAOS_FAULT = "chaos_fault"
EVICTION = "eviction"
SLOW_COMMIT = "slow_commit"
ANOMALY_RAISED = "anomaly_raised"
ANOMALY_CLEARED = "anomaly_cleared"
RETRACE_STORM = "retrace_storm"
MEMORY_PRESSURE = "memory_pressure"
INVARIANT_VIOLATION = "invariant_violation"
CONTROL_TRANSFER = "control_transfer"
ADMISSION_REFUSED = "admission_refused"


class FlightRecorder:
    """Thread-safe bounded ring of structured transition records."""

    def __init__(self, capacity: int = 512) -> None:
        if capacity <= 0:
            raise ValueError(f"capacity must be positive, got {capacity}")
        self.capacity = capacity
        self.mu = threading.Lock()
        self._cond = threading.Condition(self.mu)
        self._records: deque = deque(maxlen=capacity)     # guarded-by: mu
        self._seq = 0                                     # guarded-by: mu

    def record(self, kind: str, **fields) -> int:
        """Append one record; returns its monotonic sequence number.
        ``fields`` must be JSON-serializable (enforced at dump time)."""
        with self.mu:
            seq = self._seq
            self._seq += 1
            rec = {"seq": seq, "kind": kind}
            rec.update(fields)
            self._records.append(rec)
            self._cond.notify_all()
        return seq

    def wait_beyond(self, seq: int, timeout: float | None = None) -> bool:
        """Block until a record with sequence >= ``seq`` exists (i.e. at
        least one record landed after the caller sampled ``next_seq``).
        Event-driven convergence waits poll THIS instead of sleeping:
        the chaos runner re-checks its oracle each time any transition
        (anomaly_cleared, leader_change, ...) is recorded.  Returns
        False on timeout."""
        with self.mu:
            return self._cond.wait_for(lambda: self._seq > seq, timeout)

    def __len__(self) -> int:
        with self.mu:
            return len(self._records)

    @property
    def next_seq(self) -> int:
        with self.mu:
            return self._seq

    def tail(self, k: int | None = None) -> list:
        """The most recent ``k`` records (all retained when ``k`` is
        None), oldest first, as fresh dicts."""
        with self.mu:
            recs = [dict(r) for r in self._records]
        if k is not None and k >= 0:
            recs = recs[len(recs) - min(k, len(recs)):]
        return recs

    def clear(self) -> None:
        """Drop retained records; the sequence counter keeps running so
        pre/post-clear records remain ordered."""
        with self.mu:
            self._records.clear()

    def dump_json(self, k: int | None = None, indent: int | None = None
                  ) -> str:
        """Canonical JSON of ``tail(k)`` (sorted keys, stable across
        processes for identical record streams)."""
        return json.dumps(self.tail(k), sort_keys=True, indent=indent)

    def dump(self, path: str, k: int | None = None) -> str:
        """Write ``dump_json`` to ``path``; returns the path."""
        data = self.dump_json(k, indent=2)
        with open(path, "w", encoding="utf-8") as f:
            f.write(data + "\n")
        return path


# process-wide recorder: producers (events hub, transport hub, logdb,
# chaos runner) record here so one dump shows the interleaved fleet
# history across every host in the process
RECORDER = FlightRecorder()


def record(kind: str, **fields) -> int:
    return RECORDER.record(kind, **fields)
