"""Per-shard node: queues, pending books, Peer, RSM, snapshot glue.

Parity with the reference's ``node.go``: the node owns the per-shard
universe — ingress queues, pending-op books, the raft Peer, the managed
state machine and the snapshotter — and exposes ``step()``, the engine's
unit of work (stepNode/handleEvents → getUpdate → process, node.go:1139+).
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass, field

from dragonboat_tpu import raftpb as pb
from dragonboat_tpu.client import Session
from dragonboat_tpu.config import Config
from dragonboat_tpu.core.logentry import CompactedError
from dragonboat_tpu.core.peer import Peer
from dragonboat_tpu.core.pycore import CoreConfig, Raft
from dragonboat_tpu.logdb.logreader import LogReader
from dragonboat_tpu.raftio import ILogDB
from dragonboat_tpu.request import (
    PendingProposal,
    PendingReadIndex,
    PendingSingleton,
    RequestResultCode,
    RequestState,
)
from dragonboat_tpu.rsm.statemachine import StateMachine
from dragonboat_tpu.statemachine import Result


@dataclass
class _SnapshotRequest:
    exported: bool = False
    path: str = ""
    override_compaction: bool = False
    compaction_overhead: int = 0
    key: int = 0


class Node:
    def __init__(
        self,
        cfg: Config,
        logdb: ILogDB,
        sm: StateMachine,
        send_message,          # Callable[[pb.Message], None]
        snapshot_dir: str,
        rng=None,
    ) -> None:
        self.cfg = cfg
        self.shard_id = cfg.shard_id
        self.replica_id = cfg.replica_id
        self.logdb = logdb
        self.sm = sm
        self.send_message = send_message
        self.snapshot_dir = snapshot_dir
        self.mu = threading.RLock()
        self.log_reader = LogReader(cfg.shard_id, cfg.replica_id, logdb)

        self.pending_proposals = PendingProposal()
        self.pending_reads = PendingReadIndex()
        self.pending_config_change = PendingSingleton()
        self.pending_snapshot = PendingSingleton()
        self.pending_transfer = PendingSingleton()

        self.incoming_msgs: list[pb.Message] = []
        self.incoming_proposals: list[pb.Entry] = []
        self.transfer_target: int | None = None
        self.config_change_entry: pb.Entry | None = None
        self.snapshot_request: _SnapshotRequest | None = None

        self.peer: Peer | None = None
        self.stopped = False
        self.applied_since_snapshot = 0
        self.rng = rng
        self.initial_applied = 0

    # -- lifecycle ------------------------------------------------------

    def start(self, initial_members: dict[int, str], initial: bool,
              new_node: bool) -> None:
        """startRaft (node.go:365): build the Peer from persisted state."""
        ccfg = CoreConfig(
            shard_id=self.shard_id,
            replica_id=self.replica_id,
            election_rtt=self.cfg.election_rtt,
            heartbeat_rtt=self.cfg.heartbeat_rtt,
            check_quorum=self.cfg.check_quorum,
            pre_vote=self.cfg.pre_vote,
            is_non_voting=self.cfg.is_non_voting,
            is_witness=self.cfg.is_witness,
        )
        ss = self.logdb.get_snapshot(self.shard_id, self.replica_id)
        if ss is not None:
            self.log_reader.apply_snapshot(ss)
        rs = self.logdb.read_raft_state(
            self.shard_id, self.replica_id,
            ss.index if ss is not None else 0,
        )
        have_state = rs is not None and (
            not rs.state.is_empty() or rs.entry_count > 0 or ss is not None
        )
        if have_state:
            assert rs is not None
            if rs.entry_count > 0:
                self.log_reader.set_range(rs.first_index, rs.entry_count)
            p = Peer.launch(ccfg, self.log_reader, {}, False, False,
                            rng=self.rng)
            members = ss.membership if ss is not None else None
            if members is None or not (
                members.addresses or members.non_votings or members.witnesses
            ):
                members = pb.Membership(addresses=dict(initial_members))
            p.raft.set_initial_members(
                dict(members.addresses),
                dict(members.non_votings),
                dict(members.witnesses),
            )
            if not rs.state.is_empty():
                p.raft.load_state(rs.state)
            self.peer = p
            # replay committed-but-unapplied entries through the RSM
            if ss is not None:
                self.sm.members.set(ss.membership)
                self.sm.last_applied = max(self.sm.last_applied, ss.index)
                self.sm.last_applied_term = ss.term
        else:
            self.peer = Peer.launch(
                ccfg, self.log_reader, initial_members, initial, new_node,
                rng=self.rng,
            )
            if initial and new_node:
                self.sm.members.set(pb.Membership(
                    config_change_id=0, addresses=dict(initial_members)))
        applied = self.sm.get_last_applied()
        self.initial_applied = applied
        self.peer.notify_raft_last_applied(applied)
        if applied > 0:
            self.peer.raft.log.processed = max(
                self.peer.raft.log.processed, applied)

    def replay_committed(self) -> None:
        """Replay committed entries above the RSM's applied index
        (replayLog, node.go:666) — driven by the first engine steps."""
        pass  # the normal step loop replays via entries_to_apply

    def destroy(self) -> None:
        self.stopped = True
        for book in (self.pending_proposals, self.pending_reads,
                     self.pending_config_change, self.pending_snapshot,
                     self.pending_transfer):
            book.terminate_all()
        self.sm.close()

    # -- client entry points (called from NodeHost) ------------------------

    def propose(self, session: Session, cmd: bytes,
                timeout_ticks: int) -> RequestState:
        rs, entry = self.pending_proposals.propose(session, cmd, timeout_ticks)
        with self.mu:
            self.incoming_proposals.append(entry)
        return rs

    def propose_session_op(self, session: Session,
                           timeout_ticks: int) -> RequestState:
        rs, entry = self.pending_proposals.propose(session, b"", timeout_ticks)
        with self.mu:
            self.incoming_proposals.append(entry)
        return rs

    def read(self, timeout_ticks: int) -> RequestState:
        return self.pending_reads.read(timeout_ticks)

    def request_config_change(self, cc: pb.ConfigChange,
                              timeout_ticks: int) -> RequestState:
        rs, key = self.pending_config_change.request(timeout_ticks)
        entry = pb.Entry(
            type=pb.EntryType.CONFIG_CHANGE,
            key=key,
            cmd=pb.encode_config_change(cc),
        )
        with self.mu:
            self.config_change_entry = entry
        return rs

    def request_leader_transfer(self, target: int,
                                timeout_ticks: int) -> RequestState:
        rs, _key = self.pending_transfer.request(timeout_ticks)
        with self.mu:
            self.transfer_target = target
        return rs

    def request_snapshot(self, req: _SnapshotRequest | None,
                         timeout_ticks: int) -> RequestState:
        rs, key = self.pending_snapshot.request(timeout_ticks)
        r = req or _SnapshotRequest()
        r.key = key
        with self.mu:
            self.snapshot_request = r
        return rs

    def handle_message(self, m: pb.Message) -> None:
        with self.mu:
            self.incoming_msgs.append(m)

    def tick(self) -> None:
        with self.mu:
            self.incoming_msgs.append(
                pb.Message(type=pb.MessageType.LOCAL_TICK))
        for book in (self.pending_proposals, self.pending_reads,
                     self.pending_config_change, self.pending_snapshot,
                     self.pending_transfer):
            book.advance()
            book.gc()

    # -- the step (engine unit of work; node.go:1139 stepNode) -------------

    def step(self) -> bool:
        if self.stopped or self.peer is None:
            return False
        peer = self.peer
        with self.mu:
            msgs, self.incoming_msgs = self.incoming_msgs, []
            props, self.incoming_proposals = self.incoming_proposals, []
            cc_entry, self.config_change_entry = self.config_change_entry, None
            transfer, self.transfer_target = self.transfer_target, None
            ss_req, self.snapshot_request = self.snapshot_request, None

        # 1. read index batch (node.go:1296)
        ctx = self.pending_reads.peep()
        if ctx is not None:
            peer.read_index(ctx)
        # 2. received messages (incl. ticks)
        for m in msgs:
            if m.type == pb.MessageType.LOCAL_TICK:
                if self.cfg.quiesce:
                    peer.tick()  # quiesce manager integration later
                else:
                    peer.tick()
            elif m.type == pb.MessageType.INSTALL_SNAPSHOT:
                self._handle_install_snapshot(m)
            elif m.is_local():
                # locally-generated signals (Unreachable, SnapshotStatus, …)
                # bypass the external-message gate (node.go:1347-1400)
                peer.raft.handle(m)
            else:
                peer.handle(m)
        # 3. config change (node.go:1310)
        if cc_entry is not None:
            peer.propose_entries([cc_entry])
        # 4. proposals (node.go:1275)
        if props:
            peer.propose_entries(props)
        # 5. leader transfer
        if transfer is not None:
            peer.request_leader_transfer(transfer)
        # 6. snapshot request
        if ss_req is not None:
            self._take_snapshot(ss_req)

        if not peer.has_update(True):
            return False
        ud = peer.get_update(True, self.sm.get_last_applied())
        self._process_update(ud)
        peer.commit(ud)
        return True

    # -- update processing (engine.go:1304 processSteps order) -------------

    def _process_update(self, ud: pb.Update) -> None:
        # send replicate messages BEFORE the fsync (thesis §10.2.1,
        # engine.go:1332-1336)
        for m in ud.messages:
            if m.type == pb.MessageType.REPLICATE:
                self._send(m)
        # THE fsync
        self.logdb.save_raft_state([ud], worker_id=0)
        if ud.entries_to_save:
            self.log_reader.append(ud.entries_to_save)
        if not ud.snapshot.is_empty():
            self._apply_snapshot(ud.snapshot)
        # non-replicate messages after persistence
        for m in ud.messages:
            if m.type != pb.MessageType.REPLICATE:
                self._send(m)
        # dropped ops
        for e in ud.dropped_entries:
            self.pending_proposals.dropped(e.key)
        for sc in ud.dropped_read_indexes:
            self.pending_reads.dropped(sc)
        # ready-to-read contexts; fire immediately when the applied index
        # already covers the read index (request.go:930 applied())
        for rtr in ud.ready_to_reads:
            self.pending_reads.add_ready(rtr.system_ctx, rtr.index)
        if ud.ready_to_reads:
            self.pending_reads.applied(self.sm.get_last_applied())
        # apply committed entries to the RSM
        if ud.committed_entries:
            self._apply_entries(ud.committed_entries)
        # auto snapshot (node.go:694 saveSnapshotRequired)
        if (self.cfg.snapshot_entries > 0
                and self.applied_since_snapshot >= self.cfg.snapshot_entries):
            self._take_snapshot(_SnapshotRequest())

    def _send(self, m: pb.Message) -> None:
        if m.to == self.replica_id:
            self.handle_message(m)
            return
        self.send_message(m)

    def _apply_entries(self, entries) -> None:
        results = self.sm.handle(entries)
        for r in results:
            entry = next(e for e in entries if e.index == r.index)
            if entry.is_config_change():
                self._on_config_change_applied(entry, r)
            elif r.key:
                self.pending_proposals.applied(
                    r.key, r.client_id, r.series_id, r.result, r.rejected
                )
        self.applied_since_snapshot += len(results)
        applied = self.sm.get_last_applied()
        if self.peer is not None:
            self.peer.notify_raft_last_applied(applied)
        self.pending_reads.applied(applied)

    def _on_config_change_applied(self, entry: pb.Entry, r) -> None:
        cc = pb.decode_config_change(entry.cmd)
        assert self.peer is not None
        if not r.rejected:
            self.peer.apply_config_change(cc)
            self.membership_changed_cb(cc)
        else:
            self.peer.reject_config_change()
        code = (RequestResultCode.REJECTED if r.rejected
                else RequestResultCode.COMPLETED)
        self.pending_config_change.done(
            entry.key, code, Result(value=entry.index))

    def membership_changed_cb(self, cc: pb.ConfigChange) -> None:
        """Overridden by NodeHost to update the registry."""

    # -- snapshots -------------------------------------------------------

    def _snapshot_path(self, index: int) -> str:
        return os.path.join(
            self.snapshot_dir,
            f"snapshot-{self.shard_id:016X}-{self.replica_id:016X}-{index:016X}.gbsnap",
        )

    def _take_snapshot(self, req: _SnapshotRequest) -> None:
        """save/doSave (node.go:739-801) executed inline (the reference
        uses the snapshot worker pool; the loopback engine is synchronous)."""
        assert self.peer is not None
        index0 = self.sm.get_last_applied()
        if index0 == 0:
            if req.key:
                self.pending_snapshot.done(req.key, RequestResultCode.REJECTED)
            return
        path = req.path if req.exported else self._snapshot_path(index0)
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        index, term, membership = self.sm.save_snapshot(path)
        ss = pb.Snapshot(
            filepath=path,
            file_size=os.path.getsize(path),
            index=index,
            term=term,
            membership=membership,
            shard_id=self.shard_id,
            type=self.sm.sm_type,
            on_disk_index=(index if self.sm.sm_type == pb.StateMachineType.ON_DISK
                           else 0),
        )
        if not req.exported:
            self.logdb.save_snapshots([pb.Update(
                shard_id=self.shard_id, replica_id=self.replica_id, snapshot=ss
            )])
            # make the snapshot visible to makeInstallSnapshotMessage
            # (snapshotter.Commit → logReader.CreateSnapshot)
            self.log_reader.create_snapshot(ss)
            # compact the log, keeping compaction_overhead entries
            overhead = (req.compaction_overhead if req.override_compaction
                        else self.cfg.compaction_overhead)
            compact_to = max(0, index - overhead)
            if compact_to > 0 and not self.cfg.disable_auto_compaction:
                try:
                    self.log_reader.compact(compact_to)
                    self.logdb.remove_entries_to(
                        self.shard_id, self.replica_id, compact_to)
                except Exception:
                    pass
        self.applied_since_snapshot = 0
        if req.key:
            self.pending_snapshot.done(
                req.key, RequestResultCode.COMPLETED, snapshot_index=index)

    def _handle_install_snapshot(self, m: pb.Message) -> None:
        """Follower-side snapshot install: recover the RSM then feed the
        raft core (host slow path; engine.go:1382 applySnapshotAndUpdate)."""
        assert self.peer is not None
        ss = m.snapshot
        self.peer.raft.handle(m)  # raft-core restore (log + remotes)
        if self.peer.raft.log.inmem.snapshot is not None:
            # accepted: recover the user SM from the snapshot file
            self.sm.recover_from_snapshot(ss.filepath, ss)

    def _apply_snapshot(self, ss: pb.Snapshot) -> None:
        self.logdb.save_snapshots([pb.Update(
            shard_id=self.shard_id, replica_id=self.replica_id, snapshot=ss)])
        self.log_reader.apply_snapshot(ss)

    # -- info -----------------------------------------------------------

    def leader_id(self) -> int:
        return self.peer.raft.leader_id if self.peer else 0

    def is_leader(self) -> bool:
        return bool(self.peer and self.peer.raft.is_leader())
