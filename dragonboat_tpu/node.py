"""Per-shard node: queues, pending books, Peer, RSM, snapshot glue.

Parity with the reference's ``node.go``: the node owns the per-shard
universe — ingress queues, pending-op books, the raft Peer, the managed
state machine and the snapshotter — and exposes ``step()``, the engine's
unit of work (stepNode/handleEvents → getUpdate → process, node.go:1139+).
"""

from __future__ import annotations

import dataclasses
import os
import threading
from dataclasses import dataclass, field

from dragonboat_tpu import lifecycle
from dragonboat_tpu import raftpb as pb
from dragonboat_tpu.client import Session
from dragonboat_tpu.config import Config
from dragonboat_tpu.core.logentry import CompactedError
from dragonboat_tpu.core.peer import Peer
from dragonboat_tpu.core.pycore import CoreConfig, Raft
from dragonboat_tpu.events import EventHub
from dragonboat_tpu.logdb.logreader import LogReader
from dragonboat_tpu.logger import get_logger
from dragonboat_tpu.quiesce import QuiesceState
from dragonboat_tpu.rsm import encoded
from dragonboat_tpu.raftio import EntryInfo, ILogDB, LeaderInfo, SnapshotInfo
from dragonboat_tpu.request import (
    LogicalClock,
    PendingProposal,
    PendingReadIndex,
    PendingSingleton,
    RequestDroppedError,
    RequestResultCode,
    RequestState,
)
from dragonboat_tpu.rsm.statemachine import StateMachine
from dragonboat_tpu.server.rate import RateLimiter
from dragonboat_tpu.server.settings import soft
from dragonboat_tpu.statemachine import Result

_LOG = get_logger("node")


@dataclass
class _SnapshotRequest:
    exported: bool = False
    path: str = ""
    override_compaction: bool = False
    compaction_overhead: int = 0
    key: int = 0


class Node:
    def __init__(
        self,
        cfg: Config,
        logdb: ILogDB,
        sm: StateMachine,
        send_message,          # Callable[[pb.Message], None]
        snapshot_dir: str,
        rng=None,
        events: EventHub | None = None,
        fs=None,
        worker_id: int = 0,
        clock=None,
    ) -> None:
        from dragonboat_tpu.vfs import default_fs

        self.fs = fs if fs is not None else default_fs()
        self.cfg = cfg
        self.shard_id = cfg.shard_id
        self.replica_id = cfg.replica_id
        self.logdb = logdb
        # the step worker that owns this node (engine.go:1107 workerPool);
        # passed to save_raft_state per the single-writer-per-worker
        # contract (raftio/logdb.go:78-83)
        self.worker_id = worker_id
        self.sm = sm
        self.send_message = send_message
        self.snapshot_dir = snapshot_dir
        self.events = events or EventHub()
        self.mu = threading.RLock()
        self.log_reader = LogReader(cfg.shard_id, cfg.replica_id, logdb)

        # ONE logical clock for every book: the host ticker advances it
        # once per round (a per-book advance walk is O(lanes) Python at
        # 100k shards); a standalone Node keeps a private clock that
        # tick() advances itself
        self._clock = clock if clock is not None else LogicalClock()
        self._owns_clock = clock is None
        self.pending_proposals = PendingProposal(clock=self._clock,
                                                 shard_id=cfg.shard_id)
        self.pending_reads = PendingReadIndex(clock=self._clock,
                                              shard_id=cfg.shard_id)
        self.pending_config_change = PendingSingleton(clock=self._clock)
        self.pending_snapshot = PendingSingleton(clock=self._clock)
        self.pending_transfer = PendingSingleton(clock=self._clock)
        self.pending_log_query = PendingSingleton(clock=self._clock)

        self.incoming_msgs: list[pb.Message] = []
        self.incoming_proposals: list[pb.Entry] = []
        self.transfer_target: int | None = None
        self.config_change_entry: pb.Entry | None = None
        self.snapshot_request: _SnapshotRequest | None = None
        self.log_query_range: tuple[int, int, int] | None = None
        self.compaction_request_key: int | None = None
        self.pending_compaction = PendingSingleton(clock=self._clock)

        # quiesce bookkeeping (quiesce.go:24, node.go:195)
        self.qs = QuiesceState(
            shard_id=cfg.shard_id,
            replica_id=cfg.replica_id,
            election_tick=cfg.election_rtt,
            enabled=cfg.quiesce,
        )
        # leader-transfer completion (target, request key): the reference's
        # transfer is fire-and-forget (request.go:564); our future completes
        # on the LeaderUpdate that lands the target, timing out otherwise.
        # The key is captured at request time so a stale edge can never
        # complete a later, unrelated transfer request.
        self._transfer_awaiting: tuple[int, int] | None = None
        # last observed (leader, term): pycore emits a LeaderUpdate on every
        # follower heartbeat, so leader changes must be edge-detected here
        self._last_leader: tuple[int, int] = (0, 0)
        # requestCompaction seam (node.go:972 getCompactedTo)
        self.compacted_to = 0
        # in-memory log growth guard (server/rate.go + Config
        # MaxInMemLogSize): unapplied proposal bytes; over the limit ->
        # proposals rejected with system-busy until applies drain it.
        # Accounting is keyed by proposal key so only bytes that were
        # increased are ever decreased (drops and remote entries must not
        # erode other proposals' accounting)
        self.rate_limiter = RateLimiter(cfg.max_in_mem_log_size)
        self._rl_inflight: dict[int, int] = {}
        # NotifyCommit (nodehost.go:1656): fire committed_event on commit,
        # before apply — set by NodeHost from NodeHostConfig
        self.notify_commit = False
        # set by NodeHost for on-disk SMs: stream a live snapshot image
        # to the peer instead of sending the recorded file
        self.stream_snapshot_cb = None
        # set by NodeHost: dedicated RSM-apply workers
        # (engine/apply_pool.py; engine.go:1153 applyWorkerMain).  None ->
        # apply runs inline on the step path (standalone Node usage).
        self.apply_pool = None
        # core mutations produced by an async apply (config-change
        # application, applied-cursor notification): the raft core is
        # owned by the step thread, so the apply worker posts closures
        # here and the next step drains them (the channel the reference's
        # nodeProxy pattern expresses with configChangeC)
        self._core_notices: list = []

        self.peer: Peer | None = None
        self.stopped = False
        self.applied_since_snapshot = 0
        self.rng = rng
        self.initial_applied = 0

    # -- lifecycle ------------------------------------------------------

    def start(self, initial_members: dict[int, str], initial: bool,
              new_node: bool) -> None:
        """startRaft (node.go:365): build the Peer from persisted state."""
        ccfg = CoreConfig(
            shard_id=self.shard_id,
            replica_id=self.replica_id,
            election_rtt=self.cfg.election_rtt,
            heartbeat_rtt=self.cfg.heartbeat_rtt,
            check_quorum=self.cfg.check_quorum,
            pre_vote=self.cfg.pre_vote,
            is_non_voting=self.cfg.is_non_voting,
            is_witness=self.cfg.is_witness,
        )
        ss = self.logdb.get_snapshot(self.shard_id, self.replica_id)
        self._gc_snapshot_dir(ss)
        if ss is not None:
            self.log_reader.apply_snapshot(ss)
        rs = self.logdb.read_raft_state(
            self.shard_id, self.replica_id,
            ss.index if ss is not None else 0,
        )
        have_state = rs is not None and (
            not rs.state.is_empty() or rs.entry_count > 0 or ss is not None
        )
        if have_state:
            assert rs is not None
            if rs.entry_count > 0:
                self.log_reader.set_range(rs.first_index, rs.entry_count)
            p = Peer.launch(ccfg, self.log_reader, {}, False, False,
                            rng=self.rng)
            members = ss.membership if ss is not None else None
            if members is None or not (
                members.addresses or members.non_votings or members.witnesses
            ):
                # the RSM membership store is authoritative once CCs have
                # applied (snapshotter.go owns membership in the
                # reference): a LIVE SM — kernel/mesh eviction rebuilds a
                # Node around the running SM — carries the current
                # members, where a snapshot may not exist yet and
                # initial_members is empty on a restart
                m = self.sm.get_membership()
                if m.addresses or m.non_votings or m.witnesses:
                    members = m
                else:
                    members = pb.Membership(addresses=dict(initial_members))
            p.raft.set_initial_members(
                dict(members.addresses),
                dict(members.non_votings),
                dict(members.witnesses),
            )
            if not rs.state.is_empty():
                p.raft.load_state(rs.state)
            self.peer = p
            # recover the user SM from the latest snapshot, then the step
            # loop replays the committed tail (node.go:666 replayLog).
            # A missing snapshot file is FATAL: the log below ss.index was
            # compacted away, so skipping recovery would silently restart
            # the user SM empty while claiming applied==ss.index.
            # A LIVE SM already applied past the snapshot (kernel-engine
            # eviction rebuilds a Node around the running SM) — recovery
            # would regress it, so it is skipped.
            if ss is not None and self.sm.get_last_applied() < ss.index \
                    and (ss.witness or ss.dummy):
                # a witness/dummy record has no data file — restore the
                # RSM bookkeeping only (raft.go:728 makeWitnessSnapshot)
                self.sm.restore_bookkeeping(ss)
                self.compacted_to = max(
                    0, ss.index - self.cfg.compaction_overhead)
            elif ss is not None and self.sm.get_last_applied() < ss.index:
                if not ss.filepath or not os.path.exists(ss.filepath):
                    raise RuntimeError(
                        f"shard {self.shard_id} replica {self.replica_id}: "
                        f"snapshot file {ss.filepath!r} (index {ss.index}) "
                        f"is missing — cannot recover")
                self.sm.recover_from_snapshot(ss.filepath, ss)
                # crash window between install-recover and shrink: finish
                # the shrink now (node.go:871-877 — on-disk SM data is in
                # the SM's own storage once synced)
                if self.sm.sm_type == pb.StateMachineType.ON_DISK:
                    self.sm.sync()
                    self.sm.shrink_recorded_snapshot(ss.filepath)
                self.sm.members.set(ss.membership)
                self.sm.last_applied = max(self.sm.last_applied, ss.index)
                self.sm.last_applied_term = ss.term
                # re-seed the compaction cursor so RequestCompaction keeps
                # working across restarts (ss.getCompactedTo analog)
                self.compacted_to = max(
                    0, ss.index - self.cfg.compaction_overhead)
        else:
            self.peer = Peer.launch(
                ccfg, self.log_reader, initial_members, initial, new_node,
                rng=self.rng,
            )
            if initial and new_node:
                self.sm.members.set(pb.Membership(
                    config_change_id=0, addresses=dict(initial_members)))
        applied = self.sm.get_last_applied()
        self.initial_applied = applied
        self.peer.notify_raft_last_applied(applied)
        if applied > 0:
            self.peer.raft.log.processed = max(
                self.peer.raft.log.processed, applied)

    def replay_committed(self) -> None:
        """Replay committed entries above the RSM's applied index
        (replayLog, node.go:666) — driven by the first engine steps."""
        pass  # the normal step loop replays via entries_to_apply

    def destroy(self) -> None:
        self.stopped = True
        for book in (self.pending_proposals, self.pending_reads,
                     self.pending_config_change, self.pending_snapshot,
                     self.pending_transfer, self.pending_log_query,
                     self.pending_compaction):
            book.terminate_all()
        self.sm.close()

    # -- client entry points (called from NodeHost) ------------------------
    #
    # every ingress mutation goes through _post so an engine can redirect
    # a node's intake atomically (kernel-engine eviction swaps the serving
    # object mid-flight; see KernelNode._post)

    def _post(self, mutate) -> None:
        with self.mu:
            mutate(self)

    def _check_ingress(self) -> None:
        """System-busy gates before a proposal is accepted: the in-mem
        rate limiter (request.go canNewRequest + rate.go) and the bounded
        entry queue (queue.go:24 entryQueue capacity)."""
        if self.rate_limiter.rate_limited():
            raise RequestDroppedError("system busy: in-memory log limit")
        with self.mu:
            if len(self.incoming_proposals) >= \
                    soft.incoming_proposal_queue_length:
                raise RequestDroppedError("system busy: proposal queue full")

    def propose(self, session: Session, cmd: bytes,
                timeout_ticks: int) -> RequestState:
        self._check_ingress()
        rs, entry = self.pending_proposals.propose(session, cmd, timeout_ticks)
        if cmd and self.cfg.entry_compression != "no-compression":
            # EncodedEntry envelope at propose time (request.go:1094;
            # unwrapped at apply by rsm/encoded.get_payload on every
            # replica).  Deliberate difference: the reference wraps
            # non-empty payloads even with compression off (1-byte
            # header); here the default config keeps plain APPLICATION
            # entries — both directions of a mixed Go/TPU fleet handle
            # either type, and the uncompressed wire stays byte-stable
            # for existing deployments.
            entry = dataclasses.replace(
                entry, type=pb.EntryType.ENCODED,
                cmd=encoded.get_encoded(self.cfg.entry_compression, cmd))
        if self.rate_limiter.enabled():
            sz = pb.entry_size(entry)
            self.rate_limiter.increase(sz)
            with self.mu:
                self._rl_inflight[entry.key] = sz
        self._post(lambda n: n.incoming_proposals.append(entry))
        return rs

    def _rl_release(self, key: int) -> None:
        """Release a proposal's rate-limiter bytes exactly once (on apply
        OR on drop — whichever settles it)."""
        if not self.rate_limiter.enabled():
            return
        with self.mu:
            sz = self._rl_inflight.pop(key, None)
        if sz is not None:
            self.rate_limiter.decrease(sz)

    def propose_session_op(self, session: Session,
                           timeout_ticks: int) -> RequestState:
        self._check_ingress()
        rs, entry = self.pending_proposals.propose(session, b"", timeout_ticks)
        self._post(lambda n: n.incoming_proposals.append(entry))
        return rs

    def read(self, timeout_ticks: int) -> RequestState:
        with self.mu:
            if len(self.pending_reads.batching) >= \
                    soft.incoming_read_index_queue_length:
                raise RequestDroppedError("system busy: read queue full")
        return self.pending_reads.read(timeout_ticks)

    def request_config_change(self, cc: pb.ConfigChange,
                              timeout_ticks: int) -> RequestState:
        rs, key = self.pending_config_change.request(timeout_ticks)
        entry = pb.Entry(
            type=pb.EntryType.CONFIG_CHANGE,
            key=key,
            cmd=pb.encode_config_change(cc),
        )
        self._post(lambda n: setattr(n, "config_change_entry", entry))
        return rs

    def request_leader_transfer(self, target: int,
                                timeout_ticks: int) -> RequestState:
        rs, key = self.pending_transfer.request(timeout_ticks)

        def mutate(n):
            n.transfer_target = target
            n._transfer_awaiting = (target, key)

        self._post(mutate)
        return rs

    def query_raft_log(self, first: int, last: int, max_size: int,
                       timeout_ticks: int) -> RequestState:
        """QueryRaftLog through the engine path (node.go:517 → 1239
        handleLogQuery): the request rides the step loop; the result lands
        on the returned RequestState as ``log_query_result``."""
        rs, _key = self.pending_log_query.request(timeout_ticks)
        self._post(lambda n: setattr(n, "log_query_range",
                                     (first, last, max_size)))
        return rs

    def request_compaction(self, timeout_ticks: int) -> RequestState:
        """RequestCompaction (node.go:972): LogDB-level compaction up to
        the snapshotter's compacted-to index, on the engine thread."""
        rs, key = self.pending_compaction.request(timeout_ticks)
        self._post(lambda n: setattr(n, "compaction_request_key", key))
        return rs

    def request_snapshot(self, req: _SnapshotRequest | None,
                         timeout_ticks: int) -> RequestState:
        rs, key = self.pending_snapshot.request(timeout_ticks)
        r = req or _SnapshotRequest()
        r.key = key
        self._post(lambda n: setattr(n, "snapshot_request", r))
        return rs

    def handle_message(self, m: pb.Message) -> None:
        self._post(lambda n: n.incoming_msgs.append(m))

    def tick(self) -> None:
        with self.mu:
            self.incoming_msgs.append(
                pb.Message(type=pb.MessageType.LOCAL_TICK))
        # a host-owned clock is advanced once per round by the ticker;
        # a standalone node advances its private clock here
        if self._owns_clock:
            self._clock.advance()
        self.gc_books()

    def gc_books(self) -> None:
        """Fire request timeouts against the absolute clock (each gc is
        a no-op fast path when the book is empty — the host sweeps all
        lanes' books on an amortized cadence)."""
        for book in (self.pending_proposals, self.pending_reads,
                     self.pending_config_change, self.pending_snapshot,
                     self.pending_transfer, self.pending_log_query,
                     self.pending_compaction):
            book.gc()

    # -- the step (engine unit of work; node.go:1139 stepNode) -------------

    def step(self) -> bool:
        if self.stopped or self.peer is None:
            return False
        peer = self.peer
        with self.mu:
            notices, self._core_notices = self._core_notices, []
            msgs, self.incoming_msgs = self.incoming_msgs, []
            props, self.incoming_proposals = self.incoming_proposals, []
            cc_entry, self.config_change_entry = self.config_change_entry, None
            transfer, self.transfer_target = self.transfer_target, None
            ss_req, self.snapshot_request = self.snapshot_request, None
            lq, self.log_query_range = self.log_query_range, None
            compact_key, self.compaction_request_key = (
                self.compaction_request_key, None)

        # 0. core mutations posted by async applies (CC application,
        # applied-cursor advance) — the step thread owns the core
        for fn in notices:
            fn()
        # 1. read index batch (node.go:1296)
        ctx = self.pending_reads.peep()
        if ctx is not None:
            self.qs.record(pb.MessageType.READ_INDEX)
            peer.read_index(ctx)
        # 2. received messages (incl. ticks)
        for m in msgs:
            if m.type == pb.MessageType.LOCAL_TICK:
                # quiesce-aware tick (node.go:1562-1573): a quiesced shard
                # only advances the logical clock — no heartbeats/elections
                self.qs.tick()
                if self.qs.quiesced():
                    peer.quiesced_tick()
                else:
                    peer.tick()
            elif m.type == pb.MessageType.QUIESCE:
                self.qs.try_enter_quiesce()
            elif m.type == pb.MessageType.INSTALL_SNAPSHOT:
                self.qs.record(m.type)
                self._handle_install_snapshot(m)
            elif m.is_local():
                # locally-generated signals (Unreachable, SnapshotStatus, …)
                # bypass the external-message gate (node.go:1347-1400)
                peer.raft.handle(m)
            else:
                self.qs.record(m.type)
                peer.handle(m)
        # 3. config change (node.go:1310)
        if cc_entry is not None:
            self.qs.record(pb.MessageType.CONFIG_CHANGE_EVENT)
            peer.propose_entries([cc_entry])
        # 4. proposals (node.go:1275)
        if props:
            self.qs.record(pb.MessageType.PROPOSE)
            peer.propose_entries(props)
        # 5. leader transfer
        if transfer is not None:
            self.qs.record(pb.MessageType.LEADER_TRANSFER)
            self._start_leader_transfer(transfer)
        # 6. snapshot request — on the apply pool when one is wired:
        # save_snapshot takes the SM apply lock, and a wedged user SM
        # holding it must never block the step worker (the reference
        # takes snapshots on dedicated workers too, engine.go snapshot
        # workers); per-shard pool order also serializes it with applies
        if ss_req is not None:
            if self.apply_pool is not None:
                req = ss_req
                self.apply_pool.submit(
                    self.shard_id, lambda: self._take_snapshot(req))
            else:
                self._take_snapshot(ss_req)
        # 7. raft log query (node.go:1238 handleLogQuery)
        if lq is not None:
            peer.query_raft_log(*lq)
        # 8. LogDB compaction request (node.go:972 requestCompaction)
        if compact_key is not None:
            self._process_compaction(compact_key)
        # entering quiesce propagates to peers so the whole group goes
        # quiet together (node.go:1148 sendEnterQuiesceMessages)
        if self.qs.new_quiesce_state():
            self._send_enter_quiesce_messages()

        if not peer.has_update(True):
            return False
        ud = peer.get_update(True, self.sm.get_last_applied())
        self._process_update(ud)
        peer.commit(ud)
        return True

    # -- update processing (engine.go:1304 processSteps order) -------------

    def _process_update(self, ud: pb.Update) -> None:
        # leader change: listener event + transfer-future completion
        # (node.go:308 processLeaderUpdate)
        if ud.leader_update is not None:
            self._on_leader_update(ud.leader_update)
        # raft log query result (node.go:319 processLogQuery)
        lqr = ud.log_query_result
        if lqr.last_index > 0 or lqr.error != 0:
            self._on_log_query_result(lqr)
        # send replicate messages BEFORE the fsync (thesis §10.2.1,
        # engine.go:1332-1336)
        for m in ud.messages:
            if m.type == pb.MessageType.REPLICATE:
                self._send(m)
        # THE fsync
        self.logdb.save_raft_state([ud], worker_id=self.worker_id)
        if ud.entries_to_save:
            self.log_reader.append(ud.entries_to_save)
        if not ud.snapshot.is_empty():
            self._apply_snapshot(ud.snapshot)
        # non-replicate messages after persistence
        for m in ud.messages:
            if m.type != pb.MessageType.REPLICATE:
                self._send(m)
        # dropped ops
        for e in ud.dropped_entries:
            self._rl_release(e.key)
            self.pending_proposals.dropped(e.key)
        for sc in ud.dropped_read_indexes:
            self.pending_reads.dropped(sc)
        # NotifyCommit: complete committed_event at commit time, before
        # apply (node.go:1062 notifyCommittedEntries)
        if self.notify_commit:
            for e in ud.committed_entries:
                if e.key:
                    self.pending_proposals.committed(e.key)
        # ready-to-read contexts; fire immediately when the applied index
        # already covers the read index (request.go:930 applied())
        for rtr in ud.ready_to_reads:
            self.pending_reads.add_ready(rtr.system_ctx, rtr.index)
        if ud.ready_to_reads:
            self.pending_reads.applied(self.sm.get_last_applied())
        # apply committed entries to the RSM — handed to the apply pool
        # when one is wired so a slow user SM blocks only its own shard
        # (engine.go:1153-1204 apply workers), else inline
        if ud.committed_entries:
            trace_keys = ()
            if lifecycle.TRACER.enabled:
                trace_keys = tuple(
                    e.key for e in ud.committed_entries
                    if e.key and lifecycle.TRACER.sampled(e.key))
                for k in trace_keys:
                    lifecycle.TRACER.stamp(k, lifecycle.STAGE_APPLY_QUEUE)
            if self.apply_pool is not None:
                ents = ud.committed_entries
                self.apply_pool.submit(
                    self.shard_id,
                    lambda: self._apply_entries(ents, async_core=True),
                    trace_keys=trace_keys)
            else:
                for k in trace_keys:
                    lifecycle.TRACER.stamp(k, lifecycle.STAGE_APPLY)
                self._apply_entries(ud.committed_entries)
        # auto snapshot (node.go:694 saveSnapshotRequired); on the async
        # path the apply worker posts the request itself
        if (self.apply_pool is None and self.cfg.snapshot_entries > 0
                and self.applied_since_snapshot >= self.cfg.snapshot_entries):
            self._take_snapshot(_SnapshotRequest())

    def _send(self, m: pb.Message) -> None:
        if m.to == self.replica_id:
            self.handle_message(m)
            return
        # on-disk SMs stream a LIVE image to lagging peers instead of
        # shipping the recorded snapshot file (nodehost.go:1888-1891 →
        # rsm.ChunkWriter; wired by NodeHost._stream_snapshot)
        if (m.type == pb.MessageType.INSTALL_SNAPSHOT
                and self.stream_snapshot_cb is not None
                and self.sm.sm_type == pb.StateMachineType.ON_DISK):
            self.stream_snapshot_cb(self, m)
            return
        self.send_message(m)

    def _apply_entries(self, entries, async_core: bool = False) -> None:
        for e in entries:
            if e.key:
                self._rl_release(e.key)
        results = self.sm.handle(entries)
        for r in results:
            entry = next(e for e in entries if e.index == r.index)
            if entry.is_config_change():
                if async_core:
                    self._on_cc_applied_async(entry, r)
                else:
                    self._on_config_change_applied(entry, r)
            elif r.key:
                self.pending_proposals.applied(
                    r.key, r.client_id, r.series_id, r.result, r.rejected
                )
        with self.mu:
            # incremented here (apply worker) and reset by
            # _record_snapshot (possibly another thread) — racing the +=
            # against the reset would lose the reset and double-snapshot
            self.applied_since_snapshot += len(results)
        applied = self.sm.get_last_applied()
        if async_core:
            self._post_core_notice(
                lambda: self.peer is not None
                and self.peer.notify_raft_last_applied(applied))
        elif self.peer is not None:
            self.peer.notify_raft_last_applied(applied)
        self.pending_reads.applied(applied)
        if (async_core and self.cfg.snapshot_entries > 0
                and self.applied_since_snapshot >= self.cfg.snapshot_entries):
            with self.mu:
                if self.snapshot_request is None:
                    self.snapshot_request = _SnapshotRequest()

    def _post_core_notice(self, fn) -> None:
        with self.mu:
            self._core_notices.append(fn)

    def _on_cc_applied_async(self, entry: pb.Entry, r) -> None:
        """CC applied on an apply worker: the RSM membership store (under
        its own lock) is already updated; the raft-core notification is
        posted to the step thread, which owns the core."""
        cc = pb.decode_config_change(entry.cmd)

        def notice() -> None:
            if self.peer is None:
                return
            if not r.rejected:
                self.peer.apply_config_change(cc)
            else:
                self.peer.reject_config_change()

        self._post_core_notice(notice)
        if not r.rejected:
            self.membership_changed_cb(cc)
        code = (RequestResultCode.REJECTED if r.rejected
                else RequestResultCode.COMPLETED)
        self.pending_config_change.done(
            entry.key, code, Result(value=entry.index))

    def _on_config_change_applied(self, entry: pb.Entry, r) -> None:
        cc = pb.decode_config_change(entry.cmd)
        assert self.peer is not None
        if not r.rejected:
            self.peer.apply_config_change(cc)
            self.membership_changed_cb(cc)
        else:
            self.peer.reject_config_change()
        code = (RequestResultCode.REJECTED if r.rejected
                else RequestResultCode.COMPLETED)
        self.pending_config_change.done(
            entry.key, code, Result(value=entry.index))

    def membership_changed_cb(self, cc: pb.ConfigChange) -> None:
        """Overridden by NodeHost to update the registry."""

    # -- engine-path op completion ---------------------------------------

    def _start_leader_transfer(self, target: int) -> None:
        """Submit the transfer, completing the future immediately for the
        raft-core no-op cases (pycore handle_leader_transfer: target is
        already leader / unknown / a transfer already in flight) so the
        one-slot book is not locked out for the whole timeout."""
        assert self.peer is not None
        raft = self.peer.raft
        if target == raft.leader_id or (
                raft.is_leader() and target == self.replica_id):
            self._finish_transfer(RequestResultCode.COMPLETED, target)
            return
        if raft.is_leader() and (
                raft.leader_transfering() or target not in raft.remotes):
            self._finish_transfer(RequestResultCode.REJECTED)
            return
        self.peer.request_leader_transfer(target)

    def _finish_transfer(self, code: RequestResultCode,
                         target: int = 0) -> None:
        with self.mu:
            awaiting, self._transfer_awaiting = self._transfer_awaiting, None
        if awaiting is not None:
            self.pending_transfer.done(awaiting[1], code,
                                       Result(value=target))

    def _on_leader_update(self, lu: pb.LeaderUpdate) -> None:
        if (lu.leader_id, lu.term) == self._last_leader:
            return  # steady-state heartbeat echo, not a change
        self._last_leader = (lu.leader_id, lu.term)
        self.events.leader_updated(LeaderInfo(
            shard_id=self.shard_id, replica_id=self.replica_id,
            term=lu.term, leader_id=lu.leader_id,
        ))
        if lu.leader_id == 0:
            # step-down notification mid-transfer — the new leader is not
            # known yet; keep the future pending until it is
            return
        with self.mu:
            awaiting = self._transfer_awaiting
        if awaiting is None:
            return
        # only a leader edge landing the TARGET resolves the future; an
        # unrelated re-election mid-transfer leaves it pending (raft's
        # transfer may still land — the timeout is the failure signal,
        # matching the reference's fire-and-forget semantics)
        if lu.leader_id == awaiting[0]:
            self._finish_transfer(RequestResultCode.COMPLETED, awaiting[0])

    def _on_log_query_result(self, r: pb.LogQueryResult) -> None:
        rs = self.pending_log_query.outstanding
        if rs is not None:
            rs.log_query_result = r
        code = (RequestResultCode.COMPLETED if r.error == 0
                else RequestResultCode.REJECTED)
        self.pending_log_query.done(self.pending_log_query.key, code)

    def _process_compaction(self, key: int) -> None:
        compact_to = self.compacted_to
        if compact_to <= 0:
            self.pending_compaction.done(key, RequestResultCode.REJECTED)
            return
        self.logdb.remove_entries_to(self.shard_id, self.replica_id,
                                     compact_to)
        self.events.log_db_compacted(EntryInfo(
            shard_id=self.shard_id, replica_id=self.replica_id,
            index=compact_to))
        self.pending_compaction.done(key, RequestResultCode.COMPLETED,
                                     Result(value=compact_to))

    def _send_enter_quiesce_messages(self) -> None:
        """node.go:993: tell every peer the shard is going quiet."""
        for rid in self.sm.get_membership().addresses:
            if rid != self.replica_id:
                self._send(pb.Message(
                    type=pb.MessageType.QUIESCE,
                    from_=self.replica_id, to=rid, shard_id=self.shard_id,
                ))

    # -- snapshots -------------------------------------------------------

    def _gc_snapshot_dir(self, live: pb.Snapshot | None) -> None:
        """Startup orphan GC (snapshotter.go:200 processOrphans): remove
        half-written images (crash mid-save left a .generating temp) and
        committed-but-superseded snapshot files other than the recorded
        live one."""
        if not self.fs.exists(self.snapshot_dir):
            return
        live_name = (os.path.basename(live.filepath)
                     if live is not None and live.filepath else None)
        prefix = f"snapshot-{self.shard_id:016X}-{self.replica_id:016X}-"
        # installed snapshots land as incoming-* (transport/chunks.py)
        # and must be swept once superseded, like local ones
        in_prefix = f"incoming-{self.shard_id:016X}-{self.replica_id:016X}-"
        for fn in self.fs.listdir(self.snapshot_dir):
            full = os.path.join(self.snapshot_dir, fn)
            if not (fn.startswith(prefix) or fn.startswith(in_prefix)):
                continue  # another shard's files (shared non-env dir)
            if fn.endswith(".generating"):
                try:
                    self.fs.remove(full)
                    _LOG.info("removed orphan snapshot temp %s", fn)
                except OSError:
                    pass
            elif ".gbsnap.xf" in fn and (
                    live_name is None
                    or not fn.startswith(live_name + ".xf")):
                # external snapshot files (rsm/files.go) of superseded
                # snapshots
                try:
                    self.fs.remove(full)
                    _LOG.info("removed superseded snapshot file %s", fn)
                except OSError:
                    pass
            elif fn.endswith(".gbsnap") and fn != live_name:
                try:
                    self.fs.remove(full)
                    _LOG.info("removed superseded snapshot %s", fn)
                except OSError:
                    pass

    def _snapshot_path(self, index: int) -> str:
        return os.path.join(
            self.snapshot_dir,
            f"snapshot-{self.shard_id:016X}-{self.replica_id:016X}-{index:016X}.gbsnap",
        )

    def _take_snapshot(self, req: _SnapshotRequest) -> None:
        """save/doSave (node.go:739-801) executed inline (the reference
        uses the snapshot worker pool; the loopback engine is synchronous)."""
        assert self.peer is not None
        index0 = self.sm.get_last_applied()
        if index0 == 0:
            if req.key:
                self.pending_snapshot.done(req.key, RequestResultCode.REJECTED)
            return
        if self.cfg.is_witness and not req.exported:
            # a witness holds no data: record a file-less witness
            # snapshot (snapshotter.go witness record; raft.go:728) so
            # compaction keeps working without writing an empty image
            index, term, membership = self.sm.applied_meta()
            ss = pb.Snapshot(
                index=index, term=term, membership=membership,
                shard_id=self.shard_id, type=self.sm.sm_type, witness=True,
            )
            self._record_snapshot(ss, req)
            return
        path = req.path if req.exported else self._snapshot_path(index0)
        self.fs.makedirs(os.path.dirname(path) or ".")
        index, term, membership, files = \
            self.sm.save_snapshot_with_files(path)
        ss = pb.Snapshot(
            filepath=path,
            file_size=self.fs.getsize(path),
            index=index,
            term=term,
            membership=membership,
            shard_id=self.shard_id,
            type=self.sm.sm_type,
            files=files,
            on_disk_index=(index if self.sm.sm_type == pb.StateMachineType.ON_DISK
                           else 0),
        )
        if req.exported:
            from dragonboat_tpu.tools import write_export_metadata

            write_export_metadata(path, ss, fs=self.fs)
            with self.mu:
                self.applied_since_snapshot = 0
            if req.key:
                self.pending_snapshot.done(
                    req.key, RequestResultCode.COMPLETED,
                    snapshot_index=index)
        else:
            self._record_snapshot(ss, req)

    def _record_snapshot(self, ss: pb.Snapshot, req: _SnapshotRequest) -> None:
        """Persist the snapshot record + compact the log (node.go:781-801
        after doSave)."""
        index = ss.index
        self.logdb.save_snapshots([pb.Update(
            shard_id=self.shard_id, replica_id=self.replica_id, snapshot=ss
        )])
        # make the snapshot visible to makeInstallSnapshotMessage
        # (snapshotter.Commit → logReader.CreateSnapshot)
        self.log_reader.create_snapshot(ss)
        self.events.snapshot_created(SnapshotInfo(
            shard_id=self.shard_id, replica_id=self.replica_id,
            from_=self.replica_id, index=index, term=ss.term))
        # compact the log, keeping compaction_overhead entries
        overhead = (req.compaction_overhead if req.override_compaction
                    else self.cfg.compaction_overhead)
        compact_to = max(0, index - overhead)
        if compact_to > 0 and not self.cfg.disable_auto_compaction:
            try:
                self.log_reader.compact(compact_to)
                self.logdb.remove_entries_to(
                    self.shard_id, self.replica_id, compact_to)
                self.compacted_to = compact_to
                self.events.log_compacted(EntryInfo(
                    shard_id=self.shard_id, replica_id=self.replica_id,
                    index=compact_to))
            except Exception:
                _LOG.exception("log compaction failed")
        with self.mu:
            self.applied_since_snapshot = 0
        if req.key:
            self.pending_snapshot.done(
                req.key, RequestResultCode.COMPLETED, snapshot_index=index)

    def _handle_install_snapshot(self, m: pb.Message) -> None:
        """Follower-side snapshot install: recover the RSM then feed the
        raft core (host slow path; engine.go:1382 applySnapshotAndUpdate)."""
        assert self.peer is not None
        ss = m.snapshot
        self.peer.raft.handle(m)  # raft-core restore (log + remotes)
        if self.peer.raft.log.inmem.snapshot is not None:
            if ss.witness or ss.dummy:
                # witness snapshots carry no data file (raft.go:728
                # makeWitnessSnapshot): advance the RSM bookkeeping only
                self.sm.restore_bookkeeping(ss)
                self.events.snapshot_recovered(SnapshotInfo(
                    shard_id=self.shard_id, replica_id=self.replica_id,
                    from_=m.from_, index=ss.index, term=ss.term))
                return
            # accepted: recover the user SM from the snapshot file
            self.sm.recover_from_snapshot(ss.filepath, ss)
            # on-disk SM: once the recovered data is synced into the SM's
            # own storage the recorded file is redundant bytes — shrink
            # it to the empty-session container (node.go:871-877 Sync +
            # snapshotter.Shrink)
            if self.sm.sm_type == pb.StateMachineType.ON_DISK:
                self.sm.sync()
                self.sm.shrink_recorded_snapshot(ss.filepath)
            self.events.snapshot_recovered(SnapshotInfo(
                shard_id=self.shard_id, replica_id=self.replica_id,
                from_=m.from_, index=ss.index, term=ss.term))

    def _apply_snapshot(self, ss: pb.Snapshot) -> None:
        self.logdb.save_snapshots([pb.Update(
            shard_id=self.shard_id, replica_id=self.replica_id, snapshot=ss)])
        self.log_reader.apply_snapshot(ss)

    # -- info -----------------------------------------------------------

    def leader_id(self) -> int:
        return self.peer.raft.leader_id if self.peer else 0

    def node_term(self) -> int:
        return self.peer.raft.term if self.peer else self._last_leader[1]

    def is_leader(self) -> bool:
        return bool(self.peer and self.peer.raft.is_leader())
