"""Client-history recording + linearizability checking.

The reference's nightly chaos harness records client histories and checks
them with Jepsen Knossos / porcupine (docs/test.md; published runs at
github.com/lni/knossos-data).  This module is the equivalent seam:

- :class:`HistoryRecorder` — wraps client ops with invoke/complete
  timestamps; thread-safe; one record per operation attempt.  Timed-out
  ops stay OPEN (outcome unknown — they may have applied), which is
  exactly what a linearizability checker must assume.
- :meth:`HistoryRecorder.export_jsonl` — porcupine-style JSONL (one op
  per line: process, op, key, value, call, return, ok) for offline
  checking with external tools.
- :func:`check_linearizable_kv` — built-in Wing&Gong-style checker for
  per-key register histories (reads/writes), usable directly in chaos
  tests.  Exponential in the worst case — meant for test-sized
  histories (hundreds of ops per key).
"""

from __future__ import annotations

import json
import threading
import time
from dataclasses import dataclass


@dataclass
class Op:
    process: int
    op: str                  # "write" | "read"
    key: str
    value: object            # written value, or value observed by a read
    call: float              # invoke timestamp (monotonic)
    ret: float | None = None  # completion timestamp; None = open (unknown)
    ok: bool | None = None   # False = known-failed (never applied)
    idx: int = 0


class HistoryRecorder:
    """Thread-safe operation history (docs/test.md history recording)."""

    def __init__(self) -> None:
        self.mu = threading.Lock()
        self.ops: list[Op] = []

    def invoke(self, process: int, op: str, key: str, value=None) -> Op:
        rec = Op(process=process, op=op, key=key, value=value,
                 call=time.monotonic())
        with self.mu:
            rec.idx = len(self.ops)
            self.ops.append(rec)
        return rec

    def complete(self, rec: Op, value=None, ok: bool = True) -> None:
        rec.ret = time.monotonic()
        if rec.op == "read":
            rec.value = value
        rec.ok = ok

    def fail(self, rec: Op) -> None:
        """The op is KNOWN to have not applied (e.g. rejected)."""
        rec.ret = time.monotonic()
        rec.ok = False

    # a timed-out op is simply never completed: ret stays None and the
    # checker must consider both it-applied and it-never-applied

    def export_jsonl(self, path: str) -> None:
        with open(path, "w") as f:
            for o in self.ops:
                f.write(json.dumps({
                    "process": o.process, "op": o.op, "key": o.key,
                    "value": o.value, "call": o.call, "return": o.ret,
                    "ok": o.ok,
                }) + "\n")


def check_linearizable_kv(ops: list[Op], initial=None) -> bool:
    """Check a register history per key (writes + reads).

    Open ops (ret is None) may linearize at any point after their call —
    or never (their effect may or may not exist).  Known-failed ops are
    excluded.  Returns True iff every key's history is linearizable."""
    by_key: dict[str, list[Op]] = {}
    for o in ops:
        if o.ok is False:
            continue
        by_key.setdefault(o.key, []).append(o)
    return all(_check_register(v, initial) for v in by_key.values())


def _check_register(ops: list[Op], initial) -> bool:
    """Wing & Gong search with memoization over (done-set, value)."""
    n = len(ops)
    if n == 0:
        return True
    INF = float("inf")

    def precedes(a: Op, b: Op) -> bool:
        ra = a.ret if a.ret is not None else INF
        return ra < b.call

    ops = sorted(ops, key=lambda o: o.call)
    seen: set[tuple[frozenset, object]] = set()

    def memo_key(value):
        # values may be unhashable (dicts/lists from user SMs); the memo
        # key only needs equality-consistency, so canonicalize via repr
        try:
            hash(value)
            return value
        except TypeError:
            return repr(value)

    def minimal(done: frozenset) -> list[int]:
        """Ops not done whose every predecessor is done."""
        out = []
        for i, o in enumerate(ops):
            if i in done:
                continue
            if all((j in done) or not precedes(ops[j], o)
                   for j in range(n) if j != i):
                out.append(i)
        return out

    def choices(done: frozenset, value):
        """(next_done, next_value) successors from this state."""
        for i in minimal(done):
            o = ops[i]
            if o.op == "write":
                yield done | {i}, o.value
                if o.ret is None:
                    # an OPEN write may also never take effect
                    yield done | {i}, value
            else:  # read
                if o.ret is None or o.value == value:
                    yield done | {i}, value

    # iterative DFS (histories can be thousands of ops; recursion depth
    # would equal the op count)
    stack = [choices(frozenset(), initial)]
    if n == 0:
        return True
    seen.add((frozenset(), memo_key(initial)))
    while stack:
        it = stack[-1]
        advanced = False
        for done, value in it:
            if len(done) == n:
                return True
            key = (done, memo_key(value))
            if key in seen:
                continue
            seen.add(key)
            stack.append(choices(done, value))
            advanced = True
            break
        if not advanced:
            stack.pop()
    return False
