"""Self-driving device loop for benchmarking and the graft entry.

``full_step`` is the production-shaped training-step analog: one fused
cluster step (raft kernel + device message routing) plus the feedback the
host engine would provide — proposals enqueued on leaders, the RSM applied
cursor trailing the processed cursor, and the logical clock ticking.  It
runs entirely on device so ``lax.fori_loop`` can iterate it with zero host
dispatch, which is how the bench measures sustained writes/sec
(BASELINE config #2: shards × 3 replicas, 16B writes, vmapped step loop;
payloads live in the host mirror / device RSM value lanes, not in the raft
ring, mirroring the reference's in-memory KV benchmark shape).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from dragonboat_tpu.core import params as KP
from dragonboat_tpu.core.kernel import step
from dragonboat_tpu.core.kstate import (
    Inbox,
    ShardState,
    StepInput,
    empty_inbox,
    empty_input,
    init_state,
)
from dragonboat_tpu.core.router import route

I32 = jnp.int32


def bench_params(replicas: int = 3,
                 platform: str | None = None) -> KP.KernelParams:
    """Measured sweet spot (PERF.md): with the dispatch-by-type inbox
    (family-specialized handler bodies) the fixed scan cost is small
    enough that proposal/replication width 32 is the knee — 1.08M
    writes/s on one CPU core at 1024 groups with this exact config;
    width 48 regresses (bigger ring + conflict scans outweigh the batch
    gain).

    ``platform`` (default: the live backend) picks the ring-read
    lowering: one-hot selects on device (batched gathers serialize over
    [G] on TPU), dynamic indexing on CPU (the gather is a plain load
    there and one-hot costs ~3.5x)."""
    if platform is None:
        import jax

        platform = jax.default_backend()
    return KP.KernelParams(
        onehot_reads=(platform != "cpu"),
        num_peers=replicas,
        # 128 comfortably holds the uncompacted window (overhead 16 +
        # apply lag + the in-flight batch ≈ 96) and halves ring traffic
        # vs 256
        log_cap=128,
        inbox_cap=5 * (replicas - 1),
        msg_entries=32,
        proposal_cap=32,
        readindex_cap=4,
        apply_batch=64,
        # keep the compaction window + in-flight batch well under log_cap:
        # a large overhead pushes the ring-room gate into the proposal edge
        # and throttles accepted writes/step (measured: 64 -> 23.7/step,
        # 32 -> 23.7, 16 -> 28.0 at CAP=128; CAP=256 reaches 32/step but
        # the doubled ring traffic nets out slower)
        compaction_overhead=16,
    )


def make_cluster(kp: KP.KernelParams, num_groups: int, replicas: int = 3,
                 election: int = 10) -> ShardState:
    import numpy as np

    G = num_groups * replicas
    rids = np.tile(np.arange(1, replicas + 1, dtype=np.int32), num_groups)
    pids = np.arange(1, replicas + 1, dtype=np.int32)
    return init_state(kp, G, rids, pids, election_timeout=election)


def _self_input(kp: KP.KernelParams, state: ShardState, tick, propose,
                write_width: int | None, do_reads: bool, now) -> StepInput:
    """The self-driving feedback input: auto-propose on leaders (first
    ``write_width`` lanes, or all), optional one batched ReadIndex per
    leader, instant-apply RSM cursor, logical clock tick.  ONE builder so
    the instrumented and headline loops cannot drift apart."""
    G, B = state.term.shape[0], kp.proposal_cap
    is_leader = state.role == KP.LEADER
    pv = jnp.broadcast_to(is_leader[:, None], (G, B)) & jnp.asarray(
        propose, bool)
    if write_width is not None and write_width < B:
        pv = pv & (jnp.arange(B, dtype=jnp.int32) < write_width)[None, :]
    # inline payloads: lane j proposes value (last + 1 + j) — the entry's
    # own index, so any replica can verify lv[slot(i)] == i for committed i
    pval = (state.last[:, None] + 1 + jnp.arange(B, dtype=jnp.int32)[None, :])
    ri = (is_leader & jnp.asarray(do_reads, bool)
          & jnp.asarray(propose, bool))
    ctx = jnp.broadcast_to(jnp.asarray(now, jnp.int32) & 0x7FFFFFFF, (G,))
    return StepInput(
        prop_valid=pv,
        prop_cc=jnp.zeros((G, B), bool),
        ri_valid=ri,
        ri_low=ctx,
        ri_high=ctx,
        transfer_to=jnp.zeros((G,), jnp.int32),
        tick=jnp.broadcast_to(jnp.asarray(tick, bool), (G,)),
        quiesced=jnp.zeros((G,), bool),
        applied=state.processed,  # instant-apply RSM feedback
        prop_val=pval,
    )


def full_step(kp: KP.KernelParams, replicas: int, state: ShardState,
              box: Inbox, tick, propose):
    """One self-driving step: auto-propose on leaders, sync applied, tick.

    ``tick``/``propose`` are traced booleans so one compiled executable
    covers the elect, settle and load phases (compiles are minutes-scale
    on TPU; variants would triple that)."""
    inp = _self_input(kp, state, tick, propose, None, False, 0)
    state, out = step(kp, state, box, inp)
    nxt = route(kp, replicas, out)
    return state, nxt, out


@functools.partial(jax.jit, static_argnums=(0, 1))
def cc_step(kp: KP.KernelParams, replicas: int, state: ShardState,
            box: Inbox):
    """One step of the membership-change wave (BASELINE config #5): every
    leader proposes a config-change entry in lane 0 alongside its normal
    write batch.  The CC rides the ordinary append→replicate→commit
    pipeline (one-at-a-time gate enforced by the kernel); the bench's
    host loop plays the engine's role of releasing the gate after the
    apply (engine update_lane_membership clears pending_cc).  Returns
    (state, next_box, accepted_cc_mask, cc_index)."""
    inp = _self_input(kp, state, True, True, None, False, 0)
    inp = inp._replace(prop_cc=inp.prop_cc.at[:, 0].set(True))
    state, out = step(kp, state, box, inp)
    return (state, route(kp, replicas, out),
            out.prop_accepted[:, 0], out.prop_index[:, 0])


@functools.partial(jax.jit, static_argnums=(0, 1, 2))
def run_steps(kp: KP.KernelParams, replicas: int, iters: int,
              tick, propose, state: ShardState, box: Inbox):
    """iters self-driving steps under one jit — the bench inner loop."""
    tick = jnp.asarray(tick, bool)
    propose = jnp.asarray(propose, bool)

    def body(_, carry):
        st, bx = carry
        st, bx, _ = full_step(kp, replicas, st, bx, tick, propose)
        return st, bx

    return jax.lax.fori_loop(0, iters, body, (state, box))


# ---------------------------------------------------------------------------
# pipelined (double-pumped) loops — PipelineConfig depth 1's device shape.
#
# One PIPELINE step fuses two protocol micro-steps (step ∘ route, twice)
# under a single fori_loop body, so the host boundary — and the
# instrumentation clock `now` — advances once per fused pair.  Raft's
# propose → replicate → ack → commit chain spans 2 micro-steps; fused,
# it retires inside ONE pipeline step, which is exactly the "commit p50
# ≤ 1 tick" the roadmap targets.  Everything in the carry is i32/bool
# (threefry included), so fusing the pair is bitwise-neutral:
# run_steps_pipelined(n) must equal run_steps(2n) leaf-for-leaf — the
# depth-0 serial loop stays the differential oracle
# (tests/test_pipeline_differential.py).
# ---------------------------------------------------------------------------


@functools.partial(jax.jit, static_argnums=(0, 1, 2))
def run_steps_pipelined(kp: KP.KernelParams, replicas: int, iters: int,
                        tick, propose, state: ShardState, box: Inbox):
    """iters pipeline steps, each two fused self-driving micro-steps —
    bitwise ≡ ``run_steps(kp, replicas, 2 * iters, ...)``."""
    tick = jnp.asarray(tick, bool)
    propose = jnp.asarray(propose, bool)

    def body(_, carry):
        st, bx = carry
        st, bx, _ = full_step(kp, replicas, st, bx, tick, propose)
        st, bx, _ = full_step(kp, replicas, st, bx, tick, propose)
        return st, bx

    return jax.lax.fori_loop(0, iters, body, (state, box))


@functools.partial(jax.jit, static_argnums=(0, 1, 2))
def run_steps_storm_pipelined(kp: KP.KernelParams, replicas: int, iters: int,
                              drop_p, seed, state: ShardState, box: Inbox):
    """Pipelined election storm: the fold_in counter advances per
    MICRO-step (2i, 2i+1) so the Bernoulli drop masks replay the serial
    loop's RNG stream exactly — bitwise ≡ ``run_steps_storm(2 * iters)``."""
    key0 = jax.random.PRNGKey(seed)
    drop_p = jnp.asarray(drop_p, jnp.float32)

    def body(i, carry):
        st, bx = carry
        st, bx, _ = full_step(kp, replicas, st, bx, True, False)
        bx = _drop_box(bx, jax.random.fold_in(key0, 2 * i), drop_p)
        st, bx, _ = full_step(kp, replicas, st, bx, True, False)
        bx = _drop_box(bx, jax.random.fold_in(key0, 2 * i + 1), drop_p)
        return st, bx

    return jax.lax.fori_loop(0, iters, body, (state, box))


@functools.partial(jax.jit, static_argnums=(0, 1, 2, 3))
def run_steps_mixed_pipelined(kp: KP.KernelParams, replicas: int, iters: int,
                              write_width: int, now0, state: ShardState,
                              box: Inbox, reads):
    """Pipelined 9:1 mix: the ReadIndex ctx clock advances per micro-step
    (now0 + 2i, now0 + 2i + 1) — bitwise ≡ ``run_steps_mixed(2 * iters)``."""

    def body(i, carry):
        st, bx, rd = carry
        for j in (0, 1):
            inp = _self_input(kp, st, True, True, write_width, True,
                              now0 + 2 * i + j)
            st, out = step(kp, st, bx, inp)
            bx = route(kp, replicas, out)
            rd = rd + out.rtr_valid.sum(dtype=jnp.int32)
        return st, bx, rd

    return jax.lax.fori_loop(0, iters, body, (state, box, reads))


@functools.partial(jax.jit, static_argnums=(0, 1, 2, 3))
def run_steps_mixed(kp: KP.KernelParams, replicas: int, iters: int,
                    write_width: int, now0, state: ShardState, box: Inbox,
                    reads):
    """The mixed read/write loop WITHOUT latency instrumentation: writes
    narrowed to ``write_width`` lanes, one batched ReadIndex ctx per
    leader per step, and the only extra carry is the completed-ctx
    counter (an [RI]-bool sum — nothing like the stamp ring's one-hot
    writes).  Exists because measuring the 9:1 mix on the instrumented
    loop conflated ReadIndex cost with latency-capture cost (~2x).
    Deliberately a separate loop rather than an ``instrument`` flag on
    ``run_steps_lat``: the [G, log_cap] stamp ring would still ride the
    fori_loop carry, and whether XLA fully elides an untouched carry is
    exactly the kind of backend detail a benchmark must not bet on."""

    def body(i, carry):
        st, bx, rd = carry
        inp = _self_input(kp, st, True, True, write_width, True, now0 + i)
        st, out = step(kp, st, bx, inp)
        bx = route(kp, replicas, out)
        rd = rd + out.rtr_valid.sum(dtype=jnp.int32)
        return st, bx, rd

    return jax.lax.fori_loop(0, iters, body, (state, box, reads))


# ---------------------------------------------------------------------------
# device-SM pipeline: the full propose -> replicate -> commit -> APPLY loop
# with the rsm-apply kernel (rsm/device_kv.py) fused into the step
# ---------------------------------------------------------------------------


def sm_params(replicas: int = 3) -> KP.KernelParams:
    """bench_params with the inline-payload lanes enabled (the lv ring +
    ent_val routing the device-SM data path rides)."""
    import dataclasses

    return dataclasses.replace(bench_params(replicas), inline_payloads=True)


def make_device_sm(num_groups: int, replicas: int = 3,
                   table_cap: int = 1024, use_pallas: bool = False):
    """(DeviceKV, kv_state) sized for the bench cluster.  Direct-mapped:
    the range apply writes key = index mod table_cap, so every slot is
    that key's private home and no write can ever be rejected."""
    from dragonboat_tpu.rsm.device_kv import DeviceKV

    G = num_groups * replicas
    kv = DeviceKV(table_cap=table_cap, hash_keys=False,
                  use_pallas=use_pallas)
    return kv, kv.init_state(G)


def full_step_sm(kp: KP.KernelParams, replicas: int, kv, state: ShardState,
                 box: Inbox, kv_state, tick, propose):
    """``full_step`` plus the device RSM: payloads ride the lv ring (the
    inline payload slot — proposals stamp it, replicate messages carry
    it, so FOLLOWERS hold real values too), and the apply window the
    kernel releases is applied to the DeviceKV by the fused rsm-apply
    kernel on every replica.  This is the north star's full data path —
    the reference benches apply to an in-memory KV on the host
    (kvtest.go); here the apply itself is device work."""
    assert kp.inline_payloads, "device-SM path needs sm_params()"
    CAP, AB = kp.log_cap, kp.apply_batch
    state, box2, out = full_step(kp, replicas, state, box, tick, propose)
    # apply the released window through the rsm-apply kernel, reading
    # payloads from the replicated lv ring (valid on leaders AND followers)
    idx = out.apply_first[:, None] + jnp.arange(AB, dtype=jnp.int32)[None, :]
    valid = idx <= out.apply_last[:, None]                   # [G, AB]
    vals = jnp.take_along_axis(state.lv, idx & (CAP - 1), axis=1)
    if kv.use_pallas:
        # fused pallas apply: the table block stays in VMEM across the
        # window (bit-identical to both XLA forms —
        # tests/test_device_kv_pallas.py)
        from dragonboat_tpu.rsm.device_kv_pallas import apply_kernel_pallas

        key_space = (kv.table_cap // 2 if kv.hash_keys else kv.table_cap)
        keys = idx & (key_space - 1)
        cmds = jnp.stack([keys, vals], axis=-1)              # [G, AB, 2]
        kv_state, (_results, ok) = apply_kernel_pallas(
            kv, kv_state, cmds, valid)
    elif not kv.hash_keys:
        # raft applies a CONTIGUOUS window: one-pass range apply, no
        # serial B-iteration scan (keys = index mod table_cap)
        first_key = out.apply_first & (kv.table_cap - 1)
        kv_state, (_results, ok) = kv.apply_kernel_range(
            kv_state, first_key, vals, valid)
    else:
        # hashed tables: probing scan; half the table as key space keeps
        # load <= 0.5 so probe windows don't fill and reject
        keys = idx & (kv.table_cap // 2 - 1)
        cmds = jnp.stack([keys, vals], axis=-1)              # [G, AB, 2]
        kv_state, (_results, ok) = kv.apply_kernel(kv_state, cmds, valid)
    # a rejected committed write must be surfaced, not swallowed —
    # the bench reports the count
    n_rejected = jnp.sum(~ok & valid)
    return state, box2, kv_state, n_rejected, out


@functools.partial(jax.jit, static_argnums=(0, 1, 2, 3, 4))
def run_steps_mixed_sm(kp: KP.KernelParams, replicas: int, kv, iters: int,
                       write_width: int, now0, state: ShardState, box: Inbox,
                       kv_state, reads, acc, rejects):
    """The 9:1 mix with reads SERVED, not just permitted: the device-SM
    write pipeline (lv ring -> range apply) plus one batched ReadIndex
    ctx per leader per step, and for every confirmed ctx a window of
    ``9 * write_width`` lookups below the ctx index is executed against
    the device-resident table.  ``reads`` counts served CTXs — multiply
    by RB host-side: an on-device running sum of lookups would overflow
    int32 within one window at 100k groups.  ``acc`` folds the read
    VALUES into the carry so the lookups are live computation XLA cannot
    elide; ``rejects`` accumulates across calls like the other carries.  The read pass is slot-scan shaped ([G, T] compare/select —
    each table slot tests whether it falls in the served window) rather
    than a batched gather, for the same reason as kernel._get1.  Works
    on BOTH table kinds: direct-mapped slots test their own position;
    hashed slots test their STORED key (open addressing keeps keys
    unique per table, so a served key hits at most one slot).  The
    bench default stays direct-mapped because raft applies a contiguous
    index window — the range apply exploits exactly that; the hashed
    probing apply would measure the hash scheme, not the mix
    (equivalence across kinds: tests/test_bench_modes.py)."""
    assert kp.inline_payloads, "device-SM path needs sm_params()"
    T = kv.table_cap
    KS = T // 2 if kv.hash_keys else T      # key space (device_kv.py)
    CAP, AB = kp.log_cap, kp.apply_batch
    RB = 9 * write_width

    def body(i, carry):
        st, bx, ks, rd, ac, rej = carry
        inp = _self_input(kp, st, True, True, write_width, True, now0 + i)
        st, out = step(kp, st, bx, inp)
        bx = route(kp, replicas, out)
        # write side: released window -> device table (range apply, as
        # full_step_sm; the take_along_axis window read is shared with
        # that path and rides its device A/B)
        idx = out.apply_first[:, None] + jnp.arange(AB, dtype=I32)[None, :]
        valid = idx <= out.apply_last[:, None]
        vals = jnp.take_along_axis(st.lv, idx & (CAP - 1), axis=1)
        if kv.hash_keys:
            keys = idx & (KS - 1)
            cmds = jnp.stack([keys, vals], axis=-1)
            ks, (_res, ok) = kv.apply_kernel(ks, cmds, valid)
        else:
            first_key = out.apply_first & (T - 1)
            ks, (_res, ok) = kv.apply_kernel_range(ks, first_key, vals,
                                                   valid)
        rej = rej + jnp.sum(~ok & valid)
        # read side: serve the newest confirmed ctx per lane — RB keys
        # directly below the ctx index, read slot-scan style.  ReadIndex
        # semantics: a ctx is servable only once the SM has applied past
        # its index (node.py gates real reads the same way); an
        # unservable ctx is dropped from the count, never served stale
        rix = jnp.max(jnp.where(out.rtr_valid, out.rtr_index, 0), axis=1)
        served = jnp.any(out.rtr_valid, axis=1) & (rix <= st.processed)
        if kv.hash_keys:
            # stored key (keys-1; 0 = empty sentinel) tested against the
            # served key window, modulo the key space
            d = (rix[:, None] - 1 - (ks["keys"] - 1)) & (KS - 1)
            hit = (d < RB) & (ks["keys"] > 0) & served[:, None]
        else:
            d = ((rix[:, None] - 1
                  - jnp.arange(T, dtype=I32)[None, :]) & (T - 1))
            hit = (d < RB) & served[:, None]
        ac = ac + jnp.sum(jnp.where(hit, ks["vals"], 0))
        rd = rd + jnp.sum(served.astype(I32))
        return st, bx, ks, rd, ac, rej

    return jax.lax.fori_loop(
        0, iters, body, (state, box, kv_state, reads, acc, rejects))


@functools.partial(jax.jit, static_argnums=(0, 1, 2, 3))
def run_steps_sm(kp: KP.KernelParams, replicas: int, kv, iters: int,
                 tick, propose, state, box, kv_state):
    """iters device-SM pipeline steps under one jit (module-level: the
    executable caches across calls — kp/kv are hashable statics)."""
    tick = jnp.asarray(tick, bool)
    propose = jnp.asarray(propose, bool)

    def body(_, carry):
        st, bx, ks, rej = carry
        st, bx, ks, r, _ = full_step_sm(kp, replicas, kv, st, bx, ks,
                                        tick, propose)
        return st, bx, ks, rej + r

    return jax.lax.fori_loop(
        0, iters, body,
        (state, box, kv_state, jnp.asarray(0, jnp.int32)))


# ---------------------------------------------------------------------------
# commit-latency capture + 9:1 ReadIndex mix (BASELINE configs #2/#3 detail:
# the reference's latency tables README.md:53-64 and the 11M ops/s mixed
# number README.md:47)
# ---------------------------------------------------------------------------

LAT_BUCKETS = 64  # steps-to-release, 1-step buckets, last bucket saturates


def lat_init(kp: KP.KernelParams, G: int):
    """(stamp ring, histogram, completed-read-ctx counter)."""
    return (jnp.zeros((G, kp.log_cap), jnp.int32),
            jnp.zeros((LAT_BUCKETS,), jnp.int32),
            jnp.asarray(0, jnp.int32))


def _stamp_accepts(kp: KP.KernelParams, stamp, out, now):
    """Record the step at which each accepted proposal entered the log.
    One-hot select over the ring — NO dynamic scatters (the v5e
    miscompile class PERF.md documents)."""
    CAP = kp.log_cap
    idx = out.prop_index & (CAP - 1)                      # [G, B]
    iota = jnp.arange(CAP, dtype=jnp.int32)
    hit = ((iota[None, None, :] == idx[:, :, None])
           & out.prop_accepted[:, :, None]).any(axis=1)   # [G, CAP]
    return jnp.where(hit, now, stamp)


def _bucket_releases(kp: KP.KernelParams, stamp, hist, out, now, is_leader):
    """Histogram (now - stamp) for every entry released to the RSM on
    LEADER rows this step — the client-visible commit+apply latency in
    steps (only leader rows carry proposal stamps; follower releases of
    the same entries would read unstamped slots)."""
    CAP, AB = kp.log_cap, kp.apply_batch
    idx = out.apply_first[:, None] + jnp.arange(AB, dtype=jnp.int32)[None, :]
    valid = ((idx <= out.apply_last[:, None])
             & is_leader[:, None])                        # [G, AB]
    st = jnp.take_along_axis(stamp, idx & (CAP - 1), axis=1)
    lat = jnp.clip(now - st, 0, LAT_BUCKETS - 1)
    oh = ((lat[:, :, None] == jnp.arange(LAT_BUCKETS, dtype=jnp.int32))
          & valid[:, :, None])
    return hist + oh.sum(axis=(0, 1), dtype=jnp.int32)


def full_step_lat(kp: KP.KernelParams, replicas: int, write_width: int,
                  do_reads: bool, state: ShardState, box: Inbox,
                  tick, propose, now, stamp, hist, reads):
    """``full_step`` plus latency stamping and (optionally) a batched
    ReadIndex per leader per step — the quorum round that serves a batch
    of linearizable reads (raft.go ReadIndex; one ctx covers every read
    queued behind it, which is how the reference reaches its 9:1 mixed
    number)."""
    is_leader = state.role == KP.LEADER
    inp = _self_input(kp, state, tick, propose, write_width, do_reads, now)
    state, out = step(kp, state, box, inp)
    nxt = route(kp, replicas, out)
    stamp = _stamp_accepts(kp, stamp, out, now)
    hist = _bucket_releases(kp, stamp, hist, out, now, is_leader)
    reads = reads + out.rtr_valid.sum(dtype=jnp.int32)
    return state, nxt, stamp, hist, reads


@functools.partial(jax.jit, static_argnums=(0, 1, 2, 3, 4))
def run_steps_lat(kp: KP.KernelParams, replicas: int, iters: int,
                  write_width: int, do_reads: bool, tick, propose,
                  now0, state, box, stamp, hist, reads):
    """iters instrumented steps under one jit; carries the latency ring,
    histogram and read counter."""
    tick = jnp.asarray(tick, bool)
    propose = jnp.asarray(propose, bool)

    def body(i, carry):
        st, bx, sp, hi, rd = carry
        st, bx, sp, hi, rd = full_step_lat(
            kp, replicas, write_width, do_reads, st, bx,
            tick, propose, now0 + i, sp, hi, rd)
        return st, bx, sp, hi, rd

    return jax.lax.fori_loop(0, iters, body,
                             (state, box, stamp, hist, reads))


@functools.partial(jax.jit, static_argnums=(0, 1, 2, 3, 4))
def run_steps_lat_pipelined(kp: KP.KernelParams, replicas: int, iters: int,
                            write_width: int, do_reads: bool, tick, propose,
                            now0, state, box, stamp, hist, reads):
    """Instrumented pipelined loop: both fused micro-steps stamp and
    bucket against the SAME pipeline-step clock ``now0 + i`` — the
    histogram therefore measures commit latency in PIPELINE steps, the
    unit a client of the overlapped loop actually waits in.  (Deliberately
    NOT bitwise-comparable to ``run_steps_lat``: the stamp ring differs by
    construction.  The uninstrumented pipelined loops are the bitwise
    oracles.)"""
    tick = jnp.asarray(tick, bool)
    propose = jnp.asarray(propose, bool)

    def body(i, carry):
        st, bx, sp, hi, rd = carry
        for _ in (0, 1):
            st, bx, sp, hi, rd = full_step_lat(
                kp, replicas, write_width, do_reads, st, bx,
                tick, propose, now0 + i, sp, hi, rd)
        return st, bx, sp, hi, rd

    return jax.lax.fori_loop(0, iters, body,
                             (state, box, stamp, hist, reads))


# ---------------------------------------------------------------------------
# election storm (BASELINE config #4): randomized message drops + pre-vote
# across many shards, then measure recovery to single-leader everywhere
# ---------------------------------------------------------------------------


def _drop_box(box: Inbox, key, p):
    """Randomly drop routed messages: dropped slots are ALL-ZERO (the
    kernel's inbox contract — see tests/test_mesh_differential.py)."""
    keep = ~jax.random.bernoulli(key, p, box.mtype.shape)   # [G, K]

    def z(x):
        if x is None:
            return None
        k = keep if x.ndim == keep.ndim else keep[..., None]
        return jnp.where(k, x, jnp.zeros_like(x))

    return type(box)(*[z(f) for f in box])


@functools.partial(jax.jit, static_argnums=(0, 1, 2))
def run_steps_storm(kp: KP.KernelParams, replicas: int, iters: int,
                    drop_p, seed, state: ShardState, box: Inbox):
    """iters ticking steps with Bernoulli(drop_p) message loss — the
    randomized-drop election storm (pre-vote keeps terms from exploding,
    raft.go:2059 pre-vote rationale)."""
    key0 = jax.random.PRNGKey(seed)
    drop_p = jnp.asarray(drop_p, jnp.float32)

    def body(i, carry):
        st, bx = carry
        st, bx, _ = full_step(kp, replicas, st, bx, True, False)
        bx = _drop_box(bx, jax.random.fold_in(key0, i), drop_p)
        return st, bx

    return jax.lax.fori_loop(0, iters, body, (state, box))


def elect_all(kp: KP.KernelParams, replicas: int, state: ShardState,
              max_rounds: int = 40):
    """Tick (no proposals) until every group has a leader."""
    import numpy as np

    box = empty_inbox(kp, state.term.shape[0])
    for _ in range(max_rounds):
        state, box = run_steps(kp, replicas, 10, True, False, state, box)
        role = np.asarray(state.role).reshape(-1, replicas)
        if (role == KP.LEADER).any(axis=1).all():
            # settle in-flight traffic
            state, box = run_steps(kp, replicas, 6, False, False, state, box)
            return state, box
    raise RuntimeError("election did not converge")
