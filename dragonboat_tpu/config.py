"""Config / NodeHostConfig — parity with the reference's config package
(``config/config.go:58-198`` per-shard Config, ``:300+`` NodeHostConfig,
``Expert`` engine knobs ``:887-899``)."""

from __future__ import annotations

from dataclasses import dataclass, field


class ConfigError(ValueError):
    pass


@dataclass
class Config:
    """Per-shard raft configuration (config/config.go:58-198)."""

    replica_id: int = 0
    shard_id: int = 0
    check_quorum: bool = False
    pre_vote: bool = False
    election_rtt: int = 10
    heartbeat_rtt: int = 1
    snapshot_entries: int = 0        # 0 disables auto snapshots
    compaction_overhead: int = 0
    ordered_config_change: bool = False
    max_in_mem_log_size: int = 0     # 0 = unlimited
    is_non_voting: bool = False
    is_witness: bool = False
    quiesce: bool = False
    wait_ready: bool = False
    disable_auto_compaction: bool = False
    # compression envelope for snapshot files (config.CompressionType
    # Snappy analog; V3 per-block zlib in rsm/snapshotio.py)
    snapshot_compression: bool = False
    # per-shard proposal-payload compression (config.go:161
    # EntryCompressionType): "no-compression" (default), "snappy"
    # (go-wire interoperable — the reference's dio snappy block), or
    # "zlib" (repo extension: C-fast, NOT understood by Go fleets).
    # Applied at propose time (EncodedEntry envelope, rsm/encoded.py),
    # unwrapped at apply on every replica.
    entry_compression: str = "no-compression"
    # TPU-native surface: run this shard as a lane of the host's batched
    # device kernel instead of a host-Python Peer (engine/kernel_engine.py)
    device_resident: bool = False
    # run this shard's replica as a row of the process-wide multi-chip
    # mesh engine (ExpertConfig.mesh places it; engine/mesh_engine.py) —
    # replicas live on different devices and exchange messages over ICI
    mesh_resident: bool = False

    def validate(self) -> None:
        if self.replica_id == 0:
            raise ConfigError("invalid ReplicaID")
        if self.shard_id == 0:
            raise ConfigError("invalid ShardID")
        if self.heartbeat_rtt == 0:
            raise ConfigError("HeartbeatRTT must be > 0")
        if self.election_rtt == 0 or self.election_rtt <= 2 * self.heartbeat_rtt:
            raise ConfigError(
                "ElectionRTT must be > 2 * HeartbeatRTT"
            )
        if self.is_witness and self.snapshot_entries > 0:
            raise ConfigError("witness can not take snapshots")
        if self.is_witness and self.is_non_voting:
            raise ConfigError("witness can not be a non-voting member")
        if self.max_in_mem_log_size != 0 and self.max_in_mem_log_size < 256:
            raise ConfigError("MaxInMemLogSize must be >= 256")
        from dragonboat_tpu.rsm.encoded import COMPRESSION_TYPES

        if self.entry_compression not in COMPRESSION_TYPES:
            raise ConfigError(
                f"unknown EntryCompressionType {self.entry_compression!r}"
            )
        if self.is_witness and self.entry_compression != "no-compression":
            raise ConfigError("witness does not carry proposal payloads")


@dataclass
class EngineConfig:
    """Expert engine geometry (config/config.go:887-899).  The TPU engine
    maps ExecShards onto kernel batch slots rather than goroutine pools."""

    exec_shards: int = 16
    commit_shards: int = 16
    apply_shards: int = 16
    snapshot_shards: int = 48
    close_shards: int = 32


@dataclass
class LogDBConfig:
    """Expert log-engine geometry (config/config.go:780,845): the durable
    log is split into ``shards`` single-writer partitions so concurrent
    step workers flush different files (internal/logdb/sharded.go:34).

    ``engine`` picks the per-partition storage engine — ``"tan"`` (the
    purpose-built log-file engine, the default) or ``"kv"`` (the
    sorted-KV LSM engine, the analog of the reference's Pebble logdb);
    the choice is pinned into the on-disk layout on first open.

    ``recovery_mode`` governs what a tan partition does with a bad
    checksum in a NON-tail log file on open: ``"strict"`` refuses to
    open (historical behavior), ``"quarantine"`` truncates at the
    corruption, clamps the persisted commit to the entries still
    contiguously present, and lets raft re-replicate the rest from the
    quorum (snapshot fallback when the entries were compacted away)."""

    shards: int = 16
    engine: str = "tan"
    recovery_mode: str = "strict"


@dataclass(frozen=True)
class MeshSpec:
    """Placement of device-resident shards onto a multi-chip mesh.

    NodeHosts (one per replica slot in the common deployment) that share
    a ``name`` attach to one process-wide MeshEngine whose state spans a
    ``Mesh(('g','r'))`` of ``g_size * replicas`` devices; intra-group
    raft traffic rides ICI collectives instead of the host transport
    (the reference's multi-NodeHost TCP topology, transport.go:86-101,
    collapsed into the jitted step).  Mesh-resident shards must use
    replica ids 1..replicas (the device router's fixed addressing);
    anything else falls back / evicts to the host engine.
    """

    name: str = "default"
    g_size: int = 1          # mesh axis 'g' (disjoint group sets)
    replicas: int = 3        # mesh axis 'r' (one device per replica slot)
    n_local: int = 8         # group lanes per 'g' block


@dataclass
class ExpertConfig:
    engine: EngineConfig = field(default_factory=EngineConfig)
    logdb: LogDBConfig = field(default_factory=LogDBConfig)
    # multi-chip placement for mesh_resident shards (None = single-device
    # kernel engine only)
    mesh: MeshSpec | None = None
    # pluggable filesystem (config.go Expert.FS / vfs.IFS): OSFS by
    # default; MemFS for diskless tests; ErrorFS for fault injection
    fs: object | None = None
    # kernel geometry overrides (TPU-specific expert surface)
    kernel_log_cap: int = 1024
    kernel_inbox_cap: int = 8
    kernel_msg_entries: int = 8
    kernel_proposal_cap: int = 8
    kernel_num_peers: int = 5
    kernel_readindex_cap: int = 4
    kernel_apply_batch: int = 64
    kernel_compaction_overhead: int = 64
    # max device-resident shards per NodeHost (lanes of the batched state)
    kernel_capacity: int = 1024
    # device-side fleet telemetry decimation: the engines run the jitted
    # fleet_stats reduction (core/fleet.py) every N steps and fetch one
    # small struct to host; 0 disables the reduction entirely
    fleet_stats_every: int = 10
    # engine software-pipeline depth (engine/kernel_engine.py): 0 runs
    # the serial stage->dispatch->fetch->process loop (the differential
    # oracle); 1 overlaps host staging/output-retirement with the device
    # step, dispatching through the donating jit entry
    kernel_pipeline_depth: int = 0
    # device-side health engine (core/health.py): rides the
    # fleet_stats_every decimation, classifying every group into the
    # anomaly taxonomy and fetching one O(K) triage report to host.
    # health_top_k sizes the worst-offender list; 0 disables the pass
    health_top_k: int = 8
    # anomaly trip points, in health ticks (churn_trip is a leaky-bucket
    # level: each observed leadership handoff adds CHURN_INC=4, the
    # bucket drains 1/tick)
    health_leaderless_ticks: int = 3
    health_stall_ticks: int = 3
    health_lag_ticks: int = 3
    health_churn_trip: int = 8
    health_runaway_ticks: int = 4
    # runtime protocol-invariant probe (core/invariants.py): rides the
    # fleet_stats_every decimation, evaluating the declared
    # core/kstate.py INVARIANTS over every group and fetching one O(1)
    # verdict report.  Any violation is a BUG (kernel or declaration):
    # it raises an invariant_violation flight event and degrades
    # /healthz.  False disables the pass
    invariant_probe: bool = True
    # proposal-lifecycle tracing (lifecycle.py): every Nth proposal key
    # carries an end-to-end span stamped at each host hop (propose,
    # stage, dispatch, retire, save, fsync, apply, ack) and feeds the
    # commit_stage_us{stage=} histograms + the /trace Chrome-trace ring;
    # 0 disables sampling entirely
    trace_sample_every: int = 64
    # slow-commit SLO in microseconds: a sampled commit whose
    # propose->ack total exceeds this records a flight-recorder
    # slow_commit event with the full stage breakdown; 0 disables (the
    # default keeps chaos-replay flight tails byte-identical, since the
    # breakdown carries measured wall durations)
    trace_slow_commit_us: int = 0
    # fabric observability (fabric.py): per-(src,dst)-link transport
    # telemetry, the cross-host trace header on outbound batches, and
    # the commit-path hop census behind /debug/fabric and
    # info()["fabric"].  False stops link accounting and keeps frames
    # header-free (sampled spans still stamp hub_send/hub_recv
    # in-process)
    fabric_telemetry: bool = True
    # capacity rail (capacity.py): memory_pressure trips when headroom
    # against the device budget drops below the watermark; budget 0 uses
    # the backend-reported bytes_limit (and disables the trip where the
    # backend reports none, e.g. CPU)
    capacity_watermark_pct: float = 10.0
    capacity_device_budget_bytes: int = 0
    # elastic fleet controller (control.py): when enabled, each
    # decimated health observation may plan hysteresis-guarded,
    # rate-limited leader transfers off this host; decisions are a pure
    # function of digest contents + control_seed (flight-recorded as
    # control_transfer with evidence)
    control_enabled: bool = False
    control_hot_score: int = 8
    control_lag_hot: int = 64
    control_hysteresis: int = 2
    control_cooldown_obs: int = 8
    control_max_transfers: int = 2
    control_seed: int = 0
    # observations during which the host-hot latency input is ignored
    # (jit compile inflates the step EWMA at process start)
    control_warmup_obs: int = 8
    # host-hot gate for the controller: engine step-latency EWMA
    # (engine.kernel_step.ewma_us — the measure() window includes
    # output retirement, so apply backpressure shows up here) above
    # this marks every led shard a drain candidate; 0 disables the
    # latency input
    control_hot_ewma_us: int = 0
    # capacity-driven admission (control.check_admission): StartReplica
    # of a device-resident shard past the derated max_g_for_budget
    # watermark is refused ("enforce"), recorded only ("warn"), or
    # ungated ("off").  Needs a resolvable device budget
    # (capacity_device_budget_bytes or backend-reported bytes_limit) —
    # capacity unknown never refuses
    admission_policy: str = "off"
    # opt into the persistent JAX compilation cache at host startup
    # (hostenv.enable_compile_cache; DRAGONBOAT_TPU_COMPILE_CACHE=0
    # vetoes).  Off by default: the cache dir is process-global state
    compile_cache: bool = False


@dataclass
class GossipConfig:
    bind_address: str = ""
    advertise_address: str = ""
    seed: list[str] = field(default_factory=list)

    def is_empty(self) -> bool:
        return not (self.bind_address or self.advertise_address or self.seed)


@dataclass
class NodeHostConfig:
    """Host-level configuration (config/config.go NodeHostConfig)."""

    deployment_id: int = 0
    wal_dir: str = ""
    node_host_dir: str = ""
    rtt_millisecond: int = 200
    raft_address: str = ""
    address_by_node_host_id: bool = False
    listen_address: str = ""
    mutual_tls: bool = False
    ca_file: str = ""
    cert_file: str = ""
    key_file: str = ""
    enable_metrics: bool = False
    # /metrics listen address when enable_metrics is True; port 0 binds
    # an ephemeral port (reported by NodeHost.metrics_address)
    metrics_address: str = "127.0.0.1:0"
    notify_commit: bool = False
    max_send_queue_size: int = 0
    max_receive_queue_size: int = 0
    max_snapshot_send_bytes_per_second: int = 0
    max_snapshot_recv_bytes_per_second: int = 0
    gossip: GossipConfig = field(default_factory=GossipConfig)
    expert: ExpertConfig = field(default_factory=ExpertConfig)
    # pluggable factories (parity: config.LogDBFactory / TransportFactory)
    logdb_factory: object | None = None
    transport_factory: object | None = None
    raft_event_listener: object | None = None
    system_event_listener: object | None = None

    def validate(self) -> None:
        if self.rtt_millisecond == 0:
            raise ConfigError("invalid RTTMillisecond")
        if not self.raft_address:
            raise ConfigError("RaftAddress not set")
        if self.address_by_node_host_id:
            if self.gossip.is_empty():
                raise ConfigError(
                    "gossip must be configured for AddressByNodeHostID")
            if not self.gossip.bind_address:
                raise ConfigError("gossip.bind_address not set")
        if self.mutual_tls:
            for field_name in ("ca_file", "cert_file", "key_file"):
                if not getattr(self, field_name):
                    raise ConfigError(
                        f"MutualTLS requires {field_name} to be set")

    def prepare(self) -> None:
        if not self.node_host_dir:
            raise ConfigError("NodeHostDir not set")
