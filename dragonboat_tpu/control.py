"""Elastic fleet control plane: the observe→act loop's decision core.

The health subsystem (core/health.py) *observes* — a device-side top-K
offender digest plus step-latency telemetry.  This module *decides*:
given one decimated observation, which leaderships should move off this
host (``transfer``) and whether a new device-resident replica may be
admitted under the capacity budget (``refuse``).  The NodeHost applies
the decisions (``request_leader_transfer`` / rejecting
``start_replica``) and flight-records each one with its evidence row;
``fleet_doctor --plan`` runs the same planner read-only over a scraped
``info()`` payload.

Determinism doctrine: a decision is a pure function of the observation
sequence fed in — digest contents, shard rows, the host-hot flag — plus
the policy's fixed seed.  No wall clock, no ambient RNG: the transfer
target tie-break is a splitmix32 hash over (seed, shard_id, term), so
two replays of the same observations plan the same actions, and the
flight recorder's evidence rows are comparable across runs.

Concurrency doctrine: a ``FleetController`` is single-owner state — the
NodeHost calls ``observe`` from its engine tick round only, the doctor
builds a throwaway instance per plan.  It therefore owns no lock; do
not share one instance across threads.

Rate limiting is structural, not temporal: at most
``max_transfers`` per observation, ``hysteresis`` consecutive hot
observations before a shard is acted on, and a per-shard
``cooldown_obs`` observation cooldown after an issued transfer — all
counted in decimated observations (``fleet_stats_every`` engine steps
each), never in seconds.
"""

from __future__ import annotations

from dataclasses import dataclass, field

# decision kinds (Decision.kind / flight-record payloads)
TRANSFER = "transfer"
REFUSE = "refuse"

# admission policy modes (ExpertConfig.admission_policy)
ADMISSION_ENFORCE = "enforce"
ADMISSION_WARN = "warn"
ADMISSION_OFF = "off"
ADMISSION_MODES = (ADMISSION_ENFORCE, ADMISSION_WARN, ADMISSION_OFF)

_MASK32 = 0xFFFFFFFF


def splitmix32(x: int) -> int:
    """One round of the splitmix32 mixer — the same construction the
    kernel uses for randomized election timeouts (core/kernel.py), kept
    host-side here so transfer-target selection is seeded state, not
    ambient RNG (determinism lint DT002 doctrine)."""
    x = (x + 0x9E3779B9) & _MASK32
    z = x
    z = ((z ^ (z >> 16)) * 0x85EBCA6B) & _MASK32
    z = ((z ^ (z >> 13)) * 0xC2B2AE35) & _MASK32
    return (z ^ (z >> 16)) & _MASK32


@dataclass(frozen=True)
class ControlPolicy:
    """Planner knobs.  Defaults mirror ExpertConfig (config.py) — the
    NodeHost builds one of these from its expert block."""

    enabled: bool = False
    #: offender severity (health.py weighted class score) at or above
    #: which a led shard counts as hot
    hot_score: int = 8
    #: commit-applied lag at or above which a led shard counts as hot
    #: even when its class score is below hot_score
    lag_hot: int = 64
    #: consecutive hot observations before a transfer is issued
    hysteresis: int = 2
    #: observations a shard is exempt after a transfer was issued for it
    cooldown_obs: int = 8
    #: max transfers issued per observation (per decimated tick)
    max_transfers: int = 2
    #: tie-break seed for target selection
    seed: int = 0
    #: observations during which the host_hot latency input is IGNORED:
    #: the first engine steps after process start carry the jit-compile
    #: cost, so the step EWMA opens orders of magnitude above any sane
    #: threshold and would drain a perfectly healthy host.  Digest
    #: inputs (score/lag) are not suppressed — they are per-lane
    #: detector verdicts, not wall-clock measurements
    warmup_obs: int = 8


@dataclass(frozen=True)
class Decision:
    """One planned action, with the observation slice that justified it
    (the flight-record payload and the doctor's evidence row)."""

    kind: str          # TRANSFER | REFUSE
    shard_id: int
    target: int        # transferee replica id (0 for REFUSE)
    evidence: dict = field(default_factory=dict)


def pick_target(seed: int, shard_id: int, term: int, voters,
                exclude: int) -> int:
    """Deterministic transfer target: a voter != ``exclude`` chosen by
    splitmix32 over (seed, shard_id, term).  Term is in the key so a
    repeat decision after a failed transfer (term moved) can land on a
    different peer.  Returns 0 when no other voter exists."""
    others = sorted(int(v) for v in voters if int(v) != exclude)
    if not others:
        return 0
    h = splitmix32((seed & _MASK32)
                   ^ splitmix32(shard_id & _MASK32)
                   ^ splitmix32(term & _MASK32))
    return others[h % len(others)]


def shard_voters(shard: dict) -> tuple:
    """Voter replica ids from an ``info()`` shard row's membership."""
    mb = shard.get("membership") or {}
    return tuple(sorted(int(r) for r in (mb.get("addresses") or {})))


class FleetController:
    """Hysteresis-guarded, rate-limited leadership rebalancer.

    Feed it one observation per decimated tick via ``observe``; it
    returns the transfers to issue *this* observation.  All internal
    state (hot streaks, cooldowns, observation index) advances only on
    ``observe`` calls, so the decision sequence is a pure function of
    the observation sequence.
    """

    def __init__(self, policy: ControlPolicy | None = None) -> None:
        self.policy = policy or ControlPolicy()
        self._obs = 0               # observation index (decimated ticks)
        self._streak: dict = {}     # shard_id -> consecutive hot count
        self._cool: dict = {}       # shard_id -> obs index cooldown ends
        self.planned = 0            # cumulative transfers planned

    # -- observation -----------------------------------------------------

    def observe(self, worst, shards, host_hot: bool = False) -> list:
        """Plan transfers for one observation.

        ``worst``: offender rows (health.report_to_dict shape — dicts
        with lane/score/lag/classes/term).  ``shards``: this host's
        shard rows ({shard_id, lane, is_leader, replica_id, term,
        membership}).  ``host_hot``: step-latency telemetry says this
        host's engine is slow (EWMA over threshold) — EVERY led shard
        becomes a drain candidate, digest row or not, because host-level
        overload (e.g. apply backpressure throttling the whole engine
        round) is not attributable to any one anomalous lane.
        """
        self._obs += 1
        pol = self.policy
        by_lane = {int(r.get("lane", -1)): r for r in (worst or [])}

        candidates = []
        hot_ids: dict = {}     # shard_id -> True (insertion-ordered set)
        for sh in shards or []:
            if not sh.get("is_leader"):
                continue
            sid = int(sh["shard_id"])
            row = by_lane.get(int(sh.get("lane", -2)))
            score = int(row["score"]) if row else 0
            lag = int(row["lag"]) if row else 0
            hot = (score >= pol.hot_score
                   or lag >= pol.lag_hot
                   or (host_hot and self._obs > pol.warmup_obs))
            if not hot:
                continue
            hot_ids[sid] = True
            streak = self._streak.get(sid, 0) + 1
            self._streak[sid] = streak
            if streak < pol.hysteresis:
                continue
            if self._obs < self._cool.get(sid, 0):
                continue
            candidates.append((score, lag, sid, sh, row, streak))
        # hysteresis means CONSECUTIVE hot observations: any shard not
        # hot this round (including ones the caller no longer reports)
        # restarts from zero
        for sid in [s for s in self._streak if s not in hot_ids]:
            del self._streak[sid]

        # severity-ordered, shard id as the stable tie-break
        candidates.sort(key=lambda c: (-c[0], -c[1], c[2]))

        out = []
        for score, lag, sid, sh, row, streak in candidates:
            if not pol.enabled or len(out) >= pol.max_transfers:
                break
            term = int(sh.get("term", 0))
            target = pick_target(pol.seed, sid, term, shard_voters(sh),
                                 int(sh.get("replica_id", 0)))
            if target == 0:
                continue  # singleton: nowhere to move leadership
            self._cool[sid] = self._obs + pol.cooldown_obs
            self._streak.pop(sid, None)
            self.planned += 1
            out.append(Decision(
                kind=TRANSFER, shard_id=sid, target=target,
                evidence={
                    "obs": self._obs, "lane": int(sh.get("lane", -1)),
                    "score": score, "lag": lag, "streak": streak,
                    "term": term, "host_hot": bool(host_hot),
                    "classes": list((row or {}).get("classes", ())),
                }))
        return out


# -- capacity-driven admission ------------------------------------------


def admission_limit(kp, budget_bytes: int, watermark_pct: float,
                    max_g_for_budget) -> int:
    """Device-resident shard ceiling: the modeled capacity for the
    budget, derated by the headroom watermark.  Returns 0 when no
    budget is resolvable (admission then never refuses — capacity
    unknown is not capacity exhausted)."""
    if budget_bytes <= 0:
        return 0
    g = max_g_for_budget(kp, budget_bytes)
    keep = max(0.0, 1.0 - float(watermark_pct) / 100.0)
    return max(1, int(g * keep)) if g > 0 else 0


def plan_to_dict(decisions, quiesced: int = 0) -> dict:
    """JSON-able dry-run plan (``fleet_doctor --plan``): the decision
    list as evidence-bearing rows plus summary counts.  ``quiesced`` is
    the host's masked-quiesced lane count (fleet stats), reported so an
    operator sees the third control-plane verb alongside the two the
    planner can still take."""
    transfers = [
        {"shard_id": int(d.shard_id), "target": int(d.target),
         "evidence": dict(d.evidence)}
        for d in decisions if d.kind == TRANSFER]
    refusals = [
        {"shard_id": int(d.shard_id), "evidence": dict(d.evidence)}
        for d in decisions if d.kind == REFUSE]
    return {
        "transfers": transfers,
        "refusals": refusals,
        "counts": {"transfer": len(transfers), "refuse": len(refusals),
                   "quiesced": int(quiesced)},
    }


def _plan_req(d: dict, key: str, typ, where: str):
    if key not in d:
        raise ValueError(f"{where}: missing key {key!r}")
    v = d[key]
    if isinstance(v, bool) and typ is int or not isinstance(v, typ):
        raise ValueError(f"{where}.{key}: expected {typ.__name__}, "
                         f"got {type(v).__name__}")
    return v


def validate_plan(plan: dict, where: str = "plan") -> None:
    """Strictly check a ``plan_to_dict`` payload; raises ValueError
    naming the offending path (the same doctrine as
    core/health.validate_info — the doctor's output is a schema other
    tools may scrape, not prose)."""
    if set(plan) != {"transfers", "refusals", "counts"}:
        raise ValueError(f"{where}: keys {sorted(plan)} != "
                         f"['counts', 'refusals', 'transfers']")
    for i, t in enumerate(_plan_req(plan, "transfers", list, where)):
        w = f"{where}.transfers[{i}]"
        _plan_req(t, "shard_id", int, w)
        if _plan_req(t, "target", int, w) <= 0:
            raise ValueError(f"{w}.target: must be a replica id")
        ev = _plan_req(t, "evidence", dict, w)
        for key in ("obs", "lane", "score", "lag", "streak", "term"):
            _plan_req(ev, key, int, f"{w}.evidence")
        _plan_req(ev, "host_hot", bool, f"{w}.evidence")
        _plan_req(ev, "classes", list, f"{w}.evidence")
    for i, r in enumerate(_plan_req(plan, "refusals", list, where)):
        w = f"{where}.refusals[{i}]"
        _plan_req(r, "shard_id", int, w)
        ev = _plan_req(r, "evidence", dict, w)
        for key in ("occupied", "limit"):
            _plan_req(ev, key, int, f"{w}.evidence")
        if _plan_req(ev, "mode", str, f"{w}.evidence") not in ADMISSION_MODES:
            raise ValueError(f"{w}.evidence.mode: {ev['mode']!r}")
    counts = _plan_req(plan, "counts", dict, where)
    if set(counts) != {"transfer", "refuse", "quiesced"}:
        raise ValueError(f"{where}.counts: keys {sorted(counts)}")
    for key in ("transfer", "refuse", "quiesced"):
        if _plan_req(counts, key, int, f"{where}.counts") < 0:
            raise ValueError(f"{where}.counts.{key}: negative")
    if counts["transfer"] != len(plan["transfers"]) \
            or counts["refuse"] != len(plan["refusals"]):
        raise ValueError(f"{where}.counts: do not match the rows")


def check_admission(shard_id: int, occupied: int, limit: int,
                    mode: str = ADMISSION_ENFORCE) -> Decision | None:
    """Admission gate for one StartReplica: a REFUSE decision when the
    host is at/over its derated capacity, else None.  ``mode`` "off"
    never refuses; "warn" returns the decision with evidence noting it
    is advisory (the caller records but does not reject)."""
    if mode == ADMISSION_OFF or limit <= 0 or occupied < limit:
        return None
    return Decision(
        kind=REFUSE, shard_id=int(shard_id), target=0,
        evidence={"occupied": int(occupied), "limit": int(limit),
                  "mode": mode})
