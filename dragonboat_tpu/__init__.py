"""dragonboat_tpu — a TPU-native multi-group Raft framework.

A brand-new framework with the capabilities of dragonboat (the reference Go
library): a NodeHost hosts many Raft shards with pluggable state machines,
log storage and transport.  Unlike the reference's goroutine-pool engine
(``engine.go``), the per-shard Raft step loop is a batched, vmapped JAX/XLA
kernel advancing all shards in lockstep per step; host-side pipelines handle
fsync, transport and snapshots.

Public surface (parity with the reference's top-level package):

- :class:`dragonboat_tpu.nodehost.NodeHost` — the host façade
- :mod:`dragonboat_tpu.statemachine` — user state-machine interfaces
- :mod:`dragonboat_tpu.config` — Config / NodeHostConfig
- :mod:`dragonboat_tpu.raftio` — ILogDB / ITransport / listener interfaces
- :mod:`dragonboat_tpu.client` — client sessions
"""

__version__ = "0.1.0"
