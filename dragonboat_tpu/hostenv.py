"""Host-environment hardening for a possibly-wedged device backend.

On this host the axon TPU tunnel can wedge in a way that blocks ``import
jax`` at interpreter start (the sitecustomize registers the PJRT plugin,
and plugin init hangs on any ``backends()`` call — even CPU-only runs).
Every driver entry point (bench.py, __graft_entry__) therefore:

1. probes the backend in a SUBPROCESS with a timeout (an in-process probe
   could never time out — the import itself hangs), and
2. on hang, re-execs the workload in a clean environment: empty
   ``PYTHONPATH`` (skips the sitecustomize), ``JAX_PLATFORMS=cpu``, and
   ``--xla_force_host_platform_device_count=N`` for multi-device shapes.

This module is deliberately jax-free and import-safe under a wedged
tunnel.  Replaces what the reference achieves with process supervision
around its benchmark/test binaries (no direct file analog — the failure
mode is specific to the PJRT plugin runtime).
"""

from __future__ import annotations

import os
import subprocess
import sys


def probe_devices(
    timeout_s: float, env: dict | None = None,
) -> tuple[int | None, str]:
    """Probe ``import jax`` in a subprocess.

    Returns ``(device_count, platform)`` on success, else ``(None,
    reason)`` where reason distinguishes a hang from a fast crash (a
    crashed probe should not be misreported as a wedged tunnel).
    """
    # sentinel-tagged so banners printed by backend init can't break parsing
    code = ("import jax; print('DBTPU_PROBE', len(jax.devices()), "
            "jax.devices()[0].platform)")
    try:
        out = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True, text=True, timeout=timeout_s,
            env=env if env is not None else os.environ.copy(),
        )
    except subprocess.TimeoutExpired:
        return None, f"probe timed out after {timeout_s:.0f}s (wedged tunnel?)"
    except Exception as e:  # pragma: no cover - launch failure
        return None, f"probe failed to launch: {e!r}"
    # rc must be 0: a child that reports devices then aborts in PJRT
    # teardown (rc=134 is a known wedged-tunnel shape) is NOT healthy
    if out.returncode == 0:
        for line in reversed((out.stdout or "").splitlines()):
            parts = line.split()
            if len(parts) == 3 and parts[0] == "DBTPU_PROBE":
                try:
                    return int(parts[1]), parts[2]
                except ValueError:
                    break
    return None, (
        f"probe exited rc={out.returncode} without device report: "
        f"{(out.stderr or out.stdout or '').strip()[-500:]}"
    )


def clean_cpu_env(n_devices: int | None = None, **extra: str) -> dict:
    """Environment that sidesteps a wedged tunnel entirely.

    Empty PYTHONPATH (no sitecustomize), CPU backend, optionally
    ``n_devices`` virtual host devices; ``extra`` entries are added last.
    """
    env = os.environ.copy()
    env["PYTHONPATH"] = ""
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    if n_devices:
        env["XLA_FLAGS"] = (
            f"--xla_force_host_platform_device_count={n_devices}"
        )
    env.update(extra)
    return env


def jax_cache_dir(prefix: str = "/tmp/dragonboat_tpu_jax_cache") -> str:
    """Persistent-compile-cache dir fingerprinted by CPU features.

    Build rounds hop machines; artifacts compiled for another feature
    set at best load with warnings.  x86 exposes a ``flags`` line in
    /proc/cpuinfo, aarch64 a ``Features`` line; anything else hashes
    empty and shares one dir (acceptable: same-arch fallback)."""
    import hashlib

    line = ""
    try:
        with open("/proc/cpuinfo") as f:
            line = next((ln for ln in f
                         if ln.startswith(("flags", "Features"))), "")
    except OSError:
        pass
    return f"{prefix}_{hashlib.md5(line.encode()).hexdigest()[:8]}"


def purge_donated_cache_entries(cache_dir: str) -> int:
    """Drop persisted executables for DONATED jit entries; return count.

    Diagnosed 2026-08-08 on jax 0.4.37 / XLA:CPU: an executable compiled
    with ``donate_argnums`` round-trips through the persistent cache
    with broken buffer aliasing — the DESERIALIZED executable returns
    wrong results (diverging state a few steps in) and then segfaults
    or aborts (``std::bad_function_call``) when a result buffer is read.
    A freshly compiled donated executable is fine, and re-running the
    same entry in the same process is fine — only the load-from-disk
    path is affected.  Until the toolchain moves, donated entries are
    treated as non-cacheable: every process that points jax at the
    cache purges them first, paying the recompile instead of the
    use-after-free.  The repo's donated entries all carry the
    ``_donated`` suffix (enforced by the engine-unity pass's
    DISPATCH_ENTRIES contract), so the purge keys on the persisted
    filename."""
    import glob

    n = 0
    for path in glob.glob(os.path.join(cache_dir, "*_donated-*")):
        try:
            os.remove(path)
            n += 1
        except OSError:
            pass
    return n


def enable_compile_cache(
    min_compile_secs: float = 1.0,
    prefix: str = "/tmp/dragonboat_tpu_jax_cache",
) -> str | None:
    """Point jax at the persistent compilation cache (feature-
    fingerprinted dir from ``jax_cache_dir``), so multi-rung geometry
    sweeps and repeated script runs stop paying full recompiles.

    ``DRAGONBOAT_TPU_COMPILE_CACHE=0`` vetoes (returns None).  Imports
    jax lazily — this module must stay import-safe under a wedged
    tunnel.  Returns the cache dir when enabled."""
    if os.environ.get("DRAGONBOAT_TPU_COMPILE_CACHE", "1") == "0":
        return None
    import jax

    cache_dir = jax_cache_dir(prefix)
    purge_donated_cache_entries(cache_dir)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs",
                      float(min_compile_secs))
    return cache_dir
