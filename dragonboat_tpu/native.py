"""Loader for the native runtime primitives (native/dbtpu_native.c).

Compiles the C library on first use (cached under the user cache dir,
keyed by source hash) and exposes it through ctypes.  Every entry point
has a pure-Python fallback, so the package works identically — just
slower on the recovery/framing hot loops — when no C toolchain exists.

``tan_scan(buf, magic)`` is the one that matters: single-pass frame
validation over a whole tan log image (startup recovery over GBs of WAL,
reference internal/tan/db.go replay path).
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading
import zlib

_SRC = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                    "native", "dbtpu_native.c")

_mu = threading.Lock()
_lib = None
_tried = False


class _Rec(ctypes.Structure):
    _fields_ = [("offset", ctypes.c_uint64),
                ("payload_off", ctypes.c_uint64),
                ("payload_len", ctypes.c_uint32)]


def _build() -> str | None:
    """Compile (or reuse a cached build of) the shared library."""
    if not os.path.exists(_SRC):
        return None
    with open(_SRC, "rb") as f:
        digest = hashlib.sha256(f.read()).hexdigest()[:16]
    cache = os.path.join(
        os.environ.get("XDG_CACHE_HOME",
                       os.path.expanduser("~/.cache")),
        "dragonboat_tpu")
    os.makedirs(cache, exist_ok=True)
    so = os.path.join(cache, f"dbtpu_native-{digest}.so")
    if os.path.exists(so):
        return so
    tmp = f"{so}.{os.getpid()}.tmp"  # per-process: concurrent first
    # builds must not race each other into a corrupt cached artifact
    for cc in ("cc", "gcc", "clang"):
        try:
            r = subprocess.run(
                [cc, "-O2", "-shared", "-fPIC", _SRC, "-lz", "-o", tmp],
                capture_output=True, timeout=120)
            if r.returncode == 0:
                os.replace(tmp, so)
                return so
        except (OSError, subprocess.TimeoutExpired):
            continue
    return None


def _load():
    global _lib, _tried
    with _mu:
        if _tried:
            return _lib
        _tried = True
        if os.environ.get("DRAGONBOAT_TPU_NO_NATIVE") == "1":
            return None
        so = _build()
        if so is None:
            return None
        try:
            lib = ctypes.CDLL(so)
            lib.dbtpu_tan_scan.restype = ctypes.c_int
            lib.dbtpu_tan_scan.argtypes = [
                ctypes.c_char_p, ctypes.c_uint64, ctypes.c_uint32,
                ctypes.POINTER(_Rec), ctypes.c_uint64,
                ctypes.POINTER(ctypes.c_uint64),
                ctypes.POINTER(ctypes.c_uint64),
                ctypes.POINTER(ctypes.c_int)]
            lib.dbtpu_frame_check.restype = ctypes.c_int
            lib.dbtpu_frame_check.argtypes = [
                ctypes.c_char_p, ctypes.c_uint64, ctypes.c_uint32]
            lib.dbtpu_crc32.restype = ctypes.c_uint32
            lib.dbtpu_crc32.argtypes = [
                ctypes.c_char_p, ctypes.c_uint64, ctypes.c_uint32]
            _lib = lib
        except OSError:
            _lib = None
        return _lib


def available() -> bool:
    return _load() is not None


def tan_scan(buf: bytes, magic: int):
    """-> (records, scan_end, torn): records = [(offset, payload_off,
    payload_len)] for every frame whose magic and CRC validate, in file
    order; scan_end = offset past the last valid frame; torn = True when
    the scan stopped at a bad/partial frame (crash tail or corruption)."""
    lib = _load()
    if lib is None:
        return _tan_scan_py(buf, magic)
    n = len(buf)
    # worst case: every record is an empty payload (12 bytes of frame)
    max_out = n // 12 + 1
    out = (_Rec * max_out)()
    n_out = ctypes.c_uint64()
    scan_end = ctypes.c_uint64()
    status = ctypes.c_int()
    lib.dbtpu_tan_scan(
        buf, ctypes.c_uint64(n), ctypes.c_uint32(magic),
        out, ctypes.c_uint64(max_out),
        ctypes.byref(n_out), ctypes.byref(scan_end), ctypes.byref(status))
    recs = [(out[i].offset, out[i].payload_off, out[i].payload_len)
            for i in range(n_out.value)]
    return recs, scan_end.value, status.value == 1


def _tan_scan_py(buf: bytes, magic: int):
    import struct

    recs = []
    off, n = 0, len(buf)
    while off + 12 <= n:
        m, plen, crc = struct.unpack_from("<III", buf, off)
        if m != magic or off + 12 + plen > n:
            return recs, off, True
        if zlib.crc32(buf[off + 12: off + 12 + plen]) != crc:
            return recs, off, True
        recs.append((off, off + 12, plen))
        off += 12 + plen
    return recs, off, off != n


def frame_check(payload: bytes, crc: int) -> bool:
    lib = _load()
    if lib is None:
        return zlib.crc32(payload) == crc
    return bool(lib.dbtpu_frame_check(
        payload, ctypes.c_uint64(len(payload)), ctypes.c_uint32(crc)))
