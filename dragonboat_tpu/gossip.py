"""Gossip registry — dynamic NodeHostID-based addressing.

Parity with the reference's ``internal/registry/gossip.go``: when
``NodeHostConfig.address_by_node_host_id`` is set, raft targets are
persistent NodeHostIDs instead of raft addresses, and each host's
current raft address is disseminated by an anti-entropy gossip protocol
(the reference rides hashicorp/memberlist; this is a self-contained UDP
implementation of the same behavior: per-member versioned meta records
{nhid → raft_address}, periodic push to seeds + random peers, merge by
version, dead-member expiry).

``GossipRegistry`` wraps the static registry: (shard, replica) resolves
to a target string as usual; a target that is a NodeHostID is then
translated through the gossip view (gossip.go:157 Resolve →
metaStore.get).

Beyond addresses, hosts exchange a cluster-wide **shard view**
(``internal/registry/view.go:36-149``): per shard
``{shard_id, replicas, config_change_index, leader_id, term}``, merged
by config-change index (membership) and leader term (leadership), so any
host can answer "who leads shard N" without hosting a replica of it.
``GossipRegistry.get_shard_info`` / ``num_of_shards`` mirror
NodeHostRegistry (``internal/registry/nodehost.go:23-41``).
"""

from __future__ import annotations

import json
import random
import socket
import threading
import time

from dragonboat_tpu.logger import get_logger
from dragonboat_tpu.raftio import INodeRegistry
from dragonboat_tpu.registry import Registry

_LOG = get_logger("gossip")

GOSSIP_INTERVAL_S = 0.15
FANOUT = 3
EXPIRY_S = 30.0


class _Meta:
    __slots__ = ("raft_address", "version", "seen_at")

    def __init__(self, raft_address: str, version: int) -> None:
        self.raft_address = raft_address
        self.version = version
        self.seen_at = time.monotonic()


class ShardView:
    """One shard as the gossip mesh knows it (view.go:68-74)."""

    __slots__ = ("shard_id", "replicas", "config_change_index",
                 "leader_id", "term")

    def __init__(self, shard_id: int, replicas: dict[int, str] | None = None,
                 config_change_index: int = 0, leader_id: int = 0,
                 term: int = 0) -> None:
        self.shard_id = shard_id
        self.replicas = replicas or {}
        self.config_change_index = config_change_index
        self.leader_id = leader_id
        self.term = term


def _merge_shard_view(cur: ShardView, upd: ShardView) -> ShardView:
    """view.go:121 mergeShardView: membership by config-change index,
    leadership by (known leader, higher term)."""
    if cur.config_change_index < upd.config_change_index:
        cur.replicas = upd.replicas
        cur.config_change_index = upd.config_change_index
    if upd.leader_id != 0 and (cur.leader_id == 0 or upd.term > cur.term):
        cur.leader_id = upd.leader_id
        cur.term = upd.term
    return cur


class GossipManager:
    """UDP anti-entropy: each round, push the full view to up to FANOUT
    known members (+ the seeds until they answer)."""

    def __init__(self, nhid: str, raft_address: str, bind_address: str,
                 advertise_address: str = "", seeds: list[str] | None = None,
                 interval_s: float = GOSSIP_INTERVAL_S,
                 shard_info_fn=None) -> None:
        self.nhid = nhid
        self.raft_address = raft_address
        self.interval_s = interval_s
        # () -> list[ShardView] of the LOCAL host's shards, refreshed
        # before every push (nodehost wires get_node_host_info here)
        self.shard_info_fn = shard_info_fn
        self.shards: dict[int, ShardView] = {}
        self._last_refresh = 0.0
        host, port = _parse(bind_address)
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            # multi-datagram bursts (chunked big views) overflow the
            # default rcvbuf; losing the SAME tail chunks every round
            # would stall anti-entropy convergence
            self.sock.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF,
                                 4 << 20)
        except OSError:
            pass
        self.sock.bind((host, port))
        self.sock.settimeout(0.05)
        bound = self.sock.getsockname()
        if not advertise_address and bound[0] in ("0.0.0.0", "", "::"):
            self.sock.close()
            raise ValueError(
                "gossip: a wildcard bind_address requires an explicit "
                "advertise_address (peers would gossip to themselves)")
        self.advertise = advertise_address or f"{bound[0]}:{bound[1]}"
        self.seeds = [s for s in (seeds or []) if s != self.advertise]
        self.mu = threading.Lock()
        # nhid -> meta; members: gossip address -> last seen
        self.view: dict[str, _Meta] = {
            nhid: _Meta(raft_address, int(time.time() * 1000))}
        self.members: dict[str, float] = {}
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, name="gossip",
                                        daemon=True)
        self._thread.start()

    # -- protocol ---------------------------------------------------------

    # a UDP datagram caps at ~65507 bytes; stay well under so the view
    # plus one shard chunk always fits (big shard sets span datagrams,
    # the anti-entropy merge is idempotent so chunk loss only delays)
    _MAX_DATAGRAM = 48 << 10

    def _payloads(self) -> list[bytes]:
        """Pack the member view AND the shard views into as many
        size-capped datagrams as needed (memberlist chunks its
        broadcasts the same way — one oversized sendto would EMSGSIZE
        and silently kill ALL dissemination).  Both record kinds are
        merged idempotently on receive, so any record landing in any
        datagram is enough."""
        self._refresh_local_shards()
        with self.mu:
            view_recs = [(n, [m.raft_address, m.version])
                         for n, m in self.view.items()]
            shard_recs = [[v.shard_id,
                           {str(r): a for r, a in v.replicas.items()},
                           v.config_change_index, v.leader_id, v.term]
                          for v in self.shards.values()]
        # the local address record rides every datagram so any single
        # received chunk identifies + locates the sender
        self_view = {n: rec for n, rec in view_recs if n == self.nhid}
        overhead = len(json.dumps({
            "from": self.advertise, "view": self_view, "shards": [],
        })) + 2
        room = self._MAX_DATAGRAM - overhead
        out: list[bytes] = []
        view_chunk: dict = dict(self_view)
        shard_chunk: list = []
        used = 0

        def flush():
            nonlocal view_chunk, shard_chunk, used
            out.append(json.dumps({
                "from": self.advertise,
                "view": view_chunk,
                "shards": shard_chunk,
            }).encode())
            view_chunk, shard_chunk, used = dict(self_view), [], 0

        items = [("v", r) for r in view_recs if r[0] != self.nhid] \
            + [("s", r) for r in shard_recs]
        # randomize chunk membership per push: if a fixed-size prefix of
        # the burst is all a congested receiver keeps, a deterministic
        # order would starve the same records forever
        random.shuffle(items)
        for kind, rec in items:
            cost = len(json.dumps(rec)) + 8
            if used and used + cost > room:
                flush()
            if kind == "v":
                view_chunk[rec[0]] = rec[1]
            else:
                shard_chunk.append(rec)
            used += cost
        flush()
        return out

    def _refresh_local_shards(self, min_interval_s: float | None = None
                              ) -> None:
        """Fold the local host's current shard states into the merged
        store (the reference's delegate pulls getShardInfo the same way
        before each exchange, gossip.go LocalState)."""
        if self.shard_info_fn is None:
            return
        now = time.monotonic()
        if min_interval_s is not None and \
                now - self._last_refresh < min_interval_s:
            return
        self._last_refresh = now
        try:
            local = self.shard_info_fn()
        except Exception:
            _LOG.debug("shard_info_fn failed", exc_info=True)
            return
        with self.mu:
            for v in local:
                cur = self.shards.get(v.shard_id)
                if cur is None:
                    self.shards[v.shard_id] = v
                else:
                    self.shards[v.shard_id] = _merge_shard_view(cur, v)

    def _run(self) -> None:
        last_push = 0.0
        while not self._stop.is_set():
            now = time.monotonic()
            if now - last_push >= self.interval_s:
                last_push = now
                self._push()
            try:
                data, addr = self.sock.recvfrom(65536)
            except (socket.timeout, OSError):
                continue
            try:
                msg = json.loads(data.decode())
                if isinstance(msg, dict):
                    self._merge(msg)
            except Exception:
                # a malformed datagram must never kill the gossip thread
                _LOG.debug("dropping malformed gossip datagram from %s",
                           addr, exc_info=True)

    def _push(self) -> None:
        payloads = self._payloads()
        with self.mu:
            known = list(self.members)
        targets = set(self.seeds)
        if known:
            targets.update(random.sample(known, min(FANOUT, len(known))))
        for t in targets:
            for payload in payloads:
                try:
                    self.sock.sendto(payload, _parse(t))
                except (OSError, ValueError):
                    # skip only this datagram: a payload-specific error
                    # (e.g. EMSGSIZE) must not starve the other chunks
                    continue

    def _merge(self, msg: dict) -> None:
        src = msg.get("from")
        now = time.monotonic()
        view = msg.get("view")
        if not isinstance(view, dict):
            view = {}
        with self.mu:
            if isinstance(src, str) and src != self.advertise:
                try:
                    _parse(src)  # only track pushable member addresses
                    self.members[src] = now
                except ValueError:
                    pass
            shards = msg.get("shards")
            if isinstance(shards, list):
                for rec in shards:
                    try:
                        sid = int(rec[0])
                        upd = ShardView(
                            sid,
                            {int(r): str(a) for r, a in rec[1].items()},
                            int(rec[2]), int(rec[3]), int(rec[4]))
                    except (TypeError, ValueError, IndexError,
                            AttributeError):
                        continue
                    cur = self.shards.get(sid)
                    if cur is None:
                        self.shards[sid] = upd
                    else:
                        self.shards[sid] = _merge_shard_view(cur, upd)
            for nhid, rec in view.items():
                if nhid == self.nhid:
                    # the local record is authoritative here — a stale
                    # echo (e.g. after a clock step) must not overwrite
                    # our own advertised address (memberlist's local-node
                    # special case)
                    continue
                try:
                    addr, version = rec[0], int(rec[1])
                except (TypeError, ValueError, IndexError):
                    continue
                cur = self.view.get(nhid)
                if cur is None or version > cur.version:
                    self.view[nhid] = _Meta(addr, version)
                elif cur is not None:
                    cur.seen_at = now
            # expire members we have not heard from
            for m in [m for m, ts in self.members.items()
                      if now - ts > EXPIRY_S]:
                del self.members[m]

    # -- queries ----------------------------------------------------------

    def lookup(self, nhid: str) -> str | None:
        with self.mu:
            m = self.view.get(nhid)
            return m.raft_address if m is not None else None

    def num_members(self) -> int:
        with self.mu:
            return len(self.members) + 1

    def get_shard_info(self, shard_id: int) -> ShardView | None:
        # queries mostly ride the store the push loop maintains; the
        # rate-limited refresh just bounds staleness for hosts that are
        # pure pollers (no shards of their own changing)
        self._refresh_local_shards(min_interval_s=self.interval_s)
        with self.mu:
            v = self.shards.get(shard_id)
            if v is None:
                return None
            return ShardView(v.shard_id, dict(v.replicas),
                             v.config_change_index, v.leader_id, v.term)

    def num_of_shards(self) -> int:
        self._refresh_local_shards(min_interval_s=self.interval_s)
        with self.mu:
            return len(self.shards)

    def set_raft_address(self, raft_address: str) -> None:
        """Re-advertise after an address change (the reason this whole
        subsystem exists: stable identity over movable addresses)."""
        with self.mu:
            self.raft_address = raft_address
            self.view[self.nhid] = _Meta(raft_address,
                                         int(time.time() * 1000))

    def close(self) -> None:
        self._stop.set()
        self._thread.join(timeout=2)
        self.sock.close()


def _parse(addr: str) -> tuple[str, int]:
    host, _, port = addr.rpartition(":")
    return host or "127.0.0.1", int(port)


class GossipRegistry(INodeRegistry):
    """INodeRegistry whose targets may be NodeHostIDs (gossip.go:99)."""

    def __init__(self, manager: GossipManager) -> None:
        self.manager = manager
        self.static = Registry()

    def add(self, shard_id: int, replica_id: int, target: str) -> None:
        self.static.add(shard_id, replica_id, target)

    def remove(self, shard_id: int, replica_id: int) -> None:
        self.static.remove(shard_id, replica_id)

    def remove_shard(self, shard_id: int) -> None:
        self.static.remove_shard(shard_id)

    def resolve(self, shard_id: int, replica_id: int) -> tuple[str, str]:
        target, key = self.static.resolve(shard_id, replica_id)
        if target.startswith("nhid-"):
            addr = self.manager.lookup(target)
            if addr is None:
                raise KeyError(
                    f"NodeHostID {target} not (yet) known to gossip")
            return addr, key
        return target, key

    # -- NodeHostRegistry surface (internal/registry/nodehost.go:23-41) --

    def num_of_shards(self) -> int:
        """Number of shards known to the gossip mesh (not just local)."""
        return self.manager.num_of_shards()

    def get_shard_info(self, shard_id: int) -> ShardView | None:
        """Cluster-wide view of one shard: membership at the highest
        config-change index seen, leadership at the highest term."""
        return self.manager.get_shard_info(shard_id)

    def close(self) -> None:
        self.manager.close()
