"""Gossip registry — dynamic NodeHostID-based addressing.

Parity with the reference's ``internal/registry/gossip.go``: when
``NodeHostConfig.address_by_node_host_id`` is set, raft targets are
persistent NodeHostIDs instead of raft addresses, and each host's
current raft address is disseminated by an anti-entropy gossip protocol
(the reference rides hashicorp/memberlist; this is a self-contained UDP
implementation of the same behavior: per-member versioned meta records
{nhid → raft_address}, periodic push to seeds + random peers, merge by
version, dead-member expiry).

``GossipRegistry`` wraps the static registry: (shard, replica) resolves
to a target string as usual; a target that is a NodeHostID is then
translated through the gossip view (gossip.go:157 Resolve →
metaStore.get).
"""

from __future__ import annotations

import json
import random
import socket
import threading
import time

from dragonboat_tpu.logger import get_logger
from dragonboat_tpu.raftio import INodeRegistry
from dragonboat_tpu.registry import Registry

_LOG = get_logger("gossip")

GOSSIP_INTERVAL_S = 0.15
FANOUT = 3
EXPIRY_S = 30.0


class _Meta:
    __slots__ = ("raft_address", "version", "seen_at")

    def __init__(self, raft_address: str, version: int) -> None:
        self.raft_address = raft_address
        self.version = version
        self.seen_at = time.monotonic()


class GossipManager:
    """UDP anti-entropy: each round, push the full view to up to FANOUT
    known members (+ the seeds until they answer)."""

    def __init__(self, nhid: str, raft_address: str, bind_address: str,
                 advertise_address: str = "", seeds: list[str] | None = None,
                 interval_s: float = GOSSIP_INTERVAL_S) -> None:
        self.nhid = nhid
        self.raft_address = raft_address
        self.interval_s = interval_s
        host, port = _parse(bind_address)
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self.sock.bind((host, port))
        self.sock.settimeout(0.05)
        bound = self.sock.getsockname()
        if not advertise_address and bound[0] in ("0.0.0.0", "", "::"):
            self.sock.close()
            raise ValueError(
                "gossip: a wildcard bind_address requires an explicit "
                "advertise_address (peers would gossip to themselves)")
        self.advertise = advertise_address or f"{bound[0]}:{bound[1]}"
        self.seeds = [s for s in (seeds or []) if s != self.advertise]
        self.mu = threading.Lock()
        # nhid -> meta; members: gossip address -> last seen
        self.view: dict[str, _Meta] = {
            nhid: _Meta(raft_address, int(time.time() * 1000))}
        self.members: dict[str, float] = {}
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, name="gossip",
                                        daemon=True)
        self._thread.start()

    # -- protocol ---------------------------------------------------------

    def _payload(self) -> bytes:
        with self.mu:
            view = {n: [m.raft_address, m.version]
                    for n, m in self.view.items()}
        return json.dumps({
            "from": self.advertise,
            "view": view,
        }).encode()

    def _run(self) -> None:
        last_push = 0.0
        while not self._stop.is_set():
            now = time.monotonic()
            if now - last_push >= self.interval_s:
                last_push = now
                self._push()
            try:
                data, addr = self.sock.recvfrom(65536)
            except (socket.timeout, OSError):
                continue
            try:
                msg = json.loads(data.decode())
                if isinstance(msg, dict):
                    self._merge(msg)
            except Exception:
                # a malformed datagram must never kill the gossip thread
                _LOG.debug("dropping malformed gossip datagram from %s",
                           addr, exc_info=True)

    def _push(self) -> None:
        payload = self._payload()
        with self.mu:
            known = list(self.members)
        targets = set(self.seeds)
        if known:
            targets.update(random.sample(known, min(FANOUT, len(known))))
        for t in targets:
            try:
                self.sock.sendto(payload, _parse(t))
            except (OSError, ValueError):
                pass

    def _merge(self, msg: dict) -> None:
        src = msg.get("from")
        now = time.monotonic()
        view = msg.get("view")
        if not isinstance(view, dict):
            view = {}
        with self.mu:
            if isinstance(src, str) and src != self.advertise:
                try:
                    _parse(src)  # only track pushable member addresses
                    self.members[src] = now
                except ValueError:
                    pass
            for nhid, rec in view.items():
                if nhid == self.nhid:
                    # the local record is authoritative here — a stale
                    # echo (e.g. after a clock step) must not overwrite
                    # our own advertised address (memberlist's local-node
                    # special case)
                    continue
                try:
                    addr, version = rec[0], int(rec[1])
                except (TypeError, ValueError, IndexError):
                    continue
                cur = self.view.get(nhid)
                if cur is None or version > cur.version:
                    self.view[nhid] = _Meta(addr, version)
                elif cur is not None:
                    cur.seen_at = now
            # expire members we have not heard from
            for m in [m for m, ts in self.members.items()
                      if now - ts > EXPIRY_S]:
                del self.members[m]

    # -- queries ----------------------------------------------------------

    def lookup(self, nhid: str) -> str | None:
        with self.mu:
            m = self.view.get(nhid)
            return m.raft_address if m is not None else None

    def num_members(self) -> int:
        with self.mu:
            return len(self.members) + 1

    def set_raft_address(self, raft_address: str) -> None:
        """Re-advertise after an address change (the reason this whole
        subsystem exists: stable identity over movable addresses)."""
        with self.mu:
            self.raft_address = raft_address
            self.view[self.nhid] = _Meta(raft_address,
                                         int(time.time() * 1000))

    def close(self) -> None:
        self._stop.set()
        self._thread.join(timeout=2)
        self.sock.close()


def _parse(addr: str) -> tuple[str, int]:
    host, _, port = addr.rpartition(":")
    return host or "127.0.0.1", int(port)


class GossipRegistry(INodeRegistry):
    """INodeRegistry whose targets may be NodeHostIDs (gossip.go:99)."""

    def __init__(self, manager: GossipManager) -> None:
        self.manager = manager
        self.static = Registry()

    def add(self, shard_id: int, replica_id: int, target: str) -> None:
        self.static.add(shard_id, replica_id, target)

    def remove(self, shard_id: int, replica_id: int) -> None:
        self.static.remove(shard_id, replica_id)

    def remove_shard(self, shard_id: int) -> None:
        self.static.remove_shard(shard_id)

    def resolve(self, shard_id: int, replica_id: int) -> tuple[str, str]:
        target, key = self.static.resolve(shard_id, replica_id)
        if target.startswith("nhid-"):
            addr = self.manager.lookup(target)
            if addr is None:
                raise KeyError(
                    f"NodeHostID {target} not (yet) known to gossip")
            return addr, key
        return target, key

    def close(self) -> None:
        self.manager.close()
