"""SoA device state for the batched Raft kernel.

The reference keeps per-shard state in a ``raft`` struct of maps and slices
(``internal/raft/raft.go:199-239``); here the same information is a
structure-of-arrays pytree with a leading ``[G]`` shard axis so one vmapped
step advances every shard in lockstep (BASELINE.json north star).  Peer books
are fixed ``[G, P]`` lanes (the reference's ``remote`` is already fixed-width:
remote.go:72), the entry log is a ``[G, CAP]`` term ring (payloads live
host-side or in the device RSM's value lanes), and the ReadIndex book is a
``[G, RI]`` circular queue.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp
import numpy as np

from dragonboat_tpu.core import params as P

# ---------------------------------------------------------------------------
# Machine-readable field contracts (checked by analysis/contracts.py).
#
# Grammar, one string per field:
#
#   "[<axes>] <dtype> [tag ...]"
#
#   axes    comma-separated symbolic axis names over the kernel geometry:
#           G  shard axis               (num_shards — vmap strips it)
#           P  peer slots               (KernelParams.num_peers)
#           CAP  term-ring capacity     (KernelParams.log_cap, power of two)
#           K  inbox slots              (KernelParams.inbox_cap)
#           E  entries per message      (KernelParams.msg_entries)
#           B  proposal slots           (KernelParams.proposal_cap)
#           RI ReadIndex book slots     (KernelParams.readindex_cap, 2^n)
#   dtype   i32 | bool
#   tags    ring            the leading non-G axis is a power-of-two ring:
#                           dynamic indexing into it must be masked with
#                           `& (cap - 1)` (or argmax/arange-bounded to it)
#           domain=A..B     values live in [params.A, params.B] inclusive
#           optional        field is None unless the config materializes it
#           part=G          the field carries PER-GROUP data: at the mesh
#                           level its leading G axis is sharded over the
#                           ('g','r') device mesh (parallel/ici.py) and no
#                           kernel code may reduce/gather across G outside
#                           a declared collective (analysis/partition.py)
#           part=replicated the field is identical on every device (e.g.
#                           fleet-stats aggregates); mixing it into
#                           G-sharded math needs an explicit broadcast
#           collective=declared
#                           the struct's fields are produced by an
#                           INTENTIONAL cross-G collective (core/fleet.py
#                           FleetStats); cross-G reductions inside the
#                           producing function are by design
#
# The contracts pass (scripts/lint.py --pass contracts) parses this dict
# from the AST (it must stay a literal), abstractly interprets
# core/kernel.py against it, and cross-validates it against the
# eval-shaped structures built by init_state/empty_inbox/empty_input and
# the step output.  Editing a field here without updating the arrays (or
# vice versa) is a lint failure, not a comment drifting out of date.
# ---------------------------------------------------------------------------

CONTRACTS = {
    "ShardState": {
        # identity / config
        "replica_id": "[G] i32 part=G",
        "seed": "[G] i32 part=G",
        "e_timeout": "[G] i32 part=G",
        "h_timeout": "[G] i32 part=G",
        "check_quorum": "[G] bool part=G",
        "pre_vote": "[G] bool part=G",
        # core protocol state
        "role": "[G] i32 domain=FOLLOWER..WITNESS part=G",
        "term": "[G] i32 part=G",
        "vote": "[G] i32 part=G",
        "leader": "[G] i32 part=G",
        "applied": "[G] i32 part=G",
        "e_tick": "[G] i32 part=G",
        "h_tick": "[G] i32 part=G",
        "rand_timeout": "[G] i32 part=G",
        "rand_counter": "[G] i32 part=G",
        "pending_cc": "[G] bool part=G",
        "ltt": "[G] i32 part=G",
        "is_ltt": "[G] bool part=G",
        # peer books
        "pid": "[G, P] i32 part=G",
        "kind": "[G, P] i32 domain=K_ABSENT..K_WITNESS part=G",
        "match": "[G, P] i32 part=G",
        "next": "[G, P] i32 part=G",
        "pstate": "[G, P] i32 domain=R_RETRY..R_SNAPSHOT part=G",
        "active": "[G, P] bool part=G",
        "psnap": "[G, P] i32 part=G",
        "vresp": "[G, P] bool part=G",
        "vgrant": "[G, P] bool part=G",
        # log ring + cursors
        "lt": "[G, CAP] i32 ring part=G",
        "lcc": "[G, CAP] bool ring part=G",
        "snap_index": "[G] i32 part=G",
        "snap_term": "[G] i32 part=G",
        "last": "[G] i32 part=G",
        "committed": "[G] i32 part=G",
        "processed": "[G] i32 part=G",
        "stable": "[G] i32 part=G",
        # ReadIndex circular book
        "ri_low": "[G, RI] i32 ring part=G",
        "ri_high": "[G, RI] i32 ring part=G",
        "ri_index": "[G, RI] i32 ring part=G",
        "ri_acks": "[G, RI, P] bool ring part=G",
        "ri_head": "[G] i32 part=G",
        "ri_count": "[G] i32 part=G",
        "needs_host": "[G] bool part=G",
        # device quiesce (the kernel-masked form of quiesce.py)
        "quiesce_on": "[G] bool part=G",
        "idle_tick": "[G] i32 part=G",
        "quiesced": "[G] bool part=G",
        "quiesce_epoch": "[G] i32 part=G",
        "lv": "[G, CAP] i32 ring optional part=G",
    },
    "Inbox": {
        "mtype": "[G, K] i32 part=G",
        "from_": "[G, K] i32 part=G",
        "term": "[G, K] i32 part=G",
        "log_term": "[G, K] i32 part=G",
        "log_index": "[G, K] i32 part=G",
        "commit": "[G, K] i32 part=G",
        "reject": "[G, K] bool part=G",
        "hint": "[G, K] i32 part=G",
        "hint_high": "[G, K] i32 part=G",
        "n_ent": "[G, K] i32 part=G",
        "ent_term": "[G, K, E] i32 part=G",
        "ent_cc": "[G, K, E] bool part=G",
        "ent_val": "[G, K, E] i32 optional part=G",
    },
    "StepInput": {
        "prop_valid": "[G, B] bool part=G",
        "prop_cc": "[G, B] bool part=G",
        "ri_valid": "[G] bool part=G",
        "ri_low": "[G] i32 part=G",
        "ri_high": "[G] i32 part=G",
        "transfer_to": "[G] i32 part=G",
        "tick": "[G] bool part=G",
        "quiesced": "[G] bool part=G",
        "applied": "[G] i32 part=G",
        "prop_val": "[G, B] i32 optional part=G",
    },
    "StepOutput": {
        "r_type": "[G, K] i32 part=G",
        "r_to": "[G, K] i32 part=G",
        "r_term": "[G, K] i32 part=G",
        "r_log_index": "[G, K] i32 part=G",
        "r_reject": "[G, K] bool part=G",
        "r_hint": "[G, K] i32 part=G",
        "r_hint_high": "[G, K] i32 part=G",
        "s_rep": "[G, P] bool part=G",
        "s_prev_index": "[G, P] i32 part=G",
        "s_prev_term": "[G, P] i32 part=G",
        "s_commit": "[G, P] i32 part=G",
        "s_n_ent": "[G, P] i32 part=G",
        "s_ent_term": "[G, P, E] i32 part=G",
        "s_ent_cc": "[G, P, E] bool part=G",
        "s_ent_val": "[G, P, E] i32 optional part=G",
        "s_vote": "[G, P] i32 part=G",
        "s_vote_term": "[G, P] i32 part=G",
        "s_vote_lindex": "[G, P] i32 part=G",
        "s_vote_lterm": "[G, P] i32 part=G",
        "s_vote_hint": "[G, P] i32 part=G",
        "s_hb": "[G, P] bool part=G",
        "s_hb_commit": "[G, P] i32 part=G",
        "s_hb_low": "[G, P] i32 part=G",
        "s_hb_high": "[G, P] i32 part=G",
        "s_timeout_now": "[G, P] bool part=G",
        "s_need_snapshot": "[G, P] bool part=G",
        "s_wit_snap": "[G, P] bool part=G",
        "save_first": "[G] i32 part=G",
        "save_last": "[G] i32 part=G",
        "apply_first": "[G] i32 part=G",
        "apply_last": "[G] i32 part=G",
        "term": "[G] i32 part=G",
        "vote": "[G] i32 part=G",
        "commit": "[G] i32 part=G",
        "rtr_valid": "[G, RI] bool part=G",
        "rtr_index": "[G, RI] i32 part=G",
        "rtr_low": "[G, RI] i32 part=G",
        "rtr_high": "[G, RI] i32 part=G",
        "ri_dropped": "[G] bool part=G",
        "prop_accepted": "[G, B] bool part=G",
        "prop_index": "[G, B] i32 part=G",
        "prop_term": "[G, B] i32 part=G",
        "leader": "[G] i32 part=G",
        "leader_term": "[G] i32 part=G",
        "needs_host": "[G] bool part=G",
    },
}


# ---------------------------------------------------------------------------
# Protocol invariants (grammar: analysis/common.py parse_invariant).
#
# Machine-readable cross-field per-group invariants over ShardState —
# the Raft safety conditions the vectorized kernel must uphold, in a form
# all three verifier legs consume:
#
#   * analysis/safety.py statically checks every kernel store to a
#     participating field against these (RS001–RS006),
#   * scripts/model_check.py asserts them at every state of the
#     exhaustively explored small scope,
#   * core/invariants.py evaluates them as a jitted [G] reduction on the
#     live fleet (the runtime probe).
#
# STATE-scoped invariants hold of any single observation; ``prev.`` terms
# make an invariant STEP-scoped — it constrains a transition (for the
# runtime probe, a transition between two decimated observations, which is
# sound for the monotone/guarded forms below).  Deliberately absent:
# ``stable`` (legitimately lowered when a replicate truncates an unstable
# suffix) and the snapshot cursors (host-mediated injection moves them
# non-monotonically by design).
#
# Like CONTRACTS this must stay a pure literal (ast.literal_eval).
# ---------------------------------------------------------------------------

INVARIANTS = {
    # the commit cursor can never pass the end of the log
    "commit_within_log": "committed <= last",
    # entries are released to the apply pipeline only once committed
    "processed_within_commit": "processed <= committed",
    # the RSM-confirmed cursor can never pass what was released to it
    "applied_within_processed": "applied <= processed",
    # terms are monotonically non-decreasing
    "term_monotone": "term >= prev.term",
    # the commit cursor is monotonically non-decreasing
    "commit_monotone": "committed >= prev.committed",
    # at most one vote per term: while the term holds still, a cast vote
    # (nonzero) never changes
    "vote_once_per_term":
        "term == prev.term & prev.vote != 0 => vote == prev.vote",
    # a stable leader advances commit only to quorum-matched indexes.
    # Guarded on prev.role == LEADER & term == prev.term: a freshly
    # elected leader's peer match book resets to 0 while its commit
    # cursor (inherited as follower) may already be ahead — only commit
    # ADVANCES under stable same-term leadership must be quorum-backed.
    "leader_commit_quorum":
        "role == LEADER & prev.role == LEADER & term == prev.term"
        " & committed > prev.committed => quorum(match) >= committed",
    # a quiesced replica never campaigns (no term movement) or grants
    # votes.  quiesce_epoch bumps on every wake, so an unchanged epoch
    # between two observations proves the lane stayed quiesced for the
    # WHOLE interval — making both forms sound at any probe decimation
    # (a wake + re-quiesce between observations changes the epoch and
    # the guard fails vacuously)
    "quiesced_no_campaign":
        "prev.quiesced == 1 & quiesced == 1"
        " & quiesce_epoch == prev.quiesce_epoch => term == prev.term",
    "quiesced_no_vote":
        "prev.quiesced == 1 & quiesced == 1"
        " & quiesce_epoch == prev.quiesce_epoch => vote == prev.vote",
}


# ---------------------------------------------------------------------------
# Buffer-donation contract (checked by analysis/contracts.py, KC008).
#
# Each entry names a jitted entry point that donates argument buffers to
# XLA and records WHICH positional arguments (and the parameter names
# they bind) are donated.  Entries default to core/kernel.py; an entry
# with a ``module`` key declares a donating entry elsewhere (the mesh
# serve step in parallel/ici.py, the router differential twin).  The
# analyzer parses each module's decorators and fails lint if the
# ``donate_argnums`` there drifts from this declaration — so the
# host-side rule below is always describing the real kernel, not a
# stale comment.
#
# Host rule implied by donation: after dispatching a donated entry point
# the caller MUST NOT read or re-pass the donated argument arrays — XLA
# may have reused their memory for the outputs.  All host reads go
# through the returned state/output (or host mirrors); the engine's
# builders re-materialize fresh inbox/input device arrays every step.
# Backends that cannot donate (CPU) silently copy instead; the engine
# keeps the same discipline regardless so behavior is backend-uniform.
# ---------------------------------------------------------------------------

DONATION = {
    "step_donated": {
        "argnums": (1, 2, 3),
        "params": ("state", "inbox", "inp"),
        # partition identity of the donation (analysis/partition.py,
        # PS004): XLA reuses donor memory for results, which is only
        # sound if donor and result live under the SAME sharding.  Every
        # donor class must share its declared partition with at least one
        # result class.
        "donor_classes": ("ShardState", "Inbox", "StepInput"),
        "result_classes": ("ShardState", "StepOutput"),
    },
    "serve_step_donated": {
        # the mesh dispatch entry: state, the carried device inbox and
        # the staged input are donated; the partition mask (argnum 5) is
        # cached across steps by the engine and must NOT be donated
        "module": "dragonboat_tpu/parallel/ici.py",
        "function": "jit_serve_step_donated",
        "argnums": (2, 3, 4),
        "params": ("state", "box", "inp"),
        "donor_classes": ("ShardState", "Inbox", "StepInput"),
        "result_classes": ("ShardState", "Inbox", "StepOutput"),
    },
    "cluster_step_donated": {
        # router-layout twin used by the depth-1 differential arm: same
        # donation triple as step_donated, fused with device routing
        "module": "dragonboat_tpu/core/router.py",
        "argnums": (2, 3, 4),
        "params": ("state", "inbox", "inp"),
        "donor_classes": ("ShardState", "Inbox", "StepInput"),
        "result_classes": ("ShardState", "Inbox", "StepOutput"),
    },
}


class ShardState(NamedTuple):
    """Per-shard raft state; every field has a leading [G] axis (or [G, ...])."""

    # identity / config
    replica_id: jnp.ndarray     # [G] i32 — local replica id within the shard
    seed: jnp.ndarray           # [G] i32 — PRNG stream id
    e_timeout: jnp.ndarray      # [G] i32 — election timeout in ticks
    h_timeout: jnp.ndarray      # [G] i32 — heartbeat timeout in ticks
    check_quorum: jnp.ndarray   # [G] bool
    pre_vote: jnp.ndarray       # [G] bool

    # core protocol state
    role: jnp.ndarray           # [G] i32 ∈ {FOLLOWER..WITNESS}
    term: jnp.ndarray           # [G] i32
    vote: jnp.ndarray           # [G] i32 (replica id, 0 = none)
    leader: jnp.ndarray         # [G] i32 (0 = NoLeader)
    applied: jnp.ndarray        # [G] i32 — RSM-confirmed applied index
    e_tick: jnp.ndarray         # [G] i32
    h_tick: jnp.ndarray         # [G] i32
    rand_timeout: jnp.ndarray   # [G] i32
    rand_counter: jnp.ndarray   # [G] i32 — bumps on each timeout reset
    pending_cc: jnp.ndarray     # [G] bool
    ltt: jnp.ndarray            # [G] i32 — leader-transfer target (0 none)
    is_ltt: jnp.ndarray         # [G] bool — local node is transfer target

    # peer books [G, P]
    pid: jnp.ndarray            # peer replica ids (0 = empty slot)
    kind: jnp.ndarray           # K_ABSENT/K_VOTER/K_NON_VOTING/K_WITNESS
    match: jnp.ndarray          # i32
    next: jnp.ndarray           # i32
    pstate: jnp.ndarray         # R_RETRY/R_WAIT/R_REPLICATE/R_SNAPSHOT
    active: jnp.ndarray         # bool — recent contact (checkQuorum)
    psnap: jnp.ndarray          # i32 — pending install-snapshot index
    vresp: jnp.ndarray          # bool — vote response received this election
    vgrant: jnp.ndarray         # bool — vote granted

    # log [G, CAP] ring + cursors
    lt: jnp.ndarray             # [G, CAP] i32 — term of entry at index i (slot i & (CAP-1))
    lcc: jnp.ndarray            # [G, CAP] bool — entry is a config change
    snap_index: jnp.ndarray     # [G] i32 — last snapshot index (ring floor)
    snap_term: jnp.ndarray      # [G] i32
    last: jnp.ndarray           # [G] i32
    committed: jnp.ndarray      # [G] i32
    processed: jnp.ndarray      # [G] i32 — released to the apply pipeline
    stable: jnp.ndarray         # [G] i32 — handed to the fsync pipeline

    # ReadIndex circular book [G, RI] (+ acks [G, RI, P])
    ri_low: jnp.ndarray
    ri_high: jnp.ndarray
    ri_index: jnp.ndarray
    ri_acks: jnp.ndarray        # [G, RI, P] bool
    ri_head: jnp.ndarray        # [G] i32
    ri_count: jnp.ndarray       # [G] i32

    # host-escalation flag: shard touched a path the kernel does not model
    # (e.g. a peer needs an InstallSnapshot stream) — host must intervene
    needs_host: jnp.ndarray     # [G] bool

    # device quiesce (quiesce.go state machine folded into the step):
    # an enabled lane idle for e_timeout*10 ticks raises its quiesced
    # mask and stops taking live ticks (no elections, no heartbeats);
    # any non-heartbeat inbox or client activity wakes it and bumps
    # quiesce_epoch (the wake counter the quiesce invariants key on)
    quiesce_on: jnp.ndarray     # [G] bool — per-lane enable (Config.quiesce)
    idle_tick: jnp.ndarray      # [G] i32 — ticks since last activity
    quiesced: jnp.ndarray       # [G] bool — device-resident quiesced mask
    quiesce_epoch: jnp.ndarray  # [G] i32 — wakes so far (monotone)

    # inline payload slot ring [G, CAP] i32 (SURVEY §7: small fixed-width
    # values on device; bigger payloads stay host-side keyed by index).
    # None unless kp.inline_payloads — the plain path carries no ring.
    lv: jnp.ndarray | None = None


def init_state(
    kp: P.KernelParams,
    num_shards: int,
    replica_id,
    peer_ids,
    peer_kinds=None,
    election_timeout: int = 10,
    heartbeat_timeout: int = 1,
    check_quorum: bool = False,
    pre_vote: bool = False,
    seeds=None,
    quiesce: bool = False,
) -> ShardState:
    """Build a fresh [G] state.

    ``replica_id``: scalar or [G] — the local replica id per shard.
    ``peer_ids``: [P] or [G, P] replica ids (0 marks an empty slot).
    ``peer_kinds``: same shape, defaults to K_VOTER for non-empty slots.
    """
    G, Pn, CAP, RI = num_shards, kp.num_peers, kp.log_cap, kp.readindex_cap
    z = lambda *s: np.zeros((G, *s), np.int32)  # noqa: E731
    zb = lambda *s: np.zeros((G, *s), bool)  # noqa: E731

    rid = np.broadcast_to(np.asarray(replica_id, np.int32), (G,)).copy()
    pids = np.asarray(peer_ids, np.int32)
    if pids.ndim == 1:
        pids = np.broadcast_to(pids, (G, Pn)).copy()
    if peer_kinds is None:
        kinds = np.where(pids != 0, P.K_VOTER, P.K_ABSENT).astype(np.int32)
    else:
        kinds = np.asarray(peer_kinds, np.int32)
        if kinds.ndim == 1:
            kinds = np.broadcast_to(kinds, (G, Pn)).copy()
    if seeds is None:
        seeds = (
            np.arange(1, G + 1, dtype=np.int64) * 2654435761 % (1 << 31)
            + rid.astype(np.int64) * 40503
        ) % (1 << 31)
        seeds = seeds.astype(np.int32)
    et = np.full((G,), election_timeout, np.int32)
    rand0 = np.asarray(
        [
            P.randomized_timeout(int(seeds[g]), 0, int(et[g]))
            for g in range(G)
        ],
        np.int32,
    )

    is_nv = np.zeros((G,), bool)
    is_wt = np.zeros((G,), bool)
    for g in range(G):
        slot = np.nonzero(pids[g] == rid[g])[0]
        if slot.size:
            is_nv[g] = kinds[g, slot[0]] == P.K_NON_VOTING
            is_wt[g] = kinds[g, slot[0]] == P.K_WITNESS
    role = np.where(is_wt, P.WITNESS, np.where(is_nv, P.NON_VOTING, P.FOLLOWER))

    return ShardState(
        replica_id=jnp.asarray(rid),
        seed=jnp.asarray(seeds, jnp.int32),
        e_timeout=jnp.asarray(et),
        h_timeout=jnp.full((G,), heartbeat_timeout, jnp.int32),
        check_quorum=jnp.full((G,), check_quorum, bool),
        pre_vote=jnp.full((G,), pre_vote, bool),
        role=jnp.asarray(role.astype(np.int32)),
        term=jnp.asarray(z()),
        vote=jnp.asarray(z()),
        leader=jnp.asarray(z()),
        applied=jnp.asarray(z()),
        e_tick=jnp.asarray(z()),
        h_tick=jnp.asarray(z()),
        rand_timeout=jnp.asarray(rand0),
        rand_counter=jnp.asarray(z()),
        pending_cc=jnp.asarray(zb()),
        ltt=jnp.asarray(z()),
        is_ltt=jnp.asarray(zb()),
        pid=jnp.asarray(pids),
        kind=jnp.asarray(kinds),
        match=jnp.asarray(z(Pn)),
        next=jnp.asarray(z(Pn) + 1),
        pstate=jnp.asarray(z(Pn)),
        active=jnp.asarray(zb(Pn)),
        psnap=jnp.asarray(z(Pn)),
        vresp=jnp.asarray(zb(Pn)),
        vgrant=jnp.asarray(zb(Pn)),
        lt=jnp.asarray(z(CAP)),
        lcc=jnp.asarray(zb(CAP)),
        lv=jnp.asarray(z(CAP)) if kp.inline_payloads else None,
        snap_index=jnp.asarray(z()),
        snap_term=jnp.asarray(z()),
        last=jnp.asarray(z()),
        committed=jnp.asarray(z()),
        processed=jnp.asarray(z()),
        stable=jnp.asarray(z()),
        ri_low=jnp.asarray(z(RI)),
        ri_high=jnp.asarray(z(RI)),
        ri_index=jnp.asarray(z(RI)),
        ri_acks=jnp.asarray(zb(RI, Pn)),
        ri_head=jnp.asarray(z()),
        ri_count=jnp.asarray(z()),
        needs_host=jnp.asarray(zb()),
        quiesce_on=jnp.full((G,), quiesce, bool),
        idle_tick=jnp.asarray(z()),
        quiesced=jnp.asarray(zb()),
        quiesce_epoch=jnp.asarray(z()),
    )


class Inbox(NamedTuple):
    """Fixed-width inbound message block, [G, K] lanes (+ [G, K, E] entries).

    Message fields mirror raftpb.Message (message.go:6-20) minus snapshots —
    InstallSnapshot and ConfigChangeEvent are host-mediated and never enter
    the kernel."""

    mtype: jnp.ndarray      # i32 (NOOP = empty slot when from == 0)
    from_: jnp.ndarray      # i32 replica id (0 = empty slot)
    term: jnp.ndarray
    log_term: jnp.ndarray
    log_index: jnp.ndarray
    commit: jnp.ndarray
    reject: jnp.ndarray     # bool
    hint: jnp.ndarray
    hint_high: jnp.ndarray
    n_ent: jnp.ndarray      # i32 — entries carried (replicate)
    ent_term: jnp.ndarray   # [G, K, E] i32
    ent_cc: jnp.ndarray     # [G, K, E] bool
    # inline payload lanes; None (default) when the sender keeps payloads
    # host-side (the kernel substitutes zeros)
    ent_val: jnp.ndarray | None = None


def empty_inbox(kp: P.KernelParams, num_shards: int) -> Inbox:
    G, K, E = num_shards, kp.inbox_cap, kp.msg_entries
    z = lambda *s: jnp.zeros((G, *s), jnp.int32)  # noqa: E731
    # ent_val is materialized only under inline_payloads so the
    # self-driving loop's carry matches route()'s output structure
    return Inbox(
        mtype=z(K), from_=z(K), term=z(K), log_term=z(K), log_index=z(K),
        commit=z(K), reject=jnp.zeros((G, K), bool), hint=z(K), hint_high=z(K),
        n_ent=z(K), ent_term=z(K, E), ent_cc=jnp.zeros((G, K, E), bool),
        ent_val=z(K, E) if kp.inline_payloads else None,
    )


class StepInput(NamedTuple):
    """Everything a shard consumes in one step besides its inbox."""

    # proposals [G, B]: valid + is-config-change marker; payloads stay host-side
    prop_valid: jnp.ndarray     # [G, B] bool
    prop_cc: jnp.ndarray        # [G, B] bool
    # batched ReadIndex request (host batches all pending reads into one ctx
    # per shard per step, mirroring node.handleReadIndex's batch ctx)
    ri_valid: jnp.ndarray       # [G] bool
    ri_low: jnp.ndarray         # [G] i32
    ri_high: jnp.ndarray        # [G] i32
    # leadership transfer request (0 = none)
    transfer_to: jnp.ndarray    # [G] i32
    # clock
    tick: jnp.ndarray           # [G] bool — advance the logical clock
    quiesced: jnp.ndarray       # [G] bool — tick in quiesced mode
    # host acks: RSM applied cursor (monotonic)
    applied: jnp.ndarray        # [G] i32
    # inline proposal payloads (device-SM path); None = host-side payloads
    prop_val: jnp.ndarray | None = None


def empty_input(kp: P.KernelParams, num_shards: int) -> StepInput:
    G, B = num_shards, kp.proposal_cap
    z = lambda *s: jnp.zeros((G, *s), jnp.int32)  # noqa: E731
    zb = lambda *s: jnp.zeros((G, *s), bool)  # noqa: E731
    return StepInput(
        prop_valid=zb(B), prop_cc=zb(B),
        ri_valid=zb(), ri_low=z(), ri_high=z(),
        transfer_to=z(), tick=zb(), quiesced=zb(), applied=z(),
    )


class StepOutput(NamedTuple):
    """Per-shard, per-step results (the device-side pb.Update contract —
    update.go:74-112 re-expressed as fixed lanes)."""

    # responses to inbox slots [G, K]
    r_type: jnp.ndarray     # i32 (0 = none; NoOP uses its real enum value)
    r_to: jnp.ndarray
    r_term: jnp.ndarray
    r_log_index: jnp.ndarray
    r_reject: jnp.ndarray   # bool
    r_hint: jnp.ndarray
    r_hint_high: jnp.ndarray

    # replicate/vote lanes per peer [G, P]
    s_rep: jnp.ndarray      # bool — send a Replicate to this peer
    s_prev_index: jnp.ndarray
    s_prev_term: jnp.ndarray
    s_commit: jnp.ndarray
    s_n_ent: jnp.ndarray
    s_ent_term: jnp.ndarray  # [G, P, E]
    s_ent_cc: jnp.ndarray    # [G, P, E] bool
    # [G, P, E] i32 inline payload lanes; None unless kp.inline_payloads
    s_ent_val: jnp.ndarray | None
    s_vote: jnp.ndarray      # i32: 0 none, 1 RequestVote, 2 RequestPreVote
    s_vote_term: jnp.ndarray
    s_vote_lindex: jnp.ndarray
    s_vote_lterm: jnp.ndarray
    s_vote_hint: jnp.ndarray
    s_hb: jnp.ndarray        # bool — heartbeat to this peer
    s_hb_commit: jnp.ndarray
    s_hb_low: jnp.ndarray
    s_hb_high: jnp.ndarray
    s_timeout_now: jnp.ndarray  # bool
    s_need_snapshot: jnp.ndarray  # bool — host must stream a snapshot
    # bool — witness peer fell behind compaction: the host answers with a
    # stripped file-less witness snapshot (raft.go:728) WITHOUT evicting
    s_wit_snap: jnp.ndarray

    # persistence + apply pipeline [G]
    save_first: jnp.ndarray
    save_last: jnp.ndarray   # save (save_first..save_last]... inclusive range when >= first
    apply_first: jnp.ndarray
    apply_last: jnp.ndarray
    term: jnp.ndarray        # pb.State triple for SaveRaftState
    vote: jnp.ndarray
    commit: jnp.ndarray

    # ReadIndex results [G, RI]
    rtr_valid: jnp.ndarray
    rtr_index: jnp.ndarray
    rtr_low: jnp.ndarray
    rtr_high: jnp.ndarray
    # dropped batched-read request (host re-queues / fails it)
    ri_dropped: jnp.ndarray  # [G] bool

    # proposal fates [G, B]
    prop_accepted: jnp.ndarray  # bool
    prop_index: jnp.ndarray     # assigned log index
    prop_term: jnp.ndarray      # assigned term

    # events [G]
    leader: jnp.ndarray
    leader_term: jnp.ndarray
    needs_host: jnp.ndarray
