"""Device-side fleet telemetry reduction over the batched ShardState.

At 10^4–10^5 lanes, "how many shards are leaderless right now" must not
be answered by iterating shards on host — one vectorized reduction over
the resident ``ShardState`` produces a single small ``FleetStats``
struct, and a decimation knob on the engines (``fleet_stats_every``)
bounds the host transfer to one struct every N steps.

``fleet_stats`` is jitted and tracer-safe (pure jnp ops, no Python
branching on traced values); the host-side helpers below turn a fetched
struct into plain dicts and register callback gauges on a
``telemetry.Registry`` so the /metrics endpoint exposes
``fleet_role_count{role=...}`` and the cumulative lag / inbox-occupancy
bucket families.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from dragonboat_tpu.core import params as P

NUM_ROLES = 6
# index == the params.py role constant (FOLLOWER=0 .. WITNESS=5)
ROLE_NAMES = ("follower", "candidate", "pre_vote_candidate", "leader",
              "non_voting", "witness")

# cumulative `le` bounds; the +Inf bucket is implicit (== occupied)
LAG_BUCKETS = (0, 1, 2, 4, 8, 16, 32, 64)
INBOX_BUCKETS = (0, 1, 2, 4, 8)

# Partition contract for the stats struct (grammar: core/kstate.py
# CONTRACTS; checked by analysis/partition.py).  Every field is an
# aggregate over ALL groups: replicated on every device, and produced by
# an intentional cross-G collective — `collective=declared` licenses the
# cross-G reductions inside _fleet_stats_impl that the partition pass
# would otherwise flag as PS001.  Axis names: ROLES == NUM_ROLES,
# LAGB/INBOXB == len(*_BUCKETS)+1 (host-side constants, not kernel
# geometry — the shape side of this table is documentation, the
# part/collective side is machine-checked).
CONTRACTS = {
    "FleetStats": {
        "occupied": "[] i32 part=replicated collective=declared",
        "role_count": "[ROLES] i32 part=replicated collective=declared",
        "leaderless": "[] i32 part=replicated collective=declared",
        "election_active": "[] i32 part=replicated collective=declared",
        "quiesced": "[] i32 part=replicated collective=declared",
        "term_max": "[] i32 part=replicated collective=declared",
        "term_min": "[] i32 part=replicated collective=declared",
        "lag_hist": "[LAGB] i32 part=replicated collective=declared",
        "inbox_hist": "[INBOXB] i32 part=replicated collective=declared",
    },
}


def bucket_labels(bounds) -> tuple:
    return tuple(str(b) for b in bounds) + ("+Inf",)


class FleetStats(NamedTuple):
    """One host transfer's worth of fleet telemetry (all i32)."""

    occupied: jnp.ndarray         # [] — lanes with >= 1 configured peer
    role_count: jnp.ndarray       # [NUM_ROLES]
    leaderless: jnp.ndarray       # [] — occupied lanes with no known leader
    election_active: jnp.ndarray  # [] — candidates + pre-vote candidates
    quiesced: jnp.ndarray         # [] — occupied lanes masked-quiesced
    term_max: jnp.ndarray         # [] (0 when no lane is occupied)
    term_min: jnp.ndarray         # [] (0 when no lane is occupied)
    lag_hist: jnp.ndarray         # [len(LAG_BUCKETS)+1] cumulative counts
    inbox_hist: jnp.ndarray       # [len(INBOX_BUCKETS)+1] cumulative


def _fleet_stats_impl(state, inbox_from) -> FleetStats:
    i32 = jnp.int32
    occ = (state.kind != P.K_ABSENT).any(axis=1)              # [G] bool
    occ_i = occ.astype(i32)
    occupied = occ_i.sum()
    roles = jnp.arange(NUM_ROLES, dtype=state.role.dtype)
    role_count = (occ_i[:, None]
                  * (state.role[:, None] == roles[None, :]).astype(i32)
                  ).sum(axis=0)
    leaderless = (occ & (state.leader == P.NO_LEADER)).astype(i32).sum()
    election_active = (occ & ((state.role == P.CANDIDATE)
                              | (state.role == P.PRE_VOTE_CANDIDATE))
                       ).astype(i32).sum()
    quiesced = (occ & state.quiesced).astype(i32).sum()
    big = jnp.iinfo(jnp.int32).max
    term_max = jnp.where(occ, state.term, 0).max()
    term_min = jnp.where(occupied > 0,
                         jnp.where(occ, state.term, big).min(), 0)
    lag = state.committed - state.applied                     # [G] i32
    bounds = jnp.asarray(LAG_BUCKETS, i32)
    lag_le = ((lag[:, None] <= bounds[None, :])
              & occ[:, None]).astype(i32).sum(axis=0)
    lag_hist = jnp.concatenate([lag_le, occupied[None]])
    inbox_occ = (inbox_from != 0).astype(i32).sum(axis=1)     # [G]
    ibounds = jnp.asarray(INBOX_BUCKETS, i32)
    inbox_le = ((inbox_occ[:, None] <= ibounds[None, :])
                & occ[:, None]).astype(i32).sum(axis=0)
    inbox_hist = jnp.concatenate([inbox_le, occupied[None]])
    return FleetStats(
        occupied=occupied, role_count=role_count, leaderless=leaderless,
        election_active=election_active, quiesced=quiesced,
        term_max=term_max,
        term_min=term_min, lag_hist=lag_hist, inbox_hist=inbox_hist)


fleet_stats = jax.jit(_fleet_stats_impl)


def stats_to_dict(stats: FleetStats) -> dict:
    """Fetch to host and flatten into plain ints/dicts — the shape the
    callback gauges (and ``engine.last_fleet``) serve."""
    s = jax.device_get(stats)
    lag_labels = bucket_labels(LAG_BUCKETS)
    inbox_labels = bucket_labels(INBOX_BUCKETS)
    return {
        "occupied": int(s.occupied),
        "role_count": {ROLE_NAMES[i]: int(s.role_count[i])
                       for i in range(NUM_ROLES)},
        "leaderless": int(s.leaderless),
        "election_active": int(s.election_active),
        "quiesced": int(s.quiesced),
        "term_max": int(s.term_max),
        "term_min": int(s.term_min),
        "lag_hist": {lab: int(s.lag_hist[i])
                     for i, lab in enumerate(lag_labels)},
        "inbox_hist": {lab: int(s.inbox_hist[i])
                       for i, lab in enumerate(inbox_labels)},
    }


def empty_dict() -> dict:
    """All-zero fleet dict (merge identity for hosts with no engine)."""
    return {
        "occupied": 0,
        "role_count": {r: 0 for r in ROLE_NAMES},
        "leaderless": 0,
        "election_active": 0,
        "quiesced": 0,
        "term_max": 0,
        "term_min": 0,
        "lag_hist": {lab: 0 for lab in bucket_labels(LAG_BUCKETS)},
        "inbox_hist": {lab: 0 for lab in bucket_labels(INBOX_BUCKETS)},
    }


def merge_into(base: dict, other: dict) -> None:
    """Accumulate ``other`` (same shape as ``empty_dict``) into
    ``base``: counts add, term_max maxes, term_min mins over nonzero."""
    base["occupied"] += other["occupied"]
    base["leaderless"] += other["leaderless"]
    base["election_active"] += other["election_active"]
    base["quiesced"] += other.get("quiesced", 0)
    base["term_max"] = max(base["term_max"], other["term_max"])
    mins = [m for m in (base["term_min"], other["term_min"]) if m > 0]
    base["term_min"] = min(mins) if mins else 0
    for k in base["role_count"]:
        base["role_count"][k] += other["role_count"].get(k, 0)
    for k in base["lag_hist"]:
        base["lag_hist"][k] += other["lag_hist"].get(k, 0)
    for k in base["inbox_hist"]:
        base["inbox_hist"][k] += other["inbox_hist"].get(k, 0)


def add_host_shard(base: dict, role: str, leaderless: bool, term: int,
                   lag: int, quiesced: bool = False) -> None:
    """Fold one HOST-resident (non-kernel) replica into a fleet dict —
    host clusters have no device state to reduce, but the /metrics
    surface must still answer role/leaderless/lag questions."""
    base["occupied"] += 1
    if role in base["role_count"]:
        base["role_count"][role] += 1
    if leaderless:
        base["leaderless"] += 1
    if role in ("candidate", "pre_vote_candidate"):
        base["election_active"] += 1
    if quiesced:
        base["quiesced"] += 1
    if term > 0:
        base["term_max"] = max(base["term_max"], term)
        base["term_min"] = (term if base["term_min"] == 0
                            else min(base["term_min"], term))
    for bound in LAG_BUCKETS:
        if lag <= bound:
            base["lag_hist"][str(bound)] += 1
    base["lag_hist"]["+Inf"] += 1
    # a host replica's inbox is the Python queue, drained every step:
    # occupancy 0 lands in every cumulative bucket
    for bound in INBOX_BUCKETS:
        base["inbox_hist"][str(bound)] += 1
    base["inbox_hist"]["+Inf"] += 1


def register_exposition(registry, source, replace: bool = False) -> None:
    """Register the fleet callback-gauge families on ``registry``,
    backed by ``source()`` -> fleet dict (or None for "no data yet").

    Idempotent when ``replace`` is False: an already-registered family
    set (e.g. the owning NodeHost's merged view) is left alone, so a
    standalone engine can offer its device-only view without fighting a
    host that registered first.  ``replace=True`` re-points the
    callbacks (host restart)."""
    if not replace and registry.kind_of("fleet_role_count") is not None:
        return

    def _get() -> dict:
        d = source()
        return d if d is not None else empty_dict()

    registry.gauge_fn(
        "fleet_role_count",
        lambda: {(r,): _get()["role_count"][r] for r in ROLE_NAMES},
        help="occupied shards per raft role", labelnames=("role",))
    registry.gauge_fn("fleet.occupied_shards",
                      lambda: _get()["occupied"],
                      help="lanes with at least one configured peer")
    registry.gauge_fn("fleet.leaderless_shards",
                      lambda: _get()["leaderless"],
                      help="occupied shards with no known leader")
    registry.gauge_fn("fleet.election_active",
                      lambda: _get()["election_active"],
                      help="shards currently campaigning")
    registry.gauge_fn("fleet.quiesced_shards",
                      lambda: _get().get("quiesced", 0),
                      help="occupied shards in masked quiesce")
    registry.gauge_fn("fleet.term_max", lambda: _get()["term_max"],
                      help="max raft term over occupied shards")
    registry.gauge_fn("fleet.term_min", lambda: _get()["term_min"],
                      help="min raft term over occupied shards")
    registry.gauge_fn(
        "fleet_commit_lag_bucket",
        lambda: {(lab,): _get()["lag_hist"][lab]
                 for lab in bucket_labels(LAG_BUCKETS)},
        help="cumulative commit-applied lag distribution",
        labelnames=("le",))
    registry.gauge_fn(
        "fleet_inbox_occupancy_bucket",
        lambda: {(lab,): _get()["inbox_hist"][lab]
                 for lab in bucket_labels(INBOX_BUCKETS)},
        help="cumulative inbox slot occupancy distribution",
        labelnames=("le",))
