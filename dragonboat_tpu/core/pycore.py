"""Full-fidelity single-shard Raft core (host Python).

This is the message-in/state-out protocol engine with the same observable
behavior as the reference's ``internal/raft/raft.go`` (6 states × 29 message
types, pre-vote, check-quorum leases, pipelined replication with per-remote
flow control, ReadIndex, one-at-a-time membership change, leadership
transfer, witness/non-voting members).  It is used as:

1. the conformance anchor — the etcd-derived test suites run against it;
2. the host slow path — variable-width ops (snapshot install, membership
   restore) operate on per-shard state extracted from the device kernel;
3. the differential-test oracle for :mod:`dragonboat_tpu.core.kernel`.

Behavioral citations point into ``/root/reference/internal/raft/`` — this is
a re-implementation from the protocol's documented behavior, not a port of
its goroutine/alloc patterns.
"""

from __future__ import annotations

import enum
import random as _random
from dataclasses import dataclass, field, replace
from typing import Callable

from dragonboat_tpu import raftpb as pb
from dragonboat_tpu.core.logentry import (
    CompactedError,
    EntryLog,
    ILogDBReader,
)

NO_LEADER = 0
NO_NODE = 0

# Entry-batch cap when replicating (reference soft.MaxEntrySize is 2MB;
# we cap by byte size the same way).
MAX_ENTRY_SIZE = 2 * 1024 * 1024


class RaftState(enum.IntEnum):
    """Parity: internal/raft/raft.go:63-71 (six states)."""

    FOLLOWER = 0
    CANDIDATE = 1
    PRE_VOTE_CANDIDATE = 2
    LEADER = 3
    NON_VOTING = 4
    WITNESS = 5


class RemoteState(enum.IntEnum):
    """Per-peer replication flow control — parity internal/raft/remote.go:52-70."""

    RETRY = 0
    WAIT = 1
    REPLICATE = 2
    SNAPSHOT = 3


@dataclass
class Remote:
    """Follower progress tracked by the leader — parity internal/raft/remote.go:72."""

    match: int = 0
    next: int = 0
    snapshot_index: int = 0
    state: RemoteState = RemoteState.RETRY
    active: bool = False
    delayed_ack_tick: int = 0
    delayed_ack_rejected: bool = False

    def clear_snapshot_ack(self) -> None:
        self.delayed_ack_tick = 0
        self.delayed_ack_rejected = False

    def set_snapshot_ack(self, tick: int, rejected: bool) -> None:
        assert self.state == RemoteState.SNAPSHOT
        self.delayed_ack_tick = tick
        self.delayed_ack_rejected = rejected

    def ack_tick(self) -> bool:
        if self.delayed_ack_tick > 0:
            self.delayed_ack_tick -= 1
            return self.delayed_ack_tick == 0
        return False

    def become_retry(self) -> None:
        if self.state == RemoteState.SNAPSHOT:
            self.next = max(self.match + 1, self.snapshot_index + 1)
        else:
            self.next = self.match + 1
        self.snapshot_index = 0
        self.state = RemoteState.RETRY

    def retry_to_wait(self) -> None:
        if self.state == RemoteState.RETRY:
            self.state = RemoteState.WAIT

    def wait_to_retry(self) -> None:
        if self.state == RemoteState.WAIT:
            self.state = RemoteState.RETRY

    def become_wait(self) -> None:
        self.clear_snapshot_ack()
        self.become_retry()
        self.retry_to_wait()

    def become_replicate(self) -> None:
        self.next = self.match + 1
        self.snapshot_index = 0
        self.state = RemoteState.REPLICATE

    def become_snapshot(self, index: int) -> None:
        self.snapshot_index = index
        self.state = RemoteState.SNAPSHOT

    def clear_pending_snapshot(self) -> None:
        self.snapshot_index = 0

    def try_update(self, index: int) -> bool:
        if self.next < index + 1:
            self.next = index + 1
        if self.match < index:
            self.wait_to_retry()
            self.match = index
            return True
        return False

    def progress(self, last_index: int) -> None:
        """Optimistic pipelined advance at send time — remote.go:progress."""
        if self.state == RemoteState.REPLICATE:
            self.next = last_index + 1
        elif self.state == RemoteState.RETRY:
            self.retry_to_wait()
        else:
            raise AssertionError(f"progress() in state {self.state}")

    def responded_to(self) -> None:
        if self.state == RemoteState.RETRY:
            self.become_replicate()
        elif self.state == RemoteState.SNAPSHOT:
            if self.match >= self.snapshot_index:
                self.become_retry()

    def decrease_to(self, rejected: int, last: int) -> bool:
        """Backtrack next on rejection — remote.go:decreaseTo (etcd-derived,
        resets next to match+1, more conservative than thesis p21)."""
        if self.state == RemoteState.REPLICATE:
            if rejected <= self.match:
                return False
            self.next = self.match + 1
            return True
        if self.next - 1 != rejected:
            return False
        self.wait_to_retry()
        self.next = max(1, min(rejected, last + 1))
        return True

    def is_paused(self) -> bool:
        return self.state in (RemoteState.WAIT, RemoteState.SNAPSHOT)


@dataclass
class _ReadStatus:
    index: int
    from_: int
    ctx: pb.SystemCtx
    confirmed: set[int] = field(default_factory=set)


class ReadIndexBook:
    """FIFO of pending ReadIndex contexts — parity internal/raft/readindex.go:30."""

    def __init__(self) -> None:
        self.pending: dict[pb.SystemCtx, _ReadStatus] = {}
        self.queue: list[pb.SystemCtx] = []

    def add_request(self, index: int, ctx: pb.SystemCtx, from_: int) -> None:
        if ctx in self.pending:
            return
        self.pending[ctx] = _ReadStatus(index=index, from_=from_, ctx=ctx)
        self.queue.append(ctx)

    def has_pending_request(self) -> bool:
        return bool(self.queue)

    def peep_ctx(self) -> pb.SystemCtx:
        return self.queue[-1]

    def confirm(self, ctx: pb.SystemCtx, from_: int, quorum: int) -> list[_ReadStatus]:
        """Record an ack; once quorum reached, pop every ctx at-or-before it —
        parity readindex.go:73."""
        status = self.pending.get(ctx)
        if status is None:
            return []
        status.confirmed.add(from_)
        if len(status.confirmed) + 1 < quorum:
            return []
        done = 0
        out: list[_ReadStatus] = []
        for c in self.queue:
            done += 1
            s = self.pending[c]
            out.append(s)
            if c == ctx:
                break
        else:
            return []
        self.queue = self.queue[done:]
        for s in out:
            del self.pending[s.ctx]
        return out


@dataclass
class CoreConfig:
    """Protocol knobs for one shard — mirrors config.Config's raft-relevant
    fields (config/config.go:58-198)."""

    shard_id: int = 0
    replica_id: int = 0
    election_rtt: int = 10
    heartbeat_rtt: int = 1
    check_quorum: bool = False
    pre_vote: bool = False
    is_non_voting: bool = False
    is_witness: bool = False
    quiesce: bool = False
    max_entry_size: int = MAX_ENTRY_SIZE
    # count cap per replicate message (the kernel's fixed E entry lanes);
    # None = byte cap only.  The differential harness sets this to the
    # kernel's msg_entries so catch-up proceeds in lockstep — otherwise a
    # lagging follower refills at different rates on the two engines and
    # an election mid-catch-up diverges (found by the seed soak)
    max_entries_per_msg: int | None = None


class Raft:
    """The deterministic raft protocol state machine for one shard."""

    def __init__(
        self,
        cfg: CoreConfig,
        logdb: ILogDBReader,
        rng: Callable[[int], int] | None = None,
    ) -> None:
        self.cfg = cfg
        self.shard_id = cfg.shard_id
        self.replica_id = cfg.replica_id
        self.log = EntryLog(logdb)
        self.term = 0
        self.vote = NO_NODE
        self.leader_id = NO_LEADER
        self.applied = logdb.first_index() - 1
        self.state = RaftState.FOLLOWER
        self.remotes: dict[int, Remote] = {}
        self.non_votings: dict[int, Remote] = {}
        self.witnesses: dict[int, Remote] = {}
        self.votes: dict[int, bool] = {}
        self.msgs: list[pb.Message] = []
        self.dropped_entries: list[pb.Entry] = []
        self.dropped_read_indexes: list[pb.SystemCtx] = []
        self.ready_to_read: list[pb.ReadyToRead] = []
        self.read_index = ReadIndexBook()
        self.pending_config_change = False
        self.leader_transfer_target = NO_NODE
        self.is_leader_transfer_target = False
        self.election_tick = 0
        self.heartbeat_tick = 0
        self.tick_count = 0
        self.election_timeout = cfg.election_rtt
        self.heartbeat_timeout = cfg.heartbeat_rtt
        self.randomized_election_timeout = 0
        self.check_quorum = cfg.check_quorum
        self.pre_vote = cfg.pre_vote
        self.quiesce = False
        self.snapshotting = False
        self.leader_update: pb.LeaderUpdate | None = None
        self.log_query_result: pb.LogQueryResult | None = None
        # injectable randomness: rng(n) -> uniform int in [0, n)
        self._rng: Callable[[int], int] = rng if rng is not None else (
            lambda n: _random.randrange(n)
        )
        # test hook mirroring the reference's hasNotAppliedConfigChange
        self.has_not_applied_config_change: Callable[[], bool] | None = None
        self.set_randomized_election_timeout()

    # ------------------------------------------------------------------
    # setup / persisted-state restore (parity raft.go:241-297 newRaft)
    # ------------------------------------------------------------------

    def load_state(self, st: pb.State) -> None:
        if st.commit < self.log.committed or st.commit > self.log.last_index():
            raise AssertionError(f"out of range commit {st.commit}")
        self.term = st.term
        self.vote = st.vote
        self.log.committed = st.commit

    def set_initial_members(self, members: dict[int, str],
                            non_votings: dict[int, str] | None = None,
                            witnesses: dict[int, str] | None = None) -> None:
        next_idx = self.log.last_index() + 1
        for rid in members:
            self.remotes[rid] = Remote(next=next_idx)
        for rid in (non_votings or {}):
            self.non_votings[rid] = Remote(next=next_idx)
        for rid in (witnesses or {}):
            self.witnesses[rid] = Remote(next=next_idx)
        if self.cfg.is_non_voting or self.replica_id in self.non_votings:
            self.state = RaftState.NON_VOTING
        if self.cfg.is_witness or self.replica_id in self.witnesses:
            self.state = RaftState.WITNESS

    # ------------------------------------------------------------------
    # role predicates / quorum helpers
    # ------------------------------------------------------------------

    def is_leader(self) -> bool:
        return self.state == RaftState.LEADER

    def is_follower(self) -> bool:
        return self.state == RaftState.FOLLOWER

    def is_candidate(self) -> bool:
        return self.state == RaftState.CANDIDATE

    def is_pre_vote_candidate(self) -> bool:
        return self.state == RaftState.PRE_VOTE_CANDIDATE

    def is_non_voting(self) -> bool:
        return self.state == RaftState.NON_VOTING

    def is_witness(self) -> bool:
        return self.state == RaftState.WITNESS

    def voting_members(self) -> dict[int, Remote]:
        out = dict(self.remotes)
        out.update(self.witnesses)
        return out

    def num_voting_members(self) -> int:
        return len(self.remotes) + len(self.witnesses)

    def quorum(self) -> int:
        return self.num_voting_members() // 2 + 1

    def is_single_node_quorum(self) -> bool:
        return self.quorum() == 1

    def leader_has_quorum(self) -> bool:
        """Parity raft.go:395 — counts recently-active voters, resetting
        activity records."""
        c = 0
        for rid, member in self.voting_members().items():
            if rid == self.replica_id or member.active:
                c += 1
            member.active = False
        return c >= self.quorum()

    def self_removed(self) -> bool:
        if self.is_non_voting():
            return self.replica_id not in self.non_votings
        if self.is_witness():
            return self.replica_id not in self.witnesses
        return self.replica_id not in self.remotes

    def nodes(self) -> list[int]:
        return list(self.remotes) + list(self.non_votings) + list(self.witnesses)

    def get_remote(self, rid: int) -> Remote | None:
        return (
            self.remotes.get(rid)
            or self.non_votings.get(rid)
            or self.witnesses.get(rid)
        )

    # ------------------------------------------------------------------
    # tick (parity raft.go:540-680)
    # ------------------------------------------------------------------

    def time_for_election(self) -> bool:
        return self.election_tick >= self.randomized_election_timeout

    def time_for_heartbeat(self) -> bool:
        return self.heartbeat_tick >= self.heartbeat_timeout

    def time_for_check_quorum(self) -> bool:
        return self.election_tick >= self.election_timeout

    def time_to_abort_leader_transfer(self) -> bool:
        return self.leader_transfering() and self.election_tick >= self.election_timeout

    def tick(self) -> None:
        self.quiesce = False
        self.tick_count += 1
        if self.is_leader():
            self.leader_tick()
        else:
            self.non_leader_tick()

    def non_leader_tick(self) -> None:
        assert not self.is_leader()
        self.election_tick += 1
        # section 4.2.1 of the raft thesis: non-voting/witness never campaign
        if self.is_non_voting() or self.is_witness():
            return
        if not self.self_removed() and self.time_for_election():
            self.election_tick = 0
            self.handle(pb.Message(from_=self.replica_id, type=pb.MessageType.ELECTION))

    def leader_tick(self) -> None:
        assert self.is_leader()
        self.election_tick += 1
        time_to_abort = self.time_to_abort_leader_transfer()
        if self.time_for_check_quorum():
            self.election_tick = 0
            if self.check_quorum:
                self.handle(
                    pb.Message(from_=self.replica_id, type=pb.MessageType.CHECK_QUORUM)
                )
        if time_to_abort:
            self.abort_leader_transfer()
        self.heartbeat_tick += 1
        if self.time_for_heartbeat():
            self.heartbeat_tick = 0
            self.handle(
                pb.Message(from_=self.replica_id, type=pb.MessageType.LEADER_HEARTBEAT)
            )
        self.check_pending_snapshot_ack()

    def quiesced_tick(self) -> None:
        if not self.quiesce:
            self.quiesce = True
        self.election_tick += 1

    def set_randomized_election_timeout(self) -> None:
        self.randomized_election_timeout = (
            self.election_timeout + self._rng(self.election_timeout)
        )

    # ------------------------------------------------------------------
    # send helpers (parity raft.go:666-700)
    # ------------------------------------------------------------------

    def _finalize_message_term(self, m: pb.Message) -> pb.Message:
        is_rv = m.type in (pb.MessageType.REQUEST_VOTE, pb.MessageType.REQUEST_PREVOTE)
        is_req = m.type in (
            pb.MessageType.PROPOSE,
            pb.MessageType.READ_INDEX,
            pb.MessageType.LEADER_TRANSFER,
        )
        if not is_req and not is_rv and m.type != pb.MessageType.REQUEST_PREVOTE_RESP:
            m = replace(m, term=self.term)
        return m

    def send(self, m: pb.Message) -> None:
        m = replace(m, from_=self.replica_id, shard_id=self.shard_id)
        m = self._finalize_message_term(m)
        self.msgs.append(m)

    # ------------------------------------------------------------------
    # replication senders (parity raft.go:713-880)
    # ------------------------------------------------------------------

    def make_install_snapshot_message(self, to: int) -> pb.Message:
        ss = self.log.snapshot()
        if ss.is_empty():
            raise AssertionError("empty snapshot")
        if to in self.witnesses:
            ss = replace(ss, filepath="", file_size=0, files=(), witness=True,
                         dummy=False)
        return pb.Message(to=to, type=pb.MessageType.INSTALL_SNAPSHOT, snapshot=ss)

    def make_replicate_message(self, to: int, next_: int, max_size: int) -> pb.Message:
        term = self.log.term(next_ - 1)  # raises CompactedError when gone
        entries = self.log.entries_from(next_, max_size)
        if self.cfg.max_entries_per_msg is not None:
            entries = entries[: self.cfg.max_entries_per_msg]
        if to in self.witnesses:
            # witnesses receive metadata-only entries (raft.go:770 makeMetadataEntries)
            entries = [
                e if e.type == pb.EntryType.CONFIG_CHANGE
                else pb.Entry(term=e.term, index=e.index, type=pb.EntryType.METADATA)
                for e in entries
            ]
        return pb.Message(
            to=to,
            type=pb.MessageType.REPLICATE,
            log_index=next_ - 1,
            log_term=term,
            entries=tuple(entries),
            commit=self.log.committed,
        )

    def send_replicate_message(self, to: int) -> None:
        rp = self.get_remote(to)
        if rp is None:
            raise AssertionError(f"no remote for {to}")
        if rp.is_paused():
            return
        try:
            m = self.make_replicate_message(to, rp.next, self.cfg.max_entry_size)
        except CompactedError:
            # log truncated: send snapshot instead (raft.go:800-812)
            if not rp.active:
                return
            m = self.make_install_snapshot_message(to)
            rp.become_snapshot(m.snapshot.index)
            self.send(m)
            return
        if m.entries:
            rp.progress(m.entries[-1].index)
        self.send(m)

    def broadcast_replicate_message(self) -> None:
        assert self.is_leader()
        for rid in self.nodes():
            if rid != self.replica_id:
                self.send_replicate_message(rid)

    def send_heartbeat_message(self, to: int, hint: pb.SystemCtx, match: int) -> None:
        self.send(
            pb.Message(
                to=to,
                type=pb.MessageType.HEARTBEAT,
                commit=min(match, self.log.committed),
                hint=hint.low,
                hint_high=hint.high,
            )
        )

    def broadcast_heartbeat_message(self) -> None:
        assert self.is_leader()
        if self.read_index.has_pending_request():
            self.broadcast_heartbeat_with_hint(self.read_index.peep_ctx())
        else:
            self.broadcast_heartbeat_with_hint(pb.SystemCtx())

    def broadcast_heartbeat_with_hint(self, ctx: pb.SystemCtx) -> None:
        zero = pb.SystemCtx()
        for rid, rm in self.voting_members().items():
            if rid != self.replica_id:
                self.send_heartbeat_message(rid, ctx, rm.match)
        if ctx == zero:
            for rid, rm in self.non_votings.items():
                self.send_heartbeat_message(rid, zero, rm.match)

    def send_timeout_now_message(self, rid: int) -> None:
        self.send(pb.Message(type=pb.MessageType.TIMEOUT_NOW, to=rid))

    # ------------------------------------------------------------------
    # append / commit (parity raft.go:884-958)
    # ------------------------------------------------------------------

    def try_commit(self) -> bool:
        assert self.is_leader()
        matched = sorted(
            [v.match for v in self.remotes.values()]
            + [v.match for v in self.witnesses.values()]
        )
        q = matched[self.num_voting_members() - self.quorum()]
        return self.log.try_commit(q, self.term)

    def append_entries(self, entries: list[pb.Entry]) -> None:
        last = self.log.last_index()
        stamped = [
            replace(e, term=self.term, index=last + 1 + i)
            for i, e in enumerate(entries)
        ]
        self.log.append(stamped)
        self.remotes[self.replica_id].try_update(self.log.last_index())
        if self.is_single_node_quorum():
            self.try_commit()

    # ------------------------------------------------------------------
    # state transitions (parity raft.go:960-1130)
    # ------------------------------------------------------------------

    def set_leader_id(self, leader_id: int) -> None:
        self.leader_id = leader_id
        self.leader_update = pb.LeaderUpdate(leader_id=leader_id, term=self.term)

    def reset(self, term: int, reset_election_timeout: bool) -> None:
        if self.term != term:
            self.term = term
            self.vote = NO_LEADER
        if reset_election_timeout:
            self.election_tick = 0
            self.set_randomized_election_timeout()
        self.votes = {}
        self.heartbeat_tick = 0
        self.read_index = ReadIndexBook()
        self.pending_config_change = False
        self.abort_leader_transfer()
        last = self.log.last_index()
        for group in (self.remotes, self.non_votings, self.witnesses):
            for rid in group:
                group[rid] = Remote(next=last + 1)
                if rid == self.replica_id:
                    group[rid].match = last

    def become_follower(self, term: int, leader_id: int,
                        reset_election_timeout: bool = True) -> None:
        if self.is_witness():
            raise AssertionError("witness becoming follower")
        self.state = RaftState.FOLLOWER
        self.reset(term, reset_election_timeout)
        self.set_leader_id(leader_id)

    def become_non_voting(self, term: int, leader_id: int) -> None:
        assert self.is_non_voting()
        self.reset(term, True)
        self.set_leader_id(leader_id)

    def become_witness(self, term: int, leader_id: int) -> None:
        assert self.is_witness()
        self.reset(term, True)
        self.set_leader_id(leader_id)

    def become_pre_vote_candidate(self) -> None:
        assert self.pre_vote
        assert not self.is_leader()
        assert not self.is_non_voting() and not self.is_witness()
        self.state = RaftState.PRE_VOTE_CANDIDATE
        self.reset(self.term, True)
        self.set_leader_id(NO_LEADER)

    def become_candidate(self) -> None:
        assert not self.is_leader()
        assert not self.is_non_voting() and not self.is_witness()
        self.state = RaftState.CANDIDATE
        # 2nd paragraph section 5.2 of the raft paper
        self.reset(self.term + 1, True)
        self.set_leader_id(NO_LEADER)
        self.vote = self.replica_id

    def become_leader(self) -> None:
        assert self.is_leader() or self.is_candidate()
        self.state = RaftState.LEADER
        self.reset(self.term, True)
        self.set_leader_id(self.replica_id)
        # restore the pending-config-change flag from the unapplied log tail
        n = self.get_pending_config_change_count()
        if n > 1:
            raise AssertionError("multiple uncommitted config changes")
        if n == 1:
            self.pending_config_change = True
        # p72 of the raft thesis: append an empty entry on promotion
        self.append_entries([pb.Entry(type=pb.EntryType.APPLICATION)])

    def get_pending_config_change_count(self) -> int:
        idx = self.log.committed + 1
        count = 0
        while True:
            ents = self.log.entries_from(idx)
            if not ents:
                return count
            count += sum(1 for e in ents if e.type == pb.EntryType.CONFIG_CHANGE)
            idx = ents[-1].index + 1

    # ------------------------------------------------------------------
    # elections (parity raft.go:1125-1260)
    # ------------------------------------------------------------------

    def handle_vote_resp(self, from_: int, rejected: bool, prevote: bool) -> int:
        if from_ not in self.votes:
            self.votes[from_] = not rejected
        return sum(1 for v in self.votes.values() if v)

    def pre_vote_campaign(self) -> None:
        self.become_pre_vote_candidate()
        self.handle_vote_resp(self.replica_id, False, True)
        if self.is_single_node_quorum():
            self.campaign()
            return
        index = self.log.last_index()
        last_term = self.log.last_term()
        for rid in self.voting_members():
            if rid == self.replica_id:
                continue
            self.send(
                pb.Message(
                    term=self.term + 1,
                    to=rid,
                    type=pb.MessageType.REQUEST_PREVOTE,
                    log_index=index,
                    log_term=last_term,
                )
            )

    def campaign(self) -> None:
        self.become_candidate()
        term = self.term
        self.handle_vote_resp(self.replica_id, False, False)
        if self.is_single_node_quorum():
            self.become_leader()
            return
        hint = 0
        if self.is_leader_transfer_target:
            hint = self.replica_id
            self.is_leader_transfer_target = False
        index = self.log.last_index()
        last_term = self.log.last_term()
        for rid in self.voting_members():
            if rid == self.replica_id:
                continue
            self.send(
                pb.Message(
                    term=term,
                    to=rid,
                    type=pb.MessageType.REQUEST_VOTE,
                    log_index=index,
                    log_term=last_term,
                    hint=hint,
                )
            )

    # ------------------------------------------------------------------
    # membership (parity raft.go:1236-1340)
    # ------------------------------------------------------------------

    def add_node(self, rid: int) -> None:
        self.pending_config_change = False
        if rid == self.replica_id and self.is_witness():
            raise AssertionError("adding self while witness")
        if rid in self.remotes:
            return
        if rid in self.non_votings:
            rp = self.non_votings.pop(rid)
            self.remotes[rid] = rp
            if rid == self.replica_id:
                # local peer promoted to voter
                self.become_follower(self.term, self.leader_id)
        elif rid in self.witnesses:
            raise AssertionError("cannot promote witness to full member")
        else:
            self.remotes[rid] = Remote(match=0, next=self.log.last_index() + 1)

    def add_non_voting(self, rid: int) -> None:
        self.pending_config_change = False
        if rid in self.non_votings:
            return
        if rid in self.remotes or rid in self.witnesses:
            # demotion not allowed; reference panics on voter->nonvoting
            raise AssertionError("demoting member to nonVoting")
        self.non_votings[rid] = Remote(match=0, next=self.log.last_index() + 1)

    def add_witness(self, rid: int) -> None:
        self.pending_config_change = False
        if rid == self.replica_id and not self.is_witness():
            raise AssertionError("adding self as witness while not witness")
        if rid in self.witnesses:
            return
        if rid in self.remotes or rid in self.non_votings:
            raise AssertionError("converting member to witness")
        self.witnesses[rid] = Remote(match=0, next=self.log.last_index() + 1)

    def remove_node(self, rid: int) -> None:
        self.pending_config_change = False
        self.remotes.pop(rid, None)
        self.non_votings.pop(rid, None)
        self.witnesses.pop(rid, None)
        if rid == self.replica_id and self.is_leader():
            self.become_follower(self.term, NO_LEADER)
        if self.leader_transfering() and self.leader_transfer_target == rid:
            self.abort_leader_transfer()
        if self.is_leader() and self.num_voting_members() > 0:
            if self.try_commit():
                self.broadcast_replicate_message()

    def restore_remotes(self, ss: pb.Snapshot) -> None:
        """Rebuild peer books from snapshot membership — raft.go restoreRemotes."""
        next_idx = self.log.last_index() + 1
        match_self = next_idx - 1
        self.remotes = {}
        for rid in ss.membership.addresses:
            if rid == self.replica_id and self.is_non_voting():
                # promoted by snapshot
                self.become_follower(self.term, self.leader_id)
            if rid in self.witnesses:
                raise AssertionError("witness promoted to full member")
            m = match_self if rid == self.replica_id else 0
            self.remotes[rid] = Remote(match=m, next=next_idx)
        if self.replica_id not in self.remotes and self.is_leader():
            self.become_follower(self.term, NO_LEADER)
        self.non_votings = {}
        for rid in ss.membership.non_votings:
            m = match_self if rid == self.replica_id else 0
            self.non_votings[rid] = Remote(match=m, next=next_idx)
        self.witnesses = {}
        for rid in ss.membership.witnesses:
            m = match_self if rid == self.replica_id else 0
            self.witnesses[rid] = Remote(match=m, next=next_idx)

    # ------------------------------------------------------------------
    # leader transfer helpers
    # ------------------------------------------------------------------

    def leader_transfering(self) -> bool:
        return self.leader_transfer_target != NO_NODE and self.is_leader()

    def abort_leader_transfer(self) -> None:
        self.leader_transfer_target = NO_NODE

    # ------------------------------------------------------------------
    # snapshot restore (follower side; parity raft.go:456-530 restore)
    # ------------------------------------------------------------------

    def restore(self, ss: pb.Snapshot) -> bool:
        if ss.index <= self.log.committed:
            return False
        if not self.is_non_voting():
            for rid in ss.membership.non_votings:
                if rid == self.replica_id:
                    raise AssertionError("voter demoted to nonVoting by snapshot")
        if not self.is_witness():
            for rid in ss.membership.witnesses:
                if rid == self.replica_id:
                    raise AssertionError("converted to witness by snapshot")
        if self.log.match_term(ss.index, ss.term):
            # local log already covers the snapshot: just fast-forward commit
            self.log.commit_to(ss.index)
            return False
        self.log.restore(ss)
        return True

    # ------------------------------------------------------------------
    # term-mismatch core rules (parity raft.go:1507-1595)
    # ------------------------------------------------------------------

    @staticmethod
    def _is_request_vote_message(t: pb.MessageType) -> bool:
        return t in (pb.MessageType.REQUEST_VOTE, pb.MessageType.REQUEST_PREVOTE)

    @staticmethod
    def _is_leader_message(t: pb.MessageType) -> bool:
        return t in (
            pb.MessageType.REPLICATE,
            pb.MessageType.INSTALL_SNAPSHOT,
            pb.MessageType.HEARTBEAT,
            pb.MessageType.TIMEOUT_NOW,
            pb.MessageType.READ_INDEX_RESP,
        )

    def drop_request_vote_from_high_term_node(self, m: pb.Message) -> bool:
        if not self._is_request_vote_message(m.type) or not self.check_quorum:
            return False
        if m.term <= self.term:
            return False
        # p42 of the raft thesis: leadership-transfer hint overrides the lease
        if m.hint == m.from_:
            return False
        # recently heard from a quorum-backed leader: protect the lease
        return self.leader_id != NO_LEADER and self.election_tick < self.election_timeout

    def on_message_term_not_matched(self, m: pb.Message) -> bool:
        if m.term == 0 or m.term == self.term:
            return False
        if self.drop_request_vote_from_high_term_node(m):
            return True
        if m.term > self.term:
            is_prevote_expected = m.type == pb.MessageType.REQUEST_PREVOTE or (
                m.type == pb.MessageType.REQUEST_PREVOTE_RESP and not m.reject
            )
            if not is_prevote_expected:
                leader_id = NO_LEADER
                if self._is_leader_message(m.type):
                    leader_id = m.from_
                if self.is_non_voting():
                    self.become_non_voting(m.term, leader_id)
                elif self.is_witness():
                    self.become_witness(m.term, leader_id)
                else:
                    # RequestVote keeps the election tick (KE) so slow nodes
                    # can still campaign later (raft.go:1566-1580)
                    keep = m.type == pb.MessageType.REQUEST_VOTE
                    self.become_follower(m.term, leader_id,
                                         reset_election_timeout=not keep)
            return False
        # m.term < self.term
        if m.type == pb.MessageType.REQUEST_PREVOTE or (
            self._is_leader_message(m.type) and (self.check_quorum or self.pre_vote)
        ):
            # see TestFreeStuckCandidateWithCheckQuorum
            self.send(pb.Message(to=m.from_, type=pb.MessageType.NOOP))
        return True

    # ------------------------------------------------------------------
    # shared handlers (parity raft.go:1398-1490 + 1632-1780)
    # ------------------------------------------------------------------

    def handle_heartbeat_message(self, m: pb.Message) -> None:
        self.log.commit_to(m.commit)
        self.send(
            pb.Message(
                to=m.from_,
                type=pb.MessageType.HEARTBEAT_RESP,
                hint=m.hint,
                hint_high=m.hint_high,
            )
        )

    def handle_install_snapshot_message(self, m: pb.Message) -> None:
        resp = pb.Message(to=m.from_, type=pb.MessageType.REPLICATE_RESP)
        if self.restore(m.snapshot):
            resp = replace(resp, log_index=self.log.last_index())
            self.restore_remotes(m.snapshot)
        else:
            resp = replace(resp, log_index=self.log.committed)
        self.send(resp)

    def handle_replicate_message(self, m: pb.Message) -> None:
        resp = pb.Message(to=m.from_, type=pb.MessageType.REPLICATE_RESP)
        if m.log_index < self.log.committed:
            self.send(replace(resp, log_index=self.log.committed))
            return
        if self.log.match_term(m.log_index, m.log_term):
            self.log.try_append(m.log_index, m.entries)
            last_idx = m.log_index + len(m.entries)
            self.log.commit_to(min(last_idx, m.commit))
            self.send(replace(resp, log_index=last_idx))
        else:
            self.send(
                replace(
                    resp,
                    reject=True,
                    log_index=m.log_index,
                    hint=self.log.last_index(),
                )
            )

    def has_config_change_to_apply(self) -> bool:
        if self.has_not_applied_config_change is not None:
            return self.has_not_applied_config_change()
        # conservative: any committed-but-unapplied entry blocks campaigns
        # (raft.go:1611-1622)
        return self.log.committed > self.applied

    def can_grant_vote(self, m: pb.Message) -> bool:
        return self.vote in (NO_NODE, m.from_) or m.term > self.term

    def handle_node_election(self, m: pb.Message) -> None:
        if self.is_leader():
            return
        if self.has_config_change_to_apply():
            return
        if self.pre_vote and not self.is_leader_transfer_target:
            self.pre_vote_campaign()
        else:
            self.campaign()

    def handle_node_request_pre_vote(self, m: pb.Message) -> None:
        resp = pb.Message(to=m.from_, type=pb.MessageType.REQUEST_PREVOTE_RESP)
        up_to_date = self.log.up_to_date(m.log_index, m.log_term)
        assert m.term >= self.term
        if m.term > self.term and up_to_date:
            resp = replace(resp, term=m.term)
        else:
            resp = replace(resp, term=self.term, reject=True)
        self.send(resp)

    def handle_node_request_vote(self, m: pb.Message) -> None:
        resp = pb.Message(to=m.from_, type=pb.MessageType.REQUEST_VOTE_RESP)
        can_grant = self.can_grant_vote(m)
        up_to_date = self.log.up_to_date(m.log_index, m.log_term)
        if can_grant and up_to_date:
            self.election_tick = 0
            self.vote = m.from_
        else:
            resp = replace(resp, reject=True)
        self.send(resp)

    def handle_node_config_change(self, m: pb.Message) -> None:
        if m.reject:
            self.pending_config_change = False
            return
        cctype = pb.ConfigChangeType(m.hint_high)
        rid = m.hint
        if cctype == pb.ConfigChangeType.ADD_NODE:
            self.add_node(rid)
        elif cctype == pb.ConfigChangeType.REMOVE_NODE:
            self.remove_node(rid)
        elif cctype == pb.ConfigChangeType.ADD_NON_VOTING:
            self.add_non_voting(rid)
        elif cctype == pb.ConfigChangeType.ADD_WITNESS:
            self.add_witness(rid)
        else:
            raise AssertionError("unexpected config change type")

    def handle_log_query(self, m: pb.Message) -> None:
        if self.log_query_result is not None:
            raise AssertionError("log query result not consumed")
        error = 0
        entries: tuple[pb.Entry, ...] = ()
        try:
            entries = tuple(self.log.get_committed_entries(m.from_, m.to, m.hint))
        except CompactedError:
            error = 1
        self.log_query_result = pb.LogQueryResult(
            error=error,
            first_index=self.log.first_index(),
            last_index=self.log.committed + 1,
            entries=entries,
        )

    def handle_local_tick(self, m: pb.Message) -> None:
        if m.reject:
            self.quiesced_tick()
        else:
            self.tick()

    def handle_restore_remote(self, m: pb.Message) -> None:
        self.restore_remotes(m.snapshot)

    # ------------------------------------------------------------------
    # leader handlers (parity raft.go:1780-2050)
    # ------------------------------------------------------------------

    def handle_leader_heartbeat(self, m: pb.Message) -> None:
        self.broadcast_heartbeat_message()

    def handle_leader_check_quorum(self, m: pb.Message) -> None:
        assert self.is_leader()
        if not self.leader_has_quorum():
            self.become_follower(self.term, NO_LEADER)

    def handle_leader_propose(self, m: pb.Message) -> None:
        assert self.is_leader()
        if self.leader_transfering():
            self.report_dropped_proposal(m)
            return
        entries = list(m.entries)
        for i, e in enumerate(entries):
            if e.type == pb.EntryType.CONFIG_CHANGE:
                if self.pending_config_change:
                    self.report_dropped_config_change(e)
                    entries[i] = pb.Entry(type=pb.EntryType.APPLICATION)
                else:
                    self.pending_config_change = True
        self.append_entries(entries)
        self.broadcast_replicate_message()

    def has_committed_entry_at_current_term(self) -> bool:
        assert self.term > 0
        try:
            return self.log.term(self.log.committed) == self.term
        except CompactedError:
            return False

    def add_ready_to_read(self, index: int, ctx: pb.SystemCtx) -> None:
        self.ready_to_read.append(pb.ReadyToRead(index=index, system_ctx=ctx))

    def handle_leader_read_index(self, m: pb.Message) -> None:
        """Section 6.4 of the raft thesis."""
        assert self.is_leader()
        ctx = pb.SystemCtx(low=m.hint, high=m.hint_high)
        if m.from_ in self.witnesses:
            return  # witnesses cannot read
        if not self.is_single_node_quorum():
            if not self.has_committed_entry_at_current_term():
                self.report_dropped_read_index(m)
                return
            self.read_index.add_request(self.log.committed, ctx, m.from_)
            self.broadcast_heartbeat_with_hint(ctx)
        else:
            self.add_ready_to_read(self.log.committed, ctx)
            if m.from_ != self.replica_id and m.from_ in self.non_votings:
                self.send(
                    pb.Message(
                        to=m.from_,
                        type=pb.MessageType.READ_INDEX_RESP,
                        log_index=self.log.committed,
                        hint=m.hint,
                        hint_high=m.hint_high,
                        commit=m.commit,
                    )
                )

    def handle_leader_replicate_resp(self, m: pb.Message, rp: Remote) -> None:
        assert self.is_leader()
        rp.active = True
        if not m.reject:
            paused = rp.is_paused()
            if rp.try_update(m.log_index):
                rp.responded_to()
                if self.try_commit():
                    self.broadcast_replicate_message()
                elif paused:
                    self.send_replicate_message(m.from_)
                # leadership transfer protocol, p29 of the raft thesis
                if (
                    self.leader_transfering()
                    and m.from_ == self.leader_transfer_target
                    and self.log.last_index() == rp.match
                ):
                    self.send_timeout_now_message(self.leader_transfer_target)
        else:
            if rp.decrease_to(m.log_index, m.hint):
                if rp.state == RemoteState.REPLICATE:
                    rp.become_retry()
                self.send_replicate_message(m.from_)

    def handle_leader_heartbeat_resp(self, m: pb.Message, rp: Remote) -> None:
        assert self.is_leader()
        rp.active = True
        rp.wait_to_retry()
        if rp.match < self.log.last_index():
            self.send_replicate_message(m.from_)
        if m.hint != 0:
            self.handle_read_index_leader_confirmation(m)

    def handle_leader_transfer(self, m: pb.Message) -> None:
        assert self.is_leader()
        target = m.hint
        assert target != NO_NODE
        if self.leader_transfering():
            return
        if self.replica_id == target:
            return
        rp = self.remotes.get(target)
        if rp is None:
            return
        self.leader_transfer_target = target
        self.election_tick = 0
        if rp.match == self.log.last_index():
            self.send_timeout_now_message(target)

    def handle_read_index_leader_confirmation(self, m: pb.Message) -> None:
        ctx = pb.SystemCtx(low=m.hint, high=m.hint_high)
        for s in self.read_index.confirm(ctx, m.from_, self.quorum()):
            if s.from_ in (NO_NODE, self.replica_id):
                self.add_ready_to_read(s.index, s.ctx)
            else:
                self.send(
                    pb.Message(
                        to=s.from_,
                        type=pb.MessageType.READ_INDEX_RESP,
                        log_index=s.index,
                        hint=m.hint,
                        hint_high=m.hint_high,
                    )
                )

    def handle_leader_snapshot_status(self, m: pb.Message, rp: Remote) -> None:
        if rp.state != RemoteState.SNAPSHOT:
            return
        if m.hint == 0:
            if m.reject:
                rp.clear_pending_snapshot()
            rp.become_wait()
        else:
            rp.set_snapshot_ack(m.hint, m.reject)
            self.snapshotting = True

    def handle_leader_unreachable(self, m: pb.Message, rp: Remote) -> None:
        if rp.state == RemoteState.REPLICATE:
            rp.become_retry()

    def handle_leader_rate_limit(self, m: pb.Message) -> None:
        pass  # host-side rate limiter consumes these; kernel ignores

    def check_pending_snapshot_ack(self) -> None:
        if self.is_leader() and self.snapshotting:
            self.snapshotting = False
            for group in (self.remotes, self.non_votings, self.witnesses):
                for from_, rp in group.items():
                    if rp.state == RemoteState.SNAPSHOT:
                        if rp.ack_tick():
                            rejected = rp.delayed_ack_rejected
                            rp.clear_snapshot_ack()
                            self.handle(
                                pb.Message(
                                    type=pb.MessageType.SNAPSHOT_STATUS,
                                    from_=from_,
                                    reject=rejected,
                                    hint=0,
                                )
                            )
                        elif rp.delayed_ack_tick > 0:
                            self.snapshotting = True

    # ------------------------------------------------------------------
    # follower handlers (parity raft.go:2100-2200)
    # ------------------------------------------------------------------

    def handle_follower_propose(self, m: pb.Message) -> None:
        if self.leader_id == NO_LEADER:
            self.report_dropped_proposal(m)
            return
        self.send(replace(m, to=self.leader_id))

    def leader_is_available(self) -> None:
        self.election_tick = 0

    def handle_follower_replicate(self, m: pb.Message) -> None:
        self.leader_is_available()
        self.set_leader_id(m.from_)
        self.handle_replicate_message(m)

    def handle_follower_heartbeat(self, m: pb.Message) -> None:
        self.leader_is_available()
        self.set_leader_id(m.from_)
        self.handle_heartbeat_message(m)

    def handle_follower_read_index(self, m: pb.Message) -> None:
        if self.leader_id == NO_LEADER:
            self.report_dropped_read_index(m)
            return
        self.send(replace(m, to=self.leader_id))

    def handle_follower_leader_transfer(self, m: pb.Message) -> None:
        if self.leader_id == NO_LEADER:
            return
        self.send(replace(m, to=self.leader_id))

    def handle_follower_read_index_resp(self, m: pb.Message) -> None:
        ctx = pb.SystemCtx(low=m.hint, high=m.hint_high)
        self.leader_is_available()
        self.set_leader_id(m.from_)
        self.add_ready_to_read(m.log_index, ctx)

    def handle_follower_install_snapshot(self, m: pb.Message) -> None:
        self.leader_is_available()
        self.set_leader_id(m.from_)
        self.handle_install_snapshot_message(m)

    def handle_follower_timeout_now(self, m: pb.Message) -> None:
        # p29 of the raft thesis: same as the clock moving forward quickly
        self.election_tick = self.randomized_election_timeout
        self.is_leader_transfer_target = True
        self.tick()
        self.is_leader_transfer_target = False

    # ------------------------------------------------------------------
    # candidate handlers (parity raft.go:2205-2300)
    # ------------------------------------------------------------------

    def handle_candidate_propose(self, m: pb.Message) -> None:
        self.report_dropped_proposal(m)

    def handle_candidate_read_index(self, m: pb.Message) -> None:
        self.report_dropped_read_index(m)

    def handle_candidate_replicate(self, m: pb.Message) -> None:
        # m.term == self.term implies a leader exists for this term
        self.become_follower(self.term, m.from_)
        self.handle_replicate_message(m)

    def handle_candidate_install_snapshot(self, m: pb.Message) -> None:
        self.become_follower(self.term, m.from_)
        self.handle_install_snapshot_message(m)

    def handle_candidate_heartbeat(self, m: pb.Message) -> None:
        self.become_follower(self.term, m.from_)
        self.handle_heartbeat_message(m)

    def handle_candidate_request_vote_resp(self, m: pb.Message) -> None:
        if m.from_ in self.non_votings:
            return
        count = self.handle_vote_resp(m.from_, m.reject, False)
        if count == self.quorum():
            self.become_leader()
            self.broadcast_replicate_message()
        elif len(self.votes) - count == self.quorum():
            # etcd-raft behavior: majority rejection -> step down
            self.become_follower(self.term, NO_LEADER)

    def handle_pre_vote_candidate_request_pre_vote_resp(self, m: pb.Message) -> None:
        if m.from_ in self.non_votings:
            return
        count = self.handle_vote_resp(m.from_, m.reject, True)
        if count == self.quorum():
            self.campaign()
        elif len(self.votes) - count == self.quorum():
            self.become_follower(self.term, NO_LEADER)

    # ------------------------------------------------------------------
    # dropped-op reporting
    # ------------------------------------------------------------------

    def report_dropped_config_change(self, e: pb.Entry) -> None:
        self.dropped_entries.append(e)

    def report_dropped_proposal(self, m: pb.Message) -> None:
        self.dropped_entries.extend(m.entries)

    def report_dropped_read_index(self, m: pb.Message) -> None:
        self.dropped_read_indexes.append(
            pb.SystemCtx(low=m.hint, high=m.hint_high)
        )

    # ------------------------------------------------------------------
    # dispatch (parity raft.go:1596 Handle, 2332 initializeHandlerMap)
    # ------------------------------------------------------------------

    def handle(self, m: pb.Message) -> None:
        if not self.pre_vote and m.type in (
            pb.MessageType.REQUEST_PREVOTE,
            pb.MessageType.REQUEST_PREVOTE_RESP,
        ):
            raise AssertionError("preVote message while preVote disabled")
        if self.on_message_term_not_matched(m):
            return
        handler = _HANDLERS[self.state].get(m.type)
        if handler is not None:
            handler(self, m)

    def _with_remote(f):  # type: ignore[no-untyped-def]
        def wrapped(self: "Raft", m: pb.Message) -> None:
            rp = self.get_remote(m.from_)
            if rp is None:
                return
            f(self, m, rp)

        return wrapped

    _h_leader_replicate_resp = _with_remote(handle_leader_replicate_resp)
    _h_leader_heartbeat_resp = _with_remote(handle_leader_heartbeat_resp)
    _h_leader_snapshot_status = _with_remote(handle_leader_snapshot_status)
    _h_leader_unreachable = _with_remote(handle_leader_unreachable)


_MT = pb.MessageType

# The static [state][msgtype] dispatch matrix — parity raft.go:2332-2420.
_HANDLERS: dict[RaftState, dict[pb.MessageType, Callable[[Raft, pb.Message], None]]] = {
    RaftState.CANDIDATE: {
        _MT.HEARTBEAT: Raft.handle_candidate_heartbeat,
        _MT.PROPOSE: Raft.handle_candidate_propose,
        _MT.READ_INDEX: Raft.handle_candidate_read_index,
        _MT.REPLICATE: Raft.handle_candidate_replicate,
        _MT.INSTALL_SNAPSHOT: Raft.handle_candidate_install_snapshot,
        _MT.REQUEST_VOTE_RESP: Raft.handle_candidate_request_vote_resp,
        _MT.ELECTION: Raft.handle_node_election,
        _MT.REQUEST_VOTE: Raft.handle_node_request_vote,
        _MT.REQUEST_PREVOTE: Raft.handle_node_request_pre_vote,
        _MT.CONFIG_CHANGE_EVENT: Raft.handle_node_config_change,
        _MT.LOCAL_TICK: Raft.handle_local_tick,
        _MT.SNAPSHOT_RECEIVED: Raft.handle_restore_remote,
        _MT.LOG_QUERY: Raft.handle_log_query,
    },
    RaftState.PRE_VOTE_CANDIDATE: {
        _MT.HEARTBEAT: Raft.handle_candidate_heartbeat,
        _MT.PROPOSE: Raft.handle_candidate_propose,
        _MT.READ_INDEX: Raft.handle_candidate_read_index,
        _MT.REPLICATE: Raft.handle_candidate_replicate,
        _MT.INSTALL_SNAPSHOT: Raft.handle_candidate_install_snapshot,
        _MT.REQUEST_PREVOTE_RESP: Raft.handle_pre_vote_candidate_request_pre_vote_resp,
        _MT.ELECTION: Raft.handle_node_election,
        _MT.REQUEST_VOTE: Raft.handle_node_request_vote,
        _MT.REQUEST_PREVOTE: Raft.handle_node_request_pre_vote,
        _MT.CONFIG_CHANGE_EVENT: Raft.handle_node_config_change,
        _MT.LOCAL_TICK: Raft.handle_local_tick,
        _MT.SNAPSHOT_RECEIVED: Raft.handle_restore_remote,
        _MT.LOG_QUERY: Raft.handle_log_query,
    },
    RaftState.FOLLOWER: {
        _MT.PROPOSE: Raft.handle_follower_propose,
        _MT.REPLICATE: Raft.handle_follower_replicate,
        _MT.HEARTBEAT: Raft.handle_follower_heartbeat,
        _MT.READ_INDEX: Raft.handle_follower_read_index,
        _MT.LEADER_TRANSFER: Raft.handle_follower_leader_transfer,
        _MT.READ_INDEX_RESP: Raft.handle_follower_read_index_resp,
        _MT.INSTALL_SNAPSHOT: Raft.handle_follower_install_snapshot,
        _MT.ELECTION: Raft.handle_node_election,
        _MT.REQUEST_VOTE: Raft.handle_node_request_vote,
        _MT.REQUEST_PREVOTE: Raft.handle_node_request_pre_vote,
        _MT.TIMEOUT_NOW: Raft.handle_follower_timeout_now,
        _MT.CONFIG_CHANGE_EVENT: Raft.handle_node_config_change,
        _MT.LOCAL_TICK: Raft.handle_local_tick,
        _MT.SNAPSHOT_RECEIVED: Raft.handle_restore_remote,
        _MT.LOG_QUERY: Raft.handle_log_query,
    },
    RaftState.LEADER: {
        _MT.LEADER_HEARTBEAT: Raft.handle_leader_heartbeat,
        _MT.CHECK_QUORUM: Raft.handle_leader_check_quorum,
        _MT.PROPOSE: Raft.handle_leader_propose,
        _MT.READ_INDEX: Raft.handle_leader_read_index,
        _MT.REPLICATE_RESP: Raft._h_leader_replicate_resp,
        _MT.HEARTBEAT_RESP: Raft._h_leader_heartbeat_resp,
        _MT.SNAPSHOT_STATUS: Raft._h_leader_snapshot_status,
        _MT.UNREACHABLE: Raft._h_leader_unreachable,
        _MT.LEADER_TRANSFER: Raft.handle_leader_transfer,
        _MT.ELECTION: Raft.handle_node_election,
        _MT.REQUEST_VOTE: Raft.handle_node_request_vote,
        _MT.REQUEST_PREVOTE: Raft.handle_node_request_pre_vote,
        _MT.CONFIG_CHANGE_EVENT: Raft.handle_node_config_change,
        _MT.LOCAL_TICK: Raft.handle_local_tick,
        _MT.SNAPSHOT_RECEIVED: Raft.handle_restore_remote,
        _MT.RATE_LIMIT: Raft.handle_leader_rate_limit,
        _MT.LOG_QUERY: Raft.handle_log_query,
    },
    RaftState.NON_VOTING: {
        _MT.HEARTBEAT: Raft.handle_follower_heartbeat,
        _MT.REPLICATE: Raft.handle_follower_replicate,
        _MT.INSTALL_SNAPSHOT: Raft.handle_follower_install_snapshot,
        _MT.REQUEST_VOTE: Raft.handle_node_request_vote,
        _MT.REQUEST_PREVOTE: Raft.handle_node_request_pre_vote,
        _MT.PROPOSE: Raft.handle_follower_propose,
        _MT.READ_INDEX: Raft.handle_follower_read_index,
        _MT.READ_INDEX_RESP: Raft.handle_follower_read_index_resp,
        _MT.CONFIG_CHANGE_EVENT: Raft.handle_node_config_change,
        _MT.LOCAL_TICK: Raft.handle_local_tick,
        _MT.SNAPSHOT_RECEIVED: Raft.handle_restore_remote,
        _MT.LOG_QUERY: Raft.handle_log_query,
    },
    RaftState.WITNESS: {
        _MT.HEARTBEAT: Raft.handle_follower_heartbeat,
        _MT.REPLICATE: Raft.handle_follower_replicate,
        _MT.INSTALL_SNAPSHOT: Raft.handle_follower_install_snapshot,
        _MT.REQUEST_VOTE: Raft.handle_node_request_vote,
        _MT.REQUEST_PREVOTE: Raft.handle_node_request_pre_vote,
        _MT.CONFIG_CHANGE_EVENT: Raft.handle_node_config_change,
        _MT.LOCAL_TICK: Raft.handle_local_tick,
        _MT.SNAPSHOT_RECEIVED: Raft.handle_restore_remote,
    },
}
