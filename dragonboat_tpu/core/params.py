"""Static kernel geometry + the shared counter-based PRNG.

The batched kernel is compiled for a fixed geometry: G shards × P peer slots,
a CAP-entry term ring, K inbox slots, B proposal slots and an RI-slot
ReadIndex book per shard.  All lanes are int32: JAX's default integer width —
terms/indexes are per-shard logical clocks that a shard would take years to
overflow at raft rates, and the host records full-width u64 in raftpb.

The randomized election timeout uses a splitmix32-style counter hash keyed by
(shard seed, reset counter) so device and host cores draw identical values —
this keeps the pycore differential oracle in exact lockstep
(reference behavior: raft.go:658 setRandomizedElectionTimeout draws
uniform [electionTimeout, 2*electionTimeout)).
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class KernelParams:
    num_peers: int = 3          # P: peer slots per shard (max replicas)
    log_cap: int = 1024         # CAP: term-ring capacity (power of two)
    inbox_cap: int = 8          # K: inbound messages per shard per step
    msg_entries: int = 8        # E: max entries carried per replicate message
    proposal_cap: int = 8       # B: proposals per shard per step
    readindex_cap: int = 8      # RI: pending ReadIndex contexts per shard
    apply_batch: int = 64       # max committed entries released per step
    compaction_overhead: int = 64  # retained entries below the compact floor
    # inline payload lanes (lv ring + ent_val routing) for device-resident
    # RSMs; off by default — host-side-payload deployments skip the cost
    inline_payloads: bool = False
    # (merge_inbox_families, a hand-restructured unrolled pass over the
    # ring-invariant families, lived here r2-r4; it measured slower on
    # BOTH platforms — 28x on XLA:CPU, +40% on TPU v5e — so it was
    # removed in r5.  Reviving it would need a new hypothesis for why a
    # materialized-buffer chain could beat the aliased scan carry.)
    # read dynamically-indexed state (the [log_cap] rings, the [P] peer
    # books, the [RI] read book, the router's [K]/[R] lanes) by one-hot
    # select instead of dynamic indexing.  On TPU the batched gather
    # that vmapped indexing lowers to serializes over the [G] axis (r4
    # ladder: ~0.32 ms/group of linear step cost against a ~10 µs
    # roofline); the one-hot form is wide VPU passes.  On XLA:CPU the
    # gather is a real O(1) load and the one-hot form costs 1.4-3.5x
    # step time (rings worst).  Default False (the CPU graph — also what
    # direct constructors in tests get); the real entry points
    # (bench_loop.bench_params, NodeHost._kernel_params) flip it on
    # whenever the backend is not cpu.  Bitwise-identical either way
    # (differential-tested).
    onehot_reads: bool = False
    # unroll the per-family inbox scans (lax.scan unroll flag — bitwise
    # neutral, pure scheduling).  Off everywhere by default: XLA:CPU
    # measured 11x slower unrolled (the rolled carry aliases in place).
    # Exists for the TPU A/B, where each rolled iteration is its own
    # serial launch of the full family body.
    unroll_scans: bool = False

    def __post_init__(self) -> None:
        assert self.log_cap & (self.log_cap - 1) == 0, "log_cap must be 2^n"
        assert self.readindex_cap & (self.readindex_cap - 1) == 0


def slot_families(K: int) -> tuple[str, ...]:
    """Static per-slot message families for the kernel inbox.

    The device router's slot layout (router.py) is typed: per remote peer,
    two response lanes, a replicate lane, a heartbeat lane and a
    vote/TimeoutNow lane.  Exposing that statically lets the kernel scan
    each family with a body containing ONLY that family's handlers —
    the dispatch-by-type restructuring that removes most of the serial
    inbox-scan cost (PERF.md lever #1).  Slots beyond whole 5-slot units
    are 'any': they accept every type and run the full handler body
    (hosts staging arbitrary network traffic use these).

    resp: *_RESP, NOOP, UNREACHABLE, SNAPSHOT_STATUS
    rep:  REPLICATE      hb: HEARTBEAT
    vote: REQUEST_VOTE, REQUEST_PREVOTE, TIMEOUT_NOW
    """
    u = K // 5
    return ("resp", "resp", "rep", "hb", "vote") * u + ("any",) * (K - 5 * u)


# role encoding — parity with pycore.RaftState / raft.go:63-71
FOLLOWER = 0
CANDIDATE = 1
PRE_VOTE_CANDIDATE = 2
LEADER = 3
NON_VOTING = 4
WITNESS = 5

# peer-slot kinds
K_ABSENT = 0
K_VOTER = 1
K_NON_VOTING = 2
K_WITNESS = 3

# remote flow-control states — parity remote.go:52-70
R_RETRY = 0
R_WAIT = 1
R_REPLICATE = 2
R_SNAPSHOT = 3

NO_LEADER = 0


import numpy as np

_U = np.uint32


def splitmix32(x):
    """Deterministic 32-bit mixer usable from numpy scalars and jnp arrays.

    Callers pass uint32-typed values; constants are np.uint32 so JAX's weak
    typing doesn't reject them and numpy wraps mod 2^32."""
    if isinstance(x, (int, np.integer)):
        # host flavor: plain python ints, wrap mod 2^32
        m = 0xFFFFFFFF
        x = (int(x) + 0x9E3779B9) & m
        z = ((x ^ (x >> 16)) * 0x85EBCA6B) & m
        z = ((z ^ (z >> 13)) * 0xC2B2AE35) & m
        return _U(z ^ (z >> 16))
    x = x + _U(0x9E3779B9)
    z = (x ^ (x >> _U(16))) * _U(0x85EBCA6B)
    z = (z ^ (z >> _U(13))) * _U(0xC2B2AE35)
    return z ^ (z >> _U(16))


def randomized_timeout(seed: int, counter: int, election_timeout: int) -> int:
    """election_timeout + uniform-ish [0, election_timeout) — host flavor,
    bit-identical to the kernel's _next_rand_timeout draw."""
    mixed = splitmix32((seed & 0xFFFFFFFF) ^ (((counter & 0xFFFFFFFF) * 0x632BE5AB) & 0xFFFFFFFF))
    return election_timeout + int(mixed) % election_timeout
