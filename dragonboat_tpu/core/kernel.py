"""The batched Raft step kernel.

One jitted call advances **every shard one step**: drain the inbox lanes,
serve the batched ReadIndex request, append proposals, apply the transfer
request, tick the logical clock, then materialize one coalesced send phase
(≤1 Replicate + ≤1 Heartbeat per peer per step).  This is the TPU-first
re-expression of the reference's per-goroutine step loop
(``engine.go:1230 stepWorkerMain`` → ``node.go:1161 handleEvents``): the
scheduler becomes a vmap axis, the per-message sends become end-of-step
lanes, and the handler matrix (``raft.go:2332``) becomes masked updates —
under vmap every branch runs for every shard, so the code is written
branchless from the start.

Semantics parity is with :mod:`dragonboat_tpu.core.pycore` (itself cited
against ``/root/reference/internal/raft/raft.go``); the differential suite in
``tests/test_kernel_differential.py`` drives both on identical inputs.

Control-flow divergences from the reference (documented, behavior-safe):

- sends are coalesced per step; the content of a Replicate reflects
  end-of-step flow-control state rather than mid-step snapshots;
- proposals and reads are host-routed to the leader replica, so follower
  redirect paths never execute on device;
- InstallSnapshot / ConfigChangeEvent / LogQuery are host-mediated through
  the pycore slow path (SURVEY §7 "masked slow path");
- entry payloads are not on device: the ring stores terms + config-change
  markers, the host mirrors payloads keyed by (shard, index).
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from dragonboat_tpu import raftpb as pb
from dragonboat_tpu.core import params as P
from dragonboat_tpu.core.kstate import (
    Inbox,
    ShardState,
    StepInput,
    StepOutput,
)

I32 = jnp.int32
INT_MAX = jnp.iinfo(jnp.int32).max
MT = pb.MessageType

# Contracts for the kernel-local structs (grammar: core/kstate.py
# CONTRACTS).  These are PER-SHARD shapes — the kernel body runs under
# vmap, so there is no [G] axis here; scalars are "[]".  part=G: the
# values are still per-group data (each group computes its own), so at
# the mesh level they live G-sharded like the kstate structs.
CONTRACTS = {
    "Effects": {
        "need_rep": "[P] bool part=G",
        "need_hb": "[] bool part=G",
        "hb_low": "[] i32 part=G",
        "hb_high": "[] i32 part=G",
        "send_vote": "[] i32 part=G",
        "vote_hint": "[] i32 part=G",
        "send_tn": "[P] bool part=G",
        "rtr_valid": "[RI] bool part=G",
        "rtr_index": "[RI] i32 part=G",
        "rtr_low": "[RI] i32 part=G",
        "rtr_high": "[RI] i32 part=G",
        "rtr_n": "[] i32 part=G",
        "save_from": "[] i32 part=G",
        "ri_dropped": "[] bool part=G",
    },
    "_Pre": {
        "act": "[] bool part=G",
        "is_leader": "[] bool part=G",
        "is_candidate": "[] bool part=G",
        "is_follower_like": "[] bool part=G",
        "sender_known": "[] bool part=G",
        "sender_slot": "[] i32 part=G",
        "noop_reply": "[] bool part=G",
    },
    "_Resp": {
        "r_type": "[] i32 part=G",
        "r_to": "[] i32 part=G",
        "r_term": "[] i32 part=G",
        "r_log_index": "[] i32 part=G",
        "r_reject": "[] bool part=G",
        "r_hint": "[] i32 part=G",
        "r_hint_high": "[] i32 part=G",
    },
}


def sel(c, a, b):
    return jnp.where(c, a, b)


def mrep(s: ShardState, mask, **kw) -> ShardState:
    """Masked replace: set fields where mask (scalar bool) holds."""
    upd = {}
    for k, v in kw.items():
        old = getattr(s, k)
        upd[k] = jnp.where(mask, v, old)
    return s._replace(**upd)


class Effects(NamedTuple):
    """Step-local accumulator consumed by the send phase."""

    need_rep: jnp.ndarray       # [P] bool
    need_hb: jnp.ndarray        # bool
    hb_low: jnp.ndarray
    hb_high: jnp.ndarray
    send_vote: jnp.ndarray      # 0 none / 1 RequestVote / 2 RequestPreVote
    vote_hint: jnp.ndarray
    send_tn: jnp.ndarray        # [P] bool — TimeoutNow
    rtr_valid: jnp.ndarray      # [RI]
    rtr_index: jnp.ndarray
    rtr_low: jnp.ndarray
    rtr_high: jnp.ndarray
    rtr_n: jnp.ndarray
    save_from: jnp.ndarray      # min appended/truncated index this step
    ri_dropped: jnp.ndarray


def _empty_effects(kp: P.KernelParams) -> Effects:
    Pn, RI = kp.num_peers, kp.readindex_cap
    z = lambda *s: jnp.zeros(s, I32)  # noqa: E731
    zb = lambda *s: jnp.zeros(s, bool)  # noqa: E731
    return Effects(
        need_rep=zb(Pn), need_hb=zb(), hb_low=z(), hb_high=z(),
        send_vote=z(), vote_hint=z(), send_tn=zb(Pn),
        rtr_valid=zb(RI), rtr_index=z(RI), rtr_low=z(RI), rtr_high=z(RI),
        rtr_n=z(), save_from=jnp.asarray(INT_MAX, I32), ri_dropped=zb(),
    )


# ---------------------------------------------------------------------------
# log-ring helpers (two-tier view collapsed to ring + snapshot floor;
# parity logentry.go:97-156 term resolution)
# ---------------------------------------------------------------------------


def _slot(kp: P.KernelParams, idx):
    return idx & (kp.log_cap - 1)


def log_term_at(kp: P.KernelParams, s: ShardState, idx):
    """(term, compacted, unavailable) for index idx."""
    in_ring = (idx > s.snap_index) & (idx <= s.last)
    t = sel(
        idx == 0,
        0,
        sel(idx == s.snap_index, s.snap_term,
            sel(in_ring, _get1(kp, s.lt, _slot(kp, idx)), 0)),
    )
    compacted = idx < s.snap_index
    unavailable = idx > s.last
    return t, compacted, unavailable


def match_term(kp, s, idx, term):
    t, comp, unav = log_term_at(kp, s, idx)
    return (~comp) & (~unav) & (t == term)


def up_to_date(kp, s, idx, term):
    lt_last, _, _ = log_term_at(kp, s, s.last)
    return (term > lt_last) | ((term == lt_last) & (idx >= s.last))


def _cc_count_in(kp: P.KernelParams, s: ShardState, lo, hi):
    """Count config-change entries with index in (lo, hi] — used to restore
    the pending flag on promotion (raft.go:1075)."""
    j = jnp.arange(kp.log_cap, dtype=I32)
    idx = s.last - ((s.last - j) & (kp.log_cap - 1))
    live = (idx > lo) & (idx <= hi) & (idx > s.snap_index)
    return jnp.sum(sel(live & s.lcc, 1, 0).astype(I32))


# ---------------------------------------------------------------------------
# peer-book helpers (parity remote.go)
# ---------------------------------------------------------------------------


def _self_slot_mask(s: ShardState):
    return (s.pid == s.replica_id) & (s.kind != P.K_ABSENT)


def _voting_mask(s: ShardState):
    return (s.kind == P.K_VOTER) | (s.kind == P.K_WITNESS)


def _num_voting(s: ShardState):
    return jnp.sum(_voting_mask(s).astype(I32))


def _quorum(s: ShardState):
    return _num_voting(s) // 2 + 1


def _is_single_node(s: ShardState):
    return _quorum(s) == 1


def _self_removed(s: ShardState):
    return ~jnp.any(_self_slot_mask(s))


def _sorted_match_quorum_index(kp: P.KernelParams, s: ShardState):
    """The q-th largest match among voting members — the batched
    tryCommit's jnp.sort (mirrors raft.go:911-941 sortMatchValues)."""
    mv = sel(_voting_mask(s), s.match, INT_MAX)
    srt = jnp.sort(mv)  # ascending; absent lanes sort to the end
    nv = _num_voting(s)
    pos = jnp.clip(nv - _quorum(s), 0, s.match.shape[0] - 1)
    return _get1(kp, srt, pos)


def _try_commit(kp, s: ShardState) -> ShardState:
    q = _sorted_match_quorum_index(kp, s)
    t, comp, _ = log_term_at(kp, s, q)
    t = sel(comp, 0, t)
    ok = (q > s.committed) & (t == s.term) & (s.role == P.LEADER)
    return mrep(s, ok, committed=q)


# ---------------------------------------------------------------------------
# state transitions (parity raft.go:960-1130)
# ---------------------------------------------------------------------------


def _next_rand_timeout(s: ShardState):
    counter = s.rand_counter + 1
    mixed = P.splitmix32(
        (s.seed.astype(jnp.uint32) ^ (counter.astype(jnp.uint32) * jnp.uint32(0x632BE5AB)))
    )
    r = (mixed % s.e_timeout.astype(jnp.uint32)).astype(I32)
    return counter, s.e_timeout + r


def _reset(s: ShardState, mask, term, reset_timeout) -> ShardState:
    """Shared reset on every role transition (raft.go:1052 reset)."""
    term_changed = s.term != term
    counter, rand_t = _next_rand_timeout(s)
    self_mask = _self_slot_mask(s)
    s = mrep(
        s, mask,
        term=term,
        vote=sel(term_changed, 0, s.vote),
        e_tick=sel(reset_timeout, 0, s.e_tick),
        rand_counter=sel(reset_timeout, counter, s.rand_counter),
        rand_timeout=sel(reset_timeout, rand_t, s.rand_timeout),
        h_tick=0,
        pending_cc=False,
        ltt=0,
        vresp=jnp.zeros_like(s.vresp),
        vgrant=jnp.zeros_like(s.vgrant),
        match=sel(self_mask, s.last, 0),
        next=jnp.full_like(s.next, 1) * (s.last + 1),
        pstate=jnp.zeros_like(s.pstate),
        active=jnp.zeros_like(s.active),
        psnap=jnp.zeros_like(s.psnap),
        ri_head=0,
        ri_count=0,
        ri_acks=jnp.zeros_like(s.ri_acks),
    )
    return s


def _become_follower(s, mask, term, leader, reset_timeout=True):
    # witnesses/non-votings keep their role on term bumps (raft.go:972-990)
    new_role = sel(
        s.role == P.NON_VOTING, P.NON_VOTING,
        sel(s.role == P.WITNESS, P.WITNESS, P.FOLLOWER),
    )
    s = _reset(s, mask, sel(mask, term, s.term), reset_timeout & mask)
    return mrep(s, mask, role=new_role, leader=leader)


def _set1(arr, idx, val, mask):
    """TPU-safe masked write of one dynamic slot: arr[idx] = val where mask.

    vmapped scalar-index ``.at[i].set`` lowers to a batched scatter, and on
    TPU (jax 0.9.0, v5e) that scatter SILENTLY DROPS writes for sub-32-bit
    element types (bool/int8/int16) once the batch axis exceeds ~3k rows
    with non-uniform indices.  A one-hot select avoids scatter entirely —
    and vectorizes better on the VPU anyway, so it is also the faster
    lowering for the small [P]/[RI]/ring axes this kernel uses."""
    n = arr.shape[0]
    oh = (jnp.arange(n, dtype=I32) == idx) & mask
    return jnp.where(oh, val, arr)


def _set_row(arr, idx, val, mask):
    """Row variant of _set1: arr[idx, :] = val where mask (arr [N, P])."""
    n = arr.shape[0]
    oh = (jnp.arange(n, dtype=I32) == idx) & mask
    return jnp.where(oh[:, None], val, arr)


def onehot_select(oh, arr, axis: int):
    """Reduce ``arr`` along ``axis`` through the one-hot mask ``oh``
    (broadcastable to arr): the shared lowering behind _get1/_get_row and
    the router's lane/source selects.  Exact when at most one mask slot
    is hot (ints sum a single term; bools use any)."""
    if arr.dtype == jnp.bool_:
        return jnp.any(oh & arr, axis=axis)
    return jnp.where(oh, arr, 0).sum(axis=axis).astype(arr.dtype)


def _get1(kp: P.KernelParams, arr, idx):
    """Platform-tuned read of one dynamic slot: arr[idx], idx in [0, N).

    The read-side twin of _set1.  With ``kp.onehot_reads`` (device
    configs) this is a one-hot compare+select+reduce: vmapped dynamic
    indexing lowers to a gather, and on TPU a batched gather serializes
    over the [G] batch axis — the r4 device ladder measured the
    resulting step cost at ~0.32 ms *per group* (256 groups: 130
    ms/step; 1024: 377 ms) against a ~10 µs roofline.  Without the flag
    (CPU configs) it stays plain dynamic indexing — the gather is an
    O(1) load there and the one-hot form measurably loses (37% step time
    across all sites, 3.5x with the rings included).  ``idx`` may be any
    integer shape (the result has idx's shape); every caller passes an
    in-range index (argmax results or ring-masked offsets), so the two
    lowerings are bitwise-identical."""
    if not kp.onehot_reads:
        return arr[idx]
    n = arr.shape[0]
    oh = jnp.expand_dims(idx, -1) == jnp.arange(n, dtype=I32)
    return onehot_select(oh, arr, -1)


def _get_row(kp: P.KernelParams, arr, idx):
    """Row variant of _get1: arr[idx, :] for arr [N, P], scalar idx."""
    if not kp.onehot_reads:
        return arr[idx]
    n = arr.shape[0]
    oh = jnp.arange(n, dtype=I32) == idx
    return onehot_select(oh[:, None], arr, 0)


def _append_one(kp, s: ShardState, mask, term, is_cc,
                val=None) -> ShardState:
    idx = s.last + 1
    slot = _slot(kp, idx)
    lt = _set1(s.lt, slot, term, mask)
    lcc = _set1(s.lcc, slot, is_cc, mask)
    s = s._replace(lt=lt, lcc=lcc)
    if kp.inline_payloads:
        v = jnp.asarray(0, I32) if val is None else val
        s = s._replace(lv=_set1(s.lv, slot, v, mask))
    return mrep(s, mask, last=idx)


def _become_leader(kp, s: ShardState, mask, eff: Effects):
    """Candidate→leader: reset, restore pending-CC flag, append noop
    (p72 raft thesis), broadcast (raft.go:1038)."""
    s2 = _reset(s, mask, s.term, True)
    s2 = mrep(s2, mask, role=P.LEADER, leader=s.replica_id)
    cc_pending = _cc_count_in(kp, s2, s2.committed, s2.last) > 0
    s2 = mrep(s2, mask, pending_cc=cc_pending)
    s2 = _append_one(kp, s2, mask, s2.term, False)
    self_mask = _self_slot_mask(s2)
    s2 = s2._replace(
        match=sel(mask & self_mask, s2.last, s2.match),
        next=sel(mask & self_mask, s2.last + 1, s2.next),
    )
    s2 = _try_commit(kp, s2)
    eff = eff._replace(
        need_rep=sel(mask, jnp.ones_like(eff.need_rep), eff.need_rep),
        save_from=sel(mask, jnp.minimum(eff.save_from, s2.last), eff.save_from),
    )
    return s2, eff


def _campaign(kp, s: ShardState, eff: Effects, mask, allow_prevote=True):
    """Election entry — handleNodeElection (raft.go:1632): pre-vote campaign
    unless transferring; single-node fast paths to leader."""
    # config-change gate (raft.go:1632 handleNodeElection): refuse to
    # campaign only when a CONFIG CHANGE sits committed-but-unapplied —
    # voting safety is log-based, so plain unapplied entries don't
    # matter.  Gating on committed > applied alone is a liveness trap:
    # apply backpressure keeps the window permanently non-empty on a
    # busy host, making elections (and TimeoutNow transfers) impossible
    # exactly when load needs to move
    gate = (s.committed > s.applied) & (
        _cc_count_in(kp, s, s.applied, s.committed) > 0)
    mask = mask & ~gate & ~_self_removed(s)
    use_prevote = s.pre_vote & ~s.is_ltt & allow_prevote
    single = _is_single_node(s)

    # -- pre-vote branch: no term bump (raft.go:1149 preVoteCampaign)
    pv = mask & use_prevote
    s = _reset(s, pv, s.term, True)
    s = mrep(s, pv, role=P.PRE_VOTE_CANDIDATE, leader=0)
    self_mask = _self_slot_mask(s)
    s = s._replace(
        vresp=sel(pv & self_mask, True, s.vresp),
        vgrant=sel(pv & self_mask, True, s.vgrant),
    )
    eff = eff._replace(send_vote=sel(pv & ~single, 2, eff.send_vote))

    # -- real campaign branch (raft.go:1176 campaign)
    rc = mask & (~use_prevote | single)
    hint = sel(s.is_ltt, s.replica_id, 0)
    s = _reset(s, rc, s.term + 1, True)
    s = mrep(s, rc, role=P.CANDIDATE, leader=0, vote=s.replica_id,
             is_ltt=False)
    self_mask = _self_slot_mask(s)
    s = s._replace(
        vresp=sel(rc & self_mask, True, s.vresp),
        vgrant=sel(rc & self_mask, True, s.vgrant),
    )
    eff = eff._replace(
        send_vote=sel(rc & ~single, 1, eff.send_vote),
        vote_hint=sel(rc & ~single, hint, eff.vote_hint),
    )
    s2, eff = _become_leader(kp, s, rc & single, eff)
    return s2, eff


# ---------------------------------------------------------------------------
# readindex book (parity readindex.go)
# ---------------------------------------------------------------------------


def _ri_push(kp, s: ShardState, mask, low, high, index):
    RI = kp.readindex_cap
    full = s.ri_count >= RI
    pos = (s.ri_head + s.ri_count) & (RI - 1)
    do = mask & ~full
    s = s._replace(
        ri_low=_set1(s.ri_low, pos, low, do),
        ri_high=_set1(s.ri_high, pos, high, do),
        ri_index=_set1(s.ri_index, pos, index, do),
        ri_acks=_set_row(s.ri_acks, pos, jnp.zeros_like(s.ri_acks[0]), do),
    )
    s = mrep(s, do, ri_count=s.ri_count + 1)
    # a full book drops the request (host will retry) — bounded-memory analog
    # of the reference's unbounded pending map
    return s, mask & full


def _ri_confirm(kp, s: ShardState, eff: Effects, mask, low, high, sender_slot):
    """Ack ctx from sender; pop every ctx at-or-before once quorum reached
    (readindex.go:73 confirm)."""
    RI = kp.readindex_cap
    arange = jnp.arange(RI, dtype=I32)
    # queue position of each physical slot (0..count-1), INT_MAX if dead
    qpos = (arange - s.ri_head) & (RI - 1)
    live = qpos < s.ri_count
    hit = live & (s.ri_low == low) & (s.ri_high == high)
    hit_any = mask & jnp.any(hit)
    hit_slot = jnp.argmax(hit)
    P_ = s.ri_acks.shape[1]
    oh2 = ((jnp.arange(RI, dtype=I32) == hit_slot)[:, None]
           & (jnp.arange(P_, dtype=I32) == sender_slot)[None, :] & hit_any)
    s = s._replace(ri_acks=jnp.where(oh2, True, s.ri_acks))
    n_acks = jnp.sum(_get_row(kp, s.ri_acks, hit_slot).astype(I32))
    quorum_ok = hit_any & (n_acks + 1 >= _quorum(s))
    pop_n = sel(quorum_ok, _get1(kp, qpos, hit_slot) + 1, 0)
    # pop: emit rtr for queue positions < pop_n
    popping = live & (qpos < pop_n)
    base = eff.rtr_n
    out_pos = base + qpos  # each popped ctx goes to rtr lane base+qpos
    # scatter via explicit loop over RI lanes (RI is small)
    rv, ri_, rl, rh = eff.rtr_valid, eff.rtr_index, eff.rtr_low, eff.rtr_high
    for j in range(RI):
        src = popping & (out_pos == j)
        any_src = jnp.any(src)
        k = jnp.argmax(src)
        rv = rv.at[j].set(sel(any_src, True, rv[j]))
        ri_ = ri_.at[j].set(sel(any_src, _get1(kp, s.ri_index, k), ri_[j]))
        rl = rl.at[j].set(sel(any_src, _get1(kp, s.ri_low, k), rl[j]))
        rh = rh.at[j].set(sel(any_src, _get1(kp, s.ri_high, k), rh[j]))
    eff = eff._replace(
        rtr_valid=rv, rtr_index=ri_, rtr_low=rl, rtr_high=rh,
        rtr_n=base + pop_n,
    )
    s = mrep(s, pop_n > 0,
             ri_head=(s.ri_head + pop_n) & (RI - 1),
             ri_count=s.ri_count - pop_n)
    return s, eff


# ---------------------------------------------------------------------------
# the per-message processor (scan body over K inbox slots)
# ---------------------------------------------------------------------------


class _Pre(NamedTuple):
    """Shared term/role preamble results for one inbound message."""

    act: jnp.ndarray
    is_leader: jnp.ndarray
    is_candidate: jnp.ndarray
    is_follower_like: jnp.ndarray
    sender_known: jnp.ndarray
    sender_slot: jnp.ndarray
    noop_reply: jnp.ndarray


class _Resp(NamedTuple):
    r_type: jnp.ndarray
    r_to: jnp.ndarray
    r_term: jnp.ndarray
    r_log_index: jnp.ndarray
    r_reject: jnp.ndarray
    r_hint: jnp.ndarray
    r_hint_high: jnp.ndarray


def _preamble(kp: P.KernelParams, s: ShardState, m):
    """Term preamble + role folding shared by every handler family —
    raft.go:1540 onMessageTermNotMatched + the candidate fold
    (raft.go:2218).  Returns the updated state and the masks handlers
    key on."""
    valid = m.from_ != 0
    mtype = m.mtype

    slot_hit = (s.pid == m.from_) & (s.kind != P.K_ABSENT)
    sender_known = jnp.any(slot_hit)
    sender_slot = jnp.argmax(slot_hit)

    is_rv_msg = (mtype == MT.REQUEST_VOTE) | (mtype == MT.REQUEST_PREVOTE)
    is_leader_msg = (
        (mtype == MT.REPLICATE)
        | (mtype == MT.HEARTBEAT)
        | (mtype == MT.TIMEOUT_NOW)
        | (mtype == MT.READ_INDEX_RESP)
    )

    drop_rv = (
        valid & is_rv_msg & s.check_quorum & (m.term > s.term)
        & (m.hint != m.from_)
        & (s.leader != 0) & (s.e_tick < s.e_timeout)
    )
    higher = valid & (m.term > s.term) & ~drop_rv
    prevote_expected = (mtype == MT.REQUEST_PREVOTE) | (
        (mtype == MT.REQUEST_PREVOTE_RESP) & ~m.reject
    )
    bump = higher & ~prevote_expected
    new_leader = sel(is_leader_msg, m.from_, 0)
    keep_tick = mtype == MT.REQUEST_VOTE
    s = _become_follower(s, bump, m.term, new_leader, reset_timeout=~keep_tick)

    lower = valid & (m.term < s.term) & (m.term != 0)
    # free-stuck-candidate NoOP (raft.go:1582-1589)
    noop_reply = lower & (
        (mtype == MT.REQUEST_PREVOTE)
        | (is_leader_msg & (s.check_quorum | s.pre_vote))
    )
    ignore = drop_rv | lower

    act = valid & ~ignore
    is_candidate = (s.role == P.CANDIDATE) | (s.role == P.PRE_VOTE_CANDIDATE)
    is_follower_like = (
        (s.role == P.FOLLOWER) | (s.role == P.NON_VOTING) | (s.role == P.WITNESS)
    )

    # candidate + same-term leader message -> become follower (raft.go:2218)
    cand_fold = act & is_candidate & (
        (mtype == MT.REPLICATE) | (mtype == MT.HEARTBEAT)
    )
    s = _become_follower(s, cand_fold, s.term, m.from_)
    is_follower_like = is_follower_like | cand_fold

    pre = _Pre(
        act=act,
        is_leader=s.role == P.LEADER,
        is_candidate=is_candidate,
        is_follower_like=is_follower_like,
        sender_known=sender_known,
        sender_slot=sender_slot,
        noop_reply=noop_reply,
    )
    return s, pre


def _empty_resp(s: ShardState, m, pre: _Pre) -> _Resp:
    return _Resp(
        r_type=sel(pre.noop_reply, MT.NOOP, jnp.asarray(0, I32)),
        r_to=m.from_,
        r_term=s.term,
        r_log_index=jnp.asarray(0, I32),
        r_reject=jnp.asarray(False),
        r_hint=jnp.asarray(0, I32),
        r_hint_high=jnp.asarray(0, I32),
    )


def _h_replicate(kp, s: ShardState, eff: Effects, m, pre: _Pre, r: _Resp):
    """Follower-side Replicate (raft.go:1444 handleReplicateMessage)."""
    E = kp.msg_entries
    h_rep = pre.act & pre.is_follower_like & (m.mtype == MT.REPLICATE)
    s = mrep(s, h_rep, leader=m.from_, e_tick=0)
    below_commit = m.log_index < s.committed
    prev_ok = match_term(kp, s, m.log_index, m.log_term)
    # ring-capacity guard: never let the append run past the term ring —
    # reject instead (the leader backs off; the host drives compaction /
    # snapshot install through the slow path). Keeps the invariant
    # last - snap_index <= log_cap so ring slots never alias.
    over_cap = (m.log_index + m.n_ent - s.snap_index) > kp.log_cap
    accept = h_rep & ~below_commit & prev_ok & ~over_cap
    s = mrep(s, h_rep & over_cap, needs_host=True)
    # conflict scan over the E entry lanes
    ent_idx = m.log_index + 1 + jnp.arange(E, dtype=I32)
    ent_live = jnp.arange(E, dtype=I32) < m.n_ent
    ent_match = jax.vmap(lambda i, t: match_term(kp, s, i, t))(ent_idx, m.ent_term)
    conflict_lane = ent_live & ~ent_match
    any_conflict = jnp.any(conflict_lane)
    first_conflict = jnp.argmax(conflict_lane)  # lane index
    # append entries from the first conflicting lane on
    do_append = accept & any_conflict
    append_from_lane = first_conflict
    # ring writes for lanes >= first_conflict (and live) — scatter-free:
    # each ring slot gathers its (consecutive mod cap) message lane instead
    # of the lanes scattering into the ring (see _set1 on why TPU scatters
    # are off-limits here; the gather form also fuses better)
    write_lane = ent_live & (jnp.arange(E, dtype=I32) >= append_from_lane)
    wmask = do_append & write_lane
    cap = s.lt.shape[0]
    rel = (jnp.arange(cap, dtype=I32) - _slot(kp, m.log_index + 1)) & (cap - 1)
    lane_of_slot = jnp.minimum(rel, E - 1)
    # [CAP]-shaped reads of the [E] message lanes go through _get1 (the
    # dynamic-index form is a G*CAP-row batched gather on device)
    slot_written = (rel < E) & _get1(kp, wmask, lane_of_slot)
    s = s._replace(
        lt=jnp.where(slot_written, _get1(kp, m.ent_term, lane_of_slot), s.lt),
        lcc=jnp.where(slot_written, _get1(kp, m.ent_cc, lane_of_slot), s.lcc),
    )
    if kp.inline_payloads:
        # trace-time contract: a payload-carrying kernel must be fed
        # payload lanes — substituting zeros would silently corrupt
        # follower state machines after a failover
        if m.ent_val is None:
            raise ValueError(
                "inline_payloads kernel requires Inbox.ent_val lanes")
        s = s._replace(
            lv=jnp.where(slot_written, _get1(kp, m.ent_val, lane_of_slot),
                         s.lv))
    new_last_if_append = m.log_index + m.n_ent
    s = mrep(s, do_append, last=new_last_if_append,
             stable=jnp.minimum(s.stable, m.log_index + append_from_lane))
    eff = eff._replace(
        save_from=sel(
            do_append,
            jnp.minimum(eff.save_from, m.log_index + append_from_lane + 1),
            eff.save_from,
        )
    )
    last_idx_msg = m.log_index + m.n_ent
    commit_to = jnp.minimum(
        jnp.minimum(last_idx_msg, m.commit), s.last
    )
    s = mrep(s, accept, committed=jnp.maximum(s.committed, commit_to))
    r = r._replace(
        r_type=sel(h_rep & below_commit, MT.REPLICATE_RESP, r.r_type),
        r_log_index=sel(h_rep & below_commit, s.committed, r.r_log_index),
    )
    r = r._replace(
        r_type=sel(accept, MT.REPLICATE_RESP, r.r_type),
        r_log_index=sel(accept, last_idx_msg, r.r_log_index),
    )
    rejected = h_rep & ~below_commit & (~prev_ok | over_cap)
    r = r._replace(
        r_type=sel(rejected, MT.REPLICATE_RESP, r.r_type),
        r_reject=sel(rejected, True, r.r_reject),
        r_log_index=sel(rejected, m.log_index, r.r_log_index),
        r_hint=sel(rejected, s.last, r.r_hint),
    )
    return s, eff, r


def _h_heartbeat(kp, s: ShardState, eff: Effects, m, pre: _Pre, r: _Resp):
    """Follower-side Heartbeat (raft.go:1398 handleHeartbeatMessage)."""
    h_hb = pre.act & pre.is_follower_like & (m.mtype == MT.HEARTBEAT)
    s = mrep(s, h_hb, leader=m.from_, e_tick=0,
             committed=jnp.maximum(s.committed, jnp.minimum(m.commit, s.last)))
    r = r._replace(
        r_type=sel(h_hb, MT.HEARTBEAT_RESP, r.r_type),
        r_hint=sel(h_hb, m.hint, r.r_hint),
        r_hint_high=sel(h_hb, m.hint_high, r.r_hint_high),
    )
    return s, eff, r


def _h_votereq(kp, s: ShardState, eff: Effects, m, pre: _Pre, r: _Resp):
    """RequestVote / RequestPreVote / TimeoutNow (raft.go:1697,1670,2188)."""
    act = pre.act
    # ---- RequestVote ----
    h_rv = act & (m.mtype == MT.REQUEST_VOTE)
    can_grant = (s.vote == 0) | (s.vote == m.from_)
    utd = up_to_date(kp, s, m.log_index, m.log_term)
    grant = h_rv & can_grant & utd
    s = mrep(s, grant, vote=m.from_, e_tick=0)
    r = r._replace(
        r_type=sel(h_rv, MT.REQUEST_VOTE_RESP, r.r_type),
        r_reject=sel(h_rv & ~grant, True, r.r_reject),
    )
    # ---- RequestPreVote ----
    h_pv = act & (m.mtype == MT.REQUEST_PREVOTE)
    pv_grant = h_pv & (m.term > s.term) & utd
    r = r._replace(
        r_type=sel(h_pv, MT.REQUEST_PREVOTE_RESP, r.r_type),
        r_term=sel(pv_grant, m.term, r.r_term),
        r_reject=sel(h_pv & ~pv_grant, True, r.r_reject),
    )
    # ---- TimeoutNow (follower; raft.go:2188) ----
    h_tn = act & (s.role == P.FOLLOWER) & (m.mtype == MT.TIMEOUT_NOW)
    s = mrep(s, h_tn, is_ltt=True)
    s, eff = _campaign(kp, s, eff, h_tn)
    s = mrep(s, h_tn, is_ltt=False)
    return s, eff, r


def _h_resp(kp, s: ShardState, eff: Effects, m, pre: _Pre, r: _Resp):
    """Response-side handlers: vote tallies, replication flow control,
    heartbeat acks, unreachable, snapshot status (raft.go:2246-2267,
    1878, 1912, 1997, 1975)."""
    act = pre.act
    is_leader = pre.is_leader
    sender_known, sender_slot = pre.sender_known, pre.sender_slot

    # ---- RequestVoteResp (candidate; raft.go:2246) ----
    h_vr = act & (s.role == P.CANDIDATE) & (m.mtype == MT.REQUEST_VOTE_RESP)
    h_vr = h_vr & sender_known & (_get1(kp, s.kind, sender_slot) != P.K_NON_VOTING)
    not_seen = ~_get1(kp, s.vresp, sender_slot)
    s = s._replace(
        vresp=_set1(s.vresp, sender_slot, True, h_vr),
        vgrant=_set1(s.vgrant, sender_slot, ~m.reject, h_vr & not_seen),
    )
    votes_for = jnp.sum(s.vgrant.astype(I32))
    votes_against = jnp.sum((s.vresp & ~s.vgrant).astype(I32))
    q = _quorum(s)
    s, eff = _become_leader(kp, s, h_vr & (votes_for == q), eff)
    s = _become_follower(s, h_vr & (votes_against == q), s.term, 0)

    # ---- RequestPreVoteResp (raft.go:2267) ----
    h_pvr = act & (s.role == P.PRE_VOTE_CANDIDATE) & (
        m.mtype == MT.REQUEST_PREVOTE_RESP
    )
    h_pvr = h_pvr & sender_known & (_get1(kp, s.kind, sender_slot) != P.K_NON_VOTING)
    not_seen = ~_get1(kp, s.vresp, sender_slot)
    s = s._replace(
        vresp=_set1(s.vresp, sender_slot, True, h_pvr),
        vgrant=_set1(s.vgrant, sender_slot, ~m.reject, h_pvr & not_seen),
    )
    votes_for = jnp.sum(s.vgrant.astype(I32))
    votes_against = jnp.sum((s.vresp & ~s.vgrant).astype(I32))
    s, eff = _campaign(kp, s, eff, h_pvr & (votes_for == q),
                       allow_prevote=False)
    s = _become_follower(s, h_pvr & (votes_against == q), s.term, 0)

    # ---- ReplicateResp (leader; raft.go:1878) ----
    h_rr = act & is_leader & (m.mtype == MT.REPLICATE_RESP) & sender_known
    s = s._replace(active=_set1(s.active, sender_slot, True, h_rr))
    old_match = _get1(kp, s.match, sender_slot)
    old_next = _get1(kp, s.next, sender_slot)
    old_pstate = _get1(kp, s.pstate, sender_slot)
    paused = (old_pstate == P.R_WAIT) | (old_pstate == P.R_SNAPSHOT)
    # non-reject: tryUpdate
    ok_resp = h_rr & ~m.reject
    updated = ok_resp & (old_match < m.log_index)
    s = s._replace(
        next=_set1(s.next, sender_slot,
                   jnp.maximum(old_next, m.log_index + 1), ok_resp),
        match=_set1(s.match, sender_slot, m.log_index, updated),
    )
    # wait_to_retry then respondedTo: retry->replicate; snapshot->retry if caught up
    ps = _get1(kp, s.pstate, sender_slot)
    ps = sel(updated & (ps == P.R_WAIT), P.R_RETRY, ps)
    ps = sel(updated & (ps == P.R_RETRY), P.R_REPLICATE, ps)
    snap_caught = _get1(kp, s.match, sender_slot) >= _get1(kp, s.psnap, sender_slot)
    ps = sel(updated & (ps == P.R_SNAPSHOT) & snap_caught, P.R_RETRY, ps)
    s = s._replace(
        pstate=_set1(s.pstate, sender_slot, ps, h_rr),
        psnap=_set1(s.psnap, sender_slot, 0,
                    updated & (old_pstate == P.R_SNAPSHOT) & snap_caught),
    )
    committed_before = s.committed
    s = jax.tree_util.tree_map(
        lambda a, b: sel(updated, a, b), _try_commit(kp, s), s
    )
    commit_advanced = s.committed > committed_before
    # broadcast on commit advance; else resend to the (formerly paused) peer
    eff = eff._replace(
        need_rep=sel(
            updated & commit_advanced, jnp.ones_like(eff.need_rep),
            _set1(eff.need_rep, sender_slot, True,
                  updated & ~commit_advanced & paused),
        )
    )
    # leadership transfer: target caught up -> TimeoutNow (raft.go:1893)
    tn = updated & (s.ltt == m.from_) & (_get1(kp, s.match, sender_slot) == s.last)
    eff = eff._replace(send_tn=_set1(eff.send_tn, sender_slot, True, tn))
    # reject: decreaseTo (remote.go:decreaseTo) + resend
    rej = h_rr & m.reject
    in_replicate = old_pstate == P.R_REPLICATE
    dec_ok_rep = rej & in_replicate & (m.log_index > old_match)
    dec_ok_probe = rej & ~in_replicate & (old_next - 1 == m.log_index)
    new_next = sel(
        in_replicate, old_match + 1,
        jnp.maximum(1, jnp.minimum(m.log_index, m.hint + 1)),
    )
    dec = dec_ok_rep | dec_ok_probe
    dec_ps = sel(dec_ok_rep, P.R_RETRY,
                 sel(dec_ok_probe & (_get1(kp, s.pstate, sender_slot) == P.R_WAIT),
                     P.R_RETRY, _get1(kp, s.pstate, sender_slot)))
    s = s._replace(
        next=_set1(s.next, sender_slot, new_next, dec),
        pstate=_set1(s.pstate, sender_slot, dec_ps, h_rr),
    )
    eff = eff._replace(need_rep=_set1(eff.need_rep, sender_slot, True, dec))

    # ---- HeartbeatResp (leader; raft.go:1912) ----
    h_hr = act & is_leader & (m.mtype == MT.HEARTBEAT_RESP) & sender_known
    s = s._replace(
        active=_set1(s.active, sender_slot, True, h_hr),
        pstate=_set1(s.pstate, sender_slot, P.R_RETRY,
                     h_hr & (_get1(kp, s.pstate, sender_slot) == P.R_WAIT)),
    )
    lagging = _get1(kp, s.match, sender_slot) < s.last
    eff = eff._replace(need_rep=_set1(eff.need_rep, sender_slot, True,
                                      h_hr & lagging))
    conf = h_hr & (m.hint != 0)
    s_c, eff_c = _ri_confirm(kp, s, eff, conf, m.hint, m.hint_high, sender_slot)
    s = jax.tree_util.tree_map(lambda a, b: sel(conf, a, b), s_c, s)
    eff = jax.tree_util.tree_map(lambda a, b: sel(conf, a, b), eff_c, eff)

    # ---- Unreachable (leader; raft.go:1997) ----
    h_un = act & is_leader & (m.mtype == MT.UNREACHABLE) & sender_known
    s = s._replace(pstate=_set1(
        s.pstate, sender_slot, P.R_RETRY,
        h_un & (_get1(kp, s.pstate, sender_slot) == P.R_REPLICATE)))

    # ---- SnapshotStatus (leader, immediate variant; raft.go:1975) ----
    h_ss = act & is_leader & (m.mtype == MT.SNAPSHOT_STATUS) & sender_known
    in_snap = _get1(kp, s.pstate, sender_slot) == P.R_SNAPSHOT
    # becomeWait: next = max(match+1, psnap+1) on success; clear psnap on reject
    nn = sel(
        m.reject, _get1(kp, s.match, sender_slot) + 1,
        jnp.maximum(_get1(kp, s.match, sender_slot) + 1, _get1(kp, s.psnap, sender_slot) + 1),
    )
    s = s._replace(
        next=_set1(s.next, sender_slot, nn, h_ss & in_snap),
        psnap=_set1(s.psnap, sender_slot, 0, h_ss & in_snap),
        pstate=_set1(s.pstate, sender_slot, P.R_WAIT, h_ss & in_snap),
    )
    return s, eff, r


_FAMILY_HANDLERS = {
    "rep": (_h_replicate,),
    "hb": (_h_heartbeat,),
    "vote": (_h_votereq,),
    "resp": (_h_resp,),
    "any": (_h_replicate, _h_heartbeat, _h_votereq, _h_resp),
}

def _process_family(kp: P.KernelParams, family: str, s: ShardState,
                    eff: Effects, m):
    """One inbound message against one shard, with only ``family``'s
    handlers compiled in — the dispatch-by-type analog of raft.Handle
    (raft.go:1596).  'any' composes every handler (masks are mutually
    exclusive per message type, so composition order cannot change the
    result for a single message)."""
    s, pre = _preamble(kp, s, m)
    r = _empty_resp(s, m, pre)
    for h in _FAMILY_HANDLERS[family]:
        s, eff, r = h(kp, s, eff, m, pre, r)
    return s, eff, r


# ---------------------------------------------------------------------------
# full per-shard step
# ---------------------------------------------------------------------------


def _shard_step(kp: P.KernelParams, s: ShardState, box, inp):
    """Advance one shard one step (vmapped over [G])."""
    E, K, B, RI, Pn = (
        kp.msg_entries, kp.inbox_cap, kp.proposal_cap,
        kp.readindex_cap, kp.num_peers,
    )
    eff = _empty_effects(kp)
    save_base = s.stable  # entries above this are unsaved at step start

    # 0. host-confirmed applied cursor
    s = s._replace(applied=jnp.maximum(s.applied, inp.applied))

    # 0b. device quiesce wake (quiesce.go:60-77 record): any non-heartbeat
    # inbound message or client activity (proposal, read, transfer) wakes
    # the lane, resets its idle clock and bumps the wake epoch the quiesce
    # invariants key on.  Heartbeats never count as activity: while awake
    # they must not defer quiesce entry (quiesce.go:64), and while
    # masked-quiesced the handlers below still process them, so —
    # divergence from the reference's grace-window wake — no wake is
    # needed for state parity.  e_tick resets so a lane whose election
    # clock banked up across quiesced ticks cannot campaign the instant
    # it wakes.
    hb_like = (box.mtype == MT.HEARTBEAT) | (box.mtype == MT.HEARTBEAT_RESP)
    activity = (
        jnp.any((box.from_ != 0) & ~hb_like)
        | jnp.any(inp.prop_valid) | inp.ri_valid | (inp.transfer_to != 0)
    )
    wake = s.quiesced & activity
    s = mrep(s, wake, quiesced=False, idle_tick=0, e_tick=0,
             quiesce_epoch=s.quiesce_epoch + 1)

    # 1. inbox processing — slots grouped by their static family
    # (params.slot_families): each family's scan body compiles ONLY that
    # family's handlers, cutting the serial full-matrix cost by ~4x on
    # the router's typed layout (PERF.md lever #1).  'any' slots keep the
    # full matrix for host-staged arbitrary traffic.
    fams = P.slot_families(K)
    order = []
    for fam in ("resp", "rep", "hb", "vote", "any"):
        idxs = [k for k, f in enumerate(fams) if f == fam]
        if idxs:
            order.append((fam, idxs))
    r_parts = []
    for fam, idxs in order:
        if idxs == list(range(K)):
            sub = box
        else:
            gather = jnp.asarray(idxs, I32)
            sub = jax.tree_util.tree_map(lambda a: a[gather], box)

        def _scan_msg(carry, m, _fam=fam):
            s_, eff_ = carry
            s_, eff_, r = _process_family(kp, _fam, s_, eff_, m)
            return (s_, eff_), tuple(r)

        # Rolled by default (unrolling materializes a fresh [G, log_cap]
        # ring copy per slot in the replicate body; measured 11x slower
        # on XLA:CPU, 2026-07-30, where the rolled carry aliases in
        # place — and the hand-restructured merged-family variant that
        # deferred the ring writes measured slower on BOTH platforms, so
        # it was removed in r5).  kp.unroll_scans flips lax.scan's
        # bitwise-neutral unroll flag for the device A/B: on TPU each
        # scan iteration is a separate serial launch of the whole body.
        (s, eff), part = jax.lax.scan(
            _scan_msg, (s, eff), sub,
            unroll=len(idxs) if kp.unroll_scans else 1)
        r_parts.append(part)
    r_stack = tuple(
        jnp.concatenate([p[i] for p in r_parts], axis=0)
        if len(r_parts) > 1 else r_parts[0][i]
        for i in range(7)
    )

    # 2. batched ReadIndex request (node.go:1296 handleReadIndex batches all
    #    queued reads under one ctx; host routes to the leader replica)
    is_leader = s.role == P.LEADER
    ri_req = inp.ri_valid & is_leader
    lt_committed, comp_c, _ = log_term_at(kp, s, s.committed)
    has_cur_term_commit = (sel(comp_c, 0, lt_committed) == s.term) & (s.term > 0)
    single = _is_single_node(s)
    # single-node fast path → ready immediately
    fast = ri_req & single
    lane = jnp.minimum(eff.rtr_n, RI - 1)
    eff = eff._replace(
        rtr_valid=_set1(eff.rtr_valid, lane, True, fast),
        rtr_index=_set1(eff.rtr_index, lane, s.committed, fast),
        rtr_low=_set1(eff.rtr_low, lane, inp.ri_low, fast),
        rtr_high=_set1(eff.rtr_high, lane, inp.ri_high, fast),
        rtr_n=eff.rtr_n + sel(fast, 1, 0),
    )
    quorum_path = ri_req & ~single & has_cur_term_commit
    s, dropped_full = _ri_push(kp, s, quorum_path, inp.ri_low, inp.ri_high,
                               s.committed)
    eff = eff._replace(
        need_hb=eff.need_hb | (quorum_path & ~dropped_full),
        hb_low=sel(quorum_path, inp.ri_low, eff.hb_low),
        hb_high=sel(quorum_path, inp.ri_high, eff.hb_high),
        ri_dropped=eff.ri_dropped
        | (inp.ri_valid & (~is_leader | (ri_req & ~single & ~has_cur_term_commit)))
        | dropped_full,
    )

    # 3. proposals (leader only, not while transferring; raft.go:1794)
    can_prop = is_leader & (s.ltt == 0)

    prop_vals = (inp.prop_val if inp.prop_val is not None
                 else jnp.zeros_like(inp.prop_cc, I32))

    # Closed-form batch append — this was a B-iteration lax.scan, and
    # serial loops are poison on TPU (every iteration is its own tiny
    # launch over the whole [G] state).  The scan's slot-order semantics
    # are reproduced exactly:
    #  - ring-capacity guard: `last` advances per accept and the room
    #    check is monotone within a batch, so capping the accept RANK at
    #    the remaining room cuts the same suffix the per-slot check did
    #    (host sees prop_accepted=False → system busy; compaction frees
    #    space — the reference's in-mem log rate limiting);
    #  - one-at-a-time config change: only the first CC candidate lands
    #    while none is pending; later CCs in the batch drop.
    v0 = inp.prop_valid & can_prop                           # [B]
    cc_cand = v0 & inp.prop_cc & ~s.pending_cc
    cc_first = cc_cand & (jnp.cumsum(cc_cand.astype(I32)) == 1)
    do1 = v0 & (~inp.prop_cc | cc_first)
    m_max = kp.log_cap - (s.last - s.snap_index)             # ring room left
    do = do1 & (jnp.cumsum(do1.astype(I32)) <= m_max)
    rank = jnp.cumsum(do.astype(I32))                        # 1-based
    n_total = rank[-1]
    appended_any = n_total > 0
    prop_accepted = do
    prop_index = sel(do, s.last + rank, 0)
    prop_term = sel(do, jnp.broadcast_to(s.term, do.shape), 0)
    # compress accepted slots by rank: off j holds (is_cc, val) of the
    # rank-(j+1) accept — the ring write below reads by offset
    B = do.shape[0]
    rank_onehot = (rank[None, :] == (jnp.arange(B, dtype=I32) + 1)[:, None]) \
        & do[None, :]                                        # [B(off), B(slot)]
    cc_by_off = jnp.any(rank_onehot & cc_first[None, :], axis=1)
    val_by_off = jnp.sum(rank_onehot * prop_vals[None, :], axis=1)
    # one pass over the ring: position p hosts unwrapped index base+off;
    # n_total <= B << log_cap, so the append window never self-wraps
    base = s.last + 1
    pos = jnp.arange(kp.log_cap, dtype=I32)
    off = (pos - _slot(kp, base)) & (kp.log_cap - 1)
    in_win = off < n_total
    off_c = jnp.minimum(off, B - 1)
    # [CAP]-indexed reads of the [B] by-offset tables: _get1 handles the
    # vector index (one-hot [CAP, B] on device, gather on CPU)
    s = s._replace(
        lt=sel(in_win, jnp.broadcast_to(s.term, pos.shape), s.lt),
        lcc=sel(in_win, _get1(kp, cc_by_off, off_c), s.lcc),
        last=s.last + n_total,
        pending_cc=s.pending_cc | jnp.any(do & cc_first),
    )
    if kp.inline_payloads:
        s = s._replace(lv=sel(in_win, _get1(kp, val_by_off, off_c), s.lv))
    eff = eff._replace(save_from=sel(
        appended_any, jnp.minimum(eff.save_from, base), eff.save_from))
    self_mask = _self_slot_mask(s)
    s = s._replace(
        match=sel(appended_any & self_mask, s.last, s.match),
        next=sel(appended_any & self_mask, s.last + 1, s.next),
    )
    s = jax.tree_util.tree_map(
        lambda a, b: sel(appended_any & single, a, b), _try_commit(kp, s), s
    )
    eff = eff._replace(need_rep=sel(appended_any, jnp.ones_like(eff.need_rep),
                                    eff.need_rep))

    # 4. leadership transfer request (raft.go:1925 handleLeaderTransfer)
    tr = inp.transfer_to
    tr_req = (tr != 0) & is_leader & (s.ltt == 0) & (tr != s.replica_id)
    tr_hit = (s.pid == tr) & (s.kind == P.K_VOTER)
    tr_known = jnp.any(tr_hit)
    tr_slot = jnp.argmax(tr_hit)
    do_tr = tr_req & tr_known
    s = mrep(s, do_tr, ltt=tr, e_tick=0)
    fast_tn = do_tr & (_get1(kp, s.match, tr_slot) == s.last)
    eff = eff._replace(send_tn=_set1(eff.send_tn, tr_slot, True, fast_tn))

    # 5. tick (raft.go:571-655)
    is_leader = s.role == P.LEADER  # refresh (campaigns can't happen above)
    # the quiesced mask is the union of the host-driven input flag and
    # the device-resident mask (post-wake, so an activity step ticks live)
    q_any = inp.quiesced | s.quiesced
    live_tick = inp.tick & ~q_any
    # quiesced tick: just advance the election clock
    s = mrep(s, inp.tick & q_any, e_tick=s.e_tick + 1)
    # non-leader tick
    nl = live_tick & ~is_leader
    s = mrep(s, nl, e_tick=s.e_tick + 1)
    can_campaign = (
        (s.role == P.FOLLOWER) | (s.role == P.CANDIDATE)
        | (s.role == P.PRE_VOTE_CANDIDATE)
    )
    elect = nl & can_campaign & (s.e_tick >= s.rand_timeout)
    s = mrep(s, elect, e_tick=0)
    s, eff = _campaign(kp, s, eff, elect)
    # leader tick
    lt_ = live_tick & is_leader
    s = mrep(s, lt_, e_tick=s.e_tick + 1)
    cq_time = lt_ & (s.e_tick >= s.e_timeout)
    abort_tr = cq_time & (s.ltt != 0)
    s = mrep(s, cq_time, e_tick=0)
    # checkQuorum (raft.go:1785): count active voters (self counts), reset
    do_cq = cq_time & s.check_quorum
    active_v = jnp.sum(
        (_voting_mask(s) & (s.active | _self_slot_mask(s))).astype(I32)
    )
    lost = do_cq & (active_v < _quorum(s))
    s = s._replace(active=sel(do_cq, jnp.zeros_like(s.active), s.active))
    s = _become_follower(s, lost, s.term, 0)
    s = mrep(s, abort_tr & ~lost, ltt=0)
    is_leader = s.role == P.LEADER
    lt_ = lt_ & is_leader
    s = mrep(s, lt_, h_tick=s.h_tick + 1)
    hb_time = lt_ & (s.h_tick >= s.h_timeout)
    s = mrep(s, hb_time, h_tick=0)
    # heartbeat broadcast uses the newest pending RI ctx (raft.go:849)
    RIm = kp.readindex_cap - 1
    newest = (s.ri_head + s.ri_count - 1) & RIm
    has_pending = s.ri_count > 0
    eff = eff._replace(
        need_hb=eff.need_hb | hb_time,
        hb_low=sel(hb_time, sel(has_pending, _get1(kp, s.ri_low, newest), 0),
                   eff.hb_low),
        hb_high=sel(hb_time, sel(has_pending, _get1(kp, s.ri_high, newest), 0),
                    eff.hb_high),
    )

    # 5b. device quiesce idle clock + entry (quiesce.go:43-54 tick): an
    # enabled, awake lane idle for e_timeout*10 ticks (quiesce.py
    # threshold) raises its quiesced mask; entry clears both protocol
    # clocks so neither an election nor a heartbeat fires mid-quiesce.
    # Entry is evaluated AFTER this step's tick work, so the step that
    # crosses the threshold still ran live — the mask only gates future
    # steps, and the kernel stays bitwise-identical with quiesce_on off.
    s = mrep(s, inp.tick & ~activity & ~s.quiesced,
             idle_tick=s.idle_tick + 1)
    s = mrep(s, activity, idle_tick=0)
    enter_q = (s.quiesce_on & ~s.quiesced & inp.tick
               & (s.idle_tick >= s.e_timeout * 10))
    s = mrep(s, enter_q, quiesced=True, e_tick=0, h_tick=0)

    # 6. send phase ------------------------------------------------------
    is_leader = s.role == P.LEADER
    not_self = ~_self_slot_mask(s)
    present = s.kind != P.K_ABSENT

    # replicate lanes (sendReplicateMessage; raft.go:800)
    want_rep = eff.need_rep & is_leader & present & not_self
    pausedP = (s.pstate == P.R_WAIT) | (s.pstate == P.R_SNAPSHOT)
    can_send = want_rep & ~pausedP
    prev = s.next - 1
    prev_term, prev_comp, _ = jax.vmap(lambda i: log_term_at(kp, s, i))(prev)
    needs_snap = can_send & prev_comp  # log compacted under the peer
    # witness peers take a file-less stripped snapshot the host can
    # build from the recorded snapshot directly (raft.go:720-735) — no
    # stream, no eviction; only non-witness peers escalate
    wit_snap = needs_snap & (s.kind == P.K_WITNESS)
    send_rep = can_send & ~prev_comp
    n_avail = jnp.clip(s.last - prev, 0, E)
    lane = jnp.arange(E, dtype=I32)
    ent_idx = s.next[:, None] + lane[None, :]          # [P, E]
    ent_live = lane[None, :] < n_avail[:, None]
    eslot = _slot(kp, ent_idx)
    ent_term = sel(ent_live, _get1(kp, s.lt, eslot), 0)
    ent_cc = sel(ent_live, _get1(kp, s.lcc, eslot), False)
    ent_val = (sel(ent_live, _get1(kp, s.lv, eslot), 0)
               if kp.inline_payloads else None)
    # optimistic pipelined advance (remote.go:progress)
    adv = send_rep & (s.pstate == P.R_REPLICATE) & (n_avail > 0)
    s = s._replace(
        next=sel(adv, s.next + n_avail, s.next),
        pstate=sel(send_rep & (s.pstate == P.R_RETRY), P.R_WAIT,
                   sel(needs_snap, P.R_SNAPSHOT, s.pstate)),
        psnap=sel(needs_snap, s.snap_index, s.psnap),
    )
    s = mrep(s, jnp.any(needs_snap & ~wit_snap), needs_host=True)

    # heartbeat lanes (broadcastHeartbeatMessageWithHint; raft.go:859-871)
    has_ctx = (eff.hb_low != 0) | (eff.hb_high != 0)
    hb_target = present & not_self & (
        _voting_mask(s) | (~has_ctx & (s.kind == P.K_NON_VOTING))
    )
    send_hb = eff.need_hb & is_leader & hb_target
    hb_commit = jnp.minimum(s.match, s.committed)

    # vote-request lanes — masked by END-OF-STEP role: a campaign started
    # earlier in the step may have been cancelled by a later message (e.g.
    # a higher-term Replicate folded us back to follower); only a live
    # candidate may broadcast at its current term
    role_ok = sel(eff.send_vote == 2, s.role == P.PRE_VOTE_CANDIDATE,
                  s.role == P.CANDIDATE)
    vr = (eff.send_vote > 0) & role_ok & _voting_mask(s) & not_self
    vote_term = sel(eff.send_vote == 2, s.term + 1, s.term)
    last_t, _, _ = log_term_at(kp, s, s.last)

    # persistence: entries (save_first..save_last] inclusive-of-first form
    save_first = sel(eff.save_from == INT_MAX, save_base + 1,
                     jnp.minimum(eff.save_from, save_base + 1))
    save_last = s.last
    s = s._replace(stable=jnp.maximum(save_last, 0))

    # apply release (pagination per logentry.go:268)
    apply_first = s.processed + 1
    apply_last = jnp.minimum(s.committed, s.processed + kp.apply_batch)
    s = s._replace(processed=jnp.maximum(s.processed, apply_last))

    # device-side log compaction — the ring analog of removeLog()
    # (node.go:803): raise the snapshot floor over entries that are applied
    # everywhere we care about, keeping compaction_overhead entries for
    # laggards (config.CompactionOverhead). A leader also retains anything
    # a present peer still needs (min match).
    peer_floor = jnp.min(
        sel(
            (s.kind != P.K_ABSENT) & ~_self_slot_mask(s),
            s.match, INT_MAX,
        )
    )
    floor = jnp.minimum(s.applied, s.committed)
    floor = sel(is_leader, jnp.minimum(floor, peer_floor), floor)
    new_snap = jnp.maximum(
        s.snap_index, floor - kp.compaction_overhead
    )
    new_snap_term, nsc, nsu = log_term_at(kp, s, new_snap)
    can_compact = (new_snap > s.snap_index) & ~nsc & ~nsu
    s = mrep(s, can_compact, snap_index=new_snap, snap_term=new_snap_term)

    out = StepOutput(
        r_type=r_stack[0], r_to=r_stack[1], r_term=r_stack[2],
        r_log_index=r_stack[3], r_reject=r_stack[4], r_hint=r_stack[5],
        r_hint_high=r_stack[6],
        s_rep=send_rep, s_prev_index=prev, s_prev_term=sel(prev_comp, 0, prev_term),
        s_commit=jnp.broadcast_to(s.committed, (Pn,)),
        s_n_ent=sel(send_rep, n_avail, 0),
        s_ent_term=ent_term, s_ent_cc=ent_cc, s_ent_val=ent_val,
        s_vote=sel(vr, eff.send_vote, 0),
        s_vote_term=jnp.broadcast_to(vote_term, (Pn,)),
        s_vote_lindex=jnp.broadcast_to(s.last, (Pn,)),
        s_vote_lterm=jnp.broadcast_to(last_t, (Pn,)),
        s_vote_hint=jnp.broadcast_to(eff.vote_hint, (Pn,)),
        s_hb=send_hb, s_hb_commit=hb_commit,
        s_hb_low=jnp.broadcast_to(eff.hb_low, (Pn,)),
        s_hb_high=jnp.broadcast_to(eff.hb_high, (Pn,)),
        s_timeout_now=eff.send_tn & is_leader,
        s_need_snapshot=needs_snap & ~wit_snap,
        s_wit_snap=wit_snap,
        save_first=save_first, save_last=save_last,
        apply_first=apply_first, apply_last=apply_last,
        term=s.term, vote=s.vote, commit=s.committed,
        rtr_valid=eff.rtr_valid, rtr_index=eff.rtr_index,
        rtr_low=eff.rtr_low, rtr_high=eff.rtr_high,
        ri_dropped=eff.ri_dropped,
        prop_accepted=prop_accepted, prop_index=prop_index, prop_term=prop_term,
        leader=s.leader, leader_term=s.term,
        needs_host=s.needs_host,
    )
    return s, out


@functools.partial(jax.jit, static_argnums=0)
def step(kp: P.KernelParams, state: ShardState, inbox: Inbox,
         inp: StepInput) -> tuple[ShardState, StepOutput]:
    """vmap the per-shard step across the [G] axis and jit the result."""
    return jax.vmap(functools.partial(_shard_step, kp))(state, inbox, inp)


# Donated entry point for the pipelined engine loop: identical math to
# ``step``, but XLA may reuse the state/inbox/input buffers for the
# outputs instead of allocating fresh SoA arrays every step.  The host
# contract this implies is declared in kstate.DONATION and cross-checked
# by analysis/contracts.py (KC008): after a step_donated dispatch the
# caller must treat the donated arrays as dead — every host read goes
# through the RETURNED state or the host mirrors, never the arguments.
# Backends without donation support (CPU) fall back to copying; the
# engine keeps the same no-touch discipline on all backends so the
# differential oracle covers the strict contract.
@functools.partial(jax.jit, static_argnums=0, donate_argnums=(1, 2, 3))
def step_donated(kp: P.KernelParams, state: ShardState, inbox: Inbox,
                 inp: StepInput) -> tuple[ShardState, StepOutput]:
    """``step`` with state/inbox/input buffers donated to XLA."""
    return jax.vmap(functools.partial(_shard_step, kp))(state, inbox, inp)


# Message-class order of the [G, C] activity-flag matrix produced by
# ``output_row_flags`` — the engine's masked output fetch keys on these
# columns to decide which wide StepOutput fields to materialize at all.
FLAG_CLASSES = ("resp", "rep", "hb", "vote", "timeout_now",
                "need_snapshot", "wit_snap", "rtr")


@jax.jit
def output_row_flags(outs) -> jnp.ndarray:
    """[G, C] bool: per-row any() over each message class of a StepOutput.

    One tiny device reduction replaces the host-side per-field
    ``np.asarray(...).any(axis=1)`` sweep that previously forced every
    wide [G, K]/[G, P]/[G, RI] output field across the device boundary
    every step.  Column order is ``FLAG_CLASSES``."""
    cols = (
        jnp.any(outs.r_type != 0, axis=1),
        jnp.any(outs.s_rep, axis=1),
        jnp.any(outs.s_hb, axis=1),
        jnp.any(outs.s_vote != 0, axis=1),
        jnp.any(outs.s_timeout_now, axis=1),
        jnp.any(outs.s_need_snapshot, axis=1),
        jnp.any(outs.s_wit_snap, axis=1),
        jnp.any(outs.rtr_valid, axis=1),
    )
    return jnp.stack(cols, axis=1)
