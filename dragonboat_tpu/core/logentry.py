"""Host-side raft entry log: in-memory tier over a stable-storage reader.

Re-expression of the reference's two-tier log view
(``internal/raft/logentry.go:78`` entryLog, ``internal/raft/inmemory.go:30``
inMemory): ``committed``/``processed`` cursors over a merged view of
not-yet-stable in-memory entries and a stable LogDB window.  The TPU build
keeps this host-side structure for the slow path and host interop; the device
ring in :mod:`dragonboat_tpu.core.kernel` holds the fixed-width mirror.
"""

from __future__ import annotations

from typing import Protocol, Sequence

from dragonboat_tpu import raftpb as pb


class CompactedError(Exception):
    """Requested entries are no longer available (compacted away).

    Parity: internal/raft/logentry.go ErrCompacted."""


class UnavailableError(Exception):
    """Requested entries are beyond the last known index."""


class ILogDBReader(Protocol):
    """Read-side view the raft core has of stable storage.

    Parity: the raft.ILogDB interface at internal/raft/logentry.go:45."""

    def first_index(self) -> int: ...
    def last_index(self) -> int: ...
    def term(self, index: int) -> int: ...
    def entries(self, low: int, high: int, max_size: int) -> list[pb.Entry]: ...
    def snapshot(self) -> pb.Snapshot: ...
    def append(self, entries: Sequence[pb.Entry]) -> None: ...
    def apply_snapshot(self, ss: pb.Snapshot) -> None: ...


class InMemoryLogDB:
    """A trivial in-memory ILogDB reader used by tests and the loopback
    runtime (the model for the reference's TestLogDB fixture)."""

    def __init__(self) -> None:
        self._entries: list[pb.Entry] = []
        self._snapshot = pb.Snapshot()
        self._marker = 1  # index of _entries[0]

    def first_index(self) -> int:
        return self._marker

    def last_index(self) -> int:
        return self._marker + len(self._entries) - 1

    def term(self, index: int) -> int:
        if index == self._snapshot.index:
            return self._snapshot.term
        if index < self._marker:
            raise CompactedError(index)
        if index > self.last_index():
            raise UnavailableError(index)
        return self._entries[index - self._marker].term

    def entries(self, low: int, high: int, max_size: int) -> list[pb.Entry]:
        if low < self._marker:
            raise CompactedError(low)
        if high > self.last_index() + 1:
            raise UnavailableError(high)
        out = self._entries[low - self._marker : high - self._marker]
        if max_size > 0:
            size = 0
            for i, e in enumerate(out):
                size += pb.entry_size(e)
                if size > max_size and i > 0:
                    return out[:i]
        return list(out)

    def snapshot(self) -> pb.Snapshot:
        return self._snapshot

    def append(self, entries: Sequence[pb.Entry]) -> None:
        if not entries:
            return
        first = entries[0].index
        if first > self.last_index() + 1:
            raise ValueError(f"gap: {first} > {self.last_index() + 1}")
        if first < self._marker:
            entries = [e for e in entries if e.index >= self._marker]
            if not entries:
                return
            first = entries[0].index
        self._entries[first - self._marker :] = list(entries)

    def apply_snapshot(self, ss: pb.Snapshot) -> None:
        self._snapshot = ss
        self._marker = ss.index + 1
        self._entries = []

    def compact(self, index: int) -> None:
        if index < self._marker:
            return
        keep_from = index + 1 - self._marker
        self._entries = self._entries[keep_from:]
        self._marker = index + 1


class InMemory:
    """Sliding window of not-yet-stable entries.

    Parity: internal/raft/inmemory.go:30 (inMemory) — marker/savedTo GC,
    snapshot intake, merge with truncation."""

    def __init__(self, last_index: int) -> None:
        self.marker = last_index + 1
        self.entries: list[pb.Entry] = []
        self.saved_to = last_index
        self.snapshot: pb.Snapshot | None = None

    def get_snapshot_index(self) -> int | None:
        return self.snapshot.index if self.snapshot is not None else None

    def get_entries(self, low: int, high: int) -> list[pb.Entry]:
        if low > high or low < self.marker:
            raise CompactedError(low)
        upper = self.marker + len(self.entries)
        if high > upper:
            raise UnavailableError(high)
        return self.entries[low - self.marker : high - self.marker]

    def get_last_index(self) -> int | None:
        if self.entries:
            return self.entries[-1].index
        if self.snapshot is not None:
            return self.snapshot.index
        return None

    def has_entries_to_save(self) -> bool:
        return bool(self.entries_to_save())

    def entries_to_save(self) -> list[pb.Entry]:
        idx = self.saved_to + 1
        if idx - self.marker > len(self.entries):
            return []
        if idx < self.marker:
            idx = self.marker
        return self.entries[idx - self.marker :]

    def saved_log_to(self, index: int, term: int) -> None:
        if index < self.marker:
            return
        if not self.entries:
            return
        if index > self.entries[-1].index:
            return
        if self.entries[index - self.marker].term != term:
            return
        self.saved_to = index

    def saved_snapshot_to(self, index: int) -> None:
        if self.snapshot is not None and self.snapshot.index == index:
            self.snapshot = None

    def applied_log_to(self, index: int) -> None:
        """GC entries at or below the applied index (they are stable and
        applied, so the in-mem tier no longer needs them)."""
        if index < self.marker or not self.entries:
            return
        if index > self.saved_to:
            # never drop unsaved entries
            index = self.saved_to
        if index < self.marker:
            return
        new_marker = index + 1
        self.entries = self.entries[new_marker - self.marker :]
        self.marker = new_marker

    def merge(self, ents: Sequence[pb.Entry]) -> None:
        if not ents:
            return
        first = ents[0].index
        self.saved_to = min(self.saved_to, first - 1)
        if first == self.marker + len(self.entries):
            self.entries.extend(ents)
        elif first <= self.marker:
            self.marker = first
            self.entries = list(ents)
        else:
            self.entries = self.entries[: first - self.marker]
            self.entries.extend(ents)

    def restore(self, ss: pb.Snapshot) -> None:
        self.snapshot = ss
        self.marker = ss.index + 1
        self.entries = []
        self.saved_to = ss.index


class EntryLog:
    """The merged two-tier log view — parity with
    internal/raft/logentry.go:78 (entryLog)."""

    def __init__(self, logdb: ILogDBReader) -> None:
        self.logdb = logdb
        self.inmem = InMemory(logdb.last_index())
        self.committed = logdb.first_index() - 1
        self.processed = logdb.first_index() - 1

    # -- index/term resolution across tiers (logentry.go:97-156) --

    def first_index(self) -> int:
        idx = self.inmem.get_snapshot_index()
        if idx is not None:
            return idx + 1
        return self.logdb.first_index()

    def last_index(self) -> int:
        idx = self.inmem.get_last_index()
        if idx is not None:
            return idx
        return self.logdb.last_index()

    def term(self, index: int) -> int:
        if index == 0:
            return 0
        first, last = self.first_index(), self.last_index()
        if index < first - 1:
            raise CompactedError(index)
        if index > last:
            raise UnavailableError(index)
        snap_idx = self.inmem.get_snapshot_index()
        if snap_idx is not None and index == snap_idx:
            assert self.inmem.snapshot is not None
            return self.inmem.snapshot.term
        if self.inmem.entries and index >= self.inmem.marker:
            return self.inmem.entries[index - self.inmem.marker].term
        return self.logdb.term(index)

    def last_term(self) -> int:
        return self.term(self.last_index())

    def match_term(self, index: int, term: int) -> bool:
        try:
            return self.term(index) == term
        except (CompactedError, UnavailableError):
            return False

    def up_to_date(self, index: int, term: int) -> bool:
        """Vote restriction — parity with logentry.go:381 (upToDate)."""
        last_term = self.last_term()
        if term > last_term:
            return True
        if term == last_term:
            return index >= self.last_index()
        return False

    # -- reads --

    def get_entries(self, low: int, high: int, max_size: int = 0) -> list[pb.Entry]:
        if low > high:
            raise ValueError(f"low {low} > high {high}")
        if low < self.first_index():
            raise CompactedError(low)
        if high > self.last_index() + 1:
            raise UnavailableError(high)
        if low == high:
            return []
        in_marker = self.inmem.marker
        out: list[pb.Entry] = []
        if low < in_marker:
            out = self.logdb.entries(low, min(high, in_marker), 0)
        if high > in_marker and (not out or out[-1].index + 1 >= in_marker):
            lo = max(low, in_marker)
            out = out + self.inmem.get_entries(lo, high)
        if max_size > 0:
            size = 0
            for i, e in enumerate(out):
                size += pb.entry_size(e)
                if size > max_size and i > 0:
                    return out[:i]
        return out

    def entries_from(self, low: int, max_size: int = 0) -> list[pb.Entry]:
        if low > self.last_index():
            return []
        return self.get_entries(low, self.last_index() + 1, max_size)

    def get_committed_entries(self, low: int, high: int, max_size: int) -> list[pb.Entry]:
        """Parity: logentry.go:280 (getCommittedEntries) for LogQuery."""
        if low < self.first_index() or low > self.committed:
            raise CompactedError(low)
        high = min(high, self.committed + 1)
        if low == high:
            return []
        return self.get_entries(low, high, max_size)

    def entries_to_apply(self, limit: int = 0) -> list[pb.Entry]:
        """Committed-but-not-processed entries, paginated —
        parity with logentry.go:268 (getEntriesToApply)."""
        if self.processed < self.committed:
            return self.get_entries(self.processed + 1, self.committed + 1, limit)
        return []

    def has_entries_to_apply(self) -> bool:
        return self.committed > self.processed

    def has_entries_to_save(self) -> bool:
        return self.inmem.has_entries_to_save()

    def entries_to_save(self) -> list[pb.Entry]:
        return self.inmem.entries_to_save()

    # -- writes --

    def append(self, entries: Sequence[pb.Entry]) -> None:
        if not entries:
            return
        if entries[0].index <= self.committed:
            raise AssertionError(
                f"appending over committed entries: {entries[0].index} <= {self.committed}"
            )
        self.inmem.merge(entries)

    def get_conflict_index(self, entries: Sequence[pb.Entry]) -> int:
        for e in entries:
            if not self.match_term(e.index, e.term):
                return e.index
        return 0

    def try_append(self, index: int, entries: Sequence[pb.Entry]) -> bool:
        """Append with conflict resolution — parity with logentry.go:296."""
        conflict = self.get_conflict_index(entries)
        if conflict != 0:
            if conflict <= self.committed:
                raise AssertionError(
                    f"entry {conflict} conflicts with committed entry {self.committed}"
                )
            self.append(list(entries)[conflict - index - 1 :])
            return True
        return False

    def commit_to(self, index: int) -> None:
        if index <= self.committed:
            return
        if index > self.last_index():
            raise AssertionError(
                f"commitTo {index} > lastIndex {self.last_index()}"
            )
        self.committed = index

    def try_commit(self, index: int, term: int) -> bool:
        """Quorum commit with the current-term-only rule —
        parity with logentry.go:395 and the p8 raft-paper restriction."""
        if index <= self.committed:
            return False
        try:
            lterm = self.term(index)
        except CompactedError:
            lterm = 0
        if lterm == term:
            self.commit_to(index)
            return True
        return False

    def commit_update(self, uc: pb.UpdateCommit) -> None:
        """Advance stable/processed/applied cursors — parity with
        logentry.go:351 (commitUpdate)."""
        if uc.stable_log_to > 0:
            self.inmem.saved_log_to(uc.stable_log_to, uc.stable_log_term)
        if uc.stable_snapshot_to > 0:
            self.inmem.saved_snapshot_to(uc.stable_snapshot_to)
        if uc.processed > 0:
            if uc.processed < self.processed or uc.processed > self.committed:
                raise AssertionError(
                    f"invalid processed {uc.processed}, "
                    f"current {self.processed}, committed {self.committed}"
                )
            self.processed = uc.processed
        if uc.last_applied > 0:
            if uc.last_applied > self.committed or uc.last_applied > self.processed:
                raise AssertionError(
                    f"invalid last_applied {uc.last_applied}, "
                    f"processed {self.processed}, committed {self.committed}"
                )
            self.inmem.applied_log_to(uc.last_applied)

    def restore(self, ss: pb.Snapshot) -> None:
        self.inmem.restore(ss)
        if ss.index < self.committed:
            raise AssertionError("committed moving backwards on restore")
        self.committed = ss.index
        self.processed = ss.index

    def get_uncommitted_size(self) -> int:
        if self.committed >= self.last_index():
            return 0
        ents = self.get_entries(self.committed + 1, self.last_index() + 1)
        return sum(pb.entry_size(e) for e in ents)

    def snapshot(self) -> pb.Snapshot:
        if self.inmem.snapshot is not None:
            return self.inmem.snapshot
        return self.logdb.snapshot()
