"""Device-side message router for co-located replica groups.

The reference exchanges messages over TCP (internal/transport) or an
in-process chan transport (plugin/chan).  When all replicas of a group live
in the same kernel state (the single-host / single-slice case — BASELINE
configs #2-#4), message exchange is a pure array shuffle: out-lanes of step
t become in-lanes of step t+1 with no host involvement.  This module builds
that shuffle with gathers over a ``[N, R, ...]`` (groups × replicas) view —
the same pattern later extends across chips with collective permutes.

Inbox slot layout per target, per peer q of the R-1 remote peers:
  [q*5 + 0]  first response lane addressed to me
  [q*5 + 1]  second response lane addressed to me
  [q*5 + 2]  replicate
  [q*5 + 3]  heartbeat
  [q*5 + 4]  vote request / TimeoutNow (mutually exclusive senders)
Requires ``inbox_cap >= 5 * (R - 1)``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from dragonboat_tpu import raftpb as pb
from dragonboat_tpu.core import params as KP
from dragonboat_tpu.core.kstate import Inbox, ShardState, StepInput, StepOutput
from dragonboat_tpu.core.kernel import onehot_select, step

MT = pb.MessageType
I32 = jnp.int32

# ---------------------------------------------------------------------------
# slot-layout helpers (shared, PUBLIC): the host-side fallback stager
# (engine/kernel_engine._InboxBuilder in mesh mode) must place a
# hub-delivered message in EXACTLY the slot route() would have used, or
# the device-resident and hub-fallback delivery paths stop being bitwise
# interchangeable (tests/test_engine_differential.py third arm).  Every
# piece of layout arithmetic lives here so the two sides cannot drift.
# ---------------------------------------------------------------------------

#: slots per remote peer in the fixed inbox layout (module docstring)
SLOTS_PER_PEER = 5
#: class offsets within one peer's slot block
SLOT_RESP0, SLOT_RESP1, SLOT_REP, SLOT_HB, SLOT_VOTE = range(SLOTS_PER_PEER)

#: route()-producible message types -> slot offset within the peer block.
#: Responses get two lanes (SLOT_RESP0 then SLOT_RESP1); the vote slot is
#: shared by mutually-exclusive senders (a replica never sends a vote
#: request AND TimeoutNow in one step).
SLOT_OFFSETS_OF_TYPE = {
    int(MT.REPLICATE): (SLOT_REP,),
    int(MT.HEARTBEAT): (SLOT_HB,),
    int(MT.REQUEST_VOTE): (SLOT_VOTE,),
    int(MT.REQUEST_PREVOTE): (SLOT_VOTE,),
    int(MT.TIMEOUT_NOW): (SLOT_VOTE,),
}
# everything else (responses and host-originated kernel messages such as
# UNREACHABLE / SNAPSHOT_STATUS) rides the response lanes
_RESP_OFFSETS = (SLOT_RESP0, SLOT_RESP1)


def peer_ordinal(target_rid: int, source_rid: int, replicas: int) -> int:
    """Remote-peer ordinal ``q`` of ``source_rid`` as seen by
    ``target_rid``: the inverse of route()'s source enumeration
    ``s = (t + 1 + q) % R`` (both rids 1-based, q in 0..R-2)."""
    return (source_rid - target_rid - 1) % replicas


def slot_candidates(target_rid: int, source_rid: int, replicas: int,
                    mtype: int) -> tuple[int, ...]:
    """Inbox slot indexes (in preference order) where route() would place
    a ``mtype`` message from ``source_rid`` addressed to ``target_rid``."""
    base = peer_ordinal(target_rid, source_rid, replicas) * SLOTS_PER_PEER
    offs = SLOT_OFFSETS_OF_TYPE.get(int(mtype), _RESP_OFFSETS)
    return tuple(base + o for o in offs)


def route(kp: KP.KernelParams, replicas: int, out: StepOutput) -> Inbox:
    """Turn one step's StepOutput into the next step's Inbox, fully on device.

    All arrays have leading [G] = [N*R] with rows grouped by raft group.
    """
    R = replicas
    K, E = kp.inbox_cap, kp.msg_entries
    assert K >= SLOTS_PER_PEER * (R - 1), \
        "inbox_cap too small for the fixed slot layout"
    G = out.term.shape[0]
    N = G // R

    def grp(x):  # [G, ...] -> [N, R, ...]
        return x.reshape((N, R) + x.shape[1:])

    term = grp(out.term)

    # --- response lanes: for each (target t, source s) pick up to 2 resp
    # lanes addressed to t ------------------------------------------------
    r_type = grp(out.r_type)          # [N, R, K]
    r_to = grp(out.r_to)
    r_term = grp(out.r_term)
    r_log_index = grp(out.r_log_index)
    r_reject = grp(out.r_reject)
    r_hint = grp(out.r_hint)
    r_hint_high = grp(out.r_hint_high)

    # to_me[t, s, k]: source s's resp lane k addresses replica t+1
    rid_t = jnp.arange(1, R + 1, dtype=I32)                  # [R]
    to_me = (r_to[:, None, :, :] == rid_t[None, :, None, None]) & (
        r_type[:, None, :, :] != 0
    )                                                        # [N, Rt, Rs, K]
    # first and second matching lane indexes per (t, s)
    lane_iota = jnp.arange(K, dtype=I32)
    big = jnp.asarray(K, I32)
    lane_or_big = jnp.where(to_me, lane_iota, big)
    first = jnp.min(lane_or_big, axis=-1)                    # [N, Rt, Rs]
    lane_or_big2 = jnp.where(
        to_me & (lane_iota != first[..., None]), lane_iota, big
    )
    second = jnp.min(lane_or_big2, axis=-1)

    def pick(src_field, lane):  # src_field [N, Rs, K] ; lane [N, Rt, Rs]
        if not kp.onehot_reads:
            sf = jnp.broadcast_to(src_field[:, None], (N, R, R, K))
            return jnp.take_along_axis(
                sf, jnp.minimum(lane, K - 1)[..., None], axis=-1
            )[..., 0]
        # one-hot select instead of take_along_axis: a batched gather
        # serializes over the batch axis on TPU (see kernel._get1); a
        # lane==K sentinel has no hot slot and reads 0/False, which the
        # caller's validity mask discards either way (the gather branch
        # clamps the sentinel to K-1 under the same mask)
        oh = lane[..., None] == lane_iota                     # [N,Rt,Rs,K]
        return onehot_select(oh, src_field[:, None], -1)

    resp_valid1 = first < K
    resp_valid2 = second < K

    # --- per-peer lanes: source s's peer-slot (t) lanes --------------------
    # peer slot index for target rid t+1 is t (pid layout [1..R])
    def peer_lane(field):  # [N, Rs, P(, E)] -> [N, Rt, Rs(, E)]
        f = grp(field)                                       # [N, Rs, P, ...]
        sl = f[:, :, :R]                                     # peer slots 0..R-1
        return jnp.swapaxes(sl, 1, 2)                        # [N, Rt, Rs, ...]

    rep_valid = peer_lane(out.s_rep)
    rep_prev_i = peer_lane(out.s_prev_index)
    rep_prev_t = peer_lane(out.s_prev_term)
    rep_commit = peer_lane(out.s_commit)
    rep_n = peer_lane(out.s_n_ent)
    rep_ent_t = peer_lane(out.s_ent_term)                    # [N, Rt, Rs, E]
    rep_ent_cc = peer_lane(out.s_ent_cc)
    inline = out.s_ent_val is not None
    rep_ent_v = peer_lane(out.s_ent_val) if inline else None
    hb_valid = peer_lane(out.s_hb)
    hb_commit = peer_lane(out.s_hb_commit)
    hb_low = peer_lane(out.s_hb_low)
    hb_high = peer_lane(out.s_hb_high)
    vt_kind = peer_lane(out.s_vote)                          # 0/1/2
    vt_term = peer_lane(out.s_vote_term)
    vt_li = peer_lane(out.s_vote_lindex)
    vt_lt = peer_lane(out.s_vote_lterm)
    vt_hint = peer_lane(out.s_vote_hint)
    tn_valid = peer_lane(out.s_timeout_now)

    src_term = jnp.broadcast_to(term[:, None, :], (N, R, R))  # [N, Rt, Rs]
    src_rid = jnp.broadcast_to(
        jnp.arange(1, R + 1, dtype=I32)[None, None, :], (N, R, R)
    )

    # --- assemble the [N, Rt, K] inbox ------------------------------------
    fields = {
        "mtype": jnp.zeros((N, R, K), I32),
        "from_": jnp.zeros((N, R, K), I32),
        "term": jnp.zeros((N, R, K), I32),
        "log_term": jnp.zeros((N, R, K), I32),
        "log_index": jnp.zeros((N, R, K), I32),
        "commit": jnp.zeros((N, R, K), I32),
        "reject": jnp.zeros((N, R, K), bool),
        "hint": jnp.zeros((N, R, K), I32),
        "hint_high": jnp.zeros((N, R, K), I32),
        "n_ent": jnp.zeros((N, R, K), I32),
        "ent_term": jnp.zeros((N, R, K, E), I32),
        "ent_cc": jnp.zeros((N, R, K, E), bool),
    }
    if inline:
        fields["ent_val"] = jnp.zeros((N, R, K, E), I32)

    # enumerate the R-1 remote sources for each target: s = (t + 1 + q) % R
    t_iota = jnp.arange(R, dtype=I32)
    for q in range(R - 1):
        s_of_t = (t_iota + 1 + q) % R                        # [R]

        # one-hot over the (small, static) source axis — see pick()
        oh_src = s_of_t[:, None] == jnp.arange(R, dtype=I32)  # [Rt, Rs]

        def take(x3):  # [N, Rt, Rs] select source s_of_t[t]
            if not kp.onehot_reads:
                idx = jnp.broadcast_to(s_of_t[None, :, None], (N, R, 1))
                return jnp.take_along_axis(x3, idx, axis=2)[:, :, 0]
            return onehot_select(oh_src[None], x3, 2)

        def take4(x4):  # [N, Rt, Rs, E]
            if not kp.onehot_reads:
                idx = jnp.broadcast_to(
                    s_of_t[None, :, None, None], (N, R, 1, x4.shape[-1]))
                return jnp.take_along_axis(x4, idx, axis=2)[:, :, 0]
            return onehot_select(oh_src[None, :, :, None], x4, 2)

        base = q * SLOTS_PER_PEER
        # responses
        for lane_no, (lane, vmask) in enumerate(
            ((first, resp_valid1), (second, resp_valid2))
        ):
            v = take(vmask)
            k_slot = base + lane_no
            fields["mtype"] = fields["mtype"].at[:, :, k_slot].set(
                jnp.where(v, take(pick(r_type, lane)), 0))
            fields["from_"] = fields["from_"].at[:, :, k_slot].set(
                jnp.where(v, take(src_rid), 0))
            fields["term"] = fields["term"].at[:, :, k_slot].set(
                jnp.where(v, take(pick(r_term, lane)), 0))
            fields["log_index"] = fields["log_index"].at[:, :, k_slot].set(
                jnp.where(v, take(pick(r_log_index, lane)), 0))
            fields["reject"] = fields["reject"].at[:, :, k_slot].set(
                jnp.where(v, take(pick(r_reject, lane)).astype(bool), False))
            fields["hint"] = fields["hint"].at[:, :, k_slot].set(
                jnp.where(v, take(pick(r_hint, lane)), 0))
            fields["hint_high"] = fields["hint_high"].at[:, :, k_slot].set(
                jnp.where(v, take(pick(r_hint_high, lane)), 0))
        # replicate
        v = take(rep_valid)
        k_slot = base + SLOT_REP
        fields["mtype"] = fields["mtype"].at[:, :, k_slot].set(
            jnp.where(v, MT.REPLICATE, 0))
        fields["from_"] = fields["from_"].at[:, :, k_slot].set(
            jnp.where(v, take(src_rid), 0))
        fields["term"] = fields["term"].at[:, :, k_slot].set(
            jnp.where(v, take(src_term), 0))
        fields["log_term"] = fields["log_term"].at[:, :, k_slot].set(
            jnp.where(v, take(rep_prev_t), 0))
        fields["log_index"] = fields["log_index"].at[:, :, k_slot].set(
            jnp.where(v, take(rep_prev_i), 0))
        fields["commit"] = fields["commit"].at[:, :, k_slot].set(
            jnp.where(v, take(rep_commit), 0))
        fields["n_ent"] = fields["n_ent"].at[:, :, k_slot].set(
            jnp.where(v, take(rep_n), 0))
        fields["ent_term"] = fields["ent_term"].at[:, :, k_slot].set(
            jnp.where(v[..., None], take4(rep_ent_t), 0))
        fields["ent_cc"] = fields["ent_cc"].at[:, :, k_slot].set(
            jnp.where(v[..., None], take4(rep_ent_cc), False))
        if inline:
            fields["ent_val"] = fields["ent_val"].at[:, :, k_slot].set(
                jnp.where(v[..., None], take4(rep_ent_v), 0))
        # heartbeat
        v = take(hb_valid)
        k_slot = base + SLOT_HB
        fields["mtype"] = fields["mtype"].at[:, :, k_slot].set(
            jnp.where(v, MT.HEARTBEAT, 0))
        fields["from_"] = fields["from_"].at[:, :, k_slot].set(
            jnp.where(v, take(src_rid), 0))
        fields["term"] = fields["term"].at[:, :, k_slot].set(
            jnp.where(v, take(src_term), 0))
        fields["commit"] = fields["commit"].at[:, :, k_slot].set(
            jnp.where(v, take(hb_commit), 0))
        fields["hint"] = fields["hint"].at[:, :, k_slot].set(
            jnp.where(v, take(hb_low), 0))
        fields["hint_high"] = fields["hint_high"].at[:, :, k_slot].set(
            jnp.where(v, take(hb_high), 0))
        # vote request or TimeoutNow
        vk = take(vt_kind)
        tn = take(tn_valid)
        k_slot = base + SLOT_VOTE
        mt = jnp.where(
            tn, MT.TIMEOUT_NOW,
            jnp.where(vk == 1, MT.REQUEST_VOTE,
                      jnp.where(vk == 2, MT.REQUEST_PREVOTE, 0)),
        )
        v = mt != 0
        fields["mtype"] = fields["mtype"].at[:, :, k_slot].set(mt)
        fields["from_"] = fields["from_"].at[:, :, k_slot].set(
            jnp.where(v, take(src_rid), 0))
        fields["term"] = fields["term"].at[:, :, k_slot].set(
            jnp.where(tn, take(src_term), jnp.where(v, take(vt_term), 0)))
        fields["log_index"] = fields["log_index"].at[:, :, k_slot].set(
            jnp.where(vk > 0, take(vt_li), 0))
        fields["log_term"] = fields["log_term"].at[:, :, k_slot].set(
            jnp.where(vk > 0, take(vt_lt), 0))
        fields["hint"] = fields["hint"].at[:, :, k_slot].set(
            jnp.where(vk > 0, take(vt_hint), 0))

    return Inbox(**{k: v.reshape((G,) + v.shape[2:]) for k, v in fields.items()})


@functools.partial(jax.jit, static_argnums=(0, 1))
def cluster_step(kp: KP.KernelParams, replicas: int, state: ShardState,
                 inbox: Inbox, inp: StepInput):
    """One fused step for co-located groups: kernel step + device routing.

    Returns (state, next_inbox, out).  The host only reads the slim result
    lanes it needs (prop fates, rtr lanes, save/apply cursors)."""
    state, out = step(kp, state, inbox, inp)
    nxt = route(kp, replicas, out)
    return state, nxt, out


@functools.partial(jax.jit, static_argnums=(0, 1), donate_argnums=(2, 3, 4))
def cluster_step_donated(kp: KP.KernelParams, replicas: int,
                         state: ShardState, inbox: Inbox, inp: StepInput):
    """Donating twin of ``cluster_step`` (kstate.DONATION
    ``cluster_step_donated``): state, inbox and input hand their buffers
    to XLA, so after dispatch the caller must only read the RETURNED
    state/inbox/out — the depth-1 differential arm's retire-before-
    dispatch order (tests/test_engine_differential.py) upholds that."""
    state, out = step(kp, state, inbox, inp)
    nxt = route(kp, replicas, out)
    return state, nxt, out
